"""Device-batched PoH span engine (round 14).

Reference role: src/disco/poh/fd_poh_tile.c's hashing core — the leader
must extend an iterated-sha256 chain at ~1 M hash/s while mixing in one
merkle root per microblock.  The chain is serial *within* a span, but a
leader always has independent spans in flight: the speculative next-tick
pre-hash, the current tick's microblock chain, and the embarrassingly-
parallel `verify_entries` re-check of already-emitted entries.  Those
spans become the LANES of a (lanes, 32) state plane dispatched through
the shared PackedDispatchEngine (PR-13), so PoH work rides the same
double-buffered host handoff as sigverify and shred recover.

Row wire format (one lane per row):

    start[32] | steps * ( mixin[32] | n u32 LE | has_mixin u8 | active u8 )

Steps CHAIN within a lane: step s starts from step s-1's end state, so a
tick with j microblocks is ONE dispatch — lane steps
[(1,m_1) .. (1,m_j), (hashes_per_tick - j, None)] — and the serial mixin
dependency never round-trips to the host between hashes.  The verdict is
every step's end state (lanes, steps*32), letting the caller read entry
boundaries out of the middle of the chain.

Each step's inner hash loop is a masked lax.scan of max_hashes rounds
(the verify_entries pattern) with an `unroll` factor so XLA fuses
consecutive sha256 compressions instead of paying per-iteration loop
overhead.
"""

import functools
import struct

import jax
import jax.numpy as jnp
import numpy as np

from firedancer_tpu.models.verifier import PackedDispatchEngine, WorkloadDesc
from firedancer_tpu.ops.sha256 import sha256_fixed32

from . import entry as entry_lib
from .poh import mixin

LANE_HDR_SZ = 32
STEP_SZ = 38  # mixin[32] | n u32 | has_mixin u8 | active u8


def row_bytes(steps: int) -> int:
    return LANE_HDR_SZ + steps * STEP_SZ


def poh_spans_blob(blob, steps: int, max_hashes: int, unroll: int = 8,
                   step_caps=None):
    """The span kernel.  blob: uint8 (lanes, row_bytes(steps)) in the row
    wire format above.  Returns uint8 (lanes, steps*32): each step's end
    state (inactive steps pass the running state through unchanged).

    Step semantics per lane (matches entry.next_hash / verify_entries):
    n-1 plain sha256 appends then one final append absorbing the mixin
    when has_mixin (n plain when not); n == 0 passes through.

    step_caps: optional per-step hash-count ceilings (len == steps, each
    in [1, max_hashes]).  Each step's masked scan runs only its own cap's
    rounds instead of max_hashes — the round-15 splice kernel rides this:
    a tick re-hash from the mixin insertion point costs caps like
    (1, 1, .., full) rather than steps * max_hashes rounds."""
    caps = tuple(step_caps) if step_caps is not None \
        else (max_hashes,) * steps
    state = blob[:, :LANE_HDR_SZ]
    outs = []
    for s in range(steps):
        idxs = jnp.arange(caps[s], dtype=jnp.int32)
        base = LANE_HDR_SZ + s * STEP_SZ
        mix = blob[:, base : base + 32]
        nb = blob[:, base + 32 : base + 36].astype(jnp.int32)
        n = nb[:, 0] | (nb[:, 1] << 8) | (nb[:, 2] << 16) | (nb[:, 3] << 24)
        has_mixin = blob[:, base + 36] != 0
        active = blob[:, base + 37] != 0
        nm1 = jnp.maximum(n - 1, 0)

        def step_fn(st, i, nm1=nm1):
            plain = sha256_fixed32(st)
            return jnp.where((i < nm1)[:, None], plain, st), None

        st, _ = jax.lax.scan(step_fn, state, idxs,
                             unroll=_fit_unroll(unroll, caps[s]))
        final_plain = sha256_fixed32(st)
        final_mix = mixin(st, mix)
        last = jnp.where(has_mixin[:, None], final_mix, final_plain)
        res = jnp.where((n > 0)[:, None], last, state)
        state = jnp.where(active[:, None], res, state)
        outs.append(state)
    return jnp.concatenate(outs, axis=1)


def _fit_unroll(unroll: int, max_hashes: int) -> int:
    """Largest unroll <= requested that divides the trip count (keeps the
    scan free of a ragged tail iteration)."""
    u = max(1, min(int(unroll), int(max_hashes)))
    while max_hashes % u:
        u -= 1
    return u


def host_spans(specs, steps: int) -> np.ndarray:
    """Host golden twin of poh_spans_blob over the same lane specs
    (hashlib chain via entry.next_hash).  specs: list of
    (start: bytes32, [(n, mixin_bytes_or_None), ...]); returns uint8
    (len(specs), steps, 32)."""
    out = np.zeros((len(specs), steps, 32), dtype=np.uint8)
    for li, (start, sspec) in enumerate(specs):
        h = bytes(start)
        for si in range(steps):
            if si < len(sspec):
                n, mx = sspec[si]
                if n > 0:
                    h = entry_lib.next_hash(h, n, mx)
                elif mx is not None:
                    raise ValueError("mixin requires n >= 1")
            out[li, si] = np.frombuffer(h, dtype=np.uint8)
    return out


class PohEngine:
    """PoH span workload over the shared rotation core.

    lanes x steps geometry is fixed at construction (one compiled graph);
    submit_lanes() stamps however many lanes a call actually has into the
    rotating blob (unused lanes/steps stay inactive and pass through).
    Verdicts retire in dispatch order — the FIFO guarantee the consensus-
    critical entry ordering rides on."""

    def __init__(self, lanes: int, steps: int, max_hashes: int, *,
                 nbuf: int = 2, depth: int | None = None, unroll: int = 8,
                 step_caps=None):
        if lanes < 1 or steps < 1 or max_hashes < 1:
            raise ValueError("bad poh engine geometry")
        if step_caps is not None:
            step_caps = tuple(int(c) for c in step_caps)
            if len(step_caps) != steps:
                raise ValueError("step_caps length != steps")
            if any(not (1 <= c <= max_hashes) for c in step_caps):
                raise ValueError("step cap outside [1, max_hashes]")
        self.lanes = lanes
        self.steps = steps
        self.max_hashes = max_hashes
        self.step_caps = step_caps  # None = uniform max_hashes per step
        self.unroll = _fit_unroll(unroll, max_hashes)
        self._jit = jax.jit(functools.partial(
            poh_spans_blob, steps=steps, max_hashes=max_hashes,
            unroll=unroll, step_caps=step_caps))
        desc = WorkloadDesc(
            name="poh-append",
            rows=lanes,
            row_bytes=row_bytes(steps),
            true_rows=lanes,
            dispatch=self._dispatch,
        )
        self._eng = PackedDispatchEngine(desc, nbuf=nbuf, depth=depth)

    # ------------------------------------------------------------ plumbing
    def _dispatch(self, blob):
        return self._jit(jax.device_put(blob))

    def warm(self):
        """AOT-compile the span graph (zero active lanes) so the first
        real dispatch doesn't pay the compile."""
        self._eng.submit_packed(lambda buf: None, 0)
        self._eng.drain()

    def _validate(self, specs):
        if len(specs) > self.lanes:
            raise ValueError(f"{len(specs)} lanes > engine {self.lanes}")
        total = 0
        for start, sspec in specs:
            if len(start) != 32:
                raise ValueError("start hash must be 32 bytes")
            if len(sspec) > self.steps:
                raise ValueError(f"{len(sspec)} steps > engine {self.steps}")
            for si, (n, mx) in enumerate(sspec):
                cap = (self.step_caps[si] if self.step_caps is not None
                       else self.max_hashes)
                if not (0 <= n <= cap):
                    raise ValueError(f"step n={n} outside [0, {cap}]")
                if mx is not None and n < 1:
                    # the kernel passes n == 0 through but next_hash would
                    # absorb the mixin: reject the divergent stamp outright
                    raise ValueError("mixin requires n >= 1")
                if mx is not None and len(mx) != 32:
                    raise ValueError("mixin must be 32 bytes")
                total += 1
        return total

    def submit_lanes(self, specs) -> list[np.ndarray]:
        """Dispatch one batch of lane specs: list of
        (start: bytes32, [(n, mixin_bytes_or_None), ...]).  Returns any
        verdicts the inflight window retired this call (dispatch order);
        split with split_verdict."""
        total = self._validate(specs)

        def fill(buf):
            buf[:, :] = 0
            for li, (start, sspec) in enumerate(specs):
                row = buf[li]
                row[:32] = np.frombuffer(bytes(start), dtype=np.uint8)
                for si, (n, mx) in enumerate(sspec):
                    base = LANE_HDR_SZ + si * STEP_SZ
                    if mx is not None:
                        row[base : base + 32] = np.frombuffer(
                            bytes(mx), dtype=np.uint8)
                        row[base + 36] = 1
                    row[base + 32 : base + 36] = np.frombuffer(
                        struct.pack("<I", n), dtype=np.uint8)
                    row[base + 37] = 1

        return self._eng.submit_packed(fill, total)

    def split_verdict(self, verdict: np.ndarray) -> np.ndarray:
        """(lanes, steps*32) harvest blob -> (lanes, steps, 32)."""
        return verdict.reshape(self.lanes, self.steps, 32)

    # --------------------------------------------------- engine passthrough
    @property
    def dispatches(self) -> int:
        return self._eng.dispatches

    @property
    def inflight_depth(self) -> int:
        return self._eng.inflight_depth

    @property
    def backpressure_waits(self) -> int:
        return self._eng.backpressure_waits

    def poll(self) -> list[np.ndarray]:
        return self._eng.poll()

    def drain(self) -> list[np.ndarray]:
        return self._eng.drain()

    def stats(self) -> dict:
        return self._eng.stats()
