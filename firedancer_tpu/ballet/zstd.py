"""From-scratch zstd decompressor (RFC 8878), decode side only.

Reference role: src/ballet/zstd/fd_zstd.{h,c} — the reference implements its
own streaming zstd *decompressor* to restore Agave snapshot archives without
trusting an external library in the validator boot path; compression stays
out of scope there too.  Same split here: this module decodes frames written
by any conformant encoder (tests cross-check against libzstd via the
`zstandard` package), and the snapshot writer uses libzstd to compress.

Implements: frame parsing, raw/RLE/compressed blocks, Huffman literals
(direct + FSE-compressed weights, 1- and 4-stream), FSE sequence tables
(predefined / RLE / compressed / repeat), repeat-offset history, treeless
literal blocks, skippable frames.  Dictionaries are rejected; the xxhash64
content checksum is parsed but not verified.

Bitstreams are modeled as Python big ints: zstd's backward streams read
bits MSB-down from the sentinel bit, forward streams LSB-up — both are a
shift+mask on ``int.from_bytes(data, "little")``, which keeps this code
obviously-correct at control-plane speed (snapshot restore, not hot path).
"""

from __future__ import annotations

ZSTD_MAGIC = 0xFD2FB528
SKIPPABLE_LO = 0x184D2A50
SKIPPABLE_HI = 0x184D2A5F

MAX_WINDOW = 1 << 27  # sanity cap (128 MiB) against hostile headers


class ZstdError(ValueError):
    pass


# ------------------------------------------------------------- bitstreams


class _Backward:
    """zstd backward bitstream: bytes written little-endian, read from the
    sentinel (highest set bit of the last byte) downward."""

    def __init__(self, data: bytes):
        if not data:
            raise ZstdError("empty backward bitstream")
        self.val = int.from_bytes(data, "little")
        if self.val == 0:
            raise ZstdError("backward bitstream missing sentinel")
        self.pos = self.val.bit_length() - 1  # drop the sentinel bit

    def read(self, n: int) -> int:
        """Read n bits (earlier-read bits are more significant); over-reads
        beyond the start yield zero bits (FSE final-state flushes
        legitimately touch the boundary) and leave pos negative."""
        if n == 0:
            return 0
        self.pos -= n
        if self.pos >= 0:
            return (self.val >> self.pos) & ((1 << n) - 1)
        if self.pos < -64:  # pathological over-read: corrupt stream
            raise ZstdError("backward bitstream exhausted")
        avail = self.pos + n  # bits that really existed
        if avail <= 0:
            return 0
        return (self.val & ((1 << avail) - 1)) << (-self.pos)


class _Forward:
    """Forward LSB-first bitstream (FSE table descriptions)."""

    def __init__(self, data: bytes):
        self.val = int.from_bytes(data, "little")
        self.nbits = 8 * len(data)
        self.pos = 0

    def read(self, n: int) -> int:
        if self.pos + n > self.nbits:
            raise ZstdError("forward bitstream exhausted")
        r = (self.val >> self.pos) & ((1 << n) - 1)
        self.pos += n
        return r

    def bytes_consumed(self) -> int:
        return (self.pos + 7) // 8


# ------------------------------------------------------------------- FSE


class _FseTable:
    """Decoding table: per-state (symbol, nb_bits, baseline)."""

    __slots__ = ("accuracy", "symbol", "nbits", "base")

    def __init__(self, accuracy: int, counts: list[int]):
        self.accuracy = accuracy
        size = 1 << accuracy
        self.symbol = [0] * size
        self.nbits = [0] * size
        self.base = [0] * size

        high = size - 1
        for s, c in enumerate(counts):
            if c == -1:  # "less than 1" probability: one cell at the top
                self.symbol[high] = s
                high -= 1
        step = (size >> 1) + (size >> 3) + 3
        mask = size - 1
        pos = 0
        for s, c in enumerate(counts):
            if c <= 0:
                continue
            for _ in range(c):
                self.symbol[pos] = s
                pos = (pos + step) & mask
                while pos > high:
                    pos = (pos + step) & mask
        if pos != 0:
            raise ZstdError("FSE table spread did not return to zero")

        # per-cell transitions, visited in state order: symbol s's k-th
        # state (k from count[s]) gets nb = accuracy - flog2(k) bits and
        # baseline (k << nb) - size
        nxt = [c if c > 0 else 1 for c in counts]
        for state in range(size):
            s = self.symbol[state]
            x = nxt[s]
            nxt[s] += 1
            nb = accuracy - (x.bit_length() - 1)
            self.nbits[state] = nb
            self.base[state] = (x << nb) - size

    @classmethod
    def rle(cls, symbol: int) -> "_FseTable":
        t = cls.__new__(cls)
        t.accuracy = 0
        t.symbol = [symbol]
        t.nbits = [0]
        t.base = [0]
        return t


def _read_fse_counts(fwd: _Forward, max_symbol: int,
                     max_accuracy: int) -> tuple[int, list[int]]:
    """RFC 8878 §4.1.1 normalized-count decoding."""
    accuracy = fwd.read(4) + 5
    if accuracy > max_accuracy:
        raise ZstdError(f"FSE accuracy {accuracy} > {max_accuracy}")
    remaining = (1 << accuracy) + 1
    counts: list[int] = []
    while remaining > 1 and len(counts) <= max_symbol:
        nb = remaining.bit_length()  # bits to encode [0, remaining]
        lower_mask = (1 << (nb - 1)) - 1
        threshold = (1 << nb) - 1 - remaining
        peek_pos = fwd.pos
        peek = fwd.read(nb)
        low = peek & lower_mask
        if low < threshold:
            value = low
            fwd.pos = peek_pos + nb - 1  # only nb-1 bits consumed
        else:
            value = peek
            if value >= (1 << (nb - 1)):
                value -= threshold
        prob = value - 1
        counts.append(prob)
        remaining -= prob if prob > 0 else -prob  # |prob|; zero costs zero
        if prob == 0:
            while True:
                rep = fwd.read(2)
                counts.extend([0] * rep)
                if rep != 3:
                    break
    if remaining != 1:
        raise ZstdError("FSE counts do not sum to table size")
    counts.extend([0] * (max_symbol + 1 - len(counts)))
    return accuracy, counts


# ---------------------------------------------------------------- huffman


class _HufTable:
    __slots__ = ("max_bits", "symbol", "nbits")

    def __init__(self, weights: list[int]):
        total = sum((1 << (w - 1)) for w in weights if w > 0)
        if total == 0:
            raise ZstdError("huffman: empty weight set")
        # RFC 8878 §4.2.1: Max_Number_of_Bits = flog2(total) + 1; the last
        # symbol's weight is implied, completing total to 2^Max
        max_bits = total.bit_length()  # == flog2(total) + 1
        left = (1 << max_bits) - total
        if left <= 0 or left & (left - 1):
            raise ZstdError("huffman: weights leave a non-pow2 gap")
        weights = weights + [left.bit_length()]
        self.max_bits = max_bits
        size = 1 << self.max_bits
        self.symbol = bytearray(size)
        self.nbits = bytearray(size)
        # canonical fill: increasing weight (longest codes at low indices),
        # symbols in natural order within a weight
        idx = 0
        for w in range(1, self.max_bits + 1):
            for s, ws in enumerate(weights):
                if ws != w:
                    continue
                span = 1 << (w - 1)
                nb = self.max_bits + 1 - w
                for i in range(idx, idx + span):
                    self.symbol[i] = s
                    self.nbits[i] = nb
                idx += span
        if idx != size:
            raise ZstdError("huffman: canonical fill incomplete")

    def decode_stream(self, data: bytes, out_len: int) -> bytes:
        bs = _Backward(data)
        out = bytearray()
        # state machine: keep a max_bits-wide window; SLL semantics via
        # explicit position bookkeeping
        window = bs.read(self.max_bits)
        have = self.max_bits
        while len(out) < out_len:
            out.append(self.symbol[window])
            nb = self.nbits[window]
            fresh = bs.read(nb)
            window = ((window << nb) | fresh) & ((1 << self.max_bits) - 1)
        return bytes(out)


def _read_huffman(data: bytes) -> tuple[_HufTable, int]:
    """Huffman tree description -> (table, bytes consumed)."""
    if not data:
        raise ZstdError("missing huffman description")
    hbyte = data[0]
    if hbyte >= 128:  # direct 4-bit weights
        n = hbyte - 127
        nbytes = (n + 1) // 2
        raw = data[1:1 + nbytes]
        if len(raw) < nbytes:
            raise ZstdError("truncated huffman weights")
        weights = []
        for i in range(n):
            b = raw[i // 2]
            weights.append((b >> 4) if i % 2 == 0 else (b & 0xF))
        return _HufTable(weights), 1 + nbytes
    # FSE-compressed weights: two interleaved states over a backward stream
    csize = hbyte
    blob = data[1:1 + csize]
    if len(blob) < csize:
        raise ZstdError("truncated huffman FSE weights")
    fwd = _Forward(blob)
    accuracy, counts = _read_fse_counts(fwd, 255, 6)
    table = _FseTable(accuracy, counts)
    bs = _Backward(blob[fwd.bytes_consumed():])
    s1 = bs.read(accuracy)
    s2 = bs.read(accuracy)
    # two interleaved FSE states; when a state update over-reads the
    # stream, the OTHER state's symbol is emitted last (RFC 8878 §4.2.1.2)
    weights: list[int] = []
    while True:
        weights.append(table.symbol[s1])
        s1 = table.base[s1] + bs.read(table.nbits[s1])
        if bs.pos < 0:
            weights.append(table.symbol[s2])
            break
        weights.append(table.symbol[s2])
        s2 = table.base[s2] + bs.read(table.nbits[s2])
        if bs.pos < 0:
            weights.append(table.symbol[s1])
            break
        if len(weights) > 254:
            raise ZstdError("huffman: too many weights")
    return _HufTable(weights), 1 + csize


# --------------------------------------------------------- sequence codes

_LL_BASE = [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15,
            16, 18, 20, 22, 24, 28, 32, 40, 48, 64, 128, 256, 512, 1024,
            2048, 4096, 8192, 16384, 32768, 65536]
_LL_BITS = [0] * 16 + [1, 1, 1, 1, 2, 2, 3, 3, 4, 6, 7, 8, 9, 10, 11, 12,
                       13, 14, 15, 16]
_ML_BASE = list(range(3, 35)) + [35, 37, 39, 41, 43, 47, 51, 59, 67, 83,
                                 99, 131, 259, 515, 1027, 2051, 4099, 8195,
                                 16387, 32771, 65539]
_ML_BITS = [0] * 32 + [1, 1, 1, 1, 2, 2, 3, 3, 4, 4, 5, 7, 8, 9, 10, 11,
                       12, 13, 14, 15, 16]

_LL_DEFAULT = [4, 3, 2, 2, 2, 2, 2, 2, 2, 2, 2, 2, 2, 1, 1, 1, 2, 2, 2, 2,
               2, 2, 2, 2, 2, 3, 2, 1, 1, 1, 1, 1, -1, -1, -1, -1]
_OF_DEFAULT = [1, 1, 1, 1, 1, 1, 2, 2, 2, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1,
               1, 1, 1, 1, -1, -1, -1, -1, -1]
_ML_DEFAULT = [1, 4, 3, 2, 2, 2, 2, 2, 2, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1,
               1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1,
               1, 1, 1, 1, 1, 1, -1, -1, -1, -1, -1, -1, -1]

_PREDEFINED = {
    "ll": (6, _LL_DEFAULT, 35),
    "of": (5, _OF_DEFAULT, 31),
    "ml": (6, _ML_DEFAULT, 52),
}


# ---------------------------------------------------------------- decoder


class _FrameDecoder:
    def __init__(self):
        self.huf: _HufTable | None = None
        self.fse: dict[str, _FseTable | None] = {
            "ll": None, "of": None, "ml": None}
        self.reps = [1, 4, 8]

    # -- literals ---------------------------------------------------------
    def _literals(self, blk: bytes) -> tuple[bytes, int]:
        """Decode the literals section -> (literals, bytes consumed)."""
        b0 = blk[0]
        ltype = b0 & 3
        sf = (b0 >> 2) & 3
        if ltype in (0, 1):  # raw / RLE
            if sf in (0, 2):
                regen = b0 >> 3
                hdr = 1
            elif sf == 1:
                regen = (b0 >> 4) | (blk[1] << 4)
                hdr = 2
            else:
                regen = (b0 >> 4) | (blk[1] << 4) | (blk[2] << 12)
                hdr = 3
            if ltype == 0:
                lits = blk[hdr:hdr + regen]
                if len(lits) < regen:
                    raise ZstdError("truncated raw literals")
                return bytes(lits), hdr + regen
            return bytes([blk[hdr]]) * regen, hdr + 1
        # compressed (2) / treeless (3)
        if sf == 0:
            n_streams = 1
            h = int.from_bytes(blk[:3], "little")
            regen = (h >> 4) & 0x3FF
            csize = (h >> 14) & 0x3FF
            hdr = 3
        elif sf == 1:
            n_streams = 4
            h = int.from_bytes(blk[:3], "little")
            regen = (h >> 4) & 0x3FF
            csize = (h >> 14) & 0x3FF
            hdr = 3
        elif sf == 2:
            n_streams = 4
            h = int.from_bytes(blk[:4], "little")
            regen = (h >> 4) & 0x3FFF
            csize = (h >> 18) & 0x3FFF
            hdr = 4
        else:
            n_streams = 4
            h = int.from_bytes(blk[:5], "little")
            regen = (h >> 4) & 0x3FFFF
            csize = (h >> 22) & 0x3FFFF
            hdr = 5
        body = blk[hdr:hdr + csize]
        if len(body) < csize:
            raise ZstdError("truncated compressed literals")
        off = 0
        if ltype == 2:
            self.huf, off = _read_huffman(body)
        if self.huf is None:
            raise ZstdError("treeless literals with no previous table")
        streams = body[off:]
        if n_streams == 1:
            return self.huf.decode_stream(streams, regen), hdr + csize
        if len(streams) < 6:
            raise ZstdError("missing 4-stream jump table")
        s1 = int.from_bytes(streams[0:2], "little")
        s2 = int.from_bytes(streams[2:4], "little")
        s3 = int.from_bytes(streams[4:6], "little")
        rest = streams[6:]
        if s1 + s2 + s3 > len(rest):
            raise ZstdError("4-stream sizes exceed section")
        part = (regen + 3) // 4
        out = b""
        sizes = [s1, s2, s3, len(rest) - s1 - s2 - s3]
        pos = 0
        for i, sz in enumerate(sizes):
            want = part if i < 3 else regen - 3 * part
            if want > 0:
                out += self.huf.decode_stream(rest[pos:pos + sz], want)
            pos += sz
        return out, hdr + csize

    # -- sequences --------------------------------------------------------
    def _seq_table(self, kind: str, mode: int, blk: bytes,
                   pos: int) -> tuple[_FseTable, int]:
        max_acc, default, max_sym = {
            "ll": (9, _LL_DEFAULT, 35),
            "of": (8, _OF_DEFAULT, 31),
            "ml": (9, _ML_DEFAULT, 52),
        }[kind]
        if mode == 0:  # predefined
            acc = {"ll": 6, "of": 5, "ml": 6}[kind]
            counts = default + [0] * (max_sym + 1 - len(default))
            t = _FseTable(acc, counts)
        elif mode == 1:  # RLE: single symbol
            t = _FseTable.rle(blk[pos])
            pos += 1
        elif mode == 2:  # FSE-described
            fwd = _Forward(blk[pos:])
            acc, counts = _read_fse_counts(fwd, max_sym, max_acc)
            t = _FseTable(acc, counts)
            pos += fwd.bytes_consumed()
        else:  # repeat
            t = self.fse[kind]
            if t is None:
                raise ZstdError(f"repeat {kind} table with no previous")
        self.fse[kind] = t
        return t, pos

    def _block(self, blk: bytes, out: bytearray) -> None:
        lits, pos = self._literals(blk)
        if pos >= len(blk):
            # no sequence section at all is invalid; nbSeq=0 needs a byte
            raise ZstdError("missing sequences section")
        b0 = blk[pos]
        if b0 < 128:
            nseq = b0
            pos += 1
        elif b0 < 255:
            nseq = ((b0 - 128) << 8) | blk[pos + 1]
            pos += 2
        else:
            nseq = int.from_bytes(blk[pos + 1:pos + 3], "little") + 0x7F00
            pos += 3
        if nseq == 0:
            out += lits
            return
        modes = blk[pos]
        pos += 1
        ll_t, pos = self._seq_table("ll", (modes >> 6) & 3, blk, pos)
        of_t, pos = self._seq_table("of", (modes >> 4) & 3, blk, pos)
        ml_t, pos = self._seq_table("ml", (modes >> 2) & 3, blk, pos)

        bs = _Backward(blk[pos:])
        ll_s = bs.read(ll_t.accuracy)
        of_s = bs.read(of_t.accuracy)
        ml_s = bs.read(ml_t.accuracy)
        lit_pos = 0
        for i in range(nseq):
            of_code = of_t.symbol[of_s]
            if of_code > 31:
                raise ZstdError("offset code too large")
            of_value = (1 << of_code) + bs.read(of_code)
            ml_code = ml_t.symbol[ml_s]
            ml = _ML_BASE[ml_code] + bs.read(_ML_BITS[ml_code])
            ll_code = ll_t.symbol[ll_s]
            ll = _LL_BASE[ll_code] + bs.read(_LL_BITS[ll_code])

            # repeat-offset resolution (RFC 8878 §3.1.1.5)
            reps = self.reps
            if of_value > 3:
                offset = of_value - 3
                self.reps = [offset, reps[0], reps[1]]
            else:
                idx = of_value - 1 + (1 if ll == 0 else 0)
                if idx == 0:
                    offset = reps[0]
                elif idx == 1:
                    offset = reps[1]
                    self.reps = [offset, reps[0], reps[2]]
                elif idx == 2:
                    offset = reps[2]
                    self.reps = [offset, reps[0], reps[1]]
                else:  # ll == 0 and of_value == 3
                    offset = reps[0] - 1
                    if offset == 0:
                        raise ZstdError("zero repeat offset")
                    self.reps = [offset, reps[0], reps[1]]

            out += lits[lit_pos:lit_pos + ll]
            lit_pos += ll
            if offset > len(out):
                raise ZstdError("match offset beyond window")
            for _ in range(ml):  # byte-wise: overlap semantics
                out.append(out[-offset])

            if i + 1 < nseq:  # update states LL, ML, OF
                ll_s = ll_t.base[ll_s] + bs.read(ll_t.nbits[ll_s])
                ml_s = ml_t.base[ml_s] + bs.read(ml_t.nbits[ml_s])
                of_s = of_t.base[of_s] + bs.read(of_t.nbits[of_s])
        out += lits[lit_pos:]


def decompress(data: bytes, max_output: int = 1 << 31) -> bytes:
    """Decode a (possibly multi-frame) zstd payload.

    Every malformation maps to ZstdError: explicit validation where the
    format demands it, and a boundary conversion for truncation-shaped
    IndexErrors (memory-safe in Python; first surfaced by the fuzz sweep,
    tests/test_fuzz_corpus.py)."""
    try:
        return _decompress(data, max_output)
    except (IndexError, KeyError) as e:
        raise ZstdError(f"truncated or corrupt stream: {e}")


def _decompress(data: bytes, max_output: int) -> bytes:
    out = bytearray()
    pos = 0
    while pos < len(data):
        if len(data) - pos < 4:
            raise ZstdError("truncated frame magic")
        magic = int.from_bytes(data[pos:pos + 4], "little")
        pos += 4
        if SKIPPABLE_LO <= magic <= SKIPPABLE_HI:
            size = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4 + size
            continue
        if magic != ZSTD_MAGIC:
            raise ZstdError(f"bad magic {magic:#x}")
        fhd = data[pos]
        pos += 1
        single = (fhd >> 5) & 1
        checksum = (fhd >> 2) & 1
        dict_flag = fhd & 3
        fcs_flag = fhd >> 6
        if not single:
            pos += 1  # window descriptor (we bound memory via max_output)
        if dict_flag:
            raise ZstdError("dictionaries not supported")
        fcs_size = {0: 1 if single else 0, 1: 2, 2: 4, 3: 8}[fcs_flag]
        pos += fcs_size  # declared content size: informational
        dec = _FrameDecoder()
        frame_out = bytearray()
        while True:
            if len(data) - pos < 3:
                raise ZstdError("truncated block header")
            bh = int.from_bytes(data[pos:pos + 3], "little")
            pos += 3
            last, btype, bsize = bh & 1, (bh >> 1) & 3, bh >> 3
            if btype == 0:  # raw
                frame_out += data[pos:pos + bsize]
                pos += bsize
            elif btype == 1:  # RLE
                frame_out += bytes([data[pos]]) * bsize
                pos += 1
            elif btype == 2:
                dec._block(data[pos:pos + bsize], frame_out)
                pos += bsize
            else:
                raise ZstdError("reserved block type")
            if len(out) + len(frame_out) > max_output:
                raise ZstdError("output exceeds max_output")
            if last:
                break
        out += frame_out
        if checksum:
            pos += 4  # xxh64 low 32 bits: parsed, not verified
    return bytes(out)
