"""ChaCha20 stream cipher + ChaCha20Rng, host-side (numpy block core).

Reference role: src/ballet/chacha20/ — (a) QUIC packet protection suite
option, (b) the deterministic RNG behind stake-weighted sampling: Solana's
leader schedule and turbine trees draw from rand_chacha's ChaCha20Rng
seeded with an epoch-derived 32-byte seed, and consensus requires our
stream to match it bit-for-bit (fd_chacha20_rng).

The block function is numpy-vectorized over counters (many blocks per call)
— the host analogue of the reference's AVX lanes; the RNG's consumers
(wsample) pull 64-bit words.
"""

import numpy as np

_SIGMA = np.frombuffer(b"expand 32-byte k", dtype="<u4")


def _quarter(x, a, b, c, d):
    x[a] += x[b]
    x[d] = np.bitwise_xor(x[d], x[a])
    x[d] = (x[d] << 16) | (x[d] >> 16)
    x[c] += x[d]
    x[b] = np.bitwise_xor(x[b], x[c])
    x[b] = (x[b] << 12) | (x[b] >> 20)
    x[a] += x[b]
    x[d] = np.bitwise_xor(x[d], x[a])
    x[d] = (x[d] << 8) | (x[d] >> 24)
    x[c] += x[d]
    x[b] = np.bitwise_xor(x[b], x[c])
    x[b] = (x[b] << 7) | (x[b] >> 25)


def chacha20_blocks(key: bytes, nonce: bytes, counter0: int, n_blocks: int) -> bytes:
    """Keystream for n_blocks consecutive 64-byte blocks, all lanes at once.

    nonce is 12 bytes (IETF) with a 32-bit counter, or 8 bytes (djb/rand_chacha)
    with a 64-bit counter.
    """
    k = np.frombuffer(key, dtype="<u4")
    if len(nonce) == 12:
        ctr_words = 1
        non = np.frombuffer(nonce, dtype="<u4")
    elif len(nonce) == 8:
        ctr_words = 2
        non = np.frombuffer(nonce, dtype="<u4")
    else:
        raise ValueError("nonce must be 8 or 12 bytes")

    state = np.zeros((16, n_blocks), dtype=np.uint32)
    state[0:4] = _SIGMA[:, None]
    state[4:12] = k[:, None]
    ctrs = counter0 + np.arange(n_blocks, dtype=np.uint64)
    state[12] = ctrs.astype(np.uint32)
    if ctr_words == 2:
        state[13] = (ctrs >> np.uint64(32)).astype(np.uint32)
        state[14:16] = non[:, None]
    else:
        state[13:16] = non[:, None]

    with np.errstate(over="ignore"):
        x = state.copy()
        for _ in range(10):  # 20 rounds = 10 double rounds
            _quarter(x, 0, 4, 8, 12)
            _quarter(x, 1, 5, 9, 13)
            _quarter(x, 2, 6, 10, 14)
            _quarter(x, 3, 7, 11, 15)
            _quarter(x, 0, 5, 10, 15)
            _quarter(x, 1, 6, 11, 12)
            _quarter(x, 2, 7, 8, 13)
            _quarter(x, 3, 4, 9, 14)
        x += state
    # per block: 16 words little-endian
    return x.T.astype("<u4").tobytes()


def chacha20_encrypt(key: bytes, nonce: bytes, counter0: int, data: bytes) -> bytes:
    n_blocks = (len(data) + 63) // 64
    ks = chacha20_blocks(key, nonce, counter0, n_blocks)[: len(data)]
    return (
        np.bitwise_xor(
            np.frombuffer(data, dtype=np.uint8), np.frombuffer(ks, dtype=np.uint8)
        )
    ).tobytes()


class ChaCha20Rng:
    """Deterministic RNG matching rand_chacha's ChaCha20Rng (8-byte zero
    nonce, 64-bit block counter from 0), the stream Solana's leader schedule
    samples from (fd_chacha20_rng.h)."""

    REFILL_BLOCKS = 64  # refill granularity (4 KiB of keystream)

    def __init__(self, seed: bytes):
        if len(seed) != 32:
            raise ValueError("seed must be 32 bytes")
        self.seed = seed
        self.counter = 0
        self._buf = b""
        self._off = 0

    def _refill(self):
        self._buf = chacha20_blocks(
            self.seed, b"\0" * 8, self.counter, self.REFILL_BLOCKS
        )
        self.counter += self.REFILL_BLOCKS
        self._off = 0

    def next_u32(self) -> int:
        if self._off + 4 > len(self._buf):
            self._refill()
        v = int.from_bytes(self._buf[self._off : self._off + 4], "little")
        self._off += 4
        return v

    def next_u64(self) -> int:
        if self._off + 8 > len(self._buf):
            self._refill()
        v = int.from_bytes(self._buf[self._off : self._off + 8], "little")
        self._off += 8
        return v

    # rejection-zone modes (fd_chacha20rng.h:23-24 / Rust rand 0.7
    # UniformInt<u64>): MOD = the ahead-of-time Uniform distribution
    # (largest k, used by WeightedIndex -> leader schedules), SHIFT =
    # sample_single's power-of-two zone (used by Turbine's shuffle)
    MODE_MOD = 1
    MODE_SHIFT = 2

    def roll_u64(self, n: int, mode: int = MODE_MOD) -> int:
        """Uniform draw in [0, n): Lemire multiply-high bounded rand with
        rand-0.7-exact rejection zones (fd_chacha20rng_ulong_roll) — the
        map is hi64(v * n), accepting only draws whose lo64 falls in the
        mode's zone.  Wire-critical: leader schedules (MODE_MOD) and
        turbine trees (MODE_SHIFT) must consume the identical stream as
        Agave/the reference or every derived schedule diverges."""
        if n <= 0:
            raise ValueError("n must be positive")
        if mode == self.MODE_MOD:
            zone = ((1 << 64) - 1) - ((1 << 64) - n) % n
        else:
            zone = (n << (63 - (n.bit_length() - 1))) - 1
        while True:
            v = self.next_u64() * n
            if (v & ((1 << 64) - 1)) <= zone:
                return v >> 64
