"""Poseidon hash over the BN254 scalar field (light-poseidon / circomlib
compatible) — the sol_poseidon syscall's hash.

Parity surface: src/ballet/bn254/fd_poseidon.{h,cxx} (the reference wraps
libff + a 1 MB pregenerated parameter table from light-poseidon 0.1.2).
This build generates the parameters itself with the Grain LFSR procedure
from the Poseidon paper's reference code (the same procedure circomlib /
light-poseidon used to mint their tables): alpha=5, R_F=8, R_P from the
128-bit-security table, ARK constants from the LFSR stream, MDS as the
Cauchy matrix 1/(x_i + y_j) with x = 0..t-1, y = t..2t-1.  Correctness is
pinned by the reference's own golden vectors (test_poseidon.c) in
tests/test_poseidon.py — byte-identical output, no table shipped.

State width t = 1 + ceil(len/32); state[0] is the zero domain tag; each
32-byte input chunk is one field element (little-endian, or byte-swapped
when big_endian — including the reference's quirk that a SHORT trailing
chunk is zero-extended before the swap, so big-endian short chunks land
in the high bytes).
"""

from __future__ import annotations

import functools

# BN254 scalar field (= bn254.N, the group order)
P = 21888242871839275222246405745257275088548364400416034343698204186575808495617

ALPHA = 5
FULL_ROUNDS = 8
# partial rounds per width t=2..13 (one table entry per input count 1..12)
PARTIAL_ROUNDS = [56, 57, 56, 60, 60, 63, 64, 63, 60, 66, 60, 65]

MAX_INPUTS = 12


class PoseidonError(ValueError):
    pass


class _Grain:
    """The Poseidon paper's Grain LFSR, GF(p) instantiation."""

    def __init__(self, field_size: int, t: int, r_f: int, r_p: int):
        bits = []

        def push(v, n):
            for i in range(n - 1, -1, -1):
                bits.append((v >> i) & 1)

        push(1, 2)            # field tag: prime field
        push(0, 4)            # sbox: x^alpha
        push(field_size, 12)
        push(t, 12)
        push(r_f, 10)
        push(r_p, 10)
        bits.extend([1] * 30)
        assert len(bits) == 80
        self.state = bits
        for _ in range(160):  # discard the first 160 raw bits
            self._raw_bit()

    def _raw_bit(self) -> int:
        s = self.state
        nb = s[62] ^ s[51] ^ s[38] ^ s[23] ^ s[13] ^ s[0]
        self.state = s[1:] + [nb]
        return nb

    def _bit(self) -> int:
        # pairs: first bit 1 -> emit second; first bit 0 -> discard second
        while True:
            if self._raw_bit():
                return self._raw_bit()
            self._raw_bit()

    def field_element(self, nbits: int) -> int:
        # rejection-sample nbits-wide integers until < p
        while True:
            v = 0
            for _ in range(nbits):
                v = (v << 1) | self._bit()
            if v < P:
                return v


@functools.lru_cache(maxsize=None)
def _params(t: int):
    """(ark, mds, r_p) for state width t.  ARK is Grain-generated (verified
    byte-identical to light-poseidon's tables); MDS comes from the small
    standardized table in poseidon_mds.py (818 domain constants total —
    light-poseidon's x/y Cauchy sampling procedure is not re-derivable
    from the paper's script alone)."""
    if not (2 <= t <= MAX_INPUTS + 1):
        raise PoseidonError(f"poseidon: unsupported width {t}")
    from .poseidon_mds import MDS_HEX

    r_p = PARTIAL_ROUNDS[t - 2]
    g = _Grain(254, t, FULL_ROUNDS, r_p)
    ark = [g.field_element(254) for _ in range(t * (FULL_ROUNDS + r_p))]
    flat = [int(h, 16) for h in MDS_HEX[t]]
    mds = [flat[i * t : (i + 1) * t] for i in range(t)]
    return ark, mds, r_p


def hash_inputs(inputs: list[int]) -> int:
    """Poseidon over field-element inputs; returns the field result."""
    t = len(inputs) + 1
    ark, mds, r_p = _params(t)
    state = [0] + [v % P for v in inputs]
    half = FULL_ROUNDS // 2
    total = FULL_ROUNDS + r_p

    for rnd in range(total):
        state = [(s + ark[rnd * t + i]) % P for i, s in enumerate(state)]
        if half <= rnd < half + r_p:
            state[0] = pow(state[0], ALPHA, P)
        else:
            state = [pow(s, ALPHA, P) for s in state]
        state = [
            sum(mds[i][j] * state[j] for j in range(t)) % P for i in range(t)
        ]
    return state[0]


def hash(data: bytes, big_endian: bool = False) -> bytes:
    """fd_poseidon_hash semantics: chunk into 32-byte field elements
    (zero-filled short tail, byte-swapped per chunk when big_endian),
    hash, serialize the result in the same endianness."""
    if len(data) == 0 or len(data) > 32 * MAX_INPUTS:
        raise PoseidonError(f"poseidon: bad input length {len(data)}")
    inputs = []
    for off in range(0, len(data), 32):
        buf = data[off : off + 32].ljust(32, b"\0")
        if big_endian:
            buf = buf[::-1]
        inputs.append(int.from_bytes(buf, "little"))
    out = hash_inputs(inputs).to_bytes(32, "little")
    return out[::-1] if big_endian else out
