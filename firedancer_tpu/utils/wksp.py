"""Workspace: named, tagged, checkpointable shared-memory arena
(ref: src/util/wksp/ — fd_wksp_admin.c/fd_wksp_user.c partition
management, fd_wksp.h:967-1008 checkpoint/restore to file).

A wksp owns one contiguous shared-memory region carved into tagged
partitions.  Offsets ("gaddrs") are stable across processes and across
checkpoint/restore — exactly the property funk and long-lived state need
(persistent + relocatable).  The reference tracks free/used spans in
treaps inside the region; here the bookkeeping lives in the header region
as a compact table (same contract, simpler machinery — partition counts
are thousands, not billions).

Checkpoint format (version 1): a framed stream of used partitions.
Restore rebuilds partitions at their original gaddrs, so inter-partition
gaddr references survive.
"""

from __future__ import annotations

import os
import struct
from multiprocessing import shared_memory

_MAGIC = b"FDTPUWK1"
_ALIGN_DEFAULT = 16


class WkspError(RuntimeError):
    pass


class Wksp:
    """One workspace. create=True builds it; create=False joins by name."""

    def __init__(self, name: str, data_sz: int = 1 << 24,
                 create: bool = True):
        self.name = name
        if create:
            self.shm = shared_memory.SharedMemory(
                name=name, create=True, size=data_sz)
        else:
            self.shm = shared_memory.SharedMemory(name=name)
        self.data_sz = self.shm.size
        # bookkeeping: gaddr -> (size, tag); free spans derived on demand
        self._used: dict[int, tuple[int, int]] = {}
        self._owner = create

    # ------------------------------------------------------------ allocation

    def _free_spans(self):
        """Sorted (gaddr, size) gaps between used partitions."""
        spans = []
        pos = 0
        for g in sorted(self._used):
            sz, _ = self._used[g]
            if g > pos:
                spans.append((pos, g - pos))
            pos = max(pos, g + sz)
        if pos < self.data_sz:
            spans.append((pos, self.data_sz - pos))
        return spans

    def alloc(self, sz: int, align: int = _ALIGN_DEFAULT, tag: int = 1) -> int:
        """First-fit allocate; returns the partition gaddr
        (fd_wksp_alloc).  tag must be nonzero (0 marks free)."""
        if sz <= 0 or tag == 0:
            raise WkspError("alloc needs sz >= 1 and tag != 0")
        for g, span in self._free_spans():
            start = (g + align - 1) & ~(align - 1)
            if start + sz <= g + span:
                self._used[start] = (sz, tag)
                return start
        raise WkspError(f"wksp {self.name}: out of space for {sz} bytes")

    def free(self, gaddr: int) -> None:
        if gaddr not in self._used:
            raise WkspError(f"free of unknown gaddr {gaddr}")
        del self._used[gaddr]

    def tag_free(self, tag: int) -> int:
        """Free every partition with this tag (fd_wksp_tag_free); returns
        count."""
        doomed = [g for g, (_, t) in self._used.items() if t == tag]
        for g in doomed:
            del self._used[g]
        return len(doomed)

    def laddr(self, gaddr: int) -> memoryview:
        """gaddr -> writable local view of the partition
        (fd_wksp_laddr)."""
        if gaddr not in self._used:
            raise WkspError(f"laddr of unknown gaddr {gaddr}")
        sz, _ = self._used[gaddr]
        return self.shm.buf[gaddr : gaddr + sz]

    def gaddr_of(self, tag: int) -> list[int]:
        return [g for g, (_, t) in self._used.items() if t == tag]

    def partitions(self) -> list[tuple[int, int, int]]:
        """Sorted (gaddr, size, tag) of used partitions (fd_wksp_ctl query
        equivalent)."""
        return sorted(
            (g, sz, tag) for g, (sz, tag) in self._used.items())

    def usage(self) -> tuple[int, int]:
        """(used_bytes, free_bytes)."""
        used = sum(sz for sz, _ in self._used.values())
        return used, self.data_sz - used

    # ------------------------------------------------------ checkpoint/restore

    def checkpt(self, path: str) -> None:
        """Write every used partition to `path` (fd_wksp_checkpt, style 2:
        framed raw).  Atomic via rename."""
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<QQ", self.data_sz, len(self._used)))
            for g, (sz, tag) in sorted(self._used.items()):
                f.write(struct.pack("<QQQ", g, sz, tag))
                f.write(bytes(self.shm.buf[g : g + sz]))
        os.replace(tmp, path)

    def restore(self, path: str) -> None:
        """Replace this wksp's contents with a checkpoint's partitions
        (fd_wksp_restore).  Gaddrs are preserved; raises if the checkpoint
        needs a bigger region."""
        with open(path, "rb") as f:
            if f.read(8) != _MAGIC:
                raise WkspError(f"{path}: not a wksp checkpoint")
            data_sz, n = struct.unpack("<QQ", f.read(16))
            if data_sz > self.data_sz:
                raise WkspError(
                    f"{path}: checkpoint of {data_sz}B wksp won't fit in "
                    f"{self.data_sz}B")
            used: dict[int, tuple[int, int]] = {}
            for _ in range(n):
                g, sz, tag = struct.unpack("<QQQ", f.read(24))
                blob = f.read(sz)
                if len(blob) != sz or g + sz > self.data_sz:
                    raise WkspError(f"{path}: truncated/corrupt checkpoint")
                self.shm.buf[g : g + sz] = blob
                used[g] = (sz, tag)
        self._used = used

    # --------------------------------------------------------------- lifecycle

    def close(self) -> None:
        self.shm.close()

    def unlink(self) -> None:
        if self._owner:
            try:
                self.shm.unlink()
            except FileNotFoundError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        self.unlink()
