"""Counter-based deterministic PRNG (ref: src/util/rng/fd_rng.c contract:
a (seq, idx) pair fully determines the stream; jumping to any idx is O(1),
so parallel consumers can partition one logical stream without locks).

The mixer is our own splitmix64-style avalanche over (seq, idx) — the
reference's exact constants are not reproduced (this is a rebuild, not a
port); what is preserved is the API: O(1) random access, independent
streams per seq, and the derived-type helpers (roll, float in [0,1), ...).
"""


class Rng:
    _M = (1 << 64) - 1

    def __init__(self, seq: int = 0, idx: int = 0):
        self.seq = seq & self._M
        self.idx = idx & self._M

    @staticmethod
    def _mix(x: int) -> int:
        M = (1 << 64) - 1
        x &= M
        x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & M
        x = (x ^ (x >> 27)) * 0x94D049BB133111EB & M
        return x ^ (x >> 31)

    def ulong(self) -> int:
        """Next uniform 64-bit value; advances idx."""
        out = self._mix(self.idx ^ self._mix(self.seq ^ 0x9E3779B97F4A7C15))
        self.idx = (self.idx + 1) & self._M
        return out

    def uint(self) -> int:
        return self.ulong() >> 32

    def roll(self, n: int) -> int:
        """Uniform in [0, n) without modulo bias (fd_rng_ulong_roll):
        rejection-sample the top of the range."""
        if n <= 0:
            raise ValueError("roll needs n >= 1")
        lim = ((1 << 64) // n) * n
        while True:
            v = self.ulong()
            if v < lim:
                return v % n

    def float01(self) -> float:
        """Uniform in [0, 1) with 53-bit resolution (fd_rng_double_o)."""
        return (self.ulong() >> 11) * (1.0 / (1 << 53))

    def shuffle(self, xs: list) -> list:
        """In-place Fisher-Yates driven by this stream."""
        for i in range(len(xs) - 1, 0, -1):
            j = self.roll(i + 1)
            xs[i], xs[j] = xs[j], xs[i]
        return xs
