"""Leveled logging in the reference's style (ref: src/util/log/fd_log.h:23-45:
DEBUG/INFO/NOTICE/WARNING/ERR/CRIT/ALERT/EMERG, dual-stream ephemeral+file).

Thin layer over python logging: same level vocabulary, same "ERR exits the
tile" fail-fast contract (ref FD_LOG_ERR terminates the process so the
supervisor can restart the topology, src/app/fdctl/run/run.c:279)."""

import logging
import os
import sys

NOTICE = 25
logging.addLevelName(NOTICE, "NOTICE")

_logger = logging.getLogger("firedancer_tpu")

# per-process log context: which tile this process is, and its restart
# generation (ref: fd_log's thread-local app/thread tags, fd_log.h:150).
# "-" = the supervisor / a non-tile process.
_ctx = {"tag": "-"}


def set_context(tile: str, gen: int = 0):
    """Tag every subsequent record from this process with the tile name
    (and `#gen` once the supervisor has respawned it at least once), so
    interleaved multi-tile stderr attributes each line."""
    _ctx["tag"] = f"{tile}#{gen}" if gen > 0 else (tile or "-")


class _Ctx(logging.Filter):
    def filter(self, record):
        record.tile = _ctx["tag"]
        return True


_logger.addFilter(_Ctx())


def boot(log_path: str | None = None, level: str = "NOTICE"):
    """fd_boot-style logging init (ref fd_util.h:50-100 boot options)."""
    _logger.setLevel(logging.DEBUG)
    _logger.handlers.clear()
    eph = logging.StreamHandler(sys.stderr)
    eph.setLevel(getattr(logging, level, NOTICE) if level != "NOTICE" else NOTICE)
    eph.setFormatter(
        logging.Formatter("%(levelname)-7s %(process)d %(tile)s %(message)s"))
    eph.addFilter(_Ctx())   # handler-level too: stamps records that
    _logger.addHandler(eph)  # propagate from child loggers
    if log_path:
        fh = logging.FileHandler(log_path)
        fh.setLevel(logging.DEBUG)
        fh.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)-7s %(process)d %(tile)s %(message)s"))
        fh.addFilter(_Ctx())
        _logger.addHandler(fh)
    return _logger


def debug(msg, *a):
    _logger.debug(msg, *a)


def info(msg, *a):
    _logger.info(msg, *a)


def notice(msg, *a):
    _logger.log(NOTICE, msg, *a)


def warning(msg, *a):
    _logger.warning(msg, *a)


def err(msg, *a):
    """Log and exit: the tile supervision tree treats any tile death as fatal
    for the whole topology (fail-fast, ref run.c:279)."""
    _logger.error(msg, *a)
    sys.exit(1)
