"""Leveled logging in the reference's style (ref: src/util/log/fd_log.h:23-45:
DEBUG/INFO/NOTICE/WARNING/ERR/CRIT/ALERT/EMERG, dual-stream ephemeral+file).

Thin layer over python logging: same level vocabulary, same "ERR exits the
tile" fail-fast contract (ref FD_LOG_ERR terminates the process so the
supervisor can restart the topology, src/app/fdctl/run/run.c:279)."""

import logging
import os
import sys

NOTICE = 25
logging.addLevelName(NOTICE, "NOTICE")

_logger = logging.getLogger("firedancer_tpu")


def boot(log_path: str | None = None, level: str = "NOTICE"):
    """fd_boot-style logging init (ref fd_util.h:50-100 boot options)."""
    _logger.setLevel(logging.DEBUG)
    _logger.handlers.clear()
    eph = logging.StreamHandler(sys.stderr)
    eph.setLevel(getattr(logging, level, NOTICE) if level != "NOTICE" else NOTICE)
    eph.setFormatter(logging.Formatter("%(levelname)-7s %(process)d %(message)s"))
    _logger.addHandler(eph)
    if log_path:
        fh = logging.FileHandler(log_path)
        fh.setLevel(logging.DEBUG)
        fh.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)-7s %(process)d %(message)s")
        )
        _logger.addHandler(fh)
    return _logger


def debug(msg, *a):
    _logger.debug(msg, *a)


def info(msg, *a):
    _logger.info(msg, *a)


def notice(msg, *a):
    _logger.log(NOTICE, msg, *a)


def warning(msg, *a):
    _logger.warning(msg, *a)


def err(msg, *a):
    """Log and exit: the tile supervision tree treats any tile death as fatal
    for the whole topology (fail-fast, ref run.c:279)."""
    _logger.error(msg, *a)
    sys.exit(1)
