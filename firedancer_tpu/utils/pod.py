"""Hierarchical typed key-value pod (ref: src/util/pod/fd_pod.c).

The reference serializes a nested string-keyed store into one shared-memory
blob so a booting tile can be handed its entire config as a single buffer
(the legacy "frank" wiring, src/disco/verify/verify_synth_load.c:13-27).
Same contract here: a pod is a flat bytes blob; `query` walks dotted paths
("verify.batch.depth"); subpods nest.  Typed leaves cover the types the
reference uses most (ulong/long/int/cstr/blob/subpod).

Wire format (little-endian):
    pod  := entry*                      (concatenated, no count prefix)
    entry:= klen:u16 key:bytes vtype:u8 vlen:u32 value:bytes
    vtype: 0=subpod 1=ulong 2=long 3=cstr 4=blob 5=double
"""

from __future__ import annotations

import struct

_SUBPOD, _ULONG, _LONG, _CSTR, _BLOB, _DOUBLE = range(6)


def _enc_entry(key: str, vtype: int, val: bytes) -> bytes:
    kb = key.encode()
    return struct.pack("<H", len(kb)) + kb + bytes([vtype]) \
        + struct.pack("<I", len(val)) + val


def encode(tree: dict) -> bytes:
    """dict -> pod bytes.  Values may be int (ulong if >= 0 else long),
    float, str, bytes, or nested dict."""
    out = bytearray()
    for key, v in tree.items():
        if isinstance(v, dict):
            out += _enc_entry(key, _SUBPOD, encode(v))
        elif isinstance(v, bool):
            out += _enc_entry(key, _ULONG, struct.pack("<Q", int(v)))
        elif isinstance(v, int):
            if v >= 0:
                out += _enc_entry(key, _ULONG, struct.pack("<Q", v))
            else:
                out += _enc_entry(key, _LONG, struct.pack("<q", v))
        elif isinstance(v, float):
            out += _enc_entry(key, _DOUBLE, struct.pack("<d", v))
        elif isinstance(v, str):
            out += _enc_entry(key, _CSTR, v.encode() + b"\0")
        elif isinstance(v, (bytes, bytearray, memoryview)):
            out += _enc_entry(key, _BLOB, bytes(v))
        else:
            raise TypeError(f"pod: unsupported value type for {key!r}: "
                            f"{type(v).__name__}")
    return bytes(out)


def _iter_entries(pod: bytes):
    off = 0
    n = len(pod)
    while off < n:
        if off + 2 > n:
            raise ValueError("pod: truncated key length")
        (klen,) = struct.unpack_from("<H", pod, off)
        off += 2
        if off + klen + 5 > n:
            raise ValueError("pod: truncated entry header")
        key = pod[off : off + klen].decode()
        off += klen
        vtype = pod[off]
        off += 1
        (vlen,) = struct.unpack_from("<I", pod, off)
        off += 4
        if off + vlen > n:
            raise ValueError("pod: truncated value")
        val = pod[off : off + vlen]
        off += vlen
        yield key, vtype, val


def _decode_leaf(vtype: int, val: bytes):
    if vtype == _SUBPOD:
        return decode(val)
    if vtype in (_ULONG, _LONG, _DOUBLE) and len(val) != 8:
        raise ValueError(f"pod: fixed-width value of {len(val)} bytes")
    if vtype == _ULONG:
        return struct.unpack("<Q", val)[0]
    if vtype == _LONG:
        return struct.unpack("<q", val)[0]
    if vtype == _CSTR:
        if not val or val[-1] != 0:
            raise ValueError("pod: cstr missing NUL terminator")
        return val[:-1].decode()
    if vtype == _BLOB:
        return bytes(val)
    if vtype == _DOUBLE:
        return struct.unpack("<d", val)[0]
    raise ValueError(f"pod: bad value type {vtype}")


def decode(pod: bytes) -> dict:
    """pod bytes -> dict (inverse of encode)."""
    return {k: _decode_leaf(t, v) for k, t, v in _iter_entries(pod)}


def query(pod: bytes, path: str, default=None):
    """Walk a dotted path without decoding the whole pod
    (fd_pod_query_* family).  Returns `default` when absent."""
    parts = path.split(".")
    cur = pod
    for i, part in enumerate(parts):
        found = False
        for k, t, v in _iter_entries(cur):
            if k != part:
                continue
            if i == len(parts) - 1:
                return _decode_leaf(t, v)
            if t != _SUBPOD:
                return default  # path descends through a leaf
            cur = v
            found = True
            break
        if not found:
            return default
    return default
