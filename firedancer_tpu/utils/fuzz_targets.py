"""Fuzz harnesses for every untrusted-input parser (the per-parser
libFuzzer targets of the reference, src/*/fuzz_*.c).

Contract: each harness consumes arbitrary bytes and either succeeds or
raises one of the parser's DECLARED error types, which the harness
swallows.  Any other exception escaping — or the process dying — is a
finding.  Seed corpora live in tests/corpus/<name>/ (regenerate with
tools/fuzz_corpus.py)."""

from __future__ import annotations

import struct


def t_txn(data: bytes) -> None:
    from ..ballet import txn
    try:
        txn.parse(bytes(data))
    except txn.TxnParseError:
        pass


def t_compact_u16(data: bytes) -> None:
    from ..ballet import compact_u16 as cu16
    try:
        v, n = cu16.decode(bytes(data))
        assert cu16.encode(v)[:n] == bytes(data[:n])  # roundtrip canonical
    except ValueError:
        pass


def t_shred(data: bytes) -> None:
    from ..ballet import shred
    try:
        shred.parse(bytes(data))
    except shred.ShredParseError:
        pass


def t_entry_batch(data: bytes) -> None:
    from ..ballet import entry
    try:
        entry.deserialize_batch(bytes(data))
    except ValueError:
        pass


def t_zstd(data: bytes) -> None:
    from ..ballet import zstd
    try:
        zstd.decompress(bytes(data), max_output=1 << 22)
    except zstd.ZstdError:
        pass


def t_gossip_msg(data: bytes) -> None:
    from ..flamenco import gossip
    try:
        gossip.decode(bytes(data))
    except (ValueError, struct.error):
        pass


def t_appendvec(data: bytes) -> None:
    from ..flamenco import snapshot
    try:
        list(snapshot.read_appendvec(bytes(data)))
    except (ValueError, struct.error):
        pass


def t_lookup_table(data: bytes) -> None:
    from ..flamenco import alut_program
    from ..flamenco.system_program import InstrError
    try:
        alut_program.LookupTable.deserialize(bytes(data))
    except (InstrError, struct.error):
        pass


def t_quic_datagram(data: bytes) -> None:
    """The QUIC server endpoint must absorb ANY datagram without raising
    (one bad packet must never kill the ingest tile).  A FRESH endpoint per
    input keeps findings replayable from the saved bytes alone — a shared
    endpoint would make crashes depend on accumulated connection state."""
    from ..waltz.aio import Aio, Pkt
    from ..waltz.quic import QuicConfig, QuicEndpoint
    ep = QuicEndpoint(
        QuicConfig(identity_seed=b"\x42" * 32, is_server=True),
        Aio(lambda pkts: len(pkts)))
    ep.rx([Pkt(bytes(data), ("fuzz", 1))], 1.0)
    ep.service(2.0)


def t_repair_msg(data: bytes) -> None:
    """Repair server returns None for garbage; must not raise."""
    from ..flamenco import repair
    srv = repair.RepairServer(lambda *a: True, lambda *a: None,
                              lambda *a: None)
    srv.handle(bytes(data))


TARGETS = {
    "txn": t_txn,
    "compact_u16": t_compact_u16,
    "shred": t_shred,
    "entry_batch": t_entry_batch,
    "zstd": t_zstd,
    "gossip_msg": t_gossip_msg,
    "appendvec": t_appendvec,
    "lookup_table": t_lookup_table,
    "quic_datagram": t_quic_datagram,
    "repair_msg": t_repair_msg,
}
