"""Fork-join thread pool (ref: src/util/tpool/fd_tpool.h:740-850 —
fd_tpool_exec dispatch + FD_TPOOL_EXEC_ALL round-robin/blocked tree
dispatch, used by the flamenco runtime for intra-block parallel txn
execution and snapshot hashing).

The reference spin-waits pinned threads; CPython threads + a condition
variable serve the same contract here, and the heavy work items (jax/numpy
ops, hashing) release the GIL so the parallelism is real for the workloads
that matter.  API mirrors the reference's shape: worker_cnt fixed at
construction, exec() dispatches one task to an idle worker, exec_all_*
fan a [lo, hi) range out and join.
"""

from __future__ import annotations

import threading
from typing import Callable


class TPool:
    def __init__(self, worker_cnt: int):
        if worker_cnt < 1:
            raise ValueError("worker_cnt must be >= 1")
        self.worker_cnt = worker_cnt
        self._tasks: list = []
        self._lock = threading.Lock()
        self._work_cv = threading.Condition(self._lock)
        self._done_cv = threading.Condition(self._lock)
        self._inflight = 0
        self._stop = False
        self._threads = [
            threading.Thread(target=self._worker, name=f"tpool-{i}",
                             daemon=True)
            for i in range(worker_cnt)
        ]
        for t in self._threads:
            t.start()
        self._errors: list[BaseException] = []

    def _worker(self):
        while True:
            with self._work_cv:
                while not self._tasks and not self._stop:
                    self._work_cv.wait()
                if self._stop and not self._tasks:
                    return
                fn, args = self._tasks.pop()
            try:
                fn(*args)
            except BaseException as e:  # propagate at join time
                with self._lock:
                    self._errors.append(e)
            finally:
                with self._done_cv:
                    self._inflight -= 1
                    if self._inflight == 0:
                        self._done_cv.notify_all()

    # ---------------------------------------------------------------- dispatch

    def exec(self, fn: Callable, *args) -> None:
        """Queue one task (fd_tpool_exec; unlike the reference there is no
        per-worker addressing — any idle worker picks it up)."""
        with self._work_cv:
            self._tasks.append((fn, args))
            self._inflight += 1
            self._work_cv.notify()

    def wait(self) -> None:
        """Join all outstanding tasks (fd_tpool_wait over every worker).
        Re-raises the first task exception."""
        with self._done_cv:
            while self._inflight:
                self._done_cv.wait()
            if self._errors:
                err = self._errors[0]
                self._errors.clear()
                raise err

    def exec_all_rrobin(self, task: Callable, lo: int, hi: int) -> None:
        """task(i) for i in [lo, hi), elements dealt round-robin across
        workers (FD_TPOOL_EXEC_ALL_RROBIN)."""
        def run(worker_idx: int):
            for i in range(lo + worker_idx, hi, self.worker_cnt):
                task(i)
        for w in range(min(self.worker_cnt, max(0, hi - lo))):
            self.exec(run, w)
        self.wait()

    def exec_all_block(self, task: Callable, lo: int, hi: int) -> None:
        """task(block_lo, block_hi) per worker with contiguous blocks
        (FD_TPOOL_EXEC_ALL_BLOCK) — right when task cost is uniform and
        locality matters."""
        n = hi - lo
        if n <= 0:
            return
        w = min(self.worker_cnt, n)
        step = -(-n // w)
        for i in range(w):
            blo = lo + i * step
            bhi = min(hi, blo + step)
            if blo < bhi:
                self.exec(task, blo, bhi)
        self.wait()

    def map(self, fn: Callable, xs: list) -> list:
        """Parallel map preserving order (the runtime's per-txn helper)."""
        out = [None] * len(xs)

        def run(i):
            out[i] = fn(xs[i])

        self.exec_all_rrobin(run, 0, len(xs))
        return out

    def close(self) -> None:
        with self._work_cv:
            self._stop = True
            self._work_cv.notify_all()
        for t in self._threads:
            t.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
