"""Fixed-bucket histograms for metrics (ref: src/util/hist/fd_histf.h —
exponential-bucket approximate histograms feeding the metrics region)."""

import numpy as np


class Histf:
    """Exponentially-bucketed histogram over [min_val, max_val], numpy-backed,
    single-writer (one per tile, like the reference's per-tile hist).

    Bucket layout: counts[i] holds samples v with edges[i-1] < v <= edges[i]
    (searchsorted, left); counts[-1] is the explicit OVERFLOW bucket — every
    sample above max_val is clamped there and visible via overflow_cnt(),
    never silently merged into the top finite bucket."""

    def __init__(self, min_val: float, max_val: float, nbuckets: int = 32):
        assert 0 < min_val < max_val
        self.edges = np.geomspace(min_val, max_val, nbuckets - 1)
        self.counts = np.zeros(nbuckets, dtype=np.uint64)
        self.sum = 0.0

    def sample(self, v: float):
        self.counts[np.searchsorted(self.edges, v)] += 1
        self.sum += v

    def count(self) -> int:
        return int(self.counts.sum())

    def overflow_cnt(self) -> int:
        """Samples above max_val (the reference's fd_histf_cnt overflow
        slot): a nonzero value means the configured range is too narrow
        for the distribution being measured."""
        return int(self.counts[-1])

    def percentile(self, q: float) -> float:
        total = int(self.counts.sum())
        if total == 0:
            return 0.0
        cum = np.cumsum(self.counts)
        # first bucket whose cumulative count reaches q*total; side="left"
        # matches the reference's acc >= target scan
        i = int(np.searchsorted(cum, np.uint64(max(1, int(np.ceil(
            q * total))))))
        return float(self.edges[min(i, len(self.edges) - 1)])
