"""Fixed-bucket histograms for metrics (ref: src/util/hist/fd_histf.h —
exponential-bucket approximate histograms feeding the metrics region)."""

import numpy as np


class Histf:
    """Exponentially-bucketed histogram over [min_val, max_val], numpy-backed,
    single-writer (one per tile, like the reference's per-tile hist)."""

    def __init__(self, min_val: float, max_val: float, nbuckets: int = 32):
        assert 0 < min_val < max_val
        self.edges = np.geomspace(min_val, max_val, nbuckets - 1)
        self.counts = np.zeros(nbuckets, dtype=np.uint64)
        self.sum = 0.0

    def sample(self, v: float):
        self.counts[np.searchsorted(self.edges, v)] += 1
        self.sum += v

    def count(self) -> int:
        return int(self.counts.sum())

    def percentile(self, q: float) -> float:
        total = self.counts.sum()
        if total == 0:
            return 0.0
        target = q * float(total)
        acc = 0.0
        for i, c in enumerate(self.counts):
            acc += float(c)
            if acc >= target:
                return float(self.edges[min(i, len(self.edges) - 1)])
        return float(self.edges[-1])
