"""Tile security sandbox, best-effort (ref: src/util/sandbox/fd_sandbox.c —
the reference unshares every namespace, installs seccomp-BPF allowlists,
applies Landlock, and drops capabilities; fd_sandbox.c:279-434).

CPython cannot install seccomp filters without a helper library, so this
module applies the subset of that hardening reachable from pure Python +
ctypes, in the same spirit (fail-closed where possible, observable
everywhere):

  * PR_SET_NO_NEW_PRIVS — no privilege escalation via exec
  * PR_SET_DUMPABLE=0   — no ptrace attach / core dumps of key material
  * RLIMIT clamps       — no forks (NPROC), no new files (NOFILE=current),
                          bounded address space optional
  * close_fds           — drop every fd above the allowlist
  * uid/gid switch when launched as root

`enter()` is called by the tile runner after privileged init, mirroring
fd_sandbox_enter's position in the boot sequence (fd_topo_run.c:96).
"""

from __future__ import annotations

import ctypes
import os
import resource

PR_SET_NO_NEW_PRIVS = 38
PR_SET_DUMPABLE = 4

_libc = ctypes.CDLL(None, use_errno=True)


def no_new_privs() -> bool:
    return _libc.prctl(PR_SET_NO_NEW_PRIVS, 1, 0, 0, 0) == 0


def undumpable() -> bool:
    return _libc.prctl(PR_SET_DUMPABLE, 0, 0, 0, 0) == 0


def close_fds(keep: set[int]) -> int:
    """Close every fd not in `keep` (the reference computes a per-tile fd
    allowlist; fd_sandbox_enter closes the rest).  Returns count closed."""
    closed = 0
    for fd in os.listdir("/proc/self/fd"):
        fd = int(fd)
        if fd in keep:
            continue
        try:
            os.close(fd)
            closed += 1
        except OSError:
            pass
    return closed


def clamp_rlimits(allow_files: bool = False,
                  address_space: int | None = None) -> None:
    """No forking; no new fds beyond what's open; optional AS cap."""
    resource.setrlimit(resource.RLIMIT_NPROC, (0, 0))
    if not allow_files:
        nofile = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
        # keep current descriptors usable but forbid growth
        resource.setrlimit(resource.RLIMIT_NOFILE, (nofile, nofile))
    if address_space is not None:
        resource.setrlimit(resource.RLIMIT_AS, (address_space, address_space))


def drop_root(uid: int = 65534, gid: int = 65534) -> bool:
    """setuid away from root (nobody by default); no-op when unprivileged."""
    if os.geteuid() != 0:
        return False
    os.setgroups([])
    os.setgid(gid)
    os.setuid(uid)
    return True


def enter(keep_fds: set[int] | None = None, allow_fork: bool = False,
          switch_uid: bool = False) -> dict:
    """Apply the full best-effort sandbox; returns a report of what held
    (tiles log it — observability over silent failure, the reference
    FD_LOG_ERRs instead because its primitives cannot fail)."""
    report = {
        "no_new_privs": no_new_privs(),
        "undumpable": undumpable(),
        "dropped_root": drop_root() if switch_uid else False,
    }
    if keep_fds is not None:
        report["fds_closed"] = close_fds(keep_fds)
    if not allow_fork:
        try:
            resource.setrlimit(resource.RLIMIT_NPROC, (0, 0))
            report["nproc_zero"] = True
        except (ValueError, OSError):
            report["nproc_zero"] = False
    return report
