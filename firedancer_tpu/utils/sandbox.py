"""Tile security sandbox (ref: src/util/sandbox/fd_sandbox.c — the
reference unshares every namespace, installs seccomp-BPF allowlists,
applies Landlock, and drops capabilities; fd_sandbox.c:279-434).

This module applies the same hardening classes from pure Python + ctypes:

  * seccomp-BPF          — real kernel syscall filters, built as raw
                           sock_filter programs (allowlist like the
                           reference's per-tile policies, or a denylist
                           of the dangerous set for CPython-compatible
                           best-effort tiles)
  * PR_SET_NO_NEW_PRIVS  — no privilege escalation via exec
  * PR_SET_DUMPABLE=0    — no ptrace attach / core dumps of key material
  * RLIMIT clamps        — no forks (NPROC), no new files, bounded AS
  * close_fds            — drop every fd above the allowlist
  * uid/gid switch when launched as root

Namespaces/Landlock remain out of scope (they need privileged helpers
this runtime doesn't assume).  `enter()` is called by the tile runner
after privileged init, mirroring fd_sandbox_enter's position in the boot
sequence (fd_topo_run.c:96).
"""

from __future__ import annotations

import ctypes
import os
import resource
import struct

PR_SET_NO_NEW_PRIVS = 38
PR_SET_DUMPABLE = 4
PR_SET_SECCOMP = 22
SECCOMP_MODE_FILTER = 2

_libc = ctypes.CDLL(None, use_errno=True)

# ---------------------------------------------------------------- seccomp
# classic-BPF opcodes (linux/bpf_common.h)
_BPF_LD_W_ABS = 0x20
_BPF_JMP_JEQ_K = 0x15
_BPF_JMP_JGE_K = 0x35
_BPF_RET_K = 0x06

# x32-ABI syscalls carry this bit yet report AUDIT_ARCH_X86_64, so an
# exact-match denylist would miss them all (kernels with CONFIG_X86_X32);
# any nr >= this bit must be rejected before per-syscall comparisons
_X32_SYSCALL_BIT = 0x40000000

_SECCOMP_RET_ALLOW = 0x7FFF0000
_SECCOMP_RET_ERRNO = 0x00050000
_SECCOMP_RET_KILL = 0x80000000

_AUDIT_ARCH_X86_64 = 0xC000003E
_SECCOMP_DATA_NR = 0
_SECCOMP_DATA_ARCH = 4

# x86_64 syscall numbers for the policy sets (subset; extend as needed)
SYSCALL_NR = {
    "read": 0, "write": 1, "open": 2, "close": 3, "fstat": 5, "lseek": 8,
    "mmap": 9, "mprotect": 10, "munmap": 11, "brk": 12,
    "rt_sigaction": 13, "rt_sigprocmask": 14, "rt_sigreturn": 15,
    "ioctl": 16, "pread64": 17, "pwrite64": 18, "readv": 19, "writev": 20,
    "sched_yield": 24, "mremap": 25, "msync": 26, "madvise": 28,
    "dup": 32, "nanosleep": 35, "getpid": 39,
    "socket": 41, "connect": 42, "accept": 43, "sendto": 44,
    "recvfrom": 45, "sendmsg": 46, "recvmsg": 47, "shutdown": 48,
    "bind": 49, "listen": 50, "sendmmsg": 307, "recvmmsg": 299,
    "clone": 56, "fork": 57, "vfork": 58, "execve": 59, "exit": 60,
    "kill": 62, "fcntl": 72, "getcwd": 79, "unlink": 87,
    "gettimeofday": 96, "ptrace": 101, "prctl": 157,
    "futex": 202, "epoll_wait": 232, "epoll_ctl": 233,
    "openat": 257, "exit_group": 231, "clock_gettime": 228,
    "clock_nanosleep": 230, "getrandom": 318, "memfd_create": 319,
    "execveat": 322, "poll": 7, "ppoll": 271, "epoll_pwait": 281,
    "accept4": 288, "eventfd2": 290, "epoll_create1": 291, "dup3": 292,
    "clone3": 435, "process_vm_readv": 310, "process_vm_writev": 311,
}

# syscalls no sandboxed tile has business making (the denylist policy).
# clone/clone3 are handled specially: threads must keep working (CPython,
# JAX), so clone is allowed ONLY with CLONE_THREAD and clone3 returns
# ENOSYS to force glibc's clone fallback.
DANGEROUS = (
    "socket", "connect", "accept", "accept4", "bind", "listen",
    "execve", "execveat", "fork", "vfork",
    "ptrace", "process_vm_readv", "process_vm_writev", "memfd_create",
)

_BPF_ALU_AND_K = 0x54
_SECCOMP_DATA_ARG0 = 16
_CLONE_THREAD = 0x00010000
_ENOSYS = 38


def _bpf(code: int, jt: int, jf: int, k: int) -> bytes:
    return struct.pack("<HBBI", code, jt, jf, k & 0xFFFFFFFF)


def _assemble(prog) -> bytes:
    """Two-pass mini-assembler: prog is a list of either ('label', name)
    or (code, jt, jf, k) where jt/jf may be label strings (resolved to
    forward skip counts)."""
    labels = {}
    pc = 0
    for ent in prog:
        if ent[0] == "label":
            labels[ent[1]] = pc
        else:
            pc += 1
    out = []
    pc = 0
    for ent in prog:
        if ent[0] == "label":
            continue
        code, jt, jf, k = ent
        if isinstance(jt, str):
            jt = labels[jt] - pc - 1
        if isinstance(jf, str):
            jf = labels[jf] - pc - 1
        assert 0 <= jt < 256 and 0 <= jf < 256, (jt, jf)
        out.append(_bpf(code, jt, jf, k))
        pc += 1
    return b"".join(out)


def seccomp_supported() -> bool:
    """The BPF programs and SYSCALL_NR table are x86_64-specific; on any
    other arch the filter would SIGSYS-kill the process on its first
    syscall (the arch-mismatch branch is RET_KILL by design)."""
    import platform

    return platform.machine() == "x86_64"


def _install_filter(prog: bytes, n_insns: int) -> bool:
    if not seccomp_supported():
        return False
    buf = ctypes.create_string_buffer(prog, len(prog))
    fprog = struct.pack("<HxxxxxxQ", n_insns, ctypes.addressof(buf))
    fbuf = ctypes.create_string_buffer(fprog, len(fprog))
    if not no_new_privs():
        return False
    # explicit 64-bit args: ctypes would otherwise truncate the pointer
    # to a C int and the kernel EFAULTs
    return _libc.prctl(
        ctypes.c_ulong(PR_SET_SECCOMP), ctypes.c_ulong(SECCOMP_MODE_FILTER),
        ctypes.c_ulong(ctypes.addressof(fbuf)), ctypes.c_ulong(0),
        ctypes.c_ulong(0)) == 0


def install_seccomp_deny(names=DANGEROUS, errno_: int = 1,
                         thread_safe_clone: bool = True) -> bool:
    """Deny the listed syscalls with EPERM-style errno, allow the rest —
    the CPython-compatible policy (an interpreter needs a broad base set;
    the reference's strict per-tile allowlists are the model for
    install_seccomp_allow).

    thread_safe_clone closes the fork-via-clone hole without breaking
    pthreads: clone is allowed only when its flags carry CLONE_THREAD,
    and clone3 gets ENOSYS so glibc falls back to clone."""
    prog = [
        (_BPF_LD_W_ABS, 0, 0, _SECCOMP_DATA_ARCH),
        (_BPF_JMP_JEQ_K, 1, 0, _AUDIT_ARCH_X86_64),
        (_BPF_RET_K, 0, 0, _SECCOMP_RET_KILL),
        (_BPF_LD_W_ABS, 0, 0, _SECCOMP_DATA_NR),
        # x32 ABI escape hatch: nr | 0x40000000 would fall through every
        # JEQ below; kill it first (libseccomp does the same)
        (_BPF_JMP_JGE_K, 0, 1, _X32_SYSCALL_BIT),
        (_BPF_RET_K, 0, 0, _SECCOMP_RET_KILL),
    ]
    if thread_safe_clone:
        prog.append((_BPF_JMP_JEQ_K, "enosys", 0, SYSCALL_NR["clone3"]))
        prog.append((_BPF_JMP_JEQ_K, "clone_chk", 0, SYSCALL_NR["clone"]))
    for n in names:
        prog.append((_BPF_JMP_JEQ_K, "deny", 0, SYSCALL_NR[n]))
    prog.append((_BPF_RET_K, 0, 0, _SECCOMP_RET_ALLOW))
    prog.append(("label", "deny"))
    prog.append((_BPF_RET_K, 0, 0, _SECCOMP_RET_ERRNO | errno_))
    if thread_safe_clone:
        prog.append(("label", "enosys"))
        prog.append((_BPF_RET_K, 0, 0, _SECCOMP_RET_ERRNO | _ENOSYS))
        prog.append(("label", "clone_chk"))
        prog.append((_BPF_LD_W_ABS, 0, 0, _SECCOMP_DATA_ARG0))
        prog.append((_BPF_ALU_AND_K, 0, 0, _CLONE_THREAD))
        prog.append((_BPF_JMP_JEQ_K, 1, 0, _CLONE_THREAD))
        prog.append((_BPF_RET_K, 0, 0, _SECCOMP_RET_ERRNO | errno_))
        prog.append((_BPF_RET_K, 0, 0, _SECCOMP_RET_ALLOW))
    blob = _assemble(prog)
    return _install_filter(blob, len(blob) // 8)


def install_seccomp_allow(names, default_errno: int | None = None) -> bool:
    """Allow ONLY the listed syscalls (plus exit/exit_group/sigreturn);
    everything else gets errno (or SIGSYS kill when default_errno is
    None) — the reference's per-tile allowlist shape
    (fd_sandbox.c seccomp policies)."""
    base = {"exit", "exit_group", "rt_sigreturn"}
    nrs = sorted({SYSCALL_NR[n] for n in set(names) | base})
    insns = [_bpf(_BPF_LD_W_ABS, 0, 0, _SECCOMP_DATA_ARCH)]
    insns.append(_bpf(_BPF_JMP_JEQ_K, 1, 0, _AUDIT_ARCH_X86_64))
    insns.append(_bpf(_BPF_RET_K, 0, 0, _SECCOMP_RET_KILL))
    insns.append(_bpf(_BPF_LD_W_ABS, 0, 0, _SECCOMP_DATA_NR))
    for i, nr in enumerate(nrs):
        insns.append(_bpf(_BPF_JMP_JEQ_K, len(nrs) - i, 0, nr))
    deny = (_SECCOMP_RET_KILL if default_errno is None
            else _SECCOMP_RET_ERRNO | default_errno)
    insns.append(_bpf(_BPF_RET_K, 0, 0, deny))
    insns.append(_bpf(_BPF_RET_K, 0, 0, _SECCOMP_RET_ALLOW))
    return _install_filter(b"".join(insns), len(insns))


def no_new_privs() -> bool:
    return _libc.prctl(PR_SET_NO_NEW_PRIVS, 1, 0, 0, 0) == 0


def undumpable() -> bool:
    return _libc.prctl(PR_SET_DUMPABLE, 0, 0, 0, 0) == 0


def close_fds(keep: set[int]) -> int:
    """Close every fd not in `keep` (the reference computes a per-tile fd
    allowlist; fd_sandbox_enter closes the rest).  Returns count closed."""
    closed = 0
    for fd in os.listdir("/proc/self/fd"):
        fd = int(fd)
        if fd in keep:
            continue
        try:
            os.close(fd)
            closed += 1
        except OSError:
            pass
    return closed


def clamp_rlimits(allow_files: bool = False,
                  address_space: int | None = None) -> None:
    """No forking; no new fds beyond what's open; optional AS cap."""
    resource.setrlimit(resource.RLIMIT_NPROC, (0, 0))
    if not allow_files:
        nofile = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
        # keep current descriptors usable but forbid growth
        resource.setrlimit(resource.RLIMIT_NOFILE, (nofile, nofile))
    if address_space is not None:
        resource.setrlimit(resource.RLIMIT_AS, (address_space, address_space))


def drop_root(uid: int = 65534, gid: int = 65534) -> bool:
    """setuid away from root (nobody by default); no-op when unprivileged."""
    if os.geteuid() != 0:
        return False
    os.setgroups([])
    os.setgid(gid)
    os.setuid(uid)
    return True


def enter(keep_fds: set[int] | None = None, allow_fork: bool = False,
          switch_uid: bool = False, seccomp: bool = True,
          seccomp_deny=DANGEROUS) -> dict:
    """Apply the full sandbox; returns a report of what held (tiles log
    it — observability over silent failure, the reference FD_LOG_ERRs
    instead because its primitives cannot fail).  seccomp installs the
    denylist policy LAST (after fd close / uid drop, which it would
    otherwise forbid)."""
    report = {
        "no_new_privs": no_new_privs(),
        "undumpable": undumpable(),
        "dropped_root": drop_root() if switch_uid else False,
    }
    if keep_fds is not None:
        report["fds_closed"] = close_fds(keep_fds)
    if not allow_fork:
        try:
            resource.setrlimit(resource.RLIMIT_NPROC, (0, 0))
            report["nproc_zero"] = True
        except (ValueError, OSError):
            report["nproc_zero"] = False
    if seccomp:
        deny = tuple(seccomp_deny)
        if allow_fork:
            deny = tuple(n for n in deny if n not in ("fork", "vfork"))
        try:
            # allow_fork also lifts the clone-flags restriction (fork is
            # clone-without-CLONE_THREAD under glibc)
            report["seccomp"] = install_seccomp_deny(
                deny, thread_safe_clone=not allow_fork)
        except OSError:
            report["seccomp"] = False
    return report
