"""Host utilities (the reference's `util` layer, src/util — reduced to what a
TPU-era python/C++ runtime actually needs; hugepage/NUMA plumbing is replaced
by jax device memory, templated containers by python/numpy)."""
