"""Persistent XLA compilation cache.

Tile processes are short-lived relative to XLA compile times (the batched
ed25519 verify graph takes minutes to compile on the CPU backend), so every
entry point that jits device code enables the on-disk cache: first boot
pays, every later process joins instantly.  The reference has no analogue —
its compile cost is `make` — but this is the same role as its build cache.
"""

import os

_DEFAULT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", ".xla_cache"))

_enabled = False


def enable(path: str | None = None):
    global _enabled
    if _enabled:
        return
    import jax

    path = path or os.environ.get("FDTPU_XLA_CACHE", _DEFAULT)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _enabled = True
