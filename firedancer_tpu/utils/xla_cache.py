"""Persistent XLA compilation cache.

Tile processes are short-lived relative to XLA compile times (the batched
ed25519 verify graph takes minutes to compile on the CPU backend), so every
entry point that jits device code enables the on-disk cache: first boot
pays, every later process joins instantly.  The reference has no analogue —
its compile cost is `make` — but this is the same role as its build cache.
"""

import os

_enabled = False


def _default_dir() -> str:
    # repo-relative when running from a source checkout (shared across the
    # test matrix), else a per-user cache (site-packages isn't writable)
    repo = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))
    cand = os.path.join(repo, ".xla_cache")
    try:
        os.makedirs(cand, exist_ok=True)
        return cand
    except OSError:
        return os.path.join(
            os.environ.get("XDG_CACHE_HOME",
                           os.path.expanduser("~/.cache")), "fdtpu_xla")


def cache_dir() -> str:
    """The cache directory enable() uses/used — the one true location for
    cache-adjacent artifacts like the PRIMED sentinel (hard-coding
    repo/.xla_cache lied whenever FDTPU_XLA_CACHE pointed elsewhere)."""
    return os.environ.get("FDTPU_XLA_CACHE") or _default_dir()


def enable(path: str | None = None, readonly: bool | None = None):
    """readonly=True (or FDTPU_XLA_CACHE_READONLY=1) reads cache entries
    but never WRITES them: this jaxlib's executable-serialization path
    segfaults sporadically on large CPU graphs, and a tile process dying
    mid-boot to a cache write is a far worse trade than re-compiling an
    unprimed shape.  Tile processes (disco/run.py) default to readonly;
    the prime script and test mains keep writing."""
    global _enabled
    if _enabled:
        return
    import jax

    path = path or os.environ.get("FDTPU_XLA_CACHE") or _default_dir()
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    if readonly is None:
        readonly = bool(os.environ.get("FDTPU_XLA_CACHE_READONLY"))
    if readonly:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1e9)
    else:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _enabled = True
