"""Per-tile frame scratch allocator (ref: src/util/scratch/fd_scratch.c —
push/pop frames over a bump region; the per-callback workspace every tile
uses so the hot loop never touches malloc).

Python objects don't need manual memory, but buffer-shaped work (packet
staging, hash preimage assembly) still wants zero-alloc reuse: Scratch
hands out memoryviews into one preallocated bytearray, and frame pop
invalidates everything allocated since the matching push in O(1).
"""

from __future__ import annotations


class ScratchError(RuntimeError):
    pass


class Scratch:
    def __init__(self, sz: int = 1 << 20, frame_max: int = 64):
        self._buf = bytearray(sz)
        self._mv = memoryview(self._buf)
        self.sz = sz
        self.frame_max = frame_max
        self._off = 0
        self._frames: list[int] = []

    def push(self) -> None:
        if len(self._frames) >= self.frame_max:
            raise ScratchError("scratch frame overflow")
        self._frames.append(self._off)

    def pop(self) -> None:
        if not self._frames:
            raise ScratchError("scratch pop without push")
        self._off = self._frames.pop()

    def alloc(self, sz: int, align: int = 8) -> memoryview:
        if not self._frames:
            raise ScratchError("scratch alloc outside a frame")
        start = (self._off + align - 1) & ~(align - 1)
        if start + sz > self.sz:
            raise ScratchError(
                f"scratch exhausted ({start + sz} > {self.sz})")
        self._off = start + sz
        return self._mv[start : start + sz]

    @property
    def depth(self) -> int:
        return len(self._frames)

    def used(self) -> int:
        return self._off

    def __enter__(self):
        self.push()
        return self

    def __exit__(self, *exc):
        self.pop()
