"""Coverage-guided fuzzing engine for the host-side parsers.

Role of the reference's libFuzzer harnesses + corpora
(src/util/sanitize/fd_fuzz_stub.c, corpus/): each parser gets a harness
`fn(data: bytes) -> None` that must either parse or raise one of its
DECLARED exception types — anything else (or a hang/huge allocation) is a
finding.  The engine mutates a seed corpus and keeps inputs that reach new
(file, line) pairs, using sys.monitoring line events as the coverage map —
the pure-Python analogue of SanitizerCoverage edge counters.

Two modes:
  * replay(corpus_dir, harness): run every stored seed once (the
    fd_fuzz_stub stub-replay mode; what CI runs).
  * fuzz(harness, seeds, iters): bounded mutation loop, returns
    (new_coverage_inputs, crashes).
"""

from __future__ import annotations

import hashlib
import random
import sys

_TOOL_ID = 3  # sys.monitoring tool slot (PROFILER_ID=2, OPTIMIZER=5 taken)


class CoverageMap:
    """Line-coverage collector scoped to firedancer_tpu modules."""

    def __init__(self):
        self.seen: set = set()
        self._batch: set = set()

    def __enter__(self):
        mon = sys.monitoring
        mon.use_tool_id(_TOOL_ID, "fdtpu-fuzz")
        mon.register_callback(_TOOL_ID, mon.events.LINE, self._on_line)
        mon.set_events(_TOOL_ID, mon.events.LINE)
        return self

    def __exit__(self, *exc):
        mon = sys.monitoring
        mon.set_events(_TOOL_ID, 0)
        mon.register_callback(_TOOL_ID, mon.events.LINE, None)
        mon.free_tool_id(_TOOL_ID)

    def _on_line(self, code, line):
        fn = code.co_filename
        if "firedancer_tpu" in fn:
            self._batch.add((fn, line))
        return sys.monitoring.DISABLE  # each line reported once per batch

    def snapshot_new(self) -> int:
        """New lines since the previous snapshot; restarts per-line events."""
        new = self._batch - self.seen
        self.seen |= self._batch
        self._batch = set()
        sys.monitoring.restart_events()
        return len(new)


def mutate(data: bytes, rng: random.Random, corpus: list[bytes]) -> bytes:
    buf = bytearray(data)
    for _ in range(rng.randint(1, 4)):
        op = rng.randrange(6)
        if op == 0 and buf:            # bit flip
            i = rng.randrange(len(buf))
            buf[i] ^= 1 << rng.randrange(8)
        elif op == 1 and buf:          # byte set (interesting values)
            i = rng.randrange(len(buf))
            buf[i] = rng.choice((0, 1, 0x7F, 0x80, 0xFF, rng.randrange(256)))
        elif op == 2 and buf:          # chunk delete
            i = rng.randrange(len(buf))
            del buf[i:i + rng.randint(1, 8)]
        elif op == 3:                  # chunk insert
            i = rng.randrange(len(buf) + 1)
            buf[i:i] = bytes(rng.randrange(256)
                             for _ in range(rng.randint(1, 8)))
        elif op == 4 and corpus:       # splice from another corpus entry
            other = rng.choice(corpus)
            if other:
                i = rng.randrange(len(buf) + 1)
                j = rng.randrange(len(other))
                buf[i:i] = other[j:j + rng.randint(1, 32)]
        elif op == 5 and len(buf) > 1:  # truncate
            buf = buf[:rng.randrange(1, len(buf))]
    return bytes(buf)


class Finding(Exception):
    def __init__(self, data: bytes, exc: BaseException):
        super().__init__(f"{type(exc).__name__}: {exc}")
        self.data = data
        self.exc = exc


def fuzz(harness, seeds: list[bytes], iters: int = 2000, seed: int = 0,
         max_len: int = 4096):
    """Mutation loop with line-coverage feedback.  Returns
    (coverage_corpus, findings): inputs that reached new lines, and
    (data, exception) pairs for non-declared exceptions."""
    rng = random.Random(seed)
    corpus = [s[:max_len] for s in seeds] or [b""]
    findings: list[tuple[bytes, BaseException]] = []
    with CoverageMap() as cov:
        for s in corpus:
            try:
                harness(s)
            except Exception as e:  # seed corpora must already be clean
                findings.append((s, e))
        cov.snapshot_new()
        for i in range(iters):
            data = mutate(rng.choice(corpus), rng, corpus)[:max_len]
            try:
                harness(data)
            except Exception as e:
                findings.append((data, e))
                continue
            if cov.snapshot_new():
                corpus.append(data)
    return corpus[len(seeds):], findings


def replay(corpus_dir, harness) -> int:
    """Stub-replay: run every file in `corpus_dir` through the harness
    (declared parse errors are fine; anything else raises).  Returns the
    number of inputs replayed."""
    import pathlib

    n = 0
    for p in sorted(pathlib.Path(corpus_dir).iterdir()):
        if p.is_file():
            harness(p.read_bytes())
            n += 1
    return n


def corpus_name(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()[:16]
