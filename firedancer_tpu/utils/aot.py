"""AOT-compiled executable store for tile boot.

The reference ships precompiled tile binaries — boot is exec() plus a
shared-memory join (src/app/fdctl/run/run.c).  The TPU-native analogue of
that artifact is a serialized XLA executable: the topology builder (or the
bench harness) compiles the verify graph ONCE, serializes it here, and
every spawn-context tile process loads it in ~1 s — no re-trace, no
re-lower, no backend compile.  Measured on this host: a child boots the
(2048, 256) strict verify graph in 1.3 s from the store vs minutes of
trace+lower under multi-child CPU contention (the round-4 mp_vps boot
timeout, VERDICT r4 weak #1).

Artifacts are keyed by graph name, backend, shape parts, jax version and a
hash of the crypto-op sources, so a stale store entry can never serve a
changed graph — a miss falls back to jit (or raises, if the caller demands
warm boot with `require`).
"""

import hashlib
import hmac as _hmac
import os
import pickle

_SRC_HASH = None

# Artifacts are pickles, and unpickling attacker-controlled bytes is code
# execution.  Every artifact is therefore framed as
#     MAGIC | hmac_sha256(store_key, pickle) | pickle
# and load() refuses anything unsigned or mis-signed BEFORE pickle.load
# ever sees it.  The store key is derived from a per-workspace master key
# (0o600, created O_EXCL so concurrent first-writers agree) and the
# store's realpath, so an artifact copied between stores re-verifies only
# under the same master key.
_MAGIC = b"FDTPUAOT1\n"
_KEY_ENV = "FDTPU_AOT_KEY_FILE"


def _master_key_path() -> str:
    p = os.environ.get(_KEY_ENV)
    if p:
        return p
    return os.path.join(os.path.expanduser("~"), ".cache", "fdtpu",
                        "aot_hmac.key")


def _master_key() -> bytes:
    path = _master_key_path()
    try:
        with open(path, "rb") as f:
            k = f.read()
        if len(k) >= 32:
            return k
    except OSError:
        pass
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fresh = os.urandom(32)
    try:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
    except FileExistsError:
        with open(path, "rb") as f:  # raced: the O_EXCL winner decides
            return f.read()
    with os.fdopen(fd, "wb") as f:
        f.write(fresh)
    return fresh


def _store_key(dirpath: str) -> bytes:
    return _hmac.new(_master_key(),
                     b"fdtpu-aot\0" + os.path.realpath(dirpath).encode(),
                     hashlib.sha256).digest()


def _src_hash() -> str:
    """Content hash of the modules that define the verify/packed graphs:
    any edit invalidates every stored executable built from them (and the
    test-cache PRIMED sentinel keyed by this hash)."""
    global _SRC_HASH
    if _SRC_HASH is None:
        from .. import ops

        h = hashlib.sha256()
        d = os.path.dirname(ops.__file__)
        pkg = os.path.dirname(d)
        files = [os.path.join(d, n) for n in sorted(os.listdir(d))
                 if n.endswith(".py")]
        # graph definitions outside ops/: the packed dispatch wrapper and
        # this module's compile entry points (code-review r5: a layout
        # edit there must not leave a stale-valid sentinel); round 13 adds
        # the shred-lane graph sources (batched RS recover + merkle walk)
        files += [os.path.join(pkg, "models", "verifier.py"),
                  os.path.join(pkg, "utils", "aot.py"),
                  os.path.join(pkg, "ballet", "reedsol.py"),
                  os.path.join(pkg, "ballet", "bmtree.py")]
        for path in files:
            with open(path, "rb") as f:
                h.update(os.path.basename(path).encode())
                h.update(f.read())
        _SRC_HASH = h.hexdigest()[:12]
    return _SRC_HASH


def key(name: str, *parts) -> str:
    import jax

    backend = jax.default_backend()
    bits = "-".join(str(p) for p in parts)
    return f"{name}-{backend}-{bits}-jax{jax.__version__}-{_src_hash()}.aotx"


def save(dirpath: str, k: str, compiled) -> str:
    """Serialize a jax Compiled (fn.lower(...).compile()) under dirpath/k,
    HMAC-signed (see _MAGIC framing above).  Atomic: partial writes can
    never be loaded."""
    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = se.serialize(compiled)
    os.makedirs(dirpath, exist_ok=True)
    blob = pickle.dumps((payload, in_tree, out_tree))
    tag = _hmac.new(_store_key(dirpath), blob, hashlib.sha256).digest()
    path = os.path.join(dirpath, k)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(_MAGIC + tag + blob)
    os.replace(tmp, path)
    return path


def load(dirpath: str, k: str):
    """Deserialize a stored executable; None on any miss/corruption (the
    caller decides between jit fallback and loud failure).  Unsigned
    (legacy raw-pickle) or mis-signed artifacts are refused WITHOUT
    unpickling — pickle bytes an attacker could have written are code
    execution, so authentication comes first."""
    from jax.experimental import serialize_executable as se

    path = os.path.join(dirpath, k)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return None
    hlen = len(_MAGIC) + 32
    if len(raw) < hlen or not raw.startswith(_MAGIC):
        return None  # unsigned/legacy artifact: recompile, never unpickle
    tag, blob = raw[len(_MAGIC) : hlen], raw[hlen:]
    want = _hmac.new(_store_key(dirpath), blob, hashlib.sha256).digest()
    if not _hmac.compare_digest(tag, want):
        return None
    try:
        payload, in_tree, out_tree = pickle.loads(blob)
        return se.deserialize_and_load(payload, in_tree, out_tree)
    except Exception:  # stale jaxlib, truncated file: recompile instead
        return None


def _mode_suffix(mode: str) -> str:
    """AOT key namespace per verify mode: strict keeps the historical
    bare names; antipa graphs store under verify[-packed]-antipa."""
    if mode == "strict":
        return ""
    if mode == "antipa":
        return "-antipa"
    raise ValueError(f"no AOT graph for verify mode {mode!r}")


def _poke(heartbeat_cb) -> None:
    """Best-effort liveness poke between compile-ladder rungs: a verify
    tile compiling a large shape ladder must not be declared stale and
    killed by supervision (run.py heartbeat_timeout_s) mid-warmup."""
    if heartbeat_cb is not None:
        try:
            heartbeat_cb()
        except Exception:
            pass  # liveness is advisory; never fail a compile over it


def compile_verify_packed(batch: int, maxlen: int, mode: str = "strict",
                          heartbeat_cb=None):
    """Compile the packed-blob verify graph (ops.ed25519.verify_blob —
    the ONE definition of the row layout, shared with SigVerifier's
    packed dispatch and the native parser's packed-bucket fill; antipa
    mode compiles verify_blob_antipa over the same layout)."""
    import functools

    import jax
    import jax.numpy as jnp

    from ..ops import ed25519 as ed

    _mode_suffix(mode)  # validate
    blob_fn = ed.verify_blob_antipa if mode == "antipa" else ed.verify_blob
    _poke(heartbeat_cb)
    lowered = (jax.jit(functools.partial(blob_fn, maxlen=maxlen))
               .lower(jnp.zeros((batch, maxlen + ed.PACKED_EXTRA),
                                jnp.uint8)))
    _poke(heartbeat_cb)
    compiled = lowered.compile()
    _poke(heartbeat_cb)
    return compiled


def ensure_verify_packed(dirpath: str, batch: int, maxlen: int,
                         mode: str = "strict",
                         heartbeat_cb=None) -> str | None:
    """Compile-store-verify the packed verify graph (see ensure_verify)."""
    k = key("verify-packed" + _mode_suffix(mode), batch, maxlen)
    if load(dirpath, k) is not None:
        _poke(heartbeat_cb)
        return k
    save(dirpath, k, compile_verify_packed(batch, maxlen, mode=mode,
                                           heartbeat_cb=heartbeat_cb))
    _poke(heartbeat_cb)
    if load(dirpath, k) is None:
        try:
            os.remove(os.path.join(dirpath, k))
        except OSError:
            pass
        return None
    return k


def compile_shred_recover(batch: int, k_max: int, n_max: int, sz: int,
                          heartbeat_cb=None):
    """Compile the packed-blob batched RS-recover graph
    (ballet.reedsol.recover_blob — the shred-recover workload the
    dispatch engine rotates, one FEC set per row)."""
    import functools

    import jax
    import jax.numpy as jnp

    from ..ballet import reedsol as rs

    _poke(heartbeat_cb)
    lowered = (
        jax.jit(functools.partial(rs.recover_blob, k_max=k_max,
                                  n_max=n_max, sz=sz))
        .lower(
            jnp.zeros((batch, rs.recover_blob_row_bytes(k_max, n_max, sz)),
                      jnp.uint8),
            jnp.zeros((batch, 8 * n_max, 8 * k_max), jnp.int8)))
    _poke(heartbeat_cb)
    compiled = lowered.compile()
    _poke(heartbeat_cb)
    return compiled


def ensure_shred_recover(dirpath: str, batch: int, k_max: int, n_max: int,
                         sz: int, heartbeat_cb=None) -> str | None:
    """Compile-store-verify the shred-recover graph (see ensure_verify)."""
    k = key("shred-recover", batch, k_max, n_max, sz)
    if load(dirpath, k) is not None:
        _poke(heartbeat_cb)
        return k
    save(dirpath, k, compile_shred_recover(batch, k_max, n_max, sz,
                                           heartbeat_cb=heartbeat_cb))
    _poke(heartbeat_cb)
    if load(dirpath, k) is None:
        try:
            os.remove(os.path.join(dirpath, k))
        except OSError:
            pass
        return None
    return k


def compile_verify(batch: int, maxlen: int, mode: str = "strict",
                   heartbeat_cb=None):
    """Compile the 4-array verify graph at (batch, maxlen) -> Compiled
    (strict by default; mode="antipa" compiles the halved chain)."""
    import jax
    import jax.numpy as jnp

    from ..ops import ed25519 as ed

    _mode_suffix(mode)  # validate
    batch_fn = ed.verify_batch_antipa if mode == "antipa" else ed.verify_batch
    _poke(heartbeat_cb)
    lowered = jax.jit(batch_fn).lower(
        jnp.zeros((batch, maxlen), jnp.uint8),
        jnp.zeros((batch,), jnp.int32),
        jnp.zeros((batch, 64), jnp.uint8),
        jnp.zeros((batch, 32), jnp.uint8),
    )
    _poke(heartbeat_cb)
    compiled = lowered.compile()
    _poke(heartbeat_cb)
    return compiled


def ensure_verify(dirpath: str, batch: int, maxlen: int,
                  mode: str = "strict", heartbeat_cb=None) -> str | None:
    """Compile-and-store the verify graph unless already present, then
    VERIFY the artifact round-trips (this jaxlib's XLA:CPU AOT loader
    rejects its own artifacts across machine-feature sets — a saved-but-
    unloadable artifact plus aot_require would kill every child at boot).
    Returns the key on success, None when AOT is unusable on this backend
    (callers fall back to the jit+cache boot path)."""
    k = key("verify" + _mode_suffix(mode), batch, maxlen)
    if load(dirpath, k) is not None:
        _poke(heartbeat_cb)
        return k
    save(dirpath, k, compile_verify(batch, maxlen, mode=mode,
                                    heartbeat_cb=heartbeat_cb))
    _poke(heartbeat_cb)
    if load(dirpath, k) is None:
        try:
            os.remove(os.path.join(dirpath, k))  # never leave a bad artifact
        except OSError:
            pass
        return None
    return k
