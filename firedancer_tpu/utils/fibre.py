"""Cooperative fibres (ref: src/util/fibre/fd_fibre.c — ucontext-based
coroutines with a virtual-clock scheduler, used by the reference's waltz
ip tests to simulate concurrent protocol endpoints deterministically).

Python generators + an explicit run queue give the same contract: start
fibres, `yield` to switch, schedule wakeups on a virtual clock, run until
idle.  Deterministic by construction — no threads, no preemption.
"""

from __future__ import annotations

import heapq
from typing import Callable, Generator


class Fibre:
    def __init__(self, fid: int, gen: Generator):
        self.fid = fid
        self.gen = gen
        self.done = False


class FibreSched:
    """Virtual-clock cooperative scheduler (fd_fibre_schedule_run).

    A fibre body is a generator; `yield delay` suspends it and reschedules
    it `delay` virtual ns later (yield 0 = yield the processor now)."""

    def __init__(self):
        self.now = 0
        self._q: list[tuple[int, int, Fibre]] = []
        self._seq = 0
        self._nfid = 0

    def start(self, fn: Callable[..., Generator], *args) -> Fibre:
        self._nfid += 1
        f = Fibre(self._nfid, fn(*args))
        self._push(self.now, f)
        return f

    def _push(self, when: int, f: Fibre):
        self._seq += 1
        heapq.heappush(self._q, (when, self._seq, f))

    def run(self, until: int | None = None) -> int:
        """Run until the queue drains or virtual time passes `until`.
        Returns the final virtual clock."""
        while self._q:
            when, _, f = self._q[0]
            if until is not None and when > until:
                break
            heapq.heappop(self._q)
            self.now = max(self.now, when)
            try:
                delay = next(f.gen)
            except StopIteration:
                f.done = True
                continue
            self._push(self.now + max(0, int(delay or 0)), f)
        return self.now
