"""Host routing/neighbor mirror (ref: src/waltz/ip/fd_ip.c +
fd_netlink.c — the reference mirrors the kernel's route and ARP tables
over netlink so the net tile can resolve TX next hops without syscalls
per packet).

Python reads the same state from procfs (/proc/net/route, /proc/net/arp)
— no netlink socket needed for a periodic mirror — and answers the same
query: given a destination IPv4, which interface/gateway/MAC does the
first packet go to?  Refresh is explicit (`refresh()`), called from tile
housekeeping just like the reference's netlink re-sync.
"""

from __future__ import annotations

import socket
import struct
from dataclasses import dataclass


def _ip_to_int(ip: str) -> int:
    return struct.unpack("!I", socket.inet_aton(ip))[0]


def _int_to_ip(v: int) -> str:
    return socket.inet_ntoa(struct.pack("!I", v))


@dataclass(frozen=True)
class Route:
    dest: int
    mask: int
    gateway: int  # 0 = on-link
    iface: str
    metric: int

    @property
    def prefix_len(self) -> int:
        return bin(self.mask).count("1")


@dataclass(frozen=True)
class NextHop:
    iface: str
    gateway: str | None  # None = deliver direct
    mac: str | None  # from the neighbor table, if resolved


class IpTable:
    def __init__(self, route_path: str = "/proc/net/route",
                 arp_path: str = "/proc/net/arp"):
        self._route_path = route_path
        self._arp_path = arp_path
        self.routes: list[Route] = []
        self.neigh: dict[int, tuple[str, str]] = {}  # ip -> (mac, iface)
        self.refresh()

    def refresh(self) -> None:
        """Re-mirror kernel state (the netlink resync analogue)."""
        routes = []
        try:
            with open(self._route_path) as f:
                next(f, None)  # header
                for line in f:
                    parts = line.split()
                    if len(parts) < 8:
                        continue
                    iface = parts[0]
                    # procfs encodes addresses little-endian hex
                    dest = socket.ntohl(int(parts[1], 16))
                    gw = socket.ntohl(int(parts[2], 16))
                    metric = int(parts[6])
                    mask = socket.ntohl(int(parts[7], 16))
                    routes.append(Route(dest, mask, gw, iface, metric))
        except OSError:
            pass
        # longest-prefix first, then lowest metric (lookup takes first hit)
        routes.sort(key=lambda r: (-r.prefix_len, r.metric))
        self.routes = routes

        neigh = {}
        try:
            with open(self._arp_path) as f:
                next(f, None)
                for line in f:
                    parts = line.split()
                    if len(parts) < 6:
                        continue
                    ip, mac, iface = parts[0], parts[3], parts[5]
                    if mac != "00:00:00:00:00:00":
                        neigh[_ip_to_int(ip)] = (mac, iface)
        except OSError:
            pass
        self.neigh = neigh

    def route(self, dst_ip: str) -> NextHop | None:
        """Longest-prefix-match next hop for dst (fd_ip_route_ip_addr)."""
        d = _ip_to_int(dst_ip)
        for r in self.routes:
            if (d & r.mask) == (r.dest & r.mask):
                if r.gateway:
                    mac = self.neigh.get(r.gateway, (None, None))[0]
                    return NextHop(r.iface, _int_to_ip(r.gateway), mac)
                mac = self.neigh.get(d, (None, None))[0]
                return NextHop(r.iface, None, mac)
        return None
