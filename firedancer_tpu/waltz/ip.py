"""Host routing/neighbor mirror (ref: src/waltz/ip/fd_ip.c +
fd_netlink.c — the reference mirrors the kernel's route and ARP tables
over netlink so the net tile can resolve TX next hops without syscalls
per packet).

Python reads the same state from procfs (/proc/net/route, /proc/net/arp)
— no netlink socket needed for a periodic mirror — and answers the same
query: given a destination IPv4, which interface/gateway/MAC does the
first packet go to?  Refresh is explicit (`refresh()`), called from tile
housekeeping just like the reference's netlink re-sync.
"""

from __future__ import annotations

import socket
import struct
from dataclasses import dataclass


def _ip_to_int(ip: str) -> int:
    return struct.unpack("!I", socket.inet_aton(ip))[0]


def _int_to_ip(v: int) -> str:
    return socket.inet_ntoa(struct.pack("!I", v))


@dataclass(frozen=True)
class Route:
    dest: int
    mask: int
    gateway: int  # 0 = on-link
    iface: str
    metric: int

    @property
    def prefix_len(self) -> int:
        return bin(self.mask).count("1")


@dataclass(frozen=True)
class NextHop:
    iface: str
    gateway: str | None  # None = deliver direct
    mac: str | None  # from the neighbor table, if resolved


class IpTable:
    def __init__(self, route_path: str = "/proc/net/route",
                 arp_path: str = "/proc/net/arp"):
        self._route_path = route_path
        self._arp_path = arp_path
        self.routes: list[Route] = []
        self.neigh: dict[int, tuple[str, str]] = {}  # ip -> (mac, iface)
        self.refresh()

    def refresh(self) -> None:
        """Re-mirror kernel state (the netlink resync analogue)."""
        routes = []
        try:
            with open(self._route_path) as f:
                next(f, None)  # header
                for line in f:
                    parts = line.split()
                    if len(parts) < 8:
                        continue
                    iface = parts[0]
                    # procfs encodes addresses little-endian hex
                    dest = socket.ntohl(int(parts[1], 16))
                    gw = socket.ntohl(int(parts[2], 16))
                    metric = int(parts[6])
                    mask = socket.ntohl(int(parts[7], 16))
                    routes.append(Route(dest, mask, gw, iface, metric))
        except OSError:
            pass
        # longest-prefix first, then lowest metric (lookup takes first hit)
        routes.sort(key=lambda r: (-r.prefix_len, r.metric))
        self.routes = routes

        neigh = {}
        try:
            with open(self._arp_path) as f:
                next(f, None)
                for line in f:
                    parts = line.split()
                    if len(parts) < 6:
                        continue
                    ip, mac, iface = parts[0], parts[3], parts[5]
                    if mac != "00:00:00:00:00:00":
                        neigh[_ip_to_int(ip)] = (mac, iface)
        except OSError:
            pass
        self.neigh = neigh

    def route(self, dst_ip: str) -> NextHop | None:
        """Longest-prefix-match next hop for dst (fd_ip_route_ip_addr)."""
        d = _ip_to_int(dst_ip)
        for r in self.routes:
            if (d & r.mask) == (r.dest & r.mask):
                if r.gateway:
                    mac = self.neigh.get(r.gateway, (None, None))[0]
                    return NextHop(r.iface, _int_to_ip(r.gateway), mac)
                mac = self.neigh.get(d, (None, None))[0]
                return NextHop(r.iface, None, mac)
        return None


# ----------------------------------------------------------------- netlink
# The REAL kernel interface (round 5; parity with fd_netlink.c): rtnetlink
# RTM_GETROUTE / RTM_GETNEIGH dumps over an AF_NETLINK socket.  Use
# NetlinkIpTable to prefer it (falling back to the procfs mirror where
# the socket is denied); plain IpTable stays procfs-only.  Same
# Route/NextHop view either way.

NETLINK_ROUTE = 0
NLM_F_REQUEST, NLM_F_DUMP = 0x1, 0x300
NLMSG_DONE, NLMSG_ERROR = 3, 2
RTM_GETROUTE, RTM_GETNEIGH = 26, 30
RTA_DST, RTA_OIF, RTA_GATEWAY, RTA_PRIORITY = 1, 4, 5, 6
NDA_DST, NDA_LLADDR = 1, 2
AF_INET = socket.AF_INET




def _ifnames() -> dict[int, str]:
    return {idx: name for idx, name in socket.if_nameindex()}


def _nl_dump(msg_type: int, payload: bytes) -> list[tuple[int, bytes]]:
    """One rtnetlink dump request -> [(nlmsg_type, nlmsg_payload)]."""
    s = socket.socket(socket.AF_NETLINK, socket.SOCK_RAW, NETLINK_ROUTE)
    try:
        s.bind((0, 0))
        hdr = struct.pack("<IHHII", 16 + len(payload), msg_type,
                          NLM_F_REQUEST | NLM_F_DUMP, 1, 0)
        s.send(hdr + payload)
        out = []
        while True:
            buf = s.recv(1 << 16)
            off = 0
            while off + 16 <= len(buf):
                ln, typ, _fl, _seq, _pid = struct.unpack_from("<IHHII",
                                                             buf, off)
                if ln < 16:
                    return out
                body = buf[off + 16:off + ln]
                if typ == NLMSG_DONE:
                    return out
                if typ == NLMSG_ERROR:
                    raise OSError("netlink error")
                out.append((typ, body))
                off += (ln + 3) & ~3
    finally:
        s.close()


def _rtattrs(body: bytes, off: int) -> dict[int, bytes]:
    out = {}
    while off + 4 <= len(body):
        ln, typ = struct.unpack_from("<HH", body, off)
        if ln < 4:
            break
        out[typ] = body[off + 4:off + ln]
        off += (ln + 3) & ~3
    return out


def netlink_routes() -> list[Route]:
    """RTM_GETROUTE dump -> Route list (main table, IPv4)."""
    ifnames = _ifnames()
    routes = []
    rtmsg = struct.pack("<BBBBBBBBI", AF_INET, 0, 0, 0, 0, 0, 0, 0, 0)
    for typ, body in _nl_dump(RTM_GETROUTE, rtmsg):
        if typ != 24:                      # RTM_NEWROUTE
            continue
        fam, dst_len = body[0], body[1]
        # rtmsg: family,dst_len,src_len,tos,table,protocol,scope,type
        table, rtype = body[4], body[7]
        if fam != AF_INET or table != 254 or rtype != 1:
            continue                       # main table, unicast only
            # (the dump walks local/broadcast tables too; the procfs
            # mirror — and the reference's fd_ip view — is main-table)
        at = _rtattrs(body, 12)
        if RTA_OIF not in at:
            continue  # ECMP/multipath nexthops ride RTA_MULTIPATH; a
            # fabricated iface-"0" entry would poison route lookups
        dest = int.from_bytes(at.get(RTA_DST, b"\0\0\0\0"), "big")
        gw = int.from_bytes(at.get(RTA_GATEWAY, b"\0\0\0\0"), "big")
        oif = int.from_bytes(at[RTA_OIF], "little")
        metric = int.from_bytes(at.get(RTA_PRIORITY, b"\0\0\0\0"),
                                "little")
        mask = (0xFFFFFFFF << (32 - dst_len)) & 0xFFFFFFFF if dst_len \
            else 0
        routes.append(Route(dest, mask, gw, ifnames.get(oif, str(oif)),
                            metric))
    routes.sort(key=lambda r: (-r.prefix_len, r.metric))
    return routes


def netlink_neighbors() -> dict[int, tuple[str, str]]:
    """RTM_GETNEIGH dump -> {ipv4: (mac, iface)} (reachable entries)."""
    ifnames = _ifnames()
    neigh = {}
    ndmsg = struct.pack("<BBHiHBB", AF_INET, 0, 0, 0, 0, 0, 0)
    for typ, body in _nl_dump(RTM_GETNEIGH, ndmsg):
        if typ != 28:                      # RTM_NEWNEIGH
            continue
        fam = body[0]
        ifindex = int.from_bytes(body[4:8], "little", signed=True)
        if fam != AF_INET:
            continue
        at = _rtattrs(body, 12)
        dst = at.get(NDA_DST)
        mac = at.get(NDA_LLADDR)
        if not dst or not mac or mac == bytes(6):
            continue
        neigh[int.from_bytes(dst, "big")] = (
            ":".join(f"{b:02x}" for b in mac),
            ifnames.get(ifindex, str(ifindex)))
    return neigh


class NetlinkIpTable(IpTable):
    """IpTable whose refresh() mirrors kernel state over REAL rtnetlink
    dumps, falling back to procfs when the netlink socket is denied."""

    def refresh(self) -> None:
        try:
            routes = netlink_routes()
            neigh = netlink_neighbors()
        except OSError:
            super().refresh()
            return
        self.routes = routes
        self.neigh = neigh
