"""UDP sockets packet engine (ref: src/waltz/udpsock/fd_udpsock.c — the
no-XDP fallback aio; here it is the primary backend, same burst API).

One recvfrom syscall per datagram over a nonblocking socket, drained up to
`burst` per poll.  (The reference's batching lever is AF_XDP ring bursts; a
recvmmsg/zero-copy backend can replace this class behind the same API if
socket syscalls ever become the ingest bottleneck — today the device
round-trip dominates.)
"""

import errno
import socket

from .aio import Aio, Pkt


class UdpSock:
    MTU = 1500  # wire datagram cap; Solana txn MTU is 1232 (fd_txn.h:92)

    def __init__(self, bind_ip: str = "0.0.0.0", bind_port: int = 0,
                 burst: int = 64, rcvbuf: int = 1 << 20,
                 mutable: bool = False):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, rcvbuf)
        self.sock.bind((bind_ip, bind_port))
        self.sock.setblocking(False)
        self.burst = burst
        # mutable=True: recv into fresh bytearrays (QUIC burst decrypt
        # runs in place in the rx buffer).  Default stays bytes — gossip/
        # repair parsers key dicts on payload slices, which must hash.
        self.mutable = mutable
        self.addr = self.sock.getsockname()

    @property
    def port(self) -> int:
        return self.addr[1]

    def recv_burst(self) -> list[Pkt]:
        """Drain up to `burst` datagrams; returns [] when the socket is dry.

        With mutable=True each datagram lands in its own fresh bytearray
        (recvfrom_into, no bytes->bytearray round trip): QUIC burst
        decrypt runs IN PLACE in the rx buffer, so payloads must be
        mutable and uniquely owned."""
        out = []
        if self.mutable:
            for _ in range(self.burst):
                buf = bytearray(self.MTU)
                try:
                    n, addr = self.sock.recvfrom_into(buf, self.MTU)
                except (BlockingIOError, InterruptedError):
                    break
                except OSError as e:
                    if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                        break
                    raise
                del buf[n:]
                out.append(Pkt(buf, addr))
            return out
        for _ in range(self.burst):
            try:
                data, addr = self.sock.recvfrom(self.MTU)
            except (BlockingIOError, InterruptedError):
                break
            except OSError as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    break
                raise
            out.append(Pkt(data, addr))
        return out

    def send_burst(self, pkts: list[Pkt]) -> int:
        sent = 0
        for p in pkts:
            try:
                self.sock.sendto(p.payload, p.addr)
                sent += 1
            except (BlockingIOError, InterruptedError):
                break
        return sent

    def aio(self) -> Aio:
        return Aio(self.send_burst)

    def close(self):
        self.sock.close()
