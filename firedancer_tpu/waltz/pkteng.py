"""Native burst packet engine wrapper (ref: src/waltz/xdp/fd_xsk_aio.c
role).  Same burst API as waltz.udpsock.UdpSock, but rx/tx cross the
kernel ONCE per burst via the C++ recvmmsg/sendmmsg engine
(native/pkteng.cpp) — the portable stand-in for the reference's AF_XDP
rings, and the drop-in upgrade the udpsock docstring reserves for when
per-datagram syscalls become the ingest bottleneck."""

from __future__ import annotations

import ctypes
import socket
import struct

import numpy as np

from .. import native
from .aio import Aio, Pkt


class NativeUdpSock:
    MTU = 1500

    def __init__(self, bind_ip: str = "0.0.0.0", bind_port: int = 0,
                 burst: int = 256, rcvbuf: int = 1 << 22,
                 mutable: bool = False):
        self._L = native.lib()
        # mutable=True: rx payloads come out as fresh bytearrays (same
        # one copy off the reused ring row, but the QUIC layer can then
        # burst-decrypt in place instead of re-copying bytes->bytearray)
        self.mutable = mutable
        fd = self._L.fd_pkteng_open(bind_ip.encode(), bind_port, rcvbuf)
        if fd < 0:
            raise OSError(-fd, f"pkteng open {bind_ip}:{bind_port}")
        self.fd = fd
        self.burst = burst
        port = self._L.fd_pkteng_port(fd)
        if port < 0:
            raise OSError(-port, "pkteng getsockname")
        self.addr = (bind_ip, port)
        self._rx_buf = np.empty((burst, self.MTU), dtype=np.uint8)
        self._rx_len = np.empty(burst, dtype=np.uint32)
        self._rx_ip = np.empty(burst, dtype=np.uint32)
        self._rx_port = np.empty(burst, dtype=np.uint16)
        self._tx_buf = np.empty((burst, self.MTU), dtype=np.uint8)
        self._tx_len = np.empty(burst, dtype=np.uint32)
        self._tx_ip = np.empty(burst, dtype=np.uint32)
        self._tx_port = np.empty(burst, dtype=np.uint16)

    @property
    def port(self) -> int:
        return self.addr[1]

    def recv_burst(self) -> list[Pkt]:
        n = self._L.fd_pkteng_rx_burst(
            self.fd, self._rx_buf.ctypes.data_as(ctypes.c_void_p),
            self.MTU, self.burst,
            self._rx_len.ctypes.data_as(ctypes.c_void_p),
            self._rx_ip.ctypes.data_as(ctypes.c_void_p),
            self._rx_port.ctypes.data_as(ctypes.c_void_p))
        if n < 0:
            raise OSError(-n, "pkteng rx")
        out = []
        mk = bytearray if self.mutable else np.ndarray.tobytes
        for i in range(n):
            ip = socket.inet_ntoa(struct.pack("!I", int(self._rx_ip[i])))
            out.append(Pkt(mk(self._rx_buf[i, : self._rx_len[i]]),
                           (ip, int(self._rx_port[i]))))
        return out

    def send_burst(self, pkts: list[Pkt]) -> int:
        sent_total = 0
        for base in range(0, len(pkts), self.burst):
            chunk = pkts[base : base + self.burst]
            for i, p in enumerate(chunk):
                pl = p.payload[: self.MTU]
                self._tx_buf[i, : len(pl)] = np.frombuffer(pl, np.uint8)
                self._tx_len[i] = len(pl)
                (self._tx_ip[i],) = struct.unpack(
                    "!I", socket.inet_aton(p.addr[0]))
                self._tx_port[i] = p.addr[1]
            n = self._L.fd_pkteng_tx_burst(
                self.fd, self._tx_buf.ctypes.data_as(ctypes.c_void_p),
                self.MTU, len(chunk),
                self._tx_len.ctypes.data_as(ctypes.c_void_p),
                self._tx_ip.ctypes.data_as(ctypes.c_void_p),
                self._tx_port.ctypes.data_as(ctypes.c_void_p))
            if n < 0:
                raise OSError(-n, "pkteng tx")
            sent_total += n
            if n < len(chunk):
                break  # kernel backpressure: report partial like UdpSock
        return sent_total

    def aio(self) -> Aio:
        return Aio(self.send_burst)

    def close(self):
        self._L.fd_pkteng_close(self.fd)


class XRing:
    """AF_PACKET TPACKET_V3 mmap'd RX ring — the kernel-bypass ingest tier
    (ref: src/waltz/xdp/fd_xsk.c; design note in native/pkteng.cpp).  The
    kernel fills mmap'd blocks; recv_burst() walks ready blocks with zero
    per-packet syscalls, extracting IPv4/UDP payloads for `udp_port`
    (0 = all) behind the same Pkt contract as the socket tiers."""

    MTU = 1500

    def __init__(self, ifname: str = "lo", udp_port: int = 0,
                 burst: int = 512, block_sz: int = 1 << 18,
                 block_cnt: int = 32, frame_sz: int = 2048):
        self._L = native.lib()
        h = self._L.fd_xring_open(ifname.encode(), block_sz, block_cnt,
                                  frame_sz)
        if h < 0:
            raise OSError(int(-h), f"xring open on {ifname}")
        self._h = h
        self.udp_port = udp_port
        self.burst = burst
        self._rx_buf = np.empty((burst, self.MTU), dtype=np.uint8)
        self._rx_len = np.empty(burst, dtype=np.uint32)
        self._rx_ip = np.empty(burst, dtype=np.uint32)
        self._rx_port = np.empty(burst, dtype=np.uint16)

    def poll(self, timeout_ms: int = 10) -> bool:
        return self._L.fd_xring_poll(self._h, timeout_ms) > 0

    def recv_burst(self) -> list[Pkt]:
        n = self._L.fd_xring_rx_burst(
            self._h, self._rx_buf.ctypes.data_as(ctypes.c_void_p),
            self.MTU, self.burst,
            self._rx_len.ctypes.data_as(ctypes.c_void_p),
            self._rx_ip.ctypes.data_as(ctypes.c_void_p),
            self._rx_port.ctypes.data_as(ctypes.c_void_p),
            self.udp_port)
        out = []
        for i in range(n):
            ip = socket.inet_ntoa(struct.pack("!I", int(self._rx_ip[i])))
            out.append(Pkt(self._rx_buf[i, : self._rx_len[i]].tobytes(),
                           (ip, int(self._rx_port[i]))))
        return out

    def close(self):
        if self._h:
            self._L.fd_xring_close(self._h)
            self._h = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
