"""Native burst packet engine wrapper (ref: src/waltz/xdp/fd_xsk_aio.c
role).  Same burst API as waltz.udpsock.UdpSock, but rx/tx cross the
kernel ONCE per burst via the C++ recvmmsg/sendmmsg engine
(native/pkteng.cpp) — the portable stand-in for the reference's AF_XDP
rings, and the drop-in upgrade the udpsock docstring reserves for when
per-datagram syscalls become the ingest bottleneck."""

from __future__ import annotations

import ctypes
import socket
import struct

import numpy as np

from .. import native
from .aio import Aio, Pkt


class NativeUdpSock:
    MTU = 1500

    def __init__(self, bind_ip: str = "0.0.0.0", bind_port: int = 0,
                 burst: int = 256, rcvbuf: int = 1 << 22,
                 mutable: bool = False):
        self._L = native.lib()
        # mutable=True: rx payloads come out as fresh bytearrays (same
        # one copy off the reused ring row, but the QUIC layer can then
        # burst-decrypt in place instead of re-copying bytes->bytearray)
        self.mutable = mutable
        fd = self._L.fd_pkteng_open(bind_ip.encode(), bind_port, rcvbuf)
        if fd < 0:
            raise OSError(-fd, f"pkteng open {bind_ip}:{bind_port}")
        self.fd = fd
        self.burst = burst
        port = self._L.fd_pkteng_port(fd)
        if port < 0:
            raise OSError(-port, "pkteng getsockname")
        self.addr = (bind_ip, port)
        self._rx_buf = np.empty((burst, self.MTU), dtype=np.uint8)
        self._rx_len = np.empty(burst, dtype=np.uint32)
        self._rx_ip = np.empty(burst, dtype=np.uint32)
        self._rx_port = np.empty(burst, dtype=np.uint16)
        self._tx_buf = np.empty((burst, self.MTU), dtype=np.uint8)
        self._tx_len = np.empty(burst, dtype=np.uint32)
        self._tx_ip = np.empty(burst, dtype=np.uint32)
        self._tx_port = np.empty(burst, dtype=np.uint16)

    @property
    def port(self) -> int:
        return self.addr[1]

    def recv_burst(self) -> list[Pkt]:
        n = self._L.fd_pkteng_rx_burst(
            self.fd, self._rx_buf.ctypes.data_as(ctypes.c_void_p),
            self.MTU, self.burst,
            self._rx_len.ctypes.data_as(ctypes.c_void_p),
            self._rx_ip.ctypes.data_as(ctypes.c_void_p),
            self._rx_port.ctypes.data_as(ctypes.c_void_p))
        if n < 0:
            raise OSError(-n, "pkteng rx")
        out = []
        mk = bytearray if self.mutable else np.ndarray.tobytes
        for i in range(n):
            ip = socket.inet_ntoa(struct.pack("!I", int(self._rx_ip[i])))
            out.append(Pkt(mk(self._rx_buf[i, : self._rx_len[i]]),
                           (ip, int(self._rx_port[i]))))
        return out

    def send_burst(self, pkts: list[Pkt]) -> int:
        sent_total = 0
        for base in range(0, len(pkts), self.burst):
            chunk = pkts[base : base + self.burst]
            for i, p in enumerate(chunk):
                pl = p.payload[: self.MTU]
                self._tx_buf[i, : len(pl)] = np.frombuffer(pl, np.uint8)
                self._tx_len[i] = len(pl)
                (self._tx_ip[i],) = struct.unpack(
                    "!I", socket.inet_aton(p.addr[0]))
                self._tx_port[i] = p.addr[1]
            n = self._L.fd_pkteng_tx_burst(
                self.fd, self._tx_buf.ctypes.data_as(ctypes.c_void_p),
                self.MTU, len(chunk),
                self._tx_len.ctypes.data_as(ctypes.c_void_p),
                self._tx_ip.ctypes.data_as(ctypes.c_void_p),
                self._tx_port.ctypes.data_as(ctypes.c_void_p))
            if n < 0:
                raise OSError(-n, "pkteng tx")
            sent_total += n
            if n < len(chunk):
                break  # kernel backpressure: report partial like UdpSock
        return sent_total

    def aio(self) -> Aio:
        return Aio(self.send_burst)

    def close(self):
        self._L.fd_pkteng_close(self.fd)


class XRing:
    """AF_PACKET TPACKET_V3 mmap'd RX ring — the kernel-bypass ingest tier
    (ref: src/waltz/xdp/fd_xsk.c; design note in native/pkteng.cpp).  The
    kernel fills mmap'd blocks; recv_burst() walks ready blocks with zero
    per-packet syscalls, extracting IPv4/UDP payloads for `udp_port`
    (0 = all) behind the same Pkt contract as the socket tiers."""

    MTU = 1500

    def __init__(self, ifname: str = "lo", udp_port: int = 0,
                 burst: int = 512, block_sz: int = 1 << 18,
                 block_cnt: int = 32, frame_sz: int = 2048):
        self._L = native.lib()
        h = self._L.fd_xring_open(ifname.encode(), block_sz, block_cnt,
                                  frame_sz)
        if h < 0:
            raise OSError(int(-h), f"xring open on {ifname}")
        self._h = h
        self.udp_port = udp_port
        self.burst = burst
        self._rx_buf = np.empty((burst, self.MTU), dtype=np.uint8)
        self._rx_len = np.empty(burst, dtype=np.uint32)
        self._rx_ip = np.empty(burst, dtype=np.uint32)
        self._rx_port = np.empty(burst, dtype=np.uint16)

    def poll(self, timeout_ms: int = 10) -> bool:
        return self._L.fd_xring_poll(self._h, timeout_ms) > 0

    def recv_burst(self) -> list[Pkt]:
        n = self._L.fd_xring_rx_burst(
            self._h, self._rx_buf.ctypes.data_as(ctypes.c_void_p),
            self.MTU, self.burst,
            self._rx_len.ctypes.data_as(ctypes.c_void_p),
            self._rx_ip.ctypes.data_as(ctypes.c_void_p),
            self._rx_port.ctypes.data_as(ctypes.c_void_p),
            self.udp_port)
        out = []
        for i in range(n):
            ip = socket.inet_ntoa(struct.pack("!I", int(self._rx_ip[i])))
            out.append(Pkt(self._rx_buf[i, : self._rx_len[i]].tobytes(),
                           (ip, int(self._rx_port[i]))))
        return out

    def close(self):
        if self._h:
            self._L.fd_xring_close(self._h)
            self._h = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# Fleet steering (round 17): consistent-hash peer->host ring
# ---------------------------------------------------------------------------

import bisect as _bisect
import hashlib as _hashlib


class SteerRing:
    """Consistent-hash peer->host steering ring (fleet tier).

    Every host contributes `vnodes` points on a 64-bit hash circle;
    a key (peer address, or a sig tag's top bits) is owned by the first
    point at-or-after it, wrapping.  Points derive ONLY from the host
    id string, never from join order or fleet size, so a host that
    leaves and re-joins lands on exactly its old points and re-owns
    exactly its old ranges — the property the failover/rejoin chaos
    asserts.  Removing a host hands each of its arcs to the next point
    clockwise (some surviving host); no other ownership moves.
    """

    def __init__(self, hosts=(), vnodes: int = 64):
        self.vnodes = int(vnodes)
        self._pts: list[int] = []      # sorted point hashes
        self._own: dict[int, str] = {}  # point hash -> host id
        for h in hosts:
            self.add_host(h)

    @staticmethod
    def _h64(data: bytes) -> int:
        return int.from_bytes(
            _hashlib.blake2b(data, digest_size=8).digest(), "little")

    def _points_of(self, host: str) -> list[int]:
        return [self._h64(b"%s#%d" % (host.encode(), v))
                for v in range(self.vnodes)]

    def add_host(self, host: str):
        if host in self.hosts():
            return
        for p in self._points_of(host):
            if p in self._own:          # cross-host point collision:
                continue                # first owner keeps it (stable)
            _bisect.insort(self._pts, p)
            self._own[p] = host

    def remove_host(self, host: str):
        for p in self._points_of(host):
            if self._own.get(p) == host:
                del self._own[p]
                i = _bisect.bisect_left(self._pts, p)
                if i < len(self._pts) and self._pts[i] == p:
                    del self._pts[i]

    def hosts(self) -> set[str]:
        return set(self._own.values())

    def owner(self, key: int) -> str:
        """Owning host of a 64-bit key (first ring point >= key, wrap)."""
        if not self._pts:
            raise LookupError("empty steer ring")
        i = _bisect.bisect_left(self._pts, int(key) & ((1 << 64) - 1))
        if i == len(self._pts):
            i = 0
        return self._own[self._pts[i]]

    def owner_of_peer(self, ip: str, port: int = 0) -> str:
        """Peer steering key: hash of ip:port (the QUIC 4-tuple's remote
        half) — the key the net tier steers and Retry-bounces on."""
        return self.owner(self._h64(b"%s:%d" % (ip.encode(), port)))

    def owner_of_sig(self, tag: int) -> str:
        """Sig-tag steering: dedup-shard ownership follows the same ring
        as peer steering, keyed by the raw 64-bit tag."""
        return self.owner(int(tag))

    def shard_owner(self, shard: int, shard_bits: int) -> str:
        """Owner of a sig-prefix shard: the shard's keyspace midpoint
        (top `shard_bits` bits = shard) mapped through the ring."""
        lo = int(shard) << (64 - int(shard_bits))
        return self.owner(lo + (1 << (63 - int(shard_bits))))

    def owned_shards(self, host: str, shard_bits: int) -> set[int]:
        return {s for s in range(1 << int(shard_bits))
                if self.shard_owner(s, shard_bits) == host}


class PeerSteer:
    """Net-tier admission filter over a SteerRing.

    rx packets whose peer hashes to this host are admitted; mis-steered
    peers are bounced with an addr-bound token naming the owner —
    `bounce_fn(ip, port, owner)` plugs in the PR-7 QUIC Retry sealer
    (waltz/quic.py `_seal_retry_token`), so a bounced client re-dials
    the right host with a token only the fleet can mint.  Counters:
    admit_cnt / bounce_cnt / orphan_cnt (ring empty or owner==unknown).
    """

    def __init__(self, ring: SteerRing, self_host: str, bounce_fn=None):
        self.ring = ring
        self.self_host = self_host
        self.bounce_fn = bounce_fn
        self.admit_cnt = 0
        self.bounce_cnt = 0
        self.orphan_cnt = 0

    def admit(self, ip: str, port: int = 0):
        """-> (True, None) if this host owns the peer, else
        (False, bounce_payload|None)."""
        try:
            owner = self.ring.owner_of_peer(ip, port)
        except LookupError:
            self.orphan_cnt += 1
            return True, None          # empty ring: fail open
        if owner == self.self_host:
            self.admit_cnt += 1
            return True, None
        self.bounce_cnt += 1
        tok = (self.bounce_fn(ip, port, owner)
               if self.bounce_fn is not None else None)
        return False, tok
