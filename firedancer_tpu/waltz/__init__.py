"""waltz — networking layer (ref: src/waltz/).

The reference's ingress is AF_XDP kernel bypass (src/waltz/xdp) with an
AF_INET sockets fallback (src/waltz/udpsock); the TPU build standardizes on
the sockets path (portable, and the TPU host's bottleneck is the device
round-trip, not packet I/O), keeping the same aio burst interface so an
XDP/DPDK backend can slot in behind it later.
"""
