"""AF_XDP XSK sockets — the kernel-bypass ingest tier (VERDICT r4 #6).

Role of src/waltz/xdp/fd_xsk.c + fd_xsk_aio.c: a umem-backed AF_XDP
socket whose fill/rx rings the kernel DMA-fills; user space consumes RX
descriptors with zero per-packet syscalls.  Packets reach the socket via
the XDP redirect program (waltz/ebpf.py builds it; ebpf.KernelXdp loads
and attaches it and steers (dst ip, dst port) flows into the XSKMAP).

Split of labor: this module owns the one-time setup — socket, umem
mmap, ring setsockopts, ring mmaps, bind — in plain ctypes (setup cost
is irrelevant); the per-burst hot path (ring consume with acquire/
release ordering, in-place eth/ipv4/udp parse, payload copy, frame
recycle into the fill ring) is C++ (native/pkteng.cpp fd_xsk_rx_burst).

recv_burst() yields waltz.aio.Pkt like every other ingest backend, so
the net tile can run NIC -> XSK -> quic unchanged.  TPACKET_V3
(waltz/pkteng.XRing) remains the fallback tier where AF_XDP or bpf(2)
is unavailable.
"""

from __future__ import annotations

import ctypes
import mmap
import socket
import struct

import numpy as np

from .. import native
from .aio import Pkt

AF_XDP = 44
SOL_XDP = 283
XDP_MMAP_OFFSETS = 1
XDP_RX_RING = 2
XDP_UMEM_REG = 4
XDP_UMEM_FILL_RING = 5
XDP_UMEM_COMPLETION_RING = 6
XDP_PGOFF_RX_RING = 0
XDP_UMEM_PGOFF_FILL_RING = 0x100000000
XDP_COPY = 1 << 1


class XskUnavailable(OSError):
    pass


class XskSock:
    """One AF_XDP socket bound to (ifname, queue) with its own umem."""

    FRAME = 2048

    def __init__(self, ifname: str, queue: int = 0, frames: int = 256,
                 burst: int = 256):
        self._L = native.lib()
        self.burst = burst
        try:
            self.sock = socket.socket(AF_XDP, socket.SOCK_RAW, 0)
        except OSError as e:
            raise XskUnavailable(f"AF_XDP socket: {e}") from e
        try:
            self._setup(ifname, queue, frames)
        except OSError as e:
            self.close()   # releases any partially-created mmaps + socket
            raise XskUnavailable(f"xsk setup {ifname}:{queue}: {e}") from e

    def _setup(self, ifname: str, queue: int, frames: int):
        s = self.sock
        self.umem = mmap.mmap(-1, self.FRAME * frames)
        self._umem_addr = ctypes.addressof(
            ctypes.c_char.from_buffer(self.umem))
        s.setsockopt(SOL_XDP, XDP_UMEM_REG, struct.pack(
            "<QQIII", self._umem_addr, self.FRAME * frames, self.FRAME,
            0, 0))
        s.setsockopt(SOL_XDP, XDP_UMEM_FILL_RING,
                     struct.pack("<I", frames))
        s.setsockopt(SOL_XDP, XDP_UMEM_COMPLETION_RING,
                     struct.pack("<I", frames))
        s.setsockopt(SOL_XDP, XDP_RX_RING, struct.pack("<I", frames))

        off = s.getsockopt(SOL_XDP, XDP_MMAP_OFFSETS, 128)
        v = struct.unpack("<16Q", off[:128])
        # xdp_mmap_offsets: rx, tx, fr (fill), cr — each
        # {producer, consumer, desc, flags}
        self._rx_off = v[0:3]
        self._fr_off = v[8:11]

        self.rx_map = mmap.mmap(
            s.fileno(), int(self._rx_off[2]) + frames * 16,
            offset=XDP_PGOFF_RX_RING)
        self.fr_map = mmap.mmap(
            s.fileno(), int(self._fr_off[2]) + frames * 8,
            offset=XDP_UMEM_PGOFF_FILL_RING)
        self._rx_base = ctypes.addressof(
            ctypes.c_char.from_buffer(self.rx_map))
        self._fr_base = ctypes.addressof(
            ctypes.c_char.from_buffer(self.fr_map))
        self.ring_sz = frames

        ifindex = socket.if_nametoindex(ifname)
        sa = struct.pack("<HHIII", AF_XDP, XDP_COPY, ifindex, queue, 0)
        libc = ctypes.CDLL(None, use_errno=True)
        if libc.bind(s.fileno(), sa, len(sa)) != 0:
            import os
            e = ctypes.get_errno()
            raise OSError(e, f"xsk bind: {os.strerror(e)}")

        # prime the fill ring with every frame
        addrs = np.arange(frames, dtype=np.uint64) * self.FRAME
        vp = ctypes.c_void_p
        n = self._L.fd_xsk_fill(
            vp(self._fr_base), self._fr_off[0], self._fr_off[1],
            self._fr_off[2], self.ring_sz,
            addrs.ctypes.data_as(vp), frames)
        if n != frames:
            raise OSError(0, f"fill ring primed {n}/{frames}")

        self._buf = np.empty(self.burst * 1600, dtype=np.uint8)
        self._offs = np.empty(self.burst + 1, dtype=np.int64)
        self._srcip = np.empty(self.burst, dtype=np.uint32)
        self._srcport = np.empty(self.burst, dtype=np.uint16)
        self._dstport = np.empty(self.burst, dtype=np.uint16)

    def recv_burst(self) -> list[Pkt]:
        """Drain up to `burst` UDP payloads; zero syscalls."""
        vp = ctypes.c_void_p
        n = self._L.fd_xsk_rx_burst(
            vp(self._rx_base), self._rx_off[0], self._rx_off[1],
            self._rx_off[2], self.ring_sz,
            vp(self._fr_base), self._fr_off[0], self._fr_off[1],
            self._fr_off[2], self.ring_sz,
            vp(self._umem_addr), self.FRAME,
            self._buf.ctypes.data_as(vp), self._buf.nbytes,
            self._offs.ctypes.data_as(vp),
            self._srcip.ctypes.data_as(vp),
            self._srcport.ctypes.data_as(vp),
            self._dstport.ctypes.data_as(vp), self.burst)
        out = []
        for i in range(n):
            payload = bytes(self._buf[self._offs[i]:self._offs[i + 1]])
            ip = socket.inet_ntoa(
                int(self._srcip[i]).to_bytes(4, "little"))
            out.append(Pkt(payload, (ip, int(self._srcport[i]))))
        return out

    def recv_burst_dst(self) -> list[tuple[Pkt, int]]:
        """recv_burst plus each packet's UDP destination port (the net
        tile's per-port out-link steering key)."""
        pkts = self.recv_burst()
        return [(p, int(self._dstport[i])) for i, p in enumerate(pkts)]

    def fileno(self) -> int:
        return self.sock.fileno()

    def close(self):
        # numpy/ctypes views pin the maps; drop them first so mmap.close
        # can succeed, then release rings, umem and the socket
        for attr in ("_buf", "_offs", "_srcip", "_srcport", "_dstport"):
            if hasattr(self, attr):
                delattr(self, attr)
        for m in ("rx_map", "fr_map", "umem"):
            try:
                getattr(self, m).close()
            except (BufferError, AttributeError):
                pass
        self.sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
