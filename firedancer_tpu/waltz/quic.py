"""From-scratch QUIC v1 (RFC 9000/9001) endpoint for TPU transaction ingest.

Reference role: src/waltz/quic/fd_quic.c — a from-scratch QUIC server/client
tuned for the Solana TPU profile: unidirectional client→server streams, one
transaction per stream, event-callback API (fd_quic.h:4-110), per-conn flow
control quotas.  Same subset here:

  * packet types Initial / Handshake / 1-RTT (no 0-RTT, Retry, VN migration)
  * TLS 1.3 via waltz/tls.py (X25519 + Ed25519 certs + AES-128-GCM)
  * packet protection + AES-ECB header protection per RFC 9001
  * frames: PADDING PING ACK CRYPTO NEW_TOKEN-less STREAM MAX_DATA
    MAX_STREAM_DATA MAX_STREAMS CONNECTION_CLOSE HANDSHAKE_DONE
  * ACK tracking per packet-number space, PTO-style retransmit of
    unacked CRYPTO/STREAM data, idle timeout
  * conn map keyed by our 8-byte connection ids (the reference's conn_map)

The endpoint is sans-IO like the rest of waltz: `rx(pkts, now)` ingests
bursts from an aio, outgoing datagrams accumulate via the `tx` aio.  The
quic tile (disco/tiles.py) pumps it and feeds completed streams into
TpuReasm exactly as the reference's quic tile does (fd_quic.c:399-466).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from collections import OrderedDict

from firedancer_tpu.ballet.aes import AesGcm, aes_encrypt_block, aes_key_expand
from firedancer_tpu.ballet.hmac import hkdf_expand_label, hkdf_extract
from firedancer_tpu.waltz import quic_crypto as _qc
from firedancer_tpu.waltz import tls as _tls
from firedancer_tpu.waltz.aio import Aio, Pkt

QUIC_VERSION = 1
CID_SZ = 8  # all CIDs we mint (reference uses 8-byte conn ids)
TXN_MTU = 1232

_INITIAL_SALT = bytes.fromhex("38762cf7f55934b34d179ae6a4c80cadccbb7f0a")
# Retry integrity tag key/nonce (RFC 9001 §5.8, QUIC v1 constants)
_RETRY_KEY = bytes.fromhex("be0c690b9f66575a1d766b54e368c84e")
_RETRY_NONCE = bytes.fromhex("461599d35d632bf2239825bb")
RETRY_TOKEN_LIFETIME = 30.0  # seconds a Retry token stays redeemable

# packet-number spaces == encryption levels
SP_INITIAL, SP_HANDSHAKE, SP_APP = 0, 1, 2

_LONG_TYPE = {SP_INITIAL: 0, SP_HANDSHAKE: 2}
_TYPE_SPACE = {0: SP_INITIAL, 2: SP_HANDSHAKE}


# ----------------------------------------------------------------- varints


def enc_varint(v: int) -> bytes:
    if v < 1 << 6:
        return bytes([v])
    if v < 1 << 14:
        return (v | 0x4000).to_bytes(2, "big")
    if v < 1 << 30:
        return (v | 0x80000000).to_bytes(4, "big")
    return (v | 0xC000000000000000).to_bytes(8, "big")


def dec_varint(buf: bytes, pos: int) -> tuple[int, int]:
    first = buf[pos]
    n = 1 << (first >> 6)
    v = int.from_bytes(buf[pos : pos + n], "big") & ((1 << (8 * n - 2)) - 1)
    return v, pos + n


# ------------------------------------------------------------ transport params

_TP_ORIG_DCID = 0x00
_TP_IDLE_TIMEOUT = 0x01
_TP_MAX_UDP = 0x03
_TP_MAX_DATA = 0x04
_TP_MAX_STREAM_DATA_UNI = 0x07
_TP_MAX_STREAMS_BIDI = 0x08
_TP_MAX_STREAMS_UNI = 0x09
_TP_INITIAL_SCID = 0x0F


def encode_transport_params(p: dict[int, bytes | int]) -> bytes:
    out = b""
    for k, v in p.items():
        body = enc_varint(v) if isinstance(v, int) else v
        out += enc_varint(k) + enc_varint(len(body)) + body
    return out


def decode_transport_params(b: bytes) -> dict[int, bytes]:
    out: dict[int, bytes] = {}
    pos = 0
    while pos < len(b):
        k, pos = dec_varint(b, pos)
        ln, pos = dec_varint(b, pos)
        out[k] = b[pos : pos + ln]
        pos += ln
    return out


def _tp_int(params: dict[int, bytes], key: int, default: int) -> int:
    if key not in params:
        return default
    v, _ = dec_varint(params[key], 0)
    return v


# ------------------------------------------------------------- key material


class _Keys:
    """One direction's packet protection keys at one level.

    Burst crypto runs through a key-slot handle registered lazily with the
    shared quic_crypto backend (`slot()`); the pure-Python AesGcm and its
    GHASH table build lazily too (`aead`), so a flood of new-dcid Initials
    only pays HKDF + one backend key schedule per distinct dcid.  Slots are
    freed when the _Keys object is garbage collected — the Initial-keys
    LRU and per-conn key lists are the only owners.
    """

    __slots__ = ("key", "iv", "hp", "hp_rk", "_aead", "_slots",
                 "__weakref__")

    def __init__(self, secret: bytes):
        self.key = hkdf_expand_label(secret, "quic key", b"", 16)
        self.iv = hkdf_expand_label(secret, "quic iv", b"", 12)
        self.hp = hkdf_expand_label(secret, "quic hp", b"", 16)
        self.hp_rk = aes_key_expand(self.hp)  # per-packet mask: expand once
        self._aead = None
        self._slots: list = []  # [(backend, slot)] registered so far

    @property
    def aead(self) -> AesGcm:
        a = self._aead
        if a is None:
            a = self._aead = AesGcm(self.key)
        return a

    def slot(self, backend) -> int:
        for be, s in self._slots:
            if be is backend:
                return s
        s = backend.key_new(self.key, self.iv, self.hp)
        self._slots.append((backend, s))
        return s

    def __del__(self):
        for be, s in self._slots:
            try:
                be.key_free(s)
            except Exception:
                pass

    def nonce(self, pn: int) -> bytes:
        n = bytearray(self.iv)
        for i in range(8):
            n[11 - i] ^= (pn >> (8 * i)) & 0xFF
        return bytes(n)


def initial_keys(dcid: bytes, is_server: bool) -> tuple[_Keys, _Keys]:
    """(rx_keys, tx_keys) for the Initial space, derived from the client's
    first destination CID (RFC 9001 §5.2)."""
    initial = hkdf_extract(_INITIAL_SALT, dcid)
    client = hkdf_expand_label(initial, "client in", b"", 32)
    server = hkdf_expand_label(initial, "server in", b"", 32)
    ck, sk = _Keys(client), _Keys(server)
    return (ck, sk) if is_server else (sk, ck)


def retry_integrity_tag(odcid: bytes, retry_sans_tag: bytes) -> bytes:
    """RFC 9001 §5.8: AES-128-GCM over the Retry pseudo-packet
    (odcid_len || odcid || retry-packet-without-tag) with the fixed v1
    key/nonce; the 16-byte tag is the AEAD output over an empty
    plaintext."""
    pseudo = bytes([len(odcid)]) + odcid + retry_sans_tag
    return AesGcm(_RETRY_KEY).encrypt(_RETRY_NONCE, b"", pseudo)


# ----------------------------------------------------------------- conn state


@dataclass
class _SentPkt:
    frames: list  # retransmittable frame descriptors
    time: float
    ack_eliciting: bool


class _PnSpace:
    def __init__(self):
        self.next_pn = 0
        self.largest_rx = -1
        self.rx_pns: set[int] = set()
        self.rx_floor = -1  # pns <= floor are known-seen and pruned
        self.ack_pending = False
        self.sent: dict[int, _SentPkt] = {}

    def prune(self, keep: int = 1024) -> None:
        """Forget pns far below largest_rx; they count as duplicates.
        Bounds per-conn state on long-lived firehose connections."""
        floor = self.largest_rx - keep
        if floor > self.rx_floor:
            self.rx_floor = floor
            self.rx_pns = {p for p in self.rx_pns if p > floor}

    def ack_ranges(self, cap: int = 16):
        """Descending (largest, smallest) runs over received pns."""
        if not self.rx_pns:
            return []
        pns = sorted(self.rx_pns, reverse=True)
        runs = []
        hi = lo = pns[0]
        for p in pns[1:]:
            if p == lo - 1:
                lo = p
            else:
                runs.append((hi, lo))
                hi = lo = p
            if len(runs) >= cap:
                break
        runs.append((hi, lo))
        return runs[:cap]


class _RecvStream:
    __slots__ = ("frags", "fin_size", "delivered", "max_end")

    def __init__(self):
        self.frags: dict[int, bytes] = {}
        self.fin_size = -1
        self.delivered = False
        self.max_end = 0  # flow-control high-water mark (bytes)


class QuicConn:
    """One connection. Created via QuicEndpoint.connect() or on server rx."""

    _uid_seq = 0

    def __init__(self, ep: "QuicEndpoint", peer, is_server: bool,
                 odcid: bytes, orig_dcid: bytes | None = None,
                 init_keys: tuple | None = None):
        QuicConn._uid_seq += 1
        self.uid = QuicConn._uid_seq
        self.ep = ep
        self.peer = peer
        self.is_server = is_server
        self.scid = ep.rng(CID_SZ)
        self.dcid = odcid  # updated from peer's SCID once seen
        self.odcid = odcid  # key into ep._initial_conns (O(1) teardown)
        self.spaces = [_PnSpace(), _PnSpace(), _PnSpace()]
        self.rx_keys: list[_Keys | None] = [None, None, None]
        self.tx_keys: list[_Keys | None] = [None, None, None]
        # server conns reuse the endpoint's per-dcid cached schedules (the
        # admission probe already derived them); clients derive fresh
        rx, tx = init_keys if init_keys is not None else initial_keys(
            odcid, is_server)
        self.rx_keys[SP_INITIAL] = rx
        self.tx_keys[SP_INITIAL] = tx
        tp = {
            _TP_IDLE_TIMEOUT: int(ep.idle_timeout * 1000),
            _TP_MAX_UDP: 1472,
            _TP_MAX_DATA: ep.rx_max_data,
            _TP_MAX_STREAM_DATA_UNI: ep.rx_max_stream_data,
            _TP_MAX_STREAMS_BIDI: 0,
            _TP_MAX_STREAMS_UNI: ep.rx_max_streams,
            _TP_INITIAL_SCID: self.scid,
        }
        if is_server:
            # after a Retry the keys derive from the retry CID but the
            # transport params must name the CLIENT's original DCID
            tp[_TP_ORIG_DCID] = orig_dcid if orig_dcid is not None else odcid
        self.tls = _tls.TlsEndpoint(
            is_server=is_server,
            identity_seed=ep.identity_seed,
            transport_params=encode_transport_params(tp),
            alpn=ep.alpn,
            require_client_cert=ep.require_client_cert,
            rng=ep.rng,
            cert=ep.cert,  # built once per endpoint, not per conn
        )
        self.crypto_sent = [0, 0, 0]  # bytes of crypto stream queued per level
        self.crypto_buf = [b"", b"", b""]  # outgoing crypto stream per level
        self.token = b""  # Retry token to present in Initial packets
        self.handshake_done = False
        self.handshake_done_sent = False
        # anti-amplification state (RFC 9000 §8.1): a server must not send
        # more than 3x the bytes received from an unvalidated address.
        # Possession of handshake keys proves the peer saw our Initial, so
        # the first decrypted Handshake/1-RTT packet validates the path.
        self.addr_validated = not is_server
        self.rx_bytes = 0  # authenticated datagram bytes from peer
        self.tx_bytes = 0  # datagram bytes sent while unvalidated
        self.pto_count = 0  # consecutive PTO rounds without an ACK
        # DoS bookkeeping (maintained by the endpoint for server conns):
        # per-peer table key, half-open membership, per-conn txn token
        # bucket, and buffered partial-stream bytes under conn_reasm_budget
        self._peer_ip = None
        self._half_open = False
        self._txn_tokens = float(ep.cfg.conn_txn_burst)
        self._txn_ts = ep.now
        self.reasm_bytes = 0
        self.closed = False
        self.close_reason = None
        self.last_rx = ep.now
        # stream state
        self.next_uni_stream = 2 if not is_server else 3
        self.recv_streams: dict[int, _RecvStream] = {}
        # insertion-ordered set of delivered stream ids (dict keys) so
        # overflow evicts oldest-first instead of clearing wholesale
        self.finished_streams: dict[int, None] = {}
        # stream frames that arrived before the peer's handshake verified
        # (bounded; replayed by _on_tls_complete)
        self._early_streams: list[tuple[int, int, bytes, bool]] = []
        self.send_queue: list[tuple[int, bytes, int]] = []  # (sid, data, offset)
        self.peer_max_streams_uni = 0
        self.peer_max_data = 0
        self.peer_max_stream_data_uni = 0
        self.tx_data = 0
        self.rx_data = 0
        self.rx_max_data_sent = ep.rx_max_data
        self.rx_max_streams_sent = ep.rx_max_streams
        self.streams_opened = 0
        self.peer_streams_seen = 0  # uni stream count the peer has opened
        self._crypto_rx_off = [0, 0, 0]
        self._crypto_pend: dict[tuple, bytes] = {}
        self._frame_q: list[list] = [[], [], []]
        if not is_server:
            self._pump_tls()

    def apply_retry(self, new_dcid: bytes, token: bytes) -> None:
        """Client side of a validated Retry (RFC 9000 §17.2.5.2): adopt
        the server's new CID, re-derive Initial keys from it, and resend
        the ClientHello with the token attached.  Packets sent under the
        old keys were discarded by the server; their retrans state is
        dropped so PTO doesn't duplicate the re-queued crypto."""
        self.dcid = new_dcid
        rx, tx = initial_keys(new_dcid, is_server=False)
        self.rx_keys[SP_INITIAL] = rx
        self.tx_keys[SP_INITIAL] = tx
        self.token = token
        self.spaces[SP_INITIAL].sent.clear()
        self.crypto_sent[SP_INITIAL] = 0

    # ------------------------------------------------------------- TLS plumbing

    def _pump_tls(self) -> None:
        for lvl, msg in self.tls.take_outbox():
            self.crypto_buf[lvl] += msg
        self._install_keys()

    def _install_keys(self) -> None:
        for lvl in (SP_HANDSHAKE, SP_APP):
            if self.tls.secrets.get(lvl) and self.tx_keys[lvl] is None:
                c_sec, s_sec = self.tls.secrets[lvl]
                mine, theirs = (s_sec, c_sec) if self.is_server else (c_sec, s_sec)
                self.tx_keys[lvl] = _Keys(mine)
                self.rx_keys[lvl] = _Keys(theirs)

    def _on_tls_complete(self) -> None:
        self.handshake_done = True
        if self._half_open:
            self._half_open = False
            self.ep.half_open -= 1
        tp = decode_transport_params(self.tls.peer_transport_params or b"")
        self.peer_max_streams_uni = _tp_int(tp, _TP_MAX_STREAMS_UNI, 0)
        self.peer_max_data = _tp_int(tp, _TP_MAX_DATA, 0)
        self.peer_max_stream_data_uni = _tp_int(tp, _TP_MAX_STREAM_DATA_UNI, 0)
        if self.ep.on_handshake_complete:
            self.ep.on_handshake_complete(self)
        # replay 1-RTT stream frames that arrived (and were ACKed) before
        # the peer's handshake verified
        early, self._early_streams = self._early_streams, []
        for sid, off, data, fin in early:
            self.ep._apply_stream(self, sid, off, data, fin)

    # ---------------------------------------------------------------- app API

    def send_txn(self, data: bytes) -> int | None:
        """Open a unidirectional stream carrying one txn, FIN at the end
        (the Solana TPU stream profile).  Returns stream id or None if the
        peer's stream quota is exhausted."""
        if self.closed or not self.handshake_done:
            return None
        if self.streams_opened >= self.peer_max_streams_uni:
            return None
        sid = self.next_uni_stream
        self.next_uni_stream += 4
        self.streams_opened += 1
        self.send_queue.append((sid, data, 0))
        return sid

    def close(self, error_code: int = 0, reason: bytes = b"") -> None:
        if self.closed:
            return
        self.closed = True
        self.close_reason = (error_code, reason)
        lvl = SP_APP if self.tx_keys[SP_APP] else SP_INITIAL
        frame = (
            b"\x1d" + enc_varint(error_code) + enc_varint(len(reason)) + reason
        )
        self.ep._emit(self, lvl, frame, ack_eliciting=True, retrans=None)
        self.ep._flush(self)


# ---------------------------------------------------------------- burst rx

# job kinds: the first two ride the burst crypt wave, the rest finish-only
_J_CRYPT, _J_NEW, _J_LATE, _J_RETRY = 0, 1, 2, 3


class _RxJob:
    """One packet's slice of an rx burst between prepare and finish."""

    __slots__ = ("kind", "buf", "start", "pn_off", "end", "addr", "conn",
                 "keys", "space", "expected", "dcid", "scid", "token",
                 "result")

    def __init__(self):
        self.keys = None
        self.expected = 0
        self.result = None


# ------------------------------------------------------------------ endpoint


@dataclass
class QuicConfig:
    identity_seed: bytes
    is_server: bool = False
    # server-side stateless address validation (ref fd_quic.c:1175-1260
    # Retry): a tokenless Initial gets a Retry datagram and NO conn
    # state; only an Initial presenting a valid, address-bound,
    # integrity-protected token creates a connection
    retry: bool = False
    alpn: bytes = b"solana-tpu"
    require_client_cert: bool = True
    idle_timeout: float = 10.0
    rx_max_data: int = 1 << 24
    rx_max_stream_data: int = 2 * TXN_MTU
    rx_max_streams: int = 1 << 16
    max_conns: int = 4096
    pto: float = 0.15
    max_pto: int = 8  # consecutive ACK-less PTO rounds before conn teardown
    # --- DoS hardening (server front door, ref fd_quic.h conn quotas) ---
    # per source-IP connection cap (0 = unlimited): one hostile peer can
    # never own more than this many slots of the global table
    max_conns_per_peer: int = 0
    # handshake-flood defense: once this many server conns are mid-
    # handshake, tokenless Initials get a stateless Retry (no conn state)
    # even with cfg.retry off; 0 disables the dynamic escalation
    retry_half_open_threshold: int = 0
    # idle age (s) above which the least-recently-active conn may be LRU-
    # evicted when the global table is full (a full table of HOT conns is
    # never churned by a flood — new conns are rejected instead)
    lru_evict_idle: float = 1.0
    # per-conn completed-txn token bucket (0 rate = off): streams past the
    # budget are counted in rate_drop and not delivered to on_stream
    conn_txn_rate: float = 0.0
    conn_txn_burst: int = 32
    # per-conn partial-stream reassembly byte budget (0 = off): buffered
    # bytes across a conn's in-progress streams never exceed this; the
    # oldest partial streams are evicted (reasm_evict), never grown
    conn_reasm_budget: int = 16 * TXN_MTU
    # burst packet-protection backend: None = auto (native if aescrypt.cpp
    # builds, env FDTPU_QUIC_CRYPTO_NATIVE overrides), False = Python
    # fallback, True = require the C path (Pack(native=) idiom)
    crypto_native: bool | None = None
    # server-side LRU bound on cached per-dcid Initial key schedules: a
    # random-dcid flood can only hold this many expanded schedules alive
    # (evictions count in initial_keys_evict); 0 disables caching
    initial_key_cache: int = 1024


class QuicEndpoint:
    """Server or client endpoint multiplexing many conns over one aio.

    Callbacks (assign after construction):
      on_stream(conn, stream_id, data)   — complete uni stream received
      on_handshake_complete(conn)
      on_conn_closed(conn)
    """

    def __init__(self, cfg: QuicConfig, tx: Aio, rng=os.urandom):
        self.cfg = cfg
        self.identity_seed = cfg.identity_seed
        self.alpn = cfg.alpn
        self.require_client_cert = cfg.require_client_cert
        self.idle_timeout = cfg.idle_timeout
        self.rx_max_data = cfg.rx_max_data
        self.rx_max_stream_data = cfg.rx_max_stream_data
        self.rx_max_streams = cfg.rx_max_streams
        self.tx = tx
        self.rng = rng
        from firedancer_tpu.ballet.x509 import cert_create
        from firedancer_tpu.ops.ed25519 import keypair_from_seed

        pubkey, _, _ = keypair_from_seed(cfg.identity_seed)
        self.cert = cert_create(cfg.identity_seed, pubkey)
        self.now = 0.0
        self.conns: dict[bytes, QuicConn] = {}  # by our scid
        self._initial_conns: dict[bytes, QuicConn] = {}  # by peer's odcid
        self.on_stream = None
        self.on_handshake_complete = None
        self.on_conn_closed = None
        self._pending_dgrams: list[Pkt] = []
        self._touched: set[bytes] = set()
        # per-endpoint random token key: Retry tokens are only redeemable
        # at the endpoint that minted them, within their lifetime
        self._retry_token_aead = AesGcm(self.rng(16))
        # DoS-hardening state: per-source-IP server conn counts, half-open
        # (mid-handshake) population, and the next service() deadline
        self._peer_conns: dict = {}
        self.half_open = 0
        self._next_deadline = 0.0
        # burst packet-protection backend (native C or vectorized Python)
        # + the per-dcid Initial key-schedule LRU (satellite: a random-dcid
        # flood must not grow key material unboundedly)
        self._crypto = _qc.get_backend(cfg.crypto_native)
        self._initial_keys: OrderedDict[bytes, tuple] = OrderedDict()
        self._tx_jobs: list = []
        # deliver single-fragment streams as zero-copy memoryviews into the
        # rx buffer when the consumer opted in (disco quic tiles do)
        self.stream_views = False
        self.metrics = {
            "pkt_rx": 0, "pkt_tx": 0, "pkt_undecryptable": 0,
            "pkt_malformed": 0, "conn_created": 0, "conn_closed": 0,
            "streams_rx": 0, "retrans": 0,
            "retry_tx": 0, "retry_token_accept": 0, "retry_token_reject": 0,
            "conn_reject": 0, "conn_evict": 0, "rate_drop": 0,
            "reasm_evict": 0, "crypto_native": 0, "crypto_fallback": 0,
            "initial_keys_evict": 0,
        }

    def _initial_keys_cached(self, dcid: bytes) -> tuple:
        """(rx, tx) Initial-space schedules for a client dcid, LRU-cached
        so the admission probe and the conn it admits share one derivation
        (and a random-dcid flood is bounded to initial_key_cache expanded
        schedules)."""
        cap = self.cfg.initial_key_cache
        if not cap:
            return initial_keys(dcid, is_server=True)
        ik = self._initial_keys
        pair = ik.pop(dcid, None)
        if pair is None:
            pair = initial_keys(dcid, is_server=True)
            if len(ik) >= cap:
                ik.popitem(last=False)
                self.metrics["initial_keys_evict"] += 1
        ik[dcid] = pair  # (re-)insert at the LRU tail
        return pair

    def set_rate_knobs(self, conn_txn_rate=None, conn_txn_burst=None):
        """Live-retune the per-conn txn token bucket (autotune actuation
        path).  cfg is a mutable dataclass and _txn_admit reads it per
        call, so new rates apply to every conn's next refill."""
        if conn_txn_rate is not None and float(conn_txn_rate) > 0:
            self.cfg.conn_txn_rate = float(conn_txn_rate)
        if conn_txn_burst is not None and int(conn_txn_burst) > 0:
            self.cfg.conn_txn_burst = int(conn_txn_burst)

    # ------------------------------------------------------ retry tokens

    @staticmethod
    def _addr_aad(addr) -> bytes:
        return repr(addr).encode()

    def _seal_retry_token(self, odcid: bytes, retry_scid: bytes,
                          addr) -> bytes:
        """token = nonce12 || AEAD(key, nonce, aad=client address,
        expiry_ms u64 || odcid_len u8 || odcid || retry_scid).  Binding
        the client address into the AAD is the address validation: a
        token replayed from another source fails to open."""
        nonce = self.rng(12)
        pt = (int(self.now * 1000 + RETRY_TOKEN_LIFETIME * 1000)
              .to_bytes(8, "big")
              + bytes([len(odcid)]) + odcid + retry_scid)
        return nonce + self._retry_token_aead.encrypt(
            nonce, pt, self._addr_aad(addr))

    def _open_retry_token(self, token: bytes, addr):
        """-> (odcid, retry_scid) or None."""
        if len(token) < 12 + 16:
            return None
        pt = self._retry_token_aead.decrypt(
            token[:12], token[12:], self._addr_aad(addr))
        if pt is None or len(pt) < 9:
            return None
        expiry_ms = int.from_bytes(pt[:8], "big")
        if self.now * 1000 > expiry_ms:
            return None
        olen = pt[8]
        if len(pt) != 9 + olen + CID_SZ:
            return None
        return bytes(pt[9 : 9 + olen]), bytes(pt[9 + olen :])

    def _send_retry(self, odcid: bytes, client_scid: bytes, addr) -> None:
        """Stateless Retry datagram (ref fd_quic.c:1175-1260): new server
        CID + address-bound token + RFC 9001 §5.8 integrity tag.  No conn
        state is created."""
        retry_scid = self.rng(CID_SZ)
        token = self._seal_retry_token(odcid, retry_scid, addr)
        pkt = (bytes([0xF0])                       # long hdr, type 3
               + QUIC_VERSION.to_bytes(4, "big")
               + bytes([len(client_scid)]) + client_scid
               + bytes([len(retry_scid)]) + retry_scid
               + token)
        pkt += retry_integrity_tag(odcid, pkt)
        self._pending_dgrams.append(Pkt(pkt, addr))
        self.metrics["retry_tx"] += 1
        self.metrics["pkt_tx"] += 1

    # ------------------------------------------------------------ client open

    def connect(self, peer, now: float | None = None) -> QuicConn:
        """Open a client connection.  Pass `now` (same clock as rx/service)
        so the new conn's idle timer starts from the right epoch — without
        it a wall-clock service() would reap the conn instantly (conn
        timestamps inherit endpoint.now, which starts at 0.0)."""
        assert not self.cfg.is_server
        if now is not None:
            self.now = now
        odcid = self.rng(CID_SZ)
        conn = QuicConn(self, peer, is_server=False, odcid=odcid)
        self.conns[conn.scid] = conn
        self.metrics["conn_created"] += 1
        self._flush(conn)
        self._send_pending()
        return conn

    # -------------------------------------------------------------- receive
    #
    # Three phases per burst (the reference shape: AES-NI C unprotects the
    # whole rx burst before any per-conn dispatch):
    #   prepare — walk datagrams/coalesced packets, parse cleartext
    #             headers, collect one crypt job per packet
    #   crypt   — ONE backend call HP-unmasks + AEAD-decrypts every job in
    #             place in the rx buffers (native C or vectorized NumPy)
    #   finish  — replay packets in arrival order: pn dedup, conn
    #             admission, frame processing
    # Packets whose keys install mid-burst (a coalesced handshake flight
    # carries the CRYPTO frames that derive the next space's keys) are
    # deferred (_J_LATE) and crypt at finish once the keys exist.

    def rx(self, pkts: list[Pkt], now: float) -> None:
        self.now = now
        self._touched: set[bytes] = set()
        jobs: list[_RxJob] = []
        for pkt in pkts:
            payload = pkt.payload
            if not isinstance(payload, bytearray):
                payload = bytearray(payload)  # in-place decrypt target
            self._prepare_datagram(payload, pkt.addr, jobs)
        wave = [j for j in jobs if j.kind <= _J_NEW]
        if wave:
            be = self._crypto
            res = be.decrypt_burst(
                [(j.buf, j.start, j.pn_off, j.end, j.keys.slot(be),
                  j.expected) for j in wave])
            self.metrics["crypto_native" if be.native
                         else "crypto_fallback"] += len(wave)
            for j, r in zip(wave, res):
                j.result = r
        for j in jobs:
            if j.kind == _J_CRYPT:
                self._finish_crypt(j)
            elif j.kind == _J_NEW:
                self._finish_new(j)
            elif j.kind == _J_LATE:
                self._finish_late(j)
            else:
                self._rx_retry(j.buf, j.start, j.dcid, j.scid)
        # flush only the conns this burst touched (not all 4k of them)
        for scid in self._touched:
            conn = self.conns.get(scid)
            if conn is not None:
                self._flush(conn)
        self._send_pending()

    def _rx_retry(self, buf: bytes, pos: int, dcid: bytes,
                  retry_scid: bytes) -> int:
        """Client-side Retry processing (RFC 9000 §17.2.5): validate the
        integrity tag against the conn's original DCID, then rekey and
        resend the Initial with the token.  At most one Retry per conn."""
        if self.cfg.is_server or len(buf) - pos < 16:
            # a Retry at a server (or one too short to carry its tag) is
            # never legitimate — count the shed like any other bad packet
            self.metrics["pkt_malformed"] += 1
            return -1
        conn = self.conns.get(dcid)
        if (conn is None or conn.is_server or conn.handshake_done
                or conn.token or not retry_scid):
            return len(buf) - pos
        body = bytes(buf[pos : len(buf) - 16])
        tag = bytes(buf[len(buf) - 16 :])
        if retry_integrity_tag(conn.odcid, body) != tag:
            self.metrics["pkt_malformed"] += 1
            return len(buf) - pos
        # token = everything between the header CIDs and the tag
        p = pos + 5
        p += 1 + buf[p]                 # dcid
        p += 1 + buf[p]                 # scid
        token = bytes(buf[p : len(buf) - 16])
        if not token:
            # RFC 9000 §17.2.5.1: a Retry with a zero-length token MUST
            # be discarded (and accepting it would also defeat the
            # one-Retry-per-conn guard, which keys on conn.token)
            self.metrics["pkt_malformed"] += 1
            return len(buf) - pos
        conn.apply_retry(retry_scid, token)
        self._touched.add(conn.scid)
        return len(buf) - pos           # Retry owns its datagram

    def _prepare_datagram(self, buf: bytearray, addr, jobs: list) -> None:
        pos = 0
        while pos < len(buf):
            try:
                consumed = self._prepare_packet(buf, pos, addr, jobs)
            except (IndexError, ValueError):
                # malformed header bytes must never escape the rx path —
                # one bad datagram would otherwise kill the ingest tile
                self.metrics["pkt_malformed"] += 1
                return
            if consumed <= 0:
                return
            pos += consumed

    def _prepare_packet(self, buf: bytearray, pos: int, addr,
                        jobs: list) -> int:
        """Parse one packet's cleartext header and queue its crypt job;
        returns bytes consumed (coalesced packets carry explicit lengths,
        so the walk never needs decrypt results)."""
        self.metrics["pkt_rx"] += 1
        first = buf[pos]
        if first & 0x80:  # long header
            if pos + 6 > len(buf):
                self.metrics["pkt_malformed"] += 1
                return -1
            version = int.from_bytes(buf[pos + 1 : pos + 5], "big")
            if version != QUIC_VERSION:
                self.metrics["pkt_malformed"] += 1
                return -1
            p = pos + 5
            dcid_len = buf[p]
            dcid = bytes(buf[p + 1 : p + 1 + dcid_len])
            p += 1 + dcid_len
            scid_len = buf[p]
            scid = bytes(buf[p + 1 : p + 1 + scid_len])
            p += 1 + scid_len
            ptype = (first >> 4) & 0x3
            token = b""
            if ptype == 0:  # Initial: token
                tok_len, p = dec_varint(buf, p)
                token = bytes(buf[p : p + tok_len])
                p += tok_len
            elif ptype == 3:  # Retry: conn-state mutation, finish-phase
                j = _RxJob()
                j.kind = _J_RETRY
                j.buf, j.start, j.dcid, j.scid = buf, pos, dcid, scid
                jobs.append(j)
                return len(buf) - pos  # Retry owns its datagram
            elif ptype not in (2,):  # 0-RTT unsupported
                self.metrics["pkt_malformed"] += 1
                return -1
            length, p = dec_varint(buf, p)
            pn_off = p
            end = p + length
            if end > len(buf):
                # length field claims bytes the datagram doesn't have:
                # truncated or forged — count the shed, drop the rest
                self.metrics["pkt_malformed"] += 1
                return -1
            space = _TYPE_SPACE[ptype]
            conn = self.conns.get(dcid)
            if conn is None and self.cfg.is_server and space == SP_INITIAL:
                conn = self._initial_conns.get(dcid)
                if conn is None:
                    return self._prepare_new_conn(
                        buf, pos, pn_off, end, addr, dcid, scid, token,
                        jobs)
            if conn is None:
                self.metrics["pkt_undecryptable"] += 1
                return end - pos
            j = _RxJob()
            j.buf, j.start, j.pn_off, j.end = buf, pos, pn_off, end
            j.conn, j.space, j.scid, j.addr = conn, space, scid, addr
            keys = conn.rx_keys[space]
            if keys is None:
                # keys may install mid-burst (coalesced handshake flight):
                # defer, crypt at finish once the earlier packets ran
                j.kind = _J_LATE
            else:
                j.kind = _J_CRYPT
                j.keys = keys
                j.expected = conn.spaces[space].largest_rx + 1
            jobs.append(j)
            return end - pos
        else:  # short header: dcid is our fixed-size scid
            dcid = bytes(buf[pos + 1 : pos + 1 + CID_SZ])
            conn = self.conns.get(dcid)
            if conn is None:
                self.metrics["pkt_undecryptable"] += 1
                return -1
            j = _RxJob()
            j.buf, j.start = buf, pos
            j.pn_off, j.end = pos + 1 + CID_SZ, len(buf)
            j.conn, j.space, j.scid, j.addr = conn, SP_APP, None, addr
            keys = conn.rx_keys[SP_APP]
            if keys is None:
                j.kind = _J_LATE
            else:
                j.kind = _J_CRYPT
                j.keys = keys
                j.expected = conn.spaces[SP_APP].largest_rx + 1
            jobs.append(j)
            return len(buf) - pos

    def _prepare_new_conn(self, buf: bytearray, pos: int, pn_off: int,
                          end: int, addr, dcid: bytes, scid: bytes,
                          token: bytes, jobs: list) -> int:
        """New-conn admission, prepare half: authenticate the Initial
        against the dcid-derived keys BEFORE paying for conn state (TLS
        endpoint, maps) — spoofed garbage costs one burst-amortized AEAD
        check, nothing more.  Caps are prechecked here (cheap shed before
        the probe) and re-checked at finish under the post-burst tables."""
        peer_ip = addr[0] if isinstance(addr, tuple) else addr
        if (len(self.conns) >= self.cfg.max_conns
                and not self._evict_lru_idle()):
            self.metrics["conn_reject"] += 1
            return end - pos
        if (self.cfg.max_conns_per_peer
                and self._peer_conns.get(peer_ip, 0)
                >= self.cfg.max_conns_per_peer):
            self.metrics["conn_reject"] += 1
            return end - pos
        j = _RxJob()
        j.kind = _J_NEW
        j.buf, j.start, j.pn_off, j.end = buf, pos, pn_off, end
        j.addr, j.dcid, j.scid, j.token = addr, dcid, scid, token
        j.conn, j.space = None, SP_INITIAL
        j.keys = self._initial_keys_cached(dcid)[0]
        jobs.append(j)
        return end - pos

    def _finish_crypt(self, j: _RxJob) -> None:
        conn = j.conn
        # the conn may have been dropped, or the space's keys rotated /
        # retired, by an earlier packet in this burst
        if (self.conns.get(conn.scid) is not conn
                or conn.rx_keys[j.space] is not j.keys):
            self.metrics["pkt_undecryptable"] += 1
            return
        ok, pn, pt_off, pt_len = j.result
        if not ok:
            self.metrics["pkt_undecryptable"] += 1
            return
        self._post_decrypt(conn, j.space, pn,
                           memoryview(j.buf)[pt_off : pt_off + pt_len],
                           j.end - j.start, j.scid)

    def _finish_late(self, j: _RxJob) -> None:
        """Deferred single-packet crypt: the keys this packet needs were
        installed by an earlier packet in the same burst (or never came —
        then it shds as undecryptable, matching the sequential path)."""
        conn = j.conn
        keys = (conn.rx_keys[j.space]
                if self.conns.get(conn.scid) is conn else None)
        if keys is None:
            self.metrics["pkt_undecryptable"] += 1
            return
        be = self._crypto
        res = be.decrypt_burst(
            [(j.buf, j.start, j.pn_off, j.end, keys.slot(be),
              conn.spaces[j.space].largest_rx + 1)])
        self.metrics["crypto_native" if be.native
                     else "crypto_fallback"] += 1
        ok, pn, pt_off, pt_len = res[0]
        if not ok:
            self.metrics["pkt_undecryptable"] += 1
            return
        self._post_decrypt(conn, j.space, pn,
                           memoryview(j.buf)[pt_off : pt_off + pt_len],
                           j.end - j.start, j.scid)

    def _finish_new(self, j: _RxJob) -> None:
        """New-conn admission, finish half (arrival order preserved)."""
        conn = self._initial_conns.get(j.dcid)
        ok, pn, pt_off, pt_len = j.result
        if conn is not None:
            # an earlier packet in this burst created the conn: route as
            # an existing-conn Initial (same cached key-schedule object)
            if (self.conns.get(conn.scid) is not conn
                    or conn.rx_keys[SP_INITIAL] is not j.keys or not ok):
                self.metrics["pkt_undecryptable"] += 1
                return
            self._post_decrypt(conn, SP_INITIAL, pn,
                               memoryview(j.buf)[pt_off : pt_off + pt_len],
                               j.end - j.start, j.scid)
            return
        if not ok:
            self.metrics["pkt_undecryptable"] += 1
            return
        addr, dcid, scid, token = j.addr, j.dcid, j.scid, j.token
        peer_ip = addr[0] if isinstance(addr, tuple) else addr
        # re-check the caps: earlier packets in this burst may have
        # created conns since the prepare-phase precheck
        if (len(self.conns) >= self.cfg.max_conns
                and not self._evict_lru_idle()):
            self.metrics["conn_reject"] += 1
            return
        if (self.cfg.max_conns_per_peer
                and self._peer_conns.get(peer_ip, 0)
                >= self.cfg.max_conns_per_peer):
            self.metrics["conn_reject"] += 1
            return
        orig_dcid = dcid
        retry_on = self.cfg.retry or (
            self.cfg.retry_half_open_threshold > 0
            and self.half_open >= self.cfg.retry_half_open_threshold)
        if retry_on:
            if not token:
                # authenticated but unvalidated source: answer with a
                # stateless Retry and keep NO state — the AEAD probe
                # means random spoofed garbage never elicits the Retry
                self._send_retry(dcid, scid, addr)
                return
            tok = self._open_retry_token(token, addr)
            if tok is None or tok[1] != dcid:
                # wrong address, expired, or token not minted for this
                # CID: drop silently (RFC 9000 §8.1.3 allows close;
                # silence is cheaper)
                self.metrics["retry_token_reject"] += 1
                return
            orig_dcid = tok[0]
            self.metrics["retry_token_accept"] += 1
        conn = QuicConn(self, addr, is_server=True, odcid=dcid,
                        orig_dcid=orig_dcid,
                        init_keys=self._initial_keys_cached(dcid))
        if retry_on:
            # a token-validated source is a validated path: the 3x
            # anti-amplification clamp no longer binds
            conn.addr_validated = True
        conn._peer_ip = peer_ip
        self._peer_conns[peer_ip] = self._peer_conns.get(peer_ip, 0) + 1
        conn._half_open = True
        self.half_open += 1
        self._initial_conns[dcid] = conn
        self.conns[conn.scid] = conn
        self.metrics["conn_created"] += 1
        self._touched.add(conn.scid)
        if scid:
            conn.dcid = scid
        sp = conn.spaces[SP_INITIAL]
        sp.rx_pns.add(pn)
        sp.largest_rx = pn
        conn.rx_bytes += j.end - j.start
        conn.last_rx = self.now
        self._process_frames(conn, SP_INITIAL,
                             memoryview(j.buf)[pt_off : pt_off + pt_len])

    def _post_decrypt(self, conn: QuicConn, space: int, pn: int, payload,
                      nbytes: int, peer_scid: bytes | None) -> None:
        sp = conn.spaces[space]
        if peer_scid:
            # adopt the peer's CID only AFTER the packet authenticates —
            # a forged cleartext header must not redirect a live conn
            conn.dcid = peer_scid
        conn.rx_bytes += nbytes
        if space != SP_INITIAL:
            conn.addr_validated = True  # peer proved handshake-key possession
        self._touched.add(conn.scid)
        if pn <= sp.rx_floor or pn in sp.rx_pns:
            return  # duplicate
        sp.rx_pns.add(pn)
        sp.largest_rx = max(sp.largest_rx, pn)
        sp.prune()
        conn.last_rx = self.now
        self._process_frames(conn, space, payload)

    # ---------------------------------------------------------------- frames

    # Frames permitted in the Initial and Handshake spaces (RFC 9000 §12.4):
    # PADDING, PING, ACK, CRYPTO, CONNECTION_CLOSE (transport flavor only).
    # Everything else — STREAM, MAX_*, HANDSHAKE_DONE, ... — is 1-RTT-only;
    # processing it from an Initial packet would let an off-path attacker
    # (Initial keys derive from the public DCID) inject stream data with no
    # TLS handshake at all.
    _PRE_1RTT_FRAMES = frozenset({0x00, 0x01, 0x02, 0x03, 0x06, 0x1C})

    def _process_frames(self, conn: QuicConn, space: int, payload: bytes) -> None:
        pos = 0
        sp = conn.spaces[space]
        try:
            while pos < len(payload):
                ftype = payload[pos]
                if ftype == 0x00:  # PADDING
                    pos += 1
                    continue
                if space != SP_APP and ftype not in self._PRE_1RTT_FRAMES:
                    raise ValueError(
                        f"frame type {ftype:#x} not allowed at level {space}"
                    )
                sp.ack_pending = sp.ack_pending or ftype not in (0x02, 0x03)
                if ftype == 0x01:  # PING
                    pos += 1
                elif ftype in (0x02, 0x03):  # ACK
                    pos = self._on_ack(conn, space, payload, pos)
                elif ftype == 0x06:  # CRYPTO
                    off, pos = dec_varint(payload, pos + 1)
                    ln, pos = dec_varint(payload, pos)
                    # bytes() — the TLS layer hashes/stores its input and
                    # payload may be a view into a reused rx burst buffer
                    data = bytes(payload[pos : pos + ln])
                    pos += ln
                    self._on_crypto(conn, space, off, data)
                elif 0x08 <= ftype <= 0x0F:  # STREAM
                    pos = self._on_stream_frame(conn, payload, pos)
                elif ftype == 0x10:  # MAX_DATA
                    v, pos = dec_varint(payload, pos + 1)
                    conn.peer_max_data = max(conn.peer_max_data, v)
                elif ftype == 0x11:  # MAX_STREAM_DATA
                    _, pos = dec_varint(payload, pos + 1)
                    v, pos = dec_varint(payload, pos)
                elif ftype in (0x12, 0x13):  # MAX_STREAMS
                    v, pos = dec_varint(payload, pos + 1)
                    if ftype == 0x13:
                        conn.peer_max_streams_uni = max(
                            conn.peer_max_streams_uni, v
                        )
                elif ftype in (0x14, 0x15, 0x16, 0x17):  # blocked frames
                    _, pos = dec_varint(payload, pos + 1)
                elif ftype == 0x1E:  # HANDSHAKE_DONE
                    pos += 1
                    conn.rx_keys[SP_INITIAL] = None
                    conn.tx_keys[SP_INITIAL] = None
                elif ftype in (0x1C, 0x1D):  # CONNECTION_CLOSE
                    code, pos = dec_varint(payload, pos + 1)
                    if ftype == 0x1C:
                        _, pos = dec_varint(payload, pos)  # frame type
                    rlen, pos = dec_varint(payload, pos)
                    reason = bytes(payload[pos : pos + rlen])
                    pos += rlen
                    conn.closed = True
                    conn.close_reason = (code, reason)
                    self._drop_conn(conn)
                    return
                else:
                    raise ValueError(f"unknown frame type {ftype:#x}")
        except (_tls.TlsError, ValueError, IndexError) as e:
            self._fatal(conn, e)

    def _fatal(self, conn: QuicConn, err) -> None:
        code = 0x100 + err.alert if isinstance(err, _tls.TlsError) else 0x0A
        if not conn.closed:
            conn.close(code, str(err).encode()[:64])
        self._drop_conn(conn)

    def _drop_conn(self, conn: QuicConn) -> None:
        self.conns.pop(conn.scid, None)
        if self._initial_conns.get(conn.odcid) is conn:
            del self._initial_conns[conn.odcid]
        if conn._half_open:
            conn._half_open = False
            self.half_open -= 1
        if conn._peer_ip is not None:
            left = self._peer_conns.get(conn._peer_ip, 1) - 1
            if left > 0:
                self._peer_conns[conn._peer_ip] = left
            else:
                self._peer_conns.pop(conn._peer_ip, None)
            conn._peer_ip = None
        self.metrics["conn_closed"] += 1
        if self.on_conn_closed:
            self.on_conn_closed(conn)

    def _evict_lru_idle(self) -> bool:
        """Global conn table full: evict the least-recently-active conn —
        but only if it has been idle at least cfg.lru_evict_idle, so a
        flood can reclaim slots parked by dead peers without churning hot
        conns.  Returns True if a slot was freed."""
        if not self.conns:
            return False
        victim = min(self.conns.values(), key=lambda c: c.last_rx)
        if self.now - victim.last_rx < self.cfg.lru_evict_idle:
            return False
        victim.closed = True
        self.metrics["conn_evict"] += 1
        self._drop_conn(victim)
        return len(self.conns) < self.cfg.max_conns

    def _on_ack(self, conn: QuicConn, space: int, payload: bytes, pos: int) -> int:
        ftype = payload[pos]
        largest, pos = dec_varint(payload, pos + 1)
        _, pos = dec_varint(payload, pos)  # ack delay
        range_count, pos = dec_varint(payload, pos)
        first_range, pos = dec_varint(payload, pos)
        conn.pto_count = 0  # path is alive; reset retransmit backoff
        sp = conn.spaces[space]
        lo = largest - first_range
        _ack_span(sp, lo, largest)
        for _ in range(range_count):
            gap, pos = dec_varint(payload, pos)
            rng_len, pos = dec_varint(payload, pos)
            hi = lo - gap - 2
            lo = hi - rng_len
            if hi < 0:
                break
            _ack_span(sp, lo, hi)
        if ftype == 0x03:  # ECN counts
            for _ in range(3):
                _, pos = dec_varint(payload, pos)
        return pos

    def _on_crypto(self, conn: QuicConn, space: int, off: int, data: bytes) -> None:
        # TLS layer handles reordering-free in-order delivery; QUIC must
        # deliver in order.  We tolerate only in-order CRYPTO (the peer is
        # our own stack or a well-behaved one; out-of-order chunks are
        # buffered by retransmit).
        done_before = conn.tls.complete
        expected = conn._crypto_rx_off
        if off > expected[space]:
            # bounded out-of-order buffer: a handshake fits in well under
            # 256 KiB / 64 chunks; beyond that it's garbage or an attack
            if off > 1 << 18 or len(conn._crypto_pend) >= 64:
                return
            conn._crypto_pend[(space, off)] = data
            return
        skip = expected[space] - off
        if skip >= len(data) and len(data) > 0:
            return
        conn.tls.feed(space, data[skip:])
        expected[space] += len(data) - skip
        # drain any buffered out-of-order chunks now contiguous
        pend = conn._crypto_pend
        progressed = True
        while progressed:
            progressed = False
            for (sp_i, o), d in list(pend.items()):
                if sp_i == space and o <= expected[space]:
                    del pend[(sp_i, o)]
                    sk = expected[space] - o
                    if sk < len(d):
                        conn.tls.feed(space, d[sk:])
                        expected[space] += len(d) - sk
                    progressed = True
        conn._pump_tls()
        if conn.tls.complete and not done_before:
            conn._on_tls_complete()
            if conn.is_server:
                conn.handshake_done_sent = False  # send HANDSHAKE_DONE

    def _on_stream_frame(self, conn: QuicConn, payload: bytes, pos: int) -> int:
        ftype = payload[pos]
        pos += 1
        sid, pos = dec_varint(payload, pos)
        off = 0
        if ftype & 0x04:
            off, pos = dec_varint(payload, pos)
        if ftype & 0x02:
            ln, pos = dec_varint(payload, pos)
            data = payload[pos : pos + ln]
            pos += ln
        else:
            data = payload[pos:]
            pos = len(payload)
        fin = bool(ftype & 0x01)
        if not conn.handshake_done:
            # 1-RTT rx keys install after our own flight, i.e. before the
            # peer's Finished (and client cert, when required) has verified.
            # Acting on stream data in that window would bypass the
            # stake-identity mutual auth — but the packet still gets ACKed,
            # so dropping would lose the data forever.  Buffer (bounded) and
            # replay once the handshake completes; a peer that floods past
            # the bound pre-auth gets the conn torn down (silent loss of
            # ACKed data is never acceptable, and an unauthenticated peer
            # has no business pipelining that much).
            if len(conn._early_streams) >= 64:
                raise ValueError("pre-handshake stream flood")
            conn._early_streams.append((sid, off, bytes(data), fin))
            return pos
        self._apply_stream(conn, sid, off, data, fin)
        return pos

    def _apply_stream(
        self, conn: QuicConn, sid: int, off: int, data: bytes, fin: bool
    ) -> None:
        conn.peer_streams_seen = max(conn.peer_streams_seen, sid // 4 + 1)
        if sid in conn.finished_streams:
            return
        if len(conn.finished_streams) > 1 << 16:
            # evict the OLDEST quarter (dict preserves insertion order);
            # clearing wholesale would re-open every already-delivered
            # stream id for duplicate publication
            drop = len(conn.finished_streams) >> 2
            for old in list(conn.finished_streams)[:drop]:
                del conn.finished_streams[old]
        st = conn.recv_streams.get(sid)
        if st is None:
            if len(conn.recv_streams) >= 4096:
                # FIFO-evict the oldest in-progress stream (reference
                # reasm slot eviction, fd_tpu.h:53-69)
                self._pop_recv_stream(conn, next(iter(conn.recv_streams)))
                self.metrics["reasm_evict"] += 1
            st = conn.recv_streams[sid] = _RecvStream()
        if off + len(data) > self.rx_max_stream_data:
            self._pop_recv_stream(conn, sid)
            return
        if data:
            st.frags.setdefault(off, data)
            # count only bytes beyond the stream's high-water mark toward
            # the conn-level window: retransmits — including ones
            # resegmented at different offsets — must not inflate credit
            # consumption
            end = off + len(data)
            if end > st.max_end:
                delta = end - st.max_end
                conn.rx_data += delta
                conn.reasm_bytes += delta
                st.max_end = end
                if conn.rx_data > conn.rx_max_data_sent:
                    raise ValueError(
                        "flow control violation: rx past MAX_DATA")
                budget = self.cfg.conn_reasm_budget
                if budget and conn.reasm_bytes > budget:
                    self._shed_reasm(conn, keep_sid=sid)
                    if sid not in conn.recv_streams:
                        return  # this stream itself busted the budget
        if fin:
            st.fin_size = off + len(data)
        # deliver when contiguous through fin
        if st.fin_size >= 0 and not st.delivered:
            single = st.frags.get(0) if len(st.frags) == 1 else None
            if single is not None and len(single) >= st.fin_size:
                # zero-copy fast path: the whole stream arrived as one
                # frame (the steady-state txn shape).  When the consumer
                # opted into views the payload hands out straight from
                # the rx burst buffer — no join, no copy.
                st.delivered = True
                conn.finished_streams[sid] = None
                self._pop_recv_stream(conn, sid)
                if not self._txn_admit(conn):
                    self.metrics["rate_drop"] += 1
                    return
                self.metrics["streams_rx"] += 1
                if self.on_stream:
                    view = single[: st.fin_size]
                    self.on_stream(
                        conn, sid,
                        view if self.stream_views else bytes(view))
                return
            buf = bytearray()
            want = 0
            frags = dict(st.frags)
            while want in frags:
                d = frags.pop(want)
                buf += d
                want += len(d)
            if want >= st.fin_size:
                st.delivered = True
                conn.finished_streams[sid] = None
                self._pop_recv_stream(conn, sid)
                if not self._txn_admit(conn):
                    self.metrics["rate_drop"] += 1
                    return
                self.metrics["streams_rx"] += 1
                if self.on_stream:
                    self.on_stream(conn, sid, bytes(buf[: st.fin_size]))
                return
        # this stream outlives the call: a memoryview frag would pin its
        # whole rx datagram buffer across bursts, so demote to bytes (the
        # delivered-above fast path never pays this copy)
        if (data and isinstance(data, memoryview)
                and st.frags.get(off) is data):
            st.frags[off] = bytes(data)
        return

    @staticmethod
    def _pop_recv_stream(conn: QuicConn, sid: int) -> None:
        """Every recv_streams removal goes through here so the per-conn
        buffered-byte accounting (conn_reasm_budget) never leaks."""
        st = conn.recv_streams.pop(sid, None)
        if st is not None:
            conn.reasm_bytes -= st.max_end

    def _shed_reasm(self, conn: QuicConn, keep_sid: int) -> None:
        """Per-conn partial-stream byte budget: evict-oldest, never grow
        (the wire-path mirror of TpuReasm's conn_budget).  The in-flight
        stream is kept if shedding others gets back under budget; if it
        alone busts the budget it is shed too."""
        budget = self.cfg.conn_reasm_budget
        for old in list(conn.recv_streams):
            if conn.reasm_bytes <= budget:
                return
            if old == keep_sid:
                continue
            self._pop_recv_stream(conn, old)
            self.metrics["reasm_evict"] += 1
        if conn.reasm_bytes > budget:
            self._pop_recv_stream(conn, keep_sid)
            self.metrics["reasm_evict"] += 1

    def _txn_admit(self, conn: QuicConn) -> bool:
        """Per-conn completed-txn token bucket (quic-tile rate limiting):
        False = shed this stream (the frame is still ACKed and the stream
        marked delivered — the sender pays for the bytes either way)."""
        rate = self.cfg.conn_txn_rate
        if rate <= 0:
            return True
        tokens = min(float(self.cfg.conn_txn_burst),
                     conn._txn_tokens + (self.now - conn._txn_ts) * rate)
        conn._txn_ts = self.now
        if tokens < 1.0:
            conn._txn_tokens = tokens
            return False
        conn._txn_tokens = tokens - 1.0
        return True

    # ------------------------------------------------------------------- send

    def _emit(
        self, conn: QuicConn, space: int, frame: bytes,
        ack_eliciting: bool, retrans,
    ) -> None:
        """Queue one frame for the next packet in `space`."""
        conn._frame_q[space].append((frame, ack_eliciting, retrans))

    def _flush(self, conn: QuicConn) -> None:
        """Build and queue datagrams for everything pending on `conn`."""
        if conn.scid not in self.conns and not conn.closed:
            return
        conn._pump_tls()
        self._queue_crypto_frames(conn)
        self._queue_stream_frames(conn)
        self._queue_acks(conn)
        self._queue_flow_control(conn)
        self._queue_handshake_done(conn)
        q = conn._frame_q
        datagram: list = []          # packet parts of the coalesced dgram
        dlen = 0
        overflow: list = []          # chunks beyond the first, in order
        for space in (SP_INITIAL, SP_HANDSHAKE, SP_APP):
            frames = q[space]
            if conn.tx_keys[space] is None:
                q[space] = []  # space retired (keys dropped): the data is
                # obsolete by definition — never strand frames here
                continue
            if not frames:
                continue
            q[space] = []
            # PACKETIZE (round 5; the firehose bench found a single join
            # of every queued frame building >64 KB datagrams — EMSGSIZE
            # at sendto): greedy-chunk frames to a ~1200 B datagram
            # budget.  The first chunk joins the coalesced datagram
            # (Initial+Handshake coalescing, RFC 9000 §12.2); each
            # further chunk flushes as its own datagram.  One frame
            # larger than the budget (a full-MTU txn stream) rides alone.
            PAYLOAD_CAP = 1200 - 46          # hdr + pn + tag headroom
            chunks: list[list] = [[]]
            size = 0
            for fr in frames:
                if chunks[-1] and size + len(fr[0]) > PAYLOAD_CAP:
                    chunks.append([])
                    size = 0
                chunks[-1].append(fr)
                size += len(fr[0])
            for ci, chunk in enumerate(chunks):
                payload = b"".join(f for f, _, _ in chunk)
                ack_eliciting = any(a for _, a, _ in chunk)
                retrans = [r for _, _, r in chunk if r]
                pkt = self._build_packet(
                    conn, space, payload, ack_eliciting, retrans
                )
                if ci == 0 and (not datagram
                                or dlen + len(pkt) <= 1452):
                    # coalesce only while the DATAGRAM stays under wire
                    # MTU (1500 - headers): a padded Initial + a full
                    # later-space chunk would otherwise truncate at the
                    # receiver's recvfrom (code-review r5)
                    datagram.append(pkt)
                    dlen += len(pkt)
                else:
                    overflow.append(pkt)
        if datagram:
            self._queue_dgram(conn, datagram, dlen)
        for pkt in overflow:          # after the coalesced datagram:
            self._queue_dgram(conn, [pkt], len(pkt))  # pn/arrival order

    def _queue_dgram(self, conn: QuicConn, parts: list, length: int) -> None:
        """Queue a datagram built from still-plaintext packet parts; the
        burst encrypt in _send_pending seals them in place before the
        parts are joined for the wire."""
        if not conn.addr_validated:
            # RFC 9000 §8.1: at most 3x the bytes received from an
            # unvalidated path.  Dropping here is safe: retransmittable
            # frames are already in sp.sent and PTO re-queues them once
            # (if ever) the peer earns more credit.
            if conn.tx_bytes + length > 3 * conn.rx_bytes:
                return
            conn.tx_bytes += length
        self._pending_dgrams.append((parts, conn.peer))

    def _build_packet(
        self, conn: QuicConn, space: int, payload: bytes,
        ack_eliciting: bool, retrans,
    ) -> bytearray:
        """Assemble one packet as PLAINTEXT (header | pn | payload | tag
        space) and queue its encrypt job; _send_pending seals the whole
        pending batch with one burst-encrypt call."""
        keys = conn.tx_keys[space]
        sp = conn.spaces[space]
        pn = sp.next_pn
        sp.next_pn += 1
        pn_bytes = (pn & 0xFFFFFFFF).to_bytes(4, "big")
        # client Initial packets must make the datagram >= 1200: pad here
        if space == SP_INITIAL and not conn.is_server:
            # client datagrams containing Initial packets must be >= 1200B
            # (RFC 9000 §14.1): pad inside the packet with PADDING frames
            # long hdr = 1 + 4 + (1+8)*2 + 1 token + 2 length varint = 26;
            # pn = 4, tag = 16 → pad payload so the datagram reaches 1200
            min_payload = 1200 - 46
            if len(payload) < min_payload:
                payload = payload + b"\0" * (min_payload - len(payload))
        if len(payload) < 4:  # AEAD sample needs >= 4 bytes of pn+payload
            payload = payload + b"\0" * (4 - len(payload))
        if space in _LONG_TYPE:
            first = 0xC0 | (_LONG_TYPE[space] << 4) | 0x03  # pn_len=4
            hdr = (
                bytes([first])
                + QUIC_VERSION.to_bytes(4, "big")
                + bytes([len(conn.dcid)])
                + conn.dcid
                + bytes([len(conn.scid)])
                + conn.scid
            )
            if space == SP_INITIAL:
                hdr += enc_varint(len(conn.token)) + conn.token
            hdr += enc_varint(4 + len(payload) + 16)  # pn + payload + tag
        else:
            first = 0x40 | 0x03
            hdr = bytes([first]) + conn.dcid
        pn_off = len(hdr)
        pkt = bytearray(pn_off + 4 + len(payload) + 16)
        pkt[:pn_off] = hdr
        pkt[pn_off : pn_off + 4] = pn_bytes
        pkt[pn_off + 4 : pn_off + 4 + len(payload)] = payload
        self._tx_jobs.append(
            (pkt, pn_off, pn, len(payload), conn.tx_keys[space]))
        self.metrics["pkt_tx"] += 1
        if ack_eliciting or retrans:
            sp.sent[pn] = _SentPkt(retrans, self.now, ack_eliciting)
            # an in-flight packet arms a PTO: pull the service deadline in
            # (conservatively at the un-backed-off base PTO)
            self._next_deadline = min(
                self._next_deadline, self.now + self.cfg.pto)
        return pkt

    def _queue_crypto_frames(self, conn: QuicConn) -> None:
        for space in (SP_INITIAL, SP_HANDSHAKE, SP_APP):
            buf = conn.crypto_buf[space]
            sent = conn.crypto_sent[space]
            if sent >= len(buf) or conn.tx_keys[space] is None:
                continue
            mtu = 1100
            while sent < len(buf):
                chunk = buf[sent : sent + mtu]
                frame = (
                    b"\x06"
                    + enc_varint(sent)
                    + enc_varint(len(chunk))
                    + chunk
                )
                self._emit(
                    conn, space, frame, True,
                    ("crypto", space, sent, len(chunk)),
                )
                sent += len(chunk)
            conn.crypto_sent[space] = sent

    def _queue_stream_frames(self, conn: QuicConn) -> None:
        if conn.tx_keys[SP_APP] is None or not conn.handshake_done:
            return
        while conn.send_queue:
            sid, data, off = conn.send_queue[0]
            if conn.tx_data + len(data) > conn.peer_max_data:
                break  # out of conn-level credit; wait for MAX_DATA
            conn.send_queue.pop(0)
            frame = (
                bytes([0x08 | 0x04 | 0x02 | 0x01])
                + enc_varint(sid)
                + enc_varint(off)
                + enc_varint(len(data))
                + data
            )
            self._emit(
                conn, SP_APP, frame, True, ("stream", sid, data, off)
            )
            conn.tx_data += len(data)

    def _queue_acks(self, conn: QuicConn) -> None:
        for space in (SP_INITIAL, SP_HANDSHAKE, SP_APP):
            sp = conn.spaces[space]
            if not sp.ack_pending or conn.tx_keys[space] is None:
                continue
            sp.ack_pending = False
            runs = sp.ack_ranges()
            if not runs:
                continue
            largest, lo = runs[0]
            frame = (
                b"\x02"
                + enc_varint(largest)
                + enc_varint(0)
                + enc_varint(len(runs) - 1)
                + enc_varint(largest - lo)
            )
            prev_lo = lo
            for hi, lo2 in runs[1:]:
                frame += enc_varint(prev_lo - hi - 2) + enc_varint(hi - lo2)
                prev_lo = lo2
            self._emit(conn, space, frame, False, None)

    def _queue_flow_control(self, conn: QuicConn) -> None:
        """Replenish peer credit: MAX_STREAMS / MAX_DATA once the peer has
        consumed half its window (the reference's per-conn quota refills,
        fd_quic.h flow control)."""
        if conn.tx_keys[SP_APP] is None or not conn.handshake_done:
            return
        if conn.peer_streams_seen * 2 > conn.rx_max_streams_sent:
            conn.rx_max_streams_sent += self.rx_max_streams
            self._emit(
                conn, SP_APP,
                b"\x13" + enc_varint(conn.rx_max_streams_sent), True,
                ("maxstreams",),  # retransmittable: a lost credit frame
                # must not stall the peer at the old limit forever
            )
        if conn.rx_data * 2 > conn.rx_max_data_sent:
            conn.rx_max_data_sent += self.rx_max_data
            self._emit(
                conn, SP_APP,
                b"\x10" + enc_varint(conn.rx_max_data_sent), True,
                ("maxdata",),
            )

    def _queue_handshake_done(self, conn: QuicConn) -> None:
        if (
            conn.is_server
            and conn.handshake_done
            and not conn.handshake_done_sent
            and conn.tx_keys[SP_APP] is not None
        ):
            conn.handshake_done_sent = True
            self._emit(conn, SP_APP, b"\x1e", True, ("hsdone",))
            # initial keys no longer needed
            conn.rx_keys[SP_INITIAL] = None
            conn.tx_keys[SP_INITIAL] = None

    def _send_pending(self) -> None:
        if self._tx_jobs:
            # one burst-encrypt seals every packet built since the last
            # send — the whole tx flight pays a single crypto call
            jobs, self._tx_jobs = self._tx_jobs, []
            be = self._crypto
            be.encrypt_burst(
                [(buf, pn_off, pn, pt_len, keys.slot(be))
                 for buf, pn_off, pn, pt_len, keys in jobs])
            self.metrics["crypto_native" if be.native
                         else "crypto_fallback"] += len(jobs)
        if self._pending_dgrams:
            out, self._pending_dgrams = self._pending_dgrams, []
            self.tx.send(
                [p if isinstance(p, Pkt)
                 else Pkt(bytes(p[0][0]) if len(p[0]) == 1
                          else b"".join(p[0]), p[1])
                 for p in out])

    # ---------------------------------------------------------------- service

    def next_timeout(self) -> float:
        """Earliest instant service() has timer work (a PTO retransmit or
        an idle-timeout reap).  Computed by service() and pulled earlier by
        every in-flight send — callers drive service() from this deadline
        instead of a fixed polling cadence."""
        return self._next_deadline

    def service(self, now: float) -> None:
        """Timers: PTO retransmit, idle timeout.  Call when next_timeout()
        has elapsed (or periodically)."""
        self.now = now
        # recomputed below: min over conns of (idle deadline, earliest
        # PTO); packets recorded by _build_packet (incl. the retransmits
        # flushed at the bottom of this loop) pull it in further
        self._next_deadline = now + self.idle_timeout
        for conn in list(self.conns.values()):
            if now - conn.last_rx > self.idle_timeout:
                conn.closed = True
                self._drop_conn(conn)
                continue
            self._next_deadline = min(
                self._next_deadline, conn.last_rx + self.idle_timeout)
            # exponential PTO backoff (RFC 9002 §6.2): each ACK-less PTO
            # round doubles the timer; a cap bounds how much traffic a
            # non-responsive (possibly spoofed-source) peer can draw.
            pto = self.cfg.pto * (1 << min(conn.pto_count, 6))
            retransmitted = False
            for space in (SP_INITIAL, SP_HANDSHAKE, SP_APP):
                sp = conn.spaces[space]
                for pn, sent in list(sp.sent.items()):
                    if now - sent.time < pto:
                        self._next_deadline = min(
                            self._next_deadline, sent.time + pto)
                        continue
                    del sp.sent[pn]
                    self.metrics["retrans"] += 1
                    retransmitted = True
                    for r in sent.frames:
                        self._requeue(conn, space, r)
            if retransmitted:
                conn.pto_count += 1
                if conn.pto_count > self.cfg.max_pto:
                    conn.closed = True
                    self._drop_conn(conn)
                    continue
            self._flush(conn)
        self._send_pending()

    def _requeue(self, conn: QuicConn, space: int, r) -> None:
        kind = r[0]
        if kind == "crypto":
            _, sp_i, off, ln = r
            chunk = conn.crypto_buf[sp_i][off : off + ln]
            frame = b"\x06" + enc_varint(off) + enc_varint(len(chunk)) + chunk
            self._emit(conn, sp_i, frame, True, r)
        elif kind == "stream":
            _, sid, data, off = r
            frame = (
                bytes([0x08 | 0x04 | 0x02 | 0x01])
                + enc_varint(sid)
                + enc_varint(off)
                + enc_varint(len(data))
                + data
            )
            self._emit(conn, SP_APP, frame, True, r)
        elif kind == "hsdone":
            self._emit(conn, SP_APP, b"\x1e", True, r)
        elif kind == "maxstreams":
            # re-advertise the CURRENT limit (monotone, so always safe)
            self._emit(
                conn, SP_APP,
                b"\x13" + enc_varint(conn.rx_max_streams_sent), True, r,
            )
        elif kind == "maxdata":
            self._emit(
                conn, SP_APP,
                b"\x10" + enc_varint(conn.rx_max_data_sent), True, r,
            )


def _unprotect(
    keys: _Keys, buf: bytes, start: int, pn_off: int, end: int, expected: int
):
    """Remove header protection + AEAD-decrypt one packet.  Returns
    (pn, payload) or None if the sample is short or the tag fails."""
    sample = buf[pn_off + 4 : pn_off + 20]
    if len(sample) < 16:
        return None
    mask = aes_encrypt_block(keys.hp_rk, sample)
    first = buf[start] ^ (mask[0] & (0x0F if buf[start] & 0x80 else 0x1F))
    pn_len = (first & 0x03) + 1
    pn_bytes = bytes(buf[pn_off + i] ^ mask[1 + i] for i in range(pn_len))
    pn = _decode_pn(int.from_bytes(pn_bytes, "big"), pn_len, expected)
    header = bytes([first]) + buf[start + 1 : pn_off] + pn_bytes
    payload = keys.aead.decrypt(
        keys.nonce(pn), buf[pn_off + pn_len : end], header
    )
    if payload is None:
        return None
    return pn, payload


def _ack_span(sp: _PnSpace, lo: int, hi: int) -> None:
    """Drop acked pns in [lo, hi] from the sent map.  Iteration is bounded
    by the map size, never by the peer-supplied range width (a hostile ACK
    with a 2^61-wide range must not spin the ingest tile)."""
    if hi < lo:
        return
    if hi - lo < 64:
        for pn in range(max(lo, 0), hi + 1):
            sp.sent.pop(pn, None)
    else:
        for pn in [p for p in sp.sent if lo <= p <= hi]:
            del sp.sent[pn]


def _decode_pn(truncated: int, pn_len: int, expected: int) -> int:
    """RFC 9000 appendix A.3 packet-number reconstruction."""
    win = 1 << (pn_len * 8)
    half = win // 2
    candidate = (expected & ~(win - 1)) | truncated
    if candidate <= expected - half and candidate + win < (1 << 62):
        return candidate + win
    if candidate > expected + half and candidate >= win:
        return candidate - win
    return candidate
