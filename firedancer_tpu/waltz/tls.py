"""Minimal from-scratch TLS 1.3 for QUIC (RFC 8446 + RFC 9001 profile).

Reference role: src/waltz/tls/fd_tls.c — the reference replaced OpenSSL
with a ~5k-LoC TLS 1.3 subset speaking exactly the profile Solana QUIC
needs.  We implement the same subset, host-side Python:

  * one cipher suite: TLS_AES_128_GCM_SHA256
  * one group: X25519
  * one signature scheme: Ed25519, with self-signed X.509 certs
    (ballet/x509); mutual auth optional (Solana identifies staked peers
    by their client cert's Ed25519 key)
  * QUIC-only: no record layer, no 0-RTT, no HelloRetryRequest, no
    resumption — handshake messages are exchanged as raw bytes in CRYPTO
    frames at three encryption levels (initial/handshake/app) and the
    derived traffic secrets are exported to the QUIC packet protection
    (fd_quic_crypto_suites.c analogue lives in waltz/quic.py)

The endpoint is a pure state machine: `feed(level, bytes)` ingests
peer handshake flights (possibly fragmented), and `outbox` accumulates
(level, bytes) flights to send.  Traffic secrets appear in `secrets`.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field

from firedancer_tpu.ballet.hmac import hkdf_expand_label, hkdf_extract, hmac_sha256
from firedancer_tpu.ballet.x509 import cert_create, cert_pubkey
from firedancer_tpu.ops import x25519 as ecdh
from firedancer_tpu.ops.ed25519 import keypair_from_seed, sign, verify_one_host

# encryption levels (indices into key arrays, matching QUIC packet spaces)
INITIAL, HANDSHAKE, APP = 0, 1, 2

# handshake message types
_CLIENT_HELLO = 1
_SERVER_HELLO = 2
_ENCRYPTED_EXTS = 8
_CERTIFICATE = 11
_CERT_REQUEST = 13
_CERT_VERIFY = 15
_FINISHED = 20

_SUITE_AES128_GCM_SHA256 = 0x1301
_GROUP_X25519 = 0x001D
_SIG_ED25519 = 0x0807

_EXT_SNI = 0
_EXT_GROUPS = 10
_EXT_SIGALGS = 13
_EXT_ALPN = 16
_EXT_VERSIONS = 43
_EXT_KEYSHARE = 51
_EXT_QUIC_TP = 0x0039


class TlsError(Exception):
    """Fatal handshake failure; carries a TLS alert description code."""

    def __init__(self, alert: int, msg: str):
        super().__init__(msg)
        self.alert = alert


_A_HANDSHAKE_FAILURE = 40
_A_BAD_CERT = 42
_A_ILLEGAL_PARAM = 47
_A_DECODE_ERROR = 50
_A_DECRYPT_ERROR = 51
_A_PROTOCOL_VERSION = 70
_A_MISSING_EXT = 109


def _v8(b: bytes) -> bytes:
    return bytes([len(b)]) + b


def _v16(b: bytes) -> bytes:
    return len(b).to_bytes(2, "big") + b


def _v24(b: bytes) -> bytes:
    return len(b).to_bytes(3, "big") + b


def _msg(t: int, body: bytes) -> bytes:
    return bytes([t]) + _v24(body)


def _ext(t: int, body: bytes) -> bytes:
    return t.to_bytes(2, "big") + _v16(body)


class _Rd:
    def __init__(self, b: bytes):
        self.b = b
        self.p = 0

    def take(self, n: int) -> bytes:
        if self.p + n > len(self.b):
            raise TlsError(_A_DECODE_ERROR, "truncated")
        out = self.b[self.p : self.p + n]
        self.p += n
        return out

    def u8(self) -> int:
        return self.take(1)[0]

    def u16(self) -> int:
        return int.from_bytes(self.take(2), "big")

    def u24(self) -> int:
        return int.from_bytes(self.take(3), "big")

    def vec(self, lenbytes: int) -> bytes:
        n = int.from_bytes(self.take(lenbytes), "big")
        return self.take(n)

    def done(self) -> bool:
        return self.p >= len(self.b)


def _parse_exts(rd: _Rd) -> dict[int, bytes]:
    out: dict[int, bytes] = {}
    inner = _Rd(rd.vec(2))
    while not inner.done():
        t = inner.u16()
        out[t] = inner.vec(2)
    return out


def _transcript_hash(transcript: bytes) -> bytes:
    return hashlib.sha256(transcript).digest()


def _offers_tls13(body: bytes) -> bool:
    """Walk the ClientHello supported_versions list (vec<1> of 2-byte
    versions) — a substring scan could match 0x0304 spanning two
    entries."""
    try:
        versions = _Rd(body).vec(1)
    except TlsError:
        return False
    return any(
        versions[i : i + 2] == b"\x03\x04"
        for i in range(0, len(versions) - 1, 2)
    )


_CV_SERVER_CTX = b"\x20" * 64 + b"TLS 1.3, server CertificateVerify\x00"
_CV_CLIENT_CTX = b"\x20" * 64 + b"TLS 1.3, client CertificateVerify\x00"


@dataclass
class TlsEndpoint:
    """One side of a QUIC-TLS 1.3 handshake.

    Args:
      is_server: role
      identity_seed: 32-byte Ed25519 seed — the node identity key used for
        the self-signed cert (ref: validator identity keypair)
      transport_params: opaque QUIC transport parameters blob to offer
      alpn: application protocol (Solana TPU uses "solana-tpu")
      require_client_cert: server sends CertificateRequest (stake identity)
      rng: randomness source (injectable for tests)
      cert: pre-built DER cert for identity_seed (endpoints serving many
        conns build it once; per-conn cert generation costs three host
        scalar multiplications)
    """

    is_server: bool
    identity_seed: bytes
    transport_params: bytes = b""
    alpn: bytes = b"solana-tpu"
    require_client_cert: bool = True
    rng: object = os.urandom
    cert: bytes | None = None

    # outputs
    outbox: list = field(default_factory=list)  # [(level, bytes)]
    secrets: dict = field(default_factory=dict)  # level -> (c_secret, s_secret)
    peer_pubkey: bytes | None = None  # peer cert's Ed25519 key
    peer_transport_params: bytes | None = None
    complete: bool = False

    def __post_init__(self):
        self.pubkey, _, _ = keypair_from_seed(self.identity_seed)
        if self.cert is None:
            self.cert = cert_create(self.identity_seed, self.pubkey)
        self._esec = self.rng(32)  # ephemeral x25519 secret
        self._eshare = ecdh.public_key(self._esec)
        self._transcript = b""
        self._bufs = {INITIAL: b"", HANDSHAKE: b"", APP: b""}
        self._hs_secret = None
        self._master = None
        self._peer_fin_key = None
        self._my_fin_key = None
        self._client_cert_requested = False
        self._state = "start"
        if not self.is_server:
            self._send_client_hello()

    # ----------------------------------------------------------------- flights

    def _out(self, level: int, msg: bytes) -> None:
        self.outbox.append((level, msg))
        self._transcript += msg

    def _send_client_hello(self) -> None:
        exts = b"".join(
            [
                _ext(_EXT_VERSIONS, _v8((0x0304).to_bytes(2, "big"))),
                _ext(_EXT_GROUPS, _v16(_GROUP_X25519.to_bytes(2, "big"))),
                _ext(_EXT_SIGALGS, _v16(_SIG_ED25519.to_bytes(2, "big"))),
                _ext(
                    _EXT_KEYSHARE,
                    _v16(_GROUP_X25519.to_bytes(2, "big") + _v16(self._eshare)),
                ),
                _ext(_EXT_ALPN, _v16(_v8(self.alpn))),
                _ext(_EXT_QUIC_TP, self.transport_params),
            ]
        )
        body = (
            (0x0303).to_bytes(2, "big")
            + self.rng(32)
            + _v8(b"")  # legacy_session_id
            + _v16(_SUITE_AES128_GCM_SHA256.to_bytes(2, "big"))
            + _v8(b"\x00")  # legacy_compression
            + _v16(exts)
        )
        self._out(INITIAL, _msg(_CLIENT_HELLO, body))
        self._state = "wait_sh"

    # ------------------------------------------------------------- key schedule

    def _derive_handshake(self, peer_share: bytes) -> None:
        shared = ecdh.shared_secret(self._esec, peer_share)
        early = hkdf_extract(b"", b"\0" * 32)
        derived = hkdf_expand_label(early, "derived", hashlib.sha256(b"").digest(), 32)
        self._hs_secret = hkdf_extract(derived, shared)
        th = _transcript_hash(self._transcript)
        c_hs = hkdf_expand_label(self._hs_secret, "c hs traffic", th, 32)
        s_hs = hkdf_expand_label(self._hs_secret, "s hs traffic", th, 32)
        self.secrets[HANDSHAKE] = (c_hs, s_hs)
        peer_hs, my_hs = (c_hs, s_hs) if self.is_server else (s_hs, c_hs)
        self._peer_fin_key = hkdf_expand_label(peer_hs, "finished", b"", 32)
        self._my_fin_key = hkdf_expand_label(my_hs, "finished", b"", 32)
        derived2 = hkdf_expand_label(
            self._hs_secret, "derived", hashlib.sha256(b"").digest(), 32
        )
        self._master = hkdf_extract(derived2, b"\0" * 32)

    def _derive_app(self) -> None:
        th = _transcript_hash(self._transcript)
        c_ap = hkdf_expand_label(self._master, "c ap traffic", th, 32)
        s_ap = hkdf_expand_label(self._master, "s ap traffic", th, 32)
        self.secrets[APP] = (c_ap, s_ap)

    # ---------------------------------------------------------------- ingestion

    _BUF_MAX = 1 << 16  # real handshake flights are a few KB; a claimed
    # 16 MB message is an unauthenticated memory-exhaustion attempt

    def feed(self, level: int, data: bytes) -> None:
        """Ingest CRYPTO-frame bytes received at an encryption level."""
        if len(self._bufs[level]) + len(data) > self._BUF_MAX:
            raise TlsError(_A_DECODE_ERROR, "handshake message flood")
        self._bufs[level] += data
        while True:
            buf = self._bufs[level]
            if len(buf) < 4:
                return
            mlen = int.from_bytes(buf[1:4], "big")
            if len(buf) < 4 + mlen:
                return
            raw, self._bufs[level] = buf[: 4 + mlen], buf[4 + mlen :]
            self._handle(level, raw[0], _Rd(raw[4:]), raw)

    def _handle(self, level: int, mtype: int, rd: _Rd, raw: bytes) -> None:
        if self.is_server:
            dispatch = {
                _CLIENT_HELLO: (INITIAL, self._on_client_hello),
                _CERTIFICATE: (HANDSHAKE, self._on_peer_cert),
                _CERT_VERIFY: (HANDSHAKE, self._on_peer_cert_verify),
                _FINISHED: (HANDSHAKE, self._on_peer_finished),
            }
        else:
            dispatch = {
                _SERVER_HELLO: (INITIAL, self._on_server_hello),
                _ENCRYPTED_EXTS: (HANDSHAKE, self._on_encrypted_exts),
                _CERT_REQUEST: (HANDSHAKE, self._on_cert_request),
                _CERTIFICATE: (HANDSHAKE, self._on_peer_cert),
                _CERT_VERIFY: (HANDSHAKE, self._on_peer_cert_verify),
                _FINISHED: (HANDSHAKE, self._on_peer_finished),
            }
        if mtype not in dispatch:
            raise TlsError(_A_DECODE_ERROR, f"unexpected message type {mtype}")
        want_level, fn = dispatch[mtype]
        if level != want_level:
            raise TlsError(_A_DECODE_ERROR, f"message {mtype} at wrong level")
        fn(rd, raw)

    # ------------------------------------------------------------ server moves

    def _on_client_hello(self, rd: _Rd, raw: bytes) -> None:
        if self._state != "start":
            raise TlsError(_A_DECODE_ERROR, "duplicate ClientHello")
        self._transcript += raw
        rd.u16()  # legacy_version
        rd.take(32)  # random
        rd.vec(1)  # session id
        suites = rd.vec(2)
        if _SUITE_AES128_GCM_SHA256.to_bytes(2, "big") not in [
            suites[i : i + 2] for i in range(0, len(suites), 2)
        ]:
            raise TlsError(_A_HANDSHAKE_FAILURE, "no common cipher suite")
        rd.vec(1)  # compression
        exts = _parse_exts(rd)
        if _EXT_VERSIONS not in exts or not _offers_tls13(exts[_EXT_VERSIONS]):
            raise TlsError(_A_PROTOCOL_VERSION, "TLS 1.3 not offered")
        if _EXT_QUIC_TP not in exts:
            raise TlsError(_A_MISSING_EXT, "no QUIC transport params")
        self.peer_transport_params = exts[_EXT_QUIC_TP]
        peer_share = self._find_x25519_share(exts)
        self._peer_alpn_ok(exts)

        # ServerHello
        sh_exts = b"".join(
            [
                _ext(_EXT_VERSIONS, (0x0304).to_bytes(2, "big")),
                _ext(
                    _EXT_KEYSHARE,
                    _GROUP_X25519.to_bytes(2, "big") + _v16(self._eshare),
                ),
            ]
        )
        sh = _msg(
            _SERVER_HELLO,
            (0x0303).to_bytes(2, "big")
            + self.rng(32)
            + _v8(b"")
            + _SUITE_AES128_GCM_SHA256.to_bytes(2, "big")
            + b"\x00"
            + _v16(sh_exts),
        )
        self._out(INITIAL, sh)
        self._derive_handshake(peer_share)

        # EncryptedExtensions .. Finished at the handshake level
        ee = _msg(
            _ENCRYPTED_EXTS,
            _v16(
                _ext(_EXT_ALPN, _v16(_v8(self.alpn)))
                + _ext(_EXT_QUIC_TP, self.transport_params)
            ),
        )
        self._out(HANDSHAKE, ee)
        if self.require_client_cert:
            cr = _msg(
                _CERT_REQUEST,
                _v8(b"")
                + _v16(_ext(_EXT_SIGALGS, _v16(_SIG_ED25519.to_bytes(2, "big")))),
            )
            self._out(HANDSHAKE, cr)
        self._send_cert_and_verify(_CV_SERVER_CTX)
        fin = _msg(
            _FINISHED,
            hmac_sha256(self._my_fin_key, _transcript_hash(self._transcript)),
        )
        self._out(HANDSHAKE, fin)
        self._derive_app()
        self._state = "wait_client_flight"

    def _find_x25519_share(self, exts: dict[int, bytes]) -> bytes:
        if _EXT_KEYSHARE not in exts:
            raise TlsError(_A_MISSING_EXT, "no key_share")
        inner = _Rd(exts[_EXT_KEYSHARE])
        shares = _Rd(inner.vec(2))
        while not shares.done():
            group = shares.u16()
            key = shares.vec(2)
            if group == _GROUP_X25519:
                if len(key) != 32:
                    raise TlsError(_A_ILLEGAL_PARAM, "bad x25519 share")
                return key
        raise TlsError(_A_HANDSHAKE_FAILURE, "no x25519 key share")

    def _peer_alpn_ok(self, exts: dict[int, bytes]) -> None:
        if _EXT_ALPN not in exts:
            return  # ALPN optional on offer; we always select ours
        inner = _Rd(exts[_EXT_ALPN])
        protos = _Rd(inner.vec(2))
        while not protos.done():
            if protos.vec(1) == self.alpn:
                return
        raise TlsError(120, "no common ALPN")  # no_application_protocol

    def _send_cert_and_verify(self, ctx: bytes) -> None:
        cert_msg = _msg(_CERTIFICATE, _v8(b"") + _v24(_v24(self.cert) + _v16(b"")))
        self._out(HANDSHAKE, cert_msg)
        sig = sign(
            self.identity_seed, ctx + _transcript_hash(self._transcript)
        )
        cv = _msg(_CERT_VERIFY, _SIG_ED25519.to_bytes(2, "big") + _v16(sig))
        self._out(HANDSHAKE, cv)

    # ------------------------------------------------------------ client moves

    def _on_server_hello(self, rd: _Rd, raw: bytes) -> None:
        if self._state != "wait_sh":
            raise TlsError(_A_DECODE_ERROR, "unexpected ServerHello")
        self._transcript += raw
        rd.u16()
        rd.take(32)
        rd.vec(1)
        suite = rd.u16()
        if suite != _SUITE_AES128_GCM_SHA256:
            raise TlsError(_A_HANDSHAKE_FAILURE, "server chose unknown suite")
        rd.u8()
        exts = _parse_exts(rd)
        if _EXT_VERSIONS not in exts or exts[_EXT_VERSIONS] != b"\x03\x04":
            raise TlsError(_A_PROTOCOL_VERSION, "server not TLS 1.3")
        if _EXT_KEYSHARE not in exts:
            raise TlsError(_A_MISSING_EXT, "no server key share")
        ks = _Rd(exts[_EXT_KEYSHARE])
        group = ks.u16()
        key = ks.vec(2)
        if group != _GROUP_X25519 or len(key) != 32:
            raise TlsError(_A_ILLEGAL_PARAM, "bad server share")
        self._derive_handshake(key)
        self._state = "wait_ee"

    def _on_encrypted_exts(self, rd: _Rd, raw: bytes) -> None:
        if self._state != "wait_ee":
            raise TlsError(_A_DECODE_ERROR, "unexpected EncryptedExtensions")
        self._transcript += raw
        exts = _parse_exts(rd)
        if _EXT_QUIC_TP not in exts:
            raise TlsError(_A_MISSING_EXT, "no QUIC transport params")
        self.peer_transport_params = exts[_EXT_QUIC_TP]
        self._state = "wait_cert"

    def _on_cert_request(self, rd: _Rd, raw: bytes) -> None:
        if self._state != "wait_cert":
            raise TlsError(_A_DECODE_ERROR, "unexpected CertificateRequest")
        self._transcript += raw
        self._client_cert_requested = True

    def _on_peer_cert(self, rd: _Rd, raw: bytes) -> None:
        ok_states = ("wait_cert",) if not self.is_server else ("wait_client_flight",)
        if self._state not in ok_states:
            raise TlsError(_A_DECODE_ERROR, "unexpected Certificate")
        self._transcript += raw
        rd.vec(1)  # context
        lst = _Rd(rd.vec(3))
        der = lst.vec(3)
        try:
            self.peer_pubkey = cert_pubkey(der)
        except ValueError as e:
            raise TlsError(_A_BAD_CERT, str(e)) from None
        self._state = "wait_cv"

    def _on_peer_cert_verify(self, rd: _Rd, raw: bytes) -> None:
        if self._state != "wait_cv":
            raise TlsError(_A_DECODE_ERROR, "unexpected CertificateVerify")
        alg = rd.u16()
        sig = rd.vec(2)
        if alg != _SIG_ED25519:
            raise TlsError(_A_HANDSHAKE_FAILURE, "peer used non-ed25519 sig")
        ctx = _CV_SERVER_CTX if not self.is_server else _CV_CLIENT_CTX
        content = ctx + _transcript_hash(self._transcript)
        if not verify_one_host(sig, content, self.peer_pubkey):
            raise TlsError(_A_DECRYPT_ERROR, "CertificateVerify failed")
        self._transcript += raw
        self._state = "wait_fin"

    def _on_peer_finished(self, rd: _Rd, raw: bytes) -> None:
        if self._state != "wait_fin" and not (
            self.is_server and self._state == "wait_client_flight"
            and not self.require_client_cert
        ):
            raise TlsError(_A_DECODE_ERROR, "unexpected Finished")
        want = hmac_sha256(self._peer_fin_key, _transcript_hash(self._transcript))
        got = rd.take(32)
        if want != got:
            raise TlsError(_A_DECRYPT_ERROR, "Finished verify failed")
        self._transcript += raw
        if self.is_server:
            self.complete = True
        else:
            # client sends its flight: [Certificate, CertificateVerify,] Finished
            self._derive_app()
            if self._client_cert_requested:
                self._send_cert_and_verify(_CV_CLIENT_CTX)
            fin = _msg(
                _FINISHED,
                hmac_sha256(self._my_fin_key, _transcript_hash(self._transcript)),
            )
            self._out(HANDSHAKE, fin)
            self.complete = True

    # ------------------------------------------------------------------- misc

    def take_outbox(self) -> list:
        out, self.outbox = self.outbox, []
        return out
