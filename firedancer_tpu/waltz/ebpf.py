"""eBPF tier: the kernel-bypass upgrade path for the packet engine
(round 4, VERDICT missing #4).

The reference pairs a minimal eBPF ELF static linker
(src/waltz/ebpf/fd_ebpf.c — patch map fds into lddw instructions via
R_BPF_64_64 relocations) with an XDP redirect program
(src/waltz/xdp/fd_xdp_redirect_prog.c — steer UDP packets whose
(dst ip, dst port) is registered into AF_XDP sockets) and a userspace
installer (src/waltz/xdp/fd_xdp_redirect_user.c).

TPU-native re-design, not a translation:

  * the XDP program is EMITTED here by a tiny assembler instead of being
    compiled C — the whole program is ~40 instructions, and generating it
    removes the clang-for-bpf toolchain dependency entirely;
  * the program is unit-tested IN-REPO by executing it on the flamenco
    sBPF interpreter (the same base ISA) with shimmed kernel helpers —
    the reference can only test theirs against a live kernel;
  * the static linker handles the same relocation class so externally
    compiled .o programs (clang -target bpf) also load;
  * the kernel path (bpf(2) + XDP attach) is a thin gated layer: inside
    unprivileged containers it reports cleanly and the AF_PACKET engine
    (waltz/pkteng) remains the fallback tier.

Wire/ABI facts used (stable kernel ABI):
  bpf_insn: u8 op, u8 dst:4|src:4, s16 off, s32 imm (little-endian)
  helpers:  1 = bpf_map_lookup_elem, 51 = bpf_redirect_map
  actions:  XDP_ABORTED=0 DROP=1 PASS=2 TX=3 REDIRECT=4
"""

from __future__ import annotations

import ctypes
import os
import struct
from dataclasses import dataclass

# XDP actions
XDP_ABORTED, XDP_DROP, XDP_PASS, XDP_TX, XDP_REDIRECT = range(5)

# kernel helper ids
HELPER_MAP_LOOKUP = 1
HELPER_REDIRECT_MAP = 51

# struct xdp_md offsets (uapi/linux/bpf.h)
XDP_MD_DATA = 0
XDP_MD_DATA_END = 4
XDP_MD_RX_QUEUE = 16


def ins(op: int, dst: int = 0, src: int = 0, off: int = 0,
        imm: int = 0) -> bytes:
    return struct.pack("<BBhi", op, (src << 4) | dst, off, imm)


def lddw(dst: int, imm64: int, src: int = 0) -> bytes:
    """16-byte load-double-word; src=1 marks BPF_PSEUDO_MAP_FD (the
    kernel replaces the fd with the map pointer at load time)."""
    lo = imm64 & 0xFFFFFFFF
    hi = (imm64 >> 32) & 0xFFFFFFFF
    return (struct.pack("<BBhi", 0x18, (src << 4) | dst, 0, lo)
            + struct.pack("<BBhi", 0, 0, 0, hi))


class Asm:
    """Two-pass mini assembler: emit() instructions, label() targets,
    branches by label."""

    def __init__(self):
        self.chunks: list = []   # bytes | (fixup, label, op, dst, src)
        self.labels: dict[str, int] = {}
        self._pc = 0

    def emit(self, b: bytes):
        self.chunks.append(b)
        self._pc += len(b) // 8

    def label(self, name: str):
        self.labels[name] = self._pc

    def jmp(self, op: int, label: str, dst: int = 0, src: int = 0,
            imm: int = 0):
        self.chunks.append(("fix", label, op, dst, src, imm, self._pc))
        self._pc += 1

    def assemble(self) -> bytes:
        out = bytearray()
        for c in self.chunks:
            if isinstance(c, bytes):
                out += c
            else:
                _, label, op, dst, src, imm, pc = c
                off = self.labels[label] - pc - 1
                out += ins(op, dst, src, off, imm)
        return bytes(out)


def build_xdp_redirect_prog(udp_dsts_fd: int = 1,
                            xsks_fd: int = 2) -> bytes:
    """The redirect program (behavior parity with fd_xdp_redirect_prog.c):

      1. bounds: eth(14) + min-ip(20) + udp(8) must fit
      2. one-branch ethertype/ipproto test: data[12]<<16 | data[13]<<8 |
         data[23] == 0x080011 (IPv4 + UDP)
      3. IHL-aware UDP header locate + re-bounds-check
      4. flow_key = (ip_dst << 16) | udp_dst (both network byte order)
         looked up in the udp_dsts map; miss -> XDP_PASS
      5. hit -> bpf_redirect_map(xsks, rx_queue_index, 0)

    The map "fds" are patched into the two lddw pseudo-map loads; when
    emitting for the kernel they are real fds, for the in-repo VM they
    are shim tokens."""
    a = Asm()
    R0, R1, R2, R3, R4, R5, R6, R7, R8, R10 = 0, 1, 2, 3, 4, 5, 6, 7, 8, 10

    a.emit(ins(0xBF, R6, R1))                  # r6 = ctx
    a.emit(ins(0x61, R2, R6, XDP_MD_DATA))     # r2 = data (u32)
    a.emit(ins(0x61, R3, R6, XDP_MD_DATA_END))  # r3 = data_end
    a.emit(ins(0xBF, R4, R2))
    a.emit(ins(0x07, R4, 0, 0, 14 + 20 + 8))   # r4 = data + 42
    a.jmp(0x2D, "pass", R4, R3)                # if r4 > r3 goto pass

    # test_ethip = data[12]<<16 | data[13]<<8 | data[23]
    a.emit(ins(0x71, R4, R2, 12))              # u8 data[12]
    a.emit(ins(0x67, R4, 0, 0, 16))            # <<16
    a.emit(ins(0x71, R5, R2, 13))
    a.emit(ins(0x67, R5, 0, 0, 8))
    a.emit(ins(0x4F, R4, R5))                  # r4 |= r5
    a.emit(ins(0x71, R5, R2, 23))
    a.emit(ins(0x4F, R4, R5))
    a.jmp(0x55, "pass", R4, 0, 0x080011)       # if r4 != IPv4|UDP

    # iplen = (iphdr[0] & 0xF) * 4 ; udp = data + 14 + iplen
    a.emit(ins(0x71, R5, R2, 14))
    a.emit(ins(0x57, R5, 0, 0, 0x0F))          # &= 0xF
    a.emit(ins(0x67, R5, 0, 0, 2))             # <<= 2
    a.emit(ins(0xBF, R4, R2))
    a.emit(ins(0x07, R4, 0, 0, 14))
    a.emit(ins(0x0F, R4, R5))                  # r4 = udp hdr
    a.emit(ins(0xBF, R0, R4))
    a.emit(ins(0x07, R0, 0, 0, 8))
    a.jmp(0x2D, "pass", R0, R3)                # udp + 8 > data_end?

    # flow_key = (u32 ip_dst << 16) | u16 udp_dst  (network byte order:
    # loads are LE on LE hosts, matching the reference's key recipe)
    a.emit(ins(0x61, R7, R2, 14 + 16))         # ip dst addr
    a.emit(ins(0x69, R8, R4, 2))               # udp dst port
    a.emit(ins(0x67, R7, 0, 0, 16))
    a.emit(ins(0x4F, R7, R8))
    a.emit(ins(0x7B, R10, R7, -8))             # *(u64*)(fp-8) = key

    a.emit(lddw(R1, udp_dsts_fd, src=1))       # r1 = &udp_dsts map
    a.emit(ins(0xBF, R2, R10))
    a.emit(ins(0x07, R2, 0, 0, -8))            # r2 = &key
    a.emit(ins(0x85, 0, 0, 0, HELPER_MAP_LOOKUP))
    a.jmp(0x15, "pass", R0, 0, 0)              # miss -> pass

    a.emit(lddw(R1, xsks_fd, src=1))           # r1 = &xsks map
    a.emit(ins(0x61, R2, R6, XDP_MD_RX_QUEUE))  # r2 = rx_queue_index
    a.emit(ins(0xB7, R3, 0, 0, 0))             # r3 = flags 0
    a.emit(ins(0x85, 0, 0, 0, HELPER_REDIRECT_MAP))
    a.emit(ins(0x95))                          # exit (r0 = redirect rc)

    a.label("pass")
    a.emit(ins(0xB7, R0, 0, 0, XDP_PASS))
    a.emit(ins(0x95))
    return a.assemble()


# ------------------------------------------------------- ELF static linker


@dataclass
class LinkedProg:
    text: bytes                 # relocated program bytes
    reloc_offs: list[int]       # byte offsets of patched lddw insns


def static_link(elf: bytes, section: str,
                symbols: dict[str, int]) -> LinkedProg:
    """Minimal eBPF ELF static link (rule parity with fd_ebpf_static_link,
    src/waltz/ebpf/fd_ebpf.c): extract `section`'s program text from a
    relocatable ELF64 and patch R_BPF_64_64 references to `symbols`
    (map name -> fd) into the lddw imm pair, setting src_reg=1
    (BPF_PSEUDO_MAP_FD) as the kernel loader requires."""
    if len(elf) < 64 or elf[:4] != b"\x7fELF":
        raise ValueError("not an ELF")
    if elf[4] != 2 or elf[5] != 1:
        raise ValueError("need ELF64 little-endian")
    (e_type,) = struct.unpack_from("<H", elf, 16)
    if e_type != 1:                     # ET_REL
        raise ValueError("need a relocatable object (ET_REL)")
    e_shoff, = struct.unpack_from("<Q", elf, 40)
    e_shentsize, e_shnum, e_shstrndx = struct.unpack_from("<HHH", elf, 58)

    def sh(i):
        base = e_shoff + i * e_shentsize
        name, typ = struct.unpack_from("<II", elf, base)
        off, size = struct.unpack_from("<QQ", elf, base + 24)
        link, info = struct.unpack_from("<II", elf, base + 40)
        entsize, = struct.unpack_from("<Q", elf, base + 56)
        return name, typ, off, size, link, info, entsize

    shstr_off = sh(e_shstrndx)[2]

    def name_of(noff):
        end = elf.index(b"\0", shstr_off + noff)
        return elf[shstr_off + noff:end].decode()

    prog_idx = None
    for i in range(e_shnum):
        n, typ, off, size, *_ = sh(i)
        if name_of(n) == section and typ == 1:      # SHT_PROGBITS
            prog_idx = i
            text = bytearray(elf[off:off + size])
    if prog_idx is None:
        raise ValueError(f"no section {section!r}")
    if len(text) % 8:
        raise ValueError("program section not 8-aligned")

    patched: list[int] = []
    for i in range(e_shnum):
        n, typ, off, size, link, info, entsize = sh(i)
        if typ != 9 or info != prog_idx:            # SHT_REL for our section
            continue
        symtab = sh(link)
        strtab = sh(sh(link)[4])
        for r in range(size // entsize):
            r_off, r_info = struct.unpack_from("<QQ", elf, off + r * entsize)
            r_type = r_info & 0xFFFFFFFF
            r_sym = r_info >> 32
            if r_type != 1:                         # R_BPF_64_64
                raise ValueError(f"unsupported reloc type {r_type}")
            sname_off, = struct.unpack_from(
                "<I", elf, symtab[2] + r_sym * 24)
            send = elf.index(b"\0", strtab[2] + sname_off)
            sname = elf[strtab[2] + sname_off:send].decode()
            if sname not in symbols:
                raise ValueError(f"undefined symbol {sname!r}")
            if r_off % 8 or r_off + 16 > len(text):
                raise ValueError("bad reloc offset")
            if text[r_off] != 0x18:
                raise ValueError("reloc target is not lddw")
            val = symbols[sname]
            struct.pack_into("<i", text, r_off + 4, val & 0xFFFFFFFF)
            struct.pack_into("<i", text, r_off + 12, (val >> 32) & 0xFFFFFFFF)
            text[r_off + 1] = (1 << 4) | (text[r_off + 1] & 0x0F)
            patched.append(r_off)
    return LinkedProg(bytes(text), patched)


# --------------------------------------------------------- in-repo test VM


class XdpSim:
    """Execute an XDP program on the flamenco sBPF interpreter with
    kernel-helper shims — the in-repo equivalent of loading it into the
    kernel (the ISA is shared; only the helper ABI is shimmed)."""

    def __init__(self, prog: bytes, udp_dsts: dict[int, int],
                 xsks: dict[int, int],
                 udp_dsts_fd: int = 1, xsks_fd: int = 2):
        self.prog = prog
        self.maps = {udp_dsts_fd: dict(udp_dsts), xsks_fd: dict(xsks)}
        self.redirects: list[tuple[int, int]] = []

    # xdp_md.data/data_end are u32 in the kernel ABI (the verifier
    # rewrites those loads into real pointers); the sim has no ctx
    # rewriting, so ctx+packet live in a low region whose addresses FIT
    # a u32 — the program's u32 loads then yield directly usable vaddrs
    CTX_VADDR = 0x1000

    def run(self, packet: bytes, rx_queue: int = 0) -> int:
        from ..flamenco.vm import Region, Vm

        ctx_sz = 24
        mem = bytearray(ctx_sz + len(packet))
        data = self.CTX_VADDR + ctx_sz
        struct.pack_into("<II", mem, 0, data, data + len(packet))
        struct.pack_into("<I", mem, XDP_MD_RX_QUEUE, rx_queue)
        mem[ctx_sz:] = packet
        vm = Vm(self.prog)
        vm.regions.append(Region(self.CTX_VADDR, mem, True))
        # scratch slot for map_lookup return pointers (any valid vaddr)
        from ..flamenco.vm import MM_HEAP

        def _lookup(vm_, map_tok, key_ptr, *a):
            m = self.maps.get(map_tok)
            if m is None:
                return 0
            key = vm_.mem_read(key_ptr, 8)
            if key not in m:
                return 0
            vm_.mem_write(MM_HEAP, m[key], 4)
            return MM_HEAP

        def _redirect(vm_, map_tok, key, flags, *a):
            m = self.maps.get(map_tok)
            if m is None or (key & 0xFFFFFFFF) not in m:
                return flags & 0xFFFFFFFF     # kernel: flags as fallback
            self.redirects.append((map_tok, key & 0xFFFFFFFF))
            return XDP_REDIRECT

        from ..flamenco.vm import Syscall
        vm.syscalls[HELPER_MAP_LOOKUP] = Syscall(
            "bpf_map_lookup_elem", _lookup, cost=1)
        vm.syscalls[HELPER_REDIRECT_MAP] = Syscall(
            "bpf_redirect_map", _redirect, cost=1)
        return vm.run(self.CTX_VADDR)


# ------------------------------------------------------------- kernel path


def _bpf_syscall_available() -> bool:
    return os.path.exists("/proc/sys/kernel/unprivileged_bpf_disabled")


class KernelXdp:
    """The privileged install path (role of fd_xdp_redirect_user.c):
    create the two maps, load the program, attach to an interface.  In an
    unprivileged container every step raises EbpfUnavailable — callers
    fall back to the AF_PACKET tier (waltz/pkteng)."""

    BPF_MAP_CREATE = 0
    BPF_MAP_UPDATE_ELEM = 2
    BPF_PROG_LOAD = 5
    BPF_LINK_CREATE = 28
    BPF_MAP_TYPE_HASH = 1
    BPF_MAP_TYPE_XSKMAP = 17
    BPF_PROG_TYPE_XDP = 6

    def __init__(self):
        self._nr = {"x86_64": 321, "aarch64": 280}.get(os.uname().machine)
        if self._nr is None:
            raise EbpfUnavailable(f"no bpf(2) nr for {os.uname().machine}")
        self._libc = ctypes.CDLL(None, use_errno=True)

    def _bpf(self, cmd: int, attr: bytes) -> int:
        buf = ctypes.create_string_buffer(attr, len(attr))
        rc = self._libc.syscall(self._nr, cmd, buf, len(attr))
        if rc < 0:
            err = ctypes.get_errno()
            raise EbpfUnavailable(f"bpf(cmd={cmd}) failed: {os.strerror(err)}")
        return rc

    def map_create(self, map_type: int, key_sz: int, val_sz: int,
                   max_entries: int) -> int:
        attr = struct.pack("<IIII", map_type, key_sz, val_sz, max_entries)
        return self._bpf(self.BPF_MAP_CREATE, attr.ljust(72, b"\0"))

    def prog_load(self, prog: bytes, license_: bytes = b"Apache-2.0") -> int:
        insns = ctypes.create_string_buffer(prog, len(prog))
        lic = ctypes.create_string_buffer(license_ + b"\0")
        attr = struct.pack(
            "<II QQ I",
            self.BPF_PROG_TYPE_XDP, len(prog) // 8,
            ctypes.addressof(insns), ctypes.addressof(lic), 0)
        self._insns_ref = insns    # keep alive across the syscall
        self._lic_ref = lic
        return self._bpf(self.BPF_PROG_LOAD, attr.ljust(148, b"\0"))

    def map_update(self, map_fd: int, key: bytes, value: bytes):
        """BPF_MAP_UPDATE_ELEM (flow registration into udp_dsts, XSK fd
        into the XSKMAP — fd_xdp_redirect_user.c's listen/xsk steps)."""
        k = ctypes.create_string_buffer(key, len(key))
        v = ctypes.create_string_buffer(value, len(value))
        attr = struct.pack(
            "<I4xQQQ", map_fd, ctypes.addressof(k), ctypes.addressof(v), 0)
        self._k_ref, self._v_ref = k, v
        self._bpf(self.BPF_MAP_UPDATE_ELEM, attr.ljust(72, b"\0"))

    BPF_XDP_ATTACH_TYPE = 37

    def attach_xdp(self, ifindex: int, prog_fd: int) -> int:
        """BPF_LINK_CREATE with the XDP attach type: install the redirect
        program on an interface; the returned link fd pins the attachment
        (close it to detach — fd_xdp_hook install/uninstall role)."""
        attr = struct.pack("<IIII", prog_fd, ifindex,
                           self.BPF_XDP_ATTACH_TYPE, 0)
        return self._bpf(self.BPF_LINK_CREATE, attr.ljust(64, b"\0"))

    def install_redirect(self, ifname: str, flows: list[tuple[str, int]],
                         xsk_fds: dict[int, int]):
        """One-call bring-up (the `fdctl configure xdp` role): create the
        udp_dsts + XSKMAP maps, register `flows` [(ip, port)] and the
        per-queue XSK fds, assemble+load the redirect program against the
        REAL map fds, attach to `ifname`.  Returns (link_fd, prog_fd)."""
        import socket as _socket

        udp_dsts = self.map_create(self.BPF_MAP_TYPE_HASH, 8, 4, 64)
        xsks = self.map_create(self.BPF_MAP_TYPE_XSKMAP, 4, 4, 64)
        for ip, port in flows:
            ip_be = int.from_bytes(_socket.inet_aton(ip), "little")
            port_be = int.from_bytes(port.to_bytes(2, "big"), "little")
            key = ((ip_be << 16) | port_be).to_bytes(8, "little")
            self.map_update(udp_dsts, key, (1).to_bytes(4, "little"))
        for q, fd in xsk_fds.items():
            self.map_update(xsks, q.to_bytes(4, "little"),
                            fd.to_bytes(4, "little"))
        prog = build_xdp_redirect_prog(udp_dsts_fd=udp_dsts, xsks_fd=xsks)
        prog_fd = self.prog_load(prog)
        link = self.attach_xdp(_socket.if_nametoindex(ifname), prog_fd)
        return (link, prog_fd, udp_dsts, xsks)


class EbpfUnavailable(RuntimeError):
    pass
