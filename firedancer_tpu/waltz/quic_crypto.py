"""Burst QUIC packet-protection backend: native C or NumPy-vectorized.

Reference role: src/waltz/quic/crypto/fd_quic_crypto_suites.c — the
reference decrypts/encrypts QUIC packets in AES-NI C.  Our rx loop moves
packets in recvmmsg bursts (waltz/pkteng.py), so the crypto API here is
burst-shaped too: one call takes a whole burst of packet views plus
per-packet key-slot handles from a grow-only key registry, removes HP
masks, decodes packet numbers, and AEAD-decrypts in place; a mirror call
protects a tx burst.  Two backends, bit-identical by contract (tests
enforce it over a fuzz sweep):

  * native   — ctypes into native/aescrypt.cpp (one C call per burst)
  * fallback — NumPy-vectorized AES T-tables + GHASH position tables:
    AES states for every CTR/HP block in the burst advance as (M,) uint32
    word arrays (16 table gathers per round, amortized across the burst),
    and GHASH advances all packets' accumulators one block-column at a
    time through per-key (16, 256) position tables derived from the
    byte-table of ballet/aes.py (T_{j+1}[b] = T_j[b] * x^8).

Selection follows the Pack(native=) idiom: None = auto (env
FDTPU_QUIC_CRYPTO_NATIVE overrides, then try-build), False = force the
Python fallback, True = require the C path.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from firedancer_tpu.ballet.aes import (
    _GHASH_RED, _T0, _T1, _T2, _T3, _Ghash, _SBOX,
    aes_encrypt_block, aes_key_expand,
)

_NATIVE_ENV = "FDTPU_QUIC_CRYPTO_NATIVE"

_native_cache = [False, None]  # [probed, lib-or-None]


def _native_lib():
    if not _native_cache[0]:
        _native_cache[0] = True
        try:
            from firedancer_tpu import native as native_mod
            _native_cache[1] = native_mod.lib()
        except Exception:
            _native_cache[1] = None
    return _native_cache[1]


def _resolve_native(native):
    """native arg: None = auto (env overrides, then try-build), False =
    force the Python fallback, True = require the C path."""
    if native is False:
        return None
    env = os.environ.get(_NATIVE_ENV)
    if native is None and env is not None and env == "0":
        return None
    L = _native_lib()
    if native is True and L is None:
        raise RuntimeError("native QUIC crypto unavailable "
                           "(aescrypt.cpp failed to build)")
    return L


# ------------------------------------------------------- vectorized tables

_NT0 = np.array(_T0, dtype=np.uint32)
_NT1 = np.array(_T1, dtype=np.uint32)
_NT2 = np.array(_T2, dtype=np.uint32)
_NT3 = np.array(_T3, dtype=np.uint32)
_NSBOX = np.array(_SBOX, dtype=np.uint32)
_M64 = (1 << 64) - 1
_RED_HI = np.array([r >> 64 for r in _GHASH_RED], dtype=np.uint64)
_RED_LO = np.array([r & _M64 for r in _GHASH_RED], dtype=np.uint64)


def _vec_aes(rk: np.ndarray, idx: np.ndarray, blocks: np.ndarray) -> np.ndarray:
    """AES-128 encrypt (M,16) uint8 blocks; rk is a (S,44) uint32 round-key
    matrix, idx (M,) selects each block's row.  Returns (M,16) uint8."""
    w = blocks.astype(np.uint32).reshape(-1, 4, 4)
    s = (w[:, :, 0] << 24) | (w[:, :, 1] << 16) | (w[:, :, 2] << 8) | w[:, :, 3]
    s ^= rk[idx, :4]
    s0, s1, s2, s3 = s[:, 0], s[:, 1], s[:, 2], s[:, 3]
    for r in range(1, 10):
        k = rk[idx, 4 * r : 4 * r + 4]
        t0 = (_NT0[(s0 >> 24) & 0xFF] ^ _NT1[(s1 >> 16) & 0xFF]
              ^ _NT2[(s2 >> 8) & 0xFF] ^ _NT3[s3 & 0xFF] ^ k[:, 0])
        t1 = (_NT0[(s1 >> 24) & 0xFF] ^ _NT1[(s2 >> 16) & 0xFF]
              ^ _NT2[(s3 >> 8) & 0xFF] ^ _NT3[s0 & 0xFF] ^ k[:, 1])
        t2 = (_NT0[(s2 >> 24) & 0xFF] ^ _NT1[(s3 >> 16) & 0xFF]
              ^ _NT2[(s0 >> 8) & 0xFF] ^ _NT3[s1 & 0xFF] ^ k[:, 2])
        t3 = (_NT0[(s3 >> 24) & 0xFF] ^ _NT1[(s0 >> 16) & 0xFF]
              ^ _NT2[(s1 >> 8) & 0xFF] ^ _NT3[s2 & 0xFF] ^ k[:, 3])
        s0, s1, s2, s3 = t0, t1, t2, t3
    src = (s0, s1, s2, s3)
    out = np.empty((blocks.shape[0], 16), dtype=np.uint32)
    kf = rk[idx, 40:44]
    for c in range(4):
        out[:, 4 * c + 0] = _NSBOX[(src[c] >> 24) & 0xFF] ^ ((kf[:, c] >> 24) & 0xFF)
        out[:, 4 * c + 1] = _NSBOX[(src[(c + 1) & 3] >> 16) & 0xFF] ^ ((kf[:, c] >> 16) & 0xFF)
        out[:, 4 * c + 2] = _NSBOX[(src[(c + 2) & 3] >> 8) & 0xFF] ^ ((kf[:, c] >> 8) & 0xFF)
        out[:, 4 * c + 3] = _NSBOX[src[(c + 3) & 3] & 0xFF] ^ (kf[:, c] & 0xFF)
    return out.astype(np.uint8)


def _pos_tables(h: int) -> np.ndarray:
    """GHASH position tables for key H: T[j, b] is the (hi, lo) uint64
    pair of (b at big-endian byte position j) * H, so one 16-byte block
    multiplies as XOR_j T[j, z_bytes[j]].  Derived from the top-byte table
    of ballet/aes.py by repeated *x^8 (shift + reduction-table fold)."""
    base = _Ghash(h).table
    t = np.empty((16, 256, 2), dtype=np.uint64)
    hi = np.array([v >> 64 for v in base], dtype=np.uint64)
    lo = np.array([v & _M64 for v in base], dtype=np.uint64)
    for j in range(16):
        t[j, :, 0] = hi
        t[j, :, 1] = lo
        if j < 15:
            low = (lo & np.uint64(0xFF)).astype(np.intp)
            nlo = (lo >> np.uint64(8)) | (hi << np.uint64(56))
            nhi = hi >> np.uint64(8)
            hi = nhi ^ _RED_HI[low]
            lo = nlo ^ _RED_LO[low]
    return t


def _vec_ghash(tabs: np.ndarray, tidx: np.ndarray, blocks: np.ndarray,
               nblocks: np.ndarray) -> np.ndarray:
    """GHASH all packets at once, one block-column per step.  tabs is the
    (S, 16, 256, 2) stack of position tables, tidx (N,) each packet's row,
    blocks (N, maxB, 16) uint8 zero-padded, nblocks (N,) valid counts.
    Returns the (N, 16) uint8 digests."""
    n = blocks.shape[0]
    acc = np.zeros((n, 16), dtype=np.uint8)
    for k in range(blocks.shape[1]):
        active = k < nblocks
        if not active.any():
            break
        z = acc ^ blocks[:, k, :]
        r = tabs[tidx, 0, z[:, 0].astype(np.intp)]
        for j in range(1, 16):
            r = r ^ tabs[tidx, j, z[:, j].astype(np.intp)]
        rb = r.astype(">u8").view(np.uint8).reshape(n, 16)
        acc = np.where(active[:, None], rb, acc)
    return acc


# ------------------------------------------------------------ key registry


class _KeyMat:
    __slots__ = ("key", "iv", "hp", "c_slot")

    def __init__(self, key: bytes, iv: bytes, hp: bytes, c_slot: int):
        self.key = key
        self.iv = iv
        self.hp = hp
        self.c_slot = c_slot


class CryptoBackend:
    """One burst-crypto backend (native or fallback) plus its key registry.

    Slots are grow-only handles into the registry; `key_free` recycles
    them.  Use `get_backend(native=)` for the shared per-mode instance —
    waltz/quic._Keys registers lazily and frees from __del__.
    """

    _POS_TAB_CAP = 512  # materialized GHASH position tables (64 KB each)

    def __init__(self, native=None):
        self._L = _resolve_native(native)
        self.native = self._L is not None
        self._keys: list[_KeyMat | None] = []
        self._free: list[int] = []
        # fallback key-material matrices, grown in lockstep with _keys
        self._rk = np.zeros((0, 44), dtype=np.uint32)
        self._hp_rk = np.zeros((0, 44), dtype=np.uint32)
        self._iv = np.zeros((0, 12), dtype=np.uint8)
        self._h: list[int] = []
        self._pos_tabs: dict[int, np.ndarray] = {}  # slot -> (16,256,2), LRU

    # ----------------------------------------------------------- registry

    def key_new(self, key: bytes, iv: bytes, hp: bytes) -> int:
        c_slot = -1
        if self.native:
            c_slot = self._L.fd_aescrypt_key_new(key, iv, hp)
        if self._free:
            slot = self._free.pop()
        else:
            slot = len(self._keys)
            self._keys.append(None)
            if slot >= self._rk.shape[0]:
                grow = max(64, self._rk.shape[0])
                self._rk = np.vstack(
                    [self._rk, np.zeros((grow, 44), np.uint32)])
                self._hp_rk = np.vstack(
                    [self._hp_rk, np.zeros((grow, 44), np.uint32)])
                self._iv = np.vstack(
                    [self._iv, np.zeros((grow, 12), np.uint8)])
                self._h.extend([0] * grow)
        self._keys[slot] = _KeyMat(key, iv, hp, c_slot)
        rk = aes_key_expand(key)
        self._rk[slot] = rk
        self._hp_rk[slot] = aes_key_expand(hp)
        self._iv[slot] = np.frombuffer(iv, dtype=np.uint8)
        self._h[slot] = int.from_bytes(
            aes_encrypt_block(rk, b"\0" * 16), "big")
        return slot

    def key_free(self, slot: int) -> None:
        if slot < 0 or slot >= len(self._keys) or self._keys[slot] is None:
            return
        if self.native and self._keys[slot].c_slot >= 0:
            self._L.fd_aescrypt_key_free(self._keys[slot].c_slot)
        self._keys[slot] = None
        self._pos_tabs.pop(slot, None)
        self._free.append(slot)

    def key_cnt(self) -> int:
        return len(self._keys) - len(self._free)

    def _pos_tab(self, slot: int) -> np.ndarray:
        t = self._pos_tabs.pop(slot, None)
        if t is None:
            t = _pos_tables(self._h[slot])
            if len(self._pos_tabs) >= self._POS_TAB_CAP:
                self._pos_tabs.pop(next(iter(self._pos_tabs)))
        self._pos_tabs[slot] = t  # re-insert = move to LRU tail
        return t

    # -------------------------------------------------------------- bursts

    def decrypt_burst(self, jobs) -> list:
        """jobs: (buf, start, pn_off, end, slot, expected) per packet; buf
        is a writable buffer (bytearray).  Removes HP, decodes pns, AEAD-
        decrypts in place.  Returns [(ok, pn, pt_off, pt_len), ...]; a
        failed packet (short sample / bad tag) leaves its buffer untouched.
        """
        if not jobs:
            return []
        if self.native:
            return self._decrypt_native(jobs)
        return self._decrypt_py(jobs)

    def encrypt_burst(self, jobs) -> None:
        """jobs: (buf, pn_off, pn, pt_len, slot); buf holds header | pn(4)
        | plaintext | 16 spare tag bytes.  Protects every packet in place.
        """
        if not jobs:
            return
        if self.native:
            self._encrypt_native(jobs)
        else:
            self._encrypt_py(jobs)

    # ------------------------------------------------------------ native

    @staticmethod
    def _addr(buf) -> int:
        return ctypes.addressof(ctypes.c_char.from_buffer(buf))

    def _decrypt_native(self, jobs) -> list:
        n = len(jobs)
        i64 = ctypes.c_int64
        bufs = (ctypes.c_uint64 * n)(*[self._addr(j[0]) for j in jobs])
        blen = (i64 * n)(*[len(j[0]) for j in jobs])
        start = (i64 * n)(*[j[1] for j in jobs])
        pn_off = (i64 * n)(*[j[2] for j in jobs])
        end = (i64 * n)(*[j[3] for j in jobs])
        slots = (i64 * n)(
            *[self._keys[j[4]].c_slot if self._keys[j[4]] else -1
              for j in jobs])
        expected = (i64 * n)(*[j[5] for j in jobs])
        pn_out = (i64 * n)()
        pt_off = (i64 * n)()
        pt_len = (i64 * n)()
        ok = (ctypes.c_uint8 * n)()
        self._L.fd_aescrypt_decrypt_burst(
            bufs, blen, start, pn_off, end, slots, expected, n,
            pn_out, pt_off, pt_len, ok)
        return [(bool(ok[i]), pn_out[i], pt_off[i], pt_len[i])
                for i in range(n)]

    def _encrypt_native(self, jobs) -> None:
        n = len(jobs)
        i64 = ctypes.c_int64
        bufs = (ctypes.c_uint64 * n)(*[self._addr(j[0]) for j in jobs])
        pn_off = (i64 * n)(*[j[1] for j in jobs])
        pn = (i64 * n)(*[j[2] for j in jobs])
        pt_len = (i64 * n)(*[j[3] for j in jobs])
        slots = (i64 * n)(
            *[self._keys[j[4]].c_slot if self._keys[j[4]] else -1
              for j in jobs])
        ok = (ctypes.c_uint8 * n)()
        self._L.fd_aescrypt_encrypt_burst(bufs, pn_off, pn, pt_len, slots,
                                          n, ok)

    # ---------------------------------------------------------- fallback

    def _nonces(self, slot_idx: np.ndarray, pns) -> np.ndarray:
        non = self._iv[slot_idx].copy()
        pnv = np.array(pns, dtype=np.uint64)
        for i in range(8):
            non[:, 11 - i] ^= ((pnv >> np.uint64(8 * i))
                               & np.uint64(0xFF)).astype(np.uint8)
        return non

    def _ctr_keystream(self, slot_idx, nonces, nblk) -> list:
        """Per-packet CTR keystreams (counter from 2): one flat _vec_aes
        over every block of every packet in the burst."""
        total = int(nblk.sum())
        if total == 0:
            return [b""] * len(nblk)
        blocks = np.zeros((total, 16), dtype=np.uint8)
        bidx = np.zeros(total, dtype=np.intp)
        off = 0
        for i, nb in enumerate(nblk):
            nb = int(nb)
            if not nb:
                continue
            blocks[off : off + nb, :12] = nonces[i]
            ctr = np.arange(2, 2 + nb, dtype=np.uint32)
            blocks[off : off + nb, 12] = (ctr >> 24).astype(np.uint8)
            blocks[off : off + nb, 13] = (ctr >> 16).astype(np.uint8)
            blocks[off : off + nb, 14] = (ctr >> 8).astype(np.uint8)
            blocks[off : off + nb, 15] = ctr.astype(np.uint8)
            bidx[off : off + nb] = slot_idx[i]
            off += nb
        ks = _vec_aes(self._rk, bidx, blocks)
        out = []
        off = 0
        for nb in nblk:
            nb = int(nb)
            out.append(ks[off : off + nb].reshape(-1))
            off += nb
        return out

    def _tags(self, slot_idx, nonces, aads, cts) -> np.ndarray:
        """(N,16) GCM tags: vectorized GHASH + EK(nonce||1) mask."""
        n = len(aads)
        ab = np.array([(len(a) + 15) >> 4 for a in aads], dtype=np.intp)
        cb = np.array([(len(c) + 15) >> 4 for c in cts], dtype=np.intp)
        nblocks = ab + cb + 1
        maxb = int(nblocks.max())
        blocks = np.zeros((n, maxb * 16), dtype=np.uint8)
        for i, (a, c) in enumerate(zip(aads, cts)):
            if len(a):
                blocks[i, : len(a)] = np.frombuffer(a, dtype=np.uint8)
            co = int(ab[i]) * 16
            if len(c):
                blocks[i, co : co + len(c)] = np.frombuffer(c, dtype=np.uint8)
            lo = (int(ab[i]) + int(cb[i])) * 16
            lens = ((len(a) * 8).to_bytes(8, "big")
                    + (len(c) * 8).to_bytes(8, "big"))
            blocks[i, lo : lo + 16] = np.frombuffer(lens, dtype=np.uint8)
        blocks = blocks.reshape(n, maxb, 16)
        uniq, tloc = np.unique(slot_idx, return_inverse=True)
        tabs = np.stack([self._pos_tab(int(s)) for s in uniq])
        digest = _vec_ghash(tabs, tloc.astype(np.intp), blocks, nblocks)
        y0 = np.zeros((n, 16), dtype=np.uint8)
        y0[:, :12] = nonces
        y0[:, 15] = 1
        ek = _vec_aes(self._rk, slot_idx, y0)
        return digest ^ ek

    def _decrypt_py(self, jobs) -> list:
        n = len(jobs)
        res: list = [None] * n
        # phase 1: HP samples for every packet with a full 16-byte sample
        live: list[int] = []
        samples = []
        for i, (buf, start, pn_off, end, slot, expected) in enumerate(jobs):
            if (pn_off + 20 > len(buf) or slot < 0 or slot >= len(self._keys)
                    or self._keys[slot] is None):
                res[i] = (False, -1, 0, 0)
                continue
            live.append(i)
            samples.append(np.frombuffer(buf, np.uint8, 16, pn_off + 4))
        if not live:
            return res
        slot_idx = np.array([jobs[i][4] for i in live], dtype=np.intp)
        masks = _vec_aes(self._hp_rk, slot_idx, np.stack(samples))
        # phase 2: unmask headers, decode pns, gather AAD/ct views
        aads, cts, pns, metas = [], [], [], []
        live2: list[int] = []
        s2 = []
        for li, i in enumerate(live):
            buf, start, pn_off, end, slot, expected = jobs[i]
            end = min(end, len(buf))
            mask = masks[li]
            first = buf[start] ^ (
                int(mask[0]) & (0x0F if buf[start] & 0x80 else 0x1F))
            pn_len = (first & 0x03) + 1
            pnb = bytes(buf[pn_off + j] ^ int(mask[1 + j])
                        for j in range(pn_len))
            ct_off = pn_off + pn_len
            if end - ct_off < 16:
                res[i] = (False, -1, 0, 0)
                continue
            pn = _decode_pn(int.from_bytes(pnb, "big"), pn_len, expected)
            live2.append(i)
            s2.append(slot_idx[li])
            aads.append(bytes([first]) + bytes(buf[start + 1 : pn_off]) + pnb)
            cts.append(bytes(buf[ct_off : end - 16]))
            pns.append(pn)
            metas.append((first, pnb, ct_off, end))
        if not live2:
            return res
        slot_idx = np.array(s2, dtype=np.intp)
        nonces = self._nonces(slot_idx, pns)
        # phase 3: tags for all packets at once; compare, then CTR-decrypt
        # only the survivors (a failed tag leaves the buffer untouched)
        want = self._tags(slot_idx, nonces, aads, cts)
        ok_rows: list[int] = []
        for r, i in enumerate(live2):
            buf = jobs[i][0]
            _, _, ct_off, end = metas[r]
            tag = np.frombuffer(buf, np.uint8, 16, end - 16)
            if int((want[r] ^ tag).max(initial=0)) != 0:
                res[i] = (False, -1, 0, 0)
            else:
                ok_rows.append(r)
        if not ok_rows:
            return res
        okr = np.array(ok_rows, dtype=np.intp)
        clens = np.array([len(cts[r]) for r in ok_rows], dtype=np.intp)
        nblk = (clens + 15) >> 4
        kss = self._ctr_keystream(slot_idx[okr], nonces[okr], nblk)
        for w, r in enumerate(ok_rows):
            i = live2[r]
            buf = jobs[i][0]
            first, pnb, ct_off, end = metas[r]
            clen = int(clens[w])
            buf[jobs[i][1]] = first
            buf[jobs[i][2] : jobs[i][2] + len(pnb)] = pnb
            if clen:
                view = np.frombuffer(buf, np.uint8, clen, ct_off)
                view ^= kss[w][:clen]
            res[i] = (True, pns[r], ct_off, clen)
        return res

    def _encrypt_py(self, jobs) -> None:
        n = len(jobs)
        slot_idx = np.array([j[4] for j in jobs], dtype=np.intp)
        nonces = self._nonces(slot_idx, [j[2] for j in jobs])
        plens = np.array([j[3] for j in jobs], dtype=np.intp)
        nblk = (plens + 15) >> 4
        kss = self._ctr_keystream(slot_idx, nonces, nblk)
        aads, cts = [], []
        for w, (buf, pn_off, pn, pt_len, slot) in enumerate(jobs):
            pt_off = pn_off + 4
            view = np.frombuffer(buf, np.uint8, pt_len, pt_off)
            view ^= kss[w][:pt_len]
            aads.append(bytes(buf[: pt_off]))
            cts.append(bytes(buf[pt_off : pt_off + pt_len]))
        tags = self._tags(slot_idx, nonces, aads, cts)
        for w, (buf, pn_off, pn, pt_len, slot) in enumerate(jobs):
            pt_off = pn_off + 4
            buf[pt_off + pt_len : pt_off + pt_len + 16] = tags[w].tobytes()
        samples = np.stack([np.frombuffer(j[0], np.uint8, 16, j[1] + 4)
                            for j in jobs])
        masks = _vec_aes(self._hp_rk, slot_idx, samples)
        for w, (buf, pn_off, pn, pt_len, slot) in enumerate(jobs):
            mask = masks[w]
            buf[0] ^= int(mask[0]) & (0x0F if buf[0] & 0x80 else 0x1F)
            for j in range(4):
                buf[pn_off + j] ^= int(mask[1 + j])


def _decode_pn(truncated: int, pn_len: int, expected: int) -> int:
    """RFC 9000 appendix A.3 packet-number reconstruction (== the copy in
    waltz/quic.py; duplicated to keep this module import-light)."""
    win = 1 << (pn_len * 8)
    half = win // 2
    candidate = (expected & ~(win - 1)) | truncated
    if candidate <= expected - half and candidate + win < (1 << 62):
        return candidate + win
    if candidate > expected + half and candidate >= win:
        return candidate - win
    return candidate


_shared: dict[bool, CryptoBackend] = {}


def get_backend(native=None) -> CryptoBackend:
    """Shared per-mode backend (key slots registered once per process)."""
    resolved = _resolve_native(native) is not None
    be = _shared.get(resolved)
    if be is None:
        be = _shared[resolved] = CryptoBackend(native)
    return be
