"""Abstract async packet-burst interface (ref: src/waltz/aio/fd_aio.c).

An aio is a callback taking a burst of packets; transmitters call
send_burst, receivers poll recv_burst.  Everything above the wire (net
tile, quic tile) talks bursts of (payload, addr) so the socket backend can
be swapped for a kernel-bypass one without touching tiles.
"""

from dataclasses import dataclass
from typing import Callable, Iterable


@dataclass(frozen=True)
class Pkt:
    payload: bytes
    addr: tuple  # (ip, port) peer


class Aio:
    """Burst sink (fd_aio_t: one send_func taking a packet batch)."""

    def __init__(self, send_func: Callable[[list[Pkt]], int]):
        self._send = send_func

    def send(self, pkts: Iterable[Pkt]) -> int:
        """Returns packets accepted (backpressure = partial count)."""
        return self._send(list(pkts))


class PcapTee:
    """Tee every burst into a pcap file (ref: src/waltz/aio/fd_aio_pcapng.c
    — the packet-capture tracing hook on any aio link)."""

    _GLOBAL_HDR = (
        b"\xd4\xc3\xb2\xa1"  # magic (little endian)
        b"\x02\x00\x04\x00"  # version 2.4
        b"\x00\x00\x00\x00\x00\x00\x00\x00"
        b"\xff\xff\x00\x00"  # snaplen
        b"\x94\x00\x00\x00"  # linktype 148 = LINKTYPE_USER1 (raw UDP payloads)
    )

    def __init__(self, path: str, inner: Aio):
        self._f = open(path, "wb")
        self._f.write(self._GLOBAL_HDR)
        self._inner = inner

    def send(self, pkts) -> int:
        import struct
        import time
        now = time.time()
        sec, usec = int(now), int((now % 1) * 1e6)
        for p in pkts:
            self._f.write(struct.pack("<IIII", sec, usec,
                                      len(p.payload), len(p.payload)))
            self._f.write(p.payload)
        self._f.flush()
        return self._inner.send(pkts)

    def close(self):
        self._f.close()
