"""The flagship "model": a fixed-shape batched ed25519 verifier.

Equivalent role to the verify tile's crypto core
(ref: src/app/fdctl/run/tiles/fd_verify.c + fd_ed25519_verify_batch_single_msg),
with the wiredancer-style batch insertion point (SURVEY.md §3.2): the host
pipeline coalesces txn signatures into fixed (BATCH, MSG_MAXLEN) buffers, the
device returns pass/fail bits.
"""

import time
from collections import deque
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from firedancer_tpu.ops import ed25519 as ed


@dataclass(frozen=True)
class VerifierConfig:
    batch: int = 4096        # BASELINE.md config #2: 4096 single-sig txns
    msg_maxlen: int = 128    # padded message bucket (wire txn MTU is 1232)


class SigVerifier:
    """Jitted fixed-shape verifier.  One instance per (batch, maxlen) bucket —
    the host pipeline picks a bucket per batch, mirroring how the reference
    picks SIMD batch widths at compile time (fd_sha512.h:266-361).

    mode="strict" (the default) always runs per-sig.  mode="rlc" runs the
    random-linear-combination batch check (ed.verify_batch_rlc) first: one
    MSM amortizes the 256 doublings across `msm_m` sigs per lane, falling
    back to the strict path for exact per-sig bits when the batch check
    fails.  Measured on v5e: rlc only pays once its MSM lanes are wide
    enough to leave the per-instruction-overhead-bound regime (batch
    ~>= 64k at m=8); below that strict wins — hence the default.

    mesh / n_shards (round 7) turn this into the MULTI-CHIP serving
    verifier: the batch axis shards over a 1-D 'dp' device mesh
    (parallel.mesh — the TPU-native round_robin_cnt/idx of
    fd_verify.c:36-47).  Strict dispatch places each packed blob with
    NamedSharding(P("dp", None)) and runs the shard_map'd verify step
    with the blob DONATED (steady-state dispatch allocates nothing per
    call); batches not divisible by the mesh pad host-side with the
    padding lanes masked False on device.  rlc mode routes through
    collectives.shard_rlc_verify (per-chip partial MSM + ICI ring point
    fold).  Per-lane verdicts for REAL lanes are bit-identical to the
    single-chip engine — verify is embarrassingly lane-parallel."""

    def __init__(self, cfg: VerifierConfig = VerifierConfig(),
                 mode: str = "strict", msm_m: int = 8,
                 mesh=None, n_shards: int | None = None):
        if mode not in ("strict", "rlc", "antipa"):
            raise ValueError(f"unknown verifier mode {mode!r}")
        if mode == "rlc" and cfg.batch % msm_m:
            raise ValueError(
                f"rlc mode needs batch ({cfg.batch}) divisible by "
                f"msm_m ({msm_m})")
        if n_shards is not None and mesh is None:
            from firedancer_tpu.parallel import mesh as pm
            mesh = pm.make_mesh(n_shards)
        if mesh is not None and "dp" not in mesh.shape:
            raise ValueError(
                f"verifier mesh needs a 'dp' axis, got {dict(mesh.shape)}")
        self.mesh = mesh
        self.n_shards = int(mesh.shape["dp"]) if mesh is not None else 1
        if mode == "rlc" and self.n_shards > 1 and (
                cfg.batch % self.n_shards
                or (cfg.batch // self.n_shards) % msm_m):
            raise ValueError(
                f"sharded rlc needs batch ({cfg.batch}) to split "
                f"{self.n_shards} ways into msm_m ({msm_m})-divisible "
                "shards")
        self.cfg = cfg
        self.mode = mode
        self.msm_m = msm_m
        # antipa mode (round 9) swaps the whole per-sig graph — halved
        # scalars via the in-kernel divstep — behind the SAME dispatch
        # surfaces as strict (4-array, packed blob, mesh).  rlc keeps a
        # strict _fn: its failed-batch descent must resolve exact
        # strict bits (ed.verify_batch), never the halved graph.
        self._fn = jax.jit(ed.verify_batch_antipa if mode == "antipa"
                           else ed.verify_batch)
        self._rlc = jax.jit(partial(ed.verify_batch_rlc, m=msm_m))
        self._rng = np.random.default_rng()  # OS-entropy seeded
        self._packed_cache = {}
        self._mesh_step = None       # lazily-built sharded 4-array step
        self._rlc_sharded = None     # lazily-built sharded rlc step
        self._blob_sharding = None
        if mesh is not None:
            from firedancer_tpu.parallel import mesh as pm
            self._blob_sharding = pm.blob_sharding(mesh)

    def example_args(self, valid: bool = True, seed: int = 1234):
        """Build a host-side example batch (valid signatures by default)."""
        return make_example_batch(self.cfg.batch, self.cfg.msg_maxlen, valid, seed)

    # -- packed ingest ----------------------------------------------------
    # One contiguous (batch, ml+100) blob per dispatch: msgs[:ml] | sigs |
    # pubs | lens, uploaded with a SINGLE device_put and unpacked on
    # device inside the jitted verify graph.  Through a tunneled device
    # the four separate implicit transfers cost ~3-4 RPC round-trips per
    # batch; the packed blob measured 380 K/s fresh-ingest vs 220-270 K/s
    # (tools/exp_r5_upload2.py) — the wiredancer DMA-push shape
    # (src/wiredancer/c/wd_f1.h:85-113: txns enter the card as one
    # contiguous write, not per-field buffers).

    def packed_dispatch(self, msgs, lens, sigs, pubs, ml: int | None = None):
        """Drop-in for __call__ on the strict path: same verdict device
        array, single-blob upload.  ml trims message columns to a known
        static bound (e.g. max true length in a fixed-length bench batch);
        default packs the full msg_maxlen."""
        if self.mode == "rlc":
            return self(msgs, lens, sigs, pubs)
        msgs = np.asarray(msgs)
        lens = np.ascontiguousarray(lens, dtype=np.int32)
        if ml is None:
            ml = msgs.shape[1]
        packed = np.concatenate(
            [msgs[:, :ml], np.asarray(sigs), np.asarray(pubs),
             lens.view(np.uint8).reshape(len(lens), 4)], axis=1)
        if self.mesh is not None:
            return self._dispatch_sharded(packed, ml, msgs.shape[1])
        import jax
        blob = jax.device_put(packed)
        return self._packed_fn(ml, msgs.shape[1])(blob)

    def _dispatch_sharded(self, packed: np.ndarray, ml: int, maxlen: int):
        """Sharded single-blob dispatch: pad rows to the mesh, place with
        P(dp, None) (ONE device_put splits the contiguous blob into
        per-device row slices), run the donated shard_map step.  Padding
        lanes are masked False on device; the verdict is trimmed back to
        the caller's batch."""
        import jax

        from firedancer_tpu.parallel import mesh as pm
        b = packed.shape[0]
        padded = pm.pad_rows(packed, self.n_shards)
        rows = b if padded.shape[0] != b else None
        dev = jax.device_put(padded, self._blob_sharding)
        ok = self._packed_fn(ml, maxlen, rows=rows)(dev)
        return ok[:b] if rows is not None else ok

    def dispatch_blob(self, blob, maxlen: int | None = None):
        """Dispatch an ALREADY-packed (batch, maxlen+100) row-interleaved
        bucket (the pipeline's packed_rows layout, filled in place by the
        native burst parser): one device_put, zero host-side concat.
        Per-sig modes only — the packed graph is the configured mode's
        verify graph (strict or antipa); silently running it for an rlc
        verifier would bypass the configured mode."""
        if self.mode == "rlc":
            raise ValueError(
                f"dispatch_blob is per-sig-only (mode={self.mode!r}); "
                "the pipeline falls back to 4-array dispatch for rlc")
        if maxlen is None:
            maxlen = blob.shape[1] - ed.PACKED_EXTRA
        if self.mesh is not None:
            return self._dispatch_sharded(np.asarray(blob), maxlen, maxlen)
        import jax
        return self._packed_fn(maxlen, maxlen)(jax.device_put(blob))

    def _packed_fn(self, ml: int, maxlen: int, rows: int | None = None):
        key = (ml, maxlen, rows)
        fn = self._packed_cache.get(key)
        if fn is None:
            import jax

            if self.mesh is not None:
                from firedancer_tpu.parallel import mesh as pm
                fn = pm.shard_verify_blob(
                    self.mesh, maxlen=maxlen, ml=ml, true_rows=rows,
                    mode=self.mode)
            else:
                blob_fn = (ed.verify_blob_antipa if self.mode == "antipa"
                           else ed.verify_blob)
                fn = jax.jit(partial(blob_fn, maxlen=maxlen, ml=ml))
            self._packed_cache[key] = fn
        return fn

    def make_ingest(self, ml: int | None = None, nbuf: int = 2,
                    depth: int | None = None) -> "PackedIngest":
        """Double-buffered fresh-ingest engine over this verifier's packed
        dispatch (per-sig modes only — same contract as dispatch_blob)."""
        if self.mode == "rlc":
            raise ValueError(
                f"make_ingest is per-sig-only (mode={self.mode!r})")
        return PackedIngest(self, ml=ml, nbuf=nbuf, depth=depth)

    def __call__(self, msgs, msg_len, sigs, pubkeys):
        if self.mode in ("strict", "antipa"):
            if self.mesh is not None:
                return self._mesh_verify(msgs, msg_len, sigs, pubkeys)
            return self._fn(msgs, msg_len, sigs, pubkeys)
        batch = sigs.shape[0]
        z = self._rng.integers(0, 256, size=(batch, 16), dtype=np.uint8)
        if self.mesh is not None:
            from firedancer_tpu.parallel import collectives as co
            from firedancer_tpu.parallel import mesh as pm
            if self._rlc_sharded is None:
                self._rlc_sharded = co.shard_rlc_verify(
                    self.mesh, m=self.msm_m)
            margs = pm.shard_batch(
                self.mesh, np.asarray(msgs),
                np.asarray(msg_len, dtype=np.int32), np.asarray(sigs),
                np.asarray(pubkeys), z)
            all_ok, _pre = self._rlc_sharded(*margs)
            # the fallback descent (a failed batch localizing adversarial
            # lanes) re-verifies slices on the single-chip strict path —
            # exact bits either way, the mesh only accelerates the
            # all-pass common case
            return _LazyRlcVerdict(self, (msgs, msg_len, sigs, pubkeys),
                                   all_ok, batch)
        all_ok, _pre = self._rlc(msgs, msg_len, sigs, pubkeys,
                                 jnp.asarray(z))
        # LAZY verdict: the batch bit is dispatched, not fetched — a
        # synchronous fetch here would pay a device round trip (~100 ms
        # through this container's tunnel) PER CALL and serialize the
        # pipeline (r4 measurement: sync-fetch RLC ran 0.4x strict while
        # its device time was lower).  Materialization (np.asarray /
        # harvest) resolves the common all-pass case to ones; a failed
        # batch runs the binary-split strict descent exactly as before.
        return _LazyRlcVerdict(self, (msgs, msg_len, sigs, pubkeys),
                               all_ok, batch)

    def _mesh_verify(self, msgs, msg_len, sigs, pubkeys):
        """Per-sig 4-array verify over the dp mesh (shard_verify_step,
        in the configured strict/antipa mode): uneven batches pad
        host-side (zero sig/pub lanes verify False and are trimmed from
        the verdict)."""
        from firedancer_tpu.parallel import mesh as pm
        if self._mesh_step is None:
            self._mesh_step = pm.shard_verify_step(self.mesh,
                                                   mode=self.mode)
        arrs = (np.asarray(msgs), np.asarray(msg_len, dtype=np.int32),
                np.asarray(sigs), np.asarray(pubkeys))
        b = arrs[2].shape[0]
        padded = tuple(pm.pad_rows(a, self.n_shards) for a in arrs)
        ok, _passes = self._mesh_step(*pm.shard_batch(self.mesh, *padded))
        return ok[:b] if padded[2].shape[0] != b else ok

    # leaves below this go straight to exact per-sig bits; also bounds the
    # number of distinct compiled split shapes
    _SPLIT_LEAF = 256

    def _rlc_slice(self, arrs, lo, hi) -> bool:
        n = hi - lo
        z = jnp.asarray(
            self._rng.integers(0, 256, size=(n, 16), dtype=np.uint8))
        all_ok, _ = self._rlc(*(a[lo:hi] for a in arrs), z)
        return bool(np.asarray(all_ok))

    def _resolve(self, arrs, lo, hi, out) -> None:
        n = hi - lo
        if n <= max(self._SPLIT_LEAF, 2 * self.msm_m) or n % (2 * self.msm_m):
            out[lo:hi] = np.asarray(self._fn(*(a[lo:hi] for a in arrs)))
            return
        mid = lo + n // 2
        for a, b in ((lo, mid), (mid, hi)):
            if self._rlc_slice(arrs, a, b):
                out[a:b] = True
            else:
                self._resolve(arrs, a, b, out)


@dataclass(frozen=True)
class WorkloadDesc:
    """Everything the double-buffer rotation core needs to know about a
    workload (round 13): PackedIngest used to hard-code the sigverify
    pieces — row geometry, the packed verify dispatch, the verdict trim —
    which made the engine unusable for the second packed workload (shred
    recover).  The descriptor names them:

      name              AOT key family / debug label ("verify-packed",
                        "shred-recover", ...)
      rows, row_bytes   rotating-blob geometry (rows includes any mesh
                        padding; padding rows stay zero forever)
      true_rows         rows the caller actually fills — verdicts trim to
                        this on harvest
      dispatch          np blob -> async device verdict handle (the
                        single-device_put upload + jitted compute)
      dispatch_external optional caller-owned-blob variant (zero-copy
                        submit_rows); defaults to `dispatch`
      harvest           optional host post-process applied to the
                        materialized verdict before the trim (e.g. the
                        shred workload splits packed full||ok columns)
    """

    name: str
    rows: int
    row_bytes: int
    true_rows: int
    dispatch: object
    dispatch_external: object = None
    harvest: object = None


class PackedDispatchEngine:
    """Workload-agnostic upload/compute double-buffering (the wiredancer
    async-DMA-push shape, src/wiredancer/c/wd_f1.h:85-113: work streams
    into the card while the previous batch computes).

    `nbuf` rotating host-side packed blobs: batch k+1 packs into a free
    buffer and starts its single-blob device_put + dispatch while batch
    k's compute runs on device.  An explicit inflight window (`depth`,
    dispatch-ahead bound) applies backpressure: when full, a submit
    harvests (blocks on) the OLDEST verdict before dispatching more —
    bounded queueing, never unbounded run-ahead.

    Buffer-safety invariant (tests/test_ingest_overlap.py): a blob
    returns to the free ring only when its batch's verdict has
    MATERIALIZED on host — the upload and the compute that read it are
    then provably complete on the in-order device queue, so the buffer
    can be repacked without a torn read even on backends where
    device_put aliases host memory (jax CPU).

    The workload itself — what a row means, what graph runs, what the
    verdict looks like — lives entirely in the WorkloadDesc; sigverify
    (PackedIngest) and shred recover (disco.tiles.ShredRecoverIngest)
    share this core."""

    def __init__(self, desc: WorkloadDesc, nbuf: int = 2,
                 depth: int | None = None):
        if nbuf < 2:
            raise ValueError(f"need >= 2 buffers to overlap, got {nbuf}")
        if depth is None:
            depth = nbuf - 1
        if depth < 1:
            raise ValueError(f"inflight depth must be >= 1, got {depth}")
        self.desc = desc
        self.depth = depth
        self.rows = desc.rows
        self._bufs = [np.zeros((desc.rows, desc.row_bytes), dtype=np.uint8)
                      for _ in range(nbuf)]
        self._free = deque(range(nbuf))
        self._inflight: deque[tuple[object, int]] = deque()  # (ok_dev, buf)
        # observability: dispatches, blocking harvests forced by a full
        # window (backpressure events), the deepest window reached, and
        # the host-side pack cost (BENCH ingest_pack_us_txn)
        self.dispatches = 0
        self.backpressure_waits = 0
        self.max_depth_seen = 0
        self.pack_ns = 0
        self.pack_txns = 0

    @property
    def inflight_depth(self) -> int:
        return len(self._inflight)

    @property
    def pack_us_txn(self) -> float:
        """Mean host-side pack cost per lane (us) across all submits."""
        return self.pack_ns / max(self.pack_txns, 1) / 1e3

    def stats(self) -> dict:
        """Observability snapshot (round 14: every packed workload —
        sigverify, shred recover, poh — reports the same counters to its
        tile metrics / BENCH record instead of cherry-picking fields)."""
        return {
            "dispatches": self.dispatches,
            "backpressure_waits": self.backpressure_waits,
            "max_depth_seen": self.max_depth_seen,
            "inflight_depth": self.inflight_depth,
            "pack_us_txn": self.pack_us_txn,
        }

    def _harvest_oldest(self) -> np.ndarray:
        ok_dev, bidx = self._inflight.popleft()
        ok = np.asarray(ok_dev)          # blocks until upload+compute done
        if bidx is not None:             # caller-owned blobs never pool
            self._free.append(bidx)
        if self.desc.harvest is not None:
            ok = self.desc.harvest(ok)
        tr = self.desc.true_rows
        return ok[:tr] if len(ok) != tr else ok

    def _enqueue(self, ok_dev, bidx, out: list) -> None:
        # start the device->host verdict copy NOW (r4 lesson: on a
        # tunneled device a cold harvest fetch pays a full RTT)
        start_async = getattr(ok_dev, "copy_to_host_async", None)
        if start_async is not None:
            start_async()
        self._inflight.append((ok_dev, bidx))
        self.dispatches += 1
        self.max_depth_seen = max(self.max_depth_seen, len(self._inflight))
        while len(self._inflight) > self.depth:
            out.append(self._harvest_oldest())

    def submit_packed(self, fill_fn, count: int) -> list[np.ndarray]:
        """Generic rotating submit: acquire a free buffer (harvesting the
        oldest verdict first under backpressure), fill it via
        fill_fn(buf) — timed into the pack stats with `count` work
        items — and dispatch through the workload descriptor.  Returns
        any verdicts retired by the inflight window this call, in
        dispatch order."""
        out = []
        if not self._free:
            # every buffer is pinned under an inflight dispatch: apply
            # backpressure by retiring the oldest before repacking
            self.backpressure_waits += 1
            out.append(self._harvest_oldest())
        bidx = self._free.popleft()
        buf = self._bufs[bidx]
        t_pack = time.perf_counter_ns()
        try:
            fill_fn(buf)
        except BaseException:
            # a failed pack must not leak the rotation buffer: the row
            # blob was never dispatched, so it goes straight back on the
            # free ring and the engine stays usable
            self._free.appendleft(bidx)
            raise
        self.pack_ns += time.perf_counter_ns() - t_pack
        self.pack_txns += count
        self._enqueue(self.desc.dispatch(buf), bidx, out)
        return out

    def submit_rows(self, rows) -> list[np.ndarray]:
        """Zero-copy submit (round 8): `rows` is an ALREADY-packed row
        blob — e.g. a dcache view the producer stamped in wire format —
        dispatched as-is with NO host repack.

        The no-torn-buffer invariant transfers to the CALLER: `rows` must
        stay unmutated until this batch's verdict is harvested (on jax CPU
        device_put aliases host memory).  The dispatch is pinned in the
        same inflight window as rotation buffers but never enters the free
        ring — the caller owns the memory."""
        out = []
        dispatch = self.desc.dispatch_external or self.desc.dispatch
        self._enqueue(dispatch(rows), None, out)
        return out

    def poll(self) -> list[np.ndarray]:
        """Harvest every verdict that is ALREADY materialized, in
        dispatch order, without blocking (round 13: a tile housekeeping
        hook drains finished device work between frags; blocking there
        would stall ingest).  Backends whose arrays lack is_ready()
        report nothing ready — callers fall back to drain()/submit
        retirement."""
        out = []
        while self._inflight:
            ready = getattr(self._inflight[0][0], "is_ready", None)
            if ready is None or not ready():
                break
            out.append(self._harvest_oldest())
        return out

    def drain(self) -> list[np.ndarray]:
        """Harvest every outstanding verdict, in dispatch order."""
        out = []
        while self._inflight:
            out.append(self._harvest_oldest())
        return out


class PackedIngest(PackedDispatchEngine):
    """Sigverify workload over the rotation core (VERDICT r5 Next #4):
    rows are the packed row-interleaved verify layout
    (msg[ml] | sig | pub | len), dispatch is the verifier's single-blob
    packed verify, verdict is the per-lane bool vector.

    Multi-chip (round 7): over a mesh-mode verifier the SAME rotation
    runs sharded — buffer rows pad to a multiple of the mesh (the
    per-device slices are contiguous host-side), each rotation's upload
    is still ONE device_put (against NamedSharding(P("dp", None)), which
    splits the blob across chips), and the dispatch runs the donated
    shard_map step.  The no-torn-buffer invariant is unchanged per
    shard: verdict materialization still proves every chip's upload and
    verify complete before the blob re-enters the free ring."""

    def __init__(self, verifier: "SigVerifier", ml: int | None = None,
                 nbuf: int = 2, depth: int | None = None):
        self.verifier = verifier
        cfg = verifier.cfg
        self.batch = cfg.batch
        self.ml = cfg.msg_maxlen if ml is None else ml
        self.maxlen = cfg.msg_maxlen
        # sharded rotation: rows pad to the mesh so every device gets an
        # equal slice; rows beyond batch stay zero forever (pack never
        # touches them) and are masked False on device
        self.shards = verifier.n_shards
        rows = self.batch + ((-self.batch) % self.shards)
        super().__init__(
            WorkloadDesc(
                name="verify-packed",
                rows=rows,
                row_bytes=self.ml + ed.PACKED_EXTRA,
                true_rows=self.batch,
                dispatch=self._dispatch_rotating,
                dispatch_external=self._dispatch_external,
            ),
            nbuf=nbuf, depth=depth)

    def _dispatch_rotating(self, buf):
        v = self.verifier
        if v.mesh is not None:
            blob = jax.device_put(buf, v._blob_sharding)
            rows = self.batch if self.rows != self.batch else None
            return v._packed_fn(self.ml, self.maxlen, rows=rows)(blob)
        return v._packed_fn(self.ml, self.maxlen)(jax.device_put(buf))

    def _dispatch_external(self, rows):
        ml = rows.shape[1] - ed.PACKED_EXTRA
        v = self.verifier
        if v.mesh is not None:
            if rows.shape[0] % v.n_shards:
                raise ValueError(
                    f"rows batch {rows.shape[0]} not divisible by "
                    f"mesh shards {v.n_shards}")
            blob = jax.device_put(np.asarray(rows), v._blob_sharding)
            return v._packed_fn(ml, ml)(blob)
        return v._packed_fn(ml, ml)(jax.device_put(rows))

    def _pack_into(self, buf, msgs, lens, sigs, pubs):
        # bulk since round 6; round 7 collapses the four column writes
        # into ONE C-level concatenate pass straight into the blob
        ml = self.ml
        msgs = np.asarray(msgs)
        lens = np.ascontiguousarray(lens, dtype=np.int32)
        np.concatenate(
            [msgs[:, :ml], np.asarray(sigs), np.asarray(pubs),
             lens.view(np.uint8).reshape(len(lens), 4)],
            axis=1, out=buf[:self.batch])

    def submit(self, msgs, lens, sigs, pubs) -> list[np.ndarray]:
        """Pack one batch into a rotating buffer and dispatch it.  Returns
        any verdicts retired by the inflight window this call (in dispatch
        order); the submitted batch's own verdict surfaces on a later
        submit() or drain()."""
        return self.submit_packed(
            lambda buf: self._pack_into(buf, msgs, lens, sigs, pubs),
            self.batch)


def use_legacy_pack() -> bool:
    """FDTPU_INGEST_LEGACY_PACK=1 routes packed ingest through the
    host-side `_pack_into` concatenate (the pre-round-8 path, kept
    bit-identical) instead of zero-copy `submit_rows` / dcache views."""
    import os
    return os.environ.get("FDTPU_INGEST_LEGACY_PACK", "0") == "1"


def use_native_hostpath() -> bool:
    """FDTPU_INGEST_NATIVE_HOSTPATH=0 disables the round-11 one-pass C
    submit/harvest kernel (native/hostpath.cpp), forcing the NumPy
    fallback — the A/B knob tools/exp_r11_hostpath.py toggles.  Default
    on; the pipeline also falls back on its own when the .so cannot
    build or the tcache is not native."""
    import os
    return os.environ.get("FDTPU_INGEST_NATIVE_HOSTPATH", "1") != "0"


class _LazyRlcVerdict:
    """Deferred per-lane bits for the RLC path: behaves like the device
    array the strict path returns (is_ready / copy_to_host_async /
    np.asarray), resolving the batch verdict only when materialized.

    all-pass (the overwhelmingly common case) costs one scalar fetch;
    a failed batch runs SigVerifier's binary-split strict descent —
    one adversarial signature localizes to its leaf, so hostile lanes
    can't force the whole batch onto the slow path (round-1 DoS shape).
    Passing subtrees are accepted wholesale on RLC soundness."""

    def __init__(self, sv: "SigVerifier", args, all_ok_dev, batch: int):
        self._sv = sv
        self._args = args
        self._all_ok = all_ok_dev
        self._batch = batch
        self._result = None
        self.shape = (batch,)
        self.dtype = np.dtype(bool)

    def is_ready(self) -> bool:
        if self._result is not None:
            return True
        fn = getattr(self._all_ok, "is_ready", None)
        return True if fn is None else bool(fn())

    def copy_to_host_async(self):
        fn = getattr(self._all_ok, "copy_to_host_async", None)
        if fn is not None:
            fn()

    def _materialize(self) -> np.ndarray:
        if self._result is None:
            if bool(np.asarray(self._all_ok)):
                self._result = np.ones((self._batch,), dtype=bool)
            else:
                arrs = tuple(np.asarray(x) for x in self._args)
                out = np.zeros((self._batch,), dtype=bool)
                self._sv._resolve(arrs, 0, self._batch, out)
                self._result = out
        return self._result

    def __array__(self, dtype=None, copy=None):
        r = self._materialize()
        return r.astype(dtype) if dtype is not None else r

    def __getitem__(self, i):
        return self._materialize()[i]

    def __iter__(self):
        return iter(self._materialize())

    def __len__(self):
        return self._batch

    def __bool__(self):
        # without this, bool(verdict) would fall back to __len__ and read
        # True for ANY non-empty batch — a caller writing `if ok:` would
        # treat a failed RLC batch as all-passing.  Mirror numpy's
        # ambiguity contract instead (ADVICE r4).
        raise ValueError(
            "truth value of a per-lane verdict is ambiguous; use "
            ".all(), .any() or np.asarray(verdict)")

    def all(self):
        return self._materialize().all()

    def any(self):
        return self._materialize().any()


def host_verify_arrays(msgs, lens, sigs, pubs, mode: str = "strict"):
    """CPU ed25519 fallback backend (degraded mode): per-lane host verify
    with acceptance rules bit-identical to the ACTIVE device graph —
    mode="strict" runs ops.ed25519.verify_one_host, mode="antipa" runs
    verify_one_host_antipa (the halved equation with the divstep host
    model, torsion laxity included).  Orders of magnitude slower than a
    device dispatch; the point is to keep verdicts FLOWING while the
    device path heals (pipeline.GuardedVerifier), not to keep line rate."""
    one = (ed.verify_one_host_antipa if mode == "antipa"
           else ed.verify_one_host)
    msgs = np.asarray(msgs, dtype=np.uint8)
    lens = np.asarray(lens).astype(np.int64)
    sigs = np.asarray(sigs, dtype=np.uint8)
    pubs = np.asarray(pubs, dtype=np.uint8)
    out = np.zeros(len(msgs), dtype=bool)
    for i in range(len(msgs)):
        sig = bytes(sigs[i])
        pub = bytes(pubs[i])
        if not (any(sig) or any(pub)):
            # all-zero sig+pub = padding lane; the device rejects it too
            # ((0,...) decompresses to a small-order point), skip the
            # expensive scalar math
            continue
        ln = max(0, min(int(lens[i]), msgs.shape[1]))
        out[i] = one(sig, bytes(msgs[i, :ln]), pub)
    return out


def host_verify_blob(blob, maxlen: int | None = None,
                     mode: str = "strict"):
    """CPU fallback over the packed row-interleaved blob layout
    (row = msg[ml] | sig[64] | pub[32] | len-le32, ed25519.PACKED_EXTRA):
    the same wire format dispatch_blob uploads, verified lane by lane on
    the host.  Verdict[i] matches the device's verify_blob /
    verify_blob_antipa bit for bit (per `mode`)."""
    blob = np.asarray(blob, dtype=np.uint8)
    ml = (blob.shape[1] - ed.PACKED_EXTRA) if maxlen is None else int(maxlen)
    lens = np.ascontiguousarray(
        blob[:, ml + 96:ml + 100]).view(np.int32).ravel()
    return host_verify_arrays(
        blob[:, :ml], np.clip(lens, 0, ml),
        blob[:, ml:ml + 64], blob[:, ml + 64:ml + 96], mode=mode)


def make_example_batch(
    batch: int,
    maxlen: int,
    valid: bool = True,
    seed: int = 1234,
    sign_pool: int | None = None,
):
    """Generate `batch` (msg, sig, pubkey) triples host-side.

    Signing is host python-int math (control plane); distinct keys/messages
    per lane.  With valid=False, a quarter of lanes get corrupted sigs.
    `sign_pool` bounds the number of distinct host signings (each costs a
    python-int scalar mult); lanes beyond it repeat pool entries — device
    verify work is identical either way, so benches use a small pool."""
    rng = np.random.default_rng(seed)
    msgs = np.zeros((batch, maxlen), dtype=np.uint8)
    lens = np.full((batch,), min(64, maxlen), dtype=np.int32)
    sigs = np.zeros((batch, 64), dtype=np.uint8)
    pubs = np.zeros((batch, 32), dtype=np.uint8)

    if sign_pool is not None and sign_pool < 1:
        raise ValueError(f"sign_pool must be >= 1, got {sign_pool}")
    nsign = batch if sign_pool is None else min(batch, sign_pool)
    npool = min(batch, 32, nsign)
    pool = []
    for i in range(npool):
        seed_b = rng.bytes(32)
        pub, a, prefix = ed.keypair_from_seed(seed_b)
        pool.append((seed_b, pub))
    signed = []
    for i in range(nsign):
        seed_b, pub = pool[i % npool]
        m = rng.bytes(int(lens[i]))
        signed.append((m, ed.sign(seed_b, m), pub))
    for i in range(batch):
        m, sig, pub = signed[i % nsign]
        msgs[i, : len(m)] = np.frombuffer(m, dtype=np.uint8)
        lens[i] = len(m)
        sigs[i] = np.frombuffer(sig, dtype=np.uint8)
        pubs[i] = np.frombuffer(pub, dtype=np.uint8)
    if not valid:
        bad = rng.choice(batch, size=max(1, batch // 4), replace=False)
        sigs[bad, 0] ^= 1
    return (
        jnp.asarray(msgs),
        jnp.asarray(lens),
        jnp.asarray(sigs),
        jnp.asarray(pubs),
    )
