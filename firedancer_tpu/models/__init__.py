"""Flagship pipelines.  The reference has no ML models; its "model" analogue
is the signature-verification data plane (the north-star component,
SURVEY.md §6), packaged here as a fixed-shape, jittable batch verifier."""
