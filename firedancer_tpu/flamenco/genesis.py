"""Genesis create/read (ref: src/flamenco/genesis/fd_genesis_create.c /
the genesis.bin reader): the chain's slot-0 state — funded accounts, vote
accounts for bootstrap validators, PoH parameters, fee/rent schedules.

Format: a pickled dict (a fresh chain owns its genesis format; the Agave
bincode genesis is a compatibility non-goal this round)."""

import hashlib
import pickle
import time
from dataclasses import dataclass, field

from .types import Account, FeeRateGovernor, Rent, EpochSchedule, \
    VOTE_PROGRAM_ID
from .vote_program import VoteState


@dataclass
class Genesis:
    creation_time: int
    accounts: dict[bytes, Account]
    stakes: dict[bytes, int]          # node identity pubkey -> stake
    ticks_per_slot: int = 64
    hashes_per_tick: int = 12500
    slots_per_epoch: int = 432_000
    lamports_per_signature: int = 5000

    def genesis_hash(self) -> bytes:
        """Deterministic hash of the genesis state = blockhash of slot 0's
        parent (the chain id)."""
        h = hashlib.sha256()
        h.update(self.creation_time.to_bytes(8, "little"))
        h.update(self.ticks_per_slot.to_bytes(8, "little"))
        h.update(self.hashes_per_tick.to_bytes(8, "little"))
        h.update(self.slots_per_epoch.to_bytes(8, "little"))
        for pk in sorted(self.accounts):
            h.update(pk)
            h.update(self.accounts[pk].serialize())
        return h.digest()

    def fee_rate_governor(self) -> FeeRateGovernor:
        return FeeRateGovernor(self.lamports_per_signature)

    def epoch_schedule(self) -> EpochSchedule:
        return EpochSchedule(self.slots_per_epoch)

    def write(self, path: str):
        with open(path, "wb") as f:
            pickle.dump({"version": 1, "genesis": self}, f)

    @classmethod
    def read(cls, path: str) -> "Genesis":
        with open(path, "rb") as f:
            d = pickle.load(f)
        if d.get("version") != 1:
            raise ValueError("bad genesis version")
        return d["genesis"]


def create(faucet_pubkey: bytes, faucet_lamports: int = 500_000_000_000_000,
           bootstrap_validators: list[tuple[bytes, bytes, int]] = (),
           slots_per_epoch: int = 432_000,
           creation_time: int | None = None) -> Genesis:
    """bootstrap_validators: (node_pubkey, vote_pubkey, stake_lamports)."""
    accounts: dict[bytes, Account] = {
        faucet_pubkey: Account(lamports=faucet_lamports)}
    stakes: dict[bytes, int] = {}
    rent = Rent()
    for node_pk, vote_pk, stake in bootstrap_validators:
        vs = VoteState(node_pubkey=node_pk, authorized_voter=node_pk)
        accounts[vote_pk] = Account(
            lamports=rent.minimum_balance(128), data=vs.serialize(),
            owner=VOTE_PROGRAM_ID)
        accounts.setdefault(node_pk, Account(lamports=1_000_000_000))
        stakes[node_pk] = stake
    return Genesis(
        creation_time=int(time.time()) if creation_time is None
        else creation_time,
        accounts=accounts, stakes=stakes, slots_per_epoch=slots_per_epoch)
