"""Config program (ref: src/flamenco/runtime/program/fd_config_program.c):
store small signed config blobs on chain (validator info etc.).

Account data = u8 n_keys | n * (pubkey[32] | u8 is_signer) | payload.
A store overwrites the payload; every is_signer key in the CURRENT account
data must sign the txn (the reference's authorization rule)."""

import struct

from .system_program import InstrError
from .types import CONFIG_PROGRAM_ID


def ix_store(keys: list[tuple[bytes, bool]], payload: bytes) -> bytes:
    out = bytearray([len(keys)])
    for pk, signer in keys:
        out += pk + bytes([signer])
    return bytes(out) + payload


def parse_state(data: bytes) -> tuple[list[tuple[bytes, bool]], bytes]:
    if not data:
        return [], b""
    n = data[0]
    keys = []
    off = 1
    for _ in range(n):
        keys.append((bytes(data[off : off + 32]), bool(data[off + 32])))
        off += 33
    return keys, bytes(data[off:])


def execute(ictx) -> None:
    ca = ictx.account(0)
    if ca.acct is None or ca.acct.owner != CONFIG_PROGRAM_ID:
        raise InstrError("config account not owned by config program")
    cur_keys, _ = parse_state(ca.acct.data)
    for pk, signer in cur_keys:
        if signer and not ictx.is_signer_key(pk):
            raise InstrError("missing required config signer")
    if not cur_keys and not ictx.is_signer(0):
        # uninitialized: the account itself must sign the first store
        raise InstrError("config account must sign initial store")
    new_keys, _payload = parse_state(ictx.data)
    for pk, signer in new_keys:
        if signer and not ictx.is_signer_key(pk):
            raise InstrError("new config signer must sign")
    ca.acct.data = bytes(ictx.data)
    ca.touch()


def register():
    from .executor import register_program
    register_program(CONFIG_PROGRAM_ID, execute)


register()
