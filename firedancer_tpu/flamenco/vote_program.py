"""Vote program (ref: src/flamenco/runtime/program/fd_vote_program.c —
theirs is a 5k-LoC port of Solana's tower-vote state machine; this is the
structurally-equivalent core: vote account state, lockout doubling, root
advancement, credits).

State serialization is our own compact LE format (a fresh chain defines its
own layouts; layout compatibility with Agave snapshots is a non-goal this
round and is confined to this module)."""

import struct

from .types import Account, VOTE_PROGRAM_ID
from .system_program import InstrError

MAX_LOCKOUT_HISTORY = 31
INITIAL_LOCKOUT = 2


def apply_vote_slot(votes: list[tuple[int, int]], slot: int) -> int | None:
    """THE TowerBFT lockout machine, shared by the on-chain vote program
    (VoteState) and the validator's local tower (choreo.tower.Tower) so the
    consensus-critical rules cannot diverge.  Mutates `votes` (a stack of
    (slot, confirmation_count)); returns a newly-rooted slot or None.
    Raises ValueError on a non-increasing vote slot."""
    if votes and slot <= votes[-1][0]:
        raise ValueError("vote slot not newer than last vote")
    # pop expired lockouts: vote at (s, c) expires after s + 2^c
    while votes:
        s, c = votes[-1]
        if slot > s + (INITIAL_LOCKOUT ** c):
            votes.pop()
        else:
            break
    votes.append((slot, 1))
    rooted = None
    if len(votes) > MAX_LOCKOUT_HISTORY:
        rooted = votes.pop(0)[0]
    # deeper confirmations double lockout
    for i in range(len(votes) - 2, -1, -1):
        stack_depth = len(votes) - i
        if votes[i][1] < stack_depth:
            votes[i] = (votes[i][0], votes[i][1] + 1)
    return rooted


# -- state ------------------------------------------------------------------

class VoteState:
    def __init__(self, node_pubkey: bytes = bytes(32),
                 authorized_voter: bytes = bytes(32),
                 commission: int = 0):
        self.node_pubkey = node_pubkey
        self.authorized_voter = authorized_voter
        self.commission = commission
        self.votes: list[tuple[int, int]] = []  # (slot, confirmation_count)
        self.root_slot: int | None = None
        self.credits = 0
        self.last_timestamp = (0, 0)  # (slot, unix_ts)

    def serialize(self) -> bytes:
        out = bytearray()
        out += self.node_pubkey + self.authorized_voter
        out += struct.pack("<BQ", self.commission, self.credits)
        root = 0xFFFFFFFFFFFFFFFF if self.root_slot is None else self.root_slot
        out += struct.pack("<QQq", root, *self.last_timestamp)
        out += struct.pack("<H", len(self.votes))
        for slot, conf in self.votes:
            out += struct.pack("<QI", slot, conf)
        return bytes(out)

    @classmethod
    def deserialize(cls, raw: bytes) -> "VoteState":
        vs = cls()
        vs.node_pubkey, vs.authorized_voter = bytes(raw[0:32]), bytes(raw[32:64])
        vs.commission, vs.credits = struct.unpack_from("<BQ", raw, 64)
        root, ts_slot, ts = struct.unpack_from("<QQq", raw, 73)
        vs.root_slot = None if root == 0xFFFFFFFFFFFFFFFF else root
        vs.last_timestamp = (ts_slot, ts)
        (n,) = struct.unpack_from("<H", raw, 97)
        off = 99
        for _ in range(n):
            slot, conf = struct.unpack_from("<QI", raw, off)
            vs.votes.append((slot, conf))
            off += 12
        return vs

    # -- tower mechanics (process_vote_unchecked semantics) ---------------
    def process_vote_slot(self, slot: int):
        try:
            rooted = apply_vote_slot(self.votes, slot)
        except ValueError as e:
            raise InstrError(str(e))
        if rooted is not None:
            self.root_slot = rooted
            self.credits += 1  # rooted vote earns a credit


# -- instructions -----------------------------------------------------------

def ix_initialize(node_pubkey: bytes, authorized_voter: bytes,
                  commission: int = 0) -> bytes:
    return struct.pack("<I", 0) + node_pubkey + authorized_voter + bytes(
        [commission])


def ix_vote(slots: list[int], blockhash: bytes = bytes(32)) -> bytes:
    out = struct.pack("<IH", 1, len(slots))
    for s in slots:
        out += struct.pack("<Q", s)
    return out + blockhash


def parse_vote(data: bytes) -> list[int] | None:
    """Instruction-data parse of a vote ix (the replay/consensus side's
    read of votes landing in blocks — fd_replay's vote extraction);
    returns the voted slots or None if not a well-formed vote ix."""
    if len(data) < 6 or struct.unpack_from("<I", data)[0] != 1:
        return None
    (n,) = struct.unpack_from("<H", data, 4)
    if n == 0 or len(data) < 6 + 8 * n:
        return None
    return [struct.unpack_from("<Q", data, 6 + 8 * i)[0] for i in range(n)]


def execute(ictx) -> None:
    data = ictx.data
    if len(data) < 4:
        raise InstrError("vote: data too short")
    disc = struct.unpack_from("<I", data)[0]
    if disc == 0:
        _initialize(ictx, data)
    elif disc == 1:
        _vote(ictx, data)
    else:
        raise InstrError(f"unsupported vote instruction {disc}")


def _initialize(ictx, data):
    if len(data) < 69:
        raise InstrError("vote initialize: instruction data too short")
    va = ictx.account(0)
    if va.acct is None or va.acct.owner != VOTE_PROGRAM_ID:
        raise InstrError("vote account not owned by vote program")
    if any(b for b in va.acct.data):
        raise InstrError("vote account already initialized")
    node = bytes(data[4:36])
    voter = bytes(data[36:68])
    commission = data[68]
    if not ictx.is_signer_key(node):
        raise InstrError("node pubkey must sign initialize")
    vs = VoteState(node, voter, commission)
    va.acct.data = vs.serialize()
    va.touch()


def _vote(ictx, data):
    va = ictx.account(0)
    if va.acct is None or va.acct.owner != VOTE_PROGRAM_ID:
        raise InstrError("vote account not owned by vote program")
    if not any(b for b in va.acct.data):
        raise InstrError("vote account uninitialized")
    vs = VoteState.deserialize(va.acct.data)
    if not ictx.is_signer_key(vs.authorized_voter):
        raise InstrError("authorized voter must sign")
    (n,) = struct.unpack_from("<H", data, 4)
    off = 6
    slots = [struct.unpack_from("<Q", data, off + 8 * i)[0] for i in range(n)]
    if not slots:
        raise InstrError("empty vote")
    for s in slots:
        vs.process_vote_slot(s)
    va.acct.data = vs.serialize()
    va.touch()
