"""Transaction executor (ref: src/flamenco/runtime/fd_executor.c — the
prepare/execute/finalize phase structure of fd_execute_txn_prepare_phase1..4
and fd_execute_txn, fd_executor.h:132-140).

Phases:
  1. load    — resolve accounts from the bank's fork, check fee payer
  2. fees    — charge per-signature fees (always, even on later failure)
  3. execute — dispatch each instruction to its program; any InstrError
               rolls back every non-fee effect
  4. commit  — store touched accounts back into the fork

Native program dispatch mirrors the builtins registry
(fd_builtin_programs.c); the sBPF path plugs into the same table via the
bpf loader entry."""

import struct
from dataclasses import dataclass, field

from ..ballet import txn as txn_lib
from .accdb import AccDb
from .types import (Account, COMPUTE_BUDGET_PROGRAM_ID, SYSTEM_PROGRAM_ID,
                    VOTE_PROGRAM_ID, STAKE_PROGRAM_ID)
from . import system_program, vote_program
from .system_program import InstrError


class TxnError(Exception):
    pass


# Any of these escaping a program handler means the *instruction* failed on
# adversarial input (truncated ix data, forged lengths, huge allocations) —
# never that the bank tile should die.  Mirrors the reference's stance that
# fd_execute_instr converts every program failure into an instr error code.
PROGRAM_FAILURES = (InstrError, struct.error, ValueError, IndexError,
                    KeyError, OverflowError, MemoryError)


@dataclass
class BorrowedAccount:
    """fd_borrowed_account_t: an account loaded for one txn, with a dirty
    bit instead of refcounts (one executor per bank lane)."""
    pubkey: bytes
    acct: Account | None
    writable: bool
    signer: bool
    dirty: bool = False

    def touch(self):
        if not self.writable:
            raise InstrError(f"write to read-only account")
        self.dirty = True


class InstrCtx:
    """What a program's execute() sees (fd_exec_instr_ctx_t)."""

    def __init__(self, txctx: "TxnCtx", program_id: bytes,
                 acct_indices: list[int], data: bytes):
        self.txctx = txctx
        self.program_id = program_id
        self._indices = acct_indices
        self.data = data

    @property
    def n_accounts(self) -> int:
        return len(self._indices)

    def account(self, i: int) -> BorrowedAccount:
        if i >= len(self._indices):
            raise InstrError("not enough account keys")
        return self.txctx.accounts[self._indices[i]]

    def is_signer(self, i: int) -> bool:
        return self.account(i).signer

    def is_signer_key(self, pubkey: bytes) -> bool:
        return any(a.signer and a.pubkey == pubkey
                   for a in self.txctx.accounts)


@dataclass
class TxnCtx:
    accounts: list[BorrowedAccount] = field(default_factory=list)
    compute_units_consumed: int = 0
    epoch: int = 0  # clock epoch (sysvar clock; stake activation math)


@dataclass
class TxnResult:
    ok: bool
    err: str | None = None
    fee: int = 0
    compute_units: int = 0


def _bpf_loader_execute(ictx):
    from . import bpf_loader
    bpf_loader.execute_loader(ictx)


NATIVE_PROGRAMS = {
    SYSTEM_PROGRAM_ID: system_program.execute,
    VOTE_PROGRAM_ID: vote_program.execute,
}


def _stake_execute(ictx):
    from . import stake_program
    stake_program.execute(ictx)


def _register_builtins():
    from .types import BPF_LOADER_ID
    NATIVE_PROGRAMS[BPF_LOADER_ID] = _bpf_loader_execute
    NATIVE_PROGRAMS[STAKE_PROGRAM_ID] = _stake_execute


_register_builtins()


def register_program(program_id: bytes, execute_fn):
    """Builtins registry hook (fd_builtin_programs.c); the sBPF loader and
    tests add entries here."""
    NATIVE_PROGRAMS[program_id] = execute_fn


class Executor:
    def __init__(self, accdb: AccDb, lamports_per_signature: int = 5000,
                 blockhash_check=None):
        self.accdb = accdb
        self.lamports_per_signature = lamports_per_signature
        # recency predicate bytes->bool supplied by the Runtime's
        # BlockhashQueue; None (standalone/test executors) skips the check
        self.blockhash_check = blockhash_check

    def execute_txn(self, xid, payload: bytes,
                    parsed: txn_lib.Txn | None = None,
                    epoch: int = 0) -> TxnResult:
        """Run one (already signature-verified) txn against fork `xid`."""
        if parsed is None:
            try:
                parsed = txn_lib.parse(payload)
            except txn_lib.TxnParseError as e:
                return TxnResult(False, f"parse: {e}")

        if (self.blockhash_check is not None
                and not self.blockhash_check(parsed.recent_blockhash(payload))):
            return TxnResult(False, "blockhash not found")

        # ---- phase 1: load --------------------------------------------
        addrs = parsed.account_addrs(payload)
        if len(set(addrs)) != len(addrs):
            # two indices aliasing one account would double-count in the
            # lamport-conservation check and let last-store-wins mint funds
            return TxnResult(False, "account loaded twice")
        nsign = parsed.signature_cnt
        ctx = TxnCtx(epoch=epoch)
        for i, pk in enumerate(addrs):
            ctx.accounts.append(BorrowedAccount(
                pubkey=pk, acct=self.accdb.load(xid, pk),
                writable=parsed.is_writable(i), signer=i < nsign))
        fee_payer = ctx.accounts[0]
        fee = self.lamports_per_signature * nsign
        if fee_payer.acct is None or fee_payer.acct.lamports < fee:
            return TxnResult(False, "fee payer cannot cover fee", 0)
        if not fee_payer.writable:
            return TxnResult(False, "fee payer not writable", 0)

        # ---- phase 2: fees (survive execution failure) ----------------
        fee_payer.acct.lamports -= fee
        fee_payer.dirty = True
        # snapshot for rollback-of-everything-but-fees
        snap = [(a.acct.serialize() if a.acct else None)
                for a in ctx.accounts]
        fee_only_payer = fee_payer.acct.serialize()

        # ---- phase 3: execute -----------------------------------------
        err = None
        lamports_before = self._total_lamports(ctx)
        for instr in parsed.instrs:
            if instr.program_id >= len(addrs):
                err = "program id index out of range"
                break
            prog_id = addrs[instr.program_id]
            handler = self._resolve(ctx, instr.program_id)
            if handler is None:
                err = "invalid program for execution"
                break
            acct_indices = list(
                payload[instr.acct_off:instr.acct_off + instr.acct_cnt])
            if any(i >= len(addrs) for i in acct_indices):
                err = "instruction account index out of range"
                break
            data = payload[instr.data_off:instr.data_off + instr.data_sz]
            ictx = InstrCtx(ctx, prog_id, acct_indices, data)
            try:
                handler(ictx)
            except PROGRAM_FAILURES as e:
                err = f"{type(e).__name__}: {e}"
                break
        if err is None and self._total_lamports(ctx) != lamports_before:
            err = "sum of account balances changed"  # lamport conservation

        if err is not None:
            # roll back every effect except the fee debit
            for a, raw in zip(ctx.accounts, snap):
                a.acct = Account.deserialize(raw) if raw is not None else None
                a.dirty = False
            fee_payer.acct = Account.deserialize(fee_only_payer)
            fee_payer.dirty = True

        # ---- phase 4: commit ------------------------------------------
        for a in ctx.accounts:
            if a.dirty:
                self.accdb.store(xid, a.pubkey,
                                 a.acct if a.acct is not None else Account())
        return TxnResult(err is None, err, fee, ctx.compute_units_consumed)

    def _resolve(self, ctx: TxnCtx, prog_index: int):
        prog = ctx.accounts[prog_index]
        fn = NATIVE_PROGRAMS.get(prog.pubkey)
        if fn is not None:
            return fn
        if prog.pubkey == COMPUTE_BUDGET_PROGRAM_ID:
            return _compute_budget_noop
        # deployed sBPF program: executable account owned by the loader
        from .types import BPF_LOADER_ID
        if (prog.acct is not None and prog.acct.executable
                and prog.acct.owner == BPF_LOADER_ID):
            from . import bpf_loader
            acct = prog.acct
            return lambda ictx: bpf_loader.execute_program(ictx, acct)
        return None

    @staticmethod
    def _total_lamports(ctx: TxnCtx) -> int:
        return sum(a.acct.lamports for a in ctx.accounts if a.acct is not None)


def _compute_budget_noop(ictx):
    """Compute-budget instructions set limits parsed at pack time
    (ballet/pack.py _parse_compute_budget); at execution they are no-ops."""
