"""Transaction executor (ref: src/flamenco/runtime/fd_executor.c — the
prepare/execute/finalize phase structure of fd_execute_txn_prepare_phase1..4
and fd_execute_txn, fd_executor.h:132-140).

Phases:
  1. load    — resolve accounts from the bank's fork, check fee payer
  2. fees    — charge per-signature fees (always, even on later failure)
  3. execute — dispatch each instruction to its program; any InstrError
               rolls back every non-fee effect
  4. commit  — store touched accounts back into the fork

Native program dispatch mirrors the builtins registry
(fd_builtin_programs.c); the sBPF path plugs into the same table via the
bpf loader entry."""

import struct
from dataclasses import dataclass, field

from ..ballet import txn as txn_lib
from .accdb import AccDb
from .types import (Account, COMPUTE_BUDGET_PROGRAM_ID, SYSTEM_PROGRAM_ID,
                    VOTE_PROGRAM_ID, STAKE_PROGRAM_ID)
from . import system_program, vote_program
from .system_program import InstrError


class TxnError(Exception):
    pass


# Any of these escaping a program handler means the *instruction* failed on
# adversarial input (truncated ix data, forged lengths, huge allocations) —
# never that the bank tile should die.  Mirrors the reference's stance that
# fd_execute_instr converts every program failure into an instr error code.
PROGRAM_FAILURES = (InstrError, struct.error, ValueError, IndexError,
                    KeyError, OverflowError, MemoryError)


@dataclass
class BorrowedAccount:
    """fd_borrowed_account_t: an account loaded for one txn, with a dirty
    bit instead of refcounts (one executor per bank lane)."""
    pubkey: bytes
    acct: Account | None
    writable: bool
    signer: bool
    dirty: bool = False

    def touch(self):
        if not self.writable:
            raise InstrError(f"write to read-only account")
        self.dirty = True


class InstrCtx:
    """What a program's execute() sees (fd_exec_instr_ctx_t)."""

    def __init__(self, txctx: "TxnCtx", program_id: bytes,
                 acct_indices: list[int], data: bytes, depth: int = 0):
        self.txctx = txctx
        self.program_id = program_id
        self._indices = acct_indices
        self.data = data
        self.depth = depth  # CPI nesting level (0 = top-level instruction)

    @property
    def n_accounts(self) -> int:
        return len(self._indices)

    def account(self, i: int) -> BorrowedAccount:
        if i >= len(self._indices):
            raise InstrError("not enough account keys")
        return self.txctx.accounts[self._indices[i]]

    def is_signer(self, i: int) -> bool:
        return self.account(i).signer

    def is_signer_key(self, pubkey: bytes) -> bool:
        return any(a.signer and a.pubkey == pubkey
                   for a in self.txctx.accounts)


@dataclass
class TxnCtx:
    accounts: list[BorrowedAccount] = field(default_factory=list)
    compute_units_consumed: int = 0
    epoch: int = 0  # clock epoch (sysvar clock; stake activation math)
    slot: int = 0
    cu_limit: int = 1_400_000  # effective budget (compute-budget program)
    executor: "Executor | None" = None  # CPI dispatch hook
    instr_stack: list = field(default_factory=list)  # program ids, for CPI
    # processed-instruction trace for sibling introspection
    # (sol_get_processed_sibling_instruction): entries of
    # (stack_height, program_id, [(pubkey, is_signer, is_writable)], data)
    instr_trace: list = field(default_factory=list)
    xid: object = None  # fork id — sysvar-getter syscalls read through it
    return_data: tuple = (bytes(32), b"")  # sol_{set,get}_return_data

    def record_instr(self, program_id: bytes, acct_indices, data: bytes):
        """Append a completed instruction to the introspection trace —
        THE single definition of the trace-entry shape (executor dispatch
        and the test-vectors runner both record through here)."""
        self.instr_trace.append((
            len(self.instr_stack), program_id,
            [(self.accounts[i].pubkey, self.accounts[i].signer,
              self.accounts[i].writable) for i in acct_indices],
            bytes(data)))

    def consume_cu(self, n: int):
        self.compute_units_consumed += n
        if self.compute_units_consumed > self.cu_limit:
            raise InstrError("compute budget exceeded")


@dataclass
class TxnResult:
    ok: bool
    err: str | None = None
    fee: int = 0
    compute_units: int = 0


def _bpf_loader_execute(ictx):
    from . import bpf_loader
    bpf_loader.execute_loader(ictx)


NATIVE_PROGRAMS = {
    SYSTEM_PROGRAM_ID: system_program.execute,
    VOTE_PROGRAM_ID: vote_program.execute,
}


def _stake_execute(ictx):
    from . import stake_program
    stake_program.execute(ictx)


def _alut_execute(ictx):
    from . import alut_program
    alut_program.execute(ictx)


def _upgradeable_loader_execute(ictx):
    from . import bpf_loader_upgradeable
    bpf_loader_upgradeable.execute(ictx)


def _register_builtins():
    from .bpf_loader_upgradeable import UPGRADEABLE_LOADER_ID
    from .types import ADDRESS_LOOKUP_TABLE_PROGRAM_ID, BPF_LOADER_ID
    NATIVE_PROGRAMS[BPF_LOADER_ID] = _bpf_loader_execute
    NATIVE_PROGRAMS[UPGRADEABLE_LOADER_ID] = _upgradeable_loader_execute
    NATIVE_PROGRAMS[STAKE_PROGRAM_ID] = _stake_execute
    NATIVE_PROGRAMS[ADDRESS_LOOKUP_TABLE_PROGRAM_ID] = _alut_execute


_register_builtins()


def register_program(program_id: bytes, execute_fn):
    """Builtins registry hook (fd_builtin_programs.c); the sBPF loader and
    tests add entries here."""
    NATIVE_PROGRAMS[program_id] = execute_fn


class Executor:
    def __init__(self, accdb: AccDb, lamports_per_signature: int = 5000,
                 blockhash_check=None):
        self.accdb = accdb
        self.lamports_per_signature = lamports_per_signature
        # recency predicate bytes->bool supplied by the Runtime's
        # BlockhashQueue; None (standalone/test executors) skips the check
        self.blockhash_check = blockhash_check

    def execute_txn(self, xid, payload: bytes,
                    parsed: txn_lib.Txn | None = None,
                    epoch: int = 0, slot: int = 0,
                    resolved_lookups=None, blockhash_check=None) -> TxnResult:
        """Run one (already signature-verified) txn against fork `xid`.

        resolved_lookups: optional pre-resolved v0 lookup result — either
        the (extra_addrs, extra_writable) tuple or the exception resolution
        raised — supplied by Bank.execute_txn, which resolves once for its
        own delta-hash pre-state tracking.

        blockhash_check: per-call recency predicate overriding the
        constructor default — Bank.execute_txn passes its FORK's queue so
        recency follows the replayed fork's ancestor chain, not a shared
        runtime-wide window (ADVICE r3)."""
        if parsed is None:
            try:
                parsed = txn_lib.parse(payload)
            except txn_lib.TxnParseError as e:
                return TxnResult(False, f"parse: {e}")

        check = (blockhash_check if blockhash_check is not None
                 else self.blockhash_check)
        if check is not None and not check(parsed.recent_blockhash(payload)):
            return TxnResult(False, "blockhash not found")

        # ---- phase 1: load --------------------------------------------
        addrs = parsed.account_addrs(payload)
        writable_flags = [parsed.is_writable(i) for i in range(len(addrs))]
        if parsed.addr_table_lookup_cnt:
            # v0: resolve address-table lookups through the fork's accdb
            # (ref fd_address_lookup_table_program.c + the executor's
            # account-load phase)
            from .alut_program import TxnLookupError, resolve_lookups
            if resolved_lookups is None:
                try:
                    resolved_lookups = resolve_lookups(
                        self.accdb, xid, parsed, payload)
                except (TxnLookupError, InstrError, ValueError) as e:
                    resolved_lookups = e
            if isinstance(resolved_lookups, Exception):
                return TxnResult(False, f"lookup: {resolved_lookups}")
            extra, extra_wr = resolved_lookups
            addrs = addrs + extra
            writable_flags += extra_wr
        if len(set(addrs)) != len(addrs):
            # two indices aliasing one account would double-count in the
            # lamport-conservation check and let last-store-wins mint funds
            return TxnResult(False, "account loaded twice")
        nsign = parsed.signature_cnt
        ctx = TxnCtx(epoch=epoch, slot=slot, executor=self, xid=xid,
                     cu_limit=self._compute_budget(parsed, payload))
        for i, pk in enumerate(addrs):
            ctx.accounts.append(BorrowedAccount(
                pubkey=pk, acct=self.accdb.load(xid, pk),
                writable=writable_flags[i], signer=i < nsign))
        fee_payer = ctx.accounts[0]
        fee = self.lamports_per_signature * nsign
        if fee_payer.acct is None or fee_payer.acct.lamports < fee:
            return TxnResult(False, "fee payer cannot cover fee", 0)
        if not fee_payer.writable:
            return TxnResult(False, "fee payer not writable", 0)

        # ---- phase 2: fees (survive execution failure) ----------------
        fee_payer.acct.lamports -= fee
        fee_payer.dirty = True
        # snapshot for rollback-of-everything-but-fees
        snap = [(a.acct.serialize() if a.acct else None)
                for a in ctx.accounts]
        fee_only_payer = fee_payer.acct.serialize()

        # ---- phase 3: execute -----------------------------------------
        err = None
        lamports_before = self._total_lamports(ctx)
        for instr in parsed.instrs:
            if instr.program_id >= len(addrs):
                err = "program id index out of range"
                break
            prog_id = addrs[instr.program_id]
            acct_indices = list(
                payload[instr.acct_off:instr.acct_off + instr.acct_cnt])
            if any(i >= len(addrs) for i in acct_indices):
                err = "instruction account index out of range"
                break
            data = payload[instr.data_off:instr.data_off + instr.data_sz]
            try:
                self.run_instruction(ctx, prog_id, acct_indices, data)
            except PROGRAM_FAILURES as e:
                err = f"{type(e).__name__}: {e}"
                break
        if err is None and self._total_lamports(ctx) != lamports_before:
            err = "sum of account balances changed"  # lamport conservation

        if err is not None:
            # roll back every effect except the fee debit
            for a, raw in zip(ctx.accounts, snap):
                a.acct = Account.deserialize(raw) if raw is not None else None
                a.dirty = False
            fee_payer.acct = Account.deserialize(fee_only_payer)
            fee_payer.dirty = True

        # ---- phase 4: commit ------------------------------------------
        for a in ctx.accounts:
            if a.dirty:
                self.accdb.store(xid, a.pubkey,
                                 a.acct if a.acct is not None else Account())
        return TxnResult(err is None, err, fee, ctx.compute_units_consumed)

    MAX_INVOKE_DEPTH = 4  # CPI nesting cap (fd_vm_cpi / Solana's stack of 5)
    NATIVE_INSTR_CU = 150  # flat builtin cost (fd_builtin default_cost)

    def run_instruction(self, ctx: TxnCtx, prog_id: bytes,
                        acct_indices: list[int], data: bytes,
                        depth: int = 0) -> None:
        """Shared instruction runner: top-level dispatch and CPI both land
        here so resolution, metering and the invoke stack are uniform."""
        handler = self._resolve_pubkey(ctx, prog_id)
        if handler is None:
            raise InstrError("invalid program for execution")
        ctx.consume_cu(self.NATIVE_INSTR_CU)
        ctx.instr_stack.append(prog_id)
        try:
            handler(InstrCtx(ctx, prog_id, acct_indices, data, depth=depth))
            # record AFTER success at this stack height (Agave's
            # processed-sibling trace records completed instructions)
            ctx.record_instr(prog_id, acct_indices, data)
        finally:
            ctx.instr_stack.pop()

    def invoke_signed(self, ctx: TxnCtx, caller: InstrCtx, program_id: bytes,
                      metas: list[tuple[bytes, bool, bool]], data: bytes,
                      pda_signers: list[bytes]) -> None:
        """Cross-program invocation with privilege checks (the role of
        fd_vm_cpi.h + Solana's InvokeContext::process_instruction):

          * depth cap; reentrancy allowed only as direct self-recursion
          * callee accounts must already be loaded by the transaction
          * is_writable only if the txn loaded the account writable
          * is_signer only if the txn signer set or a PDA derived from the
            CALLER's program id via signer seeds grants it
        """
        if caller.depth + 1 > self.MAX_INVOKE_DEPTH:
            raise InstrError("max invoke depth exceeded")
        if program_id in ctx.instr_stack and ctx.instr_stack[-1] != program_id:
            raise InstrError("reentrancy not allowed")
        idx_of = {a.pubkey: i for i, a in enumerate(ctx.accounts)}
        indices, saved = [], []
        for pk, m_signer, m_writable in metas:
            i = idx_of.get(pk)
            if i is None:
                raise InstrError("CPI account not loaded by transaction")
            a = ctx.accounts[i]
            if m_writable and not a.writable:
                raise InstrError("CPI writable privilege escalation")
            if m_signer and not (a.signer or pk in pda_signers):
                raise InstrError("CPI signer privilege escalation")
            indices.append(i)
        # per-instruction privileges: narrow (or PDA-widen) for the callee,
        # restore after — touch()/is_signer() then enforce the right scope
        for i, (pk, m_signer, m_writable) in zip(indices, metas):
            a = ctx.accounts[i]
            saved.append((a, a.signer, a.writable))
            a.signer = m_signer
            a.writable = m_writable and a.writable
        try:
            self.run_instruction(ctx, program_id, indices, data,
                                 depth=caller.depth + 1)
        finally:
            # reversed: duplicate metas for one account must unwind to the
            # ORIGINAL flags, not to an intermediate narrowed/widened state
            for a, sg, wr in reversed(saved):
                a.signer, a.writable = sg, wr

    def _compute_budget(self, parsed: txn_lib.Txn, payload: bytes) -> int:
        """Effective CU limit (ref fd_compute_budget_program.c): explicit
        SetComputeUnitLimit wins (capped at 1.4M), else 200k per
        non-budget instruction."""
        accts = parsed.account_addrs(payload)
        limit = None
        n_real = 0
        for ins in parsed.instrs:
            if ins.program_id >= len(accts):
                continue
            if accts[ins.program_id] == COMPUTE_BUDGET_PROGRAM_ID:
                data = payload[ins.data_off:ins.data_off + ins.data_sz]
                if len(data) >= 5 and data[0] == 2:  # SetComputeUnitLimit
                    limit = int.from_bytes(data[1:5], "little")
            else:
                n_real += 1
        if limit is None:
            limit = 200_000 * max(1, n_real)
        return min(limit, 1_400_000)

    def _resolve_pubkey(self, ctx: TxnCtx, pubkey: bytes):
        fn = NATIVE_PROGRAMS.get(pubkey)
        if fn is not None:
            return fn
        if pubkey == COMPUTE_BUDGET_PROGRAM_ID:
            return _compute_budget_noop
        # deployed sBPF program: executable account owned by a loader
        from .types import BPF_LOADER_ID
        prog = next((a for a in ctx.accounts if a.pubkey == pubkey), None)
        if prog is None or prog.acct is None or not prog.acct.executable:
            return None
        if prog.acct.owner == BPF_LOADER_ID:
            from . import bpf_loader
            acct = prog.acct
            return lambda ictx: bpf_loader.execute_program(ictx, acct)
        from . import bpf_loader_upgradeable as up
        if prog.acct.owner == up.UPGRADEABLE_LOADER_ID:
            # indirect: the Program account points at its ProgramData,
            # which must be present in the txn's account list
            st, s = up._state_of(prog.acct.data)
            if st != up.PROGRAM:
                return None
            pd_key = bytes(s["programdata_address"])
            pd = next((a for a in ctx.accounts if a.pubkey == pd_key), None)
            if pd is None or pd.acct is None:
                return None
            # owner check: after a close+reap, a system-owned impostor at
            # the same address could otherwise mimic the layout
            if pd.acct.owner != up.UPGRADEABLE_LOADER_ID:
                return None
            std, _ = up._state_of(pd.acct.data)
            if std != up.PROGRAMDATA:
                return None
            from . import bpf_loader
            from .types import Account
            # keep the zero padding: the ELF parser reads section headers,
            # trailing fill is inert (and a real ELF may end in zeros)
            elf = up.programdata_elf(pd.acct.data)
            shim = Account(data=elf, executable=True,
                           owner=up.UPGRADEABLE_LOADER_ID)
            return lambda ictx: bpf_loader.execute_program(ictx, shim)
        return None

    @staticmethod
    def _total_lamports(ctx: TxnCtx) -> int:
        return sum(a.acct.lamports for a in ctx.accounts if a.acct is not None)


def _compute_budget_noop(ictx):
    """Compute-budget instructions set limits parsed at pack time
    (ballet/pack.py _parse_compute_budget); at execution they are no-ops."""
