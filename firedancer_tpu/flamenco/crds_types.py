"""Agave-wire gossip protocol types (VERDICT r4 missing #2: "a genuine
CRDS stream contains types the repo cannot decode").

Bincode schemas for the full Solana gossip UDP surface — the message
enum, every CrdsData variant including varint/compact-framed contact-info
v2 — on the declarative engine (bincode.py).  Wire contracts follow the
public Solana gossip protocol as catalogued by the reference's generated
type layer (fd_types: crds_data, gossip_msg, gossip_contact_info_v2 et
al.); layouts are validated against REAL Agave-captured packets in
tests/golden/agave/ (tests/test_agave_wire_fixtures.py).

The internal gossip tile (flamenco/gossip.py) keeps its compact
framework-native framing for intra-framework clusters; this module is
the interop boundary for speaking to Agave/reference nodes and for
decoding captured gossip traffic.
"""

from __future__ import annotations

from . import bincode as bc
from .bincode import HASH, PUBKEY

SIGNATURE = ("bytes", 64)

# -- addresses --------------------------------------------------------------

IP_ADDR = ("enum", (                        # gossip_ip_addr
    ("ip4", ("bytes", 4)),
    ("ip6", ("bytes", 16)),
))

SOCKET_ADDR = ("struct", (                  # gossip_socket_addr
    ("addr", IP_ADDR),
    ("port", "u16"),
))

# -- CrdsData variants ------------------------------------------------------

CONTACT_INFO_V1 = ("struct", (              # gossip_contact_info_v1
    ("id", PUBKEY),
    ("gossip", SOCKET_ADDR),
    ("tvu", SOCKET_ADDR),
    ("tvu_fwd", SOCKET_ADDR),
    ("repair", SOCKET_ADDR),
    ("tpu", SOCKET_ADDR),
    ("tpu_fwd", SOCKET_ADDR),
    ("tpu_vote", SOCKET_ADDR),
    ("rpc", SOCKET_ADDR),
    ("rpc_pubsub", SOCKET_ADDR),
    ("serve_repair", SOCKET_ADDR),
    ("wallclock", "u64"),
    ("shred_version", "u16"),
))

VOTE = ("struct", (                         # gossip_vote
    ("index", "u8"),
    ("from", PUBKEY),
    ("txn", ("solana_txn",)),               # embedded wire transaction
    ("wallclock", "u64"),
))

LOWEST_SLOT = ("struct", (                  # gossip_lowest_slot
    ("index", "u8"),
    ("from", PUBKEY),
    ("root", "u64"),
    ("lowest", "u64"),
    ("slots", ("vec", "u64")),
    ("stash", "u64"),                       # deprecated EpochIncompleteSlots
    ("wallclock", "u64"),
))

SLOT_HASH = ("struct", (("slot", "u64"), ("hash", HASH)))

SLOT_HASHES = ("struct", (                  # gossip_slot_hashes
    ("from", PUBKEY),
    ("hashes", ("vec", SLOT_HASH)),
    ("wallclock", "u64"),
))

_VERSION_TAIL_V1 = (
    ("major", "u16"),
    ("minor", "u16"),
    ("patch", "u16"),
    ("commit", ("option", "u32")),
)

VERSION_V1 = ("struct", (                   # gossip_version_v1
    ("from", PUBKEY),
    ("wallclock", "u64"),
) + _VERSION_TAIL_V1)

VERSION_V2 = ("struct", (                   # gossip_version_v2
    ("from", PUBKEY),
    ("wallclock", "u64"),
) + _VERSION_TAIL_V1 + (
    ("feature_set", "u32"),
))

NODE_INSTANCE = ("struct", (                # gossip_node_instance
    ("from", PUBKEY),
    ("wallclock", "u64"),
    ("timestamp", "u64"),
    ("token", "u64"),
))

DUPLICATE_SHRED = ("struct", (              # gossip_duplicate_shred
    ("version", "u16"),
    ("from", PUBKEY),
    ("wallclock", "u64"),
    ("slot", "u64"),
    ("shred_index", "u32"),
    ("shred_variant", "u8"),
    ("chunk_cnt", "u8"),
    ("chunk_idx", "u8"),
    ("chunk", ("vec", "u8")),
))

INCREMENTAL_SNAPSHOT_HASHES = ("struct", (  # gossip_incremental_snapshot_…
    ("from", PUBKEY),
    ("base_hash", SLOT_HASH),
    ("hashes", ("vec", SLOT_HASH)),
    ("wallclock", "u64"),
))

VERSION_V3 = ("struct", (                   # gossip_version_v3 (varints)
    ("major", ("varint",)),
    ("minor", ("varint",)),
    ("patch", ("varint",)),
    ("commit", "u32"),
    ("feature_set", "u32"),
    ("client", ("varint",)),
))

SOCKET_ENTRY = ("struct", (                 # gossip_socket_entry
    ("key", "u8"),
    ("index", "u8"),
    ("offset", ("varint",)),
))

CONTACT_INFO_V2 = ("struct", (              # gossip_contact_info_v2
    ("from", PUBKEY),
    ("wallclock", ("varint",)),
    ("outset", "u64"),
    ("shred_version", "u16"),
    ("version", VERSION_V3),
    ("addrs", ("cvec", IP_ADDR)),
    ("sockets", ("cvec", SOCKET_ENTRY)),
    ("extensions", ("cvec", "u32")),
))

BITVEC_U8 = ("struct", (                    # gossip_bitvec_u8
    ("bits", ("option", ("vec", "u8"))),
    ("len", "u64"),
))

SLOTS_ENUM = ("enum", (                     # gossip_slots_enum
    ("flate2", ("struct", (
        ("first_slot", "u64"),
        ("num", "u64"),
        ("compressed", ("vec", "u8")),
    ))),
    ("uncompressed", ("struct", (
        ("first_slot", "u64"),
        ("num", "u64"),
        ("slots", BITVEC_U8),
    ))),
))

EPOCH_SLOTS = ("struct", (                  # gossip_epoch_slots
    ("index", "u8"),
    ("from", PUBKEY),
    ("slots", ("vec", SLOTS_ENUM)),
    ("wallclock", "u64"),
))

CRDS_DATA = ("enum", (                      # crds_data (variant order is
    ("contact_info_v1", CONTACT_INFO_V1),   # the wire contract)
    ("vote", VOTE),
    ("lowest_slot", LOWEST_SLOT),
    ("snapshot_hashes", SLOT_HASHES),
    ("accounts_hashes", SLOT_HASHES),
    ("epoch_slots", EPOCH_SLOTS),
    ("version_v1", VERSION_V1),
    ("version_v2", VERSION_V2),
    ("node_instance", NODE_INSTANCE),
    ("duplicate_shred", DUPLICATE_SHRED),
    ("incremental_snapshot_hashes", INCREMENTAL_SNAPSHOT_HASHES),
    ("contact_info_v2", CONTACT_INFO_V2),
))

CRDS_VALUE = ("struct", (
    ("signature", SIGNATURE),
    ("data", CRDS_DATA),
))

# -- protocol messages ------------------------------------------------------

BITVEC_U64 = ("struct", (                   # gossip_bitvec_u64
    ("bits", ("option", ("vec", "u64"))),
    ("len", "u64"),
))

CRDS_BLOOM = ("struct", (
    ("keys", ("vec", "u64")),
    ("bits", BITVEC_U64),
    ("num_bits_set", "u64"),
))

CRDS_FILTER = ("struct", (
    ("filter", CRDS_BLOOM),
    ("mask", "u64"),
    ("mask_bits", "u32"),
))

PING = ("struct", (
    ("from", PUBKEY),
    ("token", HASH),
    ("signature", SIGNATURE),
))

PRUNE_DATA = ("struct", (
    ("pubkey", PUBKEY),
    ("prunes", ("vec", PUBKEY)),
    ("signature", SIGNATURE),
    ("destination", PUBKEY),
    ("wallclock", "u64"),
))

GOSSIP_MSG = ("enum", (                     # gossip_msg
    ("pull_req", ("struct", (
        ("filter", CRDS_FILTER),
        ("value", CRDS_VALUE),
    ))),
    ("pull_resp", ("struct", (
        ("pubkey", PUBKEY),
        ("crds", ("vec", CRDS_VALUE)),
    ))),
    ("push_msg", ("struct", (
        ("pubkey", PUBKEY),
        ("crds", ("vec", CRDS_VALUE)),
    ))),
    ("prune_msg", ("struct", (
        ("pubkey", PUBKEY),
        ("data", PRUNE_DATA),
    ))),
    ("ping", PING),
    ("pong", PING),
))


def decode_msg(raw: bytes) -> tuple:
    """One gossip UDP payload -> (variant_name, value)."""
    return bc.loads(GOSSIP_MSG, raw)


def encode_msg(variant: str, value) -> bytes:
    return bc.encode(GOSSIP_MSG, (variant, value))
