"""Blockstore: shred accumulation -> complete slots (ref:
src/flamenco/runtime/fd_blockstore.c — theirs archives to RocksDB; ours is
an in-memory slot map with FEC-set recovery and bounded retention, the shape
the store tile and replay need).

Shreds arrive out of order and possibly incomplete; each slot tracks its
FEC sets through ballet.shred.FecResolver, which erasure-recovers a set as
soon as any data_cnt of its data+code shreds are present.  When every FEC
set of a slot is complete and the slot-complete flag was seen, the slot's
entry batch bytes are assembled in shred-index order.
"""

from dataclasses import dataclass, field

from ..ballet import shred as shred_lib
from ..ballet import entry as entry_lib


@dataclass
class _SlotMeta:
    resolvers: dict[int, shred_lib.FecResolver] = field(default_factory=dict)
    complete_sets: dict[int, bytes] = field(default_factory=dict)
    set_data_cnt: dict[int, int] = field(default_factory=dict)
    last_set_idx: int | None = None  # fec_set_idx of the slot-complete set
    parent_off: int = 0
    assembled: bytes | None = None
    raw: dict[int, bytes] = field(default_factory=dict)  # data idx -> shred


class Blockstore:
    def __init__(self, max_slots: int = 1024):
        self.max_slots = max_slots
        self.slots: dict[int, _SlotMeta] = {}
        self.shred_cnt = 0
        self.recovered_cnt = 0

    def insert_shred(self, raw: bytes) -> bool:
        """Insert one serialized shred; returns True if it completed a FEC
        set.  Invalid shreds raise ShredParseError."""
        s = shred_lib.parse(raw)
        self.shred_cnt += 1
        sm = self.slots.get(s.slot)
        if sm is None:
            if (len(self.slots) >= self.max_slots
                    and s.slot < min(self.slots)):
                return False  # older than the retention window: drop, do
                # not evict a newer slot for it (and never evict the slot
                # we are mid-insert into)
            sm = self.slots[s.slot] = _SlotMeta()
            self._evict()
        if s.fec_set_idx in sm.complete_sets:
            return False
        if s.is_data:
            sm.parent_off = s.parent_off
            sm.raw[s.idx] = raw  # retained to serve repair requests
            if s.flags & shred_lib.FLAG_SLOT_COMPLETE:
                sm.last_set_idx = s.fec_set_idx
        res = sm.resolvers.get(s.fec_set_idx)
        if res is None:
            res = sm.resolvers[s.fec_set_idx] = shred_lib.FecResolver()
        res.add(s)
        if res.ready():
            sm.complete_sets[s.fec_set_idx] = res.payloads()
            sm.set_data_cnt[s.fec_set_idx] = res.data_cnt
            del sm.resolvers[s.fec_set_idx]
            self.recovered_cnt += 1
            return True
        return False

    def slot_complete(self, slot: int) -> bool:
        sm = self.slots.get(slot)
        if sm is None or sm.last_set_idx is None:
            return False
        # every fec set from 0 to last_set_idx must be recovered WITH no
        # gap: set ids are cumulative data counts, so the next set's id
        # must be exactly want + data_cnt(want) — accepting any later
        # present id would silently assemble a block with a hole in it
        want = 0
        while want <= sm.last_set_idx:
            if want not in sm.complete_sets:
                return False
            if want == sm.last_set_idx:
                return True
            want = want + sm.set_data_cnt[want]
        return False  # inconsistent set geometry walked past the end

    def slot_data(self, slot: int) -> bytes | None:
        """Concatenated entry-batch bytes for a complete slot, else None."""
        sm = self.slots.get(slot)
        if not self.slot_complete(slot):
            return None
        if sm.assembled is None:
            sm.assembled = b"".join(
                sm.complete_sets[i] for i in sorted(sm.complete_sets))
        return sm.assembled

    def slot_entries(self, slot: int) -> list[entry_lib.Entry] | None:
        data = self.slot_data(slot)
        if data is None:
            return None
        try:
            return entry_lib.deserialize_batch(data)
        except ValueError:
            # signature-valid shreds carrying a corrupt entry stream: the
            # block is garbage but must not kill the replay tile
            return None

    # -- repair serving (fd_repair's read side) -------------------------
    def shred_raw(self, slot: int, idx: int) -> bytes | None:
        sm = self.slots.get(slot)
        return None if sm is None else sm.raw.get(idx)

    def highest_shred(self, slot: int) -> tuple[int, bytes] | None:
        sm = self.slots.get(slot)
        if sm is None or not sm.raw:
            return None
        hi = max(sm.raw)
        return hi, sm.raw[hi]

    def missing_indices(self, slot: int, upto: int) -> list[int]:
        """Data shred indices not yet present in [0, upto] — what the
        repair client should request."""
        sm = self.slots.get(slot)
        have = sm.raw.keys() if sm else ()
        return [i for i in range(upto + 1) if i not in have]

    def _evict(self):
        while len(self.slots) > self.max_slots:
            del self.slots[min(self.slots)]
