"""Blockstore: shred accumulation -> complete slots, with a disk archive
(ref: src/flamenco/runtime/fd_blockstore.c — hot slots in memory, the
long tail archived; theirs archives to RocksDB, ours to an append-only
indexed slot file, SlotArchive).

Shreds arrive out of order and possibly incomplete; each slot tracks its
FEC sets through ballet.shred.FecResolver, which erasure-recovers a set as
soon as any data_cnt of its data+code shreds are present.  When every FEC
set of a slot is complete and the slot-complete flag was seen, the slot's
entry batch bytes are assembled in shred-index order (and, when an archive
is attached, persisted so eviction never loses a completed block).
"""

import os
import struct
from dataclasses import dataclass, field

from ..ballet import shred as shred_lib
from ..ballet import entry as entry_lib


class SlotArchive:
    """Append-only indexed archive of completed slots (the fd_blockstore
    RocksDB role: fd_blockstore archives rooted blocks and serves
    historical reads).  File format:

        magic "FDAR" | u32 version
        record := u64 slot | u64 parent | u32 len | entry-batch bytes

    The in-memory index (slot -> file offset) rebuilds by a single scan at
    open; duplicate appends of a slot keep the FIRST record (a completed
    block is immutable — a differing duplicate indicates equivocation and
    is ignored here, the fork-choice layer's problem)."""

    _MAGIC = b"FDAR"
    _VERSION = 1
    _HDR = struct.Struct("<4sI")
    _REC = struct.Struct("<QQI")

    def __init__(self, path: str):
        self.path = path
        self._index: dict[int, tuple[int, int, int]] = {}  # slot->(off,len,parent)
        exists = os.path.exists(path) and os.path.getsize(path) > 0
        self._f = open(path, "a+b")
        if not exists:
            self._f.write(self._HDR.pack(self._MAGIC, self._VERSION))
            self._f.flush()
        else:
            self._scan()

    def _scan(self):
        size = os.fstat(self._f.fileno()).st_size
        self._f.seek(0)
        hdr = self._f.read(self._HDR.size)
        if len(hdr) < self._HDR.size:
            raise ValueError(f"{self.path}: not a slot archive (truncated)")
        magic, ver = self._HDR.unpack(hdr)
        if magic != self._MAGIC or ver != self._VERSION:
            raise ValueError(f"{self.path}: not a slot archive")
        pos = self._HDR.size
        while True:
            self._f.seek(pos)
            rec = self._f.read(self._REC.size)
            if len(rec) < self._REC.size:
                break
            slot, parent, ln = self._REC.unpack(rec)
            data_off = pos + self._REC.size
            if data_off + ln > size:
                break  # torn final record from a crashed writer: seeking
                # past EOF "succeeds", so truncation must be checked
                # against the real file size, never via tell()
            self._index.setdefault(slot, (data_off, ln, parent))
            pos = data_off + ln
        # append AFTER the last intact record: a torn tail is overwritten,
        # never left embedded inside a later record's claimed extent
        self._f.truncate(pos)
        self._f.seek(0, 2)

    def put(self, slot: int, parent: int, data: bytes):
        if slot in self._index:
            return
        self._f.seek(0, 2)
        pos = self._f.tell()
        self._f.write(self._REC.pack(slot, parent, len(data)))
        self._f.write(data)
        self._f.flush()
        self._index[slot] = (pos + self._REC.size, len(data), parent)

    def get(self, slot: int) -> bytes | None:
        ent = self._index.get(slot)
        if ent is None:
            return None
        off, ln, _ = ent
        self._f.seek(off)
        return self._f.read(ln)

    def parent(self, slot: int) -> int | None:
        ent = self._index.get(slot)
        return None if ent is None else ent[2]

    def slots(self) -> list[int]:
        return sorted(self._index)

    def __contains__(self, slot: int) -> bool:
        return slot in self._index

    def close(self):
        self._f.close()


@dataclass
class _SlotMeta:
    resolvers: dict[int, shred_lib.FecResolver] = field(default_factory=dict)
    complete_sets: dict[int, bytes] = field(default_factory=dict)
    set_data_cnt: dict[int, int] = field(default_factory=dict)
    last_set_idx: int | None = None  # fec_set_idx of the slot-complete set
    parent_off: int = 0
    assembled: bytes | None = None
    raw: dict[int, bytes] = field(default_factory=dict)  # data idx -> shred


class Blockstore:
    def __init__(self, max_slots: int = 1024,
                 archive: SlotArchive | None = None,
                 root_check=None):
        """root_check(slot, root32, signature) -> bool: leader-signature
        gate applied to EVERY shred at the door, before any bookkeeping
        (fd_fec_resolver.c verifies the sig before admitting a set).
        Without it a single self-consistent bogus shred reaching
        insert_shred pins its root as the set's first member and blocks
        every honest shred of that set (ADVICE r4) — and could store raw
        bytes, pin last_set_idx, or evict honest slots even when a later
        resolver-level check rejected it.  None = callers signature-check
        shreds before insert (the turbine tile's shape)."""
        self.max_slots = max_slots
        self.archive = archive
        self.root_check = root_check
        self.slots: dict[int, _SlotMeta] = {}
        self.shred_cnt = 0
        self.recovered_cnt = 0
        self.sig_reject_cnt = 0

    def insert_shred(self, raw: bytes, parsed=None,
                     pre_verified: bool = False) -> bool:
        """Insert one serialized shred; returns True if it completed a FEC
        set.  Invalid shreds raise ShredParseError.  `parsed` skips the
        re-parse when the caller already holds the Shred (hot tile paths
        parse once for routing/verification).  pre_verified=True attests
        the caller already ran the leader-signature gate on THIS shred
        (turbine/repair ingress paths) — the door check below is skipped
        so validated hot paths don't pay a second ~100 ms synchronous
        device verify per shred."""
        s = parsed if parsed is not None else shred_lib.parse(raw)
        self.shred_cnt += 1
        if self.root_check is not None and not pre_verified:
            # gate at the DOOR: a rejected shred must not create slot
            # metadata, store servable raw bytes, pin last_set_idx, or
            # trigger eviction (code-review r5: the resolver-level check
            # ran after that bookkeeping had already committed)
            root = s.merkle_root()
            if root is None or not self.root_check(s.slot, root,
                                                   s.signature):
                self.sig_reject_cnt += 1
                return False
        sm = self.slots.get(s.slot)
        if sm is None:
            if (len(self.slots) >= self.max_slots
                    and s.slot < min(self.slots)):
                return False  # older than the retention window: drop, do
                # not evict a newer slot for it (and never evict the slot
                # we are mid-insert into)
            sm = self.slots[s.slot] = _SlotMeta()
            self._evict()
        if s.is_data:
            # record data-shred bookkeeping BEFORE the already-complete
            # dedup: the FLAG_SLOT_COMPLETE shred may arrive after its set
            # was erasure-recovered, and dropping the flag would leave the
            # slot permanently "incomplete" (and never archived)
            sm.parent_off = s.parent_off
            sm.raw[s.idx] = raw  # retained to serve repair requests
            if s.flags & shred_lib.FLAG_SLOT_COMPLETE:
                sm.last_set_idx = s.fec_set_idx
        if s.fec_set_idx in sm.complete_sets:
            if (self.archive is not None and s.slot not in self.archive
                    and self.slot_complete(s.slot)):
                self.slot_data(s.slot)  # late flag: persist now
            return False
        res = sm.resolvers.get(s.fec_set_idx)
        if res is None:
            # no resolver-level root_check: the door gate above already
            # leader-verified this shred, and the resolver's root-agreement
            # rule handles cross-member consistency
            res = sm.resolvers[s.fec_set_idx] = shred_lib.FecResolver()
        res.add(s)
        if res.ready():
            sm.complete_sets[s.fec_set_idx] = res.payloads()
            sm.set_data_cnt[s.fec_set_idx] = res.resolved_data_cnt
            del sm.resolvers[s.fec_set_idx]
            self.recovered_cnt += 1
            if self.archive is not None and self.slot_complete(s.slot):
                self.slot_data(s.slot)  # assemble + persist pre-eviction
            return True
        return False

    def slot_complete(self, slot: int) -> bool:
        sm = self.slots.get(slot)
        if sm is None or sm.last_set_idx is None:
            return False
        # every fec set from 0 to last_set_idx must be recovered WITH no
        # gap: set ids are cumulative data counts, so the next set's id
        # must be exactly want + data_cnt(want) — accepting any later
        # present id would silently assemble a block with a hole in it
        want = 0
        while want <= sm.last_set_idx:
            if want not in sm.complete_sets:
                return False
            if want == sm.last_set_idx:
                return True
            want = want + sm.set_data_cnt[want]
        return False  # inconsistent set geometry walked past the end

    def slot_data(self, slot: int) -> bytes | None:
        """Concatenated entry-batch bytes for a complete slot, else None.
        Evicted-but-archived slots are served from the SlotArchive (the
        RocksDB historical-read path, fd_blockstore archival reads)."""
        sm = self.slots.get(slot)
        if not self.slot_complete(slot):
            if self.archive is not None:
                return self.archive.get(slot)
            return None
        if sm.assembled is None:
            sm.assembled = b"".join(
                sm.complete_sets[i] for i in sorted(sm.complete_sets))
            if self.archive is not None:
                self.archive.put(slot, slot - sm.parent_off, sm.assembled)
        return sm.assembled

    def slot_entries(self, slot: int) -> list[entry_lib.Entry] | None:
        data = self.slot_data(slot)
        if data is None:
            return None
        try:
            return entry_lib.deserialize_batch(data)
        except ValueError:
            # signature-valid shreds carrying a corrupt entry stream: the
            # block is garbage but must not kill the replay tile
            return None

    # -- repair serving (fd_repair's read side) -------------------------
    def shred_raw(self, slot: int, idx: int) -> bytes | None:
        sm = self.slots.get(slot)
        return None if sm is None else sm.raw.get(idx)

    def parent_slot(self, slot: int) -> int | None:
        """slot's parent per its data shreds' parent_off (fd_blockstore
        tracks this in the slot meta); archived slots answer from the
        archive record."""
        sm = self.slots.get(slot)
        if sm is not None and sm.parent_off:
            return slot - sm.parent_off
        if self.archive is not None:
            return self.archive.parent(slot)
        return None

    def highest_shred(self, slot: int) -> tuple[int, bytes] | None:
        sm = self.slots.get(slot)
        if sm is None or not sm.raw:
            return None
        hi = max(sm.raw)
        return hi, sm.raw[hi]

    def missing_indices(self, slot: int, upto: int) -> list[int]:
        """Data shred indices not yet present in [0, upto] — what the
        repair client should request."""
        sm = self.slots.get(slot)
        have = sm.raw.keys() if sm else ()
        return [i for i in range(upto + 1) if i not in have]

    def _evict(self):
        while len(self.slots) > self.max_slots:
            del self.slots[min(self.slots)]
