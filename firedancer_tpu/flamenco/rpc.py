"""Minimal JSON-RPC service + client (ref: the reference's dev-tooling RPC
client src/app/fddev/rpc_client/fd_rpc_client.c, and the Agave RPC surface
Frankendancer delegates to — run_solana.c boots Agave's RPC; full
Firedancer serves its own).

Serves the small method set the dev tools and tests need, straight off the
bank tile's runtime:

  getHealth, getSlot, getBlockHeight, getLatestBlockhash, getBalance,
  getTransactionCount, sendTransaction (base64 wire txn -> ingest queue)

Thread model: the HTTP server runs on daemon threads inside the bank
tile's process; reads snapshot runtime state (GIL-atomic dict/int reads —
dev RPC, not a consensus surface), writes go through a thread-safe queue
the tile drains in its housekeeping callback (the reference's RPC->TPU
forwarding path)."""

from __future__ import annotations

import base64
import json
import queue
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class RpcError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


class RpcServer:
    """JSON-RPC 2.0 over HTTP POST.

    provider must expose: slot() -> int, blockhash() -> bytes,
    balance(pubkey: bytes) -> int, txn_count() -> int.
    Submitted txns land in .txn_queue (drained by the owning tile)."""

    def __init__(self, provider, port: int = 0, host: str = "127.0.0.1"):
        self.provider = provider
        self.txn_queue: queue.Queue[bytes] = queue.Queue(maxsize=4096)
        srv = self

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n))
                    resp = srv._dispatch(req)
                except Exception as e:  # malformed request envelope
                    resp = {"jsonrpc": "2.0", "id": None,
                            "error": {"code": -32700, "message": str(e)}}
                body = json.dumps(resp).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.httpd = ThreadingHTTPServer((host, port), H)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self._thread.start()

    def _dispatch(self, req: dict) -> dict:
        rid = req.get("id")
        method = req.get("method")
        params = req.get("params") or []
        try:
            result = self._call(method, params)
            return {"jsonrpc": "2.0", "id": rid, "result": result}
        except RpcError as e:
            return {"jsonrpc": "2.0", "id": rid,
                    "error": {"code": e.code, "message": str(e)}}
        except Exception as e:
            return {"jsonrpc": "2.0", "id": rid,
                    "error": {"code": -32603, "message": str(e)}}

    def _call(self, method: str, params: list):
        p = self.provider
        if method == "getHealth":
            return "ok"
        if method == "getSlot" or method == "getBlockHeight":
            return int(p.slot())
        if method == "getLatestBlockhash":
            return {"blockhash": p.blockhash().hex(),
                    "lastValidBlockHeight": int(p.slot()) + 150}
        if method == "getBalance":
            if not params:
                raise RpcError(-32602, "getBalance needs a pubkey")
            pk = bytes.fromhex(params[0])
            return {"value": int(p.balance(pk))}
        if method == "getTransactionCount":
            return int(p.txn_count())
        if method == "sendTransaction":
            if not params:
                raise RpcError(-32602, "sendTransaction needs a txn")
            raw = base64.b64decode(params[0])
            try:
                self.txn_queue.put_nowait(raw)
            except queue.Full:
                raise RpcError(-32005, "transaction queue full") from None
            return raw[1:65].hex() if len(raw) >= 65 else ""
        raise RpcError(-32601, f"method not found: {method}")

    def drain(self, max_n: int = 256) -> list[bytes]:
        """Collect queued txns (called from the owning tile's loop)."""
        out = []
        while len(out) < max_n:
            try:
                out.append(self.txn_queue.get_nowait())
            except queue.Empty:
                break
        return out

    def close(self):
        self.httpd.shutdown()


class RpcClient:
    """Blocking JSON-RPC client (fd_rpc_client role)."""

    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url
        self.timeout = timeout
        self._id = 0

    def call(self, method: str, params: list | None = None):
        self._id += 1
        body = json.dumps({
            "jsonrpc": "2.0", "id": self._id,
            "method": method, "params": params or [],
        }).encode()
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            resp = json.loads(r.read())
        if "error" in resp and resp["error"]:
            raise RpcError(resp["error"].get("code", -1),
                           resp["error"].get("message", "rpc error"))
        return resp["result"]

    def get_health(self) -> str:
        return self.call("getHealth")

    def get_slot(self) -> int:
        return self.call("getSlot")

    def get_latest_blockhash(self) -> bytes:
        return bytes.fromhex(self.call("getLatestBlockhash")["blockhash"])

    def get_balance(self, pubkey: bytes) -> int:
        return self.call("getBalance", [pubkey.hex()])["value"]

    def get_transaction_count(self) -> int:
        return self.call("getTransactionCount")

    def send_transaction(self, raw_txn: bytes) -> str:
        return self.call(
            "sendTransaction", [base64.b64encode(raw_txn).decode()])
