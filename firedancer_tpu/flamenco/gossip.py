"""Gossip: the CRDS cluster-info protocol (ref: src/flamenco/gossip/
fd_gossip.c — push/pull over UDP carrying signed CRDS values).

Structure kept from the reference: a CRDS table of signed, timestamped
values keyed by (kind, origin pubkey) with newest-wins upserts; PUSH
messages proactively flood fresh values to fanout peers; PULL requests
carry a digest filter and the responder returns values the requester is
missing.  Wire format is our own compact LE (a fresh chain; confined to
this module); signatures are real ed25519 over the value payload.

    value:  sig[64] | origin[32] | u8 kind | u64 wallclock_ms | u16 len | body
    msg:    u8 type (0 PUSH, 1 PULL_REQ, 2 PULL_RESP) | u16 count | values
            (PULL_REQ: count==n_digests, body is 8-byte value digests)

Kinds: CONTACT_INFO (body = ip[4] | u16 gossip_port | u16 tpu_port |
u16 repair_port), VOTE (body = serialized vote txn), LOWEST_SLOT
(body = u64), EPOCH_SLOTS (u64 first | bitmap), SNAPSHOT_HASHES
(u64 slot | hash[32]), VERSION (u16 major | u16 minor | u16 patch).

Liveness + flood control (the fd_gossip active-set machinery):

  * PING/PONG: a peer's contact is only pushed to after it echoes a
    signed hash of our random token (fd_gossip ping tokens) — spoofed
    contact-info cannot attract push floods.
  * PRUNE: a receiver that keeps seeing an origin's values duplicated
    from a sender tells that sender to stop pushing that origin
    (fd_gossip prune messages); pushers honor per-peer prune sets.
  * Push carries only FRESH values (a pending queue), not full tables;
    full sync rides the pull digest exchange.
"""

import hashlib
import struct
import time
from dataclasses import dataclass

KIND_CONTACT_INFO = 0
KIND_VOTE = 1
KIND_LOWEST_SLOT = 2
KIND_EPOCH_SLOTS = 3
KIND_SNAPSHOT_HASHES = 4
KIND_VERSION = 5
KIND_DUPLICATE_SHRED = 6   # evidence of equivocation: two conflicting
                           # shreds for one (slot, index) — ref
                           # fd_crds_value duplicate_shred
KIND_SIG_DIGEST = 7        # fleet control ring (round 17): a host's
                           # recently-verdicted sig tags for one tcache
                           # shard — exact u64 tags for the newest chunk
                           # plus a Bloom over them, so failover hosts
                           # reject already-verified sigs

MSG_PUSH = 0
MSG_PULL_REQ = 1
MSG_PULL_RESP = 2
MSG_PING = 3
MSG_PONG = 4
MSG_PRUNE = 5
MSG_PULL_REQ_BLOOM = 6

VALUE_HDR = struct.Struct("<64s32sBQH")


@dataclass(frozen=True)
class CrdsValue:
    signature: bytes      # 64B over origin|kind|wallclock|body
    origin: bytes         # 32B pubkey
    kind: int
    wallclock_ms: int
    body: bytes

    def signable(self) -> bytes:
        return (self.origin + bytes([self.kind])
                + struct.pack("<Q", self.wallclock_ms) + self.body)

    def key(self) -> tuple:
        # newest-wins per (kind, origin) — EXCEPT duplicate-shred proofs,
        # which are per-(slot, index) evidence: a node must be able to
        # advertise many (ref keys duplicate_shred per origin+index)
        if self.kind == KIND_DUPLICATE_SHRED:
            return (self.kind, self.origin, bytes(self.body[:12]))
        if self.kind == KIND_SIG_DIGEST:
            # per-(shard, chunk) — a host advertises a rolling window of
            # digest chunks per shard; newest-wins only within one chunk
            return (self.kind, self.origin, bytes(self.body[:8]))
        return (self.kind, self.origin)

    def digest(self) -> bytes:
        return hashlib.sha256(self.serialize()).digest()[:8]

    def serialize(self) -> bytes:
        return VALUE_HDR.pack(self.signature, self.origin, self.kind,
                              self.wallclock_ms, len(self.body)) + self.body

    @classmethod
    def deserialize(cls, buf: bytes, off: int = 0) -> tuple["CrdsValue", int]:
        sig, origin, kind, wc, ln = VALUE_HDR.unpack_from(buf, off)
        off += VALUE_HDR.size
        body = bytes(buf[off : off + ln])
        if len(body) != ln:
            raise ValueError("truncated crds value")
        return cls(sig, origin, kind, wc, body), off + ln


def make_value(sign_fn, origin: bytes, kind: int, body: bytes,
               wallclock_ms: int | None = None) -> CrdsValue:
    wc = int(time.time() * 1000) if wallclock_ms is None else wallclock_ms
    v = CrdsValue(bytes(64), origin, kind, wc, body)
    return CrdsValue(sign_fn(v.signable()), origin, kind, wc, body)


def contact_info_body(ip: str, gossip_port: int, tpu_port: int,
                      repair_port: int) -> bytes:
    import socket
    return (socket.inet_aton(ip)
            + struct.pack("<HHH", gossip_port, tpu_port, repair_port))


def contact_info_parse(body: bytes) -> tuple[str, int, int, int]:
    import socket
    ip = socket.inet_ntoa(body[:4])
    g, t, r = struct.unpack_from("<HHH", body, 4)
    return ip, g, t, r


class Crds:
    """The replicated data store (fd_crds): (kind, origin) -> newest value,
    with verify-on-insert."""

    def __init__(self, verify_fn, max_age_ms: int = 60_000):
        self.table: dict[tuple, CrdsValue] = {}
        self.verify_fn = verify_fn    # (sig, msg, pubkey) -> bool
        self.max_age_ms = max_age_ms

    def upsert(self, v: CrdsValue, now_ms: int | None = None) -> bool:
        """Returns True if the value was fresh (new key or newer clock)."""
        now = int(time.time() * 1000) if now_ms is None else now_ms
        if abs(now - v.wallclock_ms) > self.max_age_ms:
            return False
        cur = self.table.get(v.key())
        if cur is not None and cur.wallclock_ms >= v.wallclock_ms:
            return False
        if not self.verify_fn(v.signature, v.signable(), v.origin):
            return False
        self.table[v.key()] = v
        return True

    def purge(self, now_ms: int | None = None):
        """Drop values past max_age (the fd_crds expiration sweep)."""
        now = int(time.time() * 1000) if now_ms is None else now_ms
        dead = [k for k, v in self.table.items()
                if now - v.wallclock_ms > self.max_age_ms]
        for k in dead:
            del self.table[k]

    def values(self) -> list[CrdsValue]:
        return list(self.table.values())

    def digests(self) -> set[bytes]:
        return {v.digest() for v in self.table.values()}

    def missing_for(self, digests: set[bytes]) -> list[CrdsValue]:
        return [v for v in self.table.values() if v.digest() not in digests]

    def peers(self) -> list[tuple[bytes, tuple[str, int, int, int]]]:
        """(pubkey, (ip, gossip, tpu, repair)) for every known contact."""
        out = []
        # keys are (kind, origin) or (kind, origin, disc) — duplicate-shred
        # and sig-digest values carry a per-chunk discriminator
        for k, v in self.table.items():
            if k[0] == KIND_CONTACT_INFO:
                out.append((k[1], contact_info_parse(v.body)))
        return out


def duplicate_shred_body(slot: int, index: int, shred_a: bytes,
                         shred_b: bytes) -> bytes:
    """Equivocation proof payload: two conflicting shreds for one
    (slot, index) (ref gossip duplicate_shred values — chunked there for
    MTU; our values carry a u16-length pair)."""
    return (struct.pack("<QIHH", slot, index, len(shred_a), len(shred_b))
            + shred_a + shred_b)


def duplicate_shred_parse(body: bytes):
    slot, index, la, lb = struct.unpack_from("<QIHH", body, 0)
    off = 16
    a = body[off : off + la]
    b = body[off + la : off + la + lb]
    if len(a) != la or len(b) != lb:
        raise ValueError("short duplicate-shred body")
    return slot, index, bytes(a), bytes(b)


class CrdsBloom:
    """Bloom filter over value digests for pull requests (role of the
    reference's fd_crds bloom / CrdsFilter): the requester sends what it
    HAS as a compact filter; the responder returns values that miss.

    k indices are carved from the digest itself (digests are already
    uniform sha256 prefixes), so the filter needs no extra hashing.
    mask_bits/mask partition the digest space like CrdsFilter: a filter
    only covers digests whose top mask_bits equal mask."""

    K = 3

    def __init__(self, m_bits: int, mask_bits: int = 0, mask: int = 0,
                 seed: int = 0):
        assert m_bits and m_bits & (m_bits - 1) == 0, "m_bits power of two"
        self.m_bits = m_bits
        self.mask_bits = mask_bits
        self.mask = mask
        # per-filter salt: false positives must vary between pull rounds
        # or a colliding value could never converge (the reference salts
        # each CrdsFilter's hash keys the same way)
        self.seed = seed & 0xFFFFFFFFFFFFFFFF
        self.bits = bytearray(m_bits // 8)

    @classmethod
    def sized_for(cls, n_items: int, mask_bits: int = 0, mask: int = 0,
                  rng=None):
        # ~10 bits/item keeps false positives ~1% at k=3
        import random
        m = 64
        while m < max(64, 10 * n_items):
            m <<= 1
        seed = (rng or random).getrandbits(64)
        return cls(m, mask_bits, mask, seed)

    def covers(self, digest: bytes) -> bool:
        if not self.mask_bits:
            return True
        top = int.from_bytes(digest[:8], "big") >> (64 - self.mask_bits)
        return top == self.mask

    def _idx(self, digest: bytes):
        v = int.from_bytes(digest[:8], "little") ^ self.seed
        v = (v * 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF  # mix the salt
        for i in range(self.K):
            yield (v >> (16 * i)) % self.m_bits

    def add(self, digest: bytes):
        for ix in self._idx(digest):
            self.bits[ix >> 3] |= 1 << (ix & 7)

    def __contains__(self, digest: bytes) -> bool:
        return all(self.bits[ix >> 3] & (1 << (ix & 7))
                   for ix in self._idx(digest))

    def serialize(self) -> bytes:
        return (struct.pack("<IBxxxQQ", self.m_bits, self.mask_bits,
                            self.mask, self.seed) + bytes(self.bits))

    @classmethod
    def deserialize(cls, raw: bytes) -> "CrdsBloom":
        m_bits, mask_bits, mask, seed = struct.unpack_from("<IBxxxQQ", raw, 0)
        if not (64 <= m_bits <= 1 << 24) or m_bits & (m_bits - 1):
            raise ValueError("bad bloom size")
        f = cls(m_bits, mask_bits, mask, seed)
        body = raw[24 : 24 + m_bits // 8]
        if len(body) != m_bits // 8:
            raise ValueError("short bloom")
        f.bits = bytearray(body)
        return f


# -- wire messages -----------------------------------------------------------

def encode_push(values: list[CrdsValue]) -> bytes:
    out = bytearray(struct.pack("<BH", MSG_PUSH, len(values)))
    for v in values:
        out += v.serialize()
    return bytes(out)


def encode_pull_req(digests: set[bytes]) -> bytes:
    ds = sorted(digests)
    return (struct.pack("<BH", MSG_PULL_REQ, len(ds)) + b"".join(ds))


def encode_pull_req_bloom(f: CrdsBloom) -> bytes:
    return struct.pack("<BH", MSG_PULL_REQ_BLOOM, 0) + f.serialize()


def encode_pull_resp(values: list[CrdsValue]) -> bytes:
    out = bytearray(struct.pack("<BH", MSG_PULL_RESP, len(values)))
    for v in values:
        out += v.serialize()
    return bytes(out)


def encode_ping(from_pub: bytes, token: bytes, sig: bytes) -> bytes:
    return struct.pack("<BH", MSG_PING, 0) + from_pub + token + sig


def encode_pong(from_pub: bytes, token_hash: bytes, sig: bytes) -> bytes:
    return struct.pack("<BH", MSG_PONG, 0) + from_pub + token_hash + sig


def encode_prune(from_pub: bytes, origins: list[bytes], sig: bytes) -> bytes:
    return (struct.pack("<BH", MSG_PRUNE, len(origins)) + from_pub
            + b"".join(origins) + sig)


def decode(buf: bytes):
    """-> (msg_type, values | digest-set | raw-body tuple)."""
    mtype, cnt = struct.unpack_from("<BH", buf, 0)
    off = 3
    if mtype == MSG_PULL_REQ:
        ds = set()
        for i in range(cnt):
            ds.add(bytes(buf[off : off + 8]))
            off += 8
        return mtype, ds
    if mtype == MSG_PULL_REQ_BLOOM:
        return mtype, CrdsBloom.deserialize(bytes(buf[off:]))
    if mtype in (MSG_PING, MSG_PONG):
        frm = bytes(buf[off:off + 32])
        payload = bytes(buf[off + 32:off + 64])
        sig = bytes(buf[off + 64:off + 128])
        if len(frm) != 32 or len(payload) != 32 or len(sig) != 64:
            raise ValueError("short ping/pong")
        return mtype, (frm, payload, sig)
    if mtype == MSG_PRUNE:
        frm = bytes(buf[off:off + 32])
        off += 32
        origins = []
        for _ in range(cnt):
            o = bytes(buf[off:off + 32])
            if len(o) != 32:
                raise ValueError("short prune origin")
            origins.append(o)
            off += 32
        sig = bytes(buf[off:off + 64])
        if len(frm) != 32 or len(sig) != 64:
            raise ValueError("short prune")
        return mtype, (frm, origins, sig)
    vals = []
    for _ in range(cnt):
        v, off = CrdsValue.deserialize(buf, off)
        vals.append(v)
    return mtype, vals


class GossipNode:
    """Protocol engine over an injected packet interface (testable without
    sockets; the gossip tile wires it to waltz UDP).  fd_gossip's loop:
    periodic push of own values + pull exchange with random peers."""

    PUSH_FANOUT = 6
    PRUNE_DUP_THRESHOLD = 3  # duplicate pushes of an origin before pruning
    BLOOM_PULL_THRESHOLD = 64  # above this table size, pull via bloom

    def __init__(self, identity_pub: bytes, sign_fn, verify_fn,
                 contact_body: bytes, rng=None):
        import random
        self.identity = identity_pub
        self.sign_fn = sign_fn
        self.verify_fn = verify_fn
        self.crds = Crds(verify_fn)
        self.contact_body = contact_body
        self.rng = rng or random.Random()
        # liveness: peers answer a signed token before receiving pushes
        self._ping_tokens: dict[bytes, bytes] = {}   # peer pub -> token
        self._validated: set[bytes] = set()          # peer pubs that ponged
        # flood control
        self._pending_push: list[CrdsValue] = []     # fresh values to flood
        self._pruned_by: dict[bytes, set[bytes]] = {}  # peer -> origins
        self._dup_seen: dict[tuple[bytes, bytes], int] = {}  # (peer, origin)
        self.metrics = {"push_rx": 0, "dup_rx": 0, "prune_tx": 0,
                        "prune_rx": 0, "ping_rx": 0, "pong_rx": 0}
        self._refresh_contact()

    def _refresh_contact(self):
        v = make_value(self.sign_fn, self.identity, KIND_CONTACT_INFO,
                       self.contact_body)
        if self.crds.upsert(v):
            self._pending_push.append(v)

    def publish(self, kind: int, body: bytes):
        """Upsert one of our own values (e.g. our latest vote)."""
        v = make_value(self.sign_fn, self.identity, kind, body)
        if self.crds.upsert(v):
            self._pending_push.append(v)

    def _validated_peers(self):
        return [(pk, c) for pk, c in self.crds.peers()
                if pk != self.identity and pk in self._validated]

    def tick(self, now_ms: int | None = None) -> list[tuple[bytes, tuple]]:
        """One housekeeping round: purge stale values, ping unvalidated
        contacts, flood pending fresh values to validated fanout peers
        (minus per-peer pruned origins), pull from one validated peer."""
        self.crds.purge(now_ms)
        # drop per-peer state for contacts the purge expired — otherwise
        # ephemeral-key contact floods leak tokens/counters forever
        live = {pk for pk, _ in self.crds.peers()}
        self._ping_tokens = {pk: t for pk, t in self._ping_tokens.items()
                             if pk in live}
        self._validated &= live
        self._pruned_by = {pk: o for pk, o in self._pruned_by.items()
                           if pk in live}
        self._dup_seen = {k: c for k, c in self._dup_seen.items()
                          if k[1] in live}
        self._refresh_contact()
        out = []
        unvalidated = [(pk, c) for pk, c in self.crds.peers()
                       if pk != self.identity and pk not in self._validated]
        for pk, (ip, gport, _t, _r) in unvalidated:
            token = self._ping_tokens.get(pk)
            if token is None:
                token = bytes(self.rng.getrandbits(8) for _ in range(32))
                self._ping_tokens[pk] = token
            out.append((encode_ping(
                self.identity, token, self.sign_fn(b"ping" + token)),
                (ip, gport)))

        peers = self._validated_peers()
        if not peers:
            return out
        if self._pending_push:
            batch, self._pending_push = self._pending_push[:64], \
                self._pending_push[64:]
            targets = self.rng.sample(peers,
                                      min(self.PUSH_FANOUT, len(peers)))
            for pk, (ip, gport, _t, _r) in targets:
                pruned = self._pruned_by.get(pk, ())
                vals = [v for v in batch if v.origin not in pruned]
                if vals:
                    out.append((encode_push(vals), (ip, gport)))
        pk, (ip, gport, _t, _r) = self.rng.choice(peers)
        digests = self.crds.digests()
        if len(digests) > self.BLOOM_PULL_THRESHOLD:
            f = CrdsBloom.sized_for(len(digests), rng=self.rng)
            for d in digests:
                f.add(d)
            out.append((encode_pull_req_bloom(f), (ip, gport)))
        else:
            out.append((encode_pull_req(digests), (ip, gport)))
        return out

    def handle(self, payload: bytes, src) -> list[tuple[bytes, tuple]]:
        """Process one datagram; returns reply packets."""
        try:
            mtype, data = decode(payload)
        except (struct.error, ValueError):
            return []
        if mtype == MSG_PING:
            frm, token, sig = data
            self.metrics["ping_rx"] += 1
            if not self.verify_fn(sig, b"ping" + token, frm):
                return []
            h = hashlib.sha256(token).digest()
            return [(encode_pong(self.identity, h,
                                 self.sign_fn(b"pong" + h)), src)]
        if mtype == MSG_PONG:
            frm, h, sig = data
            self.metrics["pong_rx"] += 1
            token = self._ping_tokens.get(frm)
            if token is None or hashlib.sha256(token).digest() != h:
                return []
            if not self.verify_fn(sig, b"pong" + h, frm):
                return []
            self._validated.add(frm)
            del self._ping_tokens[frm]
            return []
        if mtype == MSG_PRUNE:
            frm, origins, sig = data
            self.metrics["prune_rx"] += 1
            if not self.verify_fn(sig, b"prune" + b"".join(origins), frm):
                return []
            self._pruned_by.setdefault(frm, set()).update(origins)
            return []
        if mtype == MSG_PUSH:
            self.metrics["push_rx"] += 1
            replies = []
            stale_origins = []
            for v in data:
                if self.crds.upsert(v):
                    self._pending_push.append(v)  # relay fresh values
                else:
                    self.metrics["dup_rx"] += 1
                    key = (src, v.origin)
                    self._dup_seen[key] = self._dup_seen.get(key, 0) + 1
                    if self._dup_seen[key] == self.PRUNE_DUP_THRESHOLD:
                        stale_origins.append(v.origin)
            if stale_origins:
                self.metrics["prune_tx"] += 1
                sig = self.sign_fn(b"prune" + b"".join(stale_origins))
                replies.append((encode_prune(
                    self.identity, stale_origins, sig), src))
            return replies
        if mtype == MSG_PULL_RESP:
            for v in data:
                self.crds.upsert(v)
            return []
        if mtype == MSG_PULL_REQ:
            missing = self.crds.missing_for(data)
            if not missing:
                return []
            return [(encode_pull_resp(missing[:64]), src)]
        if mtype == MSG_PULL_REQ_BLOOM:
            f = data
            missing = [v for v in self.crds.values()
                       if f.covers(v.digest()) and v.digest() not in f]
            if not missing:
                return []
            return [(encode_pull_resp(missing[:64]), src)]
        return []


# -- fleet sig-digest control ring (round 17) --------------------------------

SIG_DIGEST_HDR = struct.Struct("<IIH")   # shard | chunk_seq | n_tags


def sig_digest_body(shard: int, chunk_seq: int, tags,
                    bloom_seed: int = 0) -> bytes:
    """Body of a KIND_SIG_DIGEST value: one chunk of a host's verdicted
    sig tags for one tcache shard.  Exact u64 tags (authoritative while
    the chunk is retained) followed by a Bloom over the same tags (the
    compact membership summary peers keep once exact budgets age out).
    """
    tags = [int(t) & 0xFFFFFFFFFFFFFFFF for t in tags]
    if len(tags) > 4096:
        raise ValueError("sig digest chunk too large")
    bloom = CrdsBloom(max(64, 1 << (len(tags).bit_length() + 4)),
                      seed=bloom_seed)
    out = bytearray(SIG_DIGEST_HDR.pack(int(shard), int(chunk_seq),
                                        len(tags)))
    for t in tags:
        out += struct.pack("<Q", t)
        bloom.add(struct.pack("<Q", t))
    out += bloom.serialize()
    return bytes(out)


def sig_digest_parse(body: bytes):
    """-> (shard, chunk_seq, [tags], CrdsBloom).  Raises ValueError on a
    torn body (header included — struct.error must not leak to folders)."""
    try:
        shard, chunk, n = SIG_DIGEST_HDR.unpack_from(body, 0)
    except struct.error:
        raise ValueError("truncated sig digest header") from None
    off = SIG_DIGEST_HDR.size
    end = off + 8 * n
    if end > len(body):
        raise ValueError("truncated sig digest")
    tags = list(struct.unpack_from("<%dQ" % n, body, off)) if n else []
    bloom = CrdsBloom.deserialize(body[end:])
    return shard, chunk, tags, bloom


class RecentSigCache:
    """Fold of KIND_SIG_DIGEST values from the control ring: the
    failover host's already-verified reject surface.

    Exact tags are kept up to `budget` per origin (newest chunks win);
    beyond that only the Bloom bits remain.  `seen(tag)` returns
    "exact" (authoritative — safe to skip re-verification), "maybe"
    (Bloom hit only: a false-positive here must NOT drop a verdict, so
    callers treat it as advisory and count it), or False.
    """

    def __init__(self, budget: int = 1 << 16):
        self.budget = int(budget)
        self._exact: dict[bytes, dict[int, int]] = {}  # origin -> tag->chunk
        self._blooms: dict[bytes, list[CrdsBloom]] = {}
        self._chunks: dict[bytes, set[tuple[int, int]]] = {}
        self.fold_cnt = 0
        self.torn_cnt = 0

    def fold(self, value: "CrdsValue") -> int:
        """Fold one digest value in; -> number of new exact tags."""
        if value.kind != KIND_SIG_DIGEST:
            return 0
        try:
            shard, chunk, tags, bloom = sig_digest_parse(value.body)
        except (ValueError, struct.error):
            self.torn_cnt += 1
            return 0
        ck = self._chunks.setdefault(value.origin, set())
        if (shard, chunk) in ck:
            return 0
        ck.add((shard, chunk))
        ex = self._exact.setdefault(value.origin, {})
        new = 0
        for t in tags:
            if t not in ex:
                ex[t] = chunk
                new += 1
        if len(ex) > self.budget:
            # age out oldest chunks' exact tags; their bloom remains
            for t, c in sorted(ex.items(), key=lambda kv: kv[1]):
                del ex[t]
                if len(ex) <= self.budget:
                    break
        self._blooms.setdefault(value.origin, []).append(bloom)
        self.fold_cnt += 1
        return new

    def seen(self, tag: int, origin: bytes | None = None):
        tag = int(tag)
        origins = [origin] if origin is not None else list(self._exact)
        for o in origins:
            if tag in self._exact.get(o, ()):
                return "exact"
        key = struct.pack("<Q", tag)
        for o in (origins if origin is not None else list(self._blooms)):
            for b in self._blooms.get(o, ()):
                if key in b:
                    return "maybe"
        return False

    def exact_tags(self) -> set[int]:
        """Union of all authoritative tags (the failover preload set)."""
        out: set[int] = set()
        for ex in self._exact.values():
            out.update(ex)
        return out
