"""Gossip: the CRDS cluster-info protocol (ref: src/flamenco/gossip/
fd_gossip.c — push/pull over UDP carrying signed CRDS values).

Structure kept from the reference: a CRDS table of signed, timestamped
values keyed by (kind, origin pubkey) with newest-wins upserts; PUSH
messages proactively flood fresh values to fanout peers; PULL requests
carry a digest filter and the responder returns values the requester is
missing.  Wire format is our own compact LE (a fresh chain; confined to
this module); signatures are real ed25519 over the value payload.

    value:  sig[64] | origin[32] | u8 kind | u64 wallclock_ms | u16 len | body
    msg:    u8 type (0 PUSH, 1 PULL_REQ, 2 PULL_RESP) | u16 count | values
            (PULL_REQ: count==n_digests, body is 8-byte value digests)

Kinds: CONTACT_INFO (body = ip[4] | u16 gossip_port | u16 tpu_port |
u16 repair_port), VOTE (body = serialized vote txn), LOWEST_SLOT
(body = u64).
"""

import hashlib
import struct
import time
from dataclasses import dataclass

KIND_CONTACT_INFO = 0
KIND_VOTE = 1
KIND_LOWEST_SLOT = 2

MSG_PUSH = 0
MSG_PULL_REQ = 1
MSG_PULL_RESP = 2

VALUE_HDR = struct.Struct("<64s32sBQH")


@dataclass(frozen=True)
class CrdsValue:
    signature: bytes      # 64B over origin|kind|wallclock|body
    origin: bytes         # 32B pubkey
    kind: int
    wallclock_ms: int
    body: bytes

    def signable(self) -> bytes:
        return (self.origin + bytes([self.kind])
                + struct.pack("<Q", self.wallclock_ms) + self.body)

    def key(self) -> tuple[int, bytes]:
        return (self.kind, self.origin)

    def digest(self) -> bytes:
        return hashlib.sha256(self.serialize()).digest()[:8]

    def serialize(self) -> bytes:
        return VALUE_HDR.pack(self.signature, self.origin, self.kind,
                              self.wallclock_ms, len(self.body)) + self.body

    @classmethod
    def deserialize(cls, buf: bytes, off: int = 0) -> tuple["CrdsValue", int]:
        sig, origin, kind, wc, ln = VALUE_HDR.unpack_from(buf, off)
        off += VALUE_HDR.size
        body = bytes(buf[off : off + ln])
        if len(body) != ln:
            raise ValueError("truncated crds value")
        return cls(sig, origin, kind, wc, body), off + ln


def make_value(sign_fn, origin: bytes, kind: int, body: bytes,
               wallclock_ms: int | None = None) -> CrdsValue:
    wc = int(time.time() * 1000) if wallclock_ms is None else wallclock_ms
    v = CrdsValue(bytes(64), origin, kind, wc, body)
    return CrdsValue(sign_fn(v.signable()), origin, kind, wc, body)


def contact_info_body(ip: str, gossip_port: int, tpu_port: int,
                      repair_port: int) -> bytes:
    import socket
    return (socket.inet_aton(ip)
            + struct.pack("<HHH", gossip_port, tpu_port, repair_port))


def contact_info_parse(body: bytes) -> tuple[str, int, int, int]:
    import socket
    ip = socket.inet_ntoa(body[:4])
    g, t, r = struct.unpack_from("<HHH", body, 4)
    return ip, g, t, r


class Crds:
    """The replicated data store (fd_crds): (kind, origin) -> newest value,
    with verify-on-insert."""

    def __init__(self, verify_fn, max_age_ms: int = 60_000):
        self.table: dict[tuple, CrdsValue] = {}
        self.verify_fn = verify_fn    # (sig, msg, pubkey) -> bool
        self.max_age_ms = max_age_ms

    def upsert(self, v: CrdsValue, now_ms: int | None = None) -> bool:
        """Returns True if the value was fresh (new key or newer clock)."""
        now = int(time.time() * 1000) if now_ms is None else now_ms
        if abs(now - v.wallclock_ms) > self.max_age_ms:
            return False
        cur = self.table.get(v.key())
        if cur is not None and cur.wallclock_ms >= v.wallclock_ms:
            return False
        if not self.verify_fn(v.signature, v.signable(), v.origin):
            return False
        self.table[v.key()] = v
        return True

    def values(self) -> list[CrdsValue]:
        return list(self.table.values())

    def digests(self) -> set[bytes]:
        return {v.digest() for v in self.table.values()}

    def missing_for(self, digests: set[bytes]) -> list[CrdsValue]:
        return [v for v in self.table.values() if v.digest() not in digests]

    def peers(self) -> list[tuple[bytes, tuple[str, int, int, int]]]:
        """(pubkey, (ip, gossip, tpu, repair)) for every known contact."""
        out = []
        for (kind, origin), v in self.table.items():
            if kind == KIND_CONTACT_INFO:
                out.append((origin, contact_info_parse(v.body)))
        return out


# -- wire messages -----------------------------------------------------------

def encode_push(values: list[CrdsValue]) -> bytes:
    out = bytearray(struct.pack("<BH", MSG_PUSH, len(values)))
    for v in values:
        out += v.serialize()
    return bytes(out)


def encode_pull_req(digests: set[bytes]) -> bytes:
    ds = sorted(digests)
    return (struct.pack("<BH", MSG_PULL_REQ, len(ds)) + b"".join(ds))


def encode_pull_resp(values: list[CrdsValue]) -> bytes:
    out = bytearray(struct.pack("<BH", MSG_PULL_RESP, len(values)))
    for v in values:
        out += v.serialize()
    return bytes(out)


def decode(buf: bytes):
    """-> (msg_type, values | digest-set)."""
    mtype, cnt = struct.unpack_from("<BH", buf, 0)
    off = 3
    if mtype == MSG_PULL_REQ:
        ds = set()
        for i in range(cnt):
            ds.add(bytes(buf[off : off + 8]))
            off += 8
        return mtype, ds
    vals = []
    for _ in range(cnt):
        v, off = CrdsValue.deserialize(buf, off)
        vals.append(v)
    return mtype, vals


class GossipNode:
    """Protocol engine over an injected packet interface (testable without
    sockets; the gossip tile wires it to waltz UDP).  fd_gossip's loop:
    periodic push of own values + pull exchange with random peers."""

    PUSH_FANOUT = 6

    def __init__(self, identity_pub: bytes, sign_fn, verify_fn,
                 contact_body: bytes, rng=None):
        import random
        self.identity = identity_pub
        self.sign_fn = sign_fn
        self.crds = Crds(verify_fn)
        self.contact_body = contact_body
        self.rng = rng or random.Random()
        self._refresh_contact()

    def _refresh_contact(self):
        self.crds.upsert(make_value(
            self.sign_fn, self.identity, KIND_CONTACT_INFO,
            self.contact_body))

    def publish(self, kind: int, body: bytes):
        """Upsert one of our own values (e.g. our latest vote)."""
        self.crds.upsert(make_value(self.sign_fn, self.identity, kind, body))

    def tick(self) -> list[tuple[bytes, tuple[str, int]]]:
        """One housekeeping round: returns [(payload, (ip, port))] to send —
        a PUSH of our table to `PUSH_FANOUT` random peers and a PULL_REQ to
        one."""
        self._refresh_contact()
        peers = [(pk, c) for pk, c in self.crds.peers()
                 if pk != self.identity]
        if not peers:
            return []
        out = []
        push = encode_push(self.crds.values())
        targets = self.rng.sample(peers, min(self.PUSH_FANOUT, len(peers)))
        for pk, (ip, gport, _t, _r) in targets:
            out.append((push, (ip, gport)))
        pk, (ip, gport, _t, _r) = self.rng.choice(peers)
        out.append((encode_pull_req(self.crds.digests()), (ip, gport)))
        return out

    def handle(self, payload: bytes, src) -> list[tuple[bytes, tuple]]:
        """Process one datagram; returns reply packets."""
        try:
            mtype, data = decode(payload)
        except (struct.error, ValueError):
            return []
        if mtype in (MSG_PUSH, MSG_PULL_RESP):
            for v in data:
                self.crds.upsert(v)
            return []
        if mtype == MSG_PULL_REQ:
            missing = self.crds.missing_for(data)
            if not missing:
                return []
            return [(encode_pull_resp(missing[:64]), src)]
        return []
