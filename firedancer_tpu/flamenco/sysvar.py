"""Sysvars (ref: src/flamenco/runtime/sysvar/ — fd_sysvar_clock,
fd_sysvar_rent, fd_sysvar_epoch_schedule, fd_sysvar_recent_hashes):
chain state materialized as read-only accounts owned by the sysvar ids so
on-chain programs can read it; the Runtime refreshes them at slot open.

Compact LE layouts (our own; confined to this module):
    clock:  u64 slot | i64 unix_timestamp | u64 epoch
    rent:   u64 lamports_per_byte_year | f64 exemption_years | u8 burn_pct
    epoch_schedule: u64 slots_per_epoch | u64 first_normal_slot
    recent_blockhashes: u16 n | n * hash[32]   (newest first, capped 150)
"""

import struct

from .types import (Account, SYSVAR_CLOCK_ID, SYSVAR_EPOCH_SCHEDULE_ID,
                    SYSVAR_RECENT_BLOCKHASHES_ID, SYSVAR_RENT_ID, Rent)

MAX_RECENT_BLOCKHASHES = 150


def clock_bytes(slot: int, unix_ts: int, epoch: int) -> bytes:
    return struct.pack("<QqQ", slot, unix_ts, epoch)


def clock_parse(raw: bytes) -> tuple[int, int, int]:
    return struct.unpack_from("<QqQ", raw)


def rent_bytes(rent: Rent) -> bytes:
    return struct.pack("<QdB", rent.lamports_per_byte_year,
                       rent.exemption_threshold_years, rent.burn_percent)


def epoch_schedule_bytes(slots_per_epoch: int,
                         first_normal_slot: int = 0) -> bytes:
    return struct.pack("<QQ", slots_per_epoch, first_normal_slot)


def recent_blockhashes_bytes(hashes: list[bytes]) -> bytes:
    hs = hashes[-MAX_RECENT_BLOCKHASHES:][::-1]  # newest first
    return struct.pack("<H", len(hs)) + b"".join(hs)


def refresh(accdb, xid, *, slot: int, unix_ts: int, epoch: int,
            slots_per_epoch: int, rent: Rent, blockhashes: list[bytes]):
    """Write all sysvar accounts into fork `xid` (fd_sysvar_*_update at
    slot boundary, fd_runtime.c block prepare)."""
    for pk, data in (
        (SYSVAR_CLOCK_ID, clock_bytes(slot, unix_ts, epoch)),
        (SYSVAR_RENT_ID, rent_bytes(rent)),
        (SYSVAR_EPOCH_SCHEDULE_ID, epoch_schedule_bytes(slots_per_epoch)),
        (SYSVAR_RECENT_BLOCKHASHES_ID,
         recent_blockhashes_bytes(blockhashes)),
    ):
        acct = accdb.load(xid, pk) or Account(lamports=1, owner=pk)
        acct.data = data
        accdb.store(xid, pk, acct)
