"""Replay capture ("solcap") for differential debugging (ref:
src/flamenco/capture/fd_solcap_writer.c + fd_solcap_diff.c — theirs is
protobuf/nanopb; ours is gzipped JSONL, same information content: per-slot
bank preimages and per-txn outcomes, diffable across implementations/runs).

Record during replay or leader banking; diff two captures to find the first
divergent slot and WHY (which preimage field, which txn, which account).
"""

import gzip
import json
from dataclasses import asdict, dataclass, field


@dataclass
class TxnRecord:
    sig: str              # first signature, hex
    ok: bool
    err: str | None
    fee: int


@dataclass
class SlotRecord:
    slot: int
    parent_hash: str      # bank-hash preimage fields (fd_solcap BankPreimage)
    delta_hash: str
    signature_cnt: int
    poh_hash: str
    bank_hash: str
    txns: list = field(default_factory=list)
    accounts: dict = field(default_factory=dict)  # pubkey hex -> state hex


class CaptureWriter:
    def __init__(self, path: str):
        self._f = gzip.open(path, "wt")

    def write_slot(self, rec: SlotRecord):
        self._f.write(json.dumps(asdict(rec)) + "\n")
        self._f.flush()

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def record_bank(bank, results=None, accounts=None) -> SlotRecord:
    """Snapshot a FROZEN Bank into a SlotRecord (fd_solcap_write_bank_
    preimage)."""
    from ..ballet import lthash
    if bank.hash is None:
        raise ValueError("bank not frozen")
    return SlotRecord(
        slot=bank.slot,
        parent_hash=bank.parent_hash.hex(),
        delta_hash=lthash.fini(bank.delta).hex(),
        signature_cnt=bank.signature_cnt,
        poh_hash=bank.poh_hash.hex(),
        bank_hash=bank.hash.hex(),
        txns=[asdict(t) for t in (results or [])],
        accounts=accounts or {},
    )


def read(path: str) -> list[dict]:
    with gzip.open(path, "rt") as f:
        return [json.loads(line) for line in f if line.strip()]


def diff(path_a: str, path_b: str) -> dict | None:
    """First divergence between two captures (fd_solcap_diff): returns
    {slot, field, a, b} or None when identical over the common prefix."""
    a, b = read(path_a), read(path_b)
    by_slot_b = {r["slot"]: r for r in b}
    for ra in a:
        rb = by_slot_b.get(ra["slot"])
        if rb is None:
            continue
        for fld in ("parent_hash", "delta_hash", "signature_cnt",
                    "poh_hash", "bank_hash"):
            if ra[fld] != rb[fld]:
                return {"slot": ra["slot"], "field": fld,
                        "a": ra[fld], "b": rb[fld]}
        for i, (ta, tb) in enumerate(zip(ra["txns"], rb["txns"])):
            if ta != tb:
                return {"slot": ra["slot"], "field": f"txn[{i}]",
                        "a": ta, "b": tb}
    return None
