"""Address lookup table native program + v0 lookup resolution
(ref: src/flamenco/runtime/program/fd_address_lookup_table_program.c).

Tables let v0 transactions reference accounts by (table, index) instead of
carrying 32-byte addresses inline.  State machine per the reference:

  CreateLookupTable   — allocate a table account (PDA of authority+slot)
  ExtendLookupTable   — append addresses (authority must sign)
  FreezeLookupTable   — drop the authority; table becomes immutable
  DeactivateLookupTable — start the cooldown (tables can't die instantly:
                          in-flight txns may still reference them)
  CloseLookupTable    — reclaim lamports once deactivated + cooled down

Serialized table state (our own fixed little-endian layout; the reference
uses bincode ProgramState):

    u64 deactivation_slot   (u64max = active)
    u64 last_extended_slot
    u8  has_authority | authority[32]
    u16 n_addresses | addresses[n][32]
"""

import struct
from dataclasses import dataclass

from .system_program import InstrError
from .types import ADDRESS_LOOKUP_TABLE_PROGRAM_ID, Account

_U64MAX = (1 << 64) - 1
_HDR = struct.Struct("<QQB32sH")
MAX_ADDRESSES = 256  # fd_address_lookup_table_program.c LUT_MAX_ADDRESSES
DEACTIVATION_COOLDOWN_SLOTS = 513  # ~ the reference's slot hashes window


@dataclass
class LookupTable:
    deactivation_slot: int = _U64MAX
    last_extended_slot: int = 0
    authority: bytes | None = None
    addresses: list[bytes] = None

    def __post_init__(self):
        if self.addresses is None:
            self.addresses = []

    def serialize(self) -> bytes:
        out = _HDR.pack(
            self.deactivation_slot, self.last_extended_slot,
            self.authority is not None, self.authority or bytes(32),
            len(self.addresses))
        return out + b"".join(self.addresses)

    @classmethod
    def deserialize(cls, raw: bytes) -> "LookupTable":
        if len(raw) < _HDR.size:
            raise InstrError("lookup table account too small")
        d, e, has_auth, auth, n = _HDR.unpack_from(raw)
        addrs = [bytes(raw[_HDR.size + 32 * i:_HDR.size + 32 * (i + 1)])
                 for i in range(n)]
        if any(len(a) != 32 for a in addrs):
            raise InstrError("lookup table truncated")
        return cls(d, e, bytes(auth) if has_auth else None, addrs)


# instruction discriminants (u32 LE, the reference's enum order)
IX_CREATE, IX_FREEZE, IX_EXTEND, IX_DEACTIVATE, IX_CLOSE = range(5)


def ix_create(recent_slot: int) -> bytes:
    return struct.pack("<IQ", IX_CREATE, recent_slot)


def ix_extend(addresses: list[bytes]) -> bytes:
    return struct.pack("<IQ", IX_EXTEND, len(addresses)) + b"".join(addresses)


def ix_freeze() -> bytes:
    return struct.pack("<I", IX_FREEZE)


def ix_deactivate() -> bytes:
    return struct.pack("<I", IX_DEACTIVATE)


def ix_close() -> bytes:
    return struct.pack("<I", IX_CLOSE)


def execute(ictx):
    """Accounts: 0 = table (writable), 1 = authority (signer); CloseLookup
    adds 2 = lamport recipient (writable)."""
    data = ictx.data
    if len(data) < 4:
        raise InstrError("alut: data too short")
    (disc,) = struct.unpack_from("<I", data)
    table_acct = ictx.account(0)
    slot = getattr(ictx.txctx, "slot", 0)

    if disc == IX_CREATE:
        if table_acct.acct is not None and table_acct.acct.data:
            raise InstrError("alut: table already exists")
        if not ictx.is_signer(1):
            raise InstrError("alut: authority must sign create")
        auth = ictx.account(1).pubkey
        if table_acct.acct is None:
            table_acct.acct = Account(owner=ADDRESS_LOOKUP_TABLE_PROGRAM_ID)
        table_acct.acct.owner = ADDRESS_LOOKUP_TABLE_PROGRAM_ID
        table_acct.acct.data = LookupTable(authority=auth).serialize()
        table_acct.touch()
        return

    if table_acct.acct is None:
        raise InstrError("alut: table does not exist")
    if table_acct.acct.owner != ADDRESS_LOOKUP_TABLE_PROGRAM_ID:
        raise InstrError("alut: table not owned by program")
    st = LookupTable.deserialize(table_acct.acct.data)

    def check_authority():
        if st.authority is None:
            raise InstrError("alut: table is frozen")
        if not ictx.is_signer(1) or ictx.account(1).pubkey != st.authority:
            raise InstrError("alut: authority signature required")

    if disc == IX_EXTEND:
        check_authority()
        if st.deactivation_slot != _U64MAX:
            raise InstrError("alut: table deactivated")
        if len(data) < 12:
            raise InstrError("alut: extend data too short")
        (n,) = struct.unpack_from("<Q", data, 4)
        if len(data) < 12 + 32 * n:
            raise InstrError("alut: extend addresses truncated")
        new = [bytes(data[12 + 32 * i:12 + 32 * (i + 1)]) for i in range(n)]
        if not new:
            raise InstrError("alut: extend with no addresses")
        if len(st.addresses) + len(new) > MAX_ADDRESSES:
            raise InstrError("alut: table full")
        st.addresses += new
        st.last_extended_slot = slot
    elif disc == IX_FREEZE:
        check_authority()
        if not st.addresses:
            raise InstrError("alut: cannot freeze an empty table")
        st.authority = None
    elif disc == IX_DEACTIVATE:
        check_authority()
        if st.deactivation_slot != _U64MAX:
            raise InstrError("alut: already deactivated")
        st.deactivation_slot = slot
    elif disc == IX_CLOSE:
        check_authority()
        if st.deactivation_slot == _U64MAX:
            raise InstrError("alut: must deactivate before close")
        if slot < st.deactivation_slot + DEACTIVATION_COOLDOWN_SLOTS:
            raise InstrError("alut: deactivation cooldown not elapsed")
        recipient = ictx.account(2)
        recipient.acct = recipient.acct or Account()
        recipient.acct.lamports += table_acct.acct.lamports
        recipient.touch()
        table_acct.acct.lamports = 0
        table_acct.acct.data = b""
        table_acct.touch()
        return
    else:
        raise InstrError(f"alut: unknown instruction {disc}")

    table_acct.acct.data = st.serialize()
    table_acct.touch()


def resolve_lookups(accdb, xid, parsed, payload: bytes):
    """Resolve a v0 txn's address-table lookups into (addrs, writable) —
    the executor's account-load-phase hook (the reference resolves in
    fd_executor_setup_txn_account_keys via the slot ctx's funk).

    Returns (extra_addrs, extra_writable_flags): all writable lookups from
    every table first, then all readonly ones, matching the v0 message
    account ordering rule."""
    writable, readonly = [], []
    for lut in parsed.addr_tables:
        table_key = payload[lut.addr_off:lut.addr_off + 32]
        rec = accdb.load(xid, table_key)
        if rec is None or rec.owner != ADDRESS_LOOKUP_TABLE_PROGRAM_ID:
            raise TxnLookupError("lookup table account not found")
        st = LookupTable.deserialize(rec.data)
        for off, cnt, out in ((lut.writable_off, lut.writable_cnt, writable),
                              (lut.readonly_off, lut.readonly_cnt, readonly)):
            for i in range(cnt):
                idx = payload[off + i]
                if idx >= len(st.addresses):
                    raise TxnLookupError(
                        f"lookup index {idx} out of table range")
                out.append(st.addresses[idx])
    addrs = writable + readonly
    flags = [True] * len(writable) + [False] * len(readonly)
    return addrs, flags


class TxnLookupError(Exception):
    """Lookup resolution failure: the txn is unexecutable (maps to the
    reference's FD_RUNTIME_TXN_ERR_ADDRESS_LOOKUP_TABLE_* errors)."""
