"""Epoch inflation rewards (ref: src/flamenco/rewards/fd_rewards.c — the
epoch-boundary stake/vote reward calculation and distribution).

Model (Solana's published economics, as the reference implements):

  * inflation(year) = initial * (1 - taper)^year, floored at terminal —
    total annual token issuance as a fraction of capitalization
  * an epoch's pool = inflation * capitalization * epoch_year_fraction
  * each (stake, vote) pair earns POINTS = effective_stake * credits
    earned by its vote account this epoch; the pool is divided
    pro-rata by points
  * the vote account's commission percent is taken off the top of each
    stake's reward; the rest lands on the stake account (and counts as
    newly issued supply)

Distribution applies lamports directly to the account states handed in
(the runtime calls this at the epoch boundary before the first bank of
the new epoch, matching the reference's epoch processing order).
"""

from __future__ import annotations

from dataclasses import dataclass

DEFAULT_INITIAL = 0.08
DEFAULT_TERMINAL = 0.015
DEFAULT_TAPER = 0.15
SLOTS_PER_YEAR = 78_892_314  # 2 slots/800ms * seconds per average year


def inflation_rate(year: float, initial: float = DEFAULT_INITIAL,
                   terminal: float = DEFAULT_TERMINAL,
                   taper: float = DEFAULT_TAPER) -> float:
    """Annualized issuance fraction at a point in time (fd_inflation)."""
    rate = initial * (1.0 - taper) ** year
    return max(rate, terminal)


@dataclass
class StakeReward:
    stake_pubkey: bytes
    vote_pubkey: bytes
    stake_reward: int  # lamports to the stake account
    vote_reward: int  # lamports to the vote account (commission)
    points: int


def calculate_epoch_rewards(
    stakes: list[tuple[bytes, bytes, int]],
    vote_credits: dict[bytes, int],
    vote_commission: dict[bytes, int],
    capitalization: int,
    epoch_start_slot: int,
    slots_in_epoch: int,
    initial: float = DEFAULT_INITIAL,
    terminal: float = DEFAULT_TERMINAL,
    taper: float = DEFAULT_TAPER,
) -> list[StakeReward]:
    """Compute every stake's reward for the epoch that just ended.

    stakes: (stake_pubkey, vote_pubkey, effective_stake_lamports)
    vote_credits: vote_pubkey -> credits earned THIS epoch
    vote_commission: vote_pubkey -> percent [0, 100]
    """
    year = epoch_start_slot / SLOTS_PER_YEAR
    rate = inflation_rate(year, initial, terminal, taper)
    pool = int(rate * capitalization * slots_in_epoch / SLOTS_PER_YEAR)

    points: list[int] = []
    for _, vote_pk, eff in stakes:
        points.append(eff * vote_credits.get(vote_pk, 0))
    total_points = sum(points)
    out: list[StakeReward] = []
    if total_points == 0 or pool == 0:
        return out
    for (stake_pk, vote_pk, _), pts in zip(stakes, points):
        if pts == 0:
            continue
        reward = pool * pts // total_points
        commission = vote_commission.get(vote_pk, 0)
        vote_cut = reward * commission // 100
        out.append(StakeReward(stake_pk, vote_pk, reward - vote_cut,
                               vote_cut, pts))
    return out


def distribute(rewards: list[StakeReward], credit) -> int:
    """Apply rewards via `credit(pubkey, lamports)`; returns total newly
    issued lamports (the capitalization delta the bank records)."""
    total = 0
    for r in rewards:
        if r.stake_reward:
            credit(r.stake_pubkey, r.stake_reward)
            total += r.stake_reward
        if r.vote_reward:
            credit(r.vote_pubkey, r.vote_reward)
            total += r.vote_reward
    return total
