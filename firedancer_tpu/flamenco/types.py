"""Core runtime types (ref: src/flamenco/types/ — the generated bincode
type library; here only the account meta and well-known program ids the
executor needs, defined by hand).

Accounts serialize into funk values with a fixed little-endian header —
the relocatable analogue of fd_account_meta_t (src/flamenco/runtime/
fd_acc_mgr.h)."""

import struct
from dataclasses import dataclass, field

ACCOUNT_HDR = struct.Struct("<QQ32s?Q")  # lamports, data_len, owner, exec, rent_epoch

# well-known program ids / sysvars (base58 of the real Solana ids is kept in
# comments; internally we use the canonical 32-byte values)
SYSTEM_PROGRAM_ID = bytes(32)  # 11111111111111111111111111111111


def _named_id(name: str) -> bytes:
    """Deterministic 32-byte id for built-ins that aren't all-zeros.
    (The real ids are base58 strings baked into the chain; for a from-
    scratch chain the requirement is uniqueness + determinism.)"""
    import hashlib
    return hashlib.sha256(b"fdtpu-program:" + name.encode()).digest()


VOTE_PROGRAM_ID = _named_id("vote")
STAKE_PROGRAM_ID = _named_id("stake")
CONFIG_PROGRAM_ID = _named_id("config")
COMPUTE_BUDGET_PROGRAM_ID = _named_id("compute-budget")
ADDRESS_LOOKUP_TABLE_PROGRAM_ID = _named_id("addr-lookup-table")
BPF_LOADER_ID = _named_id("bpf-loader")
ED25519_PRECOMPILE_ID = _named_id("ed25519-precompile")
SECP256K1_PRECOMPILE_ID = _named_id("secp256k1-precompile")

SYSVAR_CLOCK_ID = _named_id("sysvar-clock")
SYSVAR_RENT_ID = _named_id("sysvar-rent")
SYSVAR_EPOCH_SCHEDULE_ID = _named_id("sysvar-epoch-schedule")
SYSVAR_RECENT_BLOCKHASHES_ID = _named_id("sysvar-recent-blockhashes")

NATIVE_LOADER_ID = _named_id("native-loader")


@dataclass
class Account:
    """One account's state (fd_account_meta_t + data)."""
    lamports: int = 0
    data: bytes = b""
    owner: bytes = SYSTEM_PROGRAM_ID
    executable: bool = False
    rent_epoch: int = 0

    def serialize(self) -> bytes:
        return ACCOUNT_HDR.pack(self.lamports, len(self.data), self.owner,
                                self.executable, self.rent_epoch) + self.data

    @classmethod
    def deserialize(cls, raw: bytes) -> "Account":
        lam, dlen, owner, ex, rent = ACCOUNT_HDR.unpack_from(raw)
        data = bytes(raw[ACCOUNT_HDR.size:ACCOUNT_HDR.size + dlen])
        return cls(lam, data, owner, ex, rent)


@dataclass
class FeeRateGovernor:
    """Per-signature fee schedule (ref: fee calc in fd_runtime.c)."""
    lamports_per_signature: int = 5000


@dataclass
class Rent:
    """Rent parameters (sysvar rent; fd_sysvar_rent)."""
    lamports_per_byte_year: int = 3480
    exemption_threshold_years: float = 2.0
    burn_percent: int = 50

    def minimum_balance(self, data_len: int) -> int:
        return int((128 + data_len) * self.lamports_per_byte_year
                   * self.exemption_threshold_years)


@dataclass
class EpochSchedule:
    """Slot->epoch mapping (sysvar epoch schedule; fd_sysvar_epoch_schedule).
    Fixed-length epochs (no warmup) keep the schedule trivially invertible."""
    slots_per_epoch: int = 432_000

    def epoch(self, slot: int) -> int:
        return slot // self.slots_per_epoch

    def first_slot(self, epoch: int) -> int:
        return epoch * self.slots_per_epoch


@dataclass
class Clock:
    """Sysvar clock (fd_sysvar_clock)."""
    slot: int = 0
    epoch: int = 0
    unix_timestamp: int = 0
