"""Core runtime types (ref: src/flamenco/types/ — the generated bincode
type library; here only the account meta and well-known program ids the
executor needs, defined by hand).

Accounts serialize into funk values with a fixed little-endian header —
the relocatable analogue of fd_account_meta_t (src/flamenco/runtime/
fd_acc_mgr.h)."""

import struct
from dataclasses import dataclass, field

ACCOUNT_HDR = struct.Struct("<QQ32s?Q")  # lamports, data_len, owner, exec, rent_epoch


def _b58_id(s: str) -> bytes:
    """Decode a base58 program/sysvar address to its 32-byte value."""
    from ..ballet import base58
    return base58.decode(s, 32)


# The REAL Solana program/sysvar ids (ref: the registry in
# src/flamenco/runtime/program/ and fd_flamenco_base.h's
# fd_solana_*_program_id constants).  Using the real constants — not
# invented ids — is what lets real transactions, snapshots and ledgers
# route to the right native program (round-4 conformance anchoring).
SYSTEM_PROGRAM_ID = bytes(32)                          # 1111...1111
VOTE_PROGRAM_ID = _b58_id(
    "Vote111111111111111111111111111111111111111")
STAKE_PROGRAM_ID = _b58_id(
    "Stake11111111111111111111111111111111111111")
CONFIG_PROGRAM_ID = _b58_id(
    "Config1111111111111111111111111111111111111")
COMPUTE_BUDGET_PROGRAM_ID = _b58_id(
    "ComputeBudget111111111111111111111111111111")
ADDRESS_LOOKUP_TABLE_PROGRAM_ID = _b58_id(
    "AddressLookupTab1e1111111111111111111111111")
BPF_LOADER_DEPRECATED_ID = _b58_id(
    "BPFLoader1111111111111111111111111111111111")
BPF_LOADER_ID = _b58_id(
    "BPFLoader2111111111111111111111111111111111")
BPF_LOADER_UPGRADEABLE_ID = _b58_id(
    "BPFLoaderUpgradeab1e11111111111111111111111")
ED25519_PRECOMPILE_ID = _b58_id(
    "Ed25519SigVerify111111111111111111111111111")
SECP256K1_PRECOMPILE_ID = _b58_id(
    "KeccakSecp256k11111111111111111111111111111")

SYSVAR_CLOCK_ID = _b58_id(
    "SysvarC1ock11111111111111111111111111111111")
SYSVAR_RENT_ID = _b58_id(
    "SysvarRent111111111111111111111111111111111")
SYSVAR_EPOCH_SCHEDULE_ID = _b58_id(
    "SysvarEpochSchedu1e111111111111111111111111")
SYSVAR_RECENT_BLOCKHASHES_ID = _b58_id(
    "SysvarRecentB1ockHashes11111111111111111111")
SYSVAR_SLOT_HASHES_ID = _b58_id(
    "SysvarS1otHashes111111111111111111111111111")
SYSVAR_STAKE_HISTORY_ID = _b58_id(
    "SysvarStakeHistory1111111111111111111111111")
SYSVAR_INSTRUCTIONS_ID = _b58_id(
    "Sysvar1nstructions1111111111111111111111111")
SYSVAR_FEES_ID = _b58_id(
    "SysvarFees111111111111111111111111111111111")
SYSVAR_LAST_RESTART_SLOT_ID = _b58_id(
    "SysvarLastRestartS1ot1111111111111111111111")

NATIVE_LOADER_ID = _b58_id(
    "NativeLoader1111111111111111111111111111111")


@dataclass
class Account:
    """One account's state (fd_account_meta_t + data)."""
    lamports: int = 0
    data: bytes = b""
    owner: bytes = SYSTEM_PROGRAM_ID
    executable: bool = False
    rent_epoch: int = 0

    def serialize(self) -> bytes:
        return ACCOUNT_HDR.pack(self.lamports, len(self.data), self.owner,
                                self.executable, self.rent_epoch) + self.data

    @classmethod
    def deserialize(cls, raw: bytes) -> "Account":
        lam, dlen, owner, ex, rent = ACCOUNT_HDR.unpack_from(raw)
        data = bytes(raw[ACCOUNT_HDR.size:ACCOUNT_HDR.size + dlen])
        return cls(lam, data, owner, ex, rent)


@dataclass
class FeeRateGovernor:
    """Per-signature fee schedule (ref: fee calc in fd_runtime.c)."""
    lamports_per_signature: int = 5000


@dataclass
class Rent:
    """Rent parameters (sysvar rent; fd_sysvar_rent)."""
    lamports_per_byte_year: int = 3480
    exemption_threshold_years: float = 2.0
    burn_percent: int = 50

    def minimum_balance(self, data_len: int) -> int:
        return int((128 + data_len) * self.lamports_per_byte_year
                   * self.exemption_threshold_years)


@dataclass
class EpochSchedule:
    """Slot->epoch mapping (sysvar epoch schedule; fd_sysvar_epoch_schedule).
    Fixed-length epochs (no warmup) keep the schedule trivially invertible."""
    slots_per_epoch: int = 432_000

    def epoch(self, slot: int) -> int:
        return slot // self.slots_per_epoch

    def first_slot(self, epoch: int) -> int:
        return epoch * self.slots_per_epoch


@dataclass
class Clock:
    """Sysvar clock (fd_sysvar_clock)."""
    slot: int = 0
    epoch: int = 0
    unix_timestamp: int = 0
