"""Declarative bincode serde for Solana consensus types.

Role of the reference's generated type layer (src/flamenco/types/
fd_types.{h,c} — ~34k generated lines from the IDL): every on-chain /
wire structure is a schema, and one generic engine handles both
directions.  The TPU-repo analogue is declarative rather than generated:
a schema is a tuple tree of combinators, so adding a type is one
definition, not a codegen run.

Encoding rules are upstream bincode (fixint, little-endian):
  * u8/u16/u32/u64/i64: fixed-width LE
  * bool: one byte 0/1
  * Option<T>: u8 tag 0/1 then T
  * Vec<T>: u64 length then elements
  * String: u64 length then utf-8 bytes
  * fixed byte arrays (pubkeys, hashes): raw
  * enums: u32 variant index then variant payload
  * shortvec (compact-u16) is in ballet/compact_u16.py (txn wire only)
"""

from __future__ import annotations

import struct
from typing import Any


class BincodeError(ValueError):
    pass


# ---------------------------------------------------------------- engine
# A schema is:
#   ("u8"|"u16"|"u32"|"u64"|"i64"|"f64"|"bool")       scalar
#   ("bytes", n)                                      fixed array
#   ("option", schema)
#   ("vec", schema)
#   ("array", schema, n)                              fixed-length repeat
#   ("string",)
#   ("struct", (("name", schema), ...))
#   ("enum", (("variant_name", schema|None), ...))    u32 discriminant
#
# Values: scalars -> int/bool/float; bytes -> bytes; option -> None|value;
# vec/array -> list; struct -> dict; enum -> (variant_name, value|None).

_SCALARS = {
    "u8": ("<B", 1), "u16": ("<H", 2), "u32": ("<I", 4), "u64": ("<Q", 8),
    "i64": ("<q", 8), "f64": ("<d", 8),
}


def encode(schema, val) -> bytes:
    kind = schema[0] if isinstance(schema, tuple) else schema
    if kind in _SCALARS:
        fmt, _ = _SCALARS[kind]
        return struct.pack(fmt, val)
    if kind == "bool":
        return b"\x01" if val else b"\x00"
    if kind == "bytes":
        if len(val) != schema[1]:
            raise BincodeError(f"bytes: want {schema[1]}, got {len(val)}")
        return bytes(val)
    if kind == "option":
        if val is None:
            return b"\x00"
        return b"\x01" + encode(schema[1], val)
    if kind == "vec":
        out = struct.pack("<Q", len(val))
        return out + b"".join(encode(schema[1], v) for v in val)
    if kind == "array":
        if len(val) != schema[2]:
            raise BincodeError(f"array: want {schema[2]}, got {len(val)}")
        return b"".join(encode(schema[1], v) for v in val)
    if kind == "string":
        raw = val.encode()
        return struct.pack("<Q", len(raw)) + raw
    if kind == "varint":
        # serde_varint (gossip contact-info v2 fields): 7 bits/byte LE,
        # continuation high bit — NOT the same as shortvec (no special
        # u16 3-byte cap here; width is the schema's business)
        v = int(val)
        if v < 0:
            raise BincodeError("varint must be non-negative")
        out = bytearray()
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out.append(b | 0x80)
            else:
                out.append(b)
                return bytes(out)
    if kind == "cvec":
        # shortvec (compact-u16) length + elements: the serde_short_vec
        # framing gossip v2 vectors use — EXACT txn-wire shortvec rules
        # (minimal encoding, <= 0xFFFF), one implementation in
        # ballet/compact_u16.py
        from ..ballet import compact_u16 as cu16
        return cu16.encode(len(val)) + b"".join(
            encode(schema[1], x) for x in val)
    if kind == "solana_txn":
        # an embedded wire transaction (gossip vote CRDS): self-
        # delimiting, carried as its raw bytes
        return bytes(val)
    if kind == "struct":
        out = []
        for name, sub in schema[1]:
            if name not in val:
                raise BincodeError(f"struct: missing field {name}")
            out.append(encode(sub, val[name]))
        return b"".join(out)
    if kind == "enum":
        vname, payload = val
        for i, (name, sub) in enumerate(schema[1]):
            if name == vname:
                out = struct.pack("<I", i)
                if sub is not None:
                    out += encode(sub, payload)
                return out
        raise BincodeError(f"enum: unknown variant {vname}")
    raise BincodeError(f"unknown schema kind {kind}")


def decode(schema, raw: bytes, off: int = 0) -> tuple[Any, int]:
    """Returns (value, next_offset)."""
    kind = schema[0] if isinstance(schema, tuple) else schema
    if kind in _SCALARS:
        fmt, n = _SCALARS[kind]
        if off + n > len(raw):
            raise BincodeError("truncated scalar")
        return struct.unpack_from(fmt, raw, off)[0], off + n
    if kind == "bool":
        if off >= len(raw):
            raise BincodeError("truncated bool")
        b = raw[off]
        if b > 1:
            raise BincodeError(f"bad bool byte {b}")
        return bool(b), off + 1
    if kind == "bytes":
        n = schema[1]
        if off + n > len(raw):
            raise BincodeError("truncated bytes")
        return raw[off : off + n], off + n
    if kind == "option":
        if off >= len(raw):
            raise BincodeError("truncated option")
        tag = raw[off]
        if tag == 0:
            return None, off + 1
        if tag != 1:
            raise BincodeError(f"bad option tag {tag}")
        return decode(schema[1], raw, off + 1)
    if kind == "vec":
        n, off = decode("u64", raw, off)
        if n > len(raw) - off:  # cheap DoS guard: can't have n > bytes left
            raise BincodeError(f"vec length {n} exceeds input")
        out = []
        for _ in range(n):
            v, off = decode(schema[1], raw, off)
            out.append(v)
        return out, off
    if kind == "array":
        out = []
        for _ in range(schema[2]):
            v, off = decode(schema[1], raw, off)
            out.append(v)
        return out, off
    if kind == "string":
        n, off = decode("u64", raw, off)
        if off + n > len(raw):
            raise BincodeError("truncated string")
        return raw[off : off + n].decode(), off + n
    if kind == "varint":
        # serde_varint strictness (Agave varint.rs): reject values that
        # overflow u64 AND non-minimal encodings — a continuation group
        # contributing no bits (trailing 0x80* 0x00, or a final byte
        # whose payload lands entirely above bit 63) re-encodes shorter,
        # and Agave errors rather than accepting the alias
        v = 0
        sh = 0
        while True:
            if off >= len(raw):
                raise BincodeError("truncated varint")
            b = raw[off]
            off += 1
            if sh > 63 or (sh == 63 and (b & 0x7F) > 1):
                raise BincodeError("varint overflow")
            v |= (b & 0x7F) << sh
            if not b & 0x80:
                if sh and not b:
                    # zero FINAL byte after a continuation: the value
                    # re-encodes shorter (middle zero-payload bytes are
                    # legal — 128 is 0x80 0x01)
                    raise BincodeError("non-minimal varint")
                return v, off
            sh += 7
    if kind == "cvec":
        from ..ballet import compact_u16 as cu16
        try:
            n, used = cu16.decode(raw, off)
        except ValueError as e:
            raise BincodeError(str(e)) from e
        off += used
        if n > len(raw) - off:
            raise BincodeError(f"cvec length {n} exceeds input")
        out = []
        for _ in range(n):
            v, off = decode(schema[1], raw, off)
            out.append(v)
        return out, off
    if kind == "solana_txn":
        from ..ballet import txn as txn_lib
        try:
            _t, used = txn_lib.parse(bytes(raw[off:]), partial=True)
        except txn_lib.TxnParseError as e:
            raise BincodeError(f"embedded txn: {e}") from e
        return raw[off:off + used], off + used
    if kind == "struct":
        out = {}
        for name, sub in schema[1]:
            out[name], off = decode(sub, raw, off)
        return out, off
    if kind == "enum":
        idx, off = decode("u32", raw, off)
        variants = schema[1]
        if idx >= len(variants):
            raise BincodeError(f"enum variant {idx} out of range")
        name, sub = variants[idx]
        if sub is None:
            return (name, None), off
        v, off = decode(sub, raw, off)
        return (name, v), off
    raise BincodeError(f"unknown schema kind {kind}")


def loads(schema, raw: bytes, exact: bool = True):
    v, off = decode(schema, raw, 0)
    if exact and off != len(raw):
        raise BincodeError(f"{len(raw) - off} trailing bytes")
    return v


# ------------------------------------------------------- consensus types
# Layouts follow the upstream account formats the reference's generated
# types mirror (fd_types: fd_vote_state_versioned, fd_stake_state_v2,
# the sysvars).  Citations are the reference's type names.

PUBKEY = ("bytes", 32)
HASH = ("bytes", 32)

# fd_vote_lockout
LOCKOUT = ("struct", (
    ("slot", "u64"),
    ("confirmation_count", "u32"),
))

LANDED_VOTE = ("struct", (
    ("latency", "u8"),
    ("lockout", LOCKOUT),
))

# fd_vote_authorized_voters: map<epoch, pubkey> serialized as u64 len +
# (u64, pubkey) pairs
AUTHORIZED_VOTERS = ("vec", ("struct", (
    ("epoch", "u64"),
    ("pubkey", PUBKEY),
)))

PRIOR_VOTER = ("struct", (
    ("pubkey", PUBKEY),
    ("epoch_start", "u64"),
    ("epoch_end", "u64"),
))

# fd_vote_prior_voters: 32-entry ring + index + is_empty
PRIOR_VOTERS = ("struct", (
    ("buf", ("array", PRIOR_VOTER, 32)),
    ("idx", "u64"),
    ("is_empty", "bool"),
))

EPOCH_CREDITS = ("struct", (
    ("epoch", "u64"),
    ("credits", "u64"),
    ("prev_credits", "u64"),
))

BLOCK_TIMESTAMP = ("struct", (
    ("slot", "u64"),
    ("timestamp", "i64"),
))

# fd_vote_state_1_14_11 ("current" pre-1.14 layout, lockouts without
# latency) and the current variant with landed votes
_VOTE_STATE_COMMON_HEAD = (
    ("node_pubkey", PUBKEY),
    ("authorized_withdrawer", PUBKEY),
    ("commission", "u8"),
)
_VOTE_STATE_COMMON_TAIL = (
    ("root_slot", ("option", "u64")),
    ("authorized_voters", AUTHORIZED_VOTERS),
    ("prior_voters", PRIOR_VOTERS),
    ("epoch_credits", ("vec", EPOCH_CREDITS)),
    ("last_timestamp", BLOCK_TIMESTAMP),
)

VOTE_STATE_1_14_11 = ("struct", _VOTE_STATE_COMMON_HEAD + (
    ("votes", ("vec", LOCKOUT)),
) + _VOTE_STATE_COMMON_TAIL)

VOTE_STATE_CURRENT = ("struct", _VOTE_STATE_COMMON_HEAD + (
    ("votes", ("vec", LANDED_VOTE)),
) + _VOTE_STATE_COMMON_TAIL)

# fd_vote_state_versioned: enum {V0_23_5, V1_14_11, Current}
VOTE_STATE_VERSIONED = ("enum", (
    ("v0_23_5", None),            # legacy, not constructed by this runtime
    ("v1_14_11", VOTE_STATE_1_14_11),
    ("current", VOTE_STATE_CURRENT),
))

# fd_stake_state_v2
STAKE_AUTHORIZED = ("struct", (
    ("staker", PUBKEY),
    ("withdrawer", PUBKEY),
))

STAKE_LOCKUP = ("struct", (
    ("unix_timestamp", "i64"),
    ("epoch", "u64"),
    ("custodian", PUBKEY),
))

STAKE_META = ("struct", (
    ("rent_exempt_reserve", "u64"),
    ("authorized", STAKE_AUTHORIZED),
    ("lockup", STAKE_LOCKUP),
))

STAKE_DELEGATION = ("struct", (
    ("voter_pubkey", PUBKEY),
    ("stake", "u64"),
    ("activation_epoch", "u64"),
    ("deactivation_epoch", "u64"),
    ("warmup_cooldown_rate", "f64"),
))

STAKE = ("struct", (
    ("delegation", STAKE_DELEGATION),
    ("credits_observed", "u64"),
))

STAKE_STATE_V2 = ("enum", (
    ("uninitialized", None),
    ("initialized", STAKE_META),
    ("stake", ("struct", (
        ("meta", STAKE_META),
        ("stake", STAKE),
        ("stake_flags", "u8"),
    ))),
    ("rewards_pool", None),
))

# sysvars (fd_sysvar_*)
SYSVAR_CLOCK = ("struct", (
    ("slot", "u64"),
    ("epoch_start_timestamp", "i64"),
    ("epoch", "u64"),
    ("leader_schedule_epoch", "u64"),
    ("unix_timestamp", "i64"),
))

SYSVAR_RENT = ("struct", (
    ("lamports_per_byte_year", "u64"),
    ("exemption_threshold", "f64"),
    ("burn_percent", "u8"),
))

SYSVAR_EPOCH_SCHEDULE = ("struct", (
    ("slots_per_epoch", "u64"),
    ("leader_schedule_slot_offset", "u64"),
    ("warmup", "bool"),
    ("first_normal_epoch", "u64"),
    ("first_normal_slot", "u64"),
))

SYSVAR_SLOT_HASHES = ("vec", ("struct", (
    ("slot", "u64"),
    ("hash", HASH),
)))

SYSVAR_STAKE_HISTORY = ("vec", ("struct", (
    ("epoch", "u64"),
    ("effective", "u64"),
    ("activating", "u64"),
    ("deactivating", "u64"),
)))

SYSVAR_LAST_RESTART_SLOT = ("struct", (("last_restart_slot", "u64"),))
