"""Snapshots: full-state save/load for validator restart (ref:
src/flamenco/snapshot/fd_snapshot_restore.c — streaming an Agave-style
tar+zstd archive of append-vec account files into funk, driven by the
bincode manifest's storages list).

Archive layout (the Agave snapshot container):

    version                      format version string ("1.2.0")
    snapshots/<slot>/<slot>      BINCODE manifest (fd_solana_manifest
                                 layout — snapshot_manifest.py)
    accounts/<slot>.<id>         append-vec files

Append-vec record layout (fd_solana_account_hdr,
src/flamenco/types/fd_types.h:455-461: StoredMeta + AccountMeta +
32-byte account hash, then data padded to 8):

    u64 write_version | u64 data_len | pubkey[32]
    u64 lamports | u64 rent_epoch | owner[32] | u8 executable | pad[7]
    hash[32]
    data[data_len] | pad to 8-byte alignment

The whole tar is zstd-compressed.  Loading uses the from-scratch
ballet.zstd decoder (the validator boot path must not trust an external
codec); saving compresses via libzstd (`zstandard`), matching the
reference's decode-only scope for its own fd_zstd.

Restore cross-checks every append-vec against the manifest's declared
file_sz, as fd_snapshot_restore does (fd_snapshot_restore.c:338-360).

Restart = Runtime.from_snapshot(genesis, path): restore funk, rebuild the
blockhash queue, resume banking at slot+1 — mechanism (3) of the
reference's checkpoint/resume trio (SURVEY.md §5)."""

import io
import struct
import tarfile

from ..ballet import zstd as zstd_dec
from ..funk import Funk
from . import snapshot_manifest as man
from .types import Account

FORMAT_VERSION = "1.2.0"
_STORED_META = struct.Struct("<QQ32s")       # write_version, data_len, pubkey
_ACCOUNT_META = struct.Struct("<QQ32sB7x")   # lamports, rent_epoch, owner, exec
_HASH_SZ = 32                                # stored account hash (obsolete
# in current Agave, carried for layout compatibility; written as zeros)
APPENDVEC_CHUNK = 1 << 20  # split account files about this big (many small
# append-vecs is the Agave shape: one per slot/id)


def _pad8(n: int) -> int:
    return (8 - n % 8) % 8


def write_appendvec(accounts) -> bytes:
    """Serialize [(pubkey, Account)] into one append-vec file."""
    out = io.BytesIO()
    for i, (pk, acct) in enumerate(accounts):
        out.write(_STORED_META.pack(i, len(acct.data), pk))
        out.write(_ACCOUNT_META.pack(acct.lamports, acct.rent_epoch,
                                     acct.owner, acct.executable))
        out.write(bytes(_HASH_SZ))
        out.write(acct.data)
        out.write(bytes(_pad8(len(acct.data))))
    return out.getvalue()


def read_appendvec(raw: bytes):
    """Yield (pubkey, Account) from an append-vec file."""
    hdr_sz = _STORED_META.size + _ACCOUNT_META.size + _HASH_SZ
    off = 0
    while off + hdr_sz <= len(raw):
        _wv, dlen, pk = _STORED_META.unpack_from(raw, off)
        off += _STORED_META.size
        lam, rent, owner, execu = _ACCOUNT_META.unpack_from(raw, off)
        off += _ACCOUNT_META.size + _HASH_SZ
        if off + dlen > len(raw):
            raise ValueError("append-vec record truncated")
        data = bytes(raw[off:off + dlen])
        off += dlen + _pad8(dlen)
        yield bytes(pk), Account(lamports=lam, data=data, owner=bytes(owner),
                                 executable=bool(execu), rent_epoch=rent)


def save(path: str, funk: Funk, *, slot: int, bank_hash: bytes,
         blockhashes: list[bytes], parent_hash: bytes = bytes(32),
         genesis_creation_time: int = 0, slots_per_epoch: int = 432_000,
         transaction_count: int = 0):
    """Write a snapshot of the funk ROOT (published state only — in-flight
    forks are by definition not yet consensus and are never snapshotted)."""
    import zstandard

    vecs: list[bytes] = []
    cur: list[tuple[bytes, Account]] = []
    cur_sz = 0
    capitalization = 0
    for key in funk.keys(None):
        val = funk.read(None, key)
        if val is None:
            continue
        acct = Account.deserialize(val)
        cur.append((key, acct))
        cur_sz += (_STORED_META.size + _ACCOUNT_META.size + _HASH_SZ
                   + len(acct.data) + _pad8(len(acct.data)))
        capitalization += acct.lamports
        if cur_sz >= APPENDVEC_CHUNK:
            vecs.append(write_appendvec(cur))
            cur, cur_sz = [], 0
    if cur or not vecs:
        vecs.append(write_appendvec(cur))

    manifest = {
        "bank": man.default_bank(
            slot, bank_hash, parent_hash, blockhashes,
            genesis_creation_time=genesis_creation_time,
            slots_per_epoch=slots_per_epoch,
            transaction_count=transaction_count,
            capitalization=capitalization),
        "accounts_db": man.default_accounts_db(
            slot, [(slot, i, len(blob)) for i, blob in enumerate(vecs)],
            bank_hash),
        "lamports_per_signature": 5000,
    }

    tar_buf = io.BytesIO()
    with tarfile.open(fileobj=tar_buf, mode="w") as tar:
        def add(name: str, data: bytes):
            ti = tarfile.TarInfo(name)
            ti.size = len(data)
            tar.addfile(ti, io.BytesIO(data))

        add("version", FORMAT_VERSION.encode())
        add(f"snapshots/{slot}/{slot}", man.encode_manifest(manifest))
        for i, blob in enumerate(vecs):
            add(f"accounts/{slot}.{i}", blob)

    comp = zstandard.ZstdCompressor(level=3).compress(tar_buf.getvalue())
    with open(path, "wb") as f:
        f.write(comp)


def load(path: str) -> tuple[dict, Funk]:
    """Returns (info, funk-with-root-state).  info carries the restart
    surface derived from the decoded bincode manifest: slot, bank_hash,
    blockhashes, plus the full manifest under "manifest".  Decompression
    goes through the in-tree zstd decoder."""
    with open(path, "rb") as f:
        comp = f.read()
    raw = zstd_dec.decompress(comp, max_output=1 << 33)
    funk = Funk()
    manifest = None
    version = None
    vecs: dict[tuple[int, int], bytes] = {}
    with tarfile.open(fileobj=io.BytesIO(raw), mode="r") as tar:
        for m in tar.getmembers():
            if not m.isfile():
                continue  # real Agave archives carry directory members
            parts = m.name.split("/")
            if m.name == "version":
                version = tar.extractfile(m).read().decode().strip()
            elif (len(parts) == 3 and parts[0] == "snapshots"
                    and parts[1] == parts[2]):
                # exactly snapshots/<slot>/<slot>; other members under
                # snapshots/ (status_cache, directories) are not the
                # manifest
                manifest = man.decode_manifest(tar.extractfile(m).read())
            elif (len(parts) == 2 and parts[0] == "accounts"
                    and parts[1].count(".") == 1):
                sl, idx = parts[1].split(".")
                if sl.isdigit() and idx.isdigit():
                    vecs[(int(sl), int(idx))] = tar.extractfile(m).read()
    if version is not None and version != FORMAT_VERSION:
        raise ValueError(f"snapshot version {version!r} != {FORMAT_VERSION}")
    if manifest is None:
        raise ValueError("snapshot missing manifest")

    # restore in manifest-storage order, size-checking each append-vec
    # (fd_snapshot_restore.c:338-360)
    n = 0
    for st in manifest["accounts_db"]["storages"]:
        for av in st["account_vecs"]:
            key = (st["slot"], av["id"])
            blob = vecs.get(key)
            if blob is None:
                raise ValueError(f"append-vec {key} missing from archive")
            if len(blob) < av["file_sz"]:
                raise ValueError(
                    f"append-vec {key}: manifest says {av['file_sz']} bytes, "
                    f"archive has {len(blob)}")
            for pk, acct in read_appendvec(blob[: av["file_sz"]]):
                funk.write(None, pk, acct.serialize())
                n += 1

    bank = manifest["bank"]
    ages = sorted(bank["blockhash_queue"]["ages"],
                  key=lambda a: a["val"]["hash_index"])
    info = {
        "slot": bank["slot"],
        "bank_hash": bytes(bank["hash"]),
        "blockhashes": [bytes(a["key"]) for a in ages],
        "record_cnt": n,
        "manifest": manifest,
    }
    return info, funk
