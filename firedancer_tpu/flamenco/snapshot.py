"""Snapshots: full-state save/load for validator restart (ref:
src/flamenco/snapshot/fd_snapshot.c — streaming an Agave-style tar+zstd
archive of append-vec account files into funk).

Archive layout (mirrors the Agave snapshot container the reference loads):

    version                      format version string
    snapshots/<slot>/<slot>      manifest (JSON here; Agave uses bincode —
                                 the 34k-type generated surface; the
                                 container + account layout are the
                                 compatibility point, SURVEY.md §5)
    accounts/<slot>.<id>         append-vec files

Append-vec record layout (Agave's StoredMeta + AccountMeta wire shape,
ref fd_snapshot_restore.c account frame parsing):

    u64 write_version | u64 data_len | pubkey[32]
    u64 lamports | u64 rent_epoch | owner[32] | u8 executable | pad[7]
    data[data_len] | pad to 8-byte alignment

The whole tar is zstd-compressed.  Loading uses the from-scratch
ballet.zstd decoder (the validator boot path must not trust an external
codec); saving compresses via libzstd (`zstandard`), matching the
reference's decode-only scope for its own fd_zstd.

Restart = Runtime.from_snapshot(genesis, path): restore funk, rebuild the
blockhash queue, resume banking at slot+1 — mechanism (3) of the
reference's checkpoint/resume trio (SURVEY.md §5)."""

import io
import json
import struct
import tarfile

from ..ballet import zstd as zstd_dec
from ..funk import Funk
from .types import Account

FORMAT_VERSION = "1.2.0"
_STORED_META = struct.Struct("<QQ32s")       # write_version, data_len, pubkey
_ACCOUNT_META = struct.Struct("<QQ32sB7x")   # lamports, rent_epoch, owner, exec
APPENDVEC_CHUNK = 1 << 20  # split account files about this big (many small
# append-vecs is the Agave shape: one per slot/id)


def _pad8(n: int) -> int:
    return (8 - n % 8) % 8


def write_appendvec(accounts) -> bytes:
    """Serialize [(pubkey, Account)] into one append-vec file."""
    out = io.BytesIO()
    for i, (pk, acct) in enumerate(accounts):
        out.write(_STORED_META.pack(i, len(acct.data), pk))
        out.write(_ACCOUNT_META.pack(acct.lamports, acct.rent_epoch,
                                     acct.owner, acct.executable))
        out.write(acct.data)
        out.write(bytes(_pad8(len(acct.data))))
    return out.getvalue()


def read_appendvec(raw: bytes):
    """Yield (pubkey, Account) from an append-vec file."""
    off = 0
    while off + _STORED_META.size + _ACCOUNT_META.size <= len(raw):
        _wv, dlen, pk = _STORED_META.unpack_from(raw, off)
        off += _STORED_META.size
        lam, rent, owner, execu = _ACCOUNT_META.unpack_from(raw, off)
        off += _ACCOUNT_META.size
        if off + dlen > len(raw):
            raise ValueError("append-vec record truncated")
        data = bytes(raw[off:off + dlen])
        off += dlen + _pad8(dlen)
        yield bytes(pk), Account(lamports=lam, data=data, owner=bytes(owner),
                                 executable=bool(execu), rent_epoch=rent)


def save(path: str, funk: Funk, *, slot: int, bank_hash: bytes,
         blockhashes: list[bytes]):
    """Write a snapshot of the funk ROOT (published state only — in-flight
    forks are by definition not yet consensus and are never snapshotted)."""
    import zstandard

    vecs: list[bytes] = []
    cur: list[tuple[bytes, Account]] = []
    cur_sz = 0
    n = 0
    for key in funk.keys(None):
        val = funk.read(None, key)
        if val is None:
            continue
        acct = Account.deserialize(val)
        cur.append((key, acct))
        cur_sz += 80 + len(acct.data)
        n += 1
        if cur_sz >= APPENDVEC_CHUNK:
            vecs.append(write_appendvec(cur))
            cur, cur_sz = [], 0
    if cur or not vecs:
        vecs.append(write_appendvec(cur))

    manifest = {
        "version": FORMAT_VERSION,
        "slot": slot,
        "bank_hash": bank_hash.hex(),
        "blockhashes": [h.hex() for h in blockhashes],
        "record_cnt": n,
        "appendvec_cnt": len(vecs),
    }

    tar_buf = io.BytesIO()
    with tarfile.open(fileobj=tar_buf, mode="w") as tar:
        def add(name: str, data: bytes):
            ti = tarfile.TarInfo(name)
            ti.size = len(data)
            tar.addfile(ti, io.BytesIO(data))

        add("version", FORMAT_VERSION.encode())
        add(f"snapshots/{slot}/{slot}", json.dumps(manifest).encode())
        for i, blob in enumerate(vecs):
            add(f"accounts/{slot}.{i}", blob)

    comp = zstandard.ZstdCompressor(level=3).compress(tar_buf.getvalue())
    with open(path, "wb") as f:
        f.write(comp)


def load(path: str) -> tuple[dict, Funk]:
    """Returns (manifest, funk-with-root-state).  Decompression goes
    through the in-tree zstd decoder."""
    with open(path, "rb") as f:
        comp = f.read()
    raw = zstd_dec.decompress(comp, max_output=1 << 33)
    funk = Funk()
    manifest = None
    vecs: dict[int, bytes] = {}
    with tarfile.open(fileobj=io.BytesIO(raw), mode="r") as tar:
        for m in tar.getmembers():
            if m.name.startswith("snapshots/"):
                manifest = json.loads(tar.extractfile(m).read())
            elif m.name.startswith("accounts/"):
                idx = int(m.name.rsplit(".", 1)[1])
                vecs[idx] = tar.extractfile(m).read()
    if manifest is None:
        raise ValueError("snapshot missing manifest")
    if manifest["version"] != FORMAT_VERSION:
        raise ValueError(f"snapshot version {manifest['version']}")
    n = 0
    for idx in sorted(vecs):
        for pk, acct in read_appendvec(vecs[idx]):
            funk.write(None, pk, acct.serialize())
            n += 1
    if n != manifest["record_cnt"]:
        raise ValueError(f"snapshot truncated: {n}/{manifest['record_cnt']}")
    return manifest, funk
