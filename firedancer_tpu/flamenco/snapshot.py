"""Snapshots: full-state save/load for validator restart (ref:
src/flamenco/snapshot/ — fd_snapshot_load.c streams an Agave tar+zstd
archive into funk; ours snapshots OUR state: the funk root's account
records plus the chain tip metadata).

Format: a tar archive (stdlib) holding
    manifest.json        {slot, bank_hash(hex), blockhashes[], version}
    accounts.bin         repeated: u32 klen | key | u32 vlen | val
compressed with gzip (the stdlib codec; the reference uses zstd — the
container format is the design point, the codec is fungible).

Restart = Runtime.from_snapshot(genesis, path): restore funk, rebuild the
blockhash queue, resume banking at slot+1 — mechanism (3) of the
reference's checkpoint/resume trio (SURVEY.md §5), funk's own wksp
checkpoint being mechanism (1), covered by funk.checkpoint/restore.
"""

import io
import json
import struct
import tarfile

from ..funk import Funk

FORMAT_VERSION = 1


def save(path: str, funk: Funk, *, slot: int, bank_hash: bytes,
         blockhashes: list[bytes]):
    """Write a snapshot of the funk ROOT (published state only — in-flight
    forks are by definition not yet consensus and are never snapshotted)."""
    manifest = {
        "version": FORMAT_VERSION,
        "slot": slot,
        "bank_hash": bank_hash.hex(),
        "blockhashes": [h.hex() for h in blockhashes],
    }
    acc = io.BytesIO()
    n = 0
    for key in funk.keys(None):
        val = funk.read(None, key)
        if val is None:
            continue
        acc.write(struct.pack("<I", len(key)) + key)
        acc.write(struct.pack("<I", len(val)) + val)
        n += 1
    manifest["record_cnt"] = n

    with tarfile.open(path, "w:gz") as tar:
        mb = json.dumps(manifest).encode()
        ti = tarfile.TarInfo("manifest.json")
        ti.size = len(mb)
        tar.addfile(ti, io.BytesIO(mb))
        ti = tarfile.TarInfo("accounts.bin")
        ti.size = acc.tell()
        acc.seek(0)
        tar.addfile(ti, acc)


def load(path: str) -> tuple[dict, Funk]:
    """Returns (manifest, funk-with-root-state)."""
    with tarfile.open(path, "r:gz") as tar:
        manifest = json.loads(tar.extractfile("manifest.json").read())
        if manifest["version"] != FORMAT_VERSION:
            raise ValueError(f"snapshot version {manifest['version']}")
        raw = tar.extractfile("accounts.bin").read()
    funk = Funk()
    off = 0
    n = 0
    while off < len(raw):
        (klen,) = struct.unpack_from("<I", raw, off)
        off += 4
        key = bytes(raw[off : off + klen])
        off += klen
        (vlen,) = struct.unpack_from("<I", raw, off)
        off += 4
        funk.write(None, key, bytes(raw[off : off + vlen]))
        off += vlen
        n += 1
    if n != manifest["record_cnt"]:
        raise ValueError(f"snapshot truncated: {n}/{manifest['record_cnt']}")
    return manifest, funk
