"""Stake-weighted leader schedule (ref: src/flamenco/leaders/fd_leaders.c):
epoch seed -> ChaCha20 rng -> weighted sampling over staked nodes, each
draw covering NUM_CONSECUTIVE_LEADER_SLOTS slots."""

import struct

from ..ballet.chacha20 import ChaCha20Rng
from ..ballet.wsample import WSample

NUM_CONSECUTIVE_LEADER_SLOTS = 4


def leader_schedule(epoch: int, stakes: dict[bytes, int],
                    slots_in_epoch: int) -> list[bytes]:
    """Returns the leader pubkey for each slot of the epoch.

    stakes: node pubkey -> active stake (zero-stake nodes excluded).
    Deterministic across every validator: nodes sort by (stake desc, pubkey
    desc) before sampling, the rng seeds from the epoch (fd_leaders.c
    ordering contract)."""
    staked = sorted(
        ((pk, st) for pk, st in stakes.items() if st > 0),
        key=lambda kv: (kv[1], kv[0]), reverse=True)
    if not staked:
        raise ValueError("no staked nodes")
    rng = ChaCha20Rng(struct.pack("<Q", epoch) + bytes(24))
    ws = WSample([st for _, st in staked])
    n_draws = (slots_in_epoch + NUM_CONSECUTIVE_LEADER_SLOTS - 1) \
        // NUM_CONSECUTIVE_LEADER_SLOTS
    sched = []
    for _ in range(n_draws):
        idx = ws.sample(rng)
        sched += [staked[idx][0]] * NUM_CONSECUTIVE_LEADER_SLOTS
    return sched[:slots_in_epoch]
