"""Parallel block execution: account-lock waves over process workers.

Reference role: fd_runtime_block_eval_tpool (src/flamenco/runtime/
fd_runtime.h:194, workers from src/util/tpool/fd_tpool.h:740-850) — a
block's transactions execute concurrently wherever their account locks
don't conflict.

Shape here:

  1. PLAN: partition the block's txns into conflict-free WAVES by
     account locks (two txns conflict iff an account writable in one is
     referenced at all by the other — Solana's rw-lock rule).  Txns in
     one wave commute: any execution order gives identical state.
  2. EXECUTE: each wave runs on a fork()-based process pool (real
     parallelism — thread pools can't help a Python interpreter here;
     the reference's tpool threads map to processes).  The fork gives
     every worker a snapshot of the fork bank including all prior
     waves' writes, for free, copy-on-write.
  3. MERGE: workers return (pre, post) serialized account states; the
     parent applies posts to funk and folds the accounts-delta lthash.
     lthash is commutative (add/sub homomorphism, ballet/lthash), so
     the merged delta — and therefore the bank hash — is bit-identical
     to serial execution.

Fallback: single-core hosts and tiny waves execute serially (fork +
pickle overhead would dominate)."""

from __future__ import annotations

import multiprocessing
import os
from dataclasses import dataclass

from ..ballet import lthash
from ..ballet import txn as txn_lib
from .executor import TxnResult

# a wave smaller than this executes serially: fork+IPC costs ~ms while
# a light txn executes in ~100us
MIN_PARALLEL_WAVE = 8


@dataclass
class _TxnPlan:
    idx: int
    payload: bytes
    parsed: object | None       # None = parse failed (serial no-op)
    writable: frozenset
    readonly: frozenset


def plan_waves(payloads: list[bytes], addrs_of) -> list[list[_TxnPlan]]:
    """Greedy wave partition.  addrs_of(parsed, payload) -> (addrs,
    writable_flags) for STATIC message accounts.  Order inside the block
    is preserved per-account: a txn joins the EARLIEST wave with no
    conflict against any wave that wrote an account it touches or
    touched an account it writes.

    Address-lookup-table txns are BARRIERS: their true lock set depends
    on table state at their execution point (an earlier txn in the same
    block may extend the table), so plan-time resolution can be stale.
    A barrier txn gets a wave of its own, ordered strictly between its
    neighbours — serial execution exactly where parallel locks cannot be
    derived soundly."""
    waves: list[list[_TxnPlan]] = []
    last_write: dict[bytes, int] = {}   # account -> last wave writing it
    last_touch: dict[bytes, int] = {}   # account -> last wave referencing it
    global_floor = -1                   # barriers order everything after
    for i, payload in enumerate(payloads):
        try:
            parsed = txn_lib.parse(payload)
            addrs, wr = addrs_of(parsed, payload)
        except txn_lib.TxnParseError:
            parsed, addrs, wr = None, [], []
        if parsed is not None and parsed.addr_table_lookup_cnt:
            w = len(waves)              # barrier: own wave after all
            waves.append([_TxnPlan(i, payload, parsed,
                                   frozenset(), frozenset())])
            global_floor = w
            # everything it might touch is unknown: order every later
            # txn after it
            for a in list(last_write):
                last_write[a] = max(last_write[a], w)
            for a in list(last_touch):
                last_touch[a] = max(last_touch[a], w)
            continue
        writable = frozenset(a for a, w_ in zip(addrs, wr) if w_)
        readonly = frozenset(a for a, w_ in zip(addrs, wr) if not w_)
        # earliest legal wave: after any wave that WROTE an account we
        # touch, and after any wave that TOUCHED an account we write
        floor = global_floor
        for a in writable | readonly:
            floor = max(floor, last_write.get(a, -1))
        for a in writable:
            floor = max(floor, last_touch.get(a, -1))
        w = floor + 1
        while len(waves) <= w:
            waves.append([])
        plan = _TxnPlan(i, payload, parsed, writable, readonly)
        waves[w].append(plan)
        for a in writable:
            last_write[a] = max(last_write.get(a, -1), w)
        for a in writable | readonly:
            last_touch[a] = max(last_touch.get(a, -1), w)
    return waves


# ---------------------------------------------------------------- workers

_WCTX = None  # (runtime, xid, slot, epoch, blockhash_queue) captured at fork


def _exec_capture(rt, xid, slot, epoch, payload, parsed, bh_queue=None):
    """Execute one txn, returning (TxnResult, sig_cnt, [(pk, pre, post)])
    — the Bank.execute_txn pre/post recipe without the shared-state
    delta fold (the parent does that on merge).

    bh_queue: the BANK's fork-local blockhash queue — recency must follow
    the replayed fork's ancestor chain exactly as the serial path does
    (Bank.execute_txn passes its own queue); falling back to the
    executor's constructor default would check a stale runtime-wide
    window and diverge from serial execution."""
    ex = rt.executor
    if parsed is None:
        return TxnResult(False, "parse failed"), 0, []
    addrs = list(parsed.account_addrs(payload))
    resolved = None
    if parsed.addr_table_lookup_cnt:
        from .alut_program import TxnLookupError, resolve_lookups
        from .system_program import InstrError
        try:
            resolved = resolve_lookups(ex.accdb, xid, parsed, payload)
            addrs += resolved[0]
        except (TxnLookupError, InstrError, ValueError) as e:
            resolved = e
    pre = {}
    for pk in addrs:
        if pk not in pre:
            pre[pk] = rt.funk.read(xid, pk)
    res = ex.execute_txn(
        xid, payload, parsed, epoch=epoch, slot=slot,
        resolved_lookups=resolved,
        blockhash_check=None if bh_queue is None else bh_queue.is_recent)
    changes = []
    for pk, old in pre.items():
        new = rt.funk.read(xid, pk)
        if new != old:
            changes.append((pk, old, new))
    return res, parsed.signature_cnt, changes


def _worker(args):
    idx, payload = args
    rt, xid, slot, epoch, bh_queue = _WCTX
    parsed = None
    try:
        parsed = txn_lib.parse(payload)
    except txn_lib.TxnParseError:
        pass
    res, sigs, changes = _exec_capture(rt, xid, slot, epoch, payload, parsed,
                                       bh_queue)
    # counted=False mirrors Bank.execute_txn's early return on parse
    # failure (no txn_cnt/fee accounting for unparseable payloads)
    return idx, res, sigs, changes, parsed is not None


def execute_block_parallel(bank, payloads: list[bytes],
                           workers: int | None = None) -> list[TxnResult]:
    """Execute a whole block's txns into `bank` with wave parallelism.
    Returns per-txn TxnResults in block order.  Bit-identical bank hash
    to serial execution (tests assert it)."""
    global _WCTX
    rt = bank.rt

    def addrs_of(parsed, payload):
        # static message accounts only; lookup txns never reach here
        # (plan_waves barriers them — their lock set is state-dependent)
        addrs = list(parsed.account_addrs(payload))
        return addrs, [parsed.is_writable(i) for i in range(len(addrs))]

    if workers is None:
        workers = min(os.cpu_count() or 1, 8)
    waves = plan_waves(payloads, addrs_of)
    results: dict[int, TxnResult] = {}
    for wave in waves:
        if workers <= 1 or len(wave) < MIN_PARALLEL_WAVE:
            for plan in wave:
                results[plan.idx] = bank.execute_txn(plan.payload)
            continue
        # fork AFTER prior waves committed: children see their writes
        _WCTX = (rt, bank.xid, bank.slot, bank.epoch, bank.blockhash_queue)
        ctx = multiprocessing.get_context("fork")
        with ctx.Pool(min(workers, len(wave))) as pool:
            outs = pool.map(_worker,
                            [(p.idx, p.payload) for p in wave])
        _WCTX = None
        for idx, res, sigs, changes, counted in outs:
            results[idx] = res
            if not counted:
                continue
            bank.signature_cnt += sigs
            bank.txn_cnt += 1
            bank.fees += res.fee
            for pk, old, new in changes:
                if new is None:
                    rt.funk.remove(bank.xid, pk)
                else:
                    rt.funk.write(bank.xid, pk, new)
                if old is not None:
                    bank.delta = lthash.sub(
                        bank.delta, lthash.hash_account(pk + old))
                if new is not None:
                    bank.delta = lthash.add(
                        bank.delta, lthash.hash_account(pk + new))
    return [results[i] for i in range(len(payloads))]
