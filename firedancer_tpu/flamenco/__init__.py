"""flamenco — the Solana runtime layer (ref: src/flamenco/).

Execution (accounts, native programs, fees, bank hashing) over the funk
fork database, leader schedules, genesis, and the sBPF VM.  Host-side
control plane in Python; the batch-crypto data plane (sigverify, hashes)
stays on-device via the ops/ layer.
"""
