"""Agave-layout snapshot manifest: the bincode type surface.

Parity contract: fd_solana_manifest and its component types
(src/flamenco/types/fd_types.h:905-1229, decode order
src/flamenco/types/fd_types.c:5212-5251 — bank, accounts_db,
lamports_per_signature, then stream-truncatable bincode-Option trailing
fields) as consumed by fd_snapshot_restore_manifest
(src/flamenco/snapshot/fd_snapshot_restore.c:245-299).

Everything rides the declarative bincode engine (bincode.py); the only
special case is the manifest's trailing optionals, which upstream treats
as "present if bytes remain" — encode_manifest/decode_manifest handle
that framing explicitly.

u128 note: bincode serializes Rust's u128 (ns_per_slot) as 16 LE bytes;
the engine has no u128 scalar, so the schema models it as two u64s
(lo, hi) — wire-identical.
"""

from __future__ import annotations

from . import bincode as bc
# identical wire contracts defined once in the consensus-type layer —
# a drift between copies would be a silent fork of the format
from .bincode import HASH, PUBKEY
from .bincode import STAKE_DELEGATION as DELEGATION
from .bincode import SYSVAR_EPOCH_SCHEDULE as EPOCH_SCHEDULE
from .bincode import SYSVAR_STAKE_HISTORY as STAKE_HISTORY

# -- bank components (fd_types.h cites per struct) --------------------------

FEE_CALCULATOR = ("struct", (              # fd_fee_calculator (h:28)
    ("lamports_per_signature", "u64"),
))

HASH_AGE = ("struct", (                    # fd_hash_age (h:71)
    ("fee_calculator", FEE_CALCULATOR),
    ("hash_index", "u64"),
    ("timestamp", "u64"),
))

BLOCK_HASH_VEC = ("struct", (              # fd_block_hash_vec (h:107)
    ("last_hash_index", "u64"),
    ("last_hash", ("option", HASH)),
    ("ages", ("vec", ("struct", (          # fd_hash_hash_age_pair (h:90)
        ("key", HASH),
        ("val", HASH_AGE),
    )))),
    ("max_age", "u64"),
))

SLOT_PAIR = ("struct", (("slot", "u64"), ("val", "u64")))  # fd_slot_pair

HARD_FORKS = ("vec", SLOT_PAIR)            # fd_hard_forks (h:211)

FEE_RATE_GOVERNOR = ("struct", (           # fd_fee_rate_governor (h:171)
    ("target_lamports_per_signature", "u64"),
    ("target_signatures_per_slot", "u64"),
    ("min_lamports_per_signature", "u64"),
    ("max_lamports_per_signature", "u64"),
    ("burn_percent", "u8"),
))

RENT = ("struct", (                        # fd_rent (h:253)
    ("lamports_per_uint8_year", "u64"),
    ("exemption_threshold", "f64"),
    ("burn_percent", "u8"),
))


RENT_COLLECTOR = ("struct", (              # fd_rent_collector (h:296)
    ("epoch", "u64"),
    ("epoch_schedule", EPOCH_SCHEDULE),
    ("slots_per_year", "f64"),
    ("rent", RENT),
))

INFLATION = ("struct", (                   # fd_inflation (h:227)
    ("initial", "f64"),
    ("terminal", "f64"),
    ("taper", "f64"),
    ("foundation", "f64"),
    ("foundation_term", "f64"),
    ("unused", "f64"),
))

# full account body as stored in the stakes maps (fd_solana_account, h:388)
SOLANA_ACCOUNT = ("struct", (
    ("lamports", "u64"),
    ("data", ("vec", "u8")),
    ("owner", PUBKEY),
    ("executable", "bool"),
    ("rent_epoch", "u64"),
))

# HashMap<Pubkey, (u64, Account)> — fd_vote_accounts_pair (h:502)
VOTE_ACCOUNTS = ("vec", ("struct", (
    ("key", PUBKEY),
    ("stake", "u64"),
    ("value", SOLANA_ACCOUNT),
)))


STAKE_DELEGATIONS = ("vec", ("struct", (   # fd_delegation_pair (h:688)
    ("account", PUBKEY),
    ("delegation", DELEGATION),
)))

STAKES = ("struct", (                      # fd_stakes (h:726)
    ("vote_accounts", VOTE_ACCOUNTS),
    ("stake_delegations", STAKE_DELEGATIONS),
    ("unused", "u64"),
    ("epoch", "u64"),
    ("stake_history", STAKE_HISTORY),
))

UNUSED_ACCOUNTS = ("struct", (             # fd_unused_accounts (h:882)
    ("unused1", ("vec", PUBKEY)),
    ("unused2", ("vec", PUBKEY)),
    ("unused3", ("vec", ("struct", (("key", PUBKEY), ("val", "u64"))))),
))

NODE_VOTE_ACCOUNTS = ("struct", (          # fd_node_vote_accounts (h:773)
    ("vote_accounts", ("vec", PUBKEY)),
    ("total_stake", "u64"),
))

EPOCH_STAKES = ("struct", (                # fd_epoch_stakes (h:825)
    ("stakes", STAKES),
    ("total_stake", "u64"),
    ("node_id_to_vote_accounts", ("vec", ("struct", (
        ("key", PUBKEY),
        ("value", NODE_VOTE_ACCOUNTS),
    )))),
    ("epoch_authorized_voters", ("vec", ("struct", (
        ("key", PUBKEY),
        ("value", PUBKEY),
    )))),
))

# fd_deserializable_versioned_bank (h:905-940), field-for-field
BANK = ("struct", (
    ("blockhash_queue", BLOCK_HASH_VEC),
    ("ancestors", ("vec", SLOT_PAIR)),
    ("hash", HASH),
    ("parent_hash", HASH),
    ("parent_slot", "u64"),
    ("hard_forks", HARD_FORKS),
    ("transaction_count", "u64"),
    ("tick_height", "u64"),
    ("signature_count", "u64"),
    ("capitalization", "u64"),
    ("max_tick_height", "u64"),
    ("hashes_per_tick", ("option", "u64")),
    ("ticks_per_slot", "u64"),
    ("ns_per_slot_lo", "u64"),             # u128 as two LE u64 halves
    ("ns_per_slot_hi", "u64"),
    ("genesis_creation_time", "u64"),
    ("slots_per_year", "f64"),
    ("accounts_data_len", "u64"),
    ("slot", "u64"),
    ("epoch", "u64"),
    ("block_height", "u64"),
    ("collector_id", PUBKEY),
    ("collector_fees", "u64"),
    ("fee_calculator", FEE_CALCULATOR),
    ("fee_rate_governor", FEE_RATE_GOVERNOR),
    ("collected_rent", "u64"),
    ("rent_collector", RENT_COLLECTOR),
    ("epoch_schedule", EPOCH_SCHEDULE),
    ("inflation", INFLATION),
    ("stakes", STAKES),
    ("unused_accounts", UNUSED_ACCOUNTS),
    ("epoch_stakes", ("vec", ("struct", (  # fd_epoch_epoch_stakes_pair
        ("key", "u64"),
        ("value", EPOCH_STAKES),
    )))),
    ("is_delta", "bool"),
))

# -- accounts db (fd_solana_accounts_db_fields, h:1182) ---------------------

SNAPSHOT_ACC_VEC = ("struct", (            # fd_snapshot_acc_vec (h:1043)
    ("id", "u64"),
    ("file_sz", "u64"),
))

SLOT_ACC_VECS = ("struct", (               # fd_snapshot_slot_acc_vecs
    ("slot", "u64"),
    ("account_vecs", ("vec", SNAPSHOT_ACC_VEC)),
))

BANK_HASH_STATS = ("struct", (             # fd_bank_hash_stats (h:984)
    ("num_updated_accounts", "u64"),
    ("num_removed_accounts", "u64"),
    ("num_lamports_stored", "u64"),
    ("total_data_len", "u64"),
    ("num_executable_accounts", "u64"),
))

BANK_HASH_INFO = ("struct", (              # fd_bank_hash_info (h:1007)
    ("hash", HASH),
    ("snapshot_hash", HASH),
    ("stats", BANK_HASH_STATS),
))

ACCOUNTS_DB = ("struct", (
    ("storages", ("vec", SLOT_ACC_VECS)),
    ("version", "u64"),
    ("slot", "u64"),
    ("bank_hash_info", BANK_HASH_INFO),
    ("historical_roots", ("vec", "u64")),
    ("historical_roots_with_hash", ("vec", ("struct", (
        ("slot", "u64"),
        ("hash", HASH),
    )))),
))

INCREMENTAL_PERSISTENCE = ("struct", (     # fd_bank_incremental_... (h:750)
    ("full_slot", "u64"),
    ("full_hash", HASH),
    ("full_capitalization", "u64"),
    ("incremental_hash", HASH),
    ("incremental_capitalization", "u64"),
))

_CORE = ("struct", (
    ("bank", BANK),
    ("accounts_db", ACCOUNTS_DB),
    ("lamports_per_signature", "u64"),
))


def encode_manifest(m: dict) -> bytes:
    """m carries the _CORE fields plus optional
    incremental_snapshot_persistence / epoch_account_hash (trailing
    bincode options, emitted only when present — upstream's framing)."""
    out = bc.encode(_CORE, m)
    tail_keys = ("incremental_snapshot_persistence", "epoch_account_hash")
    tails = [m.get(k) for k in tail_keys]
    schemas = (INCREMENTAL_PERSISTENCE, HASH)
    # once a later field is present, earlier Nones must be explicit
    last = max((i for i, t in enumerate(tails) if t is not None), default=-1)
    for i in range(last + 1):
        out += bc.encode(("option", schemas[i]), tails[i])
    return out


def decode_manifest(raw: bytes) -> dict:
    """fd_solana_manifest_decode semantics: core fields, then each
    trailing option only if bytes remain (fd_types.c:5220-5249)."""
    m, off = bc.decode(_CORE, raw, 0)
    for key, schema in (
            ("incremental_snapshot_persistence", INCREMENTAL_PERSISTENCE),
            ("epoch_account_hash", HASH)):
        if off == len(raw):
            break
        m[key], off = bc.decode(("option", schema), raw, off)
    # epoch_reward_status would follow the same pattern; this runtime
    # neither emits nor consumes partitioned-rewards state yet, so any
    # remaining bytes are rejected loudly rather than skipped silently
    if off != len(raw):
        raise bc.BincodeError(
            f"{len(raw) - off} trailing manifest bytes (epoch_reward_status "
            "not supported)")
    return m


def default_bank(slot: int, bank_hash: bytes, parent_hash: bytes,
                 blockhashes: list[bytes], *, genesis_creation_time: int = 0,
                 slots_per_epoch: int = 432_000, ticks_per_slot: int = 64,
                 transaction_count: int = 0, capitalization: int = 0,
                 epoch: int | None = None) -> dict:
    """A minimally-populated DeserializableVersionedBank value: every
    field the schema demands, with this runtime's state where it exists
    and upstream-default zeros elsewhere."""
    epoch = slot // slots_per_epoch if epoch is None else epoch
    ages = [
        {"key": h, "val": {"fee_calculator": {"lamports_per_signature": 0},
                           "hash_index": i, "timestamp": 0}}
        for i, h in enumerate(blockhashes)
    ]
    es = {"slots_per_epoch": slots_per_epoch,
          "leader_schedule_slot_offset": slots_per_epoch,
          "warmup": False, "first_normal_epoch": 0, "first_normal_slot": 0}
    zero_stakes = {"vote_accounts": [], "stake_delegations": [],
                   "unused": 0, "epoch": epoch, "stake_history": []}
    ns_per_slot = 400_000_000
    return {
        "blockhash_queue": {
            "last_hash_index": max(len(blockhashes) - 1, 0),
            "last_hash": blockhashes[-1] if blockhashes else None,
            "ages": ages,
            "max_age": 300,
        },
        "ancestors": [],
        "hash": bank_hash,
        "parent_hash": parent_hash,
        "parent_slot": max(slot - 1, 0),
        "hard_forks": [],
        "transaction_count": transaction_count,
        "tick_height": slot * ticks_per_slot,
        "signature_count": 0,
        "capitalization": capitalization,
        "max_tick_height": (slot + 1) * ticks_per_slot,
        "hashes_per_tick": None,
        "ticks_per_slot": ticks_per_slot,
        "ns_per_slot_lo": ns_per_slot,
        "ns_per_slot_hi": 0,
        "genesis_creation_time": genesis_creation_time,
        "slots_per_year": 78_892_314.984,
        "accounts_data_len": 0,
        "slot": slot,
        "epoch": epoch,
        "block_height": slot,
        "collector_id": bytes(32),
        "collector_fees": 0,
        "fee_calculator": {"lamports_per_signature": 5000},
        "fee_rate_governor": {
            "target_lamports_per_signature": 10_000,
            "target_signatures_per_slot": 20_000,
            "min_lamports_per_signature": 5000,
            "max_lamports_per_signature": 100_000,
            "burn_percent": 50,
        },
        "collected_rent": 0,
        "rent_collector": {
            "epoch": epoch,
            "epoch_schedule": es,
            "slots_per_year": 78_892_314.984,
            "rent": {"lamports_per_uint8_year": 3480,
                     "exemption_threshold": 2.0, "burn_percent": 50},
        },
        "epoch_schedule": es,
        "inflation": {"initial": 0.08, "terminal": 0.015, "taper": 0.15,
                      "foundation": 0.05, "foundation_term": 7.0,
                      "unused": 0.0},
        "stakes": zero_stakes,
        "unused_accounts": {"unused1": [], "unused2": [], "unused3": []},
        "epoch_stakes": [],
        "is_delta": False,
    }


def default_accounts_db(slot: int, storages: list[tuple[int, int, int]],
                        bank_hash: bytes) -> dict:
    """storages: [(slot, id, file_sz)] of the archive's append-vecs."""
    by_slot: dict[int, list] = {}
    for s, i, sz in storages:
        by_slot.setdefault(s, []).append({"id": i, "file_sz": sz})
    return {
        "storages": [{"slot": s, "account_vecs": v}
                     for s, v in sorted(by_slot.items())],
        "version": 1,
        "slot": slot,
        "bank_hash_info": {
            "hash": bank_hash,
            "snapshot_hash": bank_hash,
            "stats": {"num_updated_accounts": 0, "num_removed_accounts": 0,
                      "num_lamports_stored": 0, "total_data_len": 0,
                      "num_executable_accounts": 0},
        },
        "historical_roots": [],
        "historical_roots_with_hash": [],
    }
