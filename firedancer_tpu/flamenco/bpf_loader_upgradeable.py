"""Upgradeable BPF loader (v3): buffer-staged deploys with a
Program/ProgramData account split and upgrade authority.

Parity surface: src/flamenco/runtime/program/fd_bpf_loader_v3_program.c
(instructions InitializeBuffer / Write / DeployWithMaxDataLen / Upgrade /
SetAuthority / Close / ExtendProgram; state enum
fd_bpf_upgradeable_loader_state).  State (de)serialization uses the
declarative bincode layer; like upstream, the metadata region is
FIXED-SIZE (buffer 37 B, programdata 45 B) so the ELF payload always
starts at the same offset regardless of Option tags.

The plain loader (bpf_loader.py) is the v1/v2-style immutable-deploy
path; programs owned by THIS loader are executed by resolving their
ProgramData account (executor._resolve_pubkey)."""

from __future__ import annotations

import struct

from ..ballet import sbpf
from . import bincode as bc
from .system_program import InstrError
from .types import BPF_LOADER_UPGRADEABLE_ID, SYSTEM_PROGRAM_ID

UPGRADEABLE_LOADER_ID = BPF_LOADER_UPGRADEABLE_ID


def programdata_address(program_id: bytes) -> bytes:
    """The ProgramData account is the PDA derived from the program id
    (upstream binds them the same way: find_program_address([program_id],
    loader_id) in the deploy processor) — the derivation is what prevents
    deploying into an arbitrary writable account."""
    from .vm import try_find_program_address
    return try_find_program_address([program_id], UPGRADEABLE_LOADER_ID)[0]

# state discriminants (fd_bpf_upgradeable_loader_state enum order)
UNINITIALIZED, BUFFER, PROGRAM, PROGRAMDATA = 0, 1, 2, 3

BUFFER_META_SZ = 37        # u32 disc + Option<Pubkey> authority
PROGRAMDATA_META_SZ = 45   # u32 disc + u64 slot + Option<Pubkey> authority

STATE_BUFFER = ("struct", (("authority", ("option", ("bytes", 32))),))
STATE_PROGRAM = ("struct", (("programdata_address", ("bytes", 32)),))
STATE_PROGRAMDATA = ("struct", (
    ("slot", "u64"),
    ("upgrade_authority", ("option", ("bytes", 32))),
))

# instruction discriminants (u32, upstream ordering)
IX_INITIALIZE_BUFFER = 0
IX_WRITE = 1
IX_DEPLOY_WITH_MAX_DATA_LEN = 2
IX_UPGRADE = 3
IX_SET_AUTHORITY = 4
IX_CLOSE = 5
IX_EXTEND_PROGRAM = 6

MAX_EXTEND_BYTES = 10 * 1024  # per-instruction growth cap (matches the
                              # plain loader's realloc discipline)


def _state_of(data: bytes):
    if len(data) < 4:
        return UNINITIALIZED, None
    disc = struct.unpack_from("<I", data)[0]
    if disc == BUFFER:
        return BUFFER, bc.decode(STATE_BUFFER, data, 4)[0]
    if disc == PROGRAM:
        return PROGRAM, bc.decode(STATE_PROGRAM, data, 4)[0]
    if disc == PROGRAMDATA:
        return PROGRAMDATA, bc.decode(STATE_PROGRAMDATA, data, 4)[0]
    return UNINITIALIZED, None


def _meta(disc: int, schema, value, size: int) -> bytes:
    raw = struct.pack("<I", disc) + bc.encode(schema, value)
    assert len(raw) <= size, (len(raw), size)
    return raw.ljust(size, b"\0")


def buffer_data(acct_data: bytes) -> bytes:
    return acct_data[BUFFER_META_SZ:]


def programdata_elf(acct_data: bytes) -> bytes:
    return acct_data[PROGRAMDATA_META_SZ:]


# ------------------------------------------------------------ instructions


def ix_initialize_buffer() -> bytes:
    return struct.pack("<I", IX_INITIALIZE_BUFFER)


def ix_write(offset: int, chunk: bytes) -> bytes:
    return struct.pack("<I", IX_WRITE) + bc.encode(
        ("struct", (("offset", "u32"), ("bytes", ("vec", "u8")))),
        {"offset": offset, "bytes": list(chunk)})


def ix_deploy_with_max_data_len(max_data_len: int) -> bytes:
    return struct.pack("<IQ", IX_DEPLOY_WITH_MAX_DATA_LEN, max_data_len)


def ix_upgrade() -> bytes:
    return struct.pack("<I", IX_UPGRADE)


def ix_set_authority() -> bytes:
    return struct.pack("<I", IX_SET_AUTHORITY)


def ix_close() -> bytes:
    return struct.pack("<I", IX_CLOSE)


def ix_extend_program(additional_bytes: int) -> bytes:
    return struct.pack("<II", IX_EXTEND_PROGRAM, additional_bytes)


def _require(cond, msg):
    if not cond:
        raise InstrError(f"upgradeable-loader: {msg}")


def _auth_check(ictx, idx, expected):
    """authority account at idx must match state + sign."""
    _require(expected is not None, "immutable (authority is None)")
    a = ictx.account(idx)
    _require(a.pubkey == bytes(expected), "authority mismatch")
    _require(ictx.is_signer(idx), "authority signature missing")


def execute(ictx):
    data = bytes(ictx.data)
    _require(len(data) >= 4, "data too short")
    (disc,) = struct.unpack_from("<I", data)

    if disc == IX_INITIALIZE_BUFFER:
        # [buffer (s,w), authority] — the buffer account must SIGN so a
        # third party's account cannot be hijacked into loader ownership
        # (upstream gets the same guarantee by requiring the account be
        # created loader-owned via the system program)
        buf = ictx.account(0)
        _require(buf.acct is not None, "missing buffer account")
        _require(ictx.is_signer(0), "buffer signature missing")
        st, _ = _state_of(buf.acct.data)
        _require(st == UNINITIALIZED and not any(buf.acct.data[:4]),
                 "buffer already initialized")
        _require(len(buf.acct.data) >= BUFFER_META_SZ, "buffer too small")
        auth = ictx.account(1).pubkey
        d = bytearray(buf.acct.data)
        d[:BUFFER_META_SZ] = _meta(
            BUFFER, STATE_BUFFER, {"authority": auth}, BUFFER_META_SZ)
        buf.acct.data = bytes(d)
        buf.acct.owner = UPGRADEABLE_LOADER_ID
        buf.touch()

    elif disc == IX_WRITE:
        # [buffer (w), authority (s)]
        buf = ictx.account(0)
        _require(buf.acct is not None, "missing buffer account")
        st, s = _state_of(buf.acct.data)
        _require(st == BUFFER, "not a buffer account")
        _auth_check(ictx, 1, s["authority"])
        body, _ = bc.decode(
            ("struct", (("offset", "u32"), ("bytes", ("vec", "u8")))),
            data, 4)
        off = BUFFER_META_SZ + body["offset"]
        chunk = bytes(body["bytes"])
        _require(off + len(chunk) <= len(buf.acct.data),
                 "write past end of buffer")
        d = bytearray(buf.acct.data)
        d[off : off + len(chunk)] = chunk
        buf.acct.data = bytes(d)
        buf.touch()

    elif disc == IX_DEPLOY_WITH_MAX_DATA_LEN:
        # [payer (s,w), programdata (w), program (w), buffer (w), authority (s)]
        (max_len,) = struct.unpack_from("<Q", data, 4)
        pdata = ictx.account(1)
        prog = ictx.account(2)
        buf = ictx.account(3)
        for a, nm in ((pdata, "programdata"), (prog, "program"),
                      (buf, "buffer")):
            _require(a.acct is not None, f"missing {nm} account")
        _require(ictx.is_signer(0), "payer signature missing")
        st, s = _state_of(buf.acct.data)
        _require(st == BUFFER, "deploy source is not a buffer")
        _auth_check(ictx, 4, s["authority"])
        stp, _ = _state_of(prog.acct.data)
        _require(not prog.acct.executable and stp == UNINITIALIZED,
                 "program account already in use")
        # the program account must already be LOADER-owned: creating it
        # that way (system create_account with owner = this loader)
        # required the account's own signature, so a third party's
        # writable account cannot be seized into a Program here
        _require(prog.acct.owner == UPGRADEABLE_LOADER_ID,
                 "program account not owned by the loader")
        # programdata must be the PDA derived from the program id: binds
        # the pair cryptographically (no other deploy can ever target
        # this programdata, including after a Close resets its state)
        _require(pdata.pubkey == programdata_address(prog.pubkey),
                 "programdata is not the derived address")
        # the programdata account must be virgin: overwriting a live
        # ProgramData would hijack whatever Program points at it
        stpd, _ = _state_of(pdata.acct.data)
        _require(stpd == UNINITIALIZED and not pdata.acct.executable,
                 "programdata account already in use")
        elf = buffer_data(buf.acct.data)
        _require(len(elf) <= max_len, "max_data_len smaller than buffer")
        try:
            sbpf.load(elf)
        except sbpf.SbpfLoaderError as e:
            raise InstrError(f"invalid program: {e}")
        slot = getattr(ictx.txctx, "slot", 0)
        pdata.acct.data = _meta(
            PROGRAMDATA, STATE_PROGRAMDATA,
            {"slot": slot, "upgrade_authority": ictx.account(4).pubkey},
            PROGRAMDATA_META_SZ) + elf.ljust(max_len, b"\0")
        pdata.acct.owner = UPGRADEABLE_LOADER_ID
        pdata.touch()
        prog.acct.data = _meta(
            PROGRAM, STATE_PROGRAM,
            {"programdata_address": pdata.pubkey}, 36)
        prog.acct.owner = UPGRADEABLE_LOADER_ID
        prog.acct.executable = True
        prog.touch()
        # drain the buffer (upstream moves its lamports to the payer and
        # clears the data)
        buf.acct.data = bytes(4)
        buf.touch()

    elif disc == IX_UPGRADE:
        # [programdata (w), program, buffer (w), spill (w), authority (s)]
        pdata = ictx.account(0)
        prog = ictx.account(1)
        buf = ictx.account(2)
        for a, nm in ((pdata, "programdata"), (prog, "program"),
                      (buf, "buffer")):
            _require(a.acct is not None, f"missing {nm} account")
        stp, sp = _state_of(prog.acct.data)
        _require(stp == PROGRAM and prog.acct.executable,
                 "not an upgradeable program")
        _require(bytes(sp["programdata_address"]) == pdata.pubkey,
                 "programdata address mismatch")
        std, sd = _state_of(pdata.acct.data)
        _require(std == PROGRAMDATA, "bad programdata state")
        _auth_check(ictx, 4, sd["upgrade_authority"])
        stb, sb = _state_of(buf.acct.data)
        _require(stb == BUFFER, "upgrade source is not a buffer")
        elf = buffer_data(buf.acct.data)
        cap = len(pdata.acct.data) - PROGRAMDATA_META_SZ
        _require(len(elf) <= cap, "program larger than programdata")
        try:
            sbpf.load(elf)
        except sbpf.SbpfLoaderError as e:
            raise InstrError(f"invalid program: {e}")
        slot = getattr(ictx.txctx, "slot", 0)
        pdata.acct.data = _meta(
            PROGRAMDATA, STATE_PROGRAMDATA,
            {"slot": slot, "upgrade_authority": sd["upgrade_authority"]},
            PROGRAMDATA_META_SZ) + elf.ljust(cap, b"\0")
        pdata.touch()
        buf.acct.data = bytes(4)
        buf.touch()

    elif disc == IX_SET_AUTHORITY:
        # [buffer|programdata (w), current authority (s), new authority]
        tgt = ictx.account(0)
        _require(tgt.acct is not None, "missing account")
        st, s = _state_of(tgt.acct.data)
        new_auth = (ictx.account(2).pubkey
                    if ictx.n_accounts > 2 else None)
        if st == BUFFER:
            _auth_check(ictx, 1, s["authority"])
            _require(new_auth is not None,
                     "buffer authority cannot be removed")
            meta = _meta(BUFFER, STATE_BUFFER, {"authority": new_auth},
                         BUFFER_META_SZ)
        elif st == PROGRAMDATA:
            _auth_check(ictx, 1, s["upgrade_authority"])
            meta = _meta(
                PROGRAMDATA, STATE_PROGRAMDATA,
                {"slot": s["slot"], "upgrade_authority": new_auth},
                PROGRAMDATA_META_SZ)
        else:
            raise InstrError("upgradeable-loader: account has no authority")
        d = bytearray(tgt.acct.data)
        d[: len(meta)] = meta
        tgt.acct.data = bytes(d)
        tgt.touch()

    elif disc == IX_CLOSE:
        # [buffer|programdata (w), recipient (w), authority (s)]
        tgt = ictx.account(0)
        rcpt = ictx.account(1)
        _require(tgt.acct is not None and rcpt.acct is not None,
                 "missing account")
        _require(tgt.pubkey != rcpt.pubkey,
                 "cannot close an account into itself")
        st, s = _state_of(tgt.acct.data)
        if st == BUFFER:
            _auth_check(ictx, 2, s["authority"])
        elif st == PROGRAMDATA:
            _auth_check(ictx, 2, s["upgrade_authority"])
        elif st == UNINITIALIZED:
            pass  # closable by anyone holding it
        else:
            raise InstrError("upgradeable-loader: cannot close a program")
        rcpt.acct.lamports += tgt.acct.lamports
        tgt.acct.lamports = 0
        tgt.acct.data = bytes(4)  # Uninitialized
        # return the account to the system program: a closed programdata
        # must not remain loader-owned, or it could be recycled under a
        # still-executable Program pointing at it
        tgt.acct.owner = SYSTEM_PROGRAM_ID
        tgt.touch()
        rcpt.touch()

    elif disc == IX_EXTEND_PROGRAM:
        # [programdata (w), program, authority (s)]
        (extra,) = struct.unpack_from("<I", data, 4)
        _require(extra <= MAX_EXTEND_BYTES, "extension too large")
        pdata = ictx.account(0)
        prog = ictx.account(1)
        _require(pdata.acct is not None and prog.acct is not None,
                 "missing account")
        st, s = _state_of(pdata.acct.data)
        _require(st == PROGRAMDATA, "not a programdata account")
        stp, sp = _state_of(prog.acct.data)
        _require(stp == PROGRAM
                 and bytes(sp["programdata_address"]) == pdata.pubkey,
                 "program/programdata mismatch")
        _auth_check(ictx, 2, s["upgrade_authority"])
        pdata.acct.data = pdata.acct.data + bytes(extra)
        pdata.touch()

    else:
        raise InstrError(f"unsupported upgradeable-loader instruction "
                         f"{disc}")
