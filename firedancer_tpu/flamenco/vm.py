"""sBPF virtual machine — interpreter, memory map, syscalls, compute
metering (ref: src/flamenco/vm/fd_vm_interp.c computed-goto dispatch,
fd_vm_syscalls.c, memory map constants in fd_vm_context.h).

Executes Solana-flavored BPF (SBF v1): 64-bit two-operand register machine,
8-byte instructions (16 for lddw), fixed 4 KiB stack frames, explicit
virtual memory regions:

    program ro  0x1_0000_0000
    stack       0x2_0000_0000
    heap        0x3_0000_0000
    input       0x4_0000_0000

Python interpretation is the right altitude here: on-chain programs are
control-plane (the reference meters them at ~1 CU/insn); the data plane
(sigverify, hashing) lives in the JAX ops layer.
"""

import struct

from ..ballet.murmur3 import murmur3_32

# -- memory map (fd_vm_context.h MM_* constants) ---------------------------
MM_PROGRAM = 0x1_0000_0000
MM_STACK = 0x2_0000_0000
MM_HEAP = 0x3_0000_0000
MM_INPUT = 0x4_0000_0000

STACK_FRAME_SZ = 4096
MAX_CALL_DEPTH = 64
DEFAULT_COMPUTE_UNITS = 200_000
DEFAULT_HEAP_SZ = 32 * 1024

_U64 = (1 << 64) - 1


class VmError(Exception):
    pass


class VmFault(VmError):
    """Memory access violation / invalid instruction."""


class VmComputeExceeded(VmError):
    pass


def _s64(x: int) -> int:
    return x - (1 << 64) if x & (1 << 63) else x


def _s32(x: int) -> int:
    x &= 0xFFFFFFFF
    return x - (1 << 32) if x & (1 << 31) else x


class Region:
    __slots__ = ("vaddr", "mem", "writable")

    def __init__(self, vaddr: int, mem: bytearray | bytes, writable: bool):
        self.vaddr = vaddr
        self.mem = mem
        self.writable = writable


class Vm:
    """One program execution context (fd_vm_exec_context_t)."""

    def __init__(self, text: bytes, entry_pc: int = 0,
                 input_mem: bytearray | None = None,
                 compute_units: int = DEFAULT_COMPUTE_UNITS,
                 heap_sz: int = DEFAULT_HEAP_SZ,
                 syscalls: dict | None = None,
                 rodata: bytes | None = None):
        if len(text) % 8:
            raise VmError("text not a multiple of 8")
        self.text = text
        self.n_insn = len(text) // 8
        self.entry_pc = entry_pc
        self.reg = [0] * 11
        self.pc = entry_pc
        self.cu = compute_units
        self.call_depth = 0
        self.frames: list[tuple] = []
        self.log: list[bytes] = []
        # per-instruction trace hook: tracer(pc, opcode, regs_snapshot)
        # (role of fd_vm_trace.c, enabled per-vm instead of a build flag)
        self.tracer = None

        self.stack = bytearray(STACK_FRAME_SZ * MAX_CALL_DEPTH)
        self.heap = bytearray(heap_sz)
        self.input = input_mem if input_mem is not None else bytearray()
        self.regions = [
            Region(MM_PROGRAM, rodata if rodata is not None else text, False),
            Region(MM_STACK, self.stack, True),
            Region(MM_HEAP, self.heap, True),
            Region(MM_INPUT, self.input, True),
        ]
        # r10 = frame pointer: top of the first stack frame (grows down)
        self.reg[10] = MM_STACK + STACK_FRAME_SZ
        self.syscalls = dict(SYSCALLS)
        if syscalls:
            self.syscalls.update(syscalls)
        # function registry: murmur32(pc bytes) used by `call imm` after the
        # loader resolves bpf-to-bpf calls to target pcs
        self.calldests: set[int] = set()

    # ---------------------------------------------------------- memory
    def translate(self, vaddr: int, sz: int, write: bool) -> tuple:
        for r in self.regions:
            off = vaddr - r.vaddr
            if 0 <= off and off + sz <= len(r.mem):
                if write and not r.writable:
                    raise VmFault(f"write to ro region @{vaddr:#x}")
                return r.mem, off
        raise VmFault(f"access violation @{vaddr:#x} sz={sz}")

    def mem_read(self, vaddr: int, sz: int) -> int:
        mem, off = self.translate(vaddr, sz, False)
        return int.from_bytes(mem[off:off + sz], "little")

    def mem_read_bytes(self, vaddr: int, sz: int) -> bytes:
        mem, off = self.translate(vaddr, sz, False)
        return bytes(mem[off:off + sz])

    def mem_write(self, vaddr: int, val: int, sz: int):
        mem, off = self.translate(vaddr, sz, True)
        mem[off:off + sz] = (val & ((1 << (8 * sz)) - 1)).to_bytes(sz, "little")

    def mem_write_bytes(self, vaddr: int, data: bytes):
        mem, off = self.translate(vaddr, len(data), True)
        mem[off:off + len(data)] = data

    # ---------------------------------------------------------- running
    def _consume(self, n: int = 1):
        self.cu -= n
        if self.cu < 0:
            raise VmComputeExceeded("compute budget exhausted")

    def run(self, *args) -> int:
        """Execute from the entrypoint; args land in r1..r5.  Returns r0."""
        for i, a in enumerate(args[:5]):
            self.reg[1 + i] = a & _U64
        self.pc = self.entry_pc
        text, reg = self.text, self.reg
        tracer = self.tracer
        while True:
            if not (0 <= self.pc < self.n_insn):
                raise VmFault(f"pc out of bounds: {self.pc}")
            self._consume()
            op, regs, off, imm = struct.unpack_from("<BBhi", text, self.pc * 8)
            dst, src = regs & 0xF, regs >> 4
            if tracer is not None:
                tracer(self.pc, op, tuple(reg))
            if dst > 10 or src > 10:
                raise VmFault("bad register")
            cls = op & 0x07
            self.pc += 1
            if cls == 0x07 or cls == 0x04:            # ALU64 / ALU32
                self._alu(op, dst, src, imm, is64=(cls == 0x07))
            elif cls == 0x05:                          # JMP
                r = self._jmp(op, dst, src, off, imm)
                if r is not None:
                    return r
            elif cls == 0x01 or cls == 0x00:           # LDX / LD (lddw)
                if op == 0x18:                         # lddw: 16-byte insn
                    if self.pc >= self.n_insn:
                        raise VmFault("truncated lddw")
                    (imm2,) = struct.unpack_from("<i", text, self.pc * 8 + 4)
                    reg[dst] = (imm & 0xFFFFFFFF) | ((imm2 & 0xFFFFFFFF) << 32)
                    self.pc += 1
                elif cls == 0x01:
                    sz = {0x61: 4, 0x69: 2, 0x71: 1, 0x79: 8}.get(op)
                    if sz is None:
                        raise VmFault(f"bad ldx op {op:#x}")
                    reg[dst] = self.mem_read((reg[src] + off) & _U64, sz)
                else:
                    raise VmFault(f"bad ld op {op:#x}")
            elif cls == 0x02:                          # ST imm
                sz = {0x62: 4, 0x6A: 2, 0x72: 1, 0x7A: 8}.get(op)
                if sz is None:
                    raise VmFault(f"bad st op {op:#x}")
                self.mem_write((reg[dst] + off) & _U64, imm & _U64, sz)
            elif cls == 0x03:                          # STX
                sz = {0x63: 4, 0x6B: 2, 0x73: 1, 0x7B: 8}.get(op)
                if sz is None:
                    raise VmFault(f"bad stx op {op:#x}")
                self.mem_write((reg[dst] + off) & _U64, reg[src], sz)
            else:
                raise VmFault(f"bad class {cls:#x} (op {op:#x})")

    # ------------------------------------------------------------- alu
    def _alu(self, op, dst, src, imm, is64: bool):
        reg = self.reg
        operation = op >> 4
        if operation == 0xD:
            # endianness ops live in the ALU32 class but read the FULL
            # register (be64 swaps all 8 bytes) — handle before masking
            width = imm
            if width not in (16, 32, 64):
                raise VmFault("bad endian width")
            nbytes = width // 8
            val = reg[dst] & ((1 << width) - 1)
            if op & 0x08:  # be
                reg[dst] = int.from_bytes(val.to_bytes(nbytes, "little"),
                                          "big")
            else:          # le (no-op on LE host beyond the truncation)
                reg[dst] = val
            return
        use_reg = bool(op & 0x08)
        b = reg[src] if use_reg else (imm & _U64 if is64 else imm & 0xFFFFFFFF)
        a = reg[dst]
        if not is64:
            a &= 0xFFFFFFFF
            b &= 0xFFFFFFFF
        mask = _U64 if is64 else 0xFFFFFFFF
        shift_mask = 63 if is64 else 31
        if operation == 0x0:
            r = (a + b) & mask
        elif operation == 0x1:
            r = (a - b) & mask
        elif operation == 0x2:
            r = (a * b) & mask
        elif operation == 0x3:
            if b == 0:
                raise VmFault("division by zero")
            r = a // b
        elif operation == 0x4:
            r = a | b
        elif operation == 0x5:
            r = a & b
        elif operation == 0x6:
            r = (a << (b & shift_mask)) & mask
        elif operation == 0x7:
            r = a >> (b & shift_mask)
        elif operation == 0x8:   # neg
            r = (-a) & mask
        elif operation == 0x9:
            if b == 0:
                raise VmFault("division by zero")
            r = a % b
        elif operation == 0xA:
            r = a ^ b
        elif operation == 0xB:
            r = b
        elif operation == 0xC:   # arsh
            sa = _s64(a) if is64 else _s32(a)
            r = (sa >> (b & shift_mask)) & mask
        else:
            raise VmFault(f"bad alu operation {operation:#x}")
        self.reg[dst] = r & _U64

    # ------------------------------------------------------------- jmp
    def _jmp(self, op, dst, src, off, imm):
        reg = self.reg
        operation = op >> 4
        if operation == 0x8:                    # CALL / CALLX
            if op == 0x8D:                      # callx: target pc in reg[imm]
                tgt_reg = imm & 0xF
                if tgt_reg > 9:
                    raise VmFault("bad callx register")
                addr = reg[tgt_reg]
                if addr % 8 or addr < MM_PROGRAM:
                    raise VmFault("bad callx target")
                target = (addr - MM_PROGRAM) // 8
                self._push_frame(target)
            else:                               # call imm
                key = imm & 0xFFFFFFFF
                sc = self.syscalls.get(key)
                if sc is not None:
                    self._consume(sc.cost - 1)
                    reg[0] = sc.fn(self, reg[1], reg[2], reg[3], reg[4],
                                   reg[5]) & _U64
                else:
                    # bpf-to-bpf: loader-resolved absolute target pc
                    if not (0 <= imm < self.n_insn):
                        raise VmFault(f"bad call target {imm}")
                    self._push_frame(imm)
            return None
        if operation == 0x9:                    # EXIT
            if self.frames:
                self._pop_frame()
                return None
            return reg[0]
        use_reg = bool(op & 0x08)
        b = reg[src] if use_reg else imm & _U64
        a = reg[dst]
        sa, sb = _s64(a), _s64(b)
        taken = {
            0x0: True,                 # ja
            0x1: a == b, 0x2: a > b, 0x3: a >= b,
            0x4: bool(a & b), 0x5: a != b,
            0x6: sa > sb, 0x7: sa >= sb,
            0xA: a < b, 0xB: a <= b,
            0xC: sa < sb, 0xD: sa <= sb,
        }.get(operation)
        if taken is None:
            raise VmFault(f"bad jmp operation {operation:#x}")
        if taken:
            self.pc += off
        return None

    def _push_frame(self, target_pc: int):
        if self.call_depth + 1 >= MAX_CALL_DEPTH:
            raise VmFault("call depth exceeded")
        self.frames.append((self.pc, self.reg[6], self.reg[7], self.reg[8],
                            self.reg[9], self.reg[10]))
        self.call_depth += 1
        self.reg[10] += STACK_FRAME_SZ   # fixed frames (SBF v1)
        self.pc = target_pc

    def _pop_frame(self):
        (self.pc, self.reg[6], self.reg[7], self.reg[8], self.reg[9],
         self.reg[10]) = self.frames.pop()
        self.call_depth -= 1


# -- syscalls (fd_vm_syscalls.c registry; ids = murmur3_32 of the name) ----

class Syscall:
    __slots__ = ("name", "fn", "cost")

    def __init__(self, name, fn, cost=100):
        self.name, self.fn, self.cost = name, fn, cost


def syscall_id(name: bytes) -> int:
    return murmur3_32(name, 0)


def _sc_abort(vm, *a):
    raise VmFault("abort")


def _sc_panic(vm, file_va, flen, line, col, *a):
    raise VmFault(f"panic at line {line}:{col}")


def _sc_log(vm, msg_va, msg_len, *a):
    if msg_len > 10_000:
        raise VmFault("log too long")
    vm.log.append(vm.mem_read_bytes(msg_va, msg_len))
    return 0


def _sc_log_64(vm, a1, a2, a3, a4, a5):
    vm.log.append(f"{a1:#x} {a2:#x} {a3:#x} {a4:#x} {a5:#x}".encode())
    return 0


def _sc_memcpy(vm, dst, src, n, *a):
    if n > (1 << 30):
        raise VmFault("memcpy too large")
    if dst < src + n and src < dst + n and n:
        raise VmFault("memcpy overlap")
    vm.mem_write_bytes(dst, vm.mem_read_bytes(src, n))
    return 0


def _sc_memset(vm, dst, c, n, *a):
    # bounds-check before materializing the fill: a huge n must fault, not
    # attempt a huge host allocation
    vm.translate(dst, n, True)
    vm.mem_write_bytes(dst, bytes([c & 0xFF]) * n)
    return 0


def _sc_memcmp(vm, va, vb, n, result_va, *a):
    ba, bb = vm.mem_read_bytes(va, n), vm.mem_read_bytes(vb, n)
    r = 0
    for x, y in zip(ba, bb):
        if x != y:
            r = x - y
            break
    vm.mem_write(result_va, r & 0xFFFFFFFF, 4)
    return 0


def _gather_slices(vm, vals_va: int, vals_len: int) -> bytes:
    """vals: array of (vaddr u64, len u64) byte slices (the shared
    fd_vm_syscall hash ABI)."""
    if vals_len > 20_000:  # the reference runtime's slice-count ceiling
        raise VmFault("too many hash slices")
    out = bytearray()
    for i in range(vals_len):
        ptr = vm.mem_read(vals_va + 16 * i, 8)
        ln = vm.mem_read(vals_va + 16 * i + 8, 8)
        out += vm.mem_read_bytes(ptr, ln)
        if len(out) > 1 << 26:
            raise VmFault("hash input too long")
    return bytes(out)


def _sc_sha256(vm, vals_va, vals_len, result_va, *a):
    import hashlib
    vm.mem_write_bytes(
        result_va, hashlib.sha256(_gather_slices(vm, vals_va,
                                                 vals_len)).digest())
    return 0


def _sc_keccak256(vm, vals_va, vals_len, result_va, *a):
    from ..ballet.keccak256 import keccak256
    vm.mem_write_bytes(
        result_va, keccak256(_gather_slices(vm, vals_va, vals_len)))
    return 0


def _sc_blake3(vm, vals_va, vals_len, result_va, *a):
    from ..ops.blake3 import blake3
    vm.mem_write_bytes(
        result_va, blake3(_gather_slices(vm, vals_va, vals_len)))
    return 0


def _sc_log_data(vm, vals_va, vals_len, *a):
    """sol_log_data: log an array of byte slices (fd_vm_syscall_log)."""
    data = _gather_slices(vm, vals_va, vals_len)
    if len(data) > 10_000:
        raise VmFault("log data too long")
    vm.log.append(data)
    return 0


# -- program-derived addresses (fd_vm_syscall_pda.c semantics) -------------

_PDA_MARKER = b"ProgramDerivedAddress"
_CURVE_P = 2**255 - 19
_CURVE_D = (-121665 * pow(121666, _CURVE_P - 2, _CURVE_P)) % _CURVE_P


def _is_on_curve(b: bytes) -> bool:
    """Does the 32-byte string decode to an ed25519 curve point?  PDAs must
    NOT (so no private key can exist for them)."""
    n = int.from_bytes(b, "little")
    y = (n & ((1 << 255) - 1)) % _CURVE_P
    u = (y * y - 1) % _CURVE_P
    v = (_CURVE_D * y * y + 1) % _CURVE_P
    # x^2 = u/v has a solution iff (u/v) is a QR; check via Euler criterion
    uv = u * pow(v, _CURVE_P - 2, _CURVE_P) % _CURVE_P
    if uv == 0:
        return True
    return pow(uv, (_CURVE_P - 1) // 2, _CURVE_P) == 1


class PdaError(VmError):
    pass


def create_program_address(seeds: list[bytes], program_id: bytes) -> bytes:
    """sha256(seeds || program_id || marker); must land OFF the curve."""
    import hashlib
    if len(seeds) > 16 or any(len(s) > 32 for s in seeds):
        raise PdaError("bad PDA seeds")
    h = hashlib.sha256(
        b"".join(seeds) + program_id + _PDA_MARKER).digest()
    if _is_on_curve(h):
        raise PdaError("PDA lands on the curve")
    return h


def try_find_program_address(seeds, program_id) -> tuple[bytes, int]:
    for bump in range(255, -1, -1):
        try:
            return create_program_address(
                list(seeds) + [bytes([bump])], program_id), bump
        except PdaError:
            continue
    raise PdaError("no viable bump")


def _read_seed_slices(vm, seeds_va: int, n_seeds: int) -> list[bytes]:
    """n_seeds x (u64 ptr, u64 len) descriptors -> byte seeds."""
    if n_seeds > 16:
        raise VmFault("too many PDA seeds")
    seeds = []
    for j in range(n_seeds):
        p = vm.mem_read(seeds_va + 16 * j, 8)
        ln = vm.mem_read(seeds_va + 16 * j + 8, 8)
        if ln > 32:
            raise VmFault("PDA seed too long")
        seeds.append(vm.mem_read_bytes(p, ln))
    return seeds


def _sc_create_program_address(vm, seeds_va, n_seeds, prog_va, out_va, *a):
    seeds = _read_seed_slices(vm, seeds_va, n_seeds)
    prog = vm.mem_read_bytes(prog_va, 32)
    try:
        vm.mem_write_bytes(out_va, create_program_address(seeds, prog))
    except PdaError:
        return 1
    return 0


def _sc_try_find_program_address(vm, seeds_va, n_seeds, prog_va, out_va,
                                 bump_va):
    seeds = _read_seed_slices(vm, seeds_va, n_seeds)
    prog = vm.mem_read_bytes(prog_va, 32)
    try:
        addr, bump = try_find_program_address(seeds, prog)
    except PdaError:
        return 1
    vm.mem_write_bytes(out_va, addr)
    vm.mem_write(bump_va, bump, 1)
    return 0


# -- cross-program invocation (fd_vm_cpi.h role) ---------------------------
#
# Instruction buffer ABI (our own fixed little-endian layout, same
# information content as the reference's C/Rust dual ABIs):
#
#     pubkey[32] program_id
#     u64 n_metas
#     metas[n]: pubkey[32] | u8 is_signer | u8 is_writable | pad[6]
#     u64 data_len | data
#
# signers_va: n_signers x (u64 seeds_ptr, u64 n_seeds); each seeds_ptr is
# an array of (u64 ptr, u64 len) slices, hashed with the CALLER's program
# id into PDAs whose signer privilege the callee instruction receives.

CPI_MAX_METAS = 64


def cpi_instruction_bytes(program_id: bytes, metas, data: bytes) -> bytes:
    """Host-side builder for the CPI instruction buffer (tests/programs)."""
    out = bytearray(program_id)
    out += struct.pack("<Q", len(metas))
    for pk, s, w in metas:
        out += pk + struct.pack("<BB6x", s, w)
    out += struct.pack("<Q", len(data)) + data
    return bytes(out)


def _sc_invoke_signed(vm, instr_va, signers_va, n_signers, *a):
    cpi = getattr(vm, "cpi", None)
    if cpi is None:
        raise VmFault("CPI unavailable in this context")
    prog_id = vm.mem_read_bytes(instr_va, 32)
    n_metas = vm.mem_read(instr_va + 32, 8)
    if n_metas > CPI_MAX_METAS:
        raise VmFault("too many CPI account metas")
    off = instr_va + 40
    metas = []
    for _ in range(n_metas):
        pk = vm.mem_read_bytes(off, 32)
        s = vm.mem_read(off + 32, 1)
        w = vm.mem_read(off + 33, 1)
        metas.append((pk, bool(s), bool(w)))
        off += 40
    dlen = vm.mem_read(off, 8)
    if dlen > 10 * 1024:
        raise VmFault("CPI data too long")
    data = vm.mem_read_bytes(off + 8, dlen)
    if n_signers > 16:
        raise VmFault("too many CPI signers")
    pdas = []
    for i in range(n_signers):
        seeds_ptr = vm.mem_read(signers_va + 16 * i, 8)
        n_seeds = vm.mem_read(signers_va + 16 * i + 8, 8)
        seeds = _read_seed_slices(vm, seeds_ptr, n_seeds)
        try:
            pdas.append(create_program_address(seeds, cpi.caller_program_id))
        except PdaError as e:
            raise VmFault(f"CPI signer seeds: {e}")
    cpi.invoke(prog_id, metas, data, pdas)  # raises VmFault on failure
    return 0


# -- alt_bn128 (fd_vm_syscall_crypto.c surface over ballet/bn254) ----------
# Group-op selectors and costs follow the upstream syscall ABI: op 0=ADD,
# 1=SUB, 2=MUL, 3=PAIRING.  Inputs SHORTER than the op's fixed width are
# zero-padded (EVM-precompile semantics); LONGER inputs are an error.
# Errors return 1 (not a fault) with the result buffer untouched.  The
# flat Syscall.cost is the ADD cost; the op-dependent remainder is
# consumed here before doing the work (upstream cost table:
# MUL 3_840, PAIRING 36_364 + 12_121/pair; compression G1 30/398,
# G2 86/13_610).

_BN_ADD, _BN_SUB, _BN_MUL, _BN_PAIRING = 0, 1, 2, 3
_BN_G1_COMPRESS, _BN_G1_DECOMPRESS = 0, 1
_BN_G2_COMPRESS, _BN_G2_DECOMPRESS = 2, 3

_BN_MUL_COST = 3_840
_BN_PAIRING_BASE_COST = 36_364
_BN_PAIRING_PAIR_COST = 12_121
_BN_COMPRESS_COST = {
    _BN_G1_COMPRESS: 30, _BN_G1_DECOMPRESS: 398,
    _BN_G2_COMPRESS: 86, _BN_G2_DECOMPRESS: 13_610,
}


def _sc_alt_bn128_group_op(vm, op, input_va, input_len, result_va, *a):
    # no size cap beyond the compute budget: pairing CU is consumed per
    # pair BEFORE the work, so oversized inputs die as ComputeExceeded
    # (upstream behavior), never as a host-resource problem
    from ..ballet import bn254
    data = vm.mem_read_bytes(input_va, input_len)
    try:
        if op == _BN_ADD or op == _BN_SUB:
            if input_len > 128:
                return 1
            data = data.ljust(128, b"\0")
            q = bn254.decode_g1(data[64:128])
            if op == _BN_SUB and q is not None:
                q = (q[0], (-q[1]) % bn254.P)
            out = bn254.encode_g1(bn254._add(bn254.decode_g1(data[:64]), q))
        elif op == _BN_MUL:
            if input_len > 96:
                return 1
            vm._consume(_BN_MUL_COST - 334)
            data = data.ljust(96, b"\0")
            out = bn254.g1_scalar_mul(data[:64], data[64:96])
        elif op == _BN_PAIRING:
            vm._consume(_BN_PAIRING_BASE_COST - 334
                        + _BN_PAIRING_PAIR_COST * (input_len // 192))
            ok = bn254.pairing_check(data)
            out = (1 if ok else 0).to_bytes(32, "big")
        else:
            return 1
    except bn254.Bn254Error:
        return 1
    vm.mem_write_bytes(result_va, out)
    return 0


def _sc_alt_bn128_compression(vm, op, input_va, input_len, result_va, *a):
    from ..ballet import bn254
    expected = {_BN_G1_COMPRESS: 64, _BN_G1_DECOMPRESS: 32,
                _BN_G2_COMPRESS: 128, _BN_G2_DECOMPRESS: 64}.get(op)
    if expected is None or input_len != expected:
        return 1
    vm._consume(max(0, _BN_COMPRESS_COST[op] - 30))
    data = vm.mem_read_bytes(input_va, input_len)
    try:
        if op == _BN_G1_COMPRESS:
            out = bn254.g1_compress(data)
        elif op == _BN_G1_DECOMPRESS:
            out = bn254.g1_decompress(data)
        elif op == _BN_G2_COMPRESS:
            out = bn254.g2_compress(data)
        else:
            out = bn254.g2_decompress(data)
    except bn254.Bn254Error:
        return 1
    vm.mem_write_bytes(result_va, out)
    return 0


def _sc_poseidon(vm, params, endianness, vals_va, vals_len, result_va, *a):
    """sol_poseidon: hash an array of field-element byte slices (Poseidon
    over BN254 Fr, light-poseidon semantics — ballet/poseidon.py; the
    reference backs this with fd_poseidon.cxx).  params 0 = Bn254X5;
    endianness 0 = big, 1 = little.  Per-slice conversion is plain
    radix-256 in the given endianness (short slices allowed, <= 32 B).
    Errors return 1 with the result untouched."""
    from ..ballet import poseidon

    if params != 0 or endianness not in (0, 1) or not 1 <= vals_len <= 12:
        return 1
    vm._consume(61 * int(vals_len) ** 2 + 542)  # quadratic width cost
    vals = []
    for i in range(vals_len):
        ptr = vm.mem_read(vals_va + 16 * i, 8)
        ln = vm.mem_read(vals_va + 16 * i + 8, 8)
        if not 1 <= ln <= 32:
            return 1
        raw = vm.mem_read_bytes(ptr, ln)
        v = int.from_bytes(raw, "big" if endianness == 0 else "little")
        if v >= poseidon.P:  # non-canonical field element: reject, don't
            return 1         # reduce (light-poseidon/reference parity)
        vals.append(v)
    out = poseidon.hash_inputs(vals).to_bytes(32, "little")
    if endianness == 0:
        out = out[::-1]
    vm.mem_write_bytes(result_va, out)
    return 0


# -- round-3 syscall breadth (fd_vm_syscalls.c:200-260 registry parity) ----


def _sc_log_compute_units(vm, *a):
    vm.log.append(f"Program consumption: {vm.cu} units remaining".encode())
    return 0


def _sc_log_pubkey(vm, pk_va, *a):
    from ..ballet import base58
    vm.log.append(base58.encode(vm.mem_read_bytes(pk_va, 32)).encode())
    return 0


def _sc_memmove(vm, dst, src, n, *a):
    """Overlap-safe copy (sol_memmove_): the read materializes the whole
    source before any write, so overlap is handled by construction."""
    if n:
        vm.mem_write_bytes(dst, vm.mem_read_bytes(src, n))
    return 0


MAX_RETURN_DATA = 1024


def _return_slot(vm):
    """Return data lives on the TRANSACTION (CPI chains share it,
    fd_vm_syscall sol_{set,get}_return_data over the instr ctx); VMs with
    no txn context (unit harnesses) keep it per-vm."""
    ictx = getattr(vm, "ictx", None)
    return ictx.txctx if ictx is not None else vm


def _sc_set_return_data(vm, data_va, n, *a):
    if n > MAX_RETURN_DATA:
        raise VmFault("return data too long")
    holder = _return_slot(vm)
    prog = getattr(getattr(vm, "ictx", None), "program_id", bytes(32))
    holder.return_data = (prog, vm.mem_read_bytes(data_va, n) if n else b"")
    return 0


def _sc_get_return_data(vm, data_va, n, prog_va, *a):
    holder = _return_slot(vm)
    prog, data = getattr(holder, "return_data", (bytes(32), b""))
    ncopy = min(n, len(data))
    if ncopy:
        # the reference touches NO memory when the copy length is 0 —
        # programs legitimately probe the length with null buffers
        vm.mem_write_bytes(data_va, data[:ncopy])
        vm.mem_write_bytes(prog_va, prog)
    return len(data)


def _sc_alloc_free(vm, sz, free_addr, *a):
    """Bump allocator over the heap region (fd_vm_syscall_sol_alloc_free:
    free is a no-op, malloc 8-aligns and returns 0 on exhaustion)."""
    if free_addr:
        return 0
    pos = (getattr(vm, "_alloc_off", 0) + 7) & ~7
    vaddr = MM_HEAP + pos
    pos += int(sz)
    if pos > len(vm.heap):
        return 0
    vm._alloc_off = pos
    return vaddr


def _sc_get_fees_sysvar(vm, out_va, *a):
    from .types import SYSVAR_FEES_ID
    data = _sysvar_account_data(vm, SYSVAR_FEES_ID)
    if data is None:
        return 1
    vm.mem_write_bytes(out_va, data)
    return 0


def _sc_get_last_restart_slot(vm, out_va, *a):
    from .types import SYSVAR_LAST_RESTART_SLOT_ID
    data = _sysvar_account_data(vm, SYSVAR_LAST_RESTART_SLOT_ID)
    if data is None:
        return 1
    vm.mem_write_bytes(out_va, data)
    return 0


def _sc_remaining_compute_units(vm, *a):
    # the LIVE meter is the VM's own countdown (vm.cu); the txctx tally
    # syncs only after vm.run() returns, so it is stale mid-execution
    cu = getattr(vm, "cu", None)
    if cu is not None:
        return max(0, int(cu))
    ictx = getattr(vm, "ictx", None)
    if ictx is None:
        return 0
    tx = ictx.txctx
    return max(0, tx.cu_limit - tx.compute_units_consumed)


def _sc_get_processed_sibling_instruction(
        vm, index, meta_va, pid_va, data_va, accts_va):
    """Sibling-instruction introspection (two-phase Agave ABI): entries
    at the CURRENT stack height, reverse order; phase 1 returns lengths
    in meta, phase 2 (caller buffers sized to match) copies program id,
    data, and 34-byte AccountMeta records.  Returns 1 when found."""
    import struct as _st
    ictx = getattr(vm, "ictx", None)
    if ictx is None:
        return 0
    tx = ictx.txctx
    height = len(tx.instr_stack)
    # walk the trace BACKWARDS and stop at the first entry below the
    # current height (the parent boundary): only siblings under the
    # SAME parent are visible — entries from earlier top-level
    # instructions' subtrees must not leak (Agave's
    # stop_sibling_instruction_search_at_parent semantics)
    sibs = []
    for e in reversed(tx.instr_trace):
        if e[0] < height:
            break
        if e[0] == height:
            sibs.append(e)          # most recent FIRST
    if index >= len(sibs):
        return 0
    _h, prog_id, metas, data = sibs[int(index)]
    want_dlen, want_alen = _st.unpack(
        "<QQ", vm.mem_read_bytes(meta_va, 16))
    vm.mem_write_bytes(meta_va, _st.pack("<QQ", len(data), len(metas)))
    if want_dlen == len(data) and want_alen == len(metas):
        vm.mem_write_bytes(pid_va, prog_id)
        vm.mem_write_bytes(data_va, bytes(data))
        out = b"".join(pk + bytes([1 if sg else 0, 1 if wr else 0])
                       for pk, sg, wr in metas)
        vm.mem_write_bytes(accts_va, out)
    return 1


def _sc_get_stack_height(vm, *a):
    ictx = getattr(vm, "ictx", None)
    if ictx is None:
        return 1
    return len(ictx.txctx.instr_stack)


def _sysvar_account_data(vm, sysvar_id: bytes) -> bytes | None:
    ictx = getattr(vm, "ictx", None)
    if ictx is None:
        return None
    txctx = ictx.txctx
    ex = txctx.executor
    xid = getattr(txctx, "xid", None)
    if ex is None:
        return None
    acct = ex.accdb.load(xid, sysvar_id)
    return None if acct is None else acct.data


def _sc_get_clock_sysvar(vm, out_va, *a):
    from .types import SYSVAR_CLOCK_ID
    data = _sysvar_account_data(vm, SYSVAR_CLOCK_ID)
    if data is None:
        return 1
    vm.mem_write_bytes(out_va, data)
    return 0


def _sc_get_rent_sysvar(vm, out_va, *a):
    from .types import SYSVAR_RENT_ID
    data = _sysvar_account_data(vm, SYSVAR_RENT_ID)
    if data is None:
        return 1
    vm.mem_write_bytes(out_va, data)
    return 0


def _sc_get_epoch_schedule_sysvar(vm, out_va, *a):
    from .types import SYSVAR_EPOCH_SCHEDULE_ID
    data = _sysvar_account_data(vm, SYSVAR_EPOCH_SCHEDULE_ID)
    if data is None:
        return 1
    vm.mem_write_bytes(out_va, data)
    return 0


def _sc_secp256k1_recover(vm, hash_va, recid, sig_va, out_va, *a):
    """sol_secp256k1_recover: 32-byte hash + 64-byte (r||s) + recovery id
    -> 64-byte uncompressed pubkey (x||y), r0=0; nonzero r0 on failure
    (fd_vm_syscall_sol_secp256k1_recover error codes collapsed to 1)."""
    from ..ballet import secp256k1 as secp
    if recid > 3:
        return 1
    h = vm.mem_read_bytes(hash_va, 32)
    sig = vm.mem_read_bytes(sig_va, 64)
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    try:
        pub = secp.recover(h, r, s, recid)
    except Exception:
        return 1
    if pub is None:
        return 1
    x, y = pub
    vm.mem_write_bytes(out_va, x.to_bytes(32, "big") + y.to_bytes(32, "big"))
    return 0


# curve ids / group ops (Agave's curve25519 syscall ABI)
CURVE25519_EDWARDS = 0
CURVE25519_RISTRETTO = 1
CURVE_OP_ADD = 0
CURVE_OP_SUB = 1
CURVE_OP_MUL = 2
CURVE_MSM_MAX = 512


_SCALAR_L = 2**252 + 27742317777372353535851937790883648493


def _canonical_scalar(b: bytes):
    """Agave's Scalar::from_canonical_bytes: reject >= L (both curves;
    silently reducing would give different on-chain outcomes for the
    same bytes)."""
    k = int.from_bytes(b, "little")
    return k if k < _SCALAR_L else None


def _edwards_decode(b: bytes):
    from ..ops import ed25519 as ed
    return ed._decompress_host(b)


def _sc_curve_validate_point(vm, curve_id, point_va, *a):
    b = vm.mem_read_bytes(point_va, 32)
    if curve_id == CURVE25519_EDWARDS:
        return 0 if _edwards_decode(b) is not None else 1
    if curve_id == CURVE25519_RISTRETTO:
        from ..ops import ristretto255 as ris
        return 0 if ris.decode(b) is not None else 1
    return 1


def _sc_curve_group_op(vm, curve_id, op, left_va, right_va, out_va, *a):
    """add/sub: left,right points; mul: left = 32-byte scalar (LE),
    right = point.  Writes the compressed result, r0=0; 1 on any invalid
    input (fd_vm_syscall_sol_curve_group_op)."""
    lb = vm.mem_read_bytes(left_va, 32)
    rb = vm.mem_read_bytes(right_va, 32)
    if curve_id == CURVE25519_EDWARDS:
        from ..ops import ed25519 as ed
        if op == CURVE_OP_MUL:
            p = _edwards_decode(rb)
            k = _canonical_scalar(lb)
            if p is None or k is None:
                return 1
            res = ed._scalar_mul_host(k, p)
        else:
            p, q = _edwards_decode(lb), _edwards_decode(rb)
            if p is None or q is None:
                return 1
            if op == CURVE_OP_SUB:
                P = 2**255 - 19
                q = (P - q[0], q[1], q[2], P - q[3])
            elif op != CURVE_OP_ADD:
                return 1
            res = ed._pt_add_host(p, q)
        vm.mem_write_bytes(out_va, ed._compress_host(res))
        return 0
    if curve_id == CURVE25519_RISTRETTO:
        from ..ops import ristretto255 as ris
        if op == CURVE_OP_MUL:
            p = ris.decode(rb)
            k = _canonical_scalar(lb)
            if p is None or k is None:
                return 1
            res = p.mul(k)
        else:
            p, q = ris.decode(lb), ris.decode(rb)
            if p is None or q is None:
                return 1
            if op == CURVE_OP_ADD:
                res = p + q
            elif op == CURVE_OP_SUB:
                res = p - q
            else:
                return 1
        vm.mem_write_bytes(out_va, res.encode())
        return 0
    return 1


def _sc_curve_multiscalar_mul(vm, curve_id, scalars_va, points_va, n,
                              out_va, *a):
    """sum_i scalar_i * point_i over n pairs (32B LE scalars, 32B
    compressed points), result compressed to out_va."""
    if n == 0 or n > CURVE_MSM_MAX:
        return 1
    scalars = []
    for i in range(n):
        k = _canonical_scalar(vm.mem_read_bytes(scalars_va + 32 * i, 32))
        if k is None:
            return 1
        scalars.append(k)
    pts_raw = [vm.mem_read_bytes(points_va + 32 * i, 32) for i in range(n)]
    if curve_id == CURVE25519_EDWARDS:
        from ..ops import ed25519 as ed
        acc = (0, 1, 1, 0)
        for k, pb in zip(scalars, pts_raw):
            p = _edwards_decode(pb)
            if p is None:
                return 1
            acc = ed._pt_add_host(acc, ed._scalar_mul_host(k, p))
        vm.mem_write_bytes(out_va, ed._compress_host(acc))
        return 0
    if curve_id == CURVE25519_RISTRETTO:
        from ..ops import ristretto255 as ris
        acc = ris.Point.identity()
        for k, pb in zip(scalars, pts_raw):
            p = ris.decode(pb)
            if p is None:
                return 1
            acc = acc + p.mul(k)
        vm.mem_write_bytes(out_va, acc.encode())
        return 0
    return 1


SYSCALLS: dict[int, Syscall] = {}
for _name, _fn, _cost in [
    (b"abort", _sc_abort, 1),
    (b"sol_panic_", _sc_panic, 1),
    (b"sol_log_compute_units_", _sc_log_compute_units, 100),
    (b"sol_log_pubkey", _sc_log_pubkey, 100),
    (b"sol_memmove_", _sc_memmove, 10),
    (b"sol_set_return_data", _sc_set_return_data, 100),
    (b"sol_get_return_data", _sc_get_return_data, 100),
    (b"sol_get_stack_height", _sc_get_stack_height, 100),
    (b"custom_panic", _sc_panic, 100),
    (b"sol_alloc_free_", _sc_alloc_free, 1),
    (b"sol_get_fees_sysvar", _sc_get_fees_sysvar, 100),
    (b"sol_get_last_restart_slot", _sc_get_last_restart_slot, 100),
    (b"sol_remaining_compute_units", _sc_remaining_compute_units, 100),
    (b"sol_get_processed_sibling_instruction",
     _sc_get_processed_sibling_instruction, 100),
    (b"sol_get_clock_sysvar", _sc_get_clock_sysvar, 100),
    (b"sol_get_rent_sysvar", _sc_get_rent_sysvar, 100),
    (b"sol_get_epoch_schedule_sysvar", _sc_get_epoch_schedule_sysvar, 100),
    (b"sol_secp256k1_recover", _sc_secp256k1_recover, 25_000),
    (b"sol_curve_validate_point", _sc_curve_validate_point, 2_500),
    (b"sol_curve_group_op", _sc_curve_group_op, 8_000),
    (b"sol_curve_multiscalar_mul", _sc_curve_multiscalar_mul, 8_000),
    (b"sol_log_", _sc_log, 100),
    (b"sol_log_64_", _sc_log_64, 100),
    (b"sol_memcpy_", _sc_memcpy, 10),
    (b"sol_memset_", _sc_memset, 10),
    (b"sol_memcmp_", _sc_memcmp, 10),
    (b"sol_sha256", _sc_sha256, 85),
    (b"sol_keccak256", _sc_keccak256, 85),
    (b"sol_blake3", _sc_blake3, 85),
    (b"sol_log_data", _sc_log_data, 100),
    (b"sol_create_program_address", _sc_create_program_address, 1500),
    (b"sol_try_find_program_address", _sc_try_find_program_address, 1500),
    (b"sol_invoke_signed_c", _sc_invoke_signed, 1000),
    (b"sol_invoke_signed_rust", _sc_invoke_signed, 1000),
    (b"sol_alt_bn128_group_op", _sc_alt_bn128_group_op, 334),
    (b"sol_alt_bn128_compression", _sc_alt_bn128_compression, 30),
    (b"sol_poseidon", _sc_poseidon, 1),
]:
    SYSCALLS[syscall_id(_name)] = Syscall(_name.decode(), _fn, _cost)
