"""Instruction-fixture replayer (round 4, VERDICT missing #2): the
framework's analogue of the reference's shared runtime test-vectors —
`make run-test-vectors` replays `.fix` (InstrContext -> InstrEffects)
fixtures through test_exec_instr
(ref: contrib/test/run_test_vectors.sh:18-31).

A fixture is one JSON object describing an instruction's pre-state and
expected effects:

    {
      "name":        "system_transfer_ok",
      "program_id":  hex 32B,
      "data":        hex instruction data,
      "accounts": [                      # txn account table, in order
        {"pubkey": hex, "lamports": N, "data": hex, "owner": hex,
         "executable": false, "signer": true, "writable": true,
         "missing": false}               # missing=true -> no account yet
      ],
      "instr_accounts": [0, 1],          # indices passed to the program
      "expect": {
        "ok": true | false,
        "err_contains": "substring",     # when ok=false
        "post": [                        # when ok=true: post-state diffs
          {"index": 0, "lamports": N, "owner": hex?, "data": hex?,
           "data_len": N?}
        ]
      }
    }

replay() builds the same InstrCtx the executor builds for a top-level
instruction, dispatches through the native-program registry, and diffs
effects — instruction-level conformance without txn plumbing, exactly the
test-vectors' altitude.
"""

from __future__ import annotations

import json
from dataclasses import dataclass



@dataclass
class FixtureResult:
    name: str
    passed: bool
    detail: str = ""


def json_to_ctx(fx: dict) -> dict:
    """JSON fixture -> InstrContext dict (the .fix input half): account
    flags move onto instr_accounts, where the runtime (and the proto's
    InstrAcct) define them."""
    accounts = []
    for a in fx.get("accounts", []):
        st = {"address": bytes.fromhex(a["pubkey"])}
        if not a.get("missing", False):
            st["lamports"] = int(a.get("lamports", 0))
            st["data"] = bytes.fromhex(a.get("data", ""))
            st["owner"] = (bytes.fromhex(a["owner"]) if "owner" in a
                           else bytes(32))
            st["executable"] = bool(a.get("executable", False))
            st["rent_epoch"] = int(a.get("rent_epoch", 0))
        accounts.append(st)
    instr_accounts = []
    for idx in fx.get("instr_accounts", []):
        a = fx["accounts"][idx]
        instr_accounts.append({
            "index": idx,
            "is_writable": bool(a.get("writable", True)),
            "is_signer": bool(a.get("signer", False)),
        })
    return {
        "program_id": bytes.fromhex(fx["program_id"]),
        "accounts": accounts,
        "instr_accounts": instr_accounts,
        "data": bytes.fromhex(fx.get("data", "")),
        "epoch": int(fx.get("epoch", 0)),
        "slot": int(fx.get("slot", 0)),
    }


def execute(fx: dict):
    """Run one JSON fixture through the ONE executor-context builder
    (test_vectors.execute_instr_ctx — shared with the .fix replayer and
    the corpus generator, so the two formats cannot diverge).

    Returns (err_string_or_None, txctx)."""
    from . import test_vectors as tv
    return tv.execute_instr_ctx(json_to_ctx(fx))


def replay(fx: dict) -> FixtureResult:
    """Run one fixture; returns pass/fail with a mismatch description."""
    name = fx.get("name", "?")
    try:
        err, txctx = execute(fx)
    except (KeyError, IndexError, ValueError) as e:
        return FixtureResult(name, False, f"bad fixture: {e!r}")

    exp = fx["expect"]
    if exp.get("ok", True):
        if err is not None:
            return FixtureResult(name, False, f"unexpected error: {err}")
        for d in exp.get("post", []):
            a = txctx.accounts[int(d["index"])].acct
            if a is None:
                if not d.get("closed", False):
                    return FixtureResult(
                        name, False, f"acct {d['index']} unexpectedly gone")
                continue
            if "lamports" in d and a.lamports != int(d["lamports"]):
                return FixtureResult(
                    name, False,
                    f"acct {d['index']} lamports {a.lamports} != "
                    f"{d['lamports']}")
            if "owner" in d and a.owner != bytes.fromhex(d["owner"]):
                return FixtureResult(
                    name, False, f"acct {d['index']} owner mismatch")
            if "data" in d and a.data != bytes.fromhex(d["data"]):
                return FixtureResult(
                    name, False, f"acct {d['index']} data mismatch")
            if "data_len" in d and len(a.data) != int(d["data_len"]):
                return FixtureResult(
                    name, False,
                    f"acct {d['index']} data_len {len(a.data)} != "
                    f"{d['data_len']}")
        return FixtureResult(name, True)
    # expected failure
    if err is None:
        return FixtureResult(name, False, "expected an error; succeeded")
    want = exp.get("err_contains", "")
    if want and want.lower() not in err.lower():
        return FixtureResult(
            name, False, f"error {err!r} does not contain {want!r}")
    return FixtureResult(name, True)


def replay_file(path: str) -> list[FixtureResult]:
    with open(path) as f:
        fixtures = json.load(f)
    return [replay(fx) for fx in fixtures]
