"""BPF loader program: deploy + execute on-chain sBPF programs
(ref: src/flamenco/runtime/program/fd_bpf_loader_v3_program.c and the
input serialization in fd_bpf_loader_serialization.c).

Deployment writes the ELF into a program account owned by the loader with
executable=True; execution loads it, serializes the instruction context
into the VM's input region, runs the interpreter, and writes back mutated
account state.

Input ABI (little-endian, one buffer at MM_INPUT — our own fixed layout,
same information content as the reference's):

    u64 n_accounts
    per account:
      u8 is_signer | u8 is_writable | pubkey[32] | owner[32]
      u64 lamports | u64 data_len | data[data_len] | pad to 8
    u64 instr_data_len | instr_data | pad to 8
    pubkey[32] program_id

The program returns 0 in r0 for success (nonzero = custom program error).
"""

import struct

from ..ballet import sbpf
from .system_program import InstrError
from .types import BPF_LOADER_ID, Account
from .vm import Vm, VmError

MAX_ACCOUNT_DATA_GROWTH = 10 * 1024  # per-instruction realloc cap


def ix_deploy(elf: bytes) -> bytes:
    return struct.pack("<I", 0) + elf


def execute_loader(ictx):
    """The loader's own instructions (deploy)."""
    data = ictx.data
    if len(data) < 4:
        raise InstrError("bpf-loader: data too short")
    (disc,) = struct.unpack_from("<I", data)
    if disc == 0:
        prog_acct = ictx.account(0)
        if prog_acct.acct is None or not ictx.is_signer(0):
            raise InstrError("deploy requires the program account signature")
        elf = bytes(data[4:])
        try:
            sbpf.load(elf)  # validate before storing
        except sbpf.SbpfLoaderError as e:
            raise InstrError(f"invalid program: {e}")
        prog_acct.acct.data = elf
        prog_acct.acct.owner = BPF_LOADER_ID
        prog_acct.acct.executable = True
        prog_acct.touch()
    else:
        raise InstrError(f"unsupported bpf-loader instruction {disc}")


def serialize_input(ictx) -> tuple[bytearray, list]:
    """Returns (buffer, per-account (lamports_off, data_off, data_len)) —
    the offsets let CPI refresh the caller's view in place."""
    out = bytearray()
    accts = [ictx.account(i) for i in range(ictx.n_accounts)]
    out += struct.pack("<Q", len(accts))
    offsets = []
    for a in accts:
        acct = a.acct or Account()
        out += struct.pack("<BB", a.signer, a.writable)
        out += a.pubkey + acct.owner
        lam_off = len(out)
        out += struct.pack("<QQ", acct.lamports, len(acct.data))
        offsets.append((lam_off, len(out), len(acct.data)))
        out += acct.data
        if len(out) % 8:
            out += bytes(8 - len(out) % 8)
    out += struct.pack("<Q", len(ictx.data)) + ictx.data
    if len(out) % 8:
        out += bytes(8 - len(out) % 8)
    out += ictx.program_id
    return out, offsets


def deserialize_input(ictx, mem: bytearray):
    """Write back lamports/data of writable accounts (the reference's
    post-execution copy-back, fd_bpf_loader_serialization.c).

    The whole input region is program-writable, so every length/count field
    in it is untrusted after execution: the walk uses the *serialized*
    data lengths (recomputed from the accounts themselves), never lengths
    read back from memory.  Ownership rules are Solana's: only the owner
    program may change an account's data or debit its lamports; anyone may
    credit; executable accounts are immutable."""
    off = 8
    for i in range(ictx.n_accounts):
        a = ictx.account(i)
        acct = a.acct or Account()
        off += 2 + 64
        lamports, dlen = struct.unpack_from("<QQ", mem, off)
        off += 16
        data = bytes(mem[off:off + len(acct.data)])
        off += len(acct.data)
        if off % 8:
            off += 8 - off % 8
        if not a.writable:
            continue
        if dlen != len(acct.data):
            # programs may not resize accounts through the input buffer in
            # this ABI (fixed-size serialization)
            raise InstrError("account data resize not permitted")
        if lamports == acct.lamports and data == acct.data:
            continue
        owned = acct.owner == ictx.program_id
        if acct.executable:
            raise InstrError("program modified an executable account")
        if data != acct.data and not owned:
            raise InstrError(
                "program modified data of an account it does not own")
        if lamports < acct.lamports and not owned:
            raise InstrError(
                "program debited an account it does not own")
        acct.lamports = lamports
        acct.data = data
        a.acct = acct
        a.touch()


class _CpiContext:
    """The VM's bridge for sol_invoke_signed (fd_vm_cpi.h role): commits
    the caller's in-buffer edits, dispatches through the executor's
    privilege-checked invoke path, then refreshes the caller's view."""

    def __init__(self, ictx, inp: bytearray, offsets: list):
        self.ictx = ictx
        self.inp = inp
        self.offsets = offsets
        self.caller_program_id = ictx.program_id

    def invoke(self, program_id, metas, data, pda_signers):
        from .vm import VmFault
        txctx = self.ictx.txctx
        if txctx.executor is None:
            raise VmFault("no executor bound; CPI unavailable")
        try:
            # sync caller's writes (ownership rules enforced) so the
            # callee sees them, then run the callee
            deserialize_input(self.ictx, self.inp)
            txctx.executor.invoke_signed(
                txctx, self.ictx, program_id, metas, data, pda_signers)
        except VmFault:
            raise
        except Exception as e:  # instr errors surface as VM faults
            raise VmFault(f"CPI failed: {type(e).__name__}: {e}")
        # refresh the caller's input view: fixed-size ABI, so a callee
        # resize of a serialized account cannot be represented
        for i, (lam_off, data_off, dlen) in enumerate(self.offsets):
            a = self.ictx.account(i)
            acct = a.acct or Account()
            if len(acct.data) != dlen:
                raise VmFault("account resized during CPI")
            struct.pack_into("<Q", self.inp, lam_off, acct.lamports)
            self.inp[data_off:data_off + dlen] = acct.data


def execute_program(ictx, program_acct) -> None:
    """Run a deployed sBPF program for one instruction."""
    try:
        prog = sbpf.load(program_acct.data)
    except sbpf.SbpfLoaderError as e:
        raise InstrError(f"program account corrupt: {e}")
    inp, offsets = serialize_input(ictx)
    from .vm import DEFAULT_COMPUTE_UNITS
    txctx = ictx.txctx
    budget = max(0, min(DEFAULT_COMPUTE_UNITS,
                        txctx.cu_limit - txctx.compute_units_consumed))
    vm = Vm(prog.text, entry_pc=prog.entry_pc, rodata=prog.rodata,
            input_mem=inp, compute_units=budget)
    vm.cpi = _CpiContext(ictx, inp, offsets)
    vm.ictx = ictx  # sysvar getters / stack height / return data
    try:
        r0 = vm.run(0x4_0000_0000)  # r1 = input region base
    except VmError as e:
        raise InstrError(f"program failed: {e}")
    finally:
        txctx.compute_units_consumed += budget - vm.cu
    if r0 != 0:
        raise InstrError(f"program error {r0:#x}")
    deserialize_input(ictx, inp)
