"""Feature gates (ref: src/flamenco/features/ — fd_features.h registry of
~200 feature pubkeys with activation slots).

A feature is active from its activation slot onward; the registry maps
feature name -> activation slot (None = not scheduled).  Runtime code
branches on `features.active(name, slot)` so consensus-visible behavior
changes can roll out at a coordinated slot, exactly the reference's model
(there the registry is generated from on-chain feature accounts)."""

from dataclasses import dataclass, field

# the known feature set for this chain; grows as gated behaviors land
KNOWN = (
    "strict_blockhash_age",       # enforce the 300-slot recency window
    "stake_cliff_activation",     # cliff (vs warmup-curve) stake activation
    "batch_sigverify_rlc",        # verify tile may use the RLC fast path
)


@dataclass
class Features:
    activation_slot: dict[str, int | None] = field(
        default_factory=lambda: {k: 0 for k in KNOWN})

    def active(self, name: str, slot: int) -> bool:
        if name not in self.activation_slot:
            raise KeyError(f"unknown feature {name!r}")
        a = self.activation_slot[name]
        return a is not None and slot >= a

    def schedule(self, name: str, slot: int | None):
        if name not in self.activation_slot:
            raise KeyError(f"unknown feature {name!r}")
        self.activation_slot[name] = slot
