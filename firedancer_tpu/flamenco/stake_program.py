"""Stake program (ref: src/flamenco/runtime/program/fd_stake_program.c —
theirs ports Solana's full stake state machine; this is the structurally
equivalent core: initialize -> delegate -> (cooldown) deactivate ->
withdraw, with epoch-based activation bookkeeping).

State serialization is our own compact LE format (layout compatibility with
Agave snapshots is a non-goal this round; confined to this module).

    state: u8 kind (0 uninit, 1 initialized, 2 delegated)
    meta:  staker[32] withdrawer[32] u64 rent_exempt_reserve
    delegation (kind 2 only):
           voter[32] u64 stake u64 activation_epoch u64 deactivation_epoch
"""

import struct

from .system_program import InstrError
from .types import STAKE_PROGRAM_ID, VOTE_PROGRAM_ID

U64_MAX = 0xFFFFFFFFFFFFFFFF


class StakeState:
    UNINITIALIZED = 0
    INITIALIZED = 1
    DELEGATED = 2

    def __init__(self):
        self.kind = self.UNINITIALIZED
        self.staker = bytes(32)
        self.withdrawer = bytes(32)
        self.rent_exempt_reserve = 0
        self.voter = bytes(32)
        self.stake = 0
        self.activation_epoch = U64_MAX
        self.deactivation_epoch = U64_MAX

    def serialize(self) -> bytes:
        out = bytearray([self.kind])
        out += self.staker + self.withdrawer
        out += struct.pack("<Q", self.rent_exempt_reserve)
        if self.kind == self.DELEGATED:
            out += self.voter
            out += struct.pack("<QQQ", self.stake, self.activation_epoch,
                               self.deactivation_epoch)
        return bytes(out)

    @classmethod
    def deserialize(cls, raw: bytes) -> "StakeState":
        st = cls()
        if not raw:
            return st
        st.kind = raw[0]
        if st.kind == cls.UNINITIALIZED:
            return st
        st.staker, st.withdrawer = bytes(raw[1:33]), bytes(raw[33:65])
        (st.rent_exempt_reserve,) = struct.unpack_from("<Q", raw, 65)
        if st.kind == cls.DELEGATED:
            st.voter = bytes(raw[73:105])
            st.stake, st.activation_epoch, st.deactivation_epoch = (
                struct.unpack_from("<QQQ", raw, 105))
        return st

    def effective_stake(self, epoch: int) -> int:
        """Instant (cliff) activation/deactivation at epoch boundaries —
        the reference implements Solana's gradual warmup curve; the cliff
        keeps leader-schedule math identical one epoch after any change."""
        if self.kind != self.DELEGATED:
            return 0
        if epoch < self.activation_epoch:
            return 0
        if epoch >= self.deactivation_epoch:
            return 0
        return self.stake


# -- instruction encodings ---------------------------------------------------

def ix_initialize(staker: bytes, withdrawer: bytes) -> bytes:
    return struct.pack("<I", 0) + staker + withdrawer


def ix_delegate() -> bytes:
    return struct.pack("<I", 1)


def ix_deactivate() -> bytes:
    return struct.pack("<I", 2)


def ix_withdraw(lamports: int) -> bytes:
    return struct.pack("<IQ", 3, lamports)


def ix_authorize(new_authority: bytes, role: int) -> bytes:
    """role 0 = staker, 1 = withdrawer."""
    return struct.pack("<I", 4) + new_authority + bytes([role])


# -- execution ---------------------------------------------------------------

def _load(ictx, i):
    sa = ictx.account(i)
    if sa.acct is None or sa.acct.owner != STAKE_PROGRAM_ID:
        raise InstrError("stake account not owned by stake program")
    return sa, StakeState.deserialize(sa.acct.data)


def _store(sa, st):
    sa.acct.data = st.serialize()
    sa.touch()


def _current_epoch(ictx) -> int:
    """The clock epoch (sysvar clock; the Bank sets it per slot)."""
    return ictx.txctx.epoch


def execute(ictx) -> None:
    data = ictx.data
    if len(data) < 4:
        raise InstrError("stake: data too short")
    (disc,) = struct.unpack_from("<I", data)
    if disc == 0:
        _initialize(ictx, data)
    elif disc == 1:
        _delegate(ictx)
    elif disc == 2:
        _deactivate(ictx)
    elif disc == 3:
        _withdraw(ictx, data)
    elif disc == 4:
        _authorize(ictx, data)
    else:
        raise InstrError(f"unsupported stake instruction {disc}")


def _initialize(ictx, data):
    if len(data) < 68:
        # bincode decode of Initialize{staker,withdrawer} fails on
        # truncation (round-4 fixture corpus: a short read would install
        # short authority keys)
        raise InstrError("stake initialize: instruction data too short")
    sa, st = _load(ictx, 0)
    if st.kind != StakeState.UNINITIALIZED:
        raise InstrError("stake account already initialized")
    st.kind = StakeState.INITIALIZED
    st.staker = bytes(data[4:36])
    st.withdrawer = bytes(data[36:68])
    _store(sa, st)


def _delegate(ictx):
    sa, st = _load(ictx, 0)
    va = ictx.account(1)
    if va.acct is None or va.acct.owner != VOTE_PROGRAM_ID:
        raise InstrError("delegation target is not a vote account")
    if st.kind == StakeState.UNINITIALIZED:
        raise InstrError("stake account uninitialized")
    if not ictx.is_signer_key(st.staker):
        raise InstrError("staker must sign delegate")
    if st.kind == StakeState.DELEGATED and st.deactivation_epoch == U64_MAX:
        raise InstrError("stake already delegated")
    st.kind = StakeState.DELEGATED
    st.voter = va.pubkey
    st.stake = sa.acct.lamports - st.rent_exempt_reserve
    st.activation_epoch = _current_epoch(ictx) + 1
    st.deactivation_epoch = U64_MAX
    _store(sa, st)


def _deactivate(ictx):
    sa, st = _load(ictx, 0)
    if st.kind != StakeState.DELEGATED or st.deactivation_epoch != U64_MAX:
        raise InstrError("stake not active")
    if not ictx.is_signer_key(st.staker):
        raise InstrError("staker must sign deactivate")
    st.deactivation_epoch = _current_epoch(ictx) + 1
    _store(sa, st)


def _withdraw(ictx, data):
    sa, st = _load(ictx, 0)
    dest = ictx.account(1)
    (lamports,) = struct.unpack_from("<Q", data, 4)
    if st.kind == StakeState.UNINITIALIZED:
        # an uninitialized account's withdraw authority is the account
        # itself (Agave rule) — without this anyone could drain it
        if not ictx.is_signer_key(sa.pubkey):
            raise InstrError("uninitialized stake withdraw needs the "
                             "stake account's own signature")
    else:
        if not ictx.is_signer_key(st.withdrawer):
            raise InstrError("withdrawer must sign withdraw")
        if (st.kind == StakeState.DELEGATED
                and _current_epoch(ictx) < st.deactivation_epoch):
            raise InstrError("stake not deactivated")
    free = sa.acct.lamports - st.rent_exempt_reserve
    if lamports > free:
        raise InstrError("insufficient withdrawable lamports")
    sa.acct.lamports -= lamports
    if dest.acct is None:
        from .types import Account
        dest.acct = Account()
    dest.acct.lamports += lamports
    sa.touch()
    dest.touch()


def _authorize(ictx, data):
    if len(data) < 37:
        raise InstrError("stake authorize: instruction data too short")
    sa, st = _load(ictx, 0)
    if st.kind == StakeState.UNINITIALIZED:
        raise InstrError("stake account uninitialized")
    new_auth = bytes(data[4:36])
    role = data[36]
    old = st.staker if role == 0 else st.withdrawer
    if not ictx.is_signer_key(old):
        raise InstrError("current authority must sign authorize")
    if role == 0:
        st.staker = new_auth
    else:
        st.withdrawer = new_auth
    _store(sa, st)
