"""Shred repair protocol (ref: src/flamenco/repair/fd_repair.c — signed
window-index requests answered with the shred bytes over UDP).

Request wire format (compact LE, ours):

    sig[64] | from[32] | u8 type | u32 nonce | u64 slot | u32 shred_idx

sig covers everything after it.  Types: WINDOW_INDEX (that exact data
shred), HIGHEST_WINDOW_INDEX (the highest data shred of the slot at
idx >= shred_idx), ORPHAN (highest shred of the slot's parent — walk
towards rooted history).  Response = raw shred bytes | u32 nonce appended
(the nonce lets the requester match responses to requests, as the
reference does)."""

import struct
from dataclasses import dataclass

REQ_WINDOW_INDEX = 0
REQ_HIGHEST_WINDOW_INDEX = 1
REQ_ORPHAN = 2

_HDR = struct.Struct("<64s32sBIQI")


@dataclass(frozen=True)
class RepairRequest:
    signature: bytes
    from_pub: bytes
    type: int
    nonce: int
    slot: int
    shred_idx: int

    def signable(self) -> bytes:
        return _HDR.pack(bytes(64), self.from_pub, self.type, self.nonce,
                         self.slot, self.shred_idx)[64:]

    def serialize(self) -> bytes:
        return _HDR.pack(self.signature, self.from_pub, self.type,
                         self.nonce, self.slot, self.shred_idx)

    @classmethod
    def deserialize(cls, buf: bytes) -> "RepairRequest":
        sig, frm, t, nonce, slot, idx = _HDR.unpack_from(buf)
        return cls(sig, frm, t, nonce, slot, idx)


def make_request(sign_fn, from_pub: bytes, rtype: int, nonce: int,
                 slot: int, shred_idx: int = 0) -> RepairRequest:
    r = RepairRequest(bytes(64), from_pub, rtype, nonce, slot, shred_idx)
    return RepairRequest(sign_fn(r.signable()), from_pub, rtype, nonce,
                         slot, shred_idx)


def encode_response(shred_raw: bytes, nonce: int) -> bytes:
    return shred_raw + struct.pack("<I", nonce)


def decode_response(buf: bytes) -> tuple[bytes, int]:
    (nonce,) = struct.unpack_from("<I", buf, len(buf) - 4)
    return bytes(buf[:-4]), nonce


class RepairServer:
    """Answer repair requests from a shred archive (the serve side of the
    repair tile).  `lookup(slot, idx) -> bytes | None` and
    `highest(slot) -> (idx, bytes) | None` are provided by the blockstore
    holder."""

    def __init__(self, verify_fn, lookup, highest):
        self.verify_fn = verify_fn
        self.lookup = lookup
        self.highest = highest

    def handle(self, payload: bytes) -> bytes | None:
        try:
            req = RepairRequest.deserialize(payload)
        except struct.error:
            return None
        if not self.verify_fn(req.signature, req.signable(), req.from_pub):
            return None
        if req.type == REQ_WINDOW_INDEX:
            raw = self.lookup(req.slot, req.shred_idx)
        elif req.type == REQ_HIGHEST_WINDOW_INDEX:
            hi = self.highest(req.slot)
            raw = hi[1] if hi is not None and hi[0] >= req.shred_idx else None
        elif req.type == REQ_ORPHAN:
            hi = self.highest(req.slot - 1) if req.slot else None
            raw = hi[1] if hi is not None else None
        else:
            return None
        if raw is None:
            return None
        return encode_response(raw, req.nonce)


class RepairClient:
    """Track outstanding wants and build signed requests (the request side:
    fd_repair's needed-window accounting, minus stake-weighted peer
    selection — peers round-robin here)."""

    def __init__(self, sign_fn, identity_pub: bytes):
        self.sign_fn = sign_fn
        self.identity = identity_pub
        self._nonce = 0
        self.outstanding: dict[int, tuple[int, int]] = {}  # nonce->(slot,idx)

    def request_shred(self, slot: int, idx: int) -> RepairRequest:
        self._nonce += 1
        self.outstanding[self._nonce] = (slot, idx)
        return make_request(self.sign_fn, self.identity, REQ_WINDOW_INDEX,
                            self._nonce, slot, idx)

    def request_highest(self, slot: int) -> RepairRequest:
        self._nonce += 1
        self.outstanding[self._nonce] = (slot, -1)
        return make_request(self.sign_fn, self.identity,
                            REQ_HIGHEST_WINDOW_INDEX, self._nonce, slot)

    def handle_response(self, payload: bytes) -> bytes | None:
        """Validate the nonce; returns the shred bytes if it answers an
        outstanding request."""
        if len(payload) < 5:
            return None
        raw, nonce = decode_response(payload)
        if nonce not in self.outstanding:
            return None
        del self.outstanding[nonce]
        return raw
