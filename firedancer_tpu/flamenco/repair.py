"""Shred repair protocol (ref: src/flamenco/repair/fd_repair.c — signed
window-index requests answered with the shred bytes over UDP).

Request wire format (compact LE, ours):

    sig[64] | from[32] | u8 type | u32 nonce | u64 slot | u32 shred_idx

sig covers a DOMAIN-PREFIXED preimage (SIGN_DOMAIN || fields-after-sig):
the prefix makes repair signables disjoint from every other role's
payload shape by construction (a CRDS signable would need its 32-byte
origin pubkey to start with the 13-byte domain — grinding a valid
ed25519 key with 13 fixed prefix bytes is ~2^104 work), which is what
lets the keyguard authorize ROLE_REPAIR requests by prefix instead of a
collidable length heuristic.  Types: WINDOW_INDEX (that exact data
shred), HIGHEST_WINDOW_INDEX (the highest data shred of the slot at
idx >= shred_idx), ORPHAN (highest shred of the slot's parent — walk
towards rooted history).  Response = raw shred bytes | u32 nonce appended
(the nonce lets the requester match responses to requests, as the
reference does)."""

import struct
from dataclasses import dataclass

REQ_WINDOW_INDEX = 0
REQ_HIGHEST_WINDOW_INDEX = 1
REQ_ORPHAN = 2

SIGN_DOMAIN = b"FDTPU_REPAIR\0"  # 13-byte signing domain separator

_HDR = struct.Struct("<64s32sBIQI")

# Wire discriminator (first byte of every repair datagram): requests and
# responses previously told apart by exact payload length alone, so a
# response whose shred+nonce happened to be _HDR.size bytes was misparsed
# as a request (ADVICE r3).  One explicit type byte removes the ambiguity.
MSG_REQUEST = 0xA1
MSG_RESPONSE = 0xA2


@dataclass(frozen=True)
class RepairRequest:
    signature: bytes
    from_pub: bytes
    type: int
    nonce: int
    slot: int
    shred_idx: int

    def signable(self) -> bytes:
        return SIGN_DOMAIN + _HDR.pack(
            bytes(64), self.from_pub, self.type, self.nonce,
            self.slot, self.shred_idx)[64:]

    def serialize(self) -> bytes:
        return bytes([MSG_REQUEST]) + _HDR.pack(
            self.signature, self.from_pub, self.type,
            self.nonce, self.slot, self.shred_idx)

    @classmethod
    def deserialize(cls, buf: bytes) -> "RepairRequest":
        if not buf or buf[0] != MSG_REQUEST:
            raise struct.error("not a repair request")
        sig, frm, t, nonce, slot, idx = _HDR.unpack_from(buf, 1)
        return cls(sig, frm, t, nonce, slot, idx)


def make_request(sign_fn, from_pub: bytes, rtype: int, nonce: int,
                 slot: int, shred_idx: int = 0) -> RepairRequest:
    r = RepairRequest(bytes(64), from_pub, rtype, nonce, slot, shred_idx)
    return RepairRequest(sign_fn(r.signable()), from_pub, rtype, nonce,
                         slot, shred_idx)


def encode_response(shred_raw: bytes, nonce: int) -> bytes:
    return bytes([MSG_RESPONSE]) + shred_raw + struct.pack("<I", nonce)


def decode_response(buf: bytes) -> tuple[bytes, int]:
    if not buf or buf[0] != MSG_RESPONSE:
        raise struct.error("not a repair response")
    (nonce,) = struct.unpack_from("<I", buf, len(buf) - 4)
    return bytes(buf[1:-4]), nonce


class RepairServer:
    """Answer repair requests from a shred archive (the serve side of the
    repair tile).  `lookup(slot, idx) -> bytes | None` and
    `highest(slot) -> (idx, bytes) | None` are provided by the blockstore
    holder."""

    def __init__(self, verify_fn, lookup, highest, parent_of=None):
        self.verify_fn = verify_fn
        self.lookup = lookup
        self.highest = highest
        # slot -> parent slot (Blockstore.parent_slot); forks may skip
        # slots, so parent is NOT always slot-1
        self.parent_of = parent_of or (
            lambda slot: slot - 1 if slot else None)

    def handle(self, payload: bytes) -> bytes | None:
        try:
            req = RepairRequest.deserialize(payload)
        except struct.error:
            return None
        if not self.verify_fn(req.signature, req.signable(), req.from_pub):
            return None
        if req.type == REQ_WINDOW_INDEX:
            raw = self.lookup(req.slot, req.shred_idx)
        elif req.type == REQ_HIGHEST_WINDOW_INDEX:
            hi = self.highest(req.slot)
            raw = hi[1] if hi is not None and hi[0] >= req.shred_idx else None
        elif req.type == REQ_ORPHAN:
            parent = self.parent_of(req.slot)
            hi = self.highest(parent) if parent is not None else None
            raw = hi[1] if hi is not None else None
        else:
            return None
        if raw is None:
            return None
        return encode_response(raw, req.nonce)


class RepairClient:
    """Track outstanding wants and build signed requests (the request side:
    fd_repair's needed-window accounting, minus stake-weighted peer
    selection — peers round-robin here)."""

    def __init__(self, sign_fn, identity_pub: bytes):
        import secrets
        self.sign_fn = sign_fn
        self.identity = identity_pub
        # random starting nonce: an off-path attacker must guess it to
        # spoof a response (responses are additionally shred-sig-checked
        # by the tile when a leader schedule is known)
        self._nonce = secrets.randbits(31)
        self.outstanding: dict[int, tuple[int, int]] = {}  # nonce->(slot,idx)
        # bound the unanswered set: dead peers would otherwise grow it
        # forever (and every live nonce is spoofable by an off-path
        # guesser); dicts iterate in insertion order so eviction is FIFO
        self.max_outstanding = 4096

    def _register(self, key):
        self._nonce += 1
        while len(self.outstanding) >= self.max_outstanding:
            del self.outstanding[next(iter(self.outstanding))]
        self.outstanding[self._nonce] = key
        return self._nonce

    def request_shred(self, slot: int, idx: int) -> RepairRequest:
        nonce = self._register((slot, idx))
        return make_request(self.sign_fn, self.identity, REQ_WINDOW_INDEX,
                            nonce, slot, idx)

    def request_highest(self, slot: int) -> RepairRequest:
        nonce = self._register((slot, -1))
        return make_request(self.sign_fn, self.identity,
                            REQ_HIGHEST_WINDOW_INDEX, nonce, slot)

    def handle_response(self, payload: bytes) -> bytes | None:
        """Validate the nonce; returns the shred bytes if it answers an
        outstanding request."""
        if len(payload) < 6:
            return None
        try:
            raw, nonce = decode_response(payload)
        except struct.error:
            return None
        if nonce not in self.outstanding:
            return None
        del self.outstanding[nonce]
        return raw


class RepairPlanner:
    """The repair STRATEGY (ref fd_repair.c's needed-window accounting +
    request pacing): inspect blockstore gaps and emit the right request
    mix with retry backoff and stake-weighted peer rotation.

      * interior gaps         -> WINDOW_INDEX per missing index
      * incomplete slot tail  -> HIGHEST_WINDOW_INDEX (find the end)
      * unknown parent chain  -> ORPHAN (walk toward rooted history)

    Peers are (pubkey, addr, stake); selection is stake-weighted random
    (the reference's good-peer preference) with per-request rotation so a
    dead peer cannot stall a shred."""

    RETRY_MS = 150          # re-request after this long unanswered
    MAX_TRIES = 10          # then give up (caller re-plans from gossip)
    MAX_INFLIGHT = 256      # request budget per plan() round

    def __init__(self, client: "RepairClient", rng=None,
                 now_ms=None):
        import random
        import time as _t
        self.client = client
        self.rng = rng or random.Random()
        self.now_ms = now_ms or (lambda: int(_t.monotonic() * 1000))
        # (slot, idx) -> [last_sent_ms, tries]; idx -1 = highest, -2 = orphan
        self.pending: dict[tuple[int, int], list] = {}
        self.given_up: set[tuple[int, int]] = set()

    def _pick_peer(self, peers):
        total = sum(max(1, p[2]) for p in peers)
        r = self.rng.randrange(total)
        acc = 0
        for p in peers:
            acc += max(1, p[2])
            if r < acc:
                return p
        return peers[-1]

    def _due(self, key) -> bool:
        if key in self.given_up:
            return False
        ent = self.pending.get(key)
        if ent is None:
            return True
        if ent[1] >= self.MAX_TRIES:
            self.given_up.add(key)
            self.pending.pop(key, None)
            return False
        return self.now_ms() - ent[0] >= self.RETRY_MS

    def _emit(self, key, req, peer, out):
        ent = self.pending.setdefault(key, [0, 0])
        ent[0] = self.now_ms()
        ent[1] += 1
        out.append((req, peer))

    def plan(self, blockstore, repair_slots, peers,
             known_roots=()) -> list:
        """-> [(RepairRequest, peer)] for this round.

        repair_slots: slots replay wants completed; known_roots: slots we
        know are rooted (orphan-walk stops there)."""
        out = []
        if not peers:
            return out
        for slot in repair_slots:
            if len(out) >= self.MAX_INFLIGHT:
                break
            sm = blockstore.slots.get(slot)
            if sm is None or not sm.raw:
                # nothing at all for this slot: find its tail first
                key = (slot, -1)
                if self._due(key):
                    self._emit(key, self.client.request_highest(slot),
                               self._pick_peer(peers), out)
                continue
            if blockstore.slot_complete(slot):
                self._clear_slot(slot)
                continue
            # bound the scan at the highest RECEIVED index in both cases:
            # when last_set_idx is known, the SLOT_COMPLETE shred IS the
            # last data index — one past it no peer can serve
            missing = blockstore.missing_indices(slot, max(sm.raw))
            for idx in missing:
                if len(out) >= self.MAX_INFLIGHT:
                    break
                key = (slot, idx)
                if self._due(key):
                    self._emit(key, self.client.request_shred(slot, idx),
                               self._pick_peer(peers), out)
            if sm.last_set_idx is None:
                key = (slot, -1)
                if self._due(key):
                    self._emit(key, self.client.request_highest(slot),
                               self._pick_peer(peers), out)
            # parent unknown and not rooted: orphan-walk.  The parent is
            # slot - parent_off (data shreds carry the offset; forks skip
            # slots, so slot-1 is only the no-information fallback).
            # Archived parents need no repair (slot_complete only sees
            # hot slots; the archive holds evicted completed ones).
            parent = slot - sm.parent_off if sm.parent_off else slot - 1
            if (parent not in blockstore.slots
                    and parent not in known_roots and parent > 0
                    and (blockstore.archive is None
                         or parent not in blockstore.archive)):
                key = (parent, -2)
                if self._due(key):
                    # ORPHAN carries the CHILD slot; the server resolves
                    # the parent from its own blockstore meta
                    nonce = self.client._register((parent, -2))
                    req = make_request(
                        self.client.sign_fn, self.client.identity,
                        REQ_ORPHAN, nonce, slot)
                    self._emit(key, req, self._pick_peer(peers), out)
        return out

    def on_shred(self, slot: int, idx: int):
        """A shred arrived (any path): stop re-requesting it."""
        self.pending.pop((slot, idx), None)
        self.given_up.discard((slot, idx))

    def _clear_slot(self, slot: int):
        for key in [k for k in self.pending if k[0] == slot]:
            del self.pending[key]
        self.given_up = {k for k in self.given_up if k[0] != slot}
