"""shredcap: capture + replay archives of raw shreds (ref:
src/flamenco/shredcap/ and the `shredcap` tool src/app/shredcap/ — record
the shred stream of live slots to a file, replay it later through the
blockstore for offline debugging/conformance).

File format (version 1): magic, then framed records
    u32 magic "FDSC" | u32 version
    record := u64 slot | u32 len | raw shred bytes
Records appear in capture order (arbitrary slot interleaving, exactly as
received off the wire); replay preserves that order.
"""

from __future__ import annotations

import os
import struct
from typing import Callable, Iterator

_MAGIC = b"FDSC"
_VERSION = 1
_HDR = struct.Struct("<4sI")
_REC = struct.Struct("<QI")


class ShredCapWriter:
    def __init__(self, path: str):
        self._f = open(path, "wb")
        self._f.write(_HDR.pack(_MAGIC, _VERSION))
        self.record_cnt = 0

    def append(self, slot: int, raw: bytes) -> None:
        self._f.write(_REC.pack(slot, len(raw)))
        self._f.write(raw)
        self.record_cnt += 1

    def flush(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def iter_shreds(path: str) -> Iterator[tuple[int, bytes]]:
    """Yield (slot, raw shred) records; raises ValueError on a corrupt or
    truncated archive (a partial final record from a crashed capture is
    tolerated and ends iteration — the capture tool appends atomically
    per record but the process can die mid-write)."""
    with open(path, "rb") as f:
        hdr = f.read(_HDR.size)
        if len(hdr) != _HDR.size:
            raise ValueError(f"{path}: not a shredcap archive")
        magic, version = _HDR.unpack(hdr)
        if magic != _MAGIC:
            raise ValueError(f"{path}: bad magic")
        if version != _VERSION:
            raise ValueError(f"{path}: unsupported version {version}")
        while True:
            rec = f.read(_REC.size)
            if len(rec) < _REC.size:
                return
            slot, ln = _REC.unpack(rec)
            raw = f.read(ln)
            if len(raw) < ln:
                return  # torn final record
            yield slot, raw


def replay_into(path: str, insert: Callable[[bytes], object]) -> int:
    """Replay an archive through `insert(raw_shred)` (typically
    Blockstore.insert_shred); returns records replayed."""
    n = 0
    for _, raw in iter_shreds(path):
        insert(raw)
        n += 1
    return n
