"""System program (ref: src/flamenco/runtime/program/fd_system_program.c).

Instruction encoding follows Solana's bincode enum: u32 LE discriminant
then fields.  Supported: CreateAccount(0), Assign(1), Transfer(2),
Allocate(8) — the instructions the hot pipeline and tests exercise; the
dispatch table makes adding the seed variants mechanical."""

import struct

from .types import Account, SYSTEM_PROGRAM_ID

MAX_PERMITTED_DATA_LENGTH = 10 * 1024 * 1024


class InstrError(Exception):
    """Instruction-level failure; aborts the whole txn (Solana semantics)."""


def ix_create_account(lamports: int, space: int, owner: bytes) -> bytes:
    return struct.pack("<IQQ", 0, lamports, space) + owner


def ix_assign(owner: bytes) -> bytes:
    return struct.pack("<I", 1) + owner


def ix_transfer(lamports: int) -> bytes:
    return struct.pack("<IQ", 2, lamports)


def ix_allocate(space: int) -> bytes:
    return struct.pack("<IQ", 8, space)


def execute(ictx) -> None:
    """ictx: InstrCtx from executor.py (accounts list, data, signer set)."""
    data = ictx.data
    if len(data) < 4:
        raise InstrError("instruction data too short")
    disc = struct.unpack_from("<I", data)[0]
    if disc == 0:
        _create_account(ictx, data)
    elif disc == 1:
        _assign(ictx, data)
    elif disc == 2:
        _transfer(ictx, data)
    elif disc == 8:
        _allocate(ictx, data)
    else:
        raise InstrError(f"unsupported system instruction {disc}")


def _create_account(ictx, data):
    if len(data) < 52:
        # bincode decode of CreateAccount{lamports,space,owner} fails on
        # truncation (caught by the round-4 fixture corpus: a short read
        # would otherwise install a short owner key)
        raise InstrError("create_account: instruction data too short")
    _, lamports, space = struct.unpack_from("<IQQ", data)
    owner = bytes(data[20:52])
    frm, to = ictx.account(0), ictx.account(1)
    if not ictx.is_signer(0) or not ictx.is_signer(1):
        raise InstrError("create_account requires both signatures")
    if to.acct is not None and (to.acct.lamports or to.acct.data
                                or to.acct.owner != SYSTEM_PROGRAM_ID):
        raise InstrError("account already in use")
    if space > MAX_PERMITTED_DATA_LENGTH:
        raise InstrError("data length too large")
    if frm.acct is None or frm.acct.lamports < lamports:
        raise InstrError("insufficient funds")
    frm.acct.lamports -= lamports
    to.acct = Account(lamports=lamports, data=bytes(space), owner=owner)
    frm.touch()
    to.touch()


def _assign(ictx, data):
    if len(data) < 36:
        raise InstrError("assign: instruction data too short")
    owner = bytes(data[4:36])
    a = ictx.account(0)
    if a.acct is None or not ictx.is_signer(0):
        raise InstrError("assign requires the account's signature")
    if a.acct.owner != SYSTEM_PROGRAM_ID:
        raise InstrError("account not owned by system program")
    a.acct.owner = owner
    a.touch()


def _transfer(ictx, data):
    _, lamports = struct.unpack_from("<IQ", data)
    frm, to = ictx.account(0), ictx.account(1)
    if not ictx.is_signer(0):
        raise InstrError("transfer requires source signature")
    if frm.acct is None or frm.acct.owner != SYSTEM_PROGRAM_ID:
        raise InstrError("bad source account")
    if frm.acct.data:
        raise InstrError("source carries data")
    if frm.acct.lamports < lamports:
        raise InstrError("insufficient funds")
    frm.acct.lamports -= lamports
    if to.acct is None:
        to.acct = Account()
    to.acct.lamports += lamports
    frm.touch()
    to.touch()


def _allocate(ictx, data):
    _, space = struct.unpack_from("<IQ", data)
    a = ictx.account(0)
    if a.acct is None or not ictx.is_signer(0):
        raise InstrError("allocate requires the account's signature")
    if a.acct.data or a.acct.owner != SYSTEM_PROGRAM_ID:
        raise InstrError("account already allocated or not system-owned")
    if space > MAX_PERMITTED_DATA_LENGTH:
        raise InstrError("data length too large")
    a.acct.data = bytes(space)
    a.touch()
