"""Block-level runtime (ref: src/flamenco/runtime/fd_runtime.c — block
prepare/execute/finalize; fd_hashes.c — lthash accounts-delta bank hash).

A Bank is one slot's execution context over a funk fork: txns execute
against the fork, the accounts-delta lthash accumulates incrementally, and
freeze() seals the slot with a bank hash chaining parent hash, delta hash,
PoH blockhash and signature count (the fd_hashes.c recipe).  Forks publish
through funk when consensus roots them (choreo's job)."""

import hashlib
from dataclasses import dataclass, field

from ..ballet import lthash
from ..funk import Funk
from . import snapshot as snapshot_mod
from . import sysvar as sysvar_mod
from .accdb import AccDb
from .executor import Executor, TxnResult
from .features import Features
from .genesis import Genesis
from .leaders import leader_schedule
from .types import Account, Rent


@dataclass
class BlockhashQueue:
    """Recent blockhashes for txn recency checks (sysvar recent-blockhashes;
    fd_sysvar_recent_hashes)."""
    max_age: int = 300
    hashes: list[bytes] = field(default_factory=list)
    pinned: set = field(default_factory=set)

    def register(self, h: bytes):
        self.hashes.append(h)
        if len(self.hashes) > self.max_age:
            self.hashes.pop(0)

    def pin(self, h: bytes):
        """Exempt `h` from age eviction — bench-harness hook (the fddev
        benchg analogue refreshes its blockhash over RPC; sources here run
        in other processes with no feedback link yet, so leader-bench
        topologies pin the genesis hash instead)."""
        self.pinned.add(h)

    def is_recent(self, h: bytes) -> bool:
        return h in self.pinned or h in self.hashes

    def copy(self) -> "BlockhashQueue":
        """Fork-local snapshot: hashes are copied (each fork evolves its
        own recency window, as each Agave bank carries its own
        blockhash_queue), the pinned set stays SHARED (pins are a
        process-level bench hook, not fork state)."""
        return BlockhashQueue(self.max_age, list(self.hashes), self.pinned)


class Bank:
    """One slot in preparation (fd_exec_slot_ctx_t)."""

    def __init__(self, rt: "Runtime", slot: int, parent_slot, parent_hash,
                 blockhash_queue: BlockhashQueue | None = None):
        self.rt = rt
        self.slot = slot
        self.epoch = rt.genesis.epoch_schedule().epoch(slot)
        self.parent_slot = parent_slot
        self.parent_hash = parent_hash
        # Per-fork recency state (ADVICE r3): each bank inherits a SNAPSHOT
        # of its parent's queue, so a hash registered on one fork is never
        # "recent" on a competing fork (Agave's per-bank blockhash_queue;
        # ref fd_sysvar_recent_hashes is per-slot-ctx for the same reason).
        self.blockhash_queue = (blockhash_queue if blockhash_queue is not None
                                else rt.blockhash_queue.copy())
        self.xid = ("slot", slot)
        self.delta = lthash.zero()      # accounts-delta lthash accumulator
        self.signature_cnt = 0
        self.txn_cnt = 0
        self.fees = 0
        self.hash: bytes | None = None  # set by freeze()
        self.poh_hash: bytes | None = None

    def execute_txn(self, payload: bytes, parsed=None) -> TxnResult:
        """Execute one verified txn, tracking the accounts-delta hash
        incrementally: sub the prior account states, add the new ones
        (lthash's homomorphism is exactly what makes this a cheap running
        hash — fd_hashes.c accumulates the same way via tpool)."""
        if self.hash is not None:
            raise RuntimeError("bank is frozen")
        ex = self.rt.executor
        pre = {}
        from ..ballet import txn as txn_lib
        if parsed is None:
            try:
                parsed = txn_lib.parse(payload)
            except txn_lib.TxnParseError as e:
                # malformed frags are a txn failure, never a tile death
                return TxnResult(False, f"parse: {e}")
        addrs = list(parsed.account_addrs(payload))
        resolved = None
        if parsed.addr_table_lookup_cnt:
            # v0: resolve ONCE — the lookup-resolved accounts mutate state
            # too and must enter the delta hash; the result (or the
            # failure) is handed to the executor so it never re-resolves
            from .alut_program import TxnLookupError, resolve_lookups
            from .system_program import InstrError
            try:
                resolved = resolve_lookups(ex.accdb, self.xid, parsed,
                                           payload)
                addrs += resolved[0]
            except (TxnLookupError, InstrError, ValueError) as e:
                resolved = e  # executor converts this into a txn failure
        for pk in addrs:
            if pk not in pre:
                raw = self.rt.funk.read(self.xid, pk)
                pre[pk] = raw
        res = ex.execute_txn(self.xid, payload, parsed, epoch=self.epoch,
                             slot=self.slot, resolved_lookups=resolved,
                             blockhash_check=self.blockhash_queue.is_recent)
        for pk, old_raw in pre.items():
            new_raw = self.rt.funk.read(self.xid, pk)
            if new_raw == old_raw:
                continue
            if old_raw is not None:
                self.delta = lthash.sub(
                    self.delta, lthash.hash_account(pk + old_raw))
            if new_raw is not None:
                self.delta = lthash.add(
                    self.delta, lthash.hash_account(pk + new_raw))
        self.txn_cnt += 1
        self.signature_cnt += parsed.signature_cnt
        self.fees += res.fee
        return res

    def freeze(self, poh_hash: bytes, register: bool = True) -> bytes:
        """Seal the slot: bank_hash = sha256(parent_hash ‖ lthash(delta) ‖
        sig_cnt ‖ poh_hash) (fd_hashes.c:fd_hash_bank recipe).

        register=False computes the hash without registering it into the
        bank's own recency queue — replay uses it so a block that FAILS
        its expected-hash check leaves no trace in recency state; the
        caller registers explicitly on acceptance.  Registration is
        per-fork: only this bank's descendants (which snapshot the queue
        at new_bank) see the hash as recent."""
        if self.hash is not None:
            return self.hash
        self.poh_hash = poh_hash
        h = hashlib.sha256()
        h.update(self.parent_hash)
        h.update(lthash.fini(self.delta))
        h.update(self.signature_cnt.to_bytes(8, "little"))
        h.update(poh_hash)
        self.hash = h.digest()
        if register:
            self.blockhash_queue.register(self.hash)
        return self.hash


class Runtime:
    """The chain-level execution context (fd_exec_epoch_ctx_t + bank
    management): genesis boot, bank lifecycle over funk forks, leader
    schedule queries, root publication."""

    def __init__(self, genesis: Genesis, funk: Funk | None = None,
                 _boot: bool = True):
        self.genesis = genesis
        self.funk = funk or Funk()
        self.accdb = AccDb(self.funk)
        self.blockhash_queue = BlockhashQueue()
        self.executor = Executor(
            self.accdb, genesis.lamports_per_signature,
            blockhash_check=self.blockhash_queue.is_recent)
        self.features = Features()
        self.rent = Rent()
        self.banks: dict[int, Bank] = {}
        self.root_slot = 0
        self.root_hash = genesis.genesis_hash()
        self._schedules: dict[int, list[bytes]] = {}
        if _boot:
            # boot slot-0 state straight into the funk root
            for pk, acct in genesis.accounts.items():
                self.funk.write(None, pk, acct.serialize())
            self.blockhash_queue.register(self.root_hash)

    # ------------------------------------------------------- snapshots
    def snapshot(self, path: str):
        """Write a restartable snapshot of the published root
        (SURVEY.md §5 checkpoint/resume mechanism (2))."""
        snapshot_mod.save(
            path, self.funk, slot=self.root_slot,
            bank_hash=self.root_hash,
            blockhashes=self.blockhash_queue.hashes,
            genesis_creation_time=self.genesis.creation_time,
            slots_per_epoch=self.genesis.slots_per_epoch)

    @classmethod
    def from_snapshot(cls, genesis: Genesis, path: str) -> "Runtime":
        """Restore: rebuild funk root + chain tip; banking resumes at
        root_slot + 1 (validator restart = snapshot + catch-up)."""
        info, funk = snapshot_mod.load(path)
        rt = cls(genesis, funk, _boot=False)
        rt.root_slot = info["slot"]
        rt.root_hash = info["bank_hash"]
        for h in info["blockhashes"]:
            rt.blockhash_queue.register(h)
        return rt

    # ----------------------------------------------------------- banks
    def new_bank(self, slot: int, parent_slot: int | None = None) -> Bank:
        """Open a bank for `slot` forking off `parent_slot` (default: the
        root)."""
        if slot in self.banks:
            raise ValueError(f"bank for slot {slot} already open")
        if parent_slot is None or parent_slot == self.root_slot:
            parent_xid, parent_hash = None, self.root_hash
            parent_queue = self.blockhash_queue.copy()
        else:
            parent = self.banks.get(parent_slot)
            if parent is None:
                raise ValueError(f"unknown parent slot {parent_slot}")
            if parent.hash is None:
                raise ValueError(f"parent slot {parent_slot} not frozen")
            parent_xid, parent_hash = parent.xid, parent.hash
            parent_queue = parent.blockhash_queue.copy()
        b = Bank(self, slot, parent_slot, parent_hash, parent_queue)
        self.funk.txn_prepare(b.xid, parent_xid)
        # refresh sysvar accounts for the new slot (fd_sysvar_*_update at
        # block prepare; not part of the txn delta hash — the bank hash
        # commits to txn effects, sysvars are derivable chain metadata)
        es = self.genesis.epoch_schedule()
        sysvar_mod.refresh(
            self.accdb, b.xid, slot=slot,
            unix_ts=self.genesis.creation_time + (slot * 2) // 5,
            epoch=es.epoch(slot), slots_per_epoch=es.slots_per_epoch,
            rent=self.rent, blockhashes=b.blockhash_queue.hashes)
        self.banks[slot] = b
        return b

    def publish(self, slot: int):
        """Root a frozen bank: fold its fork into the funk root and drop
        competing banks (consensus rooting, fd_runtime publish path)."""
        b = self.banks.get(slot)
        if b is None:
            raise ValueError(f"unknown slot {slot}")
        if b.hash is None:
            raise ValueError(f"slot {slot} not frozen")
        self.funk.txn_publish(b.xid)
        self.root_slot, self.root_hash = slot, b.hash
        # the runtime-level queue follows the ROOTED chain: banks opened
        # off the root from now on inherit this fork's recency window
        self.blockhash_queue = b.blockhash_queue.copy()
        dead = [s for s, bk in self.banks.items()
                if not self.funk.txn_is_prepared(bk.xid) or s == slot]
        for s in dead:
            del self.banks[s]

    # --------------------------------------------------------- leaders
    def leader_for_slot(self, slot: int) -> bytes:
        es = self.genesis.epoch_schedule()
        epoch = es.epoch(slot)
        sched = self._schedules.get(epoch)
        if sched is None:
            sched = leader_schedule(
                epoch, self.genesis.stakes, es.slots_per_epoch)
            self._schedules[epoch] = sched
        return sched[slot - es.first_slot(epoch)]

    # --------------------------------------------------------- queries
    def balance(self, pubkey: bytes, slot: int | None = None) -> int:
        xid = None if slot is None else self.banks[slot].xid
        a = self.accdb.load(xid, pubkey)
        return 0 if a is None else a.lamports
