"""Offline ledger conformance driver (ref: src/app/ledger/main.c ingest/
replay/verify + contrib/ledger-tests/ledger_conformance.sh).

Takes a captured ledger (a shredcap archive + the genesis it was produced
from), replays every complete slot through a fresh Runtime in slot order,
emits per-slot bank hashes, optionally records a solcap-style capture, and
diffs against an expected capture — the mechanism that proves the runtime
layers execute consensus-identically.

The PoH start hash of each slot is the closing entry hash of its parent
(genesis slots start from the zero hash, matching the leader pipeline)."""

from dataclasses import dataclass, field

from . import capture as capture_mod
from . import shredcap as shredcap_mod
from .blockstore import Blockstore
from .replay import ReplayResult, replay_slot
from .runtime import Runtime


@dataclass
class LedgerReport:
    shreds: int = 0
    slots_complete: int = 0
    slots_ok: int = 0
    results: list = field(default_factory=list)  # ReplayResult per slot
    first_divergence: dict | None = None  # vs an expected capture

    @property
    def ok(self) -> bool:
        return (self.slots_ok == self.slots_complete
                and self.first_divergence is None)


def replay_ledger(rt: Runtime, shredcap_path: str,
                  capture_path: str | None = None,
                  expected_capture_path: str | None = None,
                  poh_genesis: bytes = bytes(32)) -> LedgerReport:
    """Ingest + replay an entire shredcap archive against `rt` (a freshly
    booted Runtime on the matching genesis)."""
    report = LedgerReport()
    bs = Blockstore(max_slots=1 << 20)
    report.shreds = shredcap_mod.replay_into(shredcap_path, bs.insert_shred)

    expected: dict[int, dict] = {}
    if expected_capture_path:
        expected = {r["slot"]: r
                    for r in capture_mod.read(expected_capture_path)}

    writer = capture_mod.CaptureWriter(capture_path) if capture_path else None
    poh_final: dict[int, bytes] = {}
    try:
        for slot in sorted(bs.slots):
            if not bs.slot_complete(slot):
                continue
            report.slots_complete += 1
            entries = bs.slot_entries(slot)
            if entries is None:
                report.results.append(ReplayResult(
                    slot, False, "entry stream corrupt", None))
                continue
            parent = slot - bs.slots[slot].parent_off
            start = poh_final.get(parent, poh_genesis)
            exp = expected.get(slot)
            exp_hash = bytes.fromhex(exp["bank_hash"]) if exp else None
            res = replay_slot(
                rt, slot, entries, start,
                parent_slot=parent if parent in rt.banks else None,
                expected_bank_hash=exp_hash)
            report.results.append(res)
            if res.ok:
                report.slots_ok += 1
                poh_final[slot] = entries[-1].hash
                if writer is not None:
                    writer.write_slot(capture_mod.record_bank(rt.banks[slot]))
            elif exp is not None and report.first_divergence is None:
                report.first_divergence = {
                    "slot": slot, "field": "bank_hash",
                    "a": res.bank_hash.hex() if res.bank_hash else None,
                    "b": exp["bank_hash"], "err": res.err}
    finally:
        if writer is not None:
            writer.close()
    return report
