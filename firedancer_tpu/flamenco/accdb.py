"""Account manager over funk (ref: src/flamenco/runtime/fd_acc_mgr.c,
fd_borrowed_account.c): typed account views on the fork database, with
borrow bookkeeping done by the executor's load phase instead of refcounts
(single-threaded per bank lane, like one exec tile)."""

from ..funk import Funk
from .types import Account


class AccDb:
    def __init__(self, funk: Funk | None = None):
        self.funk = funk or Funk()

    def load(self, xid, pubkey: bytes) -> Account | None:
        raw = self.funk.read(xid, pubkey)
        return None if raw is None else Account.deserialize(raw)

    def store(self, xid, pubkey: bytes, acct: Account):
        # accounts drained to zero lamports cease to exist (the runtime's
        # account-death rule, fd_executor/fd_acc_mgr)
        if acct.lamports == 0 and not acct.executable:
            self.funk.remove(xid, pubkey)
        else:
            self.funk.write(xid, pubkey, acct.serialize())

    def exists(self, xid, pubkey: bytes) -> bool:
        return self.funk.read(xid, pubkey) is not None
