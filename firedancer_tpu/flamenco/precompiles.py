"""Precompile programs: ed25519 + secp256k1 signature-verify instructions
(ref: src/flamenco/runtime/program/fd_precompiles.c).

These run at txn VERIFICATION time in the reference (no account access,
pure data validation): the instruction data carries offsets into the txn's
instruction list pointing at signature/pubkey/message bytes.  Layout (ours,
compact LE, mirroring the reference's offset-table design):

    u8 count | per item: u16 sig_off | u16 pub_off | u16 msg_off |
                          u16 msg_len   (offsets into THIS ix's data)
    ... followed by the referenced bytes

secp256k1 has no in-image backend; the gate rejects with a clear error
(the reference also gates it behind config/extra/with-secp256k1.mk).
"""

import struct

from .system_program import InstrError
from .types import ED25519_PRECOMPILE_ID, SECP256K1_PRECOMPILE_ID

_ITEM = struct.Struct("<HHHH")


def build_ed25519_ix_data(items: list[tuple[bytes, bytes, bytes]]) -> bytes:
    """items: (sig64, pubkey32, msg) -> instruction data."""
    hdr = bytearray([len(items)])
    body = bytearray()
    base = 1 + _ITEM.size * len(items)
    for sig, pub, msg in items:
        off = base + len(body)
        hdr += _ITEM.pack(off, off + 64, off + 96, len(msg))
        body += sig + pub + msg
    return bytes(hdr + body)


def ed25519_verify_execute(ictx) -> None:
    """Verify every (sig, pub, msg) triple; any failure fails the txn
    (fd_precompile_ed25519_verify)."""
    data = ictx.data
    if not data:
        raise InstrError("ed25519 precompile: empty data")
    n = data[0]
    off = 1
    for i in range(n):
        try:
            s_off, p_off, m_off, m_len = _ITEM.unpack_from(data, off)
        except struct.error:
            raise InstrError("ed25519 precompile: truncated offsets")
        off += _ITEM.size
        sig = bytes(data[s_off : s_off + 64])
        pub = bytes(data[p_off : p_off + 32])
        msg = bytes(data[m_off : m_off + m_len])
        if len(sig) != 64 or len(pub) != 32 or len(msg) != m_len:
            raise InstrError("ed25519 precompile: bad offsets")
        from ..ops.ed25519 import verify_one
        if not verify_one(sig, msg, pub):
            raise InstrError(f"ed25519 precompile: sig {i} invalid")


def secp256k1_verify_execute(ictx) -> None:
    raise InstrError(
        "secp256k1 precompile requires the secp256k1 backend "
        "(not in this build; the reference gates it the same way, "
        "config/extra/with-secp256k1.mk)")


def register():
    from .executor import register_program
    register_program(ED25519_PRECOMPILE_ID, ed25519_verify_execute)
    register_program(SECP256K1_PRECOMPILE_ID, secp256k1_verify_execute)


register()
