"""Precompile programs: ed25519 + secp256k1 signature-verify instructions
(ref: src/flamenco/runtime/program/fd_precompiles.c).

These run at txn VERIFICATION time in the reference (no account access,
pure data validation): the instruction data carries offsets into the txn's
instruction list pointing at signature/pubkey/message bytes.  Layout (ours,
compact LE, mirroring the reference's offset-table design):

    u8 count | per item: u16 sig_off | u16 pub_off | u16 msg_off |
                          u16 msg_len   (offsets into THIS ix's data)
    ... followed by the referenced bytes

secp256k1 has no in-image backend; the gate rejects with a clear error
(the reference also gates it behind config/extra/with-secp256k1.mk).
"""

import struct

from .system_program import InstrError
from .types import ED25519_PRECOMPILE_ID, SECP256K1_PRECOMPILE_ID

_ITEM = struct.Struct("<HHHH")


def build_ed25519_ix_data(items: list[tuple[bytes, bytes, bytes]]) -> bytes:
    """items: (sig64, pubkey32, msg) -> instruction data."""
    hdr = bytearray([len(items)])
    body = bytearray()
    base = 1 + _ITEM.size * len(items)
    for sig, pub, msg in items:
        off = base + len(body)
        hdr += _ITEM.pack(off, off + 64, off + 96, len(msg))
        body += sig + pub + msg
    return bytes(hdr + body)


def ed25519_verify_execute(ictx) -> None:
    """Verify every (sig, pub, msg) triple; any failure fails the txn
    (fd_precompile_ed25519_verify)."""
    data = ictx.data
    if not data:
        raise InstrError("ed25519 precompile: empty data")
    n = data[0]
    off = 1
    for i in range(n):
        try:
            s_off, p_off, m_off, m_len = _ITEM.unpack_from(data, off)
        except struct.error:
            raise InstrError("ed25519 precompile: truncated offsets")
        off += _ITEM.size
        sig = bytes(data[s_off : s_off + 64])
        pub = bytes(data[p_off : p_off + 32])
        msg = bytes(data[m_off : m_off + m_len])
        if len(sig) != 64 or len(pub) != 32 or len(msg) != m_len:
            raise InstrError("ed25519 precompile: bad offsets")
        from ..ops.ed25519 import verify_one
        if not verify_one(sig, msg, pub):
            raise InstrError(f"ed25519 precompile: sig {i} invalid")


def build_secp256k1_ix_data(
    items: list[tuple[bytes, int, bytes, bytes]]
) -> bytes:
    """items: (sig64, recid, eth_addr20, msg) -> instruction data.
    Layout mirrors the ed25519 table: u8 count | per item u16 sig_off
    (64B sig + 1B recid) | u16 addr_off (20B) | u16 msg_off | u16 msg_len."""
    hdr = bytearray([len(items)])
    body = bytearray()
    base = 1 + _ITEM.size * len(items)
    for sig, recid, addr, msg in items:
        off = base + len(body)
        hdr += _ITEM.pack(off, off + 65, off + 85, len(msg))
        body += sig + bytes([recid]) + addr + msg
    return bytes(hdr + body)


def secp256k1_verify_execute(ictx) -> None:
    """Eth-style recoverable-signature check (fd_precompile_secp256k1):
    recover the pubkey from (keccak(msg), sig, recid) and require
    keccak(pub)[12:] to equal the committed 20-byte eth address."""
    from ..ballet.keccak256 import keccak256
    from ..ballet.secp256k1 import eth_address, recover

    data = ictx.data
    if not data:
        raise InstrError("secp256k1 precompile: empty data")
    n = data[0]
    off = 1
    for i in range(n):
        try:
            s_off, a_off, m_off, m_len = _ITEM.unpack_from(data, off)
        except struct.error:
            raise InstrError("secp256k1 precompile: truncated offsets")
        off += _ITEM.size
        sig = bytes(data[s_off : s_off + 64])
        recid_b = bytes(data[s_off + 64 : s_off + 65])
        addr = bytes(data[a_off : a_off + 20])
        msg = bytes(data[m_off : m_off + m_len])
        if len(sig) != 64 or not recid_b or len(addr) != 20 \
                or len(msg) != m_len:
            raise InstrError("secp256k1 precompile: bad offsets")
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        pub = recover(keccak256(msg), r, s, recid_b[0])
        if pub is None or eth_address(pub) != addr:
            raise InstrError(f"secp256k1 precompile: sig {i} invalid")


def register():
    from .executor import register_program
    register_program(ED25519_PRECOMPILE_ID, ed25519_verify_execute)
    register_program(SECP256K1_PRECOMPILE_ID, secp256k1_verify_execute)


register()
