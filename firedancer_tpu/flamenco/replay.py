"""Slot replay: execute a stored block against the runtime (ref:
src/flamenco/runtime/fd_runtime.c block eval — fd_runtime_block_eval_tpool
— and the replay tile src/disco/replay/fd_replay_tile.c).

The follower-side counterpart of the leader's bank tile: given a complete
slot's entries from the blockstore, verify the PoH hash chain, execute
every txn into a fresh bank fork, freeze with the slot's final PoH hash,
and hand the frozen bank to consensus (choreo) for voting/rooting.

PoH verification uses the batched JAX verifier (ballet.poh.entry_verify)
when the slot is large enough to amortize a device round trip, else the
host chain walk — the same two-path split the reference gets from
tpool-parallel verify vs serial."""

from dataclasses import dataclass

from ..ballet import entry as entry_lib
from .runtime import Bank, Runtime

JAX_VERIFY_MIN_ENTRIES = 256  # device batch only pays beyond this


@dataclass
class ReplayResult:
    slot: int
    ok: bool
    err: str | None
    bank_hash: bytes | None
    txn_cnt: int = 0
    txn_fail_cnt: int = 0


def replay_slot(rt: Runtime, slot: int, entries: list[entry_lib.Entry],
                poh_start: bytes, parent_slot: int | None = None,
                expected_bank_hash: bytes | None = None) -> ReplayResult:
    """Execute one complete slot.  Failure semantics are the reference's:
    a PoH break or a bank-hash mismatch marks the block DEAD (the fork is
    cancelled); individual failed txns are recorded but do not invalidate
    the block (they were charged fees by the leader)."""
    if not entry_lib.verify_chain(poh_start, entries):
        return ReplayResult(slot, False, "poh chain mismatch", None)

    bank = rt.new_bank(slot, parent_slot)
    nfail = ntxn = 0
    for e in entries:
        for txn in e.txns:
            res = bank.execute_txn(txn)
            ntxn += 1
            if not res.ok:
                nfail += 1
    # freeze without registering into the shared blockhash queue: a block
    # rejected below must leave no trace in recency state
    bank_hash = bank.freeze(entries[-1].hash if entries else poh_start,
                            register=False)
    if expected_bank_hash is not None and bank_hash != expected_bank_hash:
        rt.funk.txn_cancel(bank.xid)
        del rt.banks[slot]
        return ReplayResult(slot, False, "bank hash mismatch", bank_hash,
                            ntxn, nfail)
    rt.blockhash_queue.register(bank_hash)
    return ReplayResult(slot, True, None, bank_hash, ntxn, nfail)
