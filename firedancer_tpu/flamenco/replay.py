"""Slot replay: execute a stored block against the runtime (ref:
src/flamenco/runtime/fd_runtime.c block eval — fd_runtime_block_eval_tpool
— and the replay tile src/disco/replay/fd_replay_tile.c).

The follower-side counterpart of the leader's bank tile: given a complete
slot's entries from the blockstore, verify the PoH hash chain, execute
every txn into a fresh bank fork, freeze with the slot's final PoH hash,
and hand the frozen bank to consensus (choreo) for voting/rooting.

PoH verification uses the batched JAX verifier (ballet.poh.entry_verify)
when the slot is large enough to amortize a device round trip, else the
host chain walk — the same two-path split the reference gets from
tpool-parallel verify vs serial."""

from dataclasses import dataclass

from ..ballet import entry as entry_lib
from .runtime import Bank, Runtime

JAX_VERIFY_MIN_ENTRIES = 256  # device batch only pays beyond this


@dataclass
class ReplayResult:
    slot: int
    ok: bool
    err: str | None
    bank_hash: bytes | None
    txn_cnt: int = 0
    txn_fail_cnt: int = 0


def replay_slot(rt: Runtime, slot: int, entries: list[entry_lib.Entry],
                poh_start: bytes, parent_slot: int | None = None,
                expected_bank_hash: bytes | None = None,
                workers: int | None = None) -> ReplayResult:
    """Execute one complete slot.  Failure semantics are the reference's:
    a PoH break or a bank-hash mismatch marks the block DEAD (the fork is
    cancelled); individual failed txns are recorded but do not invalidate
    the block (they were charged fees by the leader).

    workers > 1 executes the block's txns through the wave-parallel path
    (parallel_exec, the fd_runtime_block_eval_tpool analogue) — the bank
    hash is bit-identical to serial by lthash commutativity."""
    if not entry_lib.verify_chain(poh_start, entries):
        return ReplayResult(slot, False, "poh chain mismatch", None)

    bank = rt.new_bank(slot, parent_slot)
    nfail = ntxn = 0
    if workers is not None and workers > 1:
        from .parallel_exec import execute_block_parallel
        payloads = [txn for e in entries for txn in e.txns]
        for res in execute_block_parallel(bank, payloads, workers=workers):
            ntxn += 1
            if not res.ok:
                nfail += 1
    else:
        for e in entries:
            for txn in e.txns:
                res = bank.execute_txn(txn)
                ntxn += 1
                if not res.ok:
                    nfail += 1
    # freeze without registering: a block rejected below must leave no
    # trace in recency state.  On acceptance the hash registers into the
    # BANK's own queue (per-fork recency, ADVICE r3): only descendants of
    # this bank — which snapshot its queue at new_bank — see it as recent;
    # competing forks never do.
    bank_hash = bank.freeze(entries[-1].hash if entries else poh_start,
                            register=False)
    if expected_bank_hash is not None and bank_hash != expected_bank_hash:
        rt.funk.txn_cancel(bank.xid)
        del rt.banks[slot]
        return ReplayResult(slot, False, "bank hash mismatch", bank_hash,
                            ntxn, nfail)
    bank.blockhash_queue.register(bank_hash)
    return ReplayResult(slot, True, None, bank_hash, ntxn, nfail)


class ForkReplay:
    """Fork-aware replay + consensus loop (the tvu core: ref
    src/disco/tvu/fd_tvu.c replay/vote flow over src/choreo/ghost).

    Couples a Blockstore (shred accumulation), the Runtime's fork banks
    (funk txn tree), and choreo's Voter (ghost fork choice + TowerBFT).
    drain() replays every COMPLETE slot whose parent chain is replayed —
    across competing forks, not one linear chain — counts votes found in
    replayed blocks into ghost, votes per the tower, and roots (publishes
    into funk) when the tower says so.  A dead slot kills only its own
    subtree."""

    def __init__(self, rt: Runtime, store, voter, poh_start: bytes,
                 stakes: dict[bytes, int] | None = None):
        from .types import VOTE_PROGRAM_ID
        from . import vote_program
        from ..choreo.ghost import Ghost
        self.rt = rt
        self.store = store
        self.voter = voter
        # ghost must be rooted where the runtime is (snapshot restarts
        # begin at root_slot > 0; a 0-rooted ghost would reject the first
        # insert)
        if not voter.ghost.contains(rt.root_slot):
            voter.ghost = Ghost(rt.root_slot)
        self.stakes = dict(stakes or rt.genesis.stakes)
        self.replayed: dict[int, bytes] = {}       # slot -> bank hash
        self.poh_end: dict[int, bytes] = {rt.root_slot: poh_start}
        self.dead: set[int] = set()
        self._vp = vote_program
        self._vote_pid = VOTE_PROGRAM_ID

    def _count_block_votes(self, entries):
        """Votes landing in a replayed block move peer stake in ghost
        (fd_ghost_replay_vote's feed).  The vote txn's fee payer is the
        peer identity; its stake comes from the epoch stake view.

        The fee payer's SIGNATURE is verified before any stake moves —
        block inclusion proves only what the leader chose to pack, and an
        unverified vote would let a leader steer every follower's fork
        choice with forged high-stake votes."""
        from ..ballet import txn as txn_lib
        from ..ops.ed25519 import verify_one_host
        for e in entries:
            for raw in e.txns:
                try:
                    t = txn_lib.parse(raw)
                except txn_lib.TxnParseError:
                    continue
                addrs = t.account_addrs(raw)
                voted = None
                for ix in t.instrs:
                    if (ix.program_id >= len(addrs)
                            or addrs[ix.program_id] != self._vote_pid):
                        continue
                    slots = self._vp.parse_vote(
                        bytes(raw[ix.data_off : ix.data_off + ix.data_sz]))
                    if slots:
                        voted = max(slots) if voted is None \
                            else max(voted, max(slots))
                if voted is None:
                    continue
                node = addrs[0]
                stake = self.stakes.get(node, 0)
                if not stake:
                    continue
                sigs = t.signatures(raw)
                if not sigs or not verify_one_host(
                        sigs[0], t.message(raw), node):
                    continue                     # forged: no stake moves
                self.voter.on_peer_vote(node, stake, voted)

    def drain(self) -> list[tuple[ReplayResult, object]]:
        """Replay everything replayable; returns [(result, VoteDecision |
        None)] for newly processed slots (dead slots carry decision
        None)."""
        out = []
        progress = True
        while progress:
            progress = False
            for slot in sorted(self.store.slots):
                if (slot in self.replayed or slot in self.dead
                        or slot <= self.rt.root_slot):
                    continue
                if not self.store.slot_complete(slot):
                    continue
                parent = self.store.parent_slot(slot)
                if parent is None:
                    continue
                if parent in self.dead:
                    # descendants of a dead block are dead (the fork is
                    # cancelled, fd_replay semantics)
                    self.dead.add(slot)
                    out.append((ReplayResult(slot, False, "dead parent",
                                             None), None))
                    progress = True
                    continue
                if parent != self.rt.root_slot and parent not in self.replayed:
                    continue            # wait for the parent
                if (parent != self.rt.root_slot
                        and parent not in self.rt.banks):
                    # parent replayed but its BANK was discarded by a
                    # root elsewhere: this whole fork lost consensus
                    self.dead.add(slot)
                    out.append((ReplayResult(slot, False, "discarded fork",
                                             None), None))
                    progress = True
                    continue
                if parent not in self.poh_end:
                    continue
                entries = self.store.slot_entries(slot)
                if entries is None:
                    self.dead.add(slot)
                    out.append((ReplayResult(slot, False, "corrupt entries",
                                             None), None))
                    progress = True
                    continue
                res = replay_slot(self.rt, slot, entries,
                                  self.poh_end[parent], parent_slot=parent)
                progress = True
                if not res.ok:
                    self.dead.add(slot)
                    out.append((res, None))
                    continue
                self.replayed[slot] = res.bank_hash
                self.poh_end[slot] = (entries[-1].hash if entries
                                      else self.poh_end[parent])
                self._count_block_votes(entries)
                decision = self.voter.on_slot(slot, parent, res.bank_hash)
                if (decision.rooted is not None
                        and decision.rooted > self.rt.root_slot
                        and decision.rooted in self.rt.banks):
                    self.rt.publish(decision.rooted)
                    root = self.rt.root_slot
                    # keep only slots whose banks SURVIVED the root (the
                    # rooted chain's descendants) — slot-number pruning
                    # alone would leave discarded-fork slots looking
                    # "replayed" and their children would then fork off
                    # deleted banks
                    self.replayed = {s: h for s, h in self.replayed.items()
                                     if s in self.rt.banks}
                    self.poh_end = {s: h for s, h in self.poh_end.items()
                                    if s == root or s in self.rt.banks}
                    self.dead = {s for s in self.dead if s > root}
                out.append((res, decision))
        return out

    @property
    def head(self) -> int:
        return self.voter.ghost.head()
