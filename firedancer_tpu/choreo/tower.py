"""TowerBFT vote lockouts (ref: src/choreo/tower/fd_tower.c).

The tower is a stack of (slot, confirmation_count) votes; a vote at
confirmation c is locked out for 2^c slots — until expiration the validator
may only vote on descendants of that slot.  Voting pops expired entries,
pushes the new vote at c=1, and doubles deeper confirmations; a vote
reaching depth 32 roots its slot.

The lockout machine itself lives in flamenco.vote_program.apply_vote_slot —
one implementation shared with the on-chain vote program, because the local
tower and on-chain vote state must evolve identically."""

from ..flamenco.vote_program import (INITIAL_LOCKOUT, MAX_LOCKOUT_HISTORY,
                                     apply_vote_slot)


class Tower:
    def __init__(self):
        self.votes: list[tuple[int, int]] = []  # (slot, confirmation_count)
        self.root_slot: int | None = None

    def lockout_until(self, i: int) -> int:
        slot, conf = self.votes[i]
        return slot + INITIAL_LOCKOUT ** conf

    def is_locked_out(self, slot: int, is_ancestor) -> bool:
        """May we vote on `slot`?  For every unexpired tower vote, `slot`
        must descend from it (is_ancestor(anc_slot, slot) -> bool supplied
        by the fork tree / ghost)."""
        for i, (vslot, conf) in enumerate(self.votes):
            if slot <= vslot:
                return True  # never vote backwards/sideways onto the past
            if slot <= self.lockout_until(i) and not is_ancestor(vslot, slot):
                return True
        return False

    def record_vote(self, slot: int) -> int | None:
        """Apply a vote; returns a newly-rooted slot or None (this is the
        validator's LOCAL tower, fd_tower.c, running the shared on-chain
        lockout machine)."""
        rooted = apply_vote_slot(self.votes, slot)
        if rooted is not None:
            self.root_slot = rooted
        return rooted

    def best_vote_slot(self, ghost, candidate_slot: int) -> int | None:
        """The voter's decision (fd_voter): vote for ghost's head iff the
        tower permits it."""
        if self.is_locked_out(candidate_slot, ghost.is_ancestor):
            return None
        return candidate_slot
