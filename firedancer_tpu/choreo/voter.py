"""The voter: ghost + tower -> vote txns (ref: src/choreo/voter/fd_voter.c,
early-WIP there too — SURVEY.md §2.8).

Per replayed slot the consensus loop is:
  1. insert the slot into ghost under its parent
  2. count every validator's replayed votes into ghost
  3. pick ghost's head; ask the local tower if voting there is permitted
  4. if yes: record locally, build a vote txn (vote_program.ix_vote) over
     the vote authority, to be signed via keyguard and gossiped/submitted
  5. tower roots -> publish runtime + ghost roots
"""

from dataclasses import dataclass, field

from ..ballet import txn as txn_lib
from ..flamenco import vote_program
from .ghost import Ghost
from .tower import Tower


@dataclass
class VoteDecision:
    slot: int | None            # slot voted for (None = locked out)
    rooted: int | None          # newly rooted slot, if any
    txn_message: bytes | None   # unsigned vote txn message (keyguard signs)


@dataclass
class Voter:
    vote_account: bytes
    node_pubkey: bytes
    ghost: Ghost = field(default_factory=Ghost)
    tower: Tower = field(default_factory=Tower)

    def on_slot(self, slot: int, parent_slot: int,
                recent_blockhash: bytes) -> VoteDecision:
        """A freshly replayed (valid) slot: consider voting on it."""
        if not self.ghost.contains(slot):
            self.ghost.insert(slot, parent_slot)
        head = self.ghost.head()
        cand = self.tower.best_vote_slot(self.ghost, head)
        if cand is None:
            return VoteDecision(None, None, None)
        rooted = self.tower.record_vote(cand)
        msg = txn_lib.build_unsigned(
            [self.node_pubkey], recent_blockhash,
            [(2, bytes([1]), vote_program.ix_vote([cand]))],
            extra_accounts=[self.vote_account,
                            vote_program.VOTE_PROGRAM_ID],
            readonly_unsigned_cnt=1)
        if rooted is not None:
            self.ghost.publish(rooted)
        return VoteDecision(cand, rooted, msg)

    def on_peer_vote(self, pubkey: bytes, stake: int, slot: int):
        """A vote observed in a replayed block or over gossip."""
        if self.ghost.contains(slot):
            self.ghost.replay_vote(pubkey, stake, slot)
