"""choreo — consensus (ref: src/choreo/): ghost fork-choice tree, tower
lockouts, the voter glue."""

from .ghost import Ghost  # noqa: F401
from .tower import Tower  # noqa: F401
