"""Greedy heaviest-observed-subtree fork choice
(ref: src/choreo/ghost/fd_ghost.c).

A tree of slots; each validator's LATEST vote places its stake on one node;
a node's weight is its own stake plus all descendants'; the head is found
by walking from the root taking the heaviest child at every step (ties
break to the lower slot, the reference's deterministic tiebreak)."""


class _Node:
    __slots__ = ("slot", "parent", "children", "stake", "weight")

    def __init__(self, slot, parent):
        self.slot = slot
        self.parent = parent
        self.children: list[_Node] = []
        self.stake = 0      # stake voting directly for this slot
        self.weight = 0     # stake + descendants' weight


class Ghost:
    def __init__(self, root_slot: int = 0):
        self._nodes: dict[int, _Node] = {}
        self.root = _Node(root_slot, None)
        self._nodes[root_slot] = self.root
        self._votes: dict[bytes, tuple[int, int]] = {}  # pk -> (slot, stake)

    def insert(self, slot: int, parent_slot: int):
        if slot in self._nodes:
            raise ValueError(f"slot {slot} already in tree")
        parent = self._nodes.get(parent_slot)
        if parent is None:
            raise ValueError(f"unknown parent slot {parent_slot}")
        if slot <= parent_slot:
            raise ValueError("slot must be greater than parent")
        n = _Node(slot, parent)
        parent.children.append(n)
        self._nodes[slot] = n

    def contains(self, slot: int) -> bool:
        return slot in self._nodes

    def replay_vote(self, pubkey: bytes, stake: int, slot: int):
        """Count `pubkey`'s latest vote: move its stake from its previous
        vote slot (if any) to `slot` (fd_ghost_replay_vote)."""
        node = self._nodes.get(slot)
        if node is None:
            raise ValueError(f"vote for unknown slot {slot}")
        prev = self._votes.get(pubkey)
        if prev is not None:
            pslot, pstake = prev
            if pslot == slot and pstake == stake:
                return
            pnode = self._nodes.get(pslot)
            if pnode is not None:
                pnode.stake -= pstake
                self._adjust_weight(pnode, -pstake)
        self._votes[pubkey] = (slot, stake)
        node.stake += stake
        self._adjust_weight(node, stake)

    def _adjust_weight(self, node: _Node, delta: int):
        while node is not None:
            node.weight += delta
            node = node.parent

    def head(self) -> int:
        """Greedy heaviest descent from the root.  Zero-weight children are
        still descended (ties break to the LOWER slot): with no stake
        observed yet a validator must still pick its chain tip — e.g. a
        lone leader voting on its own blocks."""
        n = self.root
        while n.children:
            n = max(n.children, key=lambda c: (c.weight, -c.slot))
        return n.slot

    def weight(self, slot: int) -> int:
        return self._nodes[slot].weight

    def is_ancestor(self, ancestor_slot: int, slot: int) -> bool:
        n = self._nodes.get(slot)
        while n is not None:
            if n.slot == ancestor_slot:
                return True
            n = n.parent
        return False

    def publish(self, new_root_slot: int):
        """Advance the root (consensus rooted `new_root_slot`): the subtree
        under it survives, everything else is pruned (fd_ghost_publish)."""
        new_root = self._nodes.get(new_root_slot)
        if new_root is None:
            raise ValueError(f"unknown slot {new_root_slot}")
        keep: set[int] = set()
        stack = [new_root]
        while stack:
            n = stack.pop()
            keep.add(n.slot)
            stack.extend(n.children)
        self._nodes = {s: n for s, n in self._nodes.items() if s in keep}
        new_root.parent = None
        self.root = new_root
        # votes for pruned slots no longer count
        self._votes = {pk: (s, st) for pk, (s, st) in self._votes.items()
                       if s in keep}
