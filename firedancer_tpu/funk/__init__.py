from .funk import Funk, FunkTxnError  # noqa: F401
