from .funk import PART_NULL, Funk, FunkTxnError  # noqa: F401
