"""funk — fork-aware record database (ref: src/funk/fd_funk.h:1-62).

The reference models a blockchain's speculative forks: a flat root table of
key->val records plus a tree of in-preparation transactions, each holding a
delta of updated/deleted records against its parent.  Queries resolve along
the ancestry chain; publishing a transaction folds its root-path into the
root table and prunes competing forks (fd_funk_txn.c); canceling discards a
subtree (fd_funk_rec.c / fd_funk_val.c hold the record/value machinery).

TPU-native shape: the hot validator state lives in device arrays; funk is
host control-plane bookkeeping, so a dict-delta tree is the idiomatic
implementation (no shared-memory relocatable pointers needed — persistence
is an explicit checkpoint file, mirroring fd_wksp_checkpt/restore,
src/util/wksp/fd_wksp.h:967-1008).

Keys are bytes (account addresses); values are bytes; xids are opaque
hashables (slot numbers, (slot, hash) pairs, ...).
"""

import pickle
import threading

_TOMBSTONE = object()

PART_NULL = 0xFFFFFFFF  # unassigned partition (ref fd_funk_part.h NULL part)


class FunkTxnError(RuntimeError):
    pass


class _Txn:
    __slots__ = ("xid", "parent", "children", "delta", "frozen")

    def __init__(self, xid, parent):
        self.xid = xid
        self.parent = parent          # _Txn | None (None = child of root)
        self.children: list = []
        self.delta: dict = {}         # key -> bytes | _TOMBSTONE
        self.frozen = False           # has in-preparation children


class Funk:
    """Thread-safety contract (the reference's concurrency model,
    test_funk_concur.cxx): many readers + one writer per txn lane.  Every
    tree walk (prepare/cancel/publish/read/keys and root writes)
    serializes on one lock; per-txn delta writes additionally rely on the
    one-writer-per-lane rule, exactly the reference's per-txn ownership."""

    def __init__(self, part_cnt: int = 16):
        self._root: dict = {}                # published key -> val
        self._txns: dict = {}                # xid -> _Txn
        self._root_children: list[_Txn] = []
        self._lock = threading.RLock()
        # -------- partitions (ref src/funk/fd_funk_part.c) --------
        # Root records are tagged into part_cnt buckets so parallel
        # workers (tpool analogue: account-hash sweeps, snapshot writers)
        # can each walk a disjoint slice.  Unassigned = PART_NULL.
        self.part_cnt = part_cnt
        self._parts: dict = {}               # key -> partition id

    # ---------------------------------------------------------------- txns
    def txn_prepare(self, xid, parent_xid=None):
        """Open an in-preparation transaction forking off `parent_xid`
        (None = the last published root).  A parent with a child is frozen:
        no further writes (fd_funk.h: only leaves are writable)."""
        with self._lock:
            if xid in self._txns:
                raise FunkTxnError(f"xid {xid!r} already in preparation")
            parent = None
            if parent_xid is not None:
                parent = self._txns.get(parent_xid)
                if parent is None:
                    raise FunkTxnError(
                        f"parent {parent_xid!r} not in preparation")
            t = _Txn(xid, parent)
            self._txns[xid] = t
            if parent is None:
                self._root_children.append(t)
            else:
                parent.children.append(t)
                parent.frozen = True
            return xid

    def txn_cancel(self, xid):
        """Discard a transaction and its whole subtree."""
        with self._lock:
            t = self._txns.get(xid)
            if t is None:
                raise FunkTxnError(f"xid {xid!r} not in preparation")
            self._drop_subtree(t)
            if t.parent is None:
                self._root_children.remove(t)
            else:
                t.parent.children.remove(t)
                if not t.parent.children:
                    t.parent.frozen = False

    def _drop_subtree(self, t: _Txn):
        stack = [t]
        while stack:  # iterative: fork chains can exceed recursion depth
            n = stack.pop()
            stack.extend(n.children)
            del self._txns[n.xid]

    def txn_publish(self, xid) -> int:
        """Make `xid` the new root: fold every ancestor delta (oldest first)
        then its own into the root table, cancel all competing forks, and
        re-parent xid's children onto the root.  Returns published txn count
        (the reference's O(1) pointer swing becomes O(delta) folding — the
        honest cost model for a dict-backed table)."""
        with self._lock:
            t = self._txns.get(xid)
            if t is None:
                raise FunkTxnError(f"xid {xid!r} not in preparation")
            chain = []
            cur = t
            while cur is not None:
                chain.append(cur)
                cur = cur.parent
            chain.reverse()  # root-most first
            # fold deltas into the root table
            for txn in chain:
                for k, v in txn.delta.items():
                    if v is _TOMBSTONE:
                        self._root.pop(k, None)
                        self._parts.pop(k, None)
                    else:
                        self._root[k] = v
            # prune competing forks: every root child not on the chain dies
            chain_set = {c.xid for c in chain}
            for rc in list(self._root_children):
                if rc.xid not in chain_set:
                    self._drop_subtree(rc)
                    self._root_children.remove(rc)
            # drop the chain; survivors are xid's children, now root kids
            for txn in chain:
                for c in list(txn.children):
                    if c.xid not in chain_set:
                        if txn is not t:
                            # sibling fork off an interior ancestor: dies
                            self._drop_subtree(c)
                        else:
                            c.parent = None
                del self._txns[txn.xid]
            self._root_children = [c for c in t.children]
            for c in self._root_children:
                c.parent = None
            return len(chain)

    def txn_is_prepared(self, xid) -> bool:
        return xid in self._txns

    # --------------------------------------------------------------- recs
    def write(self, xid, key: bytes, val: bytes):
        """Write a record in txn `xid` (None = directly to the root —
        allowed only with no forks in flight, like the reference's root
        modify restriction)."""
        if xid is None:
            with self._lock:
                if self._txns:
                    raise FunkTxnError(
                        "cannot write root with txns in flight")
                self._root[key] = val
            return
        t = self._txns.get(xid)
        if t is None:
            raise FunkTxnError(f"xid {xid!r} not in preparation")
        if t.frozen:
            raise FunkTxnError(f"xid {xid!r} is frozen (has children)")
        t.delta[key] = val

    def remove(self, xid, key: bytes):
        if xid is None:
            with self._lock:
                if self._txns:
                    raise FunkTxnError(
                        "cannot write root with txns in flight")
                self._root.pop(key, None)
                self._parts.pop(key, None)
            return
        t = self._txns.get(xid)
        if t is None:
            raise FunkTxnError(f"xid {xid!r} not in preparation")
        if t.frozen:
            raise FunkTxnError(f"xid {xid!r} is frozen (has children)")
        t.delta[key] = _TOMBSTONE

    def read(self, xid, key: bytes):
        """Resolve `key` as seen from fork `xid` (None = root view):
        nearest delta on the ancestry chain wins (fd_funk_rec_query_global).

        Locked: the ancestry walk must not observe a concurrent publish
        mid-fold (the torn-read the reference's concur test hunts for)."""
        with self._lock:
            if xid is not None:
                t = self._txns.get(xid)
                if t is None:
                    raise FunkTxnError(f"xid {xid!r} not in preparation")
                while t is not None:
                    if key in t.delta:
                        v = t.delta[key]
                        return None if v is _TOMBSTONE else v
                    t = t.parent
            return self._root.get(key)

    def keys(self, xid=None):
        """All live keys as seen from fork `xid` (root view by default)."""
        with self._lock:
            dead, out = set(), {}
            chain = []
            if xid is not None:
                t = self._txns.get(xid)
                if t is None:
                    raise FunkTxnError(f"xid {xid!r} not in preparation")
                while t is not None:
                    chain.append(t)
                    t = t.parent
            for t in chain:  # leaf-most first: nearest delta wins
                for k, v in t.delta.items():
                    if k in out or k in dead:
                        continue
                    if v is _TOMBSTONE:
                        dead.add(k)
                    else:
                        out[k] = v
            for k, v in self._root.items():
                if k not in out and k not in dead:
                    out[k] = v
            return out

    @property
    def record_cnt(self) -> int:
        return len(self._root)

    # ------------------------------------------- partitions (fd_funk_part)
    def part_set(self, key: bytes, part: int):
        """Tag a ROOT record into a partition (fd_funk_part_set)."""
        if part != PART_NULL and not 0 <= part < self.part_cnt:
            raise ValueError(f"partition {part} out of range")
        with self._lock:
            if key not in self._root:
                raise KeyError("part_set on a key not in the root table")
            if part == PART_NULL:
                self._parts.pop(key, None)
            else:
                self._parts[key] = part

    def part_of(self, key: bytes) -> int:
        return self._parts.get(key, PART_NULL)

    def repartition(self, key_fn=None):
        """(Re)assign every root record to a partition.  Default key_fn is
        a stable hash spread — the fd_funk_part default-partitioning role
        so tpool-style workers can each own a disjoint slice."""
        if key_fn is None:
            def key_fn(k):
                return int.from_bytes(k[:8].ljust(8, b"\0"), "little") \
                    % self.part_cnt
        with self._lock:
            self._parts = {k: key_fn(k) for k in self._root}

    def part_keys(self, part: int) -> list:
        """Root keys in `part` (PART_NULL = the unassigned remainder)."""
        with self._lock:
            if part == PART_NULL:
                return [k for k in self._root if k not in self._parts]
            return [k for k, p in self._parts.items() if p == part]

    # -------------------------------------------------- checkpoint/restore
    def checkpoint(self, path: str):
        """Persist the PUBLISHED state (in-preparation forks are by
        definition speculative and excluded, like wksp checkpt of a funk
        that has been published)."""
        with self._lock:
            # snapshot under the lock, serialize OUTSIDE it: pickling a
            # GB-scale root to disk must not stall every reader
            snap = {"version": 1, "root": dict(self._root),
                    "parts": dict(self._parts), "part_cnt": self.part_cnt}
        with open(path, "wb") as f:
            pickle.dump(snap, f)

    @classmethod
    def restore(cls, path: str) -> "Funk":
        with open(path, "rb") as f:
            d = pickle.load(f)
        if d.get("version") != 1:
            raise ValueError(f"bad funk checkpoint version {d.get('version')}")
        fk = cls(part_cnt=d.get("part_cnt", 16))
        fk._root = d["root"]
        fk._parts = d.get("parts", {})
        return fk
