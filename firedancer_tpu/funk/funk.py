"""funk — fork-aware record database (ref: src/funk/fd_funk.h:1-62).

The reference models a blockchain's speculative forks: a flat root table of
key->val records plus a tree of in-preparation transactions, each holding a
delta of updated/deleted records against its parent.  Queries resolve along
the ancestry chain; publishing a transaction folds its root-path into the
root table and prunes competing forks (fd_funk_txn.c); canceling discards a
subtree (fd_funk_rec.c / fd_funk_val.c hold the record/value machinery).

TPU-native shape: the hot validator state lives in device arrays; funk is
host control-plane bookkeeping, so a dict-delta tree is the idiomatic
implementation (no shared-memory relocatable pointers needed — persistence
is an explicit checkpoint file, mirroring fd_wksp_checkpt/restore,
src/util/wksp/fd_wksp.h:967-1008).

Keys are bytes (account addresses); values are bytes; xids are opaque
hashables (slot numbers, (slot, hash) pairs, ...).
"""

import pickle

_TOMBSTONE = object()


class FunkTxnError(RuntimeError):
    pass


class _Txn:
    __slots__ = ("xid", "parent", "children", "delta", "frozen")

    def __init__(self, xid, parent):
        self.xid = xid
        self.parent = parent          # _Txn | None (None = child of root)
        self.children: list = []
        self.delta: dict = {}         # key -> bytes | _TOMBSTONE
        self.frozen = False           # has in-preparation children


class Funk:
    def __init__(self):
        self._root: dict = {}                # published key -> val
        self._txns: dict = {}                # xid -> _Txn
        self._root_children: list[_Txn] = []

    # ---------------------------------------------------------------- txns
    def txn_prepare(self, xid, parent_xid=None):
        """Open an in-preparation transaction forking off `parent_xid`
        (None = the last published root).  A parent with a child is frozen:
        no further writes (fd_funk.h: only leaves are writable)."""
        if xid in self._txns:
            raise FunkTxnError(f"xid {xid!r} already in preparation")
        parent = None
        if parent_xid is not None:
            parent = self._txns.get(parent_xid)
            if parent is None:
                raise FunkTxnError(f"parent {parent_xid!r} not in preparation")
        t = _Txn(xid, parent)
        self._txns[xid] = t
        if parent is None:
            self._root_children.append(t)
        else:
            parent.children.append(t)
            parent.frozen = True
        return xid

    def txn_cancel(self, xid):
        """Discard a transaction and its whole subtree."""
        t = self._txns.get(xid)
        if t is None:
            raise FunkTxnError(f"xid {xid!r} not in preparation")
        self._drop_subtree(t)
        if t.parent is None:
            self._root_children.remove(t)
        else:
            t.parent.children.remove(t)
            if not t.parent.children:
                t.parent.frozen = False

    def _drop_subtree(self, t: _Txn):
        stack = [t]
        while stack:  # iterative: fork chains can exceed recursion depth
            n = stack.pop()
            stack.extend(n.children)
            del self._txns[n.xid]

    def txn_publish(self, xid) -> int:
        """Make `xid` the new root: fold every ancestor delta (oldest first)
        then its own into the root table, cancel all competing forks, and
        re-parent xid's children onto the root.  Returns published txn count
        (the reference's O(1) pointer swing becomes O(delta) folding — the
        honest cost model for a dict-backed table)."""
        t = self._txns.get(xid)
        if t is None:
            raise FunkTxnError(f"xid {xid!r} not in preparation")
        chain = []
        cur = t
        while cur is not None:
            chain.append(cur)
            cur = cur.parent
        chain.reverse()  # root-most first
        # fold deltas into the root table
        for txn in chain:
            for k, v in txn.delta.items():
                if v is _TOMBSTONE:
                    self._root.pop(k, None)
                else:
                    self._root[k] = v
        # prune competing forks: every root child not on the chain dies
        chain_set = {c.xid for c in chain}
        for rc in list(self._root_children):
            if rc.xid not in chain_set:
                self._drop_subtree(rc)
                self._root_children.remove(rc)
        # drop the chain itself; survivors are xid's children, now root kids
        for txn in chain:
            for c in list(txn.children):
                if c.xid not in chain_set:
                    if txn is not t:
                        # sibling fork hanging off an interior ancestor: dies
                        self._drop_subtree(c)
                    else:
                        c.parent = None
            del self._txns[txn.xid]
        self._root_children = [c for c in t.children]
        for c in self._root_children:
            c.parent = None
        return len(chain)

    def txn_is_prepared(self, xid) -> bool:
        return xid in self._txns

    # --------------------------------------------------------------- recs
    def write(self, xid, key: bytes, val: bytes):
        """Write a record in txn `xid` (None = directly to the root —
        allowed only with no forks in flight, like the reference's root
        modify restriction)."""
        if xid is None:
            if self._txns:
                raise FunkTxnError("cannot write root with txns in flight")
            self._root[key] = val
            return
        t = self._txns.get(xid)
        if t is None:
            raise FunkTxnError(f"xid {xid!r} not in preparation")
        if t.frozen:
            raise FunkTxnError(f"xid {xid!r} is frozen (has children)")
        t.delta[key] = val

    def remove(self, xid, key: bytes):
        if xid is None:
            if self._txns:
                raise FunkTxnError("cannot write root with txns in flight")
            self._root.pop(key, None)
            return
        t = self._txns.get(xid)
        if t is None:
            raise FunkTxnError(f"xid {xid!r} not in preparation")
        if t.frozen:
            raise FunkTxnError(f"xid {xid!r} is frozen (has children)")
        t.delta[key] = _TOMBSTONE

    def read(self, xid, key: bytes):
        """Resolve `key` as seen from fork `xid` (None = root view):
        nearest delta on the ancestry chain wins (fd_funk_rec_query_global)."""
        if xid is not None:
            t = self._txns.get(xid)
            if t is None:
                raise FunkTxnError(f"xid {xid!r} not in preparation")
            while t is not None:
                if key in t.delta:
                    v = t.delta[key]
                    return None if v is _TOMBSTONE else v
                t = t.parent
        return self._root.get(key)

    def keys(self, xid=None):
        """All live keys as seen from fork `xid` (root view by default)."""
        dead, out = set(), {}
        chain = []
        if xid is not None:
            t = self._txns.get(xid)
            if t is None:
                raise FunkTxnError(f"xid {xid!r} not in preparation")
            while t is not None:
                chain.append(t)
                t = t.parent
        for t in chain:  # leaf-most first: nearest delta wins
            for k, v in t.delta.items():
                if k in out or k in dead:
                    continue
                if v is _TOMBSTONE:
                    dead.add(k)
                else:
                    out[k] = v
        for k, v in self._root.items():
            if k not in out and k not in dead:
                out[k] = v
        return out

    @property
    def record_cnt(self) -> int:
        return len(self._root)

    # -------------------------------------------------- checkpoint/restore
    def checkpoint(self, path: str):
        """Persist the PUBLISHED state (in-preparation forks are by
        definition speculative and excluded, like wksp checkpt of a funk
        that has been published)."""
        with open(path, "wb") as f:
            pickle.dump({"version": 1, "root": self._root}, f)

    @classmethod
    def restore(cls, path: str) -> "Funk":
        with open(path, "rb") as f:
            d = pickle.load(f)
        if d.get("version") != 1:
            raise ValueError(f"bad funk checkpoint version {d.get('version')}")
        fk = cls()
        fk._root = d["root"]
        return fk
