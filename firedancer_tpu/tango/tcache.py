"""Recently-seen-tag dedup cache (the reference's fd_tcache,
src/tango/tcache/fd_tcache.c): a fixed-depth ring of 64-bit tags plus a
membership map.  Inserting into a full cache evicts the oldest tag; zero is
reserved as the null tag (the reference maps real zero tags to a sentinel —
we keep that contract so a zero tag is never cached).
"""


class TCache:
    def __init__(self, depth: int):
        if depth < 1:
            raise ValueError("tcache depth must be >= 1")
        self.depth = depth
        self._ring: list[int] = [0] * depth
        self._next = 0
        self._set: set[int] = set()

    def query(self, tag: int) -> bool:
        """True if tag was seen within the last `depth` distinct inserts."""
        return tag != 0 and tag in self._set

    def query_batch(self, tags):
        """Bool mask of which tags are in the window (no insert)."""
        import numpy as np
        return np.array([self.query(int(t)) for t in tags], dtype=bool)

    def insert(self, tag: int) -> bool:
        """Insert tag; returns True if it was a DUPLICATE (already present).
        The query+insert pair is the reference's FD_TCACHE_INSERT macro."""
        if tag == 0:
            return False
        if tag in self._set:
            return True
        old = self._ring[self._next]
        if old != 0:
            self._set.discard(old)
        self._ring[self._next] = tag
        self._next = (self._next + 1) % self.depth
        self._set.add(tag)
        return False

    def reset(self):
        self._ring = [0] * self.depth
        self._next = 0
        self._set.clear()


class NativeTCache:
    """Same contract backed by the C++ tcache (native/txnparse.cpp): the
    burst parse path queries it inline from C, so the verify pipeline's
    dedup window must live native-side.  API-compatible with TCache."""

    def __init__(self, depth: int):
        if depth < 1:
            raise ValueError("tcache depth must be >= 1")
        from .. import native
        self._L = native.lib()
        self.depth = depth
        self._h = self._L.fd_tcache_new(depth)

    @property
    def handle(self):
        """Opaque pointer for native callers (fd_txn_parse_batch)."""
        return self._h

    def query(self, tag: int) -> bool:
        return bool(self._L.fd_tcache_query(self._h, tag))

    def insert(self, tag: int) -> bool:
        if self._L.fd_tcache_query(self._h, tag):
            return True
        self._L.fd_tcache_insert(self._h, tag)
        return False

    def insert_batch(self, tags) -> None:
        """Bulk insert of a uint64 numpy array (one ctypes crossing)."""
        import ctypes

        import numpy as np
        tags = np.ascontiguousarray(tags, dtype=np.uint64)
        self._L.fd_tcache_insert_batch(
            self._h, tags.ctypes.data_as(ctypes.c_void_p), len(tags))

    def query_batch(self, tags):
        """Bulk query (no insert): bool mask, True where the tag is in the
        window.  One ctypes crossing; the packed-wire verify tile uses this
        to pre-filter device rows before dispatch."""
        import ctypes

        import numpy as np
        tags = np.ascontiguousarray(tags, dtype=np.uint64)
        hit = np.empty(len(tags), dtype=np.uint8)
        self._L.fd_tcache_query_batch(
            self._h, tags.ctypes.data_as(ctypes.c_void_p), len(tags),
            hit.ctypes.data_as(ctypes.c_void_p))
        return hit.astype(bool)

    def insert_batch_dedup(self, tags):
        """Bulk FD_TCACHE_INSERT: returns a bool mask, True where the tag
        was already present (dup) — including earlier indices of this same
        batch; non-dups are inserted."""
        import ctypes

        import numpy as np
        tags = np.ascontiguousarray(tags, dtype=np.uint64)
        dup = np.empty(len(tags), dtype=np.uint8)
        self._L.fd_tcache_insert_batch_dedup(
            self._h, tags.ctypes.data_as(ctypes.c_void_p), len(tags),
            dup.ctypes.data_as(ctypes.c_void_p))
        return dup.astype(bool)

    def reset(self):
        self._L.fd_tcache_delete(self._h)
        self._h = self._L.fd_tcache_new(self.depth)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            try:
                self._L.fd_tcache_delete(h)
            except Exception:
                pass
            self._h = None


class ShardedTCache:
    """Sig-prefix sharded dedup cache (fleet tier, round 17).

    The 64-bit tag space splits into `1 << shard_bits` shards by the
    tag's TOP bits — the same prefix the fleet steering ring
    (waltz.pkteng.SteerRing.shard_owner) assigns to hosts, so shard
    ownership follows peer steering.  Each shard is an independent
    tcache ring (native when available) sized depth >> shard_bits, so
    one hot shard can't evict another shard's window — and a shard
    handed over in failover can be reset/preloaded alone.

    `owned` marks the shards this host owns per the ring; inserts that
    land on a foreign shard still dedup (fail-safe: a mis-steered txn
    must never double-verdict) but are counted in `foreign_cnt` — the
    steering-quality signal `fleet top` surfaces.  Each shard also
    keeps a bounded ring of its most recent unique tags
    (`recent(shard)`) — the export surface the gossip sig-digest
    publisher reads.
    """

    RECENT = 1024

    def __init__(self, depth: int, shard_bits: int = 4, owned=None,
                 native: bool = True):
        if not 0 <= int(shard_bits) <= 16:
            raise ValueError("shard_bits must be in [0, 16]")
        self.shard_bits = int(shard_bits)
        self.nshards = 1 << self.shard_bits
        per = max(16, int(depth) // self.nshards)
        self.depth = per * self.nshards
        self._shards = []
        for _ in range(self.nshards):
            t = None
            if native:
                try:
                    t = NativeTCache(per)
                except Exception:
                    t = None
            self._shards.append(t if t is not None else TCache(per))
        self.owned = (set(range(self.nshards)) if owned is None
                      else {int(s) for s in owned})
        self.foreign_cnt = 0
        self._recent = [[] for _ in range(self.nshards)]

    def shard_of(self, tag: int) -> int:
        return (int(tag) >> (64 - self.shard_bits)) if self.shard_bits \
            else 0

    def set_owned(self, owned):
        """Re-own shards after a steering-ring change (host loss/join)."""
        self.owned = {int(s) for s in owned}

    def insert(self, tag: int) -> bool:
        tag = int(tag)
        s = self.shard_of(tag)
        if s not in self.owned:
            self.foreign_cnt += 1
        dup = self._shards[s].insert(tag)
        if not dup and tag:
            r = self._recent[s]
            r.append(tag)
            if len(r) > self.RECENT:
                del r[: len(r) - self.RECENT]
        return dup

    def query(self, tag: int) -> bool:
        return self._shards[self.shard_of(int(tag))].query(int(tag))

    def insert_batch_dedup(self, tags):
        """Bulk insert+dedup mask, routed per shard in one pass each."""
        import numpy as np
        tags = np.ascontiguousarray(tags, dtype=np.uint64)
        dup = np.zeros(len(tags), dtype=bool)
        if not len(tags):
            return dup
        if self.shard_bits == 0:
            sh = np.zeros(len(tags), dtype=np.int64)
        else:
            sh = (tags >> np.uint64(64 - self.shard_bits)).astype(np.int64)
        for s in np.unique(sh):
            idx = np.nonzero(sh == s)[0]
            t = self._shards[int(s)]
            sub = tags[idx]
            if hasattr(t, "insert_batch_dedup"):
                d = t.insert_batch_dedup(sub)
            else:
                d = np.array([t.insert(int(x)) for x in sub], dtype=bool)
            dup[idx] = d
            if int(s) not in self.owned:
                self.foreign_cnt += len(idx)
            fresh = sub[~d]
            if len(fresh):
                r = self._recent[int(s)]
                r.extend(int(x) for x in fresh if x)
                if len(r) > self.RECENT:
                    del r[: len(r) - self.RECENT]
        return dup

    def recent(self, shard: int) -> list[int]:
        """Most recent unique tags inserted into `shard` (bounded)."""
        return list(self._recent[int(shard)])

    def reset_shard(self, shard: int):
        self._shards[int(shard)].reset()
        self._recent[int(shard)] = []

    def reset(self):
        for s in range(self.nshards):
            self.reset_shard(s)
        self.foreign_cnt = 0
