"""Recently-seen-tag dedup cache (the reference's fd_tcache,
src/tango/tcache/fd_tcache.c): a fixed-depth ring of 64-bit tags plus a
membership map.  Inserting into a full cache evicts the oldest tag; zero is
reserved as the null tag (the reference maps real zero tags to a sentinel —
we keep that contract so a zero tag is never cached).
"""


class TCache:
    def __init__(self, depth: int):
        if depth < 1:
            raise ValueError("tcache depth must be >= 1")
        self.depth = depth
        self._ring: list[int] = [0] * depth
        self._next = 0
        self._set: set[int] = set()

    def query(self, tag: int) -> bool:
        """True if tag was seen within the last `depth` distinct inserts."""
        return tag != 0 and tag in self._set

    def insert(self, tag: int) -> bool:
        """Insert tag; returns True if it was a DUPLICATE (already present).
        The query+insert pair is the reference's FD_TCACHE_INSERT macro."""
        if tag == 0:
            return False
        if tag in self._set:
            return True
        old = self._ring[self._next]
        if old != 0:
            self._set.discard(old)
        self._ring[self._next] = tag
        self._next = (self._next + 1) % self.depth
        self._set.add(tag)
        return False

    def reset(self):
        self._ring = [0] * self.depth
        self._next = 0
        self._set.clear()
