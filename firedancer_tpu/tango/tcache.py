"""Recently-seen-tag dedup cache (the reference's fd_tcache,
src/tango/tcache/fd_tcache.c): a fixed-depth ring of 64-bit tags plus a
membership map.  Inserting into a full cache evicts the oldest tag; zero is
reserved as the null tag (the reference maps real zero tags to a sentinel —
we keep that contract so a zero tag is never cached).
"""


class TCache:
    def __init__(self, depth: int):
        if depth < 1:
            raise ValueError("tcache depth must be >= 1")
        self.depth = depth
        self._ring: list[int] = [0] * depth
        self._next = 0
        self._set: set[int] = set()

    def query(self, tag: int) -> bool:
        """True if tag was seen within the last `depth` distinct inserts."""
        return tag != 0 and tag in self._set

    def query_batch(self, tags):
        """Bool mask of which tags are in the window (no insert)."""
        import numpy as np
        return np.array([self.query(int(t)) for t in tags], dtype=bool)

    def insert(self, tag: int) -> bool:
        """Insert tag; returns True if it was a DUPLICATE (already present).
        The query+insert pair is the reference's FD_TCACHE_INSERT macro."""
        if tag == 0:
            return False
        if tag in self._set:
            return True
        old = self._ring[self._next]
        if old != 0:
            self._set.discard(old)
        self._ring[self._next] = tag
        self._next = (self._next + 1) % self.depth
        self._set.add(tag)
        return False

    def reset(self):
        self._ring = [0] * self.depth
        self._next = 0
        self._set.clear()


class NativeTCache:
    """Same contract backed by the C++ tcache (native/txnparse.cpp): the
    burst parse path queries it inline from C, so the verify pipeline's
    dedup window must live native-side.  API-compatible with TCache."""

    def __init__(self, depth: int):
        if depth < 1:
            raise ValueError("tcache depth must be >= 1")
        from .. import native
        self._L = native.lib()
        self.depth = depth
        self._h = self._L.fd_tcache_new(depth)

    @property
    def handle(self):
        """Opaque pointer for native callers (fd_txn_parse_batch)."""
        return self._h

    def query(self, tag: int) -> bool:
        return bool(self._L.fd_tcache_query(self._h, tag))

    def insert(self, tag: int) -> bool:
        if self._L.fd_tcache_query(self._h, tag):
            return True
        self._L.fd_tcache_insert(self._h, tag)
        return False

    def insert_batch(self, tags) -> None:
        """Bulk insert of a uint64 numpy array (one ctypes crossing)."""
        import ctypes

        import numpy as np
        tags = np.ascontiguousarray(tags, dtype=np.uint64)
        self._L.fd_tcache_insert_batch(
            self._h, tags.ctypes.data_as(ctypes.c_void_p), len(tags))

    def query_batch(self, tags):
        """Bulk query (no insert): bool mask, True where the tag is in the
        window.  One ctypes crossing; the packed-wire verify tile uses this
        to pre-filter device rows before dispatch."""
        import ctypes

        import numpy as np
        tags = np.ascontiguousarray(tags, dtype=np.uint64)
        hit = np.empty(len(tags), dtype=np.uint8)
        self._L.fd_tcache_query_batch(
            self._h, tags.ctypes.data_as(ctypes.c_void_p), len(tags),
            hit.ctypes.data_as(ctypes.c_void_p))
        return hit.astype(bool)

    def insert_batch_dedup(self, tags):
        """Bulk FD_TCACHE_INSERT: returns a bool mask, True where the tag
        was already present (dup) — including earlier indices of this same
        batch; non-dups are inserted."""
        import ctypes

        import numpy as np
        tags = np.ascontiguousarray(tags, dtype=np.uint64)
        dup = np.empty(len(tags), dtype=np.uint8)
        self._L.fd_tcache_insert_batch_dedup(
            self._h, tags.ctypes.data_as(ctypes.c_void_p), len(tags),
            dup.ctypes.data_as(ctypes.c_void_p))
        return dup.astype(bool)

    def reset(self):
        self._L.fd_tcache_delete(self._h)
        self._h = self._L.fd_tcache_new(self.depth)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            try:
                self._L.fd_tcache_delete(h)
            except Exception:
                pass
            self._h = None
