"""Credit-based flow controller (ref: src/tango/fctl/fd_fctl.c).

A producer publishing into an mcache has cr_max credits (the ring depth);
each RELIABLE consumer advertises its progress through an fseq, and the
producer's available credit is the minimum over consumers of

    cr_max - (seq_produced - seq_consumer_seen)

i.e. it may run at most cr_max frags ahead of the slowest reliable
consumer.  Credits are only refreshed during housekeeping (reading N
consumer cachelines per frag would defeat the point); between refreshes
the producer decrements a local counter.  The controller also charges a
`slow` diagnostic to the consumer that set the minimum when the producer
is backpressured — how the reference's monitor attributes stalls
(src/tango/fctl/fd_fctl.h receiver diag).
"""

import time

from dataclasses import dataclass


@dataclass
class _Rx:
    fseq: object  # anything with .query() -> int and .diag_add(idx, delta)
    slow_diag_idx: int | None = None


class Fctl:
    """Producer-side credit controller over reliable receivers."""

    # matches FSeq.DIAG_SLOW_CNT in tango/ring.py (tango.cpp layout)
    DIAG_SLOW_CNT = 6

    def __init__(self, cr_max: int, cr_resume: int | None = None,
                 cr_refill: int | None = None):
        """cr_max: max credits (<= mcache depth).  cr_resume: credits at
        which a backpressured producer resumes (default 2/3 cr_max);
        cr_refill: min credits below which housekeeping tries a refresh
        (default cr_max/2)."""
        if cr_max < 1:
            raise ValueError("cr_max must be >= 1")
        self.cr_max = cr_max
        self.cr_resume = cr_resume or max(1, (2 * cr_max) // 3)
        self.cr_refill = cr_refill or max(1, cr_max // 2)
        self._rx: list[_Rx] = []
        self.cr_avail = cr_max
        self.in_backp = False
        self.backp_cnt = 0       # backpressure entries
        self.backp_exit_cnt = 0  # backpressure exits (resumes)
        self.stall_ns = 0        # total ns spent in backpressure
        self._backp_t0 = 0

    def rx_add(self, fseq, slow_diag_idx: int | None = DIAG_SLOW_CNT) -> "Fctl":
        self._rx.append(_Rx(fseq, slow_diag_idx))
        return self

    def rx_evict(self, fseq) -> bool:
        """Drop a receiver from credit control (its tile is gone and will
        not be respawned): the producer stops waiting on its line entirely.
        Returns True if the receiver was registered."""
        for rx in self._rx:
            if rx.fseq is fseq:
                self._rx.remove(rx)
                return True
        return False

    @staticmethod
    def evict_dead_consumer(fseq, mcache) -> int:
        """Dead-consumer credit eviction: fast-forward the corpse's fseq to
        the producer cursor so `cr_max - (seq - seen)` refills.

        This is the supervisor-side half of tile respawn — frags published
        while the consumer is down are acked on its behalf (and lost to
        it), which is exactly the reference's unreliable-consumer overrun
        semantics applied for the duration of the outage.  The respawned
        tile resumes from the evicted cursor, so no frag is ever processed
        twice.  Returns the cursor written."""
        cur = mcache.seq_query()
        reset = getattr(fseq, "reset", None) or fseq.update
        reset(cur)
        return cur

    @property
    def rx_cnt(self) -> int:
        return len(self._rx)

    def cr_query(self, seq_produced: int) -> int:
        """Recompute available credits from every receiver's fseq; charges
        the slow diag to the limiting receiver if the producer is starved
        (< cr_resume while in backpressure)."""
        cr = self.cr_max
        slowest = None
        for rx in self._rx:
            seen = rx.fseq.query()
            avail = self.cr_max - ((seq_produced - seen) & ((1 << 64) - 1))
            if avail < cr:
                cr = avail
                slowest = rx
        cr = max(0, cr)
        if self.in_backp and cr < self.cr_resume and slowest is not None \
                and slowest.slow_diag_idx is not None:
            slowest.fseq.diag_add(slowest.slow_diag_idx)
        return cr

    def tx_cr_update(self, seq_produced: int) -> int:
        """Housekeeping-time credit refresh (fd_fctl_tx_cr_update): refill
        cr_avail when it has drained below cr_refill, applying resume
        hysteresis when backpressured."""
        if self.cr_avail < self.cr_refill or self.in_backp:
            cr = self.cr_query(seq_produced)
            if self.in_backp:
                if cr >= self.cr_resume:
                    self.in_backp = False
                    self.backp_exit_cnt += 1
                    self.stall_ns += time.monotonic_ns() - self._backp_t0
                    self.cr_avail = cr
            else:
                self.cr_avail = cr
        return self.cr_avail

    def consume(self, n: int = 1) -> bool:
        """Spend credits for n publishes; returns False (and enters
        backpressure) if there aren't enough."""
        if self.cr_avail < n:
            if not self.in_backp:
                self.in_backp = True
                self.backp_cnt += 1
                self._backp_t0 = time.monotonic_ns()
            return False
        self.cr_avail -= n
        return True
