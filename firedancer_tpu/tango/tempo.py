"""Clock calibration + housekeeping pacing (ref: src/tango/tempo/
fd_tempo.c — fd_tempo.h:10-32 wallclock/tickcount models,
fd_tempo.h:102-151 lazy housekeeping defaults).

The run loop needs two clocks (cheap ticks for pacing, wallclock for
heartbeats/metrics) plus a policy for how often to do housekeeping: often
enough that flow-control credits and heartbeats stay fresh, rarely enough
that the hot loop isn't paying for it.  The async_* helpers randomize the
interval so thousands of tiles don't housekeep in lockstep (the
reference's explicit design point: synchronized housekeeping turns into
periodic system-wide latency spikes).
"""

import random
import time

# ---------------------------------------------------------------------- clocks


def wallclock() -> int:
    """ns since epoch (fd_log_wallclock model)."""
    return time.time_ns()


def tickcount() -> int:
    """Monotonic tick counter in ns units (fd_tickcount model; CPython has
    no rdtsc, perf_counter_ns is the invariant-rate equivalent)."""
    return time.perf_counter_ns()


_tick_per_ns_cache: float | None = None


def tick_per_ns(recal: bool = False) -> float:
    """Observed tickcount rate per wallclock ns (fd_tempo_tick_per_ns):
    measured once over a short joint observation and cached.  With
    perf_counter_ns both clocks are ns-scaled so this is ~1.0, but callers
    are written against the model, not the constant."""
    global _tick_per_ns_cache
    if _tick_per_ns_cache is None or recal:
        w0, t0 = time.time_ns(), time.perf_counter_ns()
        time.sleep(0.002)
        w1, t1 = time.time_ns(), time.perf_counter_ns()
        _tick_per_ns_cache = (t1 - t0) / max(1, (w1 - w0))
    return _tick_per_ns_cache


# ------------------------------------------------------------------ lazy model


def lazy_default(cr_max: int) -> int:
    """Default housekeeping interval in ns for a link with cr_max credits
    (fd_tempo_lazy_default semantics): assume a worst-case ~1 frag/ns burst
    drain is absurd, so pace housekeeping such that a consumer publishing
    its progress every interval can never be overrun within one interval at
    ~10 Gbps-class frag rates.  Clamped to [1ms, 100ms] — the reference's
    practical envelope."""
    ns = (cr_max * 1000) // 18  # ~18 frags/us sustained worst case
    return max(1_000_000, min(100_000_000, ns))


def async_min(lazy: int, event_cnt: int, _tick_per_ns: float | None = None) -> int:
    """Largest power of two <= lazy/(1.5*event_cnt) ticks: with event_cnt
    round-robin housekeeping events per cycle, each individual event recurs
    roughly every `lazy` ns on average once async_reload jitter is applied
    (fd_tempo_async_min contract)."""
    t = (_tick_per_ns or tick_per_ns()) * lazy / (1.5 * max(1, event_cnt))
    t = max(1, int(t))
    return 1 << (t.bit_length() - 1)


def async_reload(rng: random.Random | None, amin: int) -> int:
    """Next housekeeping delay: uniform in [amin, 2*amin) ticks —
    decorrelates tiles (fd_tempo_async_reload)."""
    r = rng.getrandbits(30) if rng is not None else random.getrandbits(30)
    return amin + (r & (amin - 1)) if amin > 1 else 1
