"""Bounded LRU list + map (ref: src/tango/lru/fd_lru.c — the
doubly-linked-list-with-map used for QUIC conn reuse and similar
most-recently-used working sets).

Python's dict is insertion-ordered, which gives the same O(1)
tail-evict/move-to-front contract without hand-rolling links; the API
mirrors the reference's upsert semantics: insert returns the evicted
(key, value) when the list is full, touch refreshes recency.
"""


class Lru:
    def __init__(self, depth: int):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self.depth = depth
        self._d: dict = {}

    def __len__(self) -> int:
        return len(self._d)

    def __contains__(self, key) -> bool:
        return key in self._d

    def get(self, key, default=None):
        """Lookup WITHOUT touching recency (fd_lru query)."""
        return self._d.get(key, default)

    def touch(self, key) -> bool:
        """Move to most-recently-used; False if absent."""
        try:
            self._d[key] = self._d.pop(key)
            return True
        except KeyError:
            return False

    def upsert(self, key, value=None):
        """Insert or refresh `key`; returns the evicted (key, value) pair
        when a cold entry fell off the tail, else None (fd_lru_upsert)."""
        if key in self._d:
            self._d.pop(key)
            self._d[key] = value
            return None
        self._d[key] = value
        if len(self._d) > self.depth:
            old_key = next(iter(self._d))
            return old_key, self._d.pop(old_key)
        return None

    def remove(self, key) -> bool:
        return self._d.pop(key, _MISSING) is not _MISSING

    def oldest(self):
        """(key, value) of the LRU entry, else None."""
        for k in self._d:
            return k, self._d[k]
        return None


_MISSING = object()
