"""Python face of the native tango fabric (firedancer_tpu/native/tango.cpp).

Workspace = named shared memory (the reference's hugepage wksp,
src/util/wksp/) with a deterministic bump allocator: every process that
builds the same topology computes the same offsets, so no directory needs
serializing — the same trick the reference plays by materializing the
topology identically in each tile process (src/disco/topo/fd_topo.c).

MCache / Dcache / FSeq / Cnc wrap caller-owned byte ranges; all the
concurrency-sensitive code is in C++ (see tango.cpp for the seqlock
contract).  Hot consumers drain bursts through one ctypes call into a
numpy structured array.
"""

from multiprocessing import shared_memory
import ctypes

import numpy as np

from .. import native

FRAG_META_DTYPE = np.dtype(
    [
        ("seq", "<u8"),
        ("sig", "<u8"),
        ("chunk", "<u4"),
        ("sz", "<u2"),
        ("ctl", "<u2"),
        ("tsorig", "<u4"),
        ("tspub", "<u4"),
    ]
)
assert FRAG_META_DTYPE.itemsize == 32

# ctl bits (fd_tango_base.h:76-99): ctl = origin<<3 | SOM<<2 | EOM<<1 | ERR
CTL_SOM = 1 << 2
CTL_EOM = 1 << 1
CTL_ERR = 1 << 0


def ctl(origin: int = 0, som: bool = True, eom: bool = True, err: bool = False) -> int:
    return (origin << 3) | (CTL_SOM if som else 0) | (CTL_EOM if eom else 0) | (
        CTL_ERR if err else 0
    )


PACKED_ROW_EXTRA = 100  # sig 64 + pub 32 + len-le32 4 (ops/ed25519.py blob row)


def packed_row_ml(maxlen: int, chunk_sz: int = 64) -> int:
    """Message width `ml` such that the packed-blob row stride (ml +
    PACKED_ROW_EXTRA) is a multiple of the dcache chunk size.  With this
    ml, a dcache region written row-by-row IS a valid (n, ml+100) device
    blob: rows start on chunk boundaries, stride == row width exactly, so
    `dispatch_blob` can infer maxlen and AOT executables see stable shapes.
    """
    if maxlen <= 0:
        raise ValueError("maxlen must be positive")
    stride = -(-(maxlen + PACKED_ROW_EXTRA) // chunk_sz) * chunk_sz
    return stride - PACKED_ROW_EXTRA


class Workspace:
    """Named shared-memory region with a deterministic bump allocator."""

    ALIGN = 64

    def __init__(self, name: str, size: int, create: bool = False):
        self.name = name
        self.shm = shared_memory.SharedMemory(
            name=name, create=create, size=size if create else 0
        )
        self.created = create
        self._top = 0

    @property
    def buf(self) -> memoryview:
        return self.shm.buf

    def alloc(self, footprint: int, align: int = ALIGN) -> int:
        """Bump-allocate; returns byte offset.  Deterministic: identical
        alloc sequences in different processes yield identical offsets."""
        off = (self._top + align - 1) & ~(align - 1)
        if off + footprint > len(self.shm.buf):
            raise MemoryError(
                f"workspace {self.name}: alloc {footprint} @ {off} exceeds "
                f"{len(self.shm.buf)}"
            )
        self._top = off + footprint
        return off

    def ptr(self, off: int = 0) -> ctypes.c_void_p:
        base = ctypes.addressof(ctypes.c_char.from_buffer(self.shm.buf))
        return ctypes.c_void_p(base + off)

    def close(self):
        self.shm.close()

    def unlink(self):
        try:
            self.shm.unlink()
        except FileNotFoundError:
            pass


class MCache:
    """Single-producer broadcast metadata ring (fd_mcache equivalent)."""

    def __init__(self, ws: Workspace, off: int, depth: int):
        self.ws = ws
        self.off = off
        self.depth = depth
        self._p = ws.ptr(off)
        self._L = native.lib()

    @classmethod
    def footprint(cls, depth: int) -> int:
        fp = native.lib().fd_mcache_footprint(depth)
        if not fp:
            raise ValueError(f"bad mcache depth {depth}")
        return fp

    @classmethod
    def new(cls, ws: Workspace, depth: int, seq0: int = 0) -> "MCache":
        off = ws.alloc(cls.footprint(depth))
        rc = native.lib().fd_mcache_new(ws.ptr(off), depth, seq0)
        if rc:
            raise ValueError("fd_mcache_new failed")
        return cls(ws, off, depth)

    @classmethod
    def join(cls, ws: Workspace, off: int) -> "MCache":
        depth = native.lib().fd_mcache_depth(ws.ptr(off))
        if not depth:
            raise ValueError("no mcache at offset")
        return cls(ws, off, depth)

    def seq0(self) -> int:
        return self._L.fd_mcache_seq0(self._p)

    def seq_query(self) -> int:
        return self._L.fd_mcache_seq_query(self._p)

    def publish(
        self,
        sig: int,
        chunk: int = 0,
        sz: int = 0,
        ctl_: int = CTL_SOM | CTL_EOM,
        tsorig: int = 0,
        tspub: int = 0,
    ) -> int:
        return self._L.fd_mcache_publish(
            self._p, sig, chunk, sz, ctl_, tsorig, tspub
        )

    def query(self, want: int):
        """Returns (rc, meta): rc 0 ok / -1 not yet / 1 overrun."""
        out = np.zeros(1, dtype=FRAG_META_DTYPE)
        rc = self._L.fd_mcache_query(
            self._p, want, out.ctypes.data_as(ctypes.c_void_p)
        )
        return rc, out[0]

    def consume_burst(self, want: int, max_frags: int):
        """Returns (metas, rc_after): metas is a structured array of the
        frags consumed starting at `want`; rc_after is the status of the
        first unconsumed slot (0 = burst full, -1 = caught up, 1 = overrun)."""
        out = np.zeros(max_frags, dtype=FRAG_META_DTYPE)
        n = ctypes.c_uint64(0)
        rc = self._L.fd_mcache_consume_burst(
            self._p,
            want,
            max_frags,
            out.ctypes.data_as(ctypes.c_void_p),
            ctypes.byref(n),
        )
        return out[: n.value], rc


class Dcache:
    """Chunk-addressed payload region with compact-ring allocation.

    Layout: [ 64B header (magic, mtu, data_sz, wmark) | data ].  The header
    makes join() self-describing so every process rebuilds the same view.
    Chunk indices are relative to the data area.
    """

    _HDR = 64
    _MAGIC = 0xFD7A6FDCAC4E0001

    def __init__(self, ws: Workspace, off: int):
        self.ws = ws
        self.off = off
        self.chunk_sz = native.lib().fd_dcache_chunk_sz()
        hdr = np.frombuffer(ws.buf, dtype=np.uint64, count=4, offset=off)
        if int(hdr[0]) != self._MAGIC:
            raise ValueError("no dcache at offset")
        self.mtu = int(hdr[1])
        self.data_sz = int(hdr[2])
        self.wmark = int(hdr[3])
        self.chunk0 = 0
        self._arr = np.frombuffer(
            ws.buf, dtype=np.uint8, count=self.data_sz, offset=off + self._HDR
        )

    @classmethod
    def footprint(cls, mtu: int, depth: int, burst: int = 1) -> int:
        return cls._HDR + native.lib().fd_dcache_req_data_sz(mtu, depth, burst)

    @classmethod
    def new(cls, ws: Workspace, mtu: int, depth: int, burst: int = 1) -> "Dcache":
        data_sz = native.lib().fd_dcache_req_data_sz(mtu, depth, burst)
        off = ws.alloc(cls._HDR + data_sz)
        chunk_sz = native.lib().fd_dcache_chunk_sz()
        hdr = np.frombuffer(ws.buf, dtype=np.uint64, count=4, offset=off)
        hdr[1] = mtu
        hdr[2] = data_sz
        hdr[3] = (data_sz - mtu) // chunk_sz  # last chunk an mtu write fits at
        hdr[0] = cls._MAGIC  # magic last: joiners see a complete header
        return cls(ws, off)

    @classmethod
    def join(cls, ws: Workspace, off: int) -> "Dcache":
        return cls(ws, off)

    def write(self, chunk: int, data: bytes) -> int:
        """Write payload at chunk; returns the next chunk (compact ring)."""
        start = chunk * self.chunk_sz
        self._arr[start : start + len(data)] = np.frombuffer(data, dtype=np.uint8)
        return native.lib().fd_dcache_compact_next(
            chunk, len(data), self.chunk0, self.wmark
        )

    def read(self, chunk: int, sz: int) -> bytes:
        start = chunk * self.chunk_sz
        return bytes(self._arr[start : start + sz])

    def view(self, chunk: int, sz: int) -> np.ndarray:
        """Zero-copy uint8 view of [chunk, chunk + sz bytes) over the shm.
        The view stays valid only until the producer laps the ring — pair
        any read through it with an mcache seq re-check afterwards."""
        start = chunk * self.chunk_sz
        if start + sz > self.data_sz:
            raise ValueError(
                f"dcache view [{start}, {start + sz}) exceeds data_sz "
                f"{self.data_sz}")
        return self._arr[start : start + sz]

    def rows(self, chunk: int, n: int, stride: int) -> np.ndarray:
        """Zero-copy (n, stride) row view starting at chunk: the packed-blob
        shape `dispatch_blob`/`parse_packed_bucket` consume directly.  The
        frag must not wrap the compact ring (guaranteed when the dcache mtu
        covers the whole frag, as fd_dcache_compact_next never splits an
        <= mtu write)."""
        return self.view(chunk, n * stride).reshape(n, stride)

    def write_view(self, chunk: int, sz: int) -> np.ndarray:
        """Writable zero-copy view for readinto-style producer fills.  The
        caller stamps payload bytes directly into shm, then advances with
        `advance(chunk, sz)` and publishes the frag meta — no staging bytes
        object ever materializes."""
        return self.view(chunk, sz)

    def advance(self, chunk: int, sz: int) -> int:
        """Next chunk after an sz-byte write at chunk (compact ring)."""
        return native.lib().fd_dcache_compact_next(
            chunk, sz, self.chunk0, self.wmark)

    def data_ptr(self) -> ctypes.c_void_p:
        """Base pointer of the data area (native burst rx/tx)."""
        return self.ws.ptr(self.off + self._HDR)


def rx_burst(mcache: "MCache", dcache: "Dcache", want: int, max_frags: int,
             buf: np.ndarray, metas: np.ndarray, offs: np.ndarray,
             rr_cnt: int = 1, rr_idx: int = 0):
    """Native burst consume (tango.cpp fd_ring_rx_burst): drain up to
    `max_frags` frags from `want`, seqlock-validated payload copy into
    `buf`, optional round-robin filter at the ring.  Caller provides the
    scratch arrays (reused across polls): buf uint8 (cap,), metas
    FRAG_META_DTYPE (max_frags,), offs int64 (max_frags+1,).

    Returns (rc, consumed, kept, filtered): rc is the status of the first
    unconsumed slot (0 = burst/buf full, -1 = caught up, 1 = overrun).
    Payload of kept frag i = buf[offs[i]:offs[i+1]]."""
    L = native.lib()
    vp = ctypes.c_void_p
    c_cons = ctypes.c_uint64(0)
    c_kept = ctypes.c_uint64(0)
    c_filt = ctypes.c_uint64(0)
    rc = L.fd_ring_rx_burst(
        mcache._p, dcache.data_ptr(), dcache.chunk_sz, want, max_frags,
        rr_cnt, rr_idx, metas.ctypes.data_as(vp),
        buf.ctypes.data_as(vp), buf.nbytes, offs.ctypes.data_as(vp),
        ctypes.byref(c_cons), ctypes.byref(c_kept), ctypes.byref(c_filt))
    return rc, c_cons.value, c_kept.value, c_filt.value


def tx_burst(mcache: "MCache", dcache: "Dcache", chunk: int,
             buf, starts: np.ndarray, lens: np.ndarray,
             sigs: np.ndarray, tsorig: int = 0,
             tspub: int = 0) -> tuple[int, int]:
    """Native burst publish (tango.cpp fd_ring_tx_burst): payload i =
    buf[starts[i]:starts[i]+lens[i]] with app sig sigs[i].  NO flow
    control — the caller must hold len(starts) credits.  tsorig is the
    span-chain origin stamp carried through from the consumed frag (0 =
    this burst originates the chain).  Returns (last_seq, next_chunk)."""
    L = native.lib()
    vp = ctypes.c_void_p
    n = len(starts)
    chunk_io = np.array([chunk], dtype=np.uint64)
    if isinstance(buf, (bytes, bytearray, memoryview)):
        # np.frombuffer is a zero-copy view (works for readonly buffers
        # too); the old ctypes.c_char_p(bytes(buf)) materialized a full
        # copy of the burst on every tx
        buf = np.frombuffer(buf, dtype=np.uint8)
    bp = buf.ctypes.data_as(vp)
    seq = L.fd_ring_tx_burst(
        mcache._p, dcache.data_ptr(), dcache.chunk_sz, dcache.chunk0,
        dcache.wmark, bp,
        np.ascontiguousarray(starts, np.int64).ctypes.data_as(vp),
        np.ascontiguousarray(lens, np.int32).ctypes.data_as(vp),
        np.ascontiguousarray(sigs, np.uint64).ctypes.data_as(vp),
        n, tsorig & 0xFFFFFFFF, tspub & 0xFFFFFFFF,
        chunk_io.ctypes.data_as(vp))
    return int(seq), int(chunk_io[0])


class FSeq:
    """Consumer->producer flow-control line (fd_fseq equivalent)."""

    # diag indices (see tango.cpp)
    DIAG_PUB_CNT, DIAG_PUB_SZ, DIAG_FILT_CNT, DIAG_FILT_SZ = 0, 1, 2, 3
    DIAG_OVRNP_CNT, DIAG_OVRNR_CNT, DIAG_SLOW_CNT = 4, 5, 6

    def __init__(self, ws: Workspace, off: int):
        self.ws = ws
        self.off = off
        self._p = ws.ptr(off)
        self._L = native.lib()

    @classmethod
    def new(cls, ws: Workspace, seq0: int = 0) -> "FSeq":
        off = ws.alloc(native.lib().fd_fseq_footprint())
        native.lib().fd_fseq_new(ws.ptr(off), seq0)
        return cls(ws, off)

    @classmethod
    def join(cls, ws: Workspace, off: int) -> "FSeq":
        return cls(ws, off)

    def update(self, seq: int):
        self._L.fd_fseq_update(self._p, seq)

    def reset(self, seq: int):
        """Supervisor-side eviction write: force the line to `seq`.

        Same store as update(), but named for the ONE legitimate writer
        besides the owning consumer — a supervisor fast-forwarding a dead
        consumer's line to the producer cursor so upstream credits unfreeze
        (fctl.Fctl.evict_dead_consumer).  A live consumer must never call
        this; a respawned one resumes FROM the value it finds here."""
        self._L.fd_fseq_update(self._p, seq)

    def query(self) -> int:
        return self._L.fd_fseq_query(self._p)

    def diag_add(self, idx: int, delta: int = 1):
        self._L.fd_fseq_diag_add(self._p, idx, delta)

    def diag(self, idx: int) -> int:
        return self._L.fd_fseq_diag_query(self._p, idx)


class Cnc:
    """Command-and-control line: signal + heartbeat (fd_cnc equivalent)."""

    SIGNAL_RUN, SIGNAL_BOOT, SIGNAL_FAIL, SIGNAL_HALT = 0, 1, 2, 3
    # drain protocol (graceful quiesce, supervisor-raised): DRAIN asks a
    # tile to stop admitting frags, run its in-flight work dry and park;
    # DRAINED is the tile's ack (it keeps heartbeating, parked, until the
    # supervisor raises HALT).  Values extend the fd_cnc signal space the
    # same way the reference reserves >FD_CNC_SIGNAL_FAIL for app signals
    # (fd_cnc.h: "user signals").
    SIGNAL_DRAIN, SIGNAL_DRAINED = 4, 5

    def __init__(self, ws: Workspace, off: int):
        self.ws = ws
        self.off = off
        self._p = ws.ptr(off)
        self._L = native.lib()

    @classmethod
    def new(cls, ws: Workspace) -> "Cnc":
        off = ws.alloc(native.lib().fd_cnc_footprint())
        native.lib().fd_cnc_new(ws.ptr(off))
        return cls(ws, off)

    @classmethod
    def join(cls, ws: Workspace, off: int) -> "Cnc":
        return cls(ws, off)

    def signal(self, sig: int):
        self._L.fd_cnc_signal(self._p, sig)

    def signal_query(self) -> int:
        return self._L.fd_cnc_signal_query(self._p)

    def heartbeat(self, now: int):
        self._L.fd_cnc_heartbeat(self._p, now)

    def heartbeat_query(self) -> int:
        return self._L.fd_cnc_heartbeat_query(self._p)
