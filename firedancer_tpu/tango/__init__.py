"""Host communication fabric (the reference's tango layer, src/tango/).

mcache/dcache ring + flow-control equivalents arrive with the C++ shm
module; the pure-host pieces (tcache dedup, tempo pacing) live here as
Python."""
