#!/bin/sh
# The per-commit CI gate (ref: .github/workflows/tests.yml:13-41 — the
# reference runs unit tests across a compiler/arch matrix per push; this
# repo's matrix is one Python + the virtual 8-device CPU mesh, so the gate
# is a single script: fast test tier, fuzz smoke, native build, bench
# dry-run wiring check).
#
# Usage:  sh tools/ci.sh            # fast gate (< ~5 min warm cache)
#         FDTPU_CI_FULL=1 sh tools/ci.sh   # + full suite (slow modules)
#
# Wire it as a pre-push hook:  ln -s ../../tools/ci.sh .git/hooks/pre-push

set -e
cd "$(dirname "$0")/.."
T0=$(date +%s)
tier() { echo "== $1 ($(($(date +%s) - T0))s elapsed) =="; }

tier "native build"
python -c "from firedancer_tpu import native; print(native.build())"

tier "metrics schema lint"
python - <<'EOF'
from firedancer_tpu.disco import metrics
metrics.lint_schema()
print("metrics schema ok:",
      len(metrics.MUX_SLOTS), "mux slots,",
      sum(len(metrics.slot_defs(k)) for k in metrics.TILE_SLOTS),
      "tile slots,", metrics.footprint(), "B/tile")
EOF

tier "observability smoke (monitor + trace + /metrics scrape, CPU)"
# a real file, not a heredoc: tile processes spawn by re-importing
# __main__ from its path, which stdin scripts do not have
JAX_PLATFORMS=cpu python tools/obs_smoke.py

tier "attribution smoke (per-link families + SLO table over wire topo, CPU)"
# bottleneck-attribution gate: a live quic_server -> verify -> dedup ->
# sink topology under loopback load must expose the producer->consumer
# link families on /metrics, the slo line on /healthz, and a non-empty
# stage-budget table off the span rings (real file: spawn)
JAX_PLATFORMS=cpu python tools/obs_smoke.py --wire

tier "bench diff (advisory + enforced host-path gate)"
# exit 3 = advisory (>5% run-over-run, human looks); exit 4 = ENFORCED
# (round 11: the host-path us/txn metrics regressed >10% — fatal on
# this CPU tier, someone re-introduced a per-txn hop on the hot path)
BD_RC=0; python tools/bench_diff.py || BD_RC=$?
if [ "$BD_RC" -ge 4 ]; then
    echo "bench diff: ENFORCED host-path regression (rc $BD_RC)"; exit "$BD_RC"
elif [ "$BD_RC" -ne 0 ]; then
    echo "bench diff flagged a regression (advisory, rc $BD_RC)"
fi

tier "fast test tier (prime-or-skip: cold caches defer graph modules)"
python -m pytest tests/ -q -m "not slow" -x

tier "fuzz smoke"
python -m pytest tests/test_fuzz_smoke.py -q -x || \
    python tools/fuzz_run.py --smoke 2>/dev/null || true

tier "ingest overlap smoke (double-buffered == serial, CPU)"
JAX_PLATFORMS=cpu python - <<'EOF'
# round-6 gate: the double-buffered ingest engine must produce verdicts
# BIT-IDENTICAL to serial packed dispatch on a fixed seed, across enough
# submissions that every rotating buffer is reused
import numpy as np
from firedancer_tpu.models.verifier import (
    SigVerifier, VerifierConfig, make_example_batch)
v = SigVerifier(VerifierConfig(batch=64, msg_maxlen=96))
batches = []
for seed, valid in ((1, True), (2, False)):
    args = [np.asarray(a) for a in make_example_batch(
        64, 96, valid=valid, sign_pool=8, seed=seed)]
    batches.append((args, np.asarray(v.packed_dispatch(*args, ml=96))))
assert batches[1][1].any() and not batches[1][1].all()  # mixed verdict
eng = v.make_ingest(ml=96, nbuf=3, depth=2)
got = []
for i in range(7):
    got += eng.submit(*batches[i % 2][0])
got += eng.drain()
assert len(got) == 7
for i, ok in enumerate(got):
    assert np.array_equal(ok, batches[i % 2][1]), f"verdict mismatch @{i}"
print("overlap smoke ok: 7 rotated dispatches bit-identical to serial,"
      f" max depth {eng.max_depth_seen}")
EOF

tier "divstep parity smoke (strict == antipa verdicts, zero re-compiles, CPU)"
JAX_PLATFORMS=cpu python - <<'EOF'
# round-10 gate: the antipa halved chain (in-kernel divstep) must render
# verdicts BIT-IDENTICAL to strict on a mixed small batch through the
# production SigVerifier, and steady-state redispatch on fresh data must
# land ZERO new XLA compiles — a data-dependent retrace anywhere in the
# divstep/Lagrange fori_loops would show here as a recompile
import numpy as np
from firedancer_tpu.utils import xla_cache
xla_cache.enable()
from firedancer_tpu.disco import trace
from firedancer_tpu.models.verifier import (
    SigVerifier, VerifierConfig, make_example_batch)
trace.install_jax_compile_listener()
msgs, lens, sigs, pubs = make_example_batch(16, 96, valid=True,
                                            sign_pool=4, seed=19)
sigs = np.asarray(sigs).copy()
sigs[2, 5] ^= 0xFF; sigs[7, 40] ^= 0x01; sigs[11, 63] |= 0x80
strict = SigVerifier(VerifierConfig(batch=16, msg_maxlen=96))
antipa = SigVerifier(VerifierConfig(batch=16, msg_maxlen=96),
                     mode="antipa")
ref = np.asarray(strict(msgs, lens, sigs, pubs))
got = np.asarray(antipa(msgs, lens, sigs, pubs))
assert ref.any() and not ref.all()            # mixed verdict
assert np.array_equal(ref, got), "antipa diverged from strict"
cnt0, _ = trace.compile_totals()
for seed in (23, 29):                         # fresh data, same shapes
    m2, l2, s2, p2 = make_example_batch(16, 96, valid=True,
                                        sign_pool=4, seed=seed)
    a = np.asarray(strict(m2, l2, s2, p2))
    b = np.asarray(antipa(m2, l2, s2, p2))
    assert bool(a.all()), "strict rejected a valid redispatch batch"
    assert np.array_equal(a, b), "antipa diverged on redispatch"
cnt1, _ = trace.compile_totals()
assert cnt1 == cnt0, f"steady-state redispatch compiled {cnt1 - cnt0}x"
print("divstep parity smoke ok: strict == antipa on a mixed batch, "
      f"0 steady-state compiles ({cnt0} warm)")
EOF

tier "shred recover smoke (batched == per-set bit-identity, zero re-compiles, CPU)"
JAX_PLATFORMS=cpu python - <<'EOF'
# round-13 gate: recover_batch over ragged erasure patterns must be
# BIT-IDENTICAL to the per-set host golden model, per-set failures
# (corrupt / unrecoverable) must stay isolated inside the batch, and
# steady-state redispatch at a fixed batch geometry must land ZERO new
# XLA compiles — a shape leak in the stacked recover path would show
# here as a recompile per erasure pattern
import numpy as np
from firedancer_tpu.utils import xla_cache
xla_cache.enable()
from firedancer_tpu.disco import trace
from firedancer_tpu.ballet import reedsol as rs
trace.install_jax_compile_listener()
rng = np.random.default_rng(99)
k, c, sz = 8, 8, 64
n = k + c
sets = []
for i in range(6):
    data = rng.integers(0, 256, (k, sz), dtype=np.uint8)
    full = [np.ascontiguousarray(r)
            for r in np.vstack([data, rs.encode(data, c, device=False)])]
    shreds = list(full)
    for e in range(i % (c - 1)):          # ragged patterns incl. all-data
        shreds[(2 * e + i) % n] = None
    sets.append((shreds, k, sz))
# poison set 3: corrupt a surviving UNUSED shred; starve set 4 entirely
bad = [np.array(s, copy=True) if s is not None else None
       for s in sets[3][0]]
bad[n - 1] = bad[n - 1] ^ np.uint8(1)
sets[3] = (bad, k, sz)
sets[4] = ([None] * (n - 2) + list(sets[4][0][n - 2:]), k, sz)
golden = rs.recover_batch(sets, device=False)
got = rs.recover_batch(sets)
for i, (g, w) in enumerate(zip(golden, got)):
    if isinstance(g, ValueError):
        # same failure CLASS (corrupt vs unrecoverable); the device batch
        # verdict can't name the offending shred index, so only the prefix
        # before the ':' is comparable
        assert isinstance(w, ValueError) and \
            str(g).split(":")[0] == str(w).split(":")[0], \
            f"set {i}: device {w!r} != host {g!r}"
        continue
    assert not isinstance(w, ValueError), f"set {i}: device raised {w!r}"
    assert all(np.array_equal(a, b) for a, b in zip(g, w)), \
        f"set {i}: batched recover != host golden model"
assert sum(isinstance(o, ValueError) for o in got) == 2
cnt0, _ = trace.compile_totals()
for seed in (7, 11):                      # fresh data, same batch geometry
    data = np.random.default_rng(seed).integers(
        0, 256, (k, sz), dtype=np.uint8)
    full = [np.ascontiguousarray(r)
            for r in np.vstack([data, rs.encode(data, c, device=False)])]
    dam = list(full); dam[0] = dam[5] = None
    out = rs.recover_batch([(dam, k, sz)] * 6)
    for o in out:
        assert not isinstance(o, ValueError)
        assert all(np.array_equal(a, b) for a, b in zip(o, full))
cnt1, _ = trace.compile_totals()
assert cnt1 == cnt0, f"steady-state redispatch compiled {cnt1 - cnt0}x"
ci = rs.recover_cache_info()
assert ci.hits > 0, ci                    # pattern LRU actually amortizes
print("shred recover smoke ok: 6 ragged sets bit-identical (2 isolated "
      f"failures), 0 steady-state compiles, cache {ci.hits}h/{ci.misses}m")
EOF

tier "leader smoke (full-slot pack -> device PoH bit-identity, zero re-compiles, CPU)"
JAX_PLATFORMS=cpu python - <<'EOF'
# round-14 gate: two full slots driven through the leader lane's stack —
# fee-priority pack microblocks, device-batched mixin trees, chained
# device PoH spans — must produce an entry chain BIT-IDENTICAL to the
# host hashlib golden (entry.verify_chain recomputes every mixin), the
# second slot must land ZERO new XLA compiles (pad shapes hold: the hot
# path never retraces on microblock count or txn width), and the stream
# must re-verify through the bucketed verify_entries ladder
import numpy as np
from firedancer_tpu.utils import xla_cache
xla_cache.enable()
from firedancer_tpu.disco import trace
from firedancer_tpu.ballet import entry as entry_lib, pack as pack_lib
from firedancer_tpu.ballet import poh as poh_lib, poh_engine as pe
from firedancer_tpu.ballet import txn as txn_lib
trace.install_jax_compile_listener()

HPT, TPS, MB_CAP, W = 8, 4, 3, 8   # hashes/tick, ticks/slot, mb/tick, pad
eng = pe.PohEngine(lanes=1, steps=MB_CAP + 1, max_hashes=HPT, unroll=4)
eng.warm()
entry_lib.warm_txn_mixins(batch=MB_CAP, max_width=W)

def mk(i):
    signer = bytes([1 + (i % 200), 1 + i // 200]) + bytes(30)
    msg = txn_lib.build_unsigned(
        [signer], b"\x11" * 32, [(1, bytes([0]), i.to_bytes(8, "little"))],
        extra_accounts=[b"\x07" * 32], readonly_unsigned_cnt=1)
    pay = txn_lib.assemble([b"\x5a" * 64], msg)
    return pay, txn_lib.parse(pay)

def run_slot(base, h):
    p = pack_lib.Pack(bank_tile_cnt=1, max_txn_per_microblock=4)
    for i in range(base, base + 9):
        assert p.insert(*mk(i))
    entries = []
    for tick in range(TPS):
        mbs = []
        while len(mbs) < MB_CAP:
            mb = p.schedule(0)
            if mb is None:
                break
            mbs.append(list(mb.payloads))
            p.done(0)
        j = len(mbs)
        if j:
            mix = entry_lib.txn_mixins_device(mbs, pad_batch=MB_CAP,
                                              pad_width=W)
            steps = [(1, bytes(mix[k])) for k in range(j)] \
                + [(HPT - j, None)]
        else:
            steps = [(HPT, None)]
        outs = [eng.split_verdict(v) for v in eng.submit_lanes([(h, steps)])]
        outs += [eng.split_verdict(v) for v in eng.drain()]
        planes = outs[0]
        for k in range(j):
            h = bytes(planes[0, k])
            entries.append(entry_lib.Entry(1, h, mbs[k]))
        h = bytes(planes[0, j])
        entries.append(entry_lib.Entry(HPT - j, h, []))
    assert p.pending == 0, f"{p.pending} txns never scheduled"
    return entries, h

seed = bytes(32)
e1, h1 = run_slot(0, seed)                      # slot 1: warm everything
cnt0, _ = trace.compile_totals()
e2, h2 = run_slot(100, h1)                      # slot 2: steady state
cnt1, _ = trace.compile_totals()
assert cnt1 == cnt0, f"steady-state slot compiled {cnt1 - cnt0}x"
chain = e1 + e2
assert any(not e.is_tick for e in chain)
assert entry_lib.verify_chain(seed, chain), "device chain != host golden"
n = len(chain)
starts = np.zeros((n, 32), np.uint8); nums = np.zeros((n,), np.int32)
mixins = np.zeros((n, 32), np.uint8); has = np.zeros((n,), np.bool_)
prev = seed
for i, e in enumerate(chain):
    starts[i] = np.frombuffer(prev, np.uint8); nums[i] = e.num_hashes
    if not e.is_tick:
        mixins[i] = np.frombuffer(entry_lib.txn_mixin(e.txns), np.uint8)
        has[i] = True
    prev = e.hash
got = np.asarray(poh_lib.verify_entries_fit(starts, nums, mixins, has,
                                            max_hashes=HPT))
assert all(bytes(got[i]) == chain[i].hash for i in range(n)), \
    "entry stream failed the device ladder re-verify"
print(f"leader smoke ok: 2 slots, {n} entries bit-identical to the host "
      f"chain, ladder re-verified, 0 steady-state compiles ({cnt0} warm)")
EOF

tier "leader speculation smoke (K-tick window + splice vs host rule, native pack identity, CPU)"
JAX_PLATFORMS=cpu python - <<'EOF'
# round-15 gate: the K-tick PohDevTile — one window dispatch speculates
# K whole ticks, a mixin tick SPLICES from the saved insertion point
# (per-step hash caps, never a full-tick re-hash) — must emit entry
# chains bit-identical to the host rule at EVERY mixin count, with zero
# steady-state compiles after the first window+splice warm; and the
# native pack schedule loop must stream bit-identical microblocks to
# the Python fallback on a conflict-heavy heap
import collections
import numpy as np
from firedancer_tpu.utils import xla_cache
xla_cache.enable()
from firedancer_tpu.disco import trace
from firedancer_tpu.ballet import entry as entry_lib, pack as pack_lib
from firedancer_tpu.ballet import txn as txn_lib
from firedancer_tpu.disco.tiles import PohDevTile
trace.install_jax_compile_listener()

class _M:
    def __init__(self): self.d = collections.Counter()
    def add(self, k, v=1): self.d[k] += v
    def set(self, k, v): self.d[k] = v
class _Ctx:
    def __init__(self, cfg): self.cfg, self.metrics, self.out = cfg, _M(), []
    def publish(self, payload, sig=0): self.out.append(bytes(payload))

HPT, MB_CAP, K = 8, 3, 2
P = HPT - MB_CAP - 1
# ONE tile for the whole sweep: PohEngine jits per instance, so the
# zero-compile claim only means something against a live tile in
# steady state (exactly how the topology runs it)
ctx = _Ctx(dict(hashes_per_tick=HPT, ticks_per_slot=4, mb_per_tick=MB_CAP,
                spec_ticks=K, spec_spans=3, mixin_txn_max=8, unroll=4))
t = PohDevTile(); t.init(ctx)

def seg(j, tag):
    """Close one tick carrying j mixins against the live window; returns
    the new entries and the metric deltas that tick produced."""
    head0, base, m0 = t.hash, len(ctx.out), dict(ctx.metrics.d)
    for i in range(j):
        t._mb_q.append([bytes([tag * 8 + i + 1]) * 65])
    want = 1 if j == 0 else j + 1
    for _ in range(4):                  # 1st call may only open a window
        t.house(ctx); t.after_credit(ctx)
        if len(ctx.out) - base >= want:
            break
    entries = [entry_lib.Entry.deserialize(p)[0] for p in ctx.out[base:]]
    assert len(entries) == want, (j, entries)
    assert entry_lib.verify_chain(head0, entries), f"j={j} chain broke"
    if j:
        assert [e.num_hashes for e in entries] \
            == [P + 1] + [1] * (j - 1) + [MB_CAP + 1 - j], (j, entries)
    d = {k: v - m0.get(k, 0) for k, v in ctx.metrics.d.items()}
    assert d.get("recheck_fail_cnt", 0) == 0, (j, d)
    if j:
        assert d.get("rehash_cnt", 0) == MB_CAP + 1 - j, (j, d)
        assert d.get("splice_dispatch_cnt", 0) == 1, (j, d)
    else:
        assert d.get("spec_hit_cnt", 0) == 1, (j, d)

for j in range(MB_CAP + 1):                 # warm sweep, every offset
    seg(j, 1)
cnt0, _ = trace.compile_totals()
for j in range(MB_CAP + 1):                 # steady state: no compiles
    seg(j, 2)
cnt1, _ = trace.compile_totals()
assert cnt1 == cnt0, f"steady-state speculation compiled {cnt1 - cnt0}x"

def mk(i, hot):
    signer = bytes([1 + i % 37, 1 + i // 37]) + bytes(30)
    msg = txn_lib.build_unsigned(
        [signer], b"\x11" * 32, [(2, bytes([0]), i.to_bytes(8, "little"))],
        extra_accounts=[bytes([hot]) * 32, b"\x07" * 32],
        readonly_unsigned_cnt=1)
    return txn_lib.assemble([b"\x5a" * 64], msg)

def stream(native):
    p = pack_lib.Pack(bank_tile_cnt=2, max_txn_per_microblock=4,
                      max_pending=64, native=native)
    for i in range(96):
        pay = mk(i, 200 + i % 3)
        p.insert(pay, txn_lib.parse(pay))
    out, stalls, bank, busy = [], 0, 0, [False, False]
    while stalls < 6:
        if busy[bank]:
            p.done(bank); busy[bank] = False
        mb = p.schedule(bank)
        if mb is None:
            if p.pending and not any(busy):
                p.end_block(); out.append(("END",))
            stalls += 1
        else:
            stalls = 0; busy[bank] = True
            out.append((bank, tuple(mb.payloads)))
        bank = 1 - bank
    return out, dict(p.metrics)

sn, mn = stream(True) if pack_lib.Pack(bank_tile_cnt=1).native else (None, None)
sp, mp = stream(False)
if sn is None:
    print("leader speculation smoke ok (native pack unavailable: "
          "fallback-only); splice chains bit-identical, 0 compiles")
else:
    assert sn == sp and mn == mp, "native pack diverged from fallback"
    print(f"leader speculation smoke ok: {MB_CAP + 1} mixin offsets "
          f"bit-identical to host rule, 0 steady-state compiles "
          f"({cnt0} warm), native == fallback over "
          f"{sum(1 for x in sp if x[0] != 'END')} microblocks")
EOF

tier "multichip CPU smoke (8-virtual-device dp mesh, sharded == single)"
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python - <<'EOF'
# round-7 gate: the dp-mesh serving path (sharded packed dispatch + the
# sharded PackedIngest engine) must produce verdicts BIT-IDENTICAL to the
# single-chip engine at a fixed seed, on a mixed valid/invalid batch
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from firedancer_tpu.models.verifier import (
    SigVerifier, VerifierConfig, make_example_batch)
from firedancer_tpu.parallel import mesh as pm
assert len(jax.devices()) == 8, jax.devices()
msgs, lens, sigs, pubs = make_example_batch(64, 96, True, seed=7)
sigs = np.array(sigs)
sigs[3, 5] ^= 0xFF; sigs[40, 5] ^= 0xFF          # mixed verdict
single = SigVerifier(VerifierConfig(batch=64, msg_maxlen=96))
sharded = SigVerifier(VerifierConfig(batch=64, msg_maxlen=96),
                      mesh=pm.make_mesh(8))
ref = np.asarray(single.packed_dispatch(msgs, lens, sigs, pubs))
got = np.asarray(sharded.packed_dispatch(msgs, lens, sigs, pubs))
assert ref.any() and not ref.all()
assert np.array_equal(ref, got), "sharded dispatch diverged"
eng = sharded.make_ingest(nbuf=3)
outs = []
for _ in range(4):
    outs += eng.submit(msgs, lens, sigs, pubs)
outs += eng.drain()
assert len(outs) == 4
for ok in outs:
    assert np.array_equal(ok, ref), "sharded ingest diverged"
print("multichip smoke ok: 8-device sharded dispatch + ingest "
      "bit-identical to single-chip")
EOF

tier "host-path smoke (zero-repack == legacy + native == fallback + packed egress + 2-tile mp)"
# round-8 gate: submit_rows over dcache-layout rows must be bit-identical
# to the legacy _pack_into repack, and the packed-wire topology must deal
# frags across 2 verify tiles with zero torn drops (real file: spawn).
# round-11 gates ride along: the one-pass C submit/harvest kernel must
# match the NumPy fallback wire-for-wire, and the packed verdict egress
# (one arena frag per harvest) must carry the legacy per-txn bytes
JAX_PLATFORMS=cpu python tools/hostpath_smoke.py

tier "chaos smoke (kill-respawn + device-loss fallback + eviction, CPU)"
# robustness gate: dead-consumer fseq eviction unstalls producers, a
# GuardedVerifier over injected dispatch loss serves bit-identical CPU
# fallback verdicts then recovers, and a hard-killed verify tile is
# respawned into the live workspace with zero duplicate verdicts
# (real file: spawn re-imports __main__; fixed seeds throughout)
JAX_PLATFORMS=cpu python tools/chaos_smoke.py

tier "front-door smoke (QUIC flood/malformed/slowloris over loopback, CPU)"
# DoS-hardening gate: a 1k-conn flood from one source trips the Retry
# defense and the per-peer cap with bounded quic-tile RSS, a malformed-
# packet storm sheds in the parser with zero conn state, and a slowloris
# + oversize-partial attack is evicted by the reassembly budgets — in
# every scenario legit loopback txns keep verifying with zero duplicate
# verdicts and /healthz reports the shed (real file: spawn)
JAX_PLATFORMS=cpu python tools/chaos_smoke.py --wire

tier "crypto parity smoke (RFC 9001 vectors + native<->fallback wire interop)"
JAX_PLATFORMS=cpu python - <<'EOF'
# round-16 gate: the burst packet-protection engines must be BIT-
# IDENTICAL — the C engine and the NumPy fallback both reproduce the
# RFC 9001 Appendix A client Initial byte-for-byte (decrypt AND
# re-encrypt), and a live loopback handshake + txn flow between a
# native client and a fallback server (then swapped) delivers every
# txn with ZERO undecryptable packets and every packet attributed to
# the armed backend (the other counter must stay 0)
import os, time
from firedancer_tpu.waltz import quic_crypto as qc
from firedancer_tpu.waltz.quic import QuicConfig, QuicEndpoint, initial_keys
from firedancer_tpu.waltz.udpsock import UdpSock

DCID = bytes.fromhex("8394c8f03e515708")
HDR = bytes.fromhex("c300000001088394c8f03e5157080000449e00000002")
from tests.test_quic_crypto_batch import ENCRYPTED, PAYLOAD  # RFC goldens

have_native = qc._native_lib() is not None
modes = [False] + ([True] if have_native else [])
for native in modes:
    be = qc.CryptoBackend(native=native)
    rx, _ = initial_keys(DCID, is_server=True)
    slot = be.key_new(rx.key, rx.iv, rx.hp)
    buf = bytearray(ENCRYPTED)
    (ok, pn, off, ln), = be.decrypt_burst(
        [(buf, 0, len(HDR) - 4, len(buf), slot, 0)])
    assert ok and pn == 2 and bytes(buf[off:off + ln]) == PAYLOAD, native
    ebuf = bytearray(HDR + PAYLOAD + bytes(16))
    be.encrypt_burst([(ebuf, len(HDR) - 4, 2, len(PAYLOAD), slot)])
    assert bytes(ebuf) == ENCRYPTED, f"re-encrypt diverged (native={native})"
    be.key_free(slot)

pairs = [(n, not n) for n in modes] if have_native else [(False, False)]
for cl_native, sv_native in pairs:
    ssock = UdpSock(bind_ip="127.0.0.1", burst=256, mutable=True)
    csock = UdpSock(bind_ip="127.0.0.1", burst=256, mutable=True)
    try:
        sv = QuicEndpoint(QuicConfig(identity_seed=os.urandom(32),
                                     is_server=True,
                                     crypto_native=sv_native), ssock.aio())
        cl = QuicEndpoint(QuicConfig(identity_seed=os.urandom(32),
                                     crypto_native=cl_native), csock.aio())
        got = []
        sv.on_stream = lambda conn, sid, data: got.append(bytes(data))
        conn = cl.connect(("127.0.0.1", ssock.port), now=time.monotonic())
        deadline, sent = time.monotonic() + 30, False
        while time.monotonic() < deadline and len(got) < 8:
            now = time.monotonic()
            for sock, ep in ((ssock, sv), (csock, cl)):
                pkts = sock.recv_burst()
                if pkts:
                    ep.rx(pkts, now)
            if conn.handshake_done and not sent:
                sent = True
                for t in range(8):
                    conn.send_txn(b"parity-txn-%d" % t)
            cl.service(now); sv.service(now)
            time.sleep(0.001)
        assert sorted(got) == [b"parity-txn-%d" % t for t in range(8)], \
            (cl_native, sv_native, got)
        for ep, nat in ((sv, sv_native), (cl, cl_native)):
            armed = "crypto_native" if nat else "crypto_fallback"
            other = "crypto_fallback" if nat else "crypto_native"
            assert ep.metrics[armed] > 0 and ep.metrics[other] == 0, \
                (nat, dict(ep.metrics))
            assert ep.metrics["pkt_undecryptable"] == 0, dict(ep.metrics)
    finally:
        ssock.close(); csock.close()
print("crypto parity smoke ok: RFC 9001 vectors bit-identical on "
      f"{len(modes)} backend(s), {len(pairs)} interop pairing(s) clean"
      + ("" if have_native else " (native .so unavailable: fallback-only)"))
EOF

tier "drain smoke (zero-loss rolling restart + bounded timeout fallback, CPU)"
# drain-protocol gate: a verify tile is rolling-restarted UNDER LIVE LOAD
# with changed restart-required knobs (n_buffers/max_inflight) — every
# published verdict reaches the sink exactly once (zero lost, zero
# duplicate), peers stall only for the bounded drain window, the cursor
# manifest lands, and the whole topology then drains gracefully in
# dependency order; a forced 0s drain budget must degrade to crash-
# respawn semantics with a loadable drain-timeout flight bundle
# (real file: spawn; AOT-gated like the kill-respawn scenario)
JAX_PLATFORMS=cpu python tools/chaos_smoke.py --drain

tier "shred chaos smoke (erasure storm + dup/forge admission, CPU)"
# round-13 gate: a seeded drop/corrupt storm over 12 signed FEC sets is
# shed at the parser/merkle/sig gates with every set accounted and every
# recoverable set bit-exact through the batched device recover; a
# dup/forge burst through the batched leader-sig admission forwards each
# unique shred EXACTLY once and forged signatures never poison dedup
# (forge-then-censor resistance survives deferred batch forwarding)
JAX_PLATFORMS=cpu python tools/chaos_smoke.py --shred

tier "leader chaos smoke (pack restart + shard kill mid-slot, exactly-once mixins, CPU)"
# round-14 gate: the pack tile is rolling-restarted mid-slot under live
# load — its drain hook flushes the fee-priority heap, the respawn
# resumes from the evicted fseq cursor, every verified txn lands in
# EXACTLY ONE microblock mixin at the sink, and the device PoH chain
# emitted across the outage re-verifies (host verify_chain + the batched
# verify_entries ladder) with zero recheck failures (real file: spawn).
# round-15 rides along: a 2-SHARD leader topology (fee-payer-partitioned
# leader_pack tiles + the leader_merge global-budget stage) has one
# shard killed mid-slot — steering re-converges deterministically and
# the same exactly-once + re-verify bars hold through the merge
JAX_PLATFORMS=cpu python tools/chaos_smoke.py --leader

tier "fleet chaos smoke (host SIGKILL -> failover, exactly-once verdicts, CPU)"
# round-17 gate: a 3-host fleet (each host = its own supervisor process
# + full topology + capture ledger, consistent-hash steered, sig digests
# gossiped over the control ring) has one host's whole process group
# SIGKILLed mid-load — steering re-converges deterministically, the
# ring's next owner adopts the dead host's stream with its ledger
# preloaded (capture file ∪ gossiped digests), and the union of capture
# ledgers equals the injected txn universe with every verdict EXACTLY
# once (zero lost, zero duplicated); `fdtpuctl fleet top` reports the
# loss and a fleet rolling restart of the survivors (driven through the
# fdtpuctl command file) upgrades one host at a time under the same bar
JAX_PLATFORMS=cpu python tools/chaos_smoke.py --fleet

tier "autotune smoke (closed loop converges, do-no-harm reverts, CPU)"
# self-driving gate: the policy loop converges a mis-tuned plant and
# re-converges after a load step, widens the dispatch window on a slow-
# consumer verdict, catches a poisoned (inverted) rule via do-no-harm
# and reverts it exactly, and live-actuates a real mis-tuned topology
# through the shm knob pods (modeled plants measure the POLICY, not
# this box's jit speed; the live scenario is AOT-gated)
JAX_PLATFORMS=cpu python tools/chaos_smoke.py --autotune

tier "latency smoke (dual-lane beats single-lane, bulk holds, CPU)"
JAX_PLATFORMS=cpu python - <<'EOF'
# round-9 gate: under mixed load the deadline-driven low-latency lane's
# p99 must beat the single-lane baseline and the bulk lane must hold its
# throughput; zero compiles may land on the hot path (every shape is
# warmed + mark_warm'd before the window); every latency admission is
# accounted (verified in-lane or spill-counted, never dropped).  The
# verifier is a modeled-latency fake (0.5 ms fixed + 10 us/row) so the
# gate measures the DISPATCH POLICY, not this box's jit speed.
import importlib.util, time
import numpy as np
spec = importlib.util.spec_from_file_location("bench", "bench.py")
bench = importlib.util.module_from_spec(spec)
spec.loader.exec_module(bench)

class _R:
    def __init__(self, n, ready_at):
        self.n, self.ready_at = n, ready_at
    def is_ready(self):
        return time.perf_counter() >= self.ready_at
    def __array__(self, dtype=None, copy=None):
        while time.perf_counter() < self.ready_at:
            time.sleep(20e-6)
        return np.ones((self.n,), bool)

def fake(m, l, s, p):
    n = np.asarray(m).shape[0]
    return _R(n, time.perf_counter() + 0.0005 + n * 10e-6)

best = None
for rep in range(3):  # timing gate on a shared 1-core box: best of 3
    r = bench.measure_dual_lane(fake, bulk_batch=1024, maxlen=128,
                                n_bulk=1024 * 12, lat_shapes=(16, 64),
                                deadline_us=500, n_probes=48, chunk=256,
                                lat_max_inflight=8, max_inflight=16)
    assert r["compile_cnt"] == 0, f"compile on hot path: {r}"
    assert r["lat_txns"] + r["lat_spill_cnt"] == r["probes"], \
        f"latency admission unaccounted: {r}"
    ok = (r["lat_p99_ms"] < r["single_p99_ms"] / 2
          and r["bulk_vps"] >= 0.95 * r["single_vps"])
    if best is None or r["lat_p99_ms"] < best["lat_p99_ms"]:
        best = r
    if ok:
        break
else:
    raise AssertionError(f"dual-lane gate failed 3 reps, best: {best}")
print(f"latency smoke ok: lat p99 {r['lat_p99_ms']:.2f} ms vs single "
      f"{r['single_p99_ms']:.2f} ms ({r['single_p99_ms']/r['lat_p99_ms']:.1f}x), "
      f"bulk {r['bulk_vps']:.0f} vs single {r['single_vps']:.0f} vps, "
      f"{r['lat_deadline_closes']} deadline closes, "
      f"{r['lat_spill_cnt']} spills")
EOF

tier "bench wiring (no device run)"
python - <<'EOF'
import ast, sys
src = open("bench.py").read()
ast.parse(src)                       # syntactically sound
assert '"metric"' in src and '"vs_baseline"' in src
# round-8: the record must carry the mp-vs-single-pipeline ratio so a
# multi-tile regression below 1.0 is visible (and flagged) in the log
assert '"mp_vs_pipe"' in src and '"mp_vs_pipe_flag"' in src
assert '"pipe_host_us_txn_packed"' in src
# round-9: per-lane records — a latency win must not hide a bulk
# regression (or vice versa), and spills must be visible
assert '"lat_p99_ms"' in src and '"dual_bulk_vps"' in src
assert '"lat_spill_cnt"' in src and '"single_lane_p99_ms"' in src
# round-10: the e2e wire lane — packet->verdict throughput/latency plus
# the packed-publish bit-identity flag must land in the record
assert '"net_vps"' in src and '"net_p99_ms"' in src
assert '"net_packed_vps"' in src and '"net_packed_identical"' in src
# round-10: the antipa A/B must land in the record (land-or-kill
# evidence for the [verify] mode flag accumulates run over run)
assert '"antipa_vps"' in src and '"antipa_vs_strict"' in src
assert '"antipa_wiring_only"' in src
# round-11: the closed-loop tuner lane — time-to-converge, decision and
# revert counts (a revert in steady state is a policy bug) must land
assert '"autotune_converge_s"' in src and '"autotune_decisions"' in src
assert '"autotune_revert_cnt"' in src and '"autotune_wiring_only"' in src
# round-11: the native host-path lane — packed-egress us/txn plus the
# egress bit-identity bool (the gate that lets the rewire ship) must land
assert '"hostpath_us_txn"' in src and '"egress_packed_identical"' in src
# round-12: the drain lane (opt-in) — flush cost and restart verdict gap
# of a zero-loss rolling restart must land when FDTPU_BENCH_DRAIN=1
assert '"drain_flush_ms"' in src and '"restart_gap_ms"' in src
# round-13: the batched shred lane — recovered-shred and merkle-walk
# rates, per-set recover cost, the batched-vs-perset speedup (the >=3x
# land bar on device), plus the honest CPU-wiring stamp must all land
assert '"shred_rps"' in src and '"shred_merkle_vps"' in src
assert '"shred_recover_us_set"' in src and '"shred_batch_vs_perset"' in src
assert '"shred_wiring_only"' in src
# round-14: the leader lane — device PoH hash rate / per-tick cost, the
# batched-vs-serial span speedup, pack per-txn host cost, the satellite
# fixed-schedule sha A/B, plus the honest CPU-wiring stamp (an int: the
# BENCH loader drops bools) must all land
assert '"poh_hps"' in src and '"poh_us_tick"' in src
assert '"poh_batch_vs_serial"' in src and '"pack_txn_us"' in src
assert '"poh_sha_fixed_vs_generic"' in src
assert '"leader_wiring_only"' in src
# round-15: the sharded-pack + speculation lane — the native-vs-fallback
# pack cost pair (pack_txn_us is ENFORCED in bench_diff now; the
# fallback number keeps the Python path honest), the native-availability
# stamp, and the splice-vs-full-tick re-hash A/B must all land
assert '"pack_txn_us_fallback"' in src and '"pack_native"' in src
assert '"poh_splice_us"' in src and '"poh_splice_vs_full"' in src
# round-16: the burst packet-protection lane — server-side pps beside
# the verdict rate, the native/fallback us/pkt pair, and the
# zero-fallback attribution field must all land
assert '"net_pps"' in src and '"net_crypto_fallback"' in src
assert '"quic_crypto_us_pkt"' in src
assert '"quic_crypto_us_pkt_fallback"' in src
# round-17: the fleet lane — host count, host-loss failover cost, and
# the two exactly-once invariants recorded as enforced zeros must all
# land (and bench_diff must route + enforce them)
assert '"fleet_hosts"' in src and '"fleet_failover_ms"' in src
assert '"fleet_dup_verdicts"' in src and '"fleet_lost_verdicts"' in src
bd = open("tools/bench_diff.py").read()
assert '"fleet_failover_ms"' in bd and '"fleet_dup_verdicts"' in bd
assert bd.count("fleet_dup_verdicts") >= 2   # lifted AND enforced
import importlib.util
spec = importlib.util.spec_from_file_location("bench", "bench.py")
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)           # imports resolve (no device work)
for fn in ("measure_throughput", "measure_device_batch_ms",
           "measure_pipe_vps", "measure_mp_vps", "measure_mc_vps",
           "measure_pipe_host_us_rows", "measure_hostpath_packed_egress",
           "measure_dual_lane", "measure_net_vps", "measure_drain",
           "measure_shred_recover", "measure_leader",
           "measure_quic_crypto", "measure_fleet"):
    assert hasattr(m, fn), fn
print("bench wiring ok")
EOF

tier "graft entry wiring"
python - <<'EOF'
import __graft_entry__ as g
assert callable(g.entry) and callable(g.dryrun_multichip)
print("entry wiring ok")
EOF

if [ -n "$FDTPU_CI_FULL" ]; then
    # two processes: a jaxlib CPU-compiler flakiness (sporadic SIGSEGV in
    # backend_compile_and_load / cache read) only bites when the
    # crypto-graph modules compile late in one giant accumulated process;
    # splitting resets it.  ONE list drives both halves.
    CRYPTO_TESTS="test_ed25519 test_ed25519_rlc test_ed25519_conformance \
        test_ed25519_real_corpora test_curve25519 test_curve_pallas \
        test_f25519 test_x25519_ristretto test_scalar25519 test_sha512 \
        test_sha256 test_blake3 test_collectives test_reedsol"
    IGNORES=""; PART_B=""
    for t in $CRYPTO_TESTS; do
        IGNORES="$IGNORES --ignore=tests/$t.py"
        PART_B="$PART_B tests/$t.py"
    done
    echo "== full suite part A (runtime/topology) =="
    FDTPU_XLA_CACHE_READONLY=1 python -m pytest tests/ -q $IGNORES
    echo "== full suite part B (crypto graphs) =="
    FDTPU_XLA_CACHE_READONLY=1 python -m pytest -q $PART_B
fi

echo "CI GATE PASSED in $(($(date +%s) - T0))s"
