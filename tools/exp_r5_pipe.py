"""pipe_vps decomposition: host-only cost vs real, chunk and batch
sweeps, and a cProfile of the host path."""
import cProfile
import io
import os, sys, time
import pstats
import numpy as np
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from firedancer_tpu.utils import xla_cache
xla_cache.enable()
import jax
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, "/root/repo")
from bench import _gen_payloads
from firedancer_tpu.disco.pipeline import VerifyPipeline
from firedancer_tpu.models.verifier import SigVerifier, VerifierConfig

def run(batch, n_txn, chunk, fake=False, profile=False):
    payloads = _gen_payloads(n_txn)
    if fake:
        fn = lambda m, l, s, p: np.ones((np.asarray(m).shape[0],), bool)
    else:
        v = SigVerifier(VerifierConfig(batch=batch, msg_maxlen=128))
        np.asarray(v(*v.example_args()))
        fn = v
    pipe = VerifyPipeline(fn, batch=batch, msg_maxlen=128,
                          tcache_depth=1 << 21, max_inflight=8)
    prof = cProfile.Profile() if profile else None
    if prof: prof.enable()
    t0 = time.perf_counter()
    for i in range(0, n_txn, chunk):
        pipe.submit_burst(payloads[i:i + chunk])
    pipe.flush()
    dt = time.perf_counter() - t0
    if prof:
        prof.disable()
        s = io.StringIO()
        pstats.Stats(prof, stream=s).sort_stats("cumulative").print_stats(14)
        print(s.getvalue()[:3000], flush=True)
    return n_txn / dt

print(f"host-only b4096 c1024: {run(4096, 4096*6, 1024, fake=True):,.0f}/s", flush=True)
print(f"host-only b4096 c4096: {run(4096, 4096*6, 4096, fake=True):,.0f}/s", flush=True)
print(f"real b4096 c1024: {run(4096, 4096*6, 1024):,.0f}/s", flush=True)
print(f"real b4096 c4096: {run(4096, 4096*6, 4096):,.0f}/s", flush=True)
print(f"real b8192 c8192: {run(8192, 8192*6, 8192):,.0f}/s", flush=True)
print("--- host-only profile b4096 c4096 ---", flush=True)
run(4096, 4096*8, 4096, fake=True, profile=True)
