"""Round-6 RLC select-redesign A/B: legacy 16-entry unsigned tables vs
p16 (signed digits [-8..8] + packed 16-bit limb planes + precomputed
negated T2d) in the Pallas MSM kernel, measured through the FULL
verify_batch_rlc graph at the batches where RLC leaves the
overhead-bound regime (models/verifier.py:34-37 — 64k/128k).

Protocol: same session, fresh jit identity per arm (the env flag is read
at trace time), pipelined dispatch + one draining fetch, median of reps.
The r4 profile pinned ~45% of the fused-chain kernel on table selects;
the redesign moves ~1/3 of the legacy select data volume per add, so a
real win should clear the >5% end-to-end bar (ISSUE r6) at 64k+.

On a non-Pallas backend (cpu) verify_batch_rlc falls back to the XLA
msm and the arms measure the SAME kernel — the printed backend labels
whether this run is a verdict or a wiring check.

Env: B (65536), ITERS (8), REPS (5), M (8).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def main():
    from firedancer_tpu.utils import xla_cache
    xla_cache.enable()
    import jax
    import jax.numpy as jnp

    from firedancer_tpu.models.verifier import make_example_batch
    from firedancer_tpu.ops import ed25519 as ed
    from _bench import note_wiring  # noqa: E402

    batch = int(os.environ.get("B", 65536))
    iters = int(os.environ.get("ITERS", 8))
    reps = int(os.environ.get("REPS", 5))
    m = int(os.environ.get("M", 8))

    args = make_example_batch(batch, 128, valid=True, sign_pool=64)
    rng = np.random.default_rng(5)
    z = jnp.asarray(rng.integers(0, 256, size=(batch, 16), dtype=np.uint8))

    out = {"batch": batch, "iters": iters, "reps": reps, "m": m,
           "backend": jax.devices()[0].platform}
    note_wiring(out, ed._pallas_ok(batch))
    for sel in ("legacy", "p16"):
        os.environ["FDTPU_RLC_SELECT"] = sel
        # fresh jit identity per arm: the env flag is read at trace time,
        # and two wrappers of the same callable would share a pjit entry
        fn = jax.jit(lambda ms, ln, sg, pb, zz, _s=sel: ed.verify_batch_rlc(
            ms, ln, sg, pb, zz, m=m)[0])
        t0 = time.perf_counter()
        good = bool(np.asarray(fn(*args, z)))
        print(f"{sel}: compile+first {time.perf_counter() - t0:.1f}s "
              f"all_ok={good}", file=sys.stderr)
        assert good, f"{sel} arm rejected a valid batch"
        runs = []
        for _ in range(reps):
            t0 = time.perf_counter()
            ok = None
            for _ in range(iters):
                ok = fn(*args, z)
            np.asarray(ok)
            runs.append(batch * iters / (time.perf_counter() - t0))
        out[sel] = round(median(runs), 1)
        out[sel + "_runs"] = [round(r, 1) for r in sorted(runs)]
        print(f"{sel}: {out[sel]:,.0f} v/s  {out[sel + '_runs']}",
              file=sys.stderr)
    os.environ.pop("FDTPU_RLC_SELECT", None)
    out["p16_vs_legacy"] = round(out["p16"] / out["legacy"], 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
