"""CI observability smoke: boot the verify-bench topology with the
supervisor /metrics endpoint, scrape it, and run the monitor + trace
CLI paths against the live topo.

--wire runs the attribution tier instead: a live quic_server -> verify
-> dedup -> sink topology under loopback QUIC load must expose the
per-link producer->consumer metric families on /metrics, an SLO line on
/healthz, and a non-empty stage-budget table off the span rings.

A real file (not a ci.sh heredoc) because tile processes use the
multiprocessing 'spawn' start method, which re-imports __main__ from
its path — stdin scripts have none.

Usage:  JAX_PLATFORMS=cpu python tools/obs_smoke.py [--wire]
"""

import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from firedancer_tpu.app import config as config_mod
from firedancer_tpu.app import fdtpuctl
from firedancer_tpu.disco.run import TopoRun


def main() -> int:
    cfg = config_mod.load(None)
    cfg["name"] = "fdtpu_ci_obs"
    cfg["topology"] = "verify-bench"
    cfg["development"]["source_count"] = 64
    cfg["tiles"]["verify"]["batch"] = 8
    cfg["tiles"]["verify"]["msg_maxlen"] = 256
    spec = config_mod.build_topology(cfg)
    with TopoRun(spec, metrics_port=0) as run:
        run.wait_ready(timeout=300)
        time.sleep(1.0)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{run.metrics_port}/metrics",
            timeout=10).read().decode()
        assert "# TYPE" in body and '_bucket{' in body, body[:400]
        health = urllib.request.urlopen(
            f"http://127.0.0.1:{run.metrics_port}/healthz", timeout=10)
        assert health.status == 200

        class A:
            pass
        a = A()
        a.interval = 0.1
        a.count = 1
        a.follow = False
        assert fdtpuctl.cmd_monitor(cfg, a) == 0
        t = A()
        t.duration = 0.5
        t.out = "/tmp/fdtpu_ci_trace.json"
        assert fdtpuctl.cmd_trace(cfg, t) == 0
        tr = json.load(open("/tmp/fdtpu_ci_trace.json"))
        assert tr["traceEvents"], "no spans collected"
        assert "compile_cnt" in body, "compile counter missing from /metrics"
    print("observability smoke ok")
    return 0


def main_wire() -> int:
    """Attribution + SLO against a live wire topology: per-link metric
    families, the /healthz slo line, and a non-empty stage table."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from chaos_smoke import _QuicClient, _make_txns, _wait_sink, _wire_spec

    from firedancer_tpu.disco import slo as slo_mod
    from firedancer_tpu.disco.run import TopoRun

    n = 32
    spec = _wire_spec("obswire")
    txns = _make_txns(n, seed=17)
    run = TopoRun(spec, metrics_port=0)
    client = None
    try:
        run.wait_ready(timeout=420)
        port = int(run.metrics("quic_server")["bound_port"])
        client = _QuicClient(port)
        client.wait_handshake()
        client.send_txns(txns)
        got = _wait_sink(run, n, clients=(client,))
        assert got == n, f"wire load lost txns: {got}/{n}"
        time.sleep(1.2)   # >= one housekeeping window for the gauges

        base = f"http://127.0.0.1:{run.metrics_port}"
        body = urllib.request.urlopen(f"{base}/metrics",
                                      timeout=10).read().decode()
        # per-link families, producer->consumer labeled, declared once
        assert 'fdtpu_link_lag{' in body, "per-link lag family missing"
        assert ('producer="quic_server"' in body
                and 'consumer="verify"' in body), \
            "link samples lost their producer->consumer labels"
        for fam in ("fdtpu_link_lag", "fdtpu_link_slow_cnt",
                    "fdtpu_link_occ_hwm", "fdtpu_link_frag_rate"):
            assert body.count(f"# TYPE {fam} ") == 1, \
                f"{fam} must be TYPE-declared exactly once"
        # regime gauges flow from the mux loop accounting
        assert "fdtpu_busy_ns" in body and "fdtpu_idle_ns" in body

        hz = urllib.request.urlopen(f"{base}/healthz", timeout=10)
        hz_body = hz.read().decode()
        assert hz.status == 200 and "slo " in hz_body, \
            f"/healthz lost its slo field: {hz_body!r}"

        # stage-budget table off the live span rings must be non-empty
        spans, kind_of = slo_mod.collect(run.jt)
        stats = slo_mod.stage_stats(spans, kind_of)
        seen = {r["stage"] for r in stats if r["n"] > 0}
        assert "wire" in seen, "quic_server wire spans missing"
        assert len(seen) >= 4, f"stage table too sparse: {sorted(seen)}"
        table = slo_mod.render_table(
            stats, slo_mod.burn(spans, kind_of))
        assert "burn rate:" in table
        print(table)
    finally:
        if client is not None:
            client.close()
        run.halt()
        run.close()
    print(f"observability wire smoke ok: {got}/{n} verified, "
          f"stages with samples: {sorted(seen)}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main_wire() if "--wire" in sys.argv[1:] else main())
