"""CI observability smoke: boot the verify-bench topology with the
supervisor /metrics endpoint, scrape it, and run the monitor + trace
CLI paths against the live topo.

A real file (not a ci.sh heredoc) because tile processes use the
multiprocessing 'spawn' start method, which re-imports __main__ from
its path — stdin scripts have none.

Usage:  JAX_PLATFORMS=cpu python tools/obs_smoke.py
"""

import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from firedancer_tpu.app import config as config_mod
from firedancer_tpu.app import fdtpuctl
from firedancer_tpu.disco.run import TopoRun


def main() -> int:
    cfg = config_mod.load(None)
    cfg["name"] = "fdtpu_ci_obs"
    cfg["topology"] = "verify-bench"
    cfg["development"]["source_count"] = 64
    cfg["tiles"]["verify"]["batch"] = 8
    cfg["tiles"]["verify"]["msg_maxlen"] = 256
    spec = config_mod.build_topology(cfg)
    with TopoRun(spec, metrics_port=0) as run:
        run.wait_ready(timeout=300)
        time.sleep(1.0)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{run.metrics_port}/metrics",
            timeout=10).read().decode()
        assert "# TYPE" in body and '_bucket{' in body, body[:400]
        health = urllib.request.urlopen(
            f"http://127.0.0.1:{run.metrics_port}/healthz", timeout=10)
        assert health.status == 200

        class A:
            pass
        a = A()
        a.interval = 0.1
        a.count = 1
        a.follow = False
        assert fdtpuctl.cmd_monitor(cfg, a) == 0
        t = A()
        t.duration = 0.5
        t.out = "/tmp/fdtpu_ci_trace.json"
        assert fdtpuctl.cmd_trace(cfg, t) == 0
        tr = json.load(open("/tmp/fdtpu_ci_trace.json"))
        assert tr["traceEvents"], "no spans collected"
        assert "compile_cnt" in body, "compile counter missing from /metrics"
    print("observability smoke ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
