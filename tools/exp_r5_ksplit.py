"""Split-kernel stage times at 32k (fused-path planning): decompress vs
reduce_recode vs dsm_tail_q."""
import os, sys, time
import numpy as np
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from firedancer_tpu.utils import xla_cache
xla_cache.enable()
import jax
import jax.numpy as jnp
from firedancer_tpu.models.verifier import make_example_batch
from firedancer_tpu.ops import curve25519 as cv
from firedancer_tpu.ops import curve_pallas as cpal
from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.ops import sha512 as sh

B = int(os.environ.get("B", 32768))
msgs, lens, sigs, pubs = make_example_batch(B, 128, valid=True, sign_pool=64)
r_bytes, s_bytes = sigs[:, :32], sigs[:, 32:]
pre = jnp.concatenate([r_bytes, pubs, msgs], axis=1)
digest = jax.jit(sh.sha512)(pre, lens + 64)
np.asarray(digest)
y_r = jnp.asarray(np.asarray(ed._parse_r_bytes(r_bytes)[0]))
_ok, a_pt = jax.jit(cv.decompress)(pubs)
a_pt = cv.Point(*(jnp.asarray(np.asarray(t)) for t in a_pt))
wins = jax.jit(lambda s, d: cpal.reduce_recode(s, d)[1])(s_bytes, digest)
wins = tuple(jnp.asarray(np.asarray(w)) for w in wins)

def timeit(name, fn, *args, iters=16, reps=5):
    f = jax.jit(fn)
    np.asarray(jax.tree_util.tree_leaves(f(*args))[0])
    runs = []
    for _ in range(reps):
        t0 = time.perf_counter()
        o = None
        for _ in range(iters):
            o = f(*args)
        np.asarray(jax.tree_util.tree_leaves(o)[0])
        runs.append((time.perf_counter() - t0) / iters * 1e3)
    runs.sort()
    print(f"{name:24s} {runs[2]:8.2f} ms ({runs[0]:.2f}..{runs[-1]:.2f})",
          flush=True)

timeit("decompress blk128", lambda q: cpal.decompress(q, blk=128), pubs)
timeit("reduce_recode", lambda s, d: cpal.reduce_recode(s, d)[1], s_bytes,
       digest)
timeit("dsm_tail_q", lambda w, y: cpal.dsm_tail_q(w, a_pt, y)[1], wins, y_r)
timeit("fused (ref)", lambda s, d, y: cpal.verify_tail_fused(
    pubs, s, d, y)[1], s_bytes, digest, y_r)
