"""Is int32 multiply full-rate on the TPU VPU, or emulated?

Times K broadcast-MAC ops (the _mulw ladder's inner shape) on (22, blk)
arrays in uint32 vs float32 vs int16-ish variants.  If f32 runs much
faster, re-limbing the field to 8-bit limbs in f32 (exact: products
16-bit, 32-term sums 21-bit < 2^24) is the round-5 throughput lever."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from firedancer_tpu.utils import xla_cache  # noqa: E402
xla_cache.enable()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402

ROWS = 22
BLK = 128
BATCH = 32768
K = 400


def make_kernel(dtype, rows):
    def kernel(a_ref, b_ref, o_ref):
        a = a_ref[...]
        b = b_ref[...]

        def body(i, acc):
            # rotate the broadcast row via a static-ish trick: use row 0
            # (row choice doesn't affect timing; keep it static)
            t = b * a[0:1]
            return acc + t

        acc = jax.lax.fori_loop(0, K, body, jnp.zeros_like(a))
        o_ref[...] = acc

    return kernel


def run(dtype, rows, tag):
    spec = pl.BlockSpec((rows, BLK), lambda i: (0, i))
    a = jnp.asarray(np.random.randint(0, 4096, (rows, BATCH)), dtype)
    b = jnp.asarray(np.random.randint(0, 4096, (rows, BATCH)), dtype)
    f = lambda a, b: pl.pallas_call(
        make_kernel(dtype, rows),
        out_shape=jax.ShapeDtypeStruct((rows, BATCH), dtype),
        grid=(BATCH // BLK,),
        in_specs=[spec, spec], out_specs=spec)(a, b)
    jf = jax.jit(f)
    np.asarray(jf(a, b))  # compile
    reps = []
    for _ in range(5):
        t0 = time.perf_counter()
        o = None
        for _ in range(20):
            o = jf(a, b)
        np.asarray(o)
        reps.append((time.perf_counter() - t0) / 20)
    reps.sort()
    med = reps[len(reps) // 2]
    # ns per MAC per (rows,BLK) block-op
    per = med / K / (BATCH // BLK) * 1e9
    print(f"{tag:10s} rows={rows:2d} {med*1e3:7.3f} ms/call  "
          f"{per:7.1f} ns/MAC/block", flush=True)
    return med


i32 = run(jnp.int32, 22, "int32")
u32 = run(jnp.uint32, 22, "uint32")
f32 = run(jnp.float32, 22, "float32")
f32w = run(jnp.float32, 32, "f32 32row")
print(f"int32/f32 ratio: {i32/f32:.2f}   (32-row f32 vs 22-row int32: "
      f"{i32/f32w:.2f})", flush=True)
