#!/usr/bin/env python
"""(Re)generate seed corpora for the fuzz targets into tests/corpus/.

Seeds are VALID serializations (plus a few structured edge cases) of each
wire format, produced by the same builders the tests use — the role of the
reference's checked-in corpus/ seeds.  Deterministic: same seeds on every
run."""

import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from firedancer_tpu.utils.fuzz import corpus_name  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "corpus")


def emit(target: str, blobs):
    d = os.path.join(OUT, target)
    os.makedirs(d, exist_ok=True)
    for b in blobs:
        with open(os.path.join(d, corpus_name(b)), "wb") as f:
            f.write(b)
    print(f"{target}: {len(os.listdir(d))} seeds")


def main():
    rng = random.Random(7)
    rb = lambda n: bytes(rng.getrandbits(8) for _ in range(n))  # noqa: E731

    # ---- txn ----
    from firedancer_tpu.ballet import txn as txn_lib
    pk1, pk2, prog, bh = rb(32), rb(32), rb(32), rb(32)
    txns = []
    m = txn_lib.build_unsigned([pk1], bh, [(1, b"\x00", b"hello")], [prog])
    txns.append(txn_lib.assemble([rb(64)], m))
    m = txn_lib.build_unsigned([pk1, pk2], bh,
                               [(2, bytes([0, 1]), rb(40))], [prog],
                               readonly_signed_cnt=1)
    txns.append(txn_lib.assemble([rb(64), rb(64)], m))
    m = txn_lib.build_unsigned([pk1], bh, [(1, b"\x00", rb(900))], [prog])
    txns.append(txn_lib.assemble([rb(64)], m))  # near-MTU
    m = txn_lib.build_unsigned([pk1], bh, [(1, b"\x00", b"")], [prog],
                               version=txn_lib.V0,
                               lookups=[(rb(32), bytes([0, 1]), bytes([2]))])
    txns.append(txn_lib.assemble([rb(64)], m))  # v0 with lookups
    emit("txn", txns)

    # ---- compact_u16 ----
    from firedancer_tpu.ballet import compact_u16 as cu16
    emit("compact_u16",
         [cu16.encode(v) + rb(2) for v in (0, 1, 127, 128, 16383, 16384,
                                           65535)])

    # ---- shred ----
    from firedancer_tpu.ballet import entry as entry_lib
    from firedancer_tpu.ballet import shred as shred_lib
    batch = entry_lib.serialize_batch(
        [entry_lib.Entry(1, rb(32), [txns[0]])])
    fs = shred_lib.make_fec_set(batch, slot=3, parent_off=1, version=1,
                                fec_set_idx=0, sign_fn=lambda r: rb(64),
                                data_cnt=4, code_cnt=4, slot_complete=True)
    emit("shred", fs.data_shreds[:2] + fs.code_shreds[:2])

    # ---- entry batch ----
    emit("entry_batch", [
        batch,
        entry_lib.serialize_batch([entry_lib.Entry(5, rb(32), [])]),
    ])

    # ---- zstd ----
    import zstandard
    emit("zstd", [
        zstandard.ZstdCompressor(level=1).compress(b"seed " * 200),
        zstandard.ZstdCompressor(level=19).compress(rb(512) * 4),
        zstandard.ZstdCompressor(level=3,
                                 write_checksum=True).compress(b"\0" * 5000),
    ])

    # ---- gossip ----
    from firedancer_tpu.flamenco import gossip
    v = gossip.make_value(lambda m: rb(64), pk1, gossip.KIND_VOTE, b"vote")
    emit("gossip_msg", [
        gossip.encode_push([v]),
        gossip.encode_pull_req({v.digest()}),
        gossip.encode_pull_resp([v]),
        gossip.encode_ping(pk1, rb(32), rb(64)),
        gossip.encode_pong(pk1, rb(32), rb(64)),
        gossip.encode_prune(pk1, [pk2], rb(64)),
    ])

    # ---- appendvec ----
    from firedancer_tpu.flamenco.snapshot import write_appendvec
    from firedancer_tpu.flamenco.types import Account
    emit("appendvec", [
        write_appendvec([(pk1, Account(lamports=5, data=b"xyz")),
                         (pk2, Account(lamports=9, data=rb(100),
                                       executable=True))]),
    ])

    # ---- lookup table ----
    from firedancer_tpu.flamenco.alut_program import LookupTable
    emit("lookup_table", [
        LookupTable(authority=pk1, addresses=[pk2, prog]).serialize(),
        LookupTable().serialize(),
    ])

    # ---- quic datagrams ----
    emit("quic_datagram", [
        b"\xc3" + (1).to_bytes(4, "big") + bytes([8]) + rb(8)
        + bytes([8]) + rb(8) + b"\x00" + b"\x41\x00" + rb(60),
        b"\x43" + rb(24),  # short header
        rb(1200),
    ])

    # ---- repair ----
    from firedancer_tpu.flamenco import repair
    req = repair.RepairRequest(rb(64), pk1, repair.REQ_WINDOW_INDEX, 1, 7, 3)
    emit("repair_msg", [req.serialize()])


if __name__ == "__main__":
    main()
