"""Round-4 MSM decomposition: where does the 99.8 ms (batch 32k, m=8
pair) go?  Pallas micro-kernels isolate the three inner-loop components
at production shapes (blk=128, 22-limb planes):

  S. the 16-entry select tree            (64 windows x m selects)
  A. the niels-add chain                 (64 x m adds)
  D. the doubling chain                  (256 doubles)
  T. per-block table build               (m x 14 full adds + to_niels)

Each kernel runs the component in a loop with a carried dependence;
rates are slope-timed over two loop counts so launch overhead cancels.
"""

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
from _bench import timed  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402

from firedancer_tpu.utils import xla_cache  # noqa: E402

xla_cache.enable()

from firedancer_tpu.ops import curve_pallas as cpal  # noqa: E402
from firedancer_tpu.ops import curve25519 as cv  # noqa: E402
from firedancer_tpu.ops import f25519 as fe  # noqa: E402

BATCH = 32768
BLK = 128
LANES = BATCH // 8      # m=8 -> 4096 lanes
M = 8


def _mk_points(n):
    from firedancer_tpu.models.verifier import make_example_batch
    _, _, _, pubs = make_example_batch(n, 64, True, sign_pool=8)
    ok, small, pt = cpal.decompress(pubs, blk=BLK)
    return pt


def component_kernels():
    rng = np.random.default_rng(0)
    pt = _mk_points(LANES)
    planes = [np.asarray(t) for t in pt]
    wins = jnp.asarray(rng.integers(0, 16, (64 * M, LANES), np.uint32))

    pts_spec = pl.BlockSpec((cpal.NL, BLK), lambda i: (0, i))
    win_spec = pl.BlockSpec((64 * M, BLK), lambda i: (0, i))

    def run_kernel(name, kernel, reps1, reps2, unit_per_rep):
        def mk(reps):
            @jax.jit
            def f(w, x, y, z, t):
                return pl.pallas_call(
                    kernel(reps),
                    out_shape=[jax.ShapeDtypeStruct((cpal.NL, LANES),
                                                    jnp.uint32)],
                    grid=(LANES // BLK,),
                    in_specs=[win_spec] + [pts_spec] * 4,
                    out_specs=[pts_spec],
                )(w, x, y, z, t)[0]
            return f
        f1, f2 = mk(reps1), mk(reps2)
        a = (wins, *(jnp.asarray(p) for p in planes))
        t1 = timed(f1, *a)
        t2 = timed(f2, *a)
        per = (t2 - t1) / (reps2 - reps1) / unit_per_rep
        print(f"{name:28s} {t1*1e3:7.1f}/{t2*1e3:7.1f} ms -> "
              f"{per*1e9:8.1f} ns/unit/blk", flush=True)
        return per

    # S: select tree (one rep = m selects of 16-entry niels tables)
    def sel_kernel(reps):
        def kernel(w_ref, x_ref, y_ref, z_ref, t_ref, o_ref):
            bias = fe._limb_const(fe._BIAS_PY, 2)
            d2 = cpal._constw(cv.D2)
            p = cpal._Pt(x_ref[...], y_ref[...], z_ref[...], t_ref[...])
            tab = [cpal._to_nielsw(p, bias, d2) for _ in range(1)][0]
            tabs = [tab] * 16   # same entry 16x: select cost identical
            def body(i, acc):
                s = acc
                for j in range(M):
                    wv = w_ref[pl.ds((i % 64) * M + j, 1), :]
                    n = cpal._select_list(tabs, wv)
                    s = jax.tree_util.tree_map(lambda a, b: a + b,
                                               s, n.Yp)
                return s
            acc = jax.lax.fori_loop(0, reps, body,
                                    jnp.zeros_like(x_ref[...]))
            o_ref[...] = acc
        return kernel

    # A: niels add chain (one rep = m adds)
    def add_kernel(reps):
        def kernel(w_ref, x_ref, y_ref, z_ref, t_ref, o_ref):
            bias = fe._limb_const(fe._BIAS_PY, 2)
            d2 = cpal._constw(cv.D2)
            p = cpal._Pt(x_ref[...], y_ref[...], z_ref[...], t_ref[...])
            n = cpal._to_nielsw(p, bias, d2)
            def body(i, acc):
                for _ in range(M):
                    acc = cpal._add_nielsw(acc, n, bias)
                return acc
            acc = jax.lax.fori_loop(0, reps, body, p)
            o_ref[...] = acc.X
        return kernel

    # D: double chain (one rep = 4 doubles)
    def dbl_kernel(reps):
        def kernel(w_ref, x_ref, y_ref, z_ref, t_ref, o_ref):
            bias = fe._limb_const(fe._BIAS_PY, 2)
            p = cpal._Pt(x_ref[...], y_ref[...], z_ref[...], t_ref[...])
            def body(i, acc):
                for _ in range(4):
                    acc = cpal._doublew(acc, bias)
                return acc
            acc = jax.lax.fori_loop(0, reps, body, p)
            o_ref[...] = acc.X
        return kernel

    # T: table build (one rep = one point's 14 adds + 15 to_niels)
    def tab_kernel(reps):
        def kernel(w_ref, x_ref, y_ref, z_ref, t_ref, o_ref):
            bias = fe._limb_const(fe._BIAS_PY, 2)
            d2 = cpal._constw(cv.D2)
            p = cpal._Pt(x_ref[...], y_ref[...], z_ref[...], t_ref[...])
            def body(i, carry):
                pts = [cpal._identity_k(BLK), cpal._Pt(
                    carry, p.Y, p.Z, p.T)]
                for _ in range(14):
                    pts.append(cpal._addfull(pts[-1], p, bias, d2))
                ns = [cpal._to_nielsw(q, bias, d2) for q in pts]
                return ns[-1].Yp
            acc = jax.lax.fori_loop(0, reps, body, x_ref[...])
            o_ref[...] = acc
        return kernel

    run_kernel("S select (m x 16-tree)", sel_kernel, 8, 40, 1)
    run_kernel("A add chain (m adds)", add_kernel, 8, 40, 1)
    run_kernel("D dbl chain (4 dbls)", dbl_kernel, 8, 40, 1)
    run_kernel("T table build (1 pt)", tab_kernel, 2, 10, 1)


if __name__ == "__main__":
    print(f"devices: {jax.devices()}", flush=True)
    component_kernels()
