"""Correctness + throughput check for the Pallas double-scalar-mul kernel
against host python-int expected values, on the live TPU."""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))

import numpy as np
import jax
import jax.numpy as jnp

from firedancer_tpu.ops import curve25519 as cv
from firedancer_tpu.ops import curve_pallas as cp
from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.ops import f25519 as fe

B = 128


def host_dsm(s_int, k_int, a_aff):
    x, y = a_aff
    pa = (x, y, 1, x * y % fe.P)
    q = ed._pt_add_host(
        ed._scalar_mul_base_host(s_int), ed._scalar_mul_host(k_int, pa))
    zi = pow(q[2], fe.P - 2, fe.P)
    return (q[0] * zi % fe.P, q[1] * zi % fe.P)


def main():
    rng = np.random.default_rng(0)
    s = rng.integers(0, 256, size=(B, 32), dtype=np.uint8)
    k = rng.integers(0, 256, size=(B, 32), dtype=np.uint8)
    pts = []
    for i in range(B):
        pt = ed._scalar_mul_base_host(i + 1)
        zi = pow(pt[2], fe.P - 2, fe.P)
        x, y = pt[0] * zi % fe.P, pt[1] * zi % fe.P
        pts.append((x, y))
    X = np.stack([fe._to_limbs_py(p[0]) for p in pts], 1)
    Y = np.stack([fe._to_limbs_py(p[1]) for p in pts], 1)
    Z = np.stack([fe._to_limbs_py(1) for p in pts], 1)
    T = np.stack([fe._to_limbs_py(p[0] * p[1] % fe.P) for p in pts], 1)
    a = cv.Point(*(jnp.asarray(v) for v in (X, Y, Z, T)))

    for case, s_c, k_c in (
        ("var-only", np.zeros_like(s), k),
        ("comb-only", s, np.zeros_like(k)),
        ("both", s, k),
    ):
        sw = cv.scalar_windows(jnp.asarray(s_c))
        kw = cv.scalar_windows(jnp.asarray(k_c))
        got = cp.double_scalar_mul_base(sw, kw, a, blk=128)
        gX = np.asarray(got.X)
        gY = np.asarray(got.Y)
        gZ = np.asarray(got.Z)
        bad = 0
        first = None
        for i in range(B):
            si = int.from_bytes(s_c[i].tobytes(), "little")
            ki = int.from_bytes(k_c[i].tobytes(), "little")
            ex, ey = host_dsm(si, ki, pts[i])
            zi = pow(fe._from_limbs_py(gZ[:, i]) % fe.P, fe.P - 2, fe.P)
            got_x = fe._from_limbs_py(gX[:, i]) * zi % fe.P
            got_y = fe._from_limbs_py(gY[:, i]) * zi % fe.P
            if (got_x, got_y) != (ex, ey):
                bad += 1
                if first is None:
                    first = i
        print(f"{case}: {bad}/{B} bad lanes"
              + (f" (first={first})" if bad else ""), flush=True)


if __name__ == "__main__":
    main()
