#!/usr/bin/env python
"""Stage-by-stage TPU profile of the verify hot path.

Times each jitted stage of ed25519.verify_batch separately plus a raw field
multiply microbenchmark (the muls/s ceiling), to direct optimization work.

Stage timings record through disco.trace.SpanRecorder — the same span
source the live pipeline's trace rings use — so FDTPU_TRACE_OUT=<path>
additionally dumps the run as Chrome trace_event JSON and the summary
table renders through the shared Histf percentile path.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from firedancer_tpu.disco import trace as trace_mod
from firedancer_tpu.models.verifier import make_example_batch
from firedancer_tpu.ops import curve25519 as cv
from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.ops import f25519 as fe
from firedancer_tpu.ops import scalar25519 as sc
from firedancer_tpu.ops import sha512 as sh

BATCH = 4096

REC = trace_mod.SpanRecorder(tile="profile_verify")


def timeit(name, fn, *args, iters=10):
    t0 = time.perf_counter_ns()
    out = fn(*args)
    jax.block_until_ready(out)
    trace_mod.record_compile(("profile", name),
                             time.perf_counter_ns() - t0)  # warmup = compile
    t0 = time.perf_counter_ns()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    total = time.perf_counter_ns() - t0
    # one span per measured iteration (even split: the loop pipelines
    # dispatches and syncs once, so per-iter walls aren't observable)
    for i in range(iters):
        REC.record(name, t0 + i * (total // iters), total // iters,
                   cnt=BATCH)
    dt = total / iters / 1e9
    print(f"{name:28s} {dt*1e3:9.2f} ms  ({BATCH/dt/1e3:9.1f} K items/s)")
    return dt


def main():
    msgs, lens, sigs, pubs = make_example_batch(BATCH, 128, sign_pool=32)
    r_bytes, s_bytes = sigs[:, :32], sigs[:, 32:]

    # raw field mul ceiling: chain of muls to avoid dead-code elim
    x = fe.from_bytes(pubs)
    nmul = 64

    @jax.jit
    def mulchain(x):
        def body(i, a):
            return fe.mul(a, x)
        return jax.lax.fori_loop(0, nmul, body, x)

    dt = timeit("field mul x64 chain", mulchain, x)
    print(f"  -> {BATCH*nmul/dt/1e6:.1f} M field-muls/s ceiling")

    @jax.jit
    def sqrchain(x):
        def body(i, a):
            return fe.sqr(a)
        return jax.lax.fori_loop(0, nmul, body, x)

    dt = timeit("field sqr x64 chain", sqrchain, x)
    print(f"  -> {BATCH*nmul/dt/1e6:.1f} M field-sqrs/s")

    # point double chain
    ok, a_pt = cv.decompress(pubs)
    a_pt = jax.block_until_ready(a_pt)

    @jax.jit
    def dblchain(p):
        def body(i, q):
            return cv.double(q)
        return jax.lax.fori_loop(0, 64, body, p)

    dt = timeit("point double x64 chain", dblchain, a_pt)
    print(f"  -> {BATCH*64/dt/1e6:.2f} M doubles/s")

    @jax.jit
    def addchain(p):
        def body(i, q):
            return cv.add(q, p)
        return jax.lax.fori_loop(0, 64, body, p)

    timeit("point add x64 chain", addchain, a_pt)

    timeit("decompress A", jax.jit(lambda b: cv.decompress(b)[1].X), pubs)

    @jax.jit
    def sha_stage(r, p, m, l):
        pre = jnp.concatenate([r, p, m], axis=1)
        return sh.sha512(pre, l.astype(jnp.int32) + 64)

    timeit("sha512(R||A||M)", sha_stage, r_bytes, pubs, msgs, lens)

    k_digest = sha_stage(r_bytes, pubs, msgs, lens)
    k_limbs = sc.reduce_512(k_digest)
    s_windows = cv.scalar_windows(s_bytes)
    k_windows = sc.limbs_to_windows(k_limbs)
    s_windows, k_windows = jax.block_until_ready((s_windows, k_windows))

    @jax.jit
    def dsmb(sw, kw, p):
        return cv.double_scalar_mul_base(sw, kw, cv.neg(p)).X

    timeit("double_scalar_mul_base", dsmb, s_windows, k_windows, a_pt)

    # table select + build costs inside dsmb
    tab = cv._build_var_table(a_pt)

    @jax.jit
    def sel64(tabs, kw):
        def body(i, acc):
            p = cv._table_select_var(tabs, kw[i])
            return cv.Point(*(a + b for a, b in zip(acc, p)))
        return jax.lax.fori_loop(0, 64, body, cv._identity_like(tabs.X[0]))[0]

    timeit("var table select x64", sel64, tab, k_windows)
    timeit("var table build (14 adds)", jax.jit(lambda p: cv._build_var_table(p).X), a_pt)

    timeit("verify_batch (full)", jax.jit(ed.verify_batch), msgs, lens, sigs, pubs)

    print()
    print(REC.table())
    ccnt, cns = trace_mod.compile_totals()
    print(f"\ncompile events: {ccnt}  ({cns / 1e9:.2f} s total warmup)")
    out_path = os.environ.get("FDTPU_TRACE_OUT")
    if out_path:
        import json
        with open(out_path, "w") as f:
            json.dump(REC.chrome(), f)
        print(f"chrome trace -> {out_path}")


if __name__ == "__main__":
    main()
