"""Measure per-op floors on the live TPU, one process, paired.

Establishes (a) achieved VPU int32/f32 elementwise rates, (b) achieved MXU
int8/bf16 matmul rates, (c) the field-mul/sqr/double rates of the current
ops, so the verify ceiling can be derived instead of guessed.

Measurement rules per project memory: np.asarray() is the only true sync;
chained dispatch with one final fetch; same process for every comparison.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from firedancer_tpu.ops import f25519 as fe
from firedancer_tpu.ops import curve25519 as cv

BATCH = 4096
STEPS = 256


def bench(name, fn, *args, scale=1.0, unit="op", reps=3):
    out = fn(*args)
    jax.tree_util.tree_map(lambda x: np.asarray(x), out)  # warm + sync
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.tree_util.tree_map(lambda x: np.asarray(x), out)
        best = min(best, time.perf_counter() - t0)
    per = best / scale
    print(f"{name:40s} {best*1e3:9.2f} ms  -> {per*1e9:10.2f} ns/{unit}"
          f"  ({scale/best/1e6:9.2f} M{unit}/s)")
    return per


def main():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.integers(0, 4096, size=(22, BATCH), dtype=np.uint32))
    b = jnp.asarray(rng.integers(0, 4096, size=(22, BATCH), dtype=np.uint32))

    # --- field ops (per-lane cost) --------------------------------------
    @jax.jit
    def chain_mul(x, y):
        def body(i, x):
            return fe.mul(x, y)
        return jax.lax.fori_loop(0, STEPS, body, x)

    @jax.jit
    def chain_sqr(x):
        def body(i, x):
            return fe.sqr(x)
        return jax.lax.fori_loop(0, STEPS, body, x)

    bench("field mul (22x12b, B=4096)", chain_mul, a, b,
          scale=STEPS * BATCH, unit="mul/lane")
    bench("field sqr", chain_sqr, a, scale=STEPS * BATCH, unit="sqr/lane")

    # --- point double chain --------------------------------------------
    p = cv.Point(a, b, fe.ones((BATCH,)), fe.zeros((BATCH,)))

    @jax.jit
    def chain_double(pt):
        def body(i, q):
            return cv.double(q)
        return jax.lax.fori_loop(0, STEPS, body, pt)

    bench("point double", chain_double, p, scale=STEPS * BATCH,
          unit="dbl/lane")

    # --- raw VPU rates --------------------------------------------------
    N = 22 * 44 * BATCH  # comparable footprint to one conv
    xi = jnp.asarray(rng.integers(1, 1 << 12, size=(N,), dtype=np.uint32))
    xf = xi.astype(jnp.float32)

    @jax.jit
    def chain_i32(x):
        def body(i, x):
            return x * x + jnp.uint32(12345)
        return jax.lax.fori_loop(0, STEPS, body, x)

    @jax.jit
    def chain_f32(x):
        def body(i, x):
            return x * x + jnp.float32(1.5)
        return jax.lax.fori_loop(0, STEPS, body, x)

    @jax.jit
    def chain_addshift(x):
        def body(i, x):
            return (x >> 12) + (x & jnp.uint32(0xFFF))
        return jax.lax.fori_loop(0, STEPS, body, x)

    bench("raw i32 mul+add (fused elementwise)", chain_i32, xi,
          scale=STEPS * N, unit="i32-fma")
    bench("raw f32 mul+add", chain_f32, xf, scale=STEPS * N, unit="f32-fma")
    bench("raw shift+mask+add", chain_addshift, xi,
          scale=STEPS * N, unit="i32-3op")

    # --- MXU rates ------------------------------------------------------
    mi = jnp.asarray(rng.integers(-64, 64, size=(BATCH, 128), dtype=np.int8))
    wi = jnp.asarray(rng.integers(-64, 64, size=(128, 128), dtype=np.int8))

    @jax.jit
    def chain_mm_i8(x, w):
        def body(i, acc):
            y = jax.lax.dot_general(
                x, w, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            return acc + jnp.sum(y)
        return jax.lax.fori_loop(0, STEPS, body, jnp.int32(0))

    mb = mi.astype(jnp.bfloat16)
    wb = wi.astype(jnp.bfloat16)

    @jax.jit
    def chain_mm_bf16(x, w):
        def body(i, acc):
            y = jax.lax.dot_general(
                x, w, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return acc + jnp.sum(y)
        return jax.lax.fori_loop(0, STEPS, body, jnp.float32(0))

    macs = STEPS * BATCH * 128 * 128
    bench("int8 matmul (4096x128)@(128x128)", chain_mm_i8, mi, wi,
          scale=macs, unit="MAC")
    bench("bf16 matmul (4096x128)@(128x128)", chain_mm_bf16, mb, wb,
          scale=macs, unit="MAC")

    # larger contraction: (4096x512)@(512x512)
    mi2 = jnp.asarray(rng.integers(-64, 64, size=(BATCH, 512), dtype=np.int8))
    wi2 = jnp.asarray(rng.integers(-64, 64, size=(512, 512), dtype=np.int8))
    bench("int8 matmul (4096x512)@(512x512)", chain_mm_i8, mi2, wi2,
          scale=STEPS * BATCH * 512 * 512, unit="MAC")


if __name__ == "__main__":
    main()
