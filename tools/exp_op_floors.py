"""Measure per-op floors on the live TPU via SLOPE timing.

Single timings here are poisoned by (a) the ~100 ms tunnel round trip and
(b) per-loop-iteration overheads on the remote backend.  Every rate below
is therefore a SLOPE: run the same chained graph at two step counts and
divide the time difference by the step difference — RTT and dispatch
overheads cancel; per-iteration while-loop cost stays in (the real
workload pays it too).  Loop bodies are made fat (several ops per
iteration) so iteration overhead doesn't dominate the quantity measured.

Measurement rules per project memory: np.asarray() is the only true sync;
same process for every comparison.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
from _bench import DISPATCH, slope, timed  # noqa: E402,F401

from firedancer_tpu.ops import curve25519 as cv
from firedancer_tpu.ops import f25519 as fe

BATCH = 4096








def main():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.integers(0, 4096, size=(22, BATCH), dtype=np.uint32))
    b = jnp.asarray(rng.integers(0, 4096, size=(22, BATCH), dtype=np.uint32))

    # --- field ops: per-lane cost -----------------------------------
    def mk_mul(steps):
        @jax.jit
        def f(x, y):
            def body(i, x):
                return fe.mul(x, y)
            return jax.lax.fori_loop(0, steps, body, x)
        return f, (a, b)

    def mk_sqr(steps):
        @jax.jit
        def f(x):
            def body(i, x):
                return fe.sqr(x)
            return jax.lax.fori_loop(0, steps, body, x)
        return f, (a,)

    slope("field mul (22x12b limbs)", mk_mul, 2048, 6144, BATCH, "mul/lane")
    slope("field sqr", mk_sqr, 2048, 6144, BATCH, "sqr/lane")

    p = cv.Point(a, b, fe.ones((BATCH,)), fe.zeros((BATCH,)))

    def mk_dbl(steps):
        @jax.jit
        def f(pt):
            def body(i, q):
                return cv.double(q)
            return jax.lax.fori_loop(0, steps, body, pt)
        return f, (p,)

    slope("point double", mk_dbl, 512, 1536, BATCH, "dbl/lane")

    # --- raw VPU rates: fat body (32 fma per iteration) -------------
    N = 22 * BATCH
    xi = jnp.asarray(rng.integers(1, 1 << 12, size=(N,), dtype=np.uint32))
    xf = xi.astype(jnp.float32)

    def mk_i32(steps):
        @jax.jit
        def f(x):
            def body(i, x):
                for _ in range(32):
                    x = x * x + jnp.uint32(12345)
                return x
            return jax.lax.fori_loop(0, steps, body, x)
        return f, (xi,)

    def mk_f32(steps):
        @jax.jit
        def f(x):
            def body(i, x):
                for _ in range(32):
                    x = x * x + jnp.float32(1.5)
                return x
            return jax.lax.fori_loop(0, steps, body, x)
        return f, (xf,)

    slope("raw i32 fma (32/iter, 90K elems)", mk_i32, 2048, 6144, 32 * N,
          "i32-fma")
    slope("raw f32 fma", mk_f32, 2048, 6144, 32 * N, "f32-fma")

    # --- MXU rates: 8 matmuls per iteration -------------------------
    mi = jnp.asarray(rng.integers(-64, 64, size=(BATCH, 128), dtype=np.int8))
    wi = jnp.asarray(rng.integers(-64, 64, size=(128, 128), dtype=np.int8))

    def mk_mm(steps):
        @jax.jit
        def f(x, w):
            def body(i, acc):
                s = jnp.int32(0)
                for _ in range(8):
                    y = jax.lax.dot_general(
                        x, w, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.int32)
                    s = s + jnp.sum(y)
                return acc + s
            return jax.lax.fori_loop(0, steps, body, jnp.int32(0))
        return f, (mi, wi)

    slope("int8 matmul (4096x128)@(128x128)", mk_mm, 2048, 8192,
          8 * BATCH * 128 * 128, "MAC")

    mi2 = jnp.asarray(rng.integers(-64, 64, size=(BATCH, 512), dtype=np.int8))
    wi2 = jnp.asarray(rng.integers(-64, 64, size=(512, 512), dtype=np.int8))

    def mk_mm2(steps):
        @jax.jit
        def f(x, w):
            def body(i, acc):
                s = jnp.int32(0)
                for _ in range(8):
                    y = jax.lax.dot_general(
                        x, w, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.int32)
                    s = s + jnp.sum(y)
                return acc + s
            return jax.lax.fori_loop(0, steps, body, jnp.int32(0))
        return f, (mi2, wi2)

    slope("int8 matmul (4096x512)@(512x512)", mk_mm2, 512, 2048,
          8 * BATCH * 512 * 512, "MAC")

    # --- the VERDICT-suggested mapping: per-lane banded matvec ------
    # c[n] = M_b[n] @ a[n], batched (44x22)@(22).  Measured WITHOUT the
    # band-matrix build cost (generous); 4 matvecs per iteration.
    Mb = jnp.asarray(rng.integers(0, 1 << 12, size=(BATCH, 44, 22),
                                  dtype=np.int32))
    av = jnp.asarray(rng.integers(0, 1 << 12, size=(BATCH, 22),
                                  dtype=np.int32))

    def mk_bmv(steps):
        @jax.jit
        def f(M, v):
            def body(i, acc):
                s = jnp.int32(0)
                for _ in range(4):
                    c = jax.lax.dot_general(
                        M, v, (((2,), (1,)), ((0,), (0,))),
                        preferred_element_type=jnp.int32)
                    s = s + jnp.sum(c)
                return acc + s
            return jax.lax.fori_loop(0, steps, body, jnp.int32(0))
        return f, (Mb, av)

    slope("batched matvec (B,44,22)@(B,22) i32", mk_bmv, 512, 1536,
          4 * BATCH, "fieldmul-equiv")


if __name__ == "__main__":
    main()
