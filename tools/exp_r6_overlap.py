"""Round-6 ingest-overlap A/B: serial fetch-per-batch fresh ingest vs
the double-buffered PackedIngest engine (nbuf rotating blobs, depth
dispatch-ahead), SAME session, median of reps.

Arms:
  serial     pack -> device_put -> dispatch -> np.asarray PER BATCH
             (upload, verify, and verdict fetch fully serialized — the
             pre-r5 shape of measure_throughput_fresh's failure mode)
  pipelined  pack -> device_put -> dispatch per batch, ONE draining
             fetch at the end (the r5 fresh loop: the in-order queue
             pipelines uploads against compute but the host still packs
             in the gaps)
  overlap    PackedIngest submit() loop + drain(): rotation + bounded
             window + verdict retirement per batch (batch k+1 packs and
             uploads while batch k verifies; verdicts stream back)

The acceptance bar (ISSUE r6) compares overlap vs serial: >= 1.2x.
Run on the driver chip for the recorded verdict; CPU runs are labelled
by the printed backend and measure the architecture, not the tunnel.

Env: B=batch (32768), ITERS (8), REPS (5), NBUF (3), DEPTH (2).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def main():
    from firedancer_tpu.utils import xla_cache
    xla_cache.enable()
    import jax

    from firedancer_tpu.models.verifier import (
        SigVerifier,
        VerifierConfig,
        make_example_batch,
    )

    batch = int(os.environ.get("B", 32768))
    iters = int(os.environ.get("ITERS", 8))
    reps = int(os.environ.get("REPS", 5))
    nbuf = int(os.environ.get("NBUF", 3))
    depth = int(os.environ.get("DEPTH", 2))

    v = SigVerifier(VerifierConfig(batch=batch, msg_maxlen=128))
    args = [np.asarray(a) for a in
            make_example_batch(batch, 128, valid=True, sign_pool=64)]
    ml = int(args[1].max())

    ref = np.asarray(v.packed_dispatch(*args, ml=ml))  # warm + reference
    assert ref.all()

    def run_serial():
        t0 = time.perf_counter()
        for _ in range(iters):
            ok = np.asarray(v.packed_dispatch(*args, ml=ml))
        assert ok.all()
        return batch * iters / (time.perf_counter() - t0)

    def run_pipelined():
        t0 = time.perf_counter()
        ok = None
        for _ in range(iters):
            ok = v.packed_dispatch(*args, ml=ml)
        ok = np.asarray(ok)
        assert ok.all()
        return batch * iters / (time.perf_counter() - t0)

    def run_overlap():
        eng = v.make_ingest(ml=ml, nbuf=nbuf, depth=depth)
        eng.submit(*args)
        eng.drain()                     # warm the engine path
        t0 = time.perf_counter()
        outs = []
        for _ in range(iters):
            outs += eng.submit(*args)
        outs += eng.drain()
        dt = time.perf_counter() - t0
        assert len(outs) == iters and all(o.all() for o in outs)
        return batch * iters / dt

    arms = {"serial": run_serial, "pipelined": run_pipelined,
            "overlap": run_overlap}
    out = {"batch": batch, "iters": iters, "reps": reps,
           "nbuf": nbuf, "depth": depth,
           "backend": jax.devices()[0].platform}
    for name, fn in arms.items():
        fn()  # per-arm warm rep (jit identity is shared; cheap)
        runs = [fn() for _ in range(reps)]
        out[name] = round(median(runs), 1)
        out[name + "_runs"] = [round(r, 1) for r in sorted(runs)]
        print(f"{name}: {out[name]:,.0f} v/s  {out[name + '_runs']}",
              file=sys.stderr)
    out["overlap_vs_serial"] = round(out["overlap"] / out["serial"], 3)
    out["overlap_vs_pipelined"] = round(
        out["overlap"] / out["pipelined"], 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
