"""CI chaos smoke: the self-healing topology tentpole, end to end.

Three scenarios, all deterministic (fixed seeds, counter-driven faults):

  1. dead-consumer eviction (tango level) — a producer pinned at zero
     credits by a dead reliable consumer's frozen fseq resumes publishing
     once the supervisor-side eviction fast-forwards the line.
  2. device-loss degradation (in-process) — a GuardedVerifier over a real
     CPU SigVerifier rides injected dispatch failures into degraded mode,
     serves bit-identical verdicts off the host ed25519 fallback, and
     recovers through a reprobe once the fault clears.
  3. kill -> respawn (multi-process) — FDTPU_FAULTS hard-kills the verify
     tile mid-stream (os._exit, SIGKILL-grade); the respawn-policy
     supervisor restarts it with backoff into the live workspace.  Gates:
     /healthz returns to 200, the source finishes its full count
     (producers unstalled past the outage), verdicts flow to the sink,
     and the dedup tile sees ZERO duplicate verdicts (the respawned mux
     resumed from the evicted fseq cursor, nothing re-verified).

Four extra scenario packs ride behind flags: `--wire` (front-door DoS
hardening against a live QUIC topology), `--autotune` (the closed-loop
autotuner: modeled convergence/load-step/slow-consumer/poison-revert
plants plus live shm knob actuation), `--drain` (zero-loss rolling
tile restart under live load + forced drain-timeout fallback), and
`--shred` (turbine erasure storm through the batched FEC recover lane
plus a dup/forge burst against batched leader-sig admission), and
`--leader` (rolling-restart the pack tile mid-slot: exactly-once
microblock mixins across the outage + the device PoH chain re-verifies).

A real file (not a ci.sh heredoc): tile processes use the 'spawn' start
method, which re-imports __main__ from its path.

Usage:  JAX_PLATFORMS=cpu python tools/chaos_smoke.py
        [--wire|--autotune|--drain|--shred|--leader|--fleet]
"""

import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def evict_smoke() -> None:
    from firedancer_tpu.disco import topo as topo_mod
    from firedancer_tpu.disco.topo import TopoBuilder
    from firedancer_tpu.tango.fctl import Fctl

    depth = 64
    spec = (
        TopoBuilder(f"chaosev{os.getpid()}", wksp_mb=8)
        .link("a_b", depth=depth, mtu=256)
        .tile("src", "sink", outs=["a_b"])
        .tile("dst", "sink", ins=["a_b"])
        .build()
    )
    jt = topo_mod.create(spec)
    try:
        mc = jt.links["a_b"].mcache
        fseq = jt.fseq[("dst", "a_b")]
        fctl = Fctl(cr_max=depth).rx_add(fseq)
        seq = mc.seq0()
        fseq.update(seq)                     # consumer joined ... and died
        sent = 0
        while fctl.consume(1):               # runs the ring dry: the dead
            mc.publish(sent)                 # fseq never advances
            seq += 1
            sent += 1
            fctl.tx_cr_update(seq)
        assert sent == depth, f"expected {depth} credits, spent {sent}"
        assert fctl.cr_query(seq) == 0, "producer must be pinned at zero"

        cur = Fctl.evict_dead_consumer(fseq, mc)   # the supervisor's move
        assert cur == seq and fseq.query() == seq
        assert fctl.cr_query(seq) == depth, "eviction must refill credits"
        for _ in range(depth // 2):          # and the producer flows again
            assert fctl.tx_cr_update(seq) > 0 and fctl.consume(1)
            mc.publish(sent)
            seq += 1
            sent += 1
    finally:
        jt.close()
        jt.unlink()
    print(f"chaos evict ok: producer unpinned after eviction "
          f"({sent} frags published across a dead consumer)")


def degrade_smoke() -> None:
    from firedancer_tpu.disco import faultinject
    from firedancer_tpu.disco.pipeline import GuardedVerifier
    from firedancer_tpu.models.verifier import (SigVerifier, VerifierConfig,
                                                make_example_batch)

    B, ml = 64, 96
    sv = SigVerifier(VerifierConfig(batch=B, msg_maxlen=ml))
    msgs, lens, sigs, pubs = (np.asarray(a).copy() for a in make_example_batch(
        B, ml, valid=True, sign_pool=8, seed=21))
    sigs[3, 10] ^= 0x40                      # mixed verdicts, or the test
    pubs[17, 0] ^= 0x02                      # proves nothing
    ref = np.asarray(sv(msgs, lens, sigs, pubs)).astype(bool)
    assert ref.any() and not ref.all()

    fault = faultinject.FaultInjector("verify:0", {"fail_dispatch_n": 3})
    g = GuardedVerifier(sv, fail_threshold=2, retries=0, reprobe_s=0.0,
                        fault=fault)
    for i in range(3):                       # persistent injected failure
        ok = np.asarray(g(msgs, lens, sigs, pubs))
        assert np.array_equal(ok, ref), \
            f"fallback verdict diverged on batch {i}"
    assert g.degraded, "threshold must flip degraded mode on"
    assert g.fallback_lanes == 3 * B

    ok = np.asarray(g(msgs, lens, sigs, pubs))   # fault spent: reprobe heals
    assert np.array_equal(ok, ref)
    assert not g.degraded and g.reprobe_cnt >= 1
    ok = np.asarray(g(msgs, lens, sigs, pubs))   # device path serving again
    assert np.array_equal(ok, ref)
    assert g.fallback_lanes == 3 * B
    print(f"chaos degrade ok: {g.device_fail_cnt} injected failures -> CPU "
          f"fallback bit-identical ({int(ref.sum())}/{B} pass), device "
          "recovered via reprobe")


def kill_respawn_smoke() -> None:
    import shutil
    import tempfile

    from firedancer_tpu.app import config as config_mod
    from firedancer_tpu.disco import flightrec
    from firedancer_tpu.disco.run import SupervisionPolicy, TopoRun
    from firedancer_tpu.utils import aot

    batch, maxlen = 64, 256
    aot_dir = os.environ.get("FDTPU_CI_AOT_DIR", "/tmp/fdtpu_aot_ci")
    if aot.ensure_verify(aot_dir, batch, maxlen) is None:
        print("chaos kill-respawn SKIPPED: AOT unusable on this backend")
        return

    # enough txns that the source MUST outlive the verify outage: the
    # src_verify ring is 4096 deep, the kill lands ~frag 150, so without
    # dead-consumer eviction the source wedges around txn 4246
    n_txn = 5000
    cfg = config_mod.load(None)
    cfg["name"] = "fdtpu_ci_chaos"
    cfg["topology"] = "verify-bench"
    cfg["layout"]["verify_tile_count"] = 1
    cfg["development"]["source_count"] = n_txn
    cfg["tiles"]["verify"]["batch"] = batch
    cfg["tiles"]["verify"]["msg_maxlen"] = maxlen
    cfg["tiles"]["verify"]["aot_dir"] = aot_dir
    cfg["tiles"]["verify"]["aot_require"] = 1
    cfg["supervision"] = dict(cfg.get("supervision") or {},
                              restart_policy="respawn", max_restarts=3,
                              backoff_initial_s=0.2, backoff_max_s=1.0)
    policy = SupervisionPolicy.from_cfg(cfg)
    spec = config_mod.build_topology(cfg)

    # generation-gated kill: incarnation 0 dies at the 150th-frag
    # boundary (the prefix is processed + span-recorded, the 150th is
    # never processed); the respawn runs fault-free.
    # flight_dir arms the flight recorder: the respawn must leave a
    # postmortem bundle behind before the corpse's rings are reused.
    flight_dir = tempfile.mkdtemp(prefix="fdtpu_ci_flight_")
    os.environ["FDTPU_FAULTS"] = "verify:0=kill_after_frags:150,boot:0"
    run = TopoRun(spec, metrics_port=0, policy=policy,
                  flight_dir=flight_dir, config=cfg)
    try:
        run.wait_ready(timeout=300)
        sup = threading.Thread(target=run.supervise, kwargs={"poll_s": 0.05},
                               daemon=True)
        sup.start()
        base = f"http://127.0.0.1:{run.metrics_port}"

        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if (run.restarts.get("verify:0", 0) >= 1
                    and run.metrics("source")["txn_gen_cnt"] >= n_txn
                    and run.metrics("sink")["frag_cnt"] > 0):
                break
            time.sleep(0.2)
        restarts = run.restarts.get("verify:0", 0)
        src = run.metrics("source")
        snk = run.metrics("sink")
        ddp = run.metrics("dedup")
        assert restarts >= 1, "verify tile was never killed/respawned"
        assert src["txn_gen_cnt"] >= n_txn, \
            f"source wedged at {src['txn_gen_cnt']}/{n_txn}: producers " \
            "did not unstall across the outage"
        assert snk["frag_cnt"] > 0, "no verdicts reached the sink"
        assert ddp["dup_drop_cnt"] == 0, \
            f"{ddp['dup_drop_cnt']} duplicate verdicts: the respawned mux " \
            "re-processed acked frags"

        # /healthz back to 200 within the backoff budget
        hz_deadline = time.monotonic() + 120
        status = None
        while time.monotonic() < hz_deadline:
            try:
                r = urllib.request.urlopen(f"{base}/healthz", timeout=5)
                status = r.status
                if status == 200:
                    break
            except urllib.error.HTTPError as e:
                status = e.code
            time.sleep(0.2)
        assert status == 200, f"/healthz stuck at {status} post-respawn"

        # flight recorder: the respawn left a loadable postmortem bundle
        # holding the dead incarnation's final spans
        bundles = [os.path.join(flight_dir, d)
                   for d in sorted(os.listdir(flight_dir))
                   if "-respawn-" in d]
        assert bundles, f"no respawn bundle in {flight_dir}"
        b = flightrec.load_bundle(bundles[0])
        assert b["manifest"]["reason"] == "respawn"
        assert b["manifest"]["tile"] == "verify:0"
        dead_spans = b["spans"].get("verify:0")
        assert dead_spans is not None and len(dead_spans), \
            "bundle lost the dead tile's final spans"
        assert any("tile verify:0 died; respawn" in ev
                   for ev in b["events"]), \
            f"supervisor event log missing the respawn: {b['events']}"
        rendered = flightrec.render_bundle(bundles[0])
        assert "bottleneck at death:" in rendered
        assert "final spans of verify:0:" in rendered
    finally:
        os.environ.pop("FDTPU_FAULTS", None)
        run.halt()           # stops the supervise thread too (_halting)
        sup.join(15)
        run.close()
        shutil.rmtree(flight_dir, ignore_errors=True)
    print(f"chaos kill-respawn ok: verify:0 respawned {restarts}x, source "
          f"finished {src['txn_gen_cnt']}/{n_txn}, sink got "
          f"{snk['frag_cnt']} verdict frags, 0 duplicate verdicts, "
          f"/healthz 200, {len(bundles)} flight bundle(s) with "
          "the dead tile's final spans")


# --------------------------------------------------------------------------
# drain chaos (--drain): the zero-loss rolling-restart tentpole, end to
# end against a LIVE verify-bench topology.
#
#   1. rolling restart under live load — the verify tile is drained
#      (DRAIN -> catch-up -> flush -> manifest -> DRAINED), reaped, and
#      respawned with CHANGED restart-required knobs (n_buffers,
#      max_inflight); gates: the source finishes its full count (peers
#      stalled at most the bounded drain+boot window, credit park not
#      eviction), the sink sees EVERY verdict exactly once (zero lost,
#      zero duplicate), and the cursor manifest landed.
#   2. forced drain timeout — a zero budget degrades the rolling restart
#      to today's crash-respawn semantics: flight bundle (loadable, named
#      drain-timeout), eviction-based respawn, topology recovers.


def drain_rolling_restart_smoke() -> None:
    import json
    import shutil
    import tempfile

    from firedancer_tpu.app import config as config_mod
    from firedancer_tpu.disco.run import SupervisionPolicy, TopoRun
    from firedancer_tpu.utils import aot

    batch, maxlen = 64, 256
    aot_dir = os.environ.get("FDTPU_CI_AOT_DIR", "/tmp/fdtpu_aot_ci")
    if aot.ensure_verify(aot_dir, batch, maxlen) is None:
        print("chaos drain-restart SKIPPED: AOT unusable on this backend")
        return

    n_txn = 5000
    man_dir = tempfile.mkdtemp(prefix="fdtpu_ci_drainman_")
    flight_dir = tempfile.mkdtemp(prefix="fdtpu_ci_drainfl_")
    cfg = config_mod.load(None)
    cfg["name"] = "fdtpu_ci_drain"
    cfg["topology"] = "verify-bench"
    cfg["layout"]["verify_tile_count"] = 1
    cfg["development"]["source_count"] = n_txn
    cfg["tiles"]["verify"]["batch"] = batch
    cfg["tiles"]["verify"]["msg_maxlen"] = maxlen
    cfg["tiles"]["verify"]["aot_dir"] = aot_dir
    cfg["tiles"]["verify"]["aot_require"] = 1
    cfg["supervision"] = dict(cfg.get("supervision") or {},
                              restart_policy="respawn", max_restarts=3,
                              backoff_initial_s=0.2, backoff_max_s=1.0,
                              drain_timeout_s=60.0,
                              drain_manifest_dir=man_dir)
    policy = SupervisionPolicy.from_cfg(cfg)
    spec = config_mod.build_topology(cfg)
    run = TopoRun(spec, metrics_port=0, policy=policy,
                  flight_dir=flight_dir, config=cfg)
    try:
        run.wait_ready(timeout=300)
        sup = threading.Thread(target=run.supervise, kwargs={"poll_s": 0.05},
                               daemon=True)
        sup.start()

        # live load first: restart only once verdicts are flowing
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if run.metrics("sink")["frag_cnt"] >= 200:
                break
            time.sleep(0.05)
        assert run.metrics("sink")["frag_cnt"] >= 200, \
            "no live load to restart under"

        nb_old = int(run.jt.tile_spec("verify:0").cfg.get("n_buffers", 3))
        t0 = time.monotonic()
        ok = run.rolling_restart("verify:0",
                                 {"n_buffers": nb_old + 1, "max_inflight": 6})
        gap_s = time.monotonic() - t0
        assert ok, "graceful rolling restart fell back to crash semantics"
        assert gap_s < policy.drain_timeout_s + 30, \
            f"restart window {gap_s:.1f}s blew the bounded-stall budget"
        assert run.restarts.get("verify:0", 0) == 1
        ts = run.jt.tile_spec("verify:0")
        assert ts.cfg["n_buffers"] == nb_old + 1
        assert ts.cfg["max_inflight"] == 6

        # the drained incarnation's cursor manifest landed
        man_path = os.path.join(man_dir, "verify_0.manifest.json")
        assert os.path.exists(man_path), f"no manifest in {man_dir}"
        with open(man_path) as f:
            man = json.load(f)
        assert man["tile"] == "verify:0" and man["cursors"], man

        # zero loss + zero duplicates: the source finishes (peers were
        # credit-parked, never starved out) and EVERY generated txn's
        # verdict reaches the sink exactly once across both incarnations
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            src = run.metrics("source")
            snk = run.metrics("sink")
            if (src["txn_gen_cnt"] >= n_txn
                    and snk["frag_cnt"] >= src["out_frag_cnt"]):
                break
            time.sleep(0.2)
        src = run.metrics("source")
        snk = run.metrics("sink")
        ddp = run.metrics("dedup")
        assert src["txn_gen_cnt"] >= n_txn, \
            f"source wedged at {src['txn_gen_cnt']}/{n_txn}: peers " \
            "stalled past the drain window"
        assert ddp["dup_drop_cnt"] == 0, \
            f"{ddp['dup_drop_cnt']} duplicate verdicts across the restart"
        assert snk["frag_cnt"] == src["out_frag_cnt"], \
            f"lost verdicts: sink {snk['frag_cnt']} != " \
            f"published {src['out_frag_cnt']}"
        vm = run.metrics("verify:0")
        assert vm["drain_cnt"] >= 1, "the drain state machine never ran"

        # graceful whole-topology shutdown: dependency-ordered quiesce,
        # exiting with all accepted txns verdicted
        assert run.drain() is True, "topology drain timed out"
        sup.join(15)
    finally:
        run.halt()
        run.close()
        shutil.rmtree(man_dir, ignore_errors=True)
        shutil.rmtree(flight_dir, ignore_errors=True)
    print(f"chaos drain-restart ok: verify:0 rolling-restarted in "
          f"{gap_s:.1f}s (n_buffers {nb_old}->{nb_old + 1}, max_inflight 6)"
          f", source {src['txn_gen_cnt']}/{n_txn}, sink "
          f"{snk['frag_cnt']}=={src['out_frag_cnt']} published verdicts, "
          "0 dups, manifest + graceful topology drain clean")


def drain_timeout_fallback_smoke() -> None:
    import shutil
    import tempfile

    from firedancer_tpu.app import config as config_mod
    from firedancer_tpu.disco import flightrec
    from firedancer_tpu.disco.run import SupervisionPolicy, TopoRun
    from firedancer_tpu.utils import aot

    batch, maxlen = 64, 256
    aot_dir = os.environ.get("FDTPU_CI_AOT_DIR", "/tmp/fdtpu_aot_ci")
    if aot.ensure_verify(aot_dir, batch, maxlen) is None:
        print("chaos drain-timeout SKIPPED: AOT unusable on this backend")
        return

    n_txn = 3000
    flight_dir = tempfile.mkdtemp(prefix="fdtpu_ci_drainto_")
    cfg = config_mod.load(None)
    cfg["name"] = "fdtpu_ci_drto"
    cfg["topology"] = "verify-bench"
    cfg["layout"]["verify_tile_count"] = 1
    cfg["development"]["source_count"] = n_txn
    cfg["tiles"]["verify"]["batch"] = batch
    cfg["tiles"]["verify"]["msg_maxlen"] = maxlen
    cfg["tiles"]["verify"]["aot_dir"] = aot_dir
    cfg["tiles"]["verify"]["aot_require"] = 1
    cfg["supervision"] = dict(cfg.get("supervision") or {},
                              restart_policy="respawn", max_restarts=3,
                              backoff_initial_s=0.2, backoff_max_s=1.0,
                              drain_timeout_s=30.0)
    policy = SupervisionPolicy.from_cfg(cfg)
    spec = config_mod.build_topology(cfg)
    run = TopoRun(spec, metrics_port=0, policy=policy,
                  flight_dir=flight_dir, config=cfg)
    try:
        run.wait_ready(timeout=300)
        sup = threading.Thread(target=run.supervise, kwargs={"poll_s": 0.05},
                               daemon=True)
        sup.start()

        # a zero drain budget can never see the DRAINED ack: the rolling
        # restart must degrade to crash-respawn semantics — bundle first,
        # then eviction-based respawn — and NEVER hang
        t0 = time.monotonic()
        ok = run.rolling_restart("verify:0", {"n_buffers": 4},
                                 drain_timeout_s=0.0)
        assert not ok, "a 0s budget cannot drain gracefully"
        assert time.monotonic() - t0 < 30, "timeout fallback hung"
        assert run.restarts.get("verify:0", 0) >= 1

        # the forced timeout left a LOADABLE drain-timeout flight bundle
        bundles = [os.path.join(flight_dir, d)
                   for d in sorted(os.listdir(flight_dir))
                   if "-drain-timeout-" in d]
        assert bundles, f"no drain-timeout bundle in {flight_dir}"
        b = flightrec.load_bundle(bundles[0])
        assert b["manifest"]["reason"] == "drain-timeout"
        assert b["manifest"]["tile"] == "verify:0"
        assert any("drain" in ev for ev in b["events"]), b["events"]
        rendered = flightrec.render_bundle(bundles[0])
        assert "bottleneck at death:" in rendered

        # and the topology recovers: source finishes, verdicts flow
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if (run.metrics("source")["txn_gen_cnt"] >= n_txn
                    and run.metrics("sink")["frag_cnt"] > 0):
                break
            time.sleep(0.2)
        src = run.metrics("source")
        assert src["txn_gen_cnt"] >= n_txn, \
            f"source wedged at {src['txn_gen_cnt']}/{n_txn} post-fallback"
        assert run.metrics("sink")["frag_cnt"] > 0
        assert run.metrics("dedup")["dup_drop_cnt"] == 0
    finally:
        run.halt()
        sup.join(15)
        run.close()
        shutil.rmtree(flight_dir, ignore_errors=True)
    print(f"chaos drain-timeout ok: 0s budget degraded to respawn "
          f"(gen {run.restarts.get('verify:0', 0)}), loadable "
          f"drain-timeout bundle, source {src['txn_gen_cnt']}/{n_txn} "
          "recovered, 0 dups")


# --------------------------------------------------------------------------
# autotune chaos (--autotune): the closed-loop autotuner tentpole.
# Part B scenarios drive the POLICY against deterministic modeled plants
# (the same convention as the latency smoke's modeled verifier — this box
# can't meet a 2 ms SLO with real crypto, but the control loop's
# convergence, safety clamps, and do-no-harm revert are exactly
# reproducible).  Part A runs the loop against a LIVE verify-bench
# topology and proves the shm actuation path end to end.


def autotune_converge_smoke() -> None:
    """Mis-tuned flush age -> the loop walks it down within clamps and
    converges; a 2x load step knocks it out of convergence and the loop
    re-converges.  Deterministic modeled plant: burn is a pure function
    of the flush-age knob."""
    from firedancer_tpu.disco.autotune import KNOB_SPECS, Autotuner

    state = {"flush": 1.6e9, "load": 1.0}

    def sense(tn):
        burn = min(max((state["flush"] * state["load"] - 2.0e8) / 1.4e9,
                       0.0), 1.0)
        return {"burn": burn, "trend": "flat", "n": 64,
                "bottleneck": "src_verify|verify:0", "reason": "",
                "shedding": False}

    def apply(tile, knob, value):
        if knob == "flush_age_ns":
            state["flush"] = value

    tn = Autotuner(None, {"enabled": 1, "cooldown_periods": 0},
                   target_ms=2.0,
                   tiles=[("verify:0", "verify",
                           {"flush_age_ns": 1.6e9, "batch": 64})],
                   sense_fn=sense, apply_fn=apply)
    for _ in range(12):
        tn.step()
    assert tn.converged_at is not None, \
        f"never converged: flush={state['flush']}, burn history in " \
        f"{[d['burn'] for d in tn.decisions]}"
    first_converge = tn.converged_at
    assert tn.converge_s > 0
    assert state["flush"] <= 8.0e8, f"flush barely moved: {state['flush']}"
    assert tn.revert_cnt == 0

    state["load"] = 2.0          # load step: same knobs now burn hot
    for _ in range(14):
        tn.step()
    assert tn.converged_at is not None and tn.converged_at > first_converge, \
        f"no re-convergence after load step (converged_at=" \
        f"{tn.converged_at}, first={first_converge})"
    # safety: every decision and every live value inside its clamp
    for d in tn.decisions:
        if d["knob"] in KNOB_SPECS and d["new"] is not None:
            _, lo, hi, _, _, _ = KNOB_SPECS[d["knob"]]
            assert lo <= float(d["new"]) <= hi, f"clamp breach: {d}"
    for (tile, knob), v in tn.current.items():
        _, lo, hi, _, _, _ = KNOB_SPECS[knob]
        assert lo <= v <= hi, f"clamp breach live: {tile}.{knob}={v}"
    print(f"chaos autotune-converge ok: converged at period "
          f"{first_converge}, re-converged at {tn.converged_at} after a "
          f"2x load step, {tn.decision_cnt} decisions, 0 reverts, "
          f"flush {state['flush']:.0f} ns, all moves inside clamps")


def autotune_slow_consumer_smoke() -> None:
    """A slow-consumer attribution verdict deepens the verify
    dispatch-ahead window until the consumer keeps up; the verdict
    clears and the loop rests converged."""
    from firedancer_tpu.disco.autotune import KNOB_SPECS, Autotuner

    state = {"max_inflight": 8.0}

    def sense(tn):
        slow = state["max_inflight"] < 16
        return {"burn": 0.2 if slow else 0.05, "trend": "flat", "n": 32,
                "bottleneck": "verify_dedup|dedup" if slow else "none",
                "reason": ("slow consumer dedup (slow diag fastest)"
                           if slow else ""),
                "shedding": False}

    def apply(tile, knob, value):
        if knob == "max_inflight":
            state["max_inflight"] = value

    tn = Autotuner(None, {"enabled": 1, "cooldown_periods": 0},
                   target_ms=2.0,
                   tiles=[("verify:0", "verify", {}),
                          ("source", "source", {})],
                   sense_fn=sense, apply_fn=apply)
    for _ in range(16):
        tn.step()
    assert state["max_inflight"] >= 16, \
        f"window never deepened past the slow consumer: " \
        f"{state['max_inflight']}"
    assert state["max_inflight"] <= KNOB_SPECS["max_inflight"][2]
    assert tn.converged_at is not None, "loop never rested post-verdict"
    depth_moves = [d for d in tn.decisions
                   if d["rule"] == "slow_consumer_depth"
                   and d["outcome"] == "applied"]
    assert depth_moves, f"depth rule never fired: {tn.decisions}"
    assert all("slow consumer" in d["reason"] for d in depth_moves)
    print(f"chaos autotune-slow-consumer ok: max_inflight 8 -> "
          f"{state['max_inflight']:.0f} across {len(depth_moves)} bounded "
          f"steps, verdict cleared, loop converged at period "
          f"{tn.converged_at}")


def autotune_poison_smoke() -> None:
    """A deliberately inverted rule (the `poison` hook) makes burn WORSE;
    the do-no-harm guard reverts the exact move within two periods and
    quarantines the rule — a wrong rule cannot keep hurting the
    topology."""
    from firedancer_tpu.disco.autotune import Autotuner

    state = {"flush": 1.0e9}

    def sense(tn):
        burn = min(max((state["flush"] - 2.0e8) / 1.4e9, 0.0), 1.0)
        return {"burn": burn, "trend": "flat", "n": 64,
                "bottleneck": "src_verify|verify:0", "reason": "",
                "shedding": False}

    def apply(tile, knob, value):
        if knob == "flush_age_ns":
            state["flush"] = value

    tn = Autotuner(None, {"enabled": 1, "cooldown_periods": 0,
                          "poison": "coalesce_flush"},
                   target_ms=2.0,
                   tiles=[("verify:0", "verify",
                           {"flush_age_ns": 1.0e9})],
                   sense_fn=sense, apply_fn=apply)
    for _ in range(10):
        tn.step()
    assert tn.revert_cnt == 1, \
        f"expected exactly one do-no-harm revert, got {tn.revert_cnt}: " \
        f"{[(d['rule'], d['outcome']) for d in tn.decisions]}"
    assert state["flush"] == 1.0e9, \
        f"revert did not restore the pre-poison value: {state['flush']}"
    poisoned = [d for d in tn.decisions if d["rule"] == "coalesce_flush"]
    assert len(poisoned) == 1 and poisoned[0]["outcome"] == "applied", \
        f"quarantine failed, poisoned rule fired {len(poisoned)}x"
    reverts = [d for d in tn.decisions if d["outcome"] == "reverted"]
    assert len(reverts) == 1 and reverts[0]["rule"] == "do_no_harm"
    assert reverts[0]["new"] == 1.0e9
    print(f"chaos autotune-poison ok: poisoned coalesce_flush raised "
          f"flush to {poisoned[0]['new']:.0f}, do-no-harm reverted it to "
          f"{reverts[0]['new']:.0f} and quarantined the rule "
          f"(fired once in {tn.period} periods)")


def autotune_live_smoke() -> None:
    """The shm actuation path end to end on a LIVE verify-bench topology:
    supervisor-resident loop senses real burn, writes knob pods, the
    tile's mux housekeeping applies them (knob_apply_cnt), the jsonl
    mirror and the flight bundle carry the decision history."""
    import shutil
    import tempfile

    from firedancer_tpu.app import config as config_mod
    from firedancer_tpu.disco import flightrec
    from firedancer_tpu.disco.autotune import KNOB_SPECS, load_decisions
    from firedancer_tpu.disco.run import TopoRun
    from firedancer_tpu.utils import aot

    batch, maxlen = 64, 256
    aot_dir = os.environ.get("FDTPU_CI_AOT_DIR", "/tmp/fdtpu_aot_ci")
    if aot.ensure_verify(aot_dir, batch, maxlen) is None:
        print("chaos autotune-live SKIPPED: AOT unusable on this backend")
        return

    cfg = config_mod.load(None)
    cfg["name"] = "fdtpu_ci_at"
    cfg["topology"] = "verify-bench"
    cfg["layout"]["verify_tile_count"] = 1
    cfg["development"]["source_count"] = 400_000   # outlives the smoke
    cfg["tiles"]["verify"]["batch"] = batch
    cfg["tiles"]["verify"]["msg_maxlen"] = maxlen
    cfg["tiles"]["verify"]["aot_dir"] = aot_dir
    cfg["tiles"]["verify"]["aot_require"] = 1
    # mis-tuned: partial batches age out at 0.9 s against a 2 ms SLO --
    # the loop has real burn to chew on from the first period
    cfg["tiles"]["verify"]["flush_age_ns"] = 900_000_000
    cfg["autotune"] = dict(cfg["autotune"], enabled=1, period_s=0.3,
                           cooldown_periods=1)
    spec = config_mod.build_topology(cfg)

    flight_dir = tempfile.mkdtemp(prefix="fdtpu_ci_at_")
    run = TopoRun(spec, metrics_port=0, flight_dir=flight_dir, config=cfg)
    try:
        run.wait_ready(timeout=300)
        assert run.autotuner is not None and run.autotuner.enabled
        sup = threading.Thread(target=run.supervise, kwargs={"poll_s": 0.05},
                               daemon=True)
        sup.start()

        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            applied = [d for d in run.autotuner.decisions
                       if d["outcome"] == "applied"]
            if (len(applied) >= 2
                    and run.metrics("verify:0")["knob_apply_cnt"] >= 1):
                break
            assert run.poll() is None, "a tile died under autotune"
            time.sleep(0.2)
        tn = run.autotuner
        applied = [d for d in tn.decisions if d["outcome"] == "applied"]
        kac = run.metrics("verify:0")["knob_apply_cnt"]
        assert len(applied) >= 2, \
            f"loop never actuated: {tn.decisions}"
        assert kac >= 1, "pod writes never reached the tile's mux"
        for d in tn.decisions:   # never exceeds clamps, live either
            if d["knob"] in KNOB_SPECS and d["new"] is not None:
                _, lo, hi, _, _, _ = KNOB_SPECS[d["knob"]]
                assert lo <= float(d["new"]) <= hi, f"clamp breach: {d}"

        # decision history: jsonl mirror + flight bundle + rendering
        decs = load_decisions(os.path.join(flight_dir, "autotune.jsonl"))
        assert len(decs) >= len(applied), \
            f"jsonl mirror lost decisions ({len(decs)})"
        bundle = run.flight_dump("autotune-smoke")
        assert bundle, "flight dump failed"
        rendered = flightrec.render_bundle(bundle)
        assert "autotune decision history:" in rendered
        assert "coalesce_flush" in rendered or "lat_deadline" in rendered
    finally:
        run.halt()
        sup.join(15)
        run.close()
        shutil.rmtree(flight_dir, ignore_errors=True)
    print(f"chaos autotune-live ok: {len(applied)} live actuations "
          f"({applied[0]['rule']} first), tile applied {kac} pod "
          f"generation(s), {len(decs)} jsonl decisions, bundle renders "
          "the history")


# --------------------------------------------------------------------------
# wire front-door chaos (--wire): the DoS-hardening tentpole, end to end.
# Three scenarios against a LIVE quic_server -> verify -> dedup -> sink
# topology over loopback; attacks ride secondary loopback source addresses
# (127.0.0.2/127.0.0.3) so per-peer accounting sees distinct peers.


def _wire_spec(tag: str, **qcfg):
    from firedancer_tpu.disco.topo import TopoBuilder

    return (
        TopoBuilder(f"{tag}{os.getpid()}", wksp_mb=16)
        .link("quic_verify", depth=256, mtu=1280)
        .link("verify_dedup", depth=256, mtu=1280)
        .link("dedup_sink", depth=256, mtu=1280)
        .tile("quic_server", "quic_server", outs=["quic_verify"], port=0,
              **qcfg)
        .tile("verify", "verify", ins=["quic_verify"], outs=["verify_dedup"],
              batch=16, msg_maxlen=256, flush_age_ns=50_000_000)
        .tile("dedup", "dedup", ins=["verify_dedup"], outs=["dedup_sink"])
        .tile("sink", "sink", ins=["dedup_sink"])
        .build()
    )


def _make_txns(n: int, keys: int = 4, seed: int = 7) -> list:
    from firedancer_tpu.ballet import txn as txn_lib
    from firedancer_tpu.ops import ed25519 as ed
    rng = np.random.default_rng(seed)
    pool = []
    for _ in range(keys):
        s = rng.bytes(32)
        pub, _, _ = ed.keypair_from_seed(s)
        pool.append((s, pub))
    blockhash, program = rng.bytes(32), rng.bytes(32)
    out = []
    for i in range(n):
        s, pub = pool[i % keys]
        msg = txn_lib.build_unsigned(
            [pub], blockhash, [(1, bytes([0]), i.to_bytes(8, "little"))],
            extra_accounts=[program])
        out.append(txn_lib.assemble([ed.sign(s, msg)], msg))
    return out


def _rss_kb(pid: int) -> int:
    with open(f"/proc/{pid}/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    return 0


class _QuicClient:
    """Live loopback QUIC client (the fdtpudev _quic_firehose shape)."""

    def __init__(self, port: int, bind_ip: str = "127.0.0.1"):
        from firedancer_tpu.waltz.quic import QuicConfig, QuicEndpoint
        from firedancer_tpu.waltz.udpsock import UdpSock
        self.sock = UdpSock(bind_ip=bind_ip, burst=256)
        self.ep = QuicEndpoint(
            QuicConfig(identity_seed=os.urandom(32)), self.sock.aio())
        self.conn = self.ep.connect(("127.0.0.1", int(port)),
                                    now=time.monotonic())

    def pump(self, secs: float = 0.01) -> None:
        deadline = time.monotonic() + secs
        while True:
            now = time.monotonic()
            pkts = self.sock.recv_burst()
            if pkts:
                self.ep.rx(pkts, now)
            self.ep.service(now)
            if now >= deadline:
                return
            time.sleep(0.002)

    def wait_handshake(self, timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        while not self.conn.handshake_done:
            assert time.monotonic() < deadline, "client handshake timed out"
            self.pump(0.01)

    def send_txns(self, txns, timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        sent = 0
        while sent < len(txns):
            assert time.monotonic() < deadline, \
                f"txn send stalled at {sent}/{len(txns)}"
            if self.conn.send_txn(txns[sent]) is None:
                self.pump(0.01)
                continue
            sent += 1
        self.pump(0.05)

    def close(self) -> None:
        self.sock.close()


def _wait_sink(run, want: int, clients=(), timeout: float = 120.0) -> int:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for cl in clients:
            cl.pump(0.01)
        got = run.metrics("sink")["frag_cnt"]
        if got >= want:
            return got
        assert run.poll() is None, "a tile died under attack"
        time.sleep(0.05)
    return run.metrics("sink")["frag_cnt"]


def wire_flood_smoke() -> None:
    """3k-conn handshake flood from ONE source (round 16: the PR-7
    scenario replayed at 10x packet rate — the burst packet-protection
    engine absorbs the AEAD probes): the Retry threshold trips
    (half-opens stay capped), redeemed tokens run into the per-peer conn
    cap, legit txns from a second source keep verifying, quic-tile RSS
    stays bounded (the Initial key-schedule LRU evicts under the
    distinct-dcid churn), /healthz says "shedding", every shed is
    counted, and with the .so present every packet rides the C engine."""
    from firedancer_tpu.disco.faultinject import WireFaultGen
    from firedancer_tpu.disco.run import TopoRun
    from firedancer_tpu.waltz import quic_crypto as _qc
    from firedancer_tpu.waltz.aio import Pkt
    from firedancer_tpu.waltz.udpsock import UdpSock

    n_legit = 24
    have_native = _qc._native_lib() is not None
    spec = _wire_spec("chaoswf", max_conns=64, max_conns_per_peer=8,
                      retry_half_open_threshold=4, idle_timeout=30.0,
                      # require the C engine when it builds: a silent
                      # fallback would invalidate the 10x-rate claim
                      crypto_native=1 if have_native else 0,
                      initial_key_cache=1024)
    txns = _make_txns(n_legit)
    run = TopoRun(spec, metrics_port=0)
    atk = legit = None
    try:
        run.wait_ready(timeout=420)
        port = int(run.metrics("quic_server")["bound_port"])
        rss0 = _rss_kb(run.procs["quic_server"].pid)
        dst = ("127.0.0.1", port)
        g = WireFaultGen(11)
        atk = UdpSock(bind_ip="127.0.0.2", burst=256)

        # phase 1: 3000 token-less AEAD-valid Initials from 127.0.0.2 at
        # 10x the PR-7 wave rate (waves of 500 on the same 2 ms cadence
        # vs the old 50) and 3x the volume — enough distinct dcids to
        # roll the 1024-entry key LRU, sized so a 1-core host still
        # drains the backlog inside the poll deadline.  The first
        # `threshold` become half-open conns, the rest must be answered
        # statelessly with Retry
        retries = []
        flood = g.conn_flood(3000)
        for i in range(0, len(flood), 500):
            atk.send_burst([Pkt(d, dst) for d in flood[i : i + 500]])
            retries.extend(p.payload for p in atk.recv_burst()
                           if p.payload and (p.payload[0] & 0xF0) == 0xF0)
            time.sleep(0.002)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and len(retries) < 8:
            retries.extend(p.payload for p in atk.recv_burst()
                           if p.payload and (p.payload[0] & 0xF0) == 0xF0)
            time.sleep(0.01)
        assert retries, "flood elicited no Retry packets"

        # phase 2: redeem tokens like a validation-completing attacker —
        # the per-peer cap (8) must stop conn growth, counting rejects.
        # The tile drains the 3k-packet backlog gradually (every
        # spoofed Initial costs one AEAD probe through the burst
        # engine), so redeem in waves and POLL the shed counters with a
        # deadline instead of reading them once.
        redeemed = set()
        deadline = time.monotonic() + 180
        q = run.metrics("quic_server")
        while time.monotonic() < deadline:
            retries.extend(p.payload for p in atk.recv_burst()
                           if p.payload and (p.payload[0] & 0xF0) == 0xF0)
            for rt in retries:
                if len(redeemed) >= 16:
                    break
                parsed = WireFaultGen.redeem_retry(rt)
                if parsed is None or parsed[0] in redeemed:
                    continue
                redeemed.add(parsed[0])
                atk.send_burst(
                    [Pkt(g.forged_initial(dcid=parsed[0],
                                          token=parsed[1])[0], dst)])
            q = run.metrics("quic_server")
            if q["conn_reject_cnt"] > 0:
                break
            assert run.poll() is None, "a tile died under the flood"
            time.sleep(0.25)
        assert q["retry_sent_cnt"] > 0, "Retry defense never engaged"
        assert q["conn_reject_cnt"] > 0, \
            "per-peer cap never rejected the flood"
        assert q["conn_cnt"] <= 9, \
            f"attacker holds {q['conn_cnt']} conns past the per-peer cap"
        assert q["shedding"] == 1, "shedding gauge not raised mid-flood"

        # /healthz must surface the shed (200, body names the tile)
        body = b""
        hz_deadline = time.monotonic() + 10
        while time.monotonic() < hz_deadline:
            r = urllib.request.urlopen(
                f"http://127.0.0.1:{run.metrics_port}/healthz", timeout=5)
            body = r.read()
            if r.status == 200 and body.startswith(b"shedding"):
                break
            time.sleep(0.2)
        assert body.startswith(b"shedding"), \
            f"/healthz never reported shedding: {body!r}"

        # legit source (127.0.0.1) still gets service THROUGH the Retry
        # gauntlet: its client redeems the token transparently
        legit = _QuicClient(port)
        legit.wait_handshake()
        legit.send_txns(txns)
        got = _wait_sink(run, n_legit, clients=(legit,))
        assert got == n_legit, f"legit txns starved: {got}/{n_legit}"
        assert run.metrics("dedup")["dup_drop_cnt"] == 0

        rss1 = _rss_kb(run.procs["quic_server"].pid)
        assert rss1 - rss0 < 64 * 1024, \
            f"quic_server RSS grew {rss1 - rss0} kB under flood"
        # round 16: backend attribution + key-cache bound under the
        # distinct-dcid churn (>1024 dcids probed -> the LRU must evict)
        q = run.metrics("quic_server")
        if have_native:
            assert q["crypto_native_cnt"] > 0, "C engine never engaged"
            assert q["crypto_fallback_cnt"] == 0, \
                f"{q['crypto_fallback_cnt']} pkts fell back to Python"
        assert q["initial_keys_evict_cnt"] > 0, \
            "Initial key LRU never evicted under a 3k-dcid flood"
        assert run.poll() is None
    finally:
        if atk is not None:
            atk.close()
        if legit is not None:
            legit.close()
        run.halt()
        run.close()
    print(f"chaos wire-flood ok (10x): {q['retry_sent_cnt']} retries, "
          f"{q['conn_reject_cnt']} rejects, conn_cnt={q['conn_cnt']}, "
          f"legit {got}/{n_legit} verified, 0 dups, RSS +{rss1 - rss0} kB, "
          f"crypto {'native' if have_native else 'fallback'}"
          f"={q['crypto_native_cnt' if have_native else 'crypto_fallback_cnt']}, "
          f"{q['initial_keys_evict_cnt']} key evictions, /healthz=shedding")


def wire_malformed_smoke() -> None:
    """~400 seeded malformed/truncated/bit-flipped datagrams interleaved
    with legit traffic: every mutation dies in the parser or AEAD probe
    (counted, zero crashes, zero conn state) and verdicts stay exact."""
    from firedancer_tpu.disco.faultinject import WireFaultGen
    from firedancer_tpu.disco.run import TopoRun
    from firedancer_tpu.waltz.aio import Pkt
    from firedancer_tpu.waltz.udpsock import UdpSock

    n = 24
    spec = _wire_spec("chaosmf")
    txns = _make_txns(n, seed=13)
    run = TopoRun(spec, metrics_port=0)
    atk = legit = None
    try:
        run.wait_ready(timeout=420)
        port = int(run.metrics("quic_server")["bound_port"])
        dst = ("127.0.0.1", port)
        g = WireFaultGen(23)
        atk = UdpSock(bind_ip="127.0.0.3", burst=256)
        legit = _QuicClient(port)
        legit.wait_handshake()

        storm = g.malformed(400)
        conns0 = run.metrics("quic_server")["conn_created_cnt"]
        for i in range(0, len(storm), 50):   # interleave storm and txns
            atk.send_burst([Pkt(d, dst) for d in storm[i : i + 50]])
            legit.send_txns(txns[3 * (i // 50) : 3 * (i // 50) + 3])
        legit.send_txns(txns[24:])

        got = _wait_sink(run, n, clients=(legit,))
        assert got == n, f"verdicts lost under malformed storm: {got}/{n}"
        assert run.metrics("dedup")["dup_drop_cnt"] == 0
        # the storm counters lag while the tile drains its rx backlog
        # (AEAD-probed mutations are the expensive ones): poll them
        deadline = time.monotonic() + 120
        q = run.metrics("quic_server")
        while time.monotonic() < deadline:
            q = run.metrics("quic_server")
            if q["pkt_malformed_cnt"] + q["pkt_undecryptable_cnt"] >= 300:
                break
            assert run.poll() is None, "a tile crashed on malformed input"
            time.sleep(0.25)
        assert q["pkt_malformed_cnt"] + q["pkt_undecryptable_cnt"] >= 300, \
            "the storm was not shed where it should be"
        assert q["conn_created_cnt"] - conns0 <= 1, \
            "malformed packets created conn state"
        assert run.poll() is None, "a tile crashed on malformed input"
    finally:
        if atk is not None:
            atk.close()
        if legit is not None:
            legit.close()
        run.halt()
        run.close()
    print(f"chaos wire-malformed ok: {len(storm)} mutations shed "
          f"(malformed={q['pkt_malformed_cnt']}, "
          f"undecryptable={q['pkt_undecryptable_cnt']}), "
          f"{got}/{n} exact verdicts, 0 dups, 0 crashes")


def wire_slowloris_smoke() -> None:
    """Slowloris + oversize: half-open conns are reaped by the idle timer,
    never-FIN partial streams hit the per-conn reasm byte budget
    (evict-oldest, counted), and the verify lane keeps producing."""
    from firedancer_tpu.disco.faultinject import WireFaultGen
    from firedancer_tpu.disco.run import TopoRun
    from firedancer_tpu.waltz.aio import Pkt
    from firedancer_tpu.waltz.udpsock import UdpSock

    n = 16
    spec = _wire_spec("chaossl", idle_timeout=1.0, conn_reasm_budget=4096,
                      max_conns_per_peer=32)
    txns = _make_txns(n, seed=29)
    run = TopoRun(spec, metrics_port=0)
    atk = legit = None
    try:
        run.wait_ready(timeout=420)
        port = int(run.metrics("quic_server")["bound_port"])
        dst = ("127.0.0.1", port)
        g = WireFaultGen(31)

        # 6 half-open conns from 127.0.0.2 that will never finish their
        # handshake — the slowloris herd
        atk = UdpSock(bind_ip="127.0.0.2", burst=256)
        atk.send_burst([Pkt(d, dst) for d in g.conn_flood(6)])
        deadline = time.monotonic() + 60
        q = run.metrics("quic_server")
        while time.monotonic() < deadline:
            q = run.metrics("quic_server")
            if q["half_open_cnt"] >= 6:
                break
            assert run.poll() is None
            time.sleep(0.1)
        assert q["half_open_cnt"] >= 6, \
            f"expected 6 half-open conns, gauge says {q['half_open_cnt']}"

        # a handshaked peer drip-feeds never-FIN stream bytes: 8 x 900 B
        # partials (distinct streams, sids far above send_txn's range)
        # against a 4096 B budget -> evict-oldest must fire
        legit = _QuicClient(port)
        legit.wait_handshake()
        for i in range(8):
            frame = WireFaultGen.partial_stream_frame(
                4_002 + 4 * i, 0, g.oversize_stream_payload(900))
            legit.ep._emit(legit.conn, 2, frame, True, None)
        legit.ep._flush(legit.conn)
        legit.ep._send_pending()

        # the same conn still delivers whole txns after the shed (sent
        # right away — keeps the conn warm past the 1 s idle reaper)
        legit.send_txns(txns)
        got = _wait_sink(run, n, clients=(legit,))
        assert got == n, f"verify lane starved: {got}/{n}"
        assert run.metrics("dedup")["dup_drop_cnt"] == 0
        q = run.metrics("quic_server")
        assert q["reasm_evict_cnt"] >= 1, \
            "reasm budget never evicted the slowloris partials"

        # idle reaper: the half-open herd dies within ~idle_timeout
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            q = run.metrics("quic_server")
            if q["conn_closed_cnt"] >= 6:
                break
            time.sleep(0.1)
        assert q["conn_closed_cnt"] >= 6, \
            f"slowloris conns never reaped ({q['conn_closed_cnt']} closed)"
        assert run.poll() is None
    finally:
        if atk is not None:
            atk.close()
        if legit is not None:
            legit.close()
        run.halt()
        run.close()
    print(f"chaos wire-slowloris ok: {q['conn_closed_cnt']} idle conns "
          f"reaped, {q['reasm_evict_cnt']} reasm evictions, "
          f"{got}/{n} verdicts after the attack, 0 dups")


# --------------------------------------------------------------------------
# shred chaos (--shred): the batched turbine-shred lane (round 13).
# Deterministic erasure storm through the FaultInjector grammar against
# the FEC recover path, then a dup/forge burst against the batched
# leader-signature admission — the forge-then-censor discipline must
# survive deferred (batched) forwarding.


def shred_storm_smoke() -> None:
    """12 signed FEC sets streamed through a seeded drop/corrupt fault
    plan: every corrupted shred is shed at the parser or the merkle/sig
    gate (counted, never admitted), every set that keeps >= k members
    recovers BIT-EXACT through the batched device path, and every set is
    accounted — recovered, starved, or failed, nothing silent."""
    from firedancer_tpu.ballet import reedsol as rs
    from firedancer_tpu.ballet import shred as shred_lib
    from firedancer_tpu.disco.faultinject import FaultInjector
    from firedancer_tpu.ops import ed25519 as ed

    rng = np.random.default_rng(41)
    seed = rng.bytes(32)
    leader_pub, _, _ = ed.keypair_from_seed(seed)
    n_sets, k, c = 12, 8, 8

    entries, keys, stream = [], [], []
    for i in range(n_sets):
        entry = rng.bytes(1500 + 137 * i)
        fs = shred_lib.make_fec_set(
            entry, slot=1000 + i, parent_off=1, version=1,
            fec_set_idx=0, sign_fn=lambda root: ed.sign(seed, root),
            data_cnt=k, code_cnt=c)
        entries.append(entry)
        keys.append((1000 + i, 0))
        stream.extend(fs.data_shreds + fs.code_shreds)

    fault = FaultInjector("shred:0", {"seed": 5, "drop_frag_p": 0.2,
                                      "corrupt_payload_p": 0.08})
    resolvers = {
        key: shred_lib.FecResolver(root_check=lambda root, sig: ed.verify_one_host(sig, root, leader_pub))
        for key in keys}
    dropped = parse_fail = rejected = admitted = 0
    for raw in stream:
        payload, drop = fault.frag(raw)
        if drop:
            dropped += 1
            continue
        try:
            s = shred_lib.parse(payload)
        except shred_lib.ShredParseError:
            parse_fail += 1
            continue
        res = resolvers.get((s.slot, s.fec_set_idx))
        if res is None:
            # corruption forged a key that names no real set — a stray
            # resolver could never admit it (its computed root fails the
            # leader-sig gate), so it sheds here
            rejected += 1
            continue
        if res.add(s):
            admitted += 1
        else:
            rejected += 1
    assert dropped and (rejected or parse_fail), \
        f"storm did nothing: dropped={dropped}, rejected={rejected}, " \
        f"parse_fail={parse_fail}"

    # batched recovery of every ready set in ONE device dispatch
    triples, metas, outcomes = [], [], {}
    for key, res in resolvers.items():
        if not res.ready():
            outcomes[key] = "starved"
            continue
        args = res.recover_args()
        if args is None:          # all data shreds survived: nothing to do
            outcomes[key] = res.data_regions()
            continue
        triples.append(args)
        metas.append(key)
    recovered_with_erasures = 0
    for key, out in zip(metas, rs.recover_batch(triples)):
        if isinstance(out, ValueError):
            outcomes[key] = "failed"
            continue
        outcomes[key] = resolvers[key].data_regions(out)
        recovered_with_erasures += 1

    recovered = starved = failed = 0
    for i, key in enumerate(keys):
        out = outcomes[key]
        if out == "starved":
            starved += 1
        elif out == "failed":
            failed += 1
        else:
            got = shred_lib.FecResolver.assemble_payload(out)
            assert got == entries[i], \
                f"set {key}: recovered payload diverged from the entry batch"
            recovered += 1
    assert recovered + starved + failed == n_sets, "a set went unaccounted"
    assert recovered_with_erasures >= 1, \
        "the storm never exercised actual erasure recovery"
    assert recovered >= n_sets // 2, \
        f"only {recovered}/{n_sets} sets recovered under a 20% drop plan"
    print(f"chaos shred-storm ok: {recovered}/{n_sets} sets bit-exact "
          f"({recovered_with_erasures} via batched recover, "
          f"{starved} starved, {failed} failed — all accounted), storm "
          f"shed {dropped} drops + {rejected} rejects + "
          f"{parse_fail} parse fails")


def shred_dup_forge_smoke() -> None:
    """Dup/forge burst through the batched leader-sig admission: forged
    signatures and unknown-leader shreds are censored WITHOUT poisoning
    dedup (the genuine shred arriving later still forwards — forge-then-
    censor resistance), and duplicates never forward twice whether they
    land in the same batch (verdict-time re-query) or across batches
    (ingress query)."""
    from firedancer_tpu.ballet import shred as shred_lib
    from firedancer_tpu.disco.tiles import _ShredSigBatcher
    from firedancer_tpu.ops import ed25519 as ed

    rng = np.random.default_rng(43)
    seed = rng.bytes(32)
    leader_pub, _, _ = ed.keypair_from_seed(seed)
    fs = shred_lib.make_fec_set(
        rng.bytes(2000), slot=7, parent_off=1, version=1, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(seed, root), data_cnt=8, code_cnt=8)
    genuine = fs.data_shreds + fs.code_shreds
    fsb = shred_lib.make_fec_set(
        rng.bytes(900), slot=8, parent_off=1, version=1, fec_set_idx=0,
        sign_fn=lambda root: ed.sign(seed, root), data_cnt=8, code_cnt=8)

    def forge(raw: bytes) -> bytes:
        b = bytearray(raw)
        b[5] ^= 0xFF              # signature byte: root walk unaffected
        return bytes(b)

    # forged copies FIRST (they must not poison dedup), then the genuine
    # shreds each twice (adjacent: the pair lands inside one batch), with
    # two unknown-leader shreds from a second slot mixed in
    stream = ([(forge(genuine[i]), leader_pub) for i in range(3)]
              + [(fsb.data_shreds[0], None), (fsb.data_shreds[1], None)])
    for raw in genuine:
        stream.append((raw, leader_pub))
        stream.append((raw, leader_pub))

    batcher = _ShredSigBatcher(batch=8, backend="host")
    dedup, forwards = set(), []
    censored = dup_ingress = dup_verdict = 0

    def admit(verdicts):
        nonlocal censored, dup_verdict
        for s, raw, tag, ok in verdicts:
            if not ok:
                censored += 1
                continue
            if tag in dedup:      # same-batch duplicate: verdict re-query
                dup_verdict += 1
                continue
            dedup.add(tag)        # insert ONLY after proven leader-signed
            forwards.append(raw)

    for raw, leader in stream:
        s = shred_lib.parse(raw)
        tag = (s.slot << 17) | (s.idx << 1) | int(s.is_data)
        if tag in dedup:          # cross-batch duplicate: ingress query
            dup_ingress += 1
            continue
        batcher.add(s, raw, tag, leader)
        if batcher.full:
            admit(batcher.flush())
    admit(batcher.flush())

    assert len(forwards) == len(genuine), \
        f"forwarded {len(forwards)} != {len(genuine)} unique valid shreds"
    assert sorted(forwards) == sorted(genuine), "a forward diverged"
    assert dup_ingress + dup_verdict == len(genuine), \
        f"dup accounting off: {dup_ingress} ingress + {dup_verdict} verdict"
    assert dup_verdict >= 1, "the verdict-time re-query path never fired"
    assert censored == 5, f"censored {censored} != 3 forged + 2 unknown"
    for i in range(3):            # forge-then-censor: genuine still flowed
        assert genuine[i] in forwards, \
            f"forged shred {i} censored the genuine copy"
    print(f"chaos shred-dup-forge ok: {len(forwards)} unique forwards, "
          f"{censored} censored (3 forged + 2 unknown leader), "
          f"{dup_ingress}+{dup_verdict} dups shed at ingress/verdict, "
          "forged copies never poisoned dedup")


# ---------------------------------------------------------------------------
# leader chaos (--leader): the round-14 leader lane.  Rolling-restart the
# pack tile mid-slot under live load; the drain protocol must flush its
# heap before exit and the respawn must resume from the evicted fseq
# cursor, so every verified txn lands in EXACTLY ONE microblock at the
# sink — and the PoH entry chain the device engine emitted across the
# outage must re-verify bit-exactly (host verify_chain AND the batched
# verify_entries ladder).


def _read_entry_capture(path: str):
    """Parse the sink capture (u64 sig | u32 len | payload per frag) into
    entries, tolerating a torn tail record (the writer may be mid-append)."""
    from firedancer_tpu.ballet import entry as entry_lib

    try:
        buf = open(path, "rb").read()
    except OSError:
        return []
    out = []
    off = 0
    while off + 12 <= len(buf):
        ln = int.from_bytes(buf[off + 8:off + 12], "little")
        if off + 12 + ln > len(buf):
            break                      # torn tail: writer mid-record
        e, _ = entry_lib.Entry.deserialize(buf[off + 12:off + 12 + ln])
        out.append(e)
        off += 12 + ln
    return out


def leader_drain_restart_smoke() -> None:
    import shutil
    import tempfile

    import numpy as np

    from firedancer_tpu.app import config as config_mod
    from firedancer_tpu.ballet import entry as entry_lib
    from firedancer_tpu.ballet import poh as poh_lib
    from firedancer_tpu.disco.run import SupervisionPolicy, TopoRun
    from firedancer_tpu.utils import aot

    batch, maxlen = 64, 256
    aot_dir = os.environ.get("FDTPU_CI_AOT_DIR", "/tmp/fdtpu_aot_ci")
    aot.ensure_verify(aot_dir, batch, maxlen)   # fast boot when usable

    n_txn = 400
    hpt = 8
    man_dir = tempfile.mkdtemp(prefix="fdtpu_ci_leaderman_")
    cap = os.path.join(man_dir, "entries.bin")
    cfg = config_mod.load(None)
    cfg["name"] = "fdtpu_ci_leader"
    cfg["topology"] = "leader-bench"
    cfg["layout"]["verify_tile_count"] = 1
    cfg["development"]["source_count"] = n_txn
    cfg["tiles"]["verify"].update(batch=batch, msg_maxlen=maxlen,
                                  flush_age_ns=50_000_000, aot_dir=aot_dir)
    cfg["leader"].update(hashes_per_tick=hpt, ticks_per_slot=8,
                         mb_per_tick=4, mixin_txn_max=16, capture_path=cap)
    cfg["supervision"] = dict(cfg.get("supervision") or {},
                              restart_policy="respawn", max_restarts=3,
                              backoff_initial_s=0.2, backoff_max_s=1.0,
                              drain_timeout_s=60.0,
                              drain_manifest_dir=man_dir)
    policy = SupervisionPolicy.from_cfg(cfg)
    spec = config_mod.build_topology(cfg)
    run = TopoRun(spec, metrics_port=0, policy=policy, config=cfg)
    try:
        run.wait_ready(timeout=560)
        sup = threading.Thread(target=run.supervise, kwargs={"poll_s": 0.05},
                               daemon=True)
        sup.start()

        # mid-slot live load first: restart only once microblock mixins
        # are landing in the chain
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if run.metrics("poh_dev")["mixin_cnt"] >= 2:
                break
            time.sleep(0.05)
        assert run.metrics("poh_dev")["mixin_cnt"] >= 2, \
            "no live microblock flow to restart under"

        t0 = time.monotonic()
        ok = run.rolling_restart("leader_pack", {})
        gap_s = time.monotonic() - t0
        assert ok, "graceful pack restart fell back to crash semantics"
        assert run.restarts.get("leader_pack", 0) == 1

        # every generated txn schedules exactly once across incarnations
        # (heap flushed by the drain hook; fseq cursor resumed, nothing
        # re-consumed) and reaches the chain as a microblock mixin
        deadline = time.monotonic() + 300
        mixed = []
        while time.monotonic() < deadline:
            mixed = [t for e in _read_entry_capture(cap)
                     for t in e.txns]
            if len(mixed) >= n_txn:
                break
            time.sleep(0.2)
        lp = run.metrics("leader_pack")
        pd = run.metrics("poh_dev")
        assert lp["drain_drop_cnt"] == 0, \
            f"drain dropped {lp['drain_drop_cnt']} held txns"
        assert lp["torn_drop_cnt"] == 0 and lp["parse_fail_cnt"] == 0, lp
        assert pd["recheck_fail_cnt"] == 0 and pd["parse_fail_cnt"] == 0, pd
        assert len(mixed) == n_txn, \
            f"lost microblock txns: {len(mixed)}/{n_txn} at the sink"
        assert len(set(mixed)) == n_txn, \
            f"{len(mixed) - len(set(mixed))} duplicate txns re-packed " \
            "across the restart"
        assert run.drain() is True, "topology drain timed out"
        sup.join(15)
    finally:
        run.halt()
        run.close()

    # the chain the device engine emitted across the outage re-verifies
    entries = _read_entry_capture(cap)
    assert entry_lib.verify_chain(bytes(32), entries), \
        "PoH chain broke across the pack restart"
    n = len(entries)
    starts = np.zeros((n, 32), np.uint8)
    nums = np.zeros((n,), np.int32)
    mixins = np.zeros((n, 32), np.uint8)
    has = np.zeros((n,), np.bool_)
    prev = bytes(32)
    for i, e in enumerate(entries):
        starts[i] = np.frombuffer(prev, np.uint8)
        nums[i] = e.num_hashes
        if not e.is_tick:
            mixins[i] = np.frombuffer(entry_lib.txn_mixin(e.txns), np.uint8)
            has[i] = True
        prev = e.hash
    got = np.asarray(poh_lib.verify_entries_fit(
        starts, nums, mixins, has, max_hashes=hpt))
    bad = sum(bytes(got[i]) != entries[i].hash for i in range(n))
    assert bad == 0, f"{bad} entries failed the device ladder re-verify"
    shutil.rmtree(man_dir, ignore_errors=True)
    print(f"chaos leader-restart ok: leader_pack rolling-restarted in "
          f"{gap_s:.1f}s mid-slot, {n_txn} txns -> exactly-once microblock "
          f"mixins, {n} entries re-verify (host chain + device ladder), "
          "0 rechecks failed")


def leader_shard_kill_smoke() -> None:
    """Round 15: kill one pack SHARD mid-slot in a 2-shard leader
    topology.  Fee-payer steering must re-converge after the respawn
    (the hash partition is stateless, so the same payers land on the
    same shard), the merge tile must keep interleaving the surviving
    shard meanwhile, every verified txn must land in EXACTLY ONE
    microblock mixin at the sink, and the captured slot must re-verify
    under the host chain rule AND the device verify_entries ladder with
    zero recheck failures."""
    import shutil
    import tempfile

    import numpy as np

    from firedancer_tpu.app import config as config_mod
    from firedancer_tpu.ballet import entry as entry_lib
    from firedancer_tpu.ballet import poh as poh_lib
    from firedancer_tpu.disco.run import SupervisionPolicy, TopoRun
    from firedancer_tpu.utils import aot

    batch, maxlen = 64, 256
    aot_dir = os.environ.get("FDTPU_CI_AOT_DIR", "/tmp/fdtpu_aot_ci")
    aot.ensure_verify(aot_dir, batch, maxlen)

    n_txn = 400
    hpt = 8
    man_dir = tempfile.mkdtemp(prefix="fdtpu_ci_shardman_")
    cap = os.path.join(man_dir, "entries.bin")
    cfg = config_mod.load(None)
    cfg["name"] = "fdtpu_ci_shard"
    cfg["topology"] = "leader-bench"
    cfg["layout"]["verify_tile_count"] = 1
    cfg["development"]["source_count"] = n_txn
    cfg["tiles"]["verify"].update(batch=batch, msg_maxlen=maxlen,
                                  flush_age_ns=50_000_000, aot_dir=aot_dir)
    cfg["leader"].update(hashes_per_tick=hpt, ticks_per_slot=8,
                         mb_per_tick=4, mixin_txn_max=16, pack_shards=2,
                         poh_spec_ticks=2, capture_path=cap)
    cfg["supervision"] = dict(cfg.get("supervision") or {},
                              restart_policy="respawn", max_restarts=3,
                              backoff_initial_s=0.2, backoff_max_s=1.0,
                              drain_timeout_s=60.0,
                              drain_manifest_dir=man_dir)
    policy = SupervisionPolicy.from_cfg(cfg)
    spec = config_mod.build_topology(cfg)
    assert {t.name for t in spec.tiles} >= \
        {"leader_pack:0", "leader_pack:1", "leader_merge"}, \
        [t.name for t in spec.tiles]
    run = TopoRun(spec, metrics_port=0, policy=policy, config=cfg)
    try:
        run.wait_ready(timeout=560)
        sup = threading.Thread(target=run.supervise, kwargs={"poll_s": 0.05},
                               daemon=True)
        sup.start()

        # restart only once merged microblock mixins are landing
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if run.metrics("poh_dev")["mixin_cnt"] >= 2:
                break
            time.sleep(0.05)
        assert run.metrics("poh_dev")["mixin_cnt"] >= 2, \
            "no live microblock flow to kill a shard under"
        steer0 = run.metrics("leader_pack:0")["shard_steer_cnt"]

        t0 = time.monotonic()
        ok = run.rolling_restart("leader_pack:0", {})
        gap_s = time.monotonic() - t0
        assert ok, "graceful shard restart fell back to crash semantics"
        assert run.restarts.get("leader_pack:0", 0) == 1

        deadline = time.monotonic() + 300
        mixed = []
        while time.monotonic() < deadline:
            mixed = [t for e in _read_entry_capture(cap)
                     for t in e.txns]
            if len(mixed) >= n_txn:
                break
            time.sleep(0.2)
        lp0 = run.metrics("leader_pack:0")
        lp1 = run.metrics("leader_pack:1")
        lm = run.metrics("leader_merge")
        pd = run.metrics("poh_dev")
        # steering re-converged: the respawned shard owns txns again,
        # and the stateless hash partition sends every txn to exactly
        # one shard (both shards see the full verified stream)
        assert lp0["shard_steer_cnt"] > 0, lp0
        assert lp0["shard_steer_cnt"] + lp1["shard_steer_cnt"] \
            == lp0["txn_insert_cnt"] + lp1["txn_insert_cnt"] \
            + lp0["oversize_drop_cnt"] + lp1["oversize_drop_cnt"] \
            + lp0["heap_full_drop_cnt"] + lp1["heap_full_drop_cnt"], \
            (lp0, lp1)
        for name, m in (("leader_pack:0", lp0), ("leader_pack:1", lp1)):
            assert m["drain_drop_cnt"] == 0, (name, m["drain_drop_cnt"])
            assert m["torn_drop_cnt"] == 0 and m["parse_fail_cnt"] == 0, \
                (name, m)
        assert lm["drain_drop_cnt"] == 0 and lm["parse_fail_cnt"] == 0, lm
        assert lm["mb_merge_cnt"] == lm["mb_rx_cnt"], lm
        assert pd["recheck_fail_cnt"] == 0 and pd["parse_fail_cnt"] == 0, pd
        assert len(mixed) == n_txn, \
            f"lost microblock txns: {len(mixed)}/{n_txn} at the sink"
        assert len(set(mixed)) == n_txn, \
            f"{len(mixed) - len(set(mixed))} duplicate txns re-packed " \
            "across the shard kill"
        assert run.drain() is True, "topology drain timed out"
        sup.join(15)
    finally:
        run.halt()
        run.close()

    entries = _read_entry_capture(cap)
    assert entry_lib.verify_chain(bytes(32), entries), \
        "PoH chain broke across the shard kill"
    n = len(entries)
    starts = np.zeros((n, 32), np.uint8)
    nums = np.zeros((n,), np.int32)
    mixins = np.zeros((n, 32), np.uint8)
    has = np.zeros((n,), np.bool_)
    prev = bytes(32)
    for i, e in enumerate(entries):
        starts[i] = np.frombuffer(prev, np.uint8)
        nums[i] = e.num_hashes
        if not e.is_tick:
            mixins[i] = np.frombuffer(entry_lib.txn_mixin(e.txns), np.uint8)
            has[i] = True
        prev = e.hash
    got = np.asarray(poh_lib.verify_entries_fit(
        starts, nums, mixins, has, max_hashes=hpt))
    bad = sum(bytes(got[i]) != entries[i].hash for i in range(n))
    assert bad == 0, f"{bad} entries failed the device ladder re-verify"
    shutil.rmtree(man_dir, ignore_errors=True)
    print(f"chaos shard-kill ok: leader_pack:0 killed mid-slot in {gap_s:.1f}s "
          f"(steer {steer0} pre-kill), steering re-converged, {n_txn} txns -> "
          f"exactly-once mixins through leader_merge, {n} entries re-verify "
          "(host chain + device ladder), 0 rechecks failed")


# ---------------------------------------------------------------------------
# --fleet: the multi-host fault-tolerance tentpole (round 17).  A ≥3-host
# fleet (each host = its own supervisor process + full topology + capture
# ledger) takes a SIGKILL to one host's whole process group mid-load.
# PASS bar, fleet-wide:
#   1. consistent-hash steering re-converges (no shard/peer maps to the
#      dead host; survivors' arcs deterministic),
#   2. the dead host's in-flight txns re-verify on the adopter (stream
#      adoption + dedup preload from the dead ledger ∪ gossiped digests),
#   3. the union of capture ledgers == the injected txn universe with
#      every verdict EXACTLY once (zero lost, zero duplicated),
#   4. `fdtpuctl fleet top` (state file + per-host /healthz + /metrics
#      scrape) reports the loss,
#   5. a fleet rolling restart (via the fdtpuctl command file) upgrades
#      the survivors one at a time under the same zero-loss/zero-dup bar.


def fleet_smoke() -> None:
    import contextlib
    import io
    import shutil
    import tempfile

    from firedancer_tpu.app import config as config_mod
    from firedancer_tpu.app import fdtpuctl
    from firedancer_tpu.disco import faultinject
    from firedancer_tpu.disco import fleet as fleet_mod
    from firedancer_tpu.utils import aot

    batch, maxlen = 64, 256
    aot_dir = os.environ.get("FDTPU_CI_AOT_DIR", "/tmp/fdtpu_aot_ci")
    if aot.ensure_verify(aot_dir, batch, maxlen) is None:
        print("chaos fleet SKIPPED: AOT unusable on this backend")
        return

    n_hosts, n_txn = 3, 600
    kill_idx = 1
    cfg = config_mod.load(None)
    cfg["name"] = "fdtpu_ci_fleet"
    cfg["topology"] = "verify-bench"
    cfg["layout"]["verify_tile_count"] = 1
    cfg["development"]["source_count"] = n_txn
    cfg["development"]["bench_seed"] = 42
    # pace the sources so the kill provably lands mid-stream (the
    # after_capture gate below would hold it anyway)
    cfg["development"]["source_extra"] = {"rate_ns": 10_000_000}
    cfg["tiles"]["verify"]["batch"] = batch
    cfg["tiles"]["verify"]["msg_maxlen"] = maxlen
    cfg["tiles"]["verify"]["aot_dir"] = aot_dir
    cfg["tiles"]["verify"]["aot_require"] = 1
    cfg["fleet"] = dict(cfg.get("fleet") or {}, hosts=n_hosts,
                        digest_period_s=0.2)
    sb = int(cfg["fleet"].get("shard_bits", 4))

    # seeded, boot-gen-gated fleet fault: SIGKILL host 1's process group
    # once it has exported >=120 verdicts (mid-load by construction)
    os.environ["FDTPU_FAULTS"] = \
        f"fleet=host_kill:{kill_idx},after_capture:120,boot:0"
    faults = faultinject.fleet_faults(os.environ, cfg, 0)
    assert faults is not None and faults.host_kill == kill_idx

    workdir = tempfile.mkdtemp(prefix="fdtpu_ci_fleet_")
    uni = fleet_mod.stream_universe(
        [fleet_mod.host_stream_spec(cfg, i) for i in range(n_hosts)])
    assert len(uni) == n_hosts * n_txn
    fr = fleet_mod.FleetRun(cfg, workdir, faults=faults)
    try:
        fr.wait_ready(timeout=420)

        # ---- phase A: host loss mid-load -> failover, exactly-once
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            fr.poll()
            if fr.lost and len(set(fr.ledger())) >= len(uni):
                break
            time.sleep(0.1)
        led = fr.ledger()
        dup = len(led) - len(set(led))
        lost = len(set(uni)) - len(set(led) & set(uni))
        stray = len(set(led) - set(uni))
        assert fr.lost == {kill_idx}, \
            f"expected host {kill_idx} lost, got {fr.lost}"
        assert dup == 0, f"{dup} duplicated verdicts fleet-wide"
        assert lost == 0, f"{lost} lost verdicts fleet-wide"
        assert stray == 0, f"{stray} verdicts outside the universe"
        # steering re-converged: nothing maps to the dead host, and the
        # survivors' ring is the deterministic n-1 host ring
        dead = fleet_mod.host_name(kill_idx)
        from firedancer_tpu.waltz.pkteng import SteerRing
        want = SteerRing([fleet_mod.host_name(i) for i in range(n_hosts)
                          if i != kill_idx],
                         vnodes=int(cfg["fleet"].get("vnodes", 64)))
        for s in range(1 << sb):
            assert fr.ring.shard_owner(s, sb) != dead
            assert fr.ring.shard_owner(s, sb) == want.shard_owner(s, sb)
        adopter = fr.adopting.get(kill_idx)
        assert adopter is not None and fr.adopted.get(kill_idx), \
            "no adoption report"
        assert fr.adopted[kill_idx]["preload"] >= 120

        # ---- fleet top (the out-of-process control plane) sees the loss
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = fdtpuctl.main(["fleet", "top", "--workdir", workdir])
        top_out = buf.getvalue()
        assert rc == 0, top_out
        assert "state=lost" in top_out.splitlines()[0], top_out
        assert f"lost=h{kill_idx}" in top_out, top_out

        # ---- phase B: fleet rolling restart of the survivors under the
        # same bar, driven end to end through the fdtpuctl command file
        rc_box = {}

        def _ctl():
            buf2 = io.StringIO()
            with contextlib.redirect_stdout(buf2):
                rc_box["rc"] = fdtpuctl.main(
                    ["fleet", "rolling_restart", "--workdir", workdir,
                     "--timeout", "180"])
            rc_box["out"] = buf2.getvalue()

        ctl = threading.Thread(target=_ctl, daemon=True)
        ctl.start()
        deadline = time.monotonic() + 600
        while ctl.is_alive() and time.monotonic() < deadline:
            fr.poll()                  # serves the command file
            time.sleep(0.1)
        ctl.join(5)
        assert rc_box.get("rc") == 0, rc_box
        assert all(fr.boot_gen[i] == 1 for i in range(n_hosts)
                   if i != kill_idx), fr.boot_gen
        # rebooted hosts re-emit their whole stream; the resume preload
        # (their own exported ledger) must reject every re-verdict
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            fr.poll()
            led = fr.ledger()
            if len(set(led)) >= len(uni) and len(led) == len(set(led)):
                time.sleep(2.0)        # settle: catch late duplicates
                fr.poll()
                led = fr.ledger()
                break
            time.sleep(0.2)
        dup = len(led) - len(set(led))
        lost = len(set(uni)) - len(set(led) & set(uni))
        assert dup == 0, f"{dup} duplicated verdicts after fleet restart"
        assert lost == 0, f"{lost} lost verdicts after fleet restart"
    finally:
        fr.close()
        shutil.rmtree(workdir, ignore_errors=True)
        os.environ.pop("FDTPU_FAULTS", None)
    print(f"chaos fleet ok: {n_hosts} hosts, h{kill_idx} SIGKILLed "
          f"mid-load -> h{adopter} adopted "
          f"(preload {fr.adopted[kill_idx]['preload']}), steering "
          f"re-converged, {len(uni)} verdicts exactly-once "
          f"(failover {fr.failover_ms[kill_idx]:.0f} ms), fleet top "
          "reported the loss, rolling restart of survivors zero-loss")


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--fleet" in argv:
        fleet_smoke()
        return 0
    if "--shred" in argv:
        shred_storm_smoke()
        shred_dup_forge_smoke()
        return 0
    if "--leader" in argv:
        leader_drain_restart_smoke()
        leader_shard_kill_smoke()
        return 0
    if "--wire" in argv:
        wire_flood_smoke()
        wire_malformed_smoke()
        wire_slowloris_smoke()
        return 0
    if "--autotune" in argv:
        autotune_converge_smoke()
        autotune_slow_consumer_smoke()
        autotune_poison_smoke()
        autotune_live_smoke()
        return 0
    if "--drain" in argv:
        drain_rolling_restart_smoke()
        drain_timeout_fallback_smoke()
        return 0
    evict_smoke()
    degrade_smoke()
    kill_respawn_smoke()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
