"""CI chaos smoke: the self-healing topology tentpole, end to end.

Three scenarios, all deterministic (fixed seeds, counter-driven faults):

  1. dead-consumer eviction (tango level) — a producer pinned at zero
     credits by a dead reliable consumer's frozen fseq resumes publishing
     once the supervisor-side eviction fast-forwards the line.
  2. device-loss degradation (in-process) — a GuardedVerifier over a real
     CPU SigVerifier rides injected dispatch failures into degraded mode,
     serves bit-identical verdicts off the host ed25519 fallback, and
     recovers through a reprobe once the fault clears.
  3. kill -> respawn (multi-process) — FDTPU_FAULTS hard-kills the verify
     tile mid-stream (os._exit, SIGKILL-grade); the respawn-policy
     supervisor restarts it with backoff into the live workspace.  Gates:
     /healthz returns to 200, the source finishes its full count
     (producers unstalled past the outage), verdicts flow to the sink,
     and the dedup tile sees ZERO duplicate verdicts (the respawned mux
     resumed from the evicted fseq cursor, nothing re-verified).

A real file (not a ci.sh heredoc): tile processes use the 'spawn' start
method, which re-imports __main__ from its path.

Usage:  JAX_PLATFORMS=cpu python tools/chaos_smoke.py
"""

import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def evict_smoke() -> None:
    from firedancer_tpu.disco import topo as topo_mod
    from firedancer_tpu.disco.topo import TopoBuilder
    from firedancer_tpu.tango.fctl import Fctl

    depth = 64
    spec = (
        TopoBuilder(f"chaosev{os.getpid()}", wksp_mb=8)
        .link("a_b", depth=depth, mtu=256)
        .tile("src", "sink", outs=["a_b"])
        .tile("dst", "sink", ins=["a_b"])
        .build()
    )
    jt = topo_mod.create(spec)
    try:
        mc = jt.links["a_b"].mcache
        fseq = jt.fseq[("dst", "a_b")]
        fctl = Fctl(cr_max=depth).rx_add(fseq)
        seq = mc.seq0()
        fseq.update(seq)                     # consumer joined ... and died
        sent = 0
        while fctl.consume(1):               # runs the ring dry: the dead
            mc.publish(sent)                 # fseq never advances
            seq += 1
            sent += 1
            fctl.tx_cr_update(seq)
        assert sent == depth, f"expected {depth} credits, spent {sent}"
        assert fctl.cr_query(seq) == 0, "producer must be pinned at zero"

        cur = Fctl.evict_dead_consumer(fseq, mc)   # the supervisor's move
        assert cur == seq and fseq.query() == seq
        assert fctl.cr_query(seq) == depth, "eviction must refill credits"
        for _ in range(depth // 2):          # and the producer flows again
            assert fctl.tx_cr_update(seq) > 0 and fctl.consume(1)
            mc.publish(sent)
            seq += 1
            sent += 1
    finally:
        jt.close()
        jt.unlink()
    print(f"chaos evict ok: producer unpinned after eviction "
          f"({sent} frags published across a dead consumer)")


def degrade_smoke() -> None:
    from firedancer_tpu.disco import faultinject
    from firedancer_tpu.disco.pipeline import GuardedVerifier
    from firedancer_tpu.models.verifier import (SigVerifier, VerifierConfig,
                                                make_example_batch)

    B, ml = 64, 96
    sv = SigVerifier(VerifierConfig(batch=B, msg_maxlen=ml))
    msgs, lens, sigs, pubs = (np.asarray(a).copy() for a in make_example_batch(
        B, ml, valid=True, sign_pool=8, seed=21))
    sigs[3, 10] ^= 0x40                      # mixed verdicts, or the test
    pubs[17, 0] ^= 0x02                      # proves nothing
    ref = np.asarray(sv(msgs, lens, sigs, pubs)).astype(bool)
    assert ref.any() and not ref.all()

    fault = faultinject.FaultInjector("verify:0", {"fail_dispatch_n": 3})
    g = GuardedVerifier(sv, fail_threshold=2, retries=0, reprobe_s=0.0,
                        fault=fault)
    for i in range(3):                       # persistent injected failure
        ok = np.asarray(g(msgs, lens, sigs, pubs))
        assert np.array_equal(ok, ref), \
            f"fallback verdict diverged on batch {i}"
    assert g.degraded, "threshold must flip degraded mode on"
    assert g.fallback_lanes == 3 * B

    ok = np.asarray(g(msgs, lens, sigs, pubs))   # fault spent: reprobe heals
    assert np.array_equal(ok, ref)
    assert not g.degraded and g.reprobe_cnt >= 1
    ok = np.asarray(g(msgs, lens, sigs, pubs))   # device path serving again
    assert np.array_equal(ok, ref)
    assert g.fallback_lanes == 3 * B
    print(f"chaos degrade ok: {g.device_fail_cnt} injected failures -> CPU "
          f"fallback bit-identical ({int(ref.sum())}/{B} pass), device "
          "recovered via reprobe")


def kill_respawn_smoke() -> None:
    from firedancer_tpu.app import config as config_mod
    from firedancer_tpu.disco.run import SupervisionPolicy, TopoRun
    from firedancer_tpu.utils import aot

    batch, maxlen = 64, 256
    aot_dir = os.environ.get("FDTPU_CI_AOT_DIR", "/tmp/fdtpu_aot_ci")
    if aot.ensure_verify(aot_dir, batch, maxlen) is None:
        print("chaos kill-respawn SKIPPED: AOT unusable on this backend")
        return

    # enough txns that the source MUST outlive the verify outage: the
    # src_verify ring is 4096 deep, the kill lands ~frag 150, so without
    # dead-consumer eviction the source wedges around txn 4246
    n_txn = 5000
    cfg = config_mod.load(None)
    cfg["name"] = "fdtpu_ci_chaos"
    cfg["topology"] = "verify-bench"
    cfg["layout"]["verify_tile_count"] = 1
    cfg["development"]["source_count"] = n_txn
    cfg["tiles"]["verify"]["batch"] = batch
    cfg["tiles"]["verify"]["msg_maxlen"] = maxlen
    cfg["tiles"]["verify"]["aot_dir"] = aot_dir
    cfg["tiles"]["verify"]["aot_require"] = 1
    cfg["supervision"] = dict(cfg.get("supervision") or {},
                              restart_policy="respawn", max_restarts=3,
                              backoff_initial_s=0.2, backoff_max_s=1.0)
    policy = SupervisionPolicy.from_cfg(cfg)
    spec = config_mod.build_topology(cfg)

    # generation-gated kill: incarnation 0 dies right before its 150th
    # frag (neither processed nor acked); the respawn runs fault-free
    os.environ["FDTPU_FAULTS"] = "verify:0=kill_after_frags:150,boot:0"
    run = TopoRun(spec, metrics_port=0, policy=policy)
    try:
        run.wait_ready(timeout=300)
        sup = threading.Thread(target=run.supervise, kwargs={"poll_s": 0.05},
                               daemon=True)
        sup.start()
        base = f"http://127.0.0.1:{run.metrics_port}"

        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if (run.restarts.get("verify:0", 0) >= 1
                    and run.metrics("source")["txn_gen_cnt"] >= n_txn
                    and run.metrics("sink")["frag_cnt"] > 0):
                break
            time.sleep(0.2)
        restarts = run.restarts.get("verify:0", 0)
        src = run.metrics("source")
        snk = run.metrics("sink")
        ddp = run.metrics("dedup")
        assert restarts >= 1, "verify tile was never killed/respawned"
        assert src["txn_gen_cnt"] >= n_txn, \
            f"source wedged at {src['txn_gen_cnt']}/{n_txn}: producers " \
            "did not unstall across the outage"
        assert snk["frag_cnt"] > 0, "no verdicts reached the sink"
        assert ddp["dup_drop_cnt"] == 0, \
            f"{ddp['dup_drop_cnt']} duplicate verdicts: the respawned mux " \
            "re-processed acked frags"

        # /healthz back to 200 within the backoff budget
        hz_deadline = time.monotonic() + 120
        status = None
        while time.monotonic() < hz_deadline:
            try:
                r = urllib.request.urlopen(f"{base}/healthz", timeout=5)
                status = r.status
                if status == 200:
                    break
            except urllib.error.HTTPError as e:
                status = e.code
            time.sleep(0.2)
        assert status == 200, f"/healthz stuck at {status} post-respawn"
    finally:
        os.environ.pop("FDTPU_FAULTS", None)
        run.halt()           # stops the supervise thread too (_halting)
        sup.join(15)
        run.close()
    print(f"chaos kill-respawn ok: verify:0 respawned {restarts}x, source "
          f"finished {src['txn_gen_cnt']}/{n_txn}, sink got "
          f"{snk['frag_cnt']} verdict frags, 0 duplicate verdicts, "
          "/healthz 200")


def main() -> int:
    evict_smoke()
    degrade_smoke()
    kill_respawn_smoke()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
