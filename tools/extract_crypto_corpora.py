#!/usr/bin/env python
"""Extract the real ed25519 conformance corpora from the reference tree
into JSON fixtures (round 4, VERDICT missing #3).

Sources (PUBLIC TEST DATA — Wycheproof and the "Taming the many EdDSAs"
CCTV corpus, with pass/fail expectations as regenerated for Solana
consensus semantics by the reference's gen_wycheproofs.py):

  /root/reference/src/ballet/ed25519/test_ed25519_wycheproof.c   (134 tcs)
  /root/reference/src/ballet/ed25519/test_ed25519_cctv.c         (915 tcs)
  .../test_ed25519_signature_malleability_should_{pass,fail}.bin

Only the vector DATA (hex constants + expected bits) is extracted; no
code.  Output: tests/golden/{wycheproof,cctv}_ed25519.json and
malleability_ed25519.json, each a list of
{tc_id, comment, msg (hex), pub (hex), sig (hex), ok (bool)}.
"""

import json
import os
import re

REF = "/root/reference/src/ballet/ed25519"
OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "golden")


def _c_bytes(lit: str) -> bytes:
    """Decode a C string literal body (only \\xHH escapes + plain chars are
    present in the generated files)."""
    out = bytearray()
    i = 0
    while i < len(lit):
        if lit[i] == "\\" and i + 3 < len(lit) and lit[i + 1] == "x":
            out.append(int(lit[i + 2 : i + 4], 16))
            i += 4
        else:
            out.append(ord(lit[i]))
            i += 1
    return bytes(out)


_ENTRY = re.compile(
    r"\{\s*\.tc_id\s*=\s*(\d+),\s*"
    r"\.comment\s*=\s*\"((?:[^\"\\]|\\.)*)\",\s*"
    r"\.msg\s*=\s*\(uchar const \*\)\s*\"((?:[^\"\\]|\\.)*)\",\s*"
    r"\.msg_sz\s*=\s*(\d+)UL,\s*"
    r"\.sig\s*=\s*\"((?:[^\"\\]|\\.)*)\",\s*"
    r"\.pub\s*=\s*\"((?:[^\"\\]|\\.)*)\",\s*"
    r"\.ok\s*=\s*(\d+)\s*\}",
    re.S)


def extract_table(path: str) -> list[dict]:
    src = open(path).read()
    out = []
    for m in _ENTRY.finditer(src):
        tc_id, comment, msg, msg_sz, sig, pub, ok = m.groups()
        msg_b = _c_bytes(msg)
        sig_b = _c_bytes(sig)
        pub_b = _c_bytes(pub)
        # C string literals drop an explicit trailing NUL; msg_sz is the
        # authority (zero-length msgs encode as "")
        assert len(msg_b) == int(msg_sz), (tc_id, len(msg_b), msg_sz)
        assert len(sig_b) == 64 and len(pub_b) == 32, tc_id
        out.append({
            "tc_id": int(tc_id),
            "comment": comment,
            "msg": msg_b.hex(),
            "sig": sig_b.hex(),
            "pub": pub_b.hex(),
            "ok": bool(int(ok)),
        })
    return out


def extract_malleability() -> list[dict]:
    out = []
    for name, ok in (("should_pass", True), ("should_fail", False)):
        raw = open(os.path.join(
            REF, f"test_ed25519_signature_malleability_{name}.bin"),
            "rb").read()
        assert len(raw) % 96 == 0
        for i in range(len(raw) // 96):
            rec = raw[96 * i : 96 * (i + 1)]
            out.append({
                "tc_id": i,
                "comment": name,
                "msg": b"Zcash".hex(),      # fixed msg in the ref harness
                "sig": rec[:64].hex(),
                "pub": rec[64:96].hex(),
                "ok": ok,
            })
    return out


def main():
    os.makedirs(OUT, exist_ok=True)
    for fname, path in (("wycheproof_ed25519.json",
                         os.path.join(REF, "test_ed25519_wycheproof.c")),
                        ("cctv_ed25519.json",
                         os.path.join(REF, "test_ed25519_cctv.c"))):
        vecs = extract_table(path)
        with open(os.path.join(OUT, fname), "w") as f:
            json.dump(vecs, f, indent=0)
        print(f"{fname}: {len(vecs)} vectors")
    mal = extract_malleability()
    with open(os.path.join(OUT, "malleability_ed25519.json"), "w") as f:
        json.dump(mal, f, indent=0)
    print(f"malleability_ed25519.json: {len(mal)} vectors")


if __name__ == "__main__":
    main()
