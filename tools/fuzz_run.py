#!/usr/bin/env python
"""Open-ended coverage-guided fuzz runner (the local libFuzzer-loop
analogue; CI runs the bounded sweep in tests/test_fuzz_corpus.py).

    python tools/fuzz_run.py [target ...] [--iters N] [--save]

--save writes coverage-growing inputs back into tests/corpus/<target>/ so
the checked-in corpora deepen over time."""

import argparse
import os
import pathlib
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from firedancer_tpu.utils import fuzz  # noqa: E402
from firedancer_tpu.utils.fuzz_targets import TARGETS  # noqa: E402

CORPUS = pathlib.Path(__file__).parent.parent / "tests" / "corpus"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("targets", nargs="*", default=None)
    ap.add_argument("--iters", type=int, default=50_000)
    ap.add_argument("--save", action="store_true")
    args = ap.parse_args()
    names = args.targets or sorted(TARGETS)
    rc = 0
    for name in names:
        seeds = [p.read_bytes() for p in sorted((CORPUS / name).iterdir())]
        grown, findings = fuzz.fuzz(TARGETS[name], seeds, iters=args.iters,
                                    seed=int.from_bytes(os.urandom(4),
                                                        "little"))
        print(f"{name}: {args.iters} iters, +{len(grown)} coverage inputs, "
              f"{len(findings)} findings")
        for data, exc in findings[:10]:
            print(f"  FINDING {type(exc).__name__}: {exc} "
                  f"input={data[:48].hex()}")
            rc = 1
        if args.save:
            d = CORPUS / name
            for b in grown:
                (d / fuzz.corpus_name(b)).write_bytes(b)
    return rc


if __name__ == "__main__":
    sys.exit(main())
