"""Round-9 in-kernel divstep go/no-go: strict vs antipa FULL verify
chains, end to end (docs/perf_ceiling.md round-5/round-10 addenda).

Round 6 measured the halved curve chain with the halving done on host
and killed the lever on the ~590 us/sig host leg.  Round 9 moves the
halving on device (scalar25519.halve_scalar: 250 Bernstein-Yang divstep
iterations + 24 branchless Lagrange rounds), so this A/B charges each
arm EVERYTHING it costs, parse to verdict, over identical inputs:

  strict   ed.verify_batch         256 doubles + 64 var adds + 64 comb
  antipa   ed.verify_batch_antipa  in-kernel halve + 128 doubles +
                                   2x32 var adds + 64 comb + R
                                   decompress add-back

plus a divstep-only microbench (jitted sc.halve_scalar over the same
batch of digest scalars) so the halving's share of the antipa arm is
attributable.  Verdict bit-parity between the arms is asserted on a
mixed valid/corrupt corpus before any timing — a fast wrong answer is
not a result.

Protocol per tools/_bench.py doctrine: same session, both arms jitted,
pipelined dispatch + one draining fetch, median of reps.  The JSON
carries pallas/wiring_only (see _bench.note_wiring): on a non-Pallas
backend both arms lower to the XLA fallback and the ratio is a wiring
check, not the land-or-kill verdict.

Env: B (4096), ITERS (4), REPS (5).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def main():
    from firedancer_tpu.utils import xla_cache
    xla_cache.enable()
    import jax
    import jax.numpy as jnp

    from firedancer_tpu.models.verifier import make_example_batch
    from firedancer_tpu.ops import ed25519 as ed
    from firedancer_tpu.ops import scalar25519 as sc
    from _bench import note_wiring  # noqa: E402

    batch = int(os.environ.get("B", 4096))
    iters = int(os.environ.get("ITERS", 4))
    reps = int(os.environ.get("REPS", 5))

    msgs, lens, sigs, pubs = make_example_batch(
        batch, 128, valid=True, sign_pool=64)

    # parity gate: mixed corpus, bit-identical verdicts required (the
    # honest corpus has no small-torsion defects, so antipa laxity is
    # out of frame here — tests/test_ed25519_antipa.py pins that edge)
    bad = np.asarray(sigs).copy()
    rng = np.random.default_rng(9)
    flip = rng.integers(0, batch, size=max(8, batch // 64))
    for i in flip:
        bad[i, int(rng.integers(0, 64))] ^= 0xFF
    bad = jnp.asarray(bad)
    want = np.asarray(ed.verify_batch(msgs, lens, bad, pubs))
    got = np.asarray(ed.verify_batch_antipa(msgs, lens, bad, pubs))
    if got.tolist() != want.tolist():
        print("PARITY FAILURE: strict and antipa verdicts differ on the "
              "mixed corpus — timing aborted", file=sys.stderr)
        sys.exit(1)
    n_bad = int(batch - want.sum())
    print(f"parity: {batch} rows bit-identical ({n_bad} rejects)",
          file=sys.stderr)

    # divstep microbench input: the real digest scalars k = H(R||A||m)
    r_bytes = sigs[:, :32]
    pre = jnp.concatenate([r_bytes, pubs, msgs], axis=1)
    k_limbs = sc.reduce_512(ed._sha512_k(
        pre, lens.astype(jnp.int32) + 64, batch, False))

    halve = jax.jit(sc.halve_scalar)
    arms = {
        "strict": (jax.jit(ed.verify_batch),
                   (msgs, lens, sigs, pubs)),
        "antipa": (jax.jit(ed.verify_batch_antipa),
                   (msgs, lens, sigs, pubs)),
        "divstep": (lambda kl: halve(kl)[0], (k_limbs,)),
    }
    out = {"batch": batch, "iters": iters, "reps": reps,
           "backend": jax.devices()[0].platform,
           "parity_rows": batch, "parity_rejects": n_bad}
    note_wiring(out, ed._pallas_ok(batch))
    for name, (fn, args) in arms.items():
        t0 = time.perf_counter()
        first = np.asarray(fn(*args))
        print(f"{name}: compile+first {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)
        if name in ("strict", "antipa"):
            assert bool(first.all()), f"{name} arm rejected valid sigs"
        runs = []
        for _ in range(reps):
            t0 = time.perf_counter()
            ok = None
            for _ in range(iters):
                ok = fn(*args)
            np.asarray(ok)
            runs.append((time.perf_counter() - t0) / iters * 1e3)
        out[name + "_ms"] = round(median(runs), 2)
        out[name + "_runs_ms"] = [round(r, 2) for r in sorted(runs)]
        print(f"{name}: {out[name + '_ms']} ms/batch "
              f"{out[name + '_runs_ms']}", file=sys.stderr)
    out["antipa_vps"] = round(batch / (out["antipa_ms"] / 1e3), 1)
    out["strict_vps"] = round(batch / (out["strict_ms"] / 1e3), 1)
    out["divstep_share"] = round(out["divstep_ms"] / out["antipa_ms"], 3)
    out["antipa_vs_strict"] = round(out["strict_ms"] / out["antipa_ms"], 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
