"""Where do the 72 ms at batch 32k go, post-fusion?  Times the full fused
verify, the fused tail alone (precomputed digest), SHA-512 alone (both
backends), and the XLA finish (parse_r + batch-inv + sgn)."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from firedancer_tpu.utils import xla_cache  # noqa: E402
xla_cache.enable()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from firedancer_tpu.models.verifier import make_example_batch  # noqa: E402
from firedancer_tpu.ops import curve_pallas as cpal  # noqa: E402
from firedancer_tpu.ops import ed25519 as ed  # noqa: E402
from firedancer_tpu.ops import sha512 as sh  # noqa: E402
from firedancer_tpu.ops import sha512_pallas as shp  # noqa: E402

B = int(os.environ.get("B", 32768))
msgs, lens, sigs, pubs = make_example_batch(B, 128, valid=True, sign_pool=64)
r_bytes, s_bytes = sigs[:, :32], sigs[:, 32:]
pre = jnp.concatenate([r_bytes, pubs, msgs], axis=1)
lens64 = lens + 64
digest = jax.jit(sh.sha512)(pre, lens64)
np.asarray(digest)
parsed0 = np.asarray(ed._parse_r_bytes(r_bytes)[0])
y_r = jnp.asarray(parsed0)


def timeit(name, fn, *args, iters=24, reps=5):
    f = jax.jit(fn)
    np.asarray(jax.tree_util.tree_leaves(f(*args))[0])
    runs = []
    for _ in range(reps):
        t0 = time.perf_counter()
        o = None
        for _ in range(iters):
            o = f(*args)
        np.asarray(jax.tree_util.tree_leaves(o)[0])
        runs.append((time.perf_counter() - t0) / iters * 1e3)
    runs.sort()
    print(f"{name:28s} {runs[len(runs)//2]:8.2f} ms  "
          f"({runs[0]:.2f}..{runs[-1]:.2f})", flush=True)
    return runs[len(runs) // 2]


full = timeit("full fused verify", ed.verify_batch, msgs, lens, sigs, pubs)
tail = timeit("fused kernel only", lambda s, d, y: cpal.verify_tail_fused(
    pubs, s, d, y)[1], s_bytes, digest, y_r)
sha_x = timeit("sha512 XLA", sh.sha512, pre, lens64)
sha_p = timeit("sha512 pallas", shp.sha512, pre, lens64)


def finish(qx, qz):
    pr = ed._parse_r_bytes(r_bytes)
    ok = jnp.ones((B,), bool)
    return ed._compressed_r_check(qx, None, qz, r_bytes, ok_y=ok,
                                  parsed_r=pr)


_, qx, qz = cpal.verify_tail_fused(pubs, s_bytes, digest, y_r)
qx, qz = jnp.asarray(np.asarray(qx)), jnp.asarray(np.asarray(qz))
fin = timeit("XLA finish (inv+sgn)", finish, qx, qz)
print(f"sum tail+sha_p+finish = {tail + sha_p + fin:.2f} vs full {full:.2f}",
      flush=True)
