#!/usr/bin/env python
"""Generate the cross-client conformance corpus (VERDICT r4 #4) in the
test-vectors `.fix` interchange format (org.solana.sealevel.v1 proto3;
flamenco/test_vectors.py is the codec + runner).

Corpus composition:

1. The hand-derived instruction fixtures (tests/fixtures/
   instr_fixtures.json — every expectation cites the reference C that
   defines the behavior).  These are the SEMANTICS ANCHOR: the generator
   asserts each one's ok/err expectation still holds before recording
   its executed post-state as InstrEffects.
2. Systematic adversarial mutations of every anchor fixture (signer
   stripped, writability stripped, data truncated/flipped), with effects
   captured by execution.  These pin today's behavior against regression
   and exercise the error surface the way the real test-vectors corpus
   does; their expectations are machine-derived, not independently
   hand-verified (the anchors are).
3. Parametric families: lamport/space/seed sweeps over the system
   program's arithmetic edges.
4. ELF-loader fixtures: valid mini sBPF ELFs (entry offsets, call
   graphs) and malformed ones (truncations, bad magic/class/entry),
   effects from ballet/sbpf.load.

Output: tests/fixtures/test_vectors.tar (instr/*.fix + elf_loader/*.fix,
deterministic order and mtimes).  Run tests/test_test_vectors.py to
replay.
"""

import json
import os
import struct
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from firedancer_tpu.ballet import sbpf
from firedancer_tpu.flamenco import fixtures as fxmod
from firedancer_tpu.flamenco import test_vectors as tv
from firedancer_tpu.flamenco.types import SYSTEM_PROGRAM_ID

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "tests", "fixtures", "test_vectors.tar")

corpus: dict[str, bytes] = {}
stats = {"anchor": 0, "mutation": 0, "parametric": 0, "elf": 0}


# ------------------------------------------------------------ instr side


def _effects_from_execution(ctx: dict) -> dict:
    """Run the CONVERTED InstrContext through the exact executor entry
    the replayer uses (tv.execute_instr_ctx) and capture effects — one
    code path for generation and replay, so they cannot diverge."""
    err, txctx = tv.execute_instr_ctx(ctx)
    eff: dict = {"result": 0 if err is None else 1}
    pre = {}
    for a in ctx.get("accounts", []):
        addr = a.get("address", bytes(32))
        if "lamports" in a or a.get("data") or "owner" in a:
            pre[addr] = (int(a.get("lamports", 0)),
                         bytes(a.get("data", b"")),
                         a.get("owner", bytes(32)),
                         bool(a.get("executable", False)))
        else:
            pre[addr] = None
    modified = []
    seen = set()
    for ba in txctx.accounts:
        if ba.pubkey in seen:
            continue
        seen.add(ba.pubkey)
        a = ba.acct
        post = (None if a is None else
                (a.lamports, bytes(a.data), a.owner, a.executable))
        if post == pre.get(ba.pubkey):
            continue
        st = {"address": ba.pubkey}
        if a is not None:
            st.update(lamports=a.lamports, data=bytes(a.data),
                      owner=a.owner, executable=a.executable)
        modified.append(st)
    if modified:
        eff["modified_accounts"] = modified
    rd = getattr(txctx, "return_data", (None, b""))[1]
    if rd:
        eff["return_data"] = bytes(rd)
    return eff


def add_instr(name: str, fx: dict, kind: str):
    ctx = fxmod.json_to_ctx(fx)
    eff = _effects_from_execution(ctx)
    blob = tv.encode("InstrFixture", {"input": ctx, "output": eff})
    assert tv.decode("InstrFixture", blob)  # round-trip sanity
    corpus[f"instr/fixtures/{name}.fix"] = blob
    stats[kind] += 1


def anchors() -> list[dict]:
    with open(os.path.join(ROOT, "tests", "fixtures",
                           "instr_fixtures.json")) as f:
        return json.load(f)


def gen_anchors():
    for fx in anchors():
        # the hand-written expectation must still hold — the corpus is
        # anchored to reference-cited semantics, not to drift
        r = fxmod.replay(fx)
        assert r.passed, f"anchor {r.name} regressed: {r.detail}"
        add_instr(fx["name"], fx, "anchor")


def gen_mutations():
    for fx in anchors():
        base = fx["name"]
        accounts = fx.get("accounts", [])
        # strip each signer
        for i, a in enumerate(accounts):
            if a.get("signer"):
                m = json.loads(json.dumps(fx))
                m["accounts"][i]["signer"] = False
                add_instr(f"{base}__nosign{i}", m, "mutation")
        # strip each writable instr account
        for i in set(fx.get("instr_accounts", [])):
            if accounts[i].get("writable", True):
                m = json.loads(json.dumps(fx))
                m["accounts"][i]["writable"] = False
                add_instr(f"{base}__rdonly{i}", m, "mutation")
        data = bytes.fromhex(fx.get("data", ""))
        # truncations: empty, first byte, half
        for cut in sorted({0, 1, len(data) // 2} - {len(data)}):
            m = dict(fx, data=data[:cut].hex())
            add_instr(f"{base}__trunc{cut}", m, "mutation")
        if data:
            # flipped discriminant and flipped tail byte
            for pos in sorted({0, len(data) - 1}):
                flipped = bytearray(data)
                flipped[pos] ^= 0xFF
                m = dict(fx, data=bytes(flipped).hex())
                add_instr(f"{base}__flip{pos}", m, "mutation")
            # drop the last instr account if any
            if fx.get("instr_accounts"):
                m = dict(fx, instr_accounts=fx["instr_accounts"][:-1])
                add_instr(f"{base}__dropacct", m, "mutation")


def gen_parametric():
    sysid = SYSTEM_PROGRAM_ID

    def acct(i, lamports=0, signer=False, writable=True):
        return {"pubkey": (bytes([0xC0, i]) + bytes(30)).hex(),
                "lamports": lamports, "data": "", "owner": sysid.hex(),
                "signer": signer, "writable": writable, "missing": False}

    # transfer sweep: balances x amounts (incl. overflow-adjacent edges)
    amounts = [0, 1, 999, 10**9, 2**63, 2**64 - 1]
    balances = [0, 1, 10**9, 2**64 - 1]
    for bi, bal in enumerate(balances):
        for ai, amt in enumerate(amounts):
            fx = {
                "name": f"sys_transfer_sweep_b{bi}_a{ai}",
                "program_id": sysid.hex(),
                "data": struct.pack("<I", 2).hex()
                + struct.pack("<Q", amt).hex(),
                "accounts": [acct(1, bal, signer=True), acct(2, 50)],
                "instr_accounts": [0, 1],
                "expect": {"ok": True},  # placeholder; effects captured
            }
            add_instr(fx["name"], fx, "parametric")
    # allocate sweep (space edges incl. over-limit)
    for si, space in enumerate([0, 1, 1024, 10 * 1024 * 1024,
                                10 * 1024 * 1024 + 1, 2**32]):
        fx = {
            "name": f"sys_allocate_sweep_{si}",
            "program_id": sysid.hex(),
            "data": struct.pack("<I", 8).hex() + struct.pack("<Q", space).hex(),
            "accounts": [acct(3, 10**9, signer=True)],
            "instr_accounts": [0],
            "expect": {"ok": True},
        }
        add_instr(fx["name"], fx, "parametric")


# -------------------------------------------------------------- elf side


def add_elf(name: str, elf: bytes, deploy_checks: bool = False):
    try:
        prog = sbpf.load(elf)
        out = {
            "rodata": prog.rodata, "rodata_sz": len(prog.rodata),
            "text_cnt": len(prog.text) // 8, "text_off": prog.text_off,
            "entry_pc": prog.entry_pc,
            "calldests": sorted(prog.calldests),
        }
    except Exception:
        out = None
    fix = {"input": {"elf": {"data": elf}, "elf_sz": len(elf),
                     "deploy_checks": deploy_checks}}
    if out is not None:
        fix["output"] = out
    corpus[f"elf_loader/fixtures/{name}.fix"] = tv.encode(
        "ELFLoaderFixture", fix)
    stats["elf"] += 1


def gen_elf():
    progs = {
        "ret1234": "mov r0, 1234\nexit",
        "branchy": """
            mov r0, 0
            mov r1, 5
            jeq r1, 5, +1
            exit
            mov r0, 7
            exit""",
        "arith": """
            mov r0, 21
            lsh r0, 1
            add r0, 0
            exit""",
    }
    for name, src in progs.items():
        text = sbpf.asm(src)
        add_elf(f"ok_{name}", sbpf.mini_elf(text))
        # nonzero entry offsets
        add_elf(f"ok_{name}_entry8", sbpf.mini_elf(
            sbpf.ins(0x95) + text, entry_sym_value=8))
    # call graph: function at pc 4 reached via call imm (registers a
    # calldest); entry falls through to exit
    callprog = (sbpf.ins(0x85, imm=3)           # call +3 -> pc 4
                + sbpf.ins(0xB7, dst=0, imm=1)  # mov r0, 1
                + sbpf.ins(0x95)                # exit
                + sbpf.ins(0x95)                # pad
                + sbpf.ins(0xB7, dst=0, imm=9)  # callee
                + sbpf.ins(0x95))
    add_elf("ok_call_graph", sbpf.mini_elf(callprog))

    base = sbpf.mini_elf(sbpf.asm("mov r0, 1\nexit"))
    # malformed family: truncations at structural boundaries
    for cut in (0, 3, 4, 16, 63, 64, 100, len(base) - 1):
        add_elf(f"bad_trunc_{cut}", base[:cut])
    add_elf("bad_magic", b"XELF" + base[4:])
    add_elf("bad_class32", base[:4] + b"\x01" + base[5:])
    add_elf("bad_bigendian", base[:5] + b"\x02" + base[6:])
    # entry symbol out of .text
    add_elf("bad_entry_oob",
            sbpf.mini_elf(sbpf.asm("mov r0, 1\nexit"),
                          entry_sym_value=4096))
    # text not multiple of 8
    odd = sbpf.mini_elf(sbpf.asm("mov r0, 1\nexit") + b"\x95")
    add_elf("bad_text_odd", odd)
    # byte-flip sweep over the header region
    for pos in range(0, 64, 7):
        mut = bytearray(base)
        mut[pos] ^= 0xA5
        add_elf(f"fuzz_hdr_{pos}", bytes(mut))


def main():
    gen_anchors()
    gen_mutations()
    gen_parametric()
    gen_elf()
    tv.write_tar(OUT, corpus)
    total = len(corpus)
    print(f"wrote {OUT}: {total} fixtures {stats}")
    assert total >= 1000, total


if __name__ == "__main__":
    main()
