"""Round-8 host-wall A/B: legacy copies-on ingest (`_pack_into` host
repack per batch) vs the zero-repack views-on path (`submit_rows` over
dcache-layout rows), SAME harness, median of reps.

Arms:
  legacy  FDTPU_INGEST_LEGACY_PACK=1 — the pipeline slices each frag out
          of a (buf, offs) window and `_pack_into` scatters msg/sig/pub
          into a fresh blob per batch (the pre-r8 shape: rx memcpy +
          region bytes() + bucket scatter = 3 payload copies per frag)
  views   rows arrive pre-stamped in device-blob layout (the packed-wire
          dcache format) and go straight to dispatch_blob: 0 payload
          copies between ring rx and device upload

Both arms run `bench.measure_pipe_host_us_rows`, which stubs the device
fn (all-pass) so the wall is pure host work — this experiment measures
the wiring, not the verifier.  Run wherever; the recorded backend labels
the run.  On the r8 dev container (1-core CPU) the measured medians were
legacy 4.28 us/txn vs views 3.58 us/txn (~16% host-wall cut) at B=1024.

Env: B=batch (1024), NTXN (8192), REPS (5).
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def main():
    from firedancer_tpu.utils import xla_cache
    xla_cache.enable()
    import jax

    import bench

    batch = int(os.environ.get("B", 1024))
    n_txn = int(os.environ.get("NTXN", batch * 8))
    reps = int(os.environ.get("REPS", 5))

    out = {"batch": batch, "n_txn": n_txn, "reps": reps,
           "backend": jax.devices()[0].platform}
    for name, env in (("legacy", "1"), ("views", "0")):
        os.environ["FDTPU_INGEST_LEGACY_PACK"] = env
        try:
            bench.measure_pipe_host_us_rows(batch, n_txn)  # warm rep
            runs = [bench.measure_pipe_host_us_rows(batch, n_txn)
                    for _ in range(reps)]
        finally:
            os.environ.pop("FDTPU_INGEST_LEGACY_PACK", None)
        out[name + "_us_txn"] = round(median(runs), 3)
        out[name + "_runs"] = [round(r, 3) for r in sorted(runs)]
        print(f"{name}: {out[name + '_us_txn']:.2f} us/txn  "
              f"{out[name + '_runs']}", file=sys.stderr)
    out["views_vs_legacy"] = round(
        out["legacy_us_txn"] / out["views_us_txn"], 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
