"""Round-11 host fast-lane A/B: per-txn Python host path vs the
one-pass native submit/harvest kernel, SAME harness, median of reps.

Arms (all over `bench.measure_pipe_host_us_rows`, device fn stubbed
all-pass so the wall is pure host work):
  legacy    FDTPU_INGEST_LEGACY_PACK=1 — pre-r8 `_pack_into` host repack,
            per-txn Python assembly on harvest
  fallback  FDTPU_INGEST_NATIVE_HOSTPATH=0 — packed row views with the
            vectorised NumPy submit/finish fallback (bit-identical to
            the C kernel, no .so required)
  native    default — `fd_hostpath_submit_rows` (strided tag gather +
            tcache query + dup mask, one C call per frag) and
            `fd_hostpath_finish_rows` (verdict mask + conditional dedup
            insert + wire build into a caller arena, one C call per
            harvest)
plus the packed-egress arm over `bench.measure_hostpath_packed_egress`:
  packed    egress_packed=True — the verify tile ships ONE arena frag
            (u32 offs[k+1] | wires) per harvest instead of k per-txn
            frags; the returned identity bool asserts the arena bytes
            equal the legacy per-txn wires.

The r11 land bar is pipe_host_us_txn_packed <= 1.8 us/txn (seed: 3.58).
On the r11 dev container (B=1024) the medians were legacy 2.57 /
fallback 1.09 / native 0.78 / packed 0.43 us/txn — the historic 3.58
"host wall" was mostly first-touch page faults on the lazily-mapped
tcache, now pre-faulted in fd_tcache_new; the arms above measure what
remains after that fix.

Env: B=batch (1024), NTXN (B*8), REPS (5).
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def main():
    from firedancer_tpu.utils import xla_cache
    xla_cache.enable()
    import jax

    import bench

    batch = int(os.environ.get("B", 1024))
    n_txn = int(os.environ.get("NTXN", batch * 8))
    reps = int(os.environ.get("REPS", 5))

    out = {"batch": batch, "n_txn": n_txn, "reps": reps,
           "backend": jax.devices()[0].platform}
    arms = (("legacy", {"FDTPU_INGEST_LEGACY_PACK": "1"}),
            ("fallback", {"FDTPU_INGEST_NATIVE_HOSTPATH": "0"}),
            ("native", {}))
    for name, env in arms:
        os.environ.update(env)
        try:
            bench.measure_pipe_host_us_rows(batch, n_txn)  # warm rep
            runs = [bench.measure_pipe_host_us_rows(batch, n_txn)
                    for _ in range(reps)]
        finally:
            for k in env:
                os.environ.pop(k, None)
        out[name + "_us_txn"] = round(median(runs), 3)
        out[name + "_runs"] = [round(r, 3) for r in sorted(runs)]
        print(f"{name}: {out[name + '_us_txn']:.2f} us/txn  "
              f"{out[name + '_runs']}", file=sys.stderr)

    bench.measure_hostpath_packed_egress(batch, n_txn)  # warm rep
    pruns, ident = [], True
    for _ in range(reps):
        us, ok = bench.measure_hostpath_packed_egress(batch, n_txn)
        pruns.append(us)
        ident = ident and bool(ok)
    out["packed_us_txn"] = round(median(pruns), 3)
    out["packed_runs"] = [round(r, 3) for r in sorted(pruns)]
    out["egress_packed_identical"] = ident
    print(f"packed: {out['packed_us_txn']:.2f} us/txn  "
          f"{out['packed_runs']}  identical={ident}", file=sys.stderr)

    out["native_vs_legacy"] = round(
        out["legacy_us_txn"] / out["native_us_txn"], 3)
    out["native_vs_fallback"] = round(
        out["fallback_us_txn"] / out["native_us_txn"], 3)
    out["packed_vs_native"] = round(
        out["native_us_txn"] / out["packed_us_txn"], 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
