"""Experiment: Pallas VMEM-resident point-double chain vs XLA fusion.

Hypothesis: the XLA-compiled double (28.3 ns/lane, ~25% ALU efficiency)
is bounded by HBM round-trips between fusion islands; a Pallas kernel
that keeps all limb planes in VMEM across a chain of doublings should
approach the VPU ALU floor.

Methodology per tools/_bench.py: slope timing, np.asarray sync.
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from _bench import slope, timed  # noqa: E402

from firedancer_tpu.ops import curve25519 as cv
from firedancer_tpu.ops import f25519 as fe

# fe constants are array constants in the jit path (fast XLA compiles) but
# Mosaic rejects captured arrays inside kernels — swap in the scalar-literal
# constructors for this experiment's fe-code-inside-pallas usage.
fe.const = lambda v, ndim=1: fe._limb_const(fe._to_limbs_py(v % fe.P), ndim)
fe._bias = lambda ndim: fe._limb_const(fe._BIAS_PY, ndim)

BATCH = 4096


def rand_point(rng, batch):
    a = jnp.asarray(rng.integers(0, 4096, size=(22, batch), dtype=np.uint32))
    b = jnp.asarray(rng.integers(0, 4096, size=(22, batch), dtype=np.uint32))
    return cv.Point(a, b, fe.ones((batch,)), fe.zeros((batch,)))


def make_xla_chain(steps):
    rng = np.random.default_rng(0)
    p = rand_point(rng, BATCH)

    @jax.jit
    def f(pt):
        def body(i, q):
            return cv.double(q)
        return jax.lax.fori_loop(0, steps, body, pt)

    return f, (p,)


def make_pallas_chain(steps, blk=512, inner=None, interpret=False,
                      batch=BATCH):
    """Pallas kernel: `steps` doublings with limbs resident in VMEM.

    inner: if set, the kernel unrolls `inner` doubles inside a fori_loop of
    steps//inner trips (keeps the Mosaic program small at large `steps`).
    """
    if inner is None:
        inner = steps
    assert steps % inner == 0
    rng = np.random.default_rng(0)
    p = rand_point(rng, batch)

    def kernel(x_ref, y_ref, z_ref, t_ref, xo, yo, zo, to):
        # trailing batch dims (1, blk): keeps every row op 2D for Mosaic
        pt = cv.Point(
            x_ref[...][:, None, :], y_ref[...][:, None, :],
            z_ref[...][:, None, :], t_ref[...][:, None, :])

        def body(i, q):
            for _ in range(inner):
                q = cv.double(q)
            return q

        pt = jax.lax.fori_loop(0, steps // inner, body, pt)
        xo[...] = pt.X[:, 0, :]
        yo[...] = pt.Y[:, 0, :]
        zo[...] = pt.Z[:, 0, :]
        to[...] = pt.T[:, 0, :]

    spec = pl.BlockSpec((fe.NLIMB, blk), lambda i: (0, i))

    @jax.jit
    def f(pt):
        outs = pl.pallas_call(
            kernel,
            out_shape=[jax.ShapeDtypeStruct((fe.NLIMB, batch), jnp.uint32)] * 4,
            grid=(batch // blk,),
            in_specs=[spec] * 4,
            out_specs=[spec] * 4,
            interpret=interpret,
        )(pt.X, pt.Y, pt.Z, pt.T)
        return cv.Point(*outs)

    return f, (p,)


def check_correct():
    rng = np.random.default_rng(1)
    p = rand_point(rng, 512)

    @jax.jit
    def fx(pt):
        for _ in range(8):
            pt = cv.double(pt)
        return pt

    want = fx(p)
    for blk in (128, 512):
        f, _ = make_pallas_chain(8, blk=blk, batch=512)
        got = f(p)
        for w, g in zip(want, got):
            np.testing.assert_array_equal(np.asarray(w), np.asarray(g))
    print("correctness: pallas dbl-chain == xla dbl-chain", flush=True)


def main():
    check_correct()
    slope("xla double chain", make_xla_chain, 512, 1536, BATCH, "dbl/lane")
    for blk in (256, 512, 1024):
        try:
            slope(
                f"pallas double chain blk={blk}",
                lambda s, blk=blk: make_pallas_chain(s, blk=blk, inner=8),
                512, 1536, BATCH, "dbl/lane")
        except Exception as e:  # lowering failures are data too
            print(f"pallas blk={blk} FAILED: {type(e).__name__}: {e}",
                  flush=True)


if __name__ == "__main__":
    main()
