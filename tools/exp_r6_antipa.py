"""Round-6 Antipa scalar-halving go/no-go (docs/perf_ceiling.md round-5
addendum: model says ~10-13% net for a large risky kernel — measure it).

Isolates the quantity the lever changes: the variable-scalar curve
chain.  Same session, both arms jitted over PRE-STAGED device inputs
(windows, decompressed -A, parsed R bytes), pipelined dispatch + one
draining fetch, median of reps.

  full     [s]B + [k](-A) via double_scalar_mul_base: 256 doubles +
           64 var-table adds + 64 comb adds (the production shape;
           R stays compressed — round-4 elimination)
  halved   decompress(R) + [u](-A) + [|v|](R~) over 32 windows +
           [vS mod L]B comb: 128 doubles + 2x32 var adds + 64 comb
           adds + the R decompress ADD-BACK + a second var table

The halved arm charges everything the lever costs EXCEPT the host
half-gcd (reported separately as host_us_per_sig — the production
version would need an in-kernel ~590-iteration divstep instead).

Env: B (4096), ITERS (4), REPS (5).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def main():
    from firedancer_tpu.utils import xla_cache
    xla_cache.enable()
    import jax
    import jax.numpy as jnp

    from firedancer_tpu.models.verifier import make_example_batch
    from firedancer_tpu.ops import curve25519 as cv
    from firedancer_tpu.ops import ed25519 as ed
    from firedancer_tpu.ops import f25519 as fe
    from firedancer_tpu.ops import scalar25519 as sc
    from _bench import note_wiring  # noqa: E402

    batch = int(os.environ.get("B", 4096))
    iters = int(os.environ.get("ITERS", 4))
    reps = int(os.environ.get("REPS", 5))

    msgs, lens, sigs, pubs = make_example_batch(
        batch, 128, valid=True, sign_pool=64)
    r_bytes, s_bytes = sigs[:, :32], sigs[:, 32:]

    # staged inputs (both arms): decompressed -A, digest scalar windows
    _, a_pt = cv.decompress(pubs)
    a_neg = cv.neg(a_pt)
    pre = jnp.concatenate([r_bytes, pubs, msgs], axis=1)
    k_limbs = sc.reduce_512(ed._sha512_k(
        pre, lens.astype(jnp.int32) + 64, batch, False))
    s_wins = cv.scalar_windows(s_bytes)
    k_wins = sc.limbs_to_windows(k_limbs)

    # host leg of the halved arm (timed separately)
    kh = np.asarray(k_limbs)
    sh_ = np.asarray(s_bytes)
    t0 = time.perf_counter()
    us, vs, cs = [], [], []
    for b in range(batch):
        k = sum(int(kh[i, b]) << (12 * i) for i in range(kh.shape[0]))
        u, v = ed._halve_scalar_host(k)
        s_int = int.from_bytes(bytes(sh_[b]), "little") % sc.L
        us.append(u)
        vs.append(v)
        cs.append((s_int * v) % sc.L)
    host_us = (time.perf_counter() - t0) / batch * 1e6
    u_wins = jnp.asarray(ed._int_windows(us, 32))
    av_wins = jnp.asarray(ed._int_windows([abs(v) for v in vs], 32))
    c_wins = jnp.asarray(ed._int_windows(cs, 64))
    v_pos = jnp.asarray(np.array([v > 0 for v in vs]))

    @jax.jit
    def full(sw, kw, an):
        q = cv.double_scalar_mul_base(sw, kw, an)
        return fe.is_zero(q.X)          # tiny output forces the chain

    @jax.jit
    def halved(uw, avw, an, rb, vp, cw):
        _, r_pt = cv.decompress(rb)     # the add-back cost
        r_neg = cv.neg(r_pt)
        r_eff = cv.Point(*(jnp.where(vp[None, :], n, p)
                           for n, p in zip(r_neg, r_pt)))
        q = cv.add(cv.double_scalar_mul_halved(uw, avw, an, r_eff,
                                               nwin=32),
                   cv.scalar_mul_base(cw))
        return fe.is_zero(q.X) & fe.eq(q.Y, q.Z)

    arms = {
        "full": lambda: full(s_wins, k_wins, a_neg),
        "halved": lambda: halved(u_wins, av_wins, a_neg, r_bytes,
                                 v_pos, c_wins),
    }
    out = {"batch": batch, "iters": iters, "reps": reps,
           "backend": jax.devices()[0].platform,
           "host_us_per_sig": round(host_us, 2)}
    note_wiring(out, ed._pallas_ok(batch))
    for name, fn in arms.items():
        t0 = time.perf_counter()
        first = np.asarray(fn())
        print(f"{name}: compile+first {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)
        if name == "halved":
            assert bool(first.all()), "halved arm rejected valid sigs"
        runs = []
        for _ in range(reps):
            t0 = time.perf_counter()
            ok = None
            for _ in range(iters):
                ok = fn()
            np.asarray(ok)
            runs.append((time.perf_counter() - t0) / iters * 1e3)
        out[name + "_ms"] = round(median(runs), 2)
        out[name + "_runs_ms"] = [round(r, 2) for r in sorted(runs)]
        print(f"{name}: {out[name + '_ms']} ms/batch "
              f"{out[name + '_runs_ms']}", file=sys.stderr)
    out["halved_vs_full"] = round(
        out["full_ms"] / out["halved_ms"], 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
