"""Round-4 profiling: why does RLC lose to strict below 64k lanes?

Hypothesis (docs/perf_ceiling.md): the strict path moved its scalar mod-L
chain into the reduce_recode Pallas kernel, but verify_batch_rlc still
runs reduce_512 + mul_mod_l + limbs_to_windows as XLA serial row chains —
measured at 32k those cost MORE than the dsm kernel itself.

Stages measured (batch 32k, slope-timed):
  A. full strict verify
  B. full rlc verify (m=8, m=16)
  C. rlc scalar chain alone (XLA): reduce_512 + 2x mul_mod_l + windows
  D. the two MSMs alone (decompress + windows precomputed)
  E. decompress alone
Plus upload bandwidth vs blob size (the tile-path ingest wall).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
from _bench import timed  # noqa: E402

from firedancer_tpu.utils import xla_cache

xla_cache.enable()

BATCH = 32768


def stage_breakdown():
    from firedancer_tpu.models.verifier import make_example_batch
    from firedancer_tpu.ops import curve_pallas as cpal
    from firedancer_tpu.ops import curve25519 as cv
    from firedancer_tpu.ops import ed25519 as ed
    from firedancer_tpu.ops import scalar25519 as sc
    from firedancer_tpu.ops import sha512_pallas as shp

    msgs, lens, sigs, pubs = make_example_batch(BATCH, 128, True, sign_pool=32)
    rng = np.random.default_rng(3)
    z = jnp.asarray(rng.integers(0, 256, (BATCH, 16), np.uint8))

    # A/B: full paths
    strict = jax.jit(ed.verify_batch)
    t = timed(strict, msgs, lens, sigs, pubs)
    print(f"A strict full           {t*1e3:8.1f} ms  {BATCH/t:10.0f} v/s",
          flush=True)

    for m in (8,):
        from functools import partial
        rlc = jax.jit(partial(ed.verify_batch_rlc, m=m))
        try:
            t = timed(rlc, msgs, lens, sigs, pubs, z)
            print(f"B rlc full (m={m:2d})       {t*1e3:8.1f} ms  "
                  f"{BATCH/t:10.0f} v/s", flush=True)
        except Exception as e:
            print(f"B rlc full (m={m:2d})  FAILED {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    # C: the XLA scalar chain alone as used by verify_batch_rlc
    @jax.jit
    def scalar_chain(sigs, digest, z_bytes):
        s_bytes = sigs[:, 32:]
        k_limbs = sc.reduce_512(digest)
        z_limbs = sc.bytes_to_limbs(z_bytes, 11)
        s_limbs = sc.bytes_to_limbs(s_bytes, 22)
        w_limbs = sc.mul_mod_l(k_limbs, z_limbs)
        c_limbs = sc.sum_mod_l(sc.mul_mod_l(s_limbs, z_limbs), axis=0)
        return sc.limbs_to_windows(w_limbs), c_limbs

    digest = jnp.zeros((BATCH, 64), jnp.uint8)
    t = timed(scalar_chain, sigs, digest, z)
    print(f"C rlc scalar chain XLA  {t*1e3:8.1f} ms", flush=True)

    # C2: the round-4 Pallas replacement
    @jax.jit
    def scalar_chain_kernel(sigs, digest, z_bytes):
        ok_s, ww, zw, zs = cpal.rlc_recode(sigs[:, 32:], digest, z_bytes,
                                           blk=128)
        return ok_s, ww, zw, sc.sum_mod_l(zs, axis=0)
    t = timed(scalar_chain_kernel, sigs, digest, z)
    print(f"C2 rlc_recode kernel    {t*1e3:8.1f} ms", flush=True)

    # D: the two MSMs alone
    ok, small, a_pt = cpal.decompress(pubs, blk=128)
    ok2, small2, r_pt = cpal.decompress(sigs[:, :32], blk=128)
    wins64 = jnp.asarray(
        rng.integers(0, 16, (64, BATCH), np.uint32))
    wins32 = jnp.asarray(
        rng.integers(0, 16, (32, BATCH), np.uint32))
    na = cv.neg(a_pt)
    nr = cv.neg(r_pt)

    for m in (8, 16):
        @jax.jit
        def msms(w64, w32, na_pl, nr_pl, _m=m):
            acc_a = cpal.msm(w64, cv.Point(*na_pl), m=_m, nwin=64)
            acc_r = cpal.msm(w32, cv.Point(*nr_pl), m=_m, nwin=32)
            return cv.add(acc_a, acc_r)
        try:
            t = timed(msms, wins64, wins32, tuple(na), tuple(nr))
            print(f"D msm pair (m={m:2d})       {t*1e3:8.1f} ms", flush=True)
        except Exception as e:
            print(f"D msm pair (m={m:2d})  FAILED {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    # E: decompress alone (both points)
    @jax.jit
    def dec(pubs, rb):
        o1, s1, a = cpal.decompress(pubs, blk=128)
        o2, s2, r = cpal.decompress(rb, blk=128)
        return o1 & o2 & ~s1 & ~s2, a.X[0], r.X[0]
    t = timed(dec, pubs, sigs[:, :32])
    print(f"E decompress x2         {t*1e3:8.1f} ms", flush=True)

    # F: sha512 alone
    pre = jnp.concatenate([sigs[:, :32], pubs, msgs], axis=1)
    sha = jax.jit(lambda p, l: shp.sha512(p, l))
    t = timed(sha, pre, lens + 64)
    print(f"F sha512 pallas         {t*1e3:8.1f} ms", flush=True)

    # G: strict tail (reduce_recode + dsm_tail_q + compressed-R check)
    @jax.jit
    def strict_tail(sb, rb, dg, a_pl):
        ok_s, wins = cpal.reduce_recode(sb, dg, blk=128)
        y_r, _sg, _sm = ed._parse_r_bytes(rb)
        ok_y, qx, qz = cpal.dsm_tail_q(wins, cv.Point(*a_pl), y_r, blk=128)
        return ok_s & ed._compressed_r_check(qx, None, qz, rb, ok_y=ok_y)
    t = timed(strict_tail, sigs[:, 32:], sigs[:, :32], digest, tuple(a_pt))
    print(f"G strict recode+tail    {t*1e3:8.1f} ms", flush=True)


def upload_scaling():
    for mb in (4, 16, 64):
        blob = np.zeros((mb << 20,), np.uint8)
        jax.device_put(blob).block_until_ready()
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            jax.device_put(blob).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        print(f"upload {mb:3d} MB: {len(blob)/best/1e6:8.1f} MB/s",
              flush=True)
    # concurrent: 8 x 8MB dispatched together
    blobs = [np.zeros((8 << 20,), np.uint8) for _ in range(8)]
    jax.device_put(blobs[0]).block_until_ready()
    t0 = time.perf_counter()
    devs = [jax.device_put(b) for b in blobs]
    for d in devs:
        d.block_until_ready()
    dt = time.perf_counter() - t0
    print(f"upload 8x8 MB concurrent: {64*(1<<20)/dt/1e6:8.1f} MB/s",
          flush=True)


if __name__ == "__main__":
    print(f"devices: {jax.devices()}", flush=True)
    stage_breakdown()
