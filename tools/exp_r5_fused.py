"""Round-5 fused-kernel A/B: split (r4 layout, 3 kernels) vs fused
(1 kernel) strict verify at the bench shape, same session, pipelined
dispatch + one draining fetch, median of reps.  Run on the real chip."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def measure(fn, args, iters=24, reps=5):
    runs = []
    for _ in range(reps):
        t0 = time.perf_counter()
        ok = None
        for _ in range(iters):
            ok = fn(*args)
        np.asarray(ok)
        runs.append(args[2].shape[0] * iters / (time.perf_counter() - t0))
    runs.sort()
    return runs[len(runs) // 2], runs


def main():
    from firedancer_tpu.utils import xla_cache
    xla_cache.enable()
    import jax

    from firedancer_tpu.models.verifier import make_example_batch
    from firedancer_tpu.ops import ed25519 as ed

    batch = int(os.environ.get("B", 32768))
    args = make_example_batch(batch, 128, valid=True, sign_pool=64)

    results = {}
    for name, env in (("split", "1"), ("fused", "")):
        os.environ["FDTPU_NO_FUSED"] = env
        if not env:
            os.environ.pop("FDTPU_NO_FUSED", None)
        # fresh function identity per mode: two jax.jit(ed.verify_batch)
        # wrappers share one pjit cache entry and the second would silently
        # reuse the first's executable (env is read at trace time)
        fn = jax.jit(lambda m, l, s, p, _n=name: ed.verify_batch(m, l, s, p))
        t0 = time.perf_counter()
        ok = fn(*args)
        good = bool(np.asarray(ok).all())
        print(f"{name}: compile+first {time.perf_counter()-t0:.1f}s "
              f"correct={good}", flush=True)
        assert good
        med, runs = measure(fn, args)
        results[name] = med
        print(f"{name}: {med:,.0f} v/s  (runs {runs[0]:,.0f}..{runs[-1]:,.0f})",
              flush=True)
    print(f"fused/split = {results['fused']/results['split']:.3f}", flush=True)


if __name__ == "__main__":
    main()
