"""Shared measurement harness for the TPU experiment scripts.

The methodology IS the result (see project memory / docs/perf_ceiling.md):
  * np.asarray() is the only true sync on the tunneled TPU;
  * DISPATCH back-to-back dispatches amortize the ~100 ms tunnel RTT
    (the in-order device queue drains on the final fetch);
  * rates are SLOPES over two step counts so RTT + dispatch overhead
    cancel;
  * loop bodies must carry data dependence or XLA hoists them.
"""

import sys
import time

import jax
import numpy as np

DISPATCH = 6


def note_wiring(out: dict, pallas_ok: bool) -> dict:
    """Stamp an A/B result dict with whether this run can render a kernel
    verdict.  When the Pallas path is unavailable (wrong platform, ragged
    batch, FDTPU_NO_PALLAS) both arms lower to the same XLA fallback, so
    the measured ratio only proves the WIRING works — mark the JSON and
    warn loudly so a CPU number is never quoted as a perf result."""
    out["pallas"] = bool(pallas_ok)
    out["wiring_only"] = not pallas_ok
    if out["wiring_only"]:
        bar = "!" * 72
        print(f"{bar}\n"
              "! WIRING-ONLY RUN: no Pallas backend for this batch/platform.\n"
              "! Arms measure the XLA fallback; ratios below check plumbing,\n"
              "! they are NOT a kernel verdict.  Rerun on TPU to decide.\n"
              f"{bar}", file=sys.stderr, flush=True)
    return out


def timed(fn, *args):
    out = fn(*args)
    jax.tree_util.tree_map(lambda x: np.asarray(x), out)  # warm + sync
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(DISPATCH):
            out = fn(*args)
        jax.tree_util.tree_map(lambda x: np.asarray(x), out)
        best = min(best, (time.perf_counter() - t0) / DISPATCH)
    return best


def slope(name, make_chain, s1, s2, work_per_step, unit="op"):
    """make_chain(steps) -> (jitted_fn, args).  Prints + returns s/unit."""
    f1, a1 = make_chain(s1)
    f2, a2 = make_chain(s2)
    t1, t2 = timed(f1, *a1), timed(f2, *a2)
    per_unit = (t2 - t1) / (s2 - s1) / work_per_step
    print(f"{name:44s} {t1*1e3:8.1f}/{t2*1e3:8.1f} ms "
          f"-> {per_unit*1e9:9.4f} ns/{unit} "
          f"({1/per_unit/1e6:10.2f} M{unit}/s)", flush=True)
    return per_unit
