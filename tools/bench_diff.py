"""Diff the repo's accumulated bench result files (BENCH_r*.json, one
per CI round: {"n": round, "parsed": {"metric", "value", "unit"}}) and
flag run-over-run regressions past a threshold.

Per metric, prints the run series with deltas vs the previous round and
vs the series best, then a verdict line.  A metric regresses when the
latest round is more than --threshold (default 5%) worse than the
previous round; direction comes from the metric itself (latency-ish
metrics are lower-is-better, everything else higher-is-better).

Mostly non-fatal in CI: ci.sh runs this as an advisory step — exit 3
marks a regression for a human to look at without failing the build.
The exception is the ENFORCED set (host-path us/txn, round 11): those
metrics regressing more than --enforced-threshold (default 10%)
run-over-run exits 4, which ci.sh treats as fatal on the CPU tier.

Usage:  python tools/bench_diff.py [--glob 'BENCH_r*.json'] [--threshold 0.05]
"""

import argparse
import glob
import json
import os
import sys

_LOWER_IS_BETTER = ("latency", "_ns", "_ms", "stall", "jitter", "p50",
                    "p99", "converge", "revert", "us/txn", "us/set",
                    "us/tick", "us/pkt", "wiring", "dup_verdicts",
                    "lost_verdicts")

# Sub-metrics lifted out of the headline record into their own series.
# antipa_vps is a plain throughput (higher is better); antipa_vs_strict
# is the halved-chain speedup ratio whose land bar is 1.05 — a drop
# below threshold is exactly the regression worth flagging, so it rides
# the default higher-is-better direction (neither name trips the
# lower-is-better substrings above).  Rounds whose BENCH file predates a
# field simply contribute no points, so history stays green.
_SUB_METRICS = {
    "antipa_vps": "verifies/sec",
    "antipa_strict_vps": "verifies/sec",
    "antipa_vs_strict": "x_vs_strict",
    # closed-loop tuner lane: time-to-converge creeping up or reverts
    # appearing in steady state are both policy regressions (the
    # "converge"/"revert" substrings route them lower-is-better)
    "autotune_converge_s": "seconds",
    "autotune_revert_cnt": "reverts",
    # round-11 host-path lane: per-txn host cost of the zero-copy rows
    # path (views arm) and of the packed-verdict-egress arm — the
    # "us/txn" unit routes both lower-is-better
    "pipe_host_us_txn_packed": "us/txn",
    "hostpath_us_txn": "us/txn",
    # round-12 drain lane (opt-in, FDTPU_BENCH_DRAIN=1): flush cost of
    # the DRAIN state machine and the verdict gap across a zero-loss
    # rolling restart — the "_ms" substring routes both lower-is-better;
    # advisory only (not _ENFORCED): the lane timeshare-jitters too much
    # on a 1-core host to gate a build on
    "drain_flush_ms": "ms",
    "restart_gap_ms": "ms",
    # round-13 batched shred lane: recovered shreds/s and merkle walks/s
    # ride higher-is-better; per-set recover cost routes lower-is-better
    # via the "us/set" unit token; the batched-vs-perset speedup ratio is
    # the land bar (>= 3 on device) and a drop is the regression.
    # Advisory on CPU hosts (wiring-only numbers timeshare-jitter).
    "shred_rps": "shreds/sec",
    "shred_merkle_vps": "roots/sec",
    "shred_recover_us_set": "us/set",
    "shred_batch_vs_perset": "x_vs_perset",
    # round-14 leader lane: device PoH hash rate and per-tick span cost
    # (the "us/tick" token routes the tick cost lower-is-better), host
    # pack scheduler per-txn cost ("us/txn"), and the batched-vs-serial
    # span speedup ratio (land bar on device; wiring-only on CPU —
    # leader_wiring_only rides along as an int so a CPU round never
    # poses as a device land, and the "wiring" token keeps a 0 -> 1
    # flip from reading as an improvement)
    "poh_hps": "hashes/sec",
    "poh_us_tick": "us/tick",
    "pack_txn_us": "us/txn",
    "poh_batch_vs_serial": "x_vs_serial",
    "leader_wiring_only": "wiring_flag",
    # round-15 sharded-pack + speculation lane: auto-path pack cost is
    # ENFORCED below (native C hot loop; the 4x land bar lives here),
    # the pure-Python fallback rides advisory so a fallback regression
    # still surfaces; the splice speedup ratio is the K-tick spec-miss
    # land metric (higher is better), splice cost routes lower via
    # "us/" ("us/splice" unit token below)
    "pack_txn_us_fallback": "us/txn",
    "pack_native": "native_flag",
    "poh_splice_us": "us/tick",
    "poh_splice_vs_full": "x_vs_full",
    # round-16 burst packet-protection lane: e2e wire verdicts/sec and
    # server-side datagram rate ride higher-is-better; the per-packet
    # AEAD+HP cost of one burst-decrypt call routes lower-is-better via
    # the "us/pkt" unit token (native C engine ENFORCED below, the
    # NumPy fallback advisory so a fallback-path regression still
    # surfaces).  Rounds whose BENCH predates the lane contribute no
    # points, so old history stays green.
    "net_vps": "verdicts/sec",
    "net_pps": "pkts/sec",
    "quic_crypto_us_pkt": "us/pkt",
    "quic_crypto_us_pkt_fallback": "us/pkt",
    # round-17 fleet lane: host-loss failover latency routes lower via
    # "_ms"; the two exactly-once invariants route lower via their own
    # "dup_verdicts"/"lost_verdicts" tokens (NOT bare "verdicts", which
    # would flip net_vps's "verdicts/sec" unit) — recorded as 0, so ANY
    # duplicated or lost verdict is an infinite-percent regression and
    # the diff flags it.  fleet_hosts is scale context (more hosts
    # covered is the better direction, the default).
    "fleet_hosts": "hosts",
    "fleet_failover_ms": "ms",
    "fleet_dup_verdicts": "dup_verdicts",
    "fleet_lost_verdicts": "lost_verdicts",
}

# Metrics whose regression FAILS the build (exit 4) instead of the
# advisory exit 3.  The host-path us/txn pair is the round-11 tentpole's
# hard floor: a >10% run-over-run loss means someone re-introduced a
# per-txn Python hop on the hot path.  pack_txn_us joins in round 15:
# the native schedule loop's 4x win is a land bar, and a >10% loss means
# the C path stopped building (auto fell back) or someone put Python
# back on the per-txn path.  net_vps joins in round 16: the burst
# packet-protection engine's 2x e2e win is a land bar, and a >10% loss
# means the crypto path fell back to Python or a per-packet hop crept
# back into the rx/tx wave.
_ENFORCED = ("pipe_host_us_txn_packed", "hostpath_us_txn", "pack_txn_us",
             "net_vps",
             # round 17: the fleet exactly-once invariants are recorded
             # as 0 — any nonzero is a correctness loss, not a perf
             # wobble, so they gate the build, not just advise
             "fleet_dup_verdicts", "fleet_lost_verdicts")


def lower_is_better(metric: str, unit: str) -> bool:
    hay = f"{metric} {unit}".lower()
    return any(tok in hay for tok in _LOWER_IS_BETTER)


def load_series(pattern: str, root: str) -> dict:
    """metric -> [(round_n, value, unit)] sorted by round."""
    series = {}
    for path in sorted(glob.glob(os.path.join(root, pattern))):
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError) as e:
            print(f"skipping {path}: {e}", file=sys.stderr)
            continue
        parsed = d.get("parsed")
        if not parsed or d.get("rc", 0) != 0:
            continue
        recs = parsed if isinstance(parsed, list) else [parsed]
        for p in recs:
            metric, value = p.get("metric"), p.get("value")
            if not metric or not isinstance(value, (int, float)):
                continue
            series.setdefault(metric, []).append(
                (int(d.get("n", 0)), float(value), p.get("unit", "")))
            for sub, unit in _SUB_METRICS.items():
                sv = p.get(sub)
                if isinstance(sv, (int, float)) and not isinstance(sv, bool):
                    series.setdefault(sub, []).append(
                        (int(d.get("n", 0)), float(sv), unit))
    return {m: sorted(v) for m, v in series.items()}


def diff(series: dict, threshold: float,
         enforced_threshold: float = 0.10) -> tuple[list[str], list[str]]:
    """Returns (advisory, enforced) regression verdict strings."""
    regressions, fatal = [], []
    for metric, runs in series.items():
        unit = runs[-1][2]
        lower = lower_is_better(metric, unit)
        best = (min if lower else max)(v for _, v, _ in runs)
        print(f"{metric} ({unit}, "
              f"{'lower' if lower else 'higher'} is better)")
        prev = None
        for n, v, _ in runs:
            d_prev = ""
            if prev:
                d_prev = f"  {100 * (v - prev) / prev:+6.1f}% vs prev"
            d_best = f"  {100 * (v - best) / best:+6.1f}% vs best" \
                if best else ""
            print(f"  r{n:02d}  {v:>14,.1f}{d_prev}{d_best}")
            prev = v
        if len(runs) >= 2:
            (pn, pv, _), (ln, lv, _) = runs[-2], runs[-1]
            if pv or (lower and lv > 0):
                # a 0 baseline on a lower-is-better metric (e.g. the
                # fleet dup/lost verdict gates) going nonzero is an
                # infinite-percent regression, not a skipped compare
                delta = (lv - pv) / pv if pv else float("inf")
                thr = (enforced_threshold if metric in _ENFORCED
                       else threshold)
                worse = delta > thr if lower else delta < -thr
                if worse:
                    tag = ("ENFORCED REGRESSION" if metric in _ENFORCED
                           else "REGRESSION")
                    msg = (f"{tag} {metric}: r{pn:02d} -> r{ln:02d} "
                           f"{100 * delta:+.1f}% (threshold "
                           f"{100 * thr:.0f}%)")
                    (fatal if metric in _ENFORCED
                     else regressions).append(msg)
    return regressions, fatal


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--glob", default="BENCH_r*.json",
                    help="result files to diff, relative to --root")
    ap.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="run-over-run fraction that flags a regression")
    ap.add_argument("--enforced-threshold", type=float, default=0.10,
                    help="run-over-run fraction that FAILS the enforced "
                         "host-path metrics (exit 4)")
    args = ap.parse_args(argv)

    series = load_series(args.glob, args.root)
    if not series:
        print(f"no parsable results match {args.glob} — nothing to diff")
        return 0
    regressions, fatal = diff(series, args.threshold,
                              args.enforced_threshold)
    for r in regressions + fatal:
        print(r)
    if fatal:
        return 4
    if regressions:
        return 3
    print(f"bench diff ok: no metric regressed more than "
          f"{100 * args.threshold:.0f}% run-over-run "
          f"({100 * args.enforced_threshold:.0f}% enforced)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
