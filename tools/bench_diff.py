"""Diff the repo's accumulated bench result files (BENCH_r*.json, one
per CI round: {"n": round, "parsed": {"metric", "value", "unit"}}) and
flag run-over-run regressions past a threshold.

Per metric, prints the run series with deltas vs the previous round and
vs the series best, then a verdict line.  A metric regresses when the
latest round is more than --threshold (default 5%) worse than the
previous round; direction comes from the metric itself (latency-ish
metrics are lower-is-better, everything else higher-is-better).

Non-fatal in CI: ci.sh runs this as an advisory step — exit 3 marks a
regression for a human to look at, never fails the build.

Usage:  python tools/bench_diff.py [--glob 'BENCH_r*.json'] [--threshold 0.05]
"""

import argparse
import glob
import json
import os
import sys

_LOWER_IS_BETTER = ("latency", "_ns", "_ms", "stall", "jitter", "p50",
                    "p99", "converge", "revert")

# Sub-metrics lifted out of the headline record into their own series.
# antipa_vps is a plain throughput (higher is better); antipa_vs_strict
# is the halved-chain speedup ratio whose land bar is 1.05 — a drop
# below threshold is exactly the regression worth flagging, so it rides
# the default higher-is-better direction (neither name trips the
# lower-is-better substrings above).  Rounds whose BENCH file predates a
# field simply contribute no points, so history stays green.
_SUB_METRICS = {
    "antipa_vps": "verifies/sec",
    "antipa_strict_vps": "verifies/sec",
    "antipa_vs_strict": "x_vs_strict",
    # closed-loop tuner lane: time-to-converge creeping up or reverts
    # appearing in steady state are both policy regressions (the
    # "converge"/"revert" substrings route them lower-is-better)
    "autotune_converge_s": "seconds",
    "autotune_revert_cnt": "reverts",
}


def lower_is_better(metric: str, unit: str) -> bool:
    hay = f"{metric} {unit}".lower()
    return any(tok in hay for tok in _LOWER_IS_BETTER)


def load_series(pattern: str, root: str) -> dict:
    """metric -> [(round_n, value, unit)] sorted by round."""
    series = {}
    for path in sorted(glob.glob(os.path.join(root, pattern))):
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError) as e:
            print(f"skipping {path}: {e}", file=sys.stderr)
            continue
        parsed = d.get("parsed")
        if not parsed or d.get("rc", 0) != 0:
            continue
        recs = parsed if isinstance(parsed, list) else [parsed]
        for p in recs:
            metric, value = p.get("metric"), p.get("value")
            if not metric or not isinstance(value, (int, float)):
                continue
            series.setdefault(metric, []).append(
                (int(d.get("n", 0)), float(value), p.get("unit", "")))
            for sub, unit in _SUB_METRICS.items():
                sv = p.get(sub)
                if isinstance(sv, (int, float)) and not isinstance(sv, bool):
                    series.setdefault(sub, []).append(
                        (int(d.get("n", 0)), float(sv), unit))
    return {m: sorted(v) for m, v in series.items()}


def diff(series: dict, threshold: float) -> list[str]:
    """Returns the regression verdict strings (empty = all clear)."""
    regressions = []
    for metric, runs in series.items():
        unit = runs[-1][2]
        lower = lower_is_better(metric, unit)
        best = (min if lower else max)(v for _, v, _ in runs)
        print(f"{metric} ({unit}, "
              f"{'lower' if lower else 'higher'} is better)")
        prev = None
        for n, v, _ in runs:
            d_prev = ""
            if prev:
                d_prev = f"  {100 * (v - prev) / prev:+6.1f}% vs prev"
            d_best = f"  {100 * (v - best) / best:+6.1f}% vs best" \
                if best else ""
            print(f"  r{n:02d}  {v:>14,.1f}{d_prev}{d_best}")
            prev = v
        if len(runs) >= 2:
            (pn, pv, _), (ln, lv, _) = runs[-2], runs[-1]
            if pv:
                delta = (lv - pv) / pv
                worse = delta > threshold if lower else delta < -threshold
                if worse:
                    regressions.append(
                        f"REGRESSION {metric}: r{pn:02d} -> r{ln:02d} "
                        f"{100 * delta:+.1f}% (threshold "
                        f"{100 * threshold:.0f}%)")
    return regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--glob", default="BENCH_r*.json",
                    help="result files to diff, relative to --root")
    ap.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="run-over-run fraction that flags a regression")
    args = ap.parse_args(argv)

    series = load_series(args.glob, args.root)
    if not series:
        print(f"no parsable results match {args.glob} — nothing to diff")
        return 0
    regressions = diff(series, args.threshold)
    if regressions:
        for r in regressions:
            print(r)
        return 3
    print(f"bench diff ok: no metric regressed more than "
          f"{100 * args.threshold:.0f}% run-over-run")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
