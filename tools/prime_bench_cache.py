#!/usr/bin/env python
"""Prime the persistent XLA cache with the bench configurations.

The RLC verify graph takes several minutes to compile cold on TPU; this
compiles the configs bench.py uses so later runs (the driver's) start hot.
Run detached: `nohup python tools/prime_bench_cache.py > prime.log 2>&1 &`
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from firedancer_tpu.utils import xla_cache  # noqa: E402

xla_cache.enable()

import jax  # noqa: E402
import numpy as np  # noqa: E402


def main():
    from firedancer_tpu.models.verifier import (SigVerifier, VerifierConfig,
                                                make_example_batch)

    for batch in (8192, 16384):
        for mode in ("rlc", "strict"):
            t0 = time.perf_counter()
            v = SigVerifier(VerifierConfig(batch=batch, msg_maxlen=128),
                            mode=mode, msm_m=8)
            args = make_example_batch(batch, 128, sign_pool=16)
            ok = np.asarray(v(*args))
            t1 = time.perf_counter()
            print(f"{mode} b={batch}: compile+run {t1-t0:.1f}s "
                  f"all={ok.all()}", flush=True)
            iters = 5
            t0 = time.perf_counter()
            for _ in range(iters):
                ok = v(*args)
            np.asarray(ok)
            dt = (time.perf_counter() - t0) / iters
            print(f"{mode} b={batch}: {dt*1e3:8.2f} ms -> "
                  f"{batch/dt/1e3:8.1f} K sigs/s", flush=True)


if __name__ == "__main__":
    main()
