#!/usr/bin/env python
"""Prime the persistent XLA cache with the bench configurations.

The RLC verify graph takes several minutes to compile cold on TPU; this
compiles the configs bench.py uses so later runs (the driver's) start hot.
Run detached: `nohup python tools/prime_bench_cache.py > prime.log 2>&1 &`
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from firedancer_tpu.utils import xla_cache  # noqa: E402

xla_cache.enable()

import jax  # noqa: E402
import numpy as np  # noqa: E402


def main():
    from firedancer_tpu.models.verifier import (SigVerifier, VerifierConfig,
                                                make_example_batch)

    for batch in (8192, 16384):
        for mode in ("rlc", "strict"):
            t0 = time.perf_counter()
            v = SigVerifier(VerifierConfig(batch=batch, msg_maxlen=128),
                            mode=mode, msm_m=8)
            args = make_example_batch(batch, 128, sign_pool=16)
            ok = np.asarray(v(*args))
            t1 = time.perf_counter()
            print(f"{mode} b={batch}: compile+run {t1-t0:.1f}s "
                  f"all={ok.all()}", flush=True)
            iters = 5
            t0 = time.perf_counter()
            for _ in range(iters):
                ok = v(*args)
            np.asarray(ok)
            dt = (time.perf_counter() - t0) / iters
            print(f"{mode} b={batch}: {dt*1e3:8.2f} ms -> "
                  f"{batch/dt/1e3:8.1f} K sigs/s", flush=True)

    # round 7: the multichip lane's CPU-mesh child (bench.py spawns this
    # exact subprocess when only one device is attached) — running it
    # here compiles the sharded + single-chip graphs into the shared
    # cache so the bench-time child starts hot
    import subprocess
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    env["FDTPU_BENCH_MC_ONLY"] = "1"
    env["FDTPU_BENCH_MC_FORCE_CPU"] = "1"
    bench = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    t0 = time.perf_counter()
    out = subprocess.run([sys.executable, bench], env=env,
                         capture_output=True, text=True)
    tail = (out.stdout.strip().splitlines()[-1] if out.stdout.strip()
            else out.stderr.strip()[-160:])
    print(f"mc lane (cpu mesh): {time.perf_counter() - t0:.1f}s {tail}",
          flush=True)


if __name__ == "__main__":
    main()
