#!/usr/bin/env python
"""Prime the persistent XLA cache with the CPU graphs the test suite compiles.

The slow test tier (tests/conftest.py SLOW_MODULES) is dominated by cold
compiles of the ed25519 verify graph at the shapes the pipeline/topology
tests use, plus the 8-virtual-device sharded step.  Compiling them once here
(the cache is keyed by graph + shape + backend) turns a >10-minute cold
suite into a few minutes.  Run detached on a free machine:

    nohup python tools/prime_test_cache.py > prime_tests.log 2>&1 &

Keep this list in sync with the (batch, msg_maxlen) buckets tests construct.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# identical bootstrap to tests/conftest.py: CPU backend, 8 virtual devices
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# env vars alone lose to the baked sitecustomize's plugin registration;
# the config update (pre-backend-init) is what actually selects the
# 8-virtual-device CPU platform (same as tests/conftest.py)
jax.config.update("jax_platforms", "cpu")

from firedancer_tpu.utils import xla_cache  # noqa: E402

xla_cache.enable()

import numpy as np  # noqa: E402


def _t(label, fn):
    t0 = time.perf_counter()
    fn()
    print(f"{label}: {time.perf_counter() - t0:.1f}s", flush=True)


def main(sharded_only: bool = False):
    import jax

    if sharded_only:
        _prime_sharded()
        return

    from firedancer_tpu.models.verifier import (
        SigVerifier,
        VerifierConfig,
        make_example_batch,
    )
    from firedancer_tpu.ops import ed25519 as ed

    # pipeline/topology tests: batch=16 msg=256 (leader/topo/waltz/bank)
    # plus the test_pipeline buckets and the conformance shape (128,256)
    # (64,96) is the rlc module's strict-fallback shape (binary-split
    # descent re-verifies slices at the full batch width)
    for batch, maxlen in ((16, 256), (2, 64), (8, 64), (128, 256),
                          (4, 256), (64, 96)):
        v = SigVerifier(VerifierConfig(batch=batch, msg_maxlen=maxlen))
        args = make_example_batch(batch, maxlen, valid=True, sign_pool=2)
        _t(f"verify strict ({batch},{maxlen})", lambda: np.asarray(v(*args)))

    # rlc tier (test_ed25519_rlc: batch 64, msg 96, m=4 and m=8)
    for m in (4, 8):
        v = SigVerifier(VerifierConfig(batch=64, msg_maxlen=96), mode="rlc",
                        msm_m=m)
        args = make_example_batch(64, 96, valid=True, sign_pool=4)
        _t(f"verify rlc (64,96) m={m}", lambda: np.asarray(v(*args)))

    # the (1, 1280) control-plane verifier (ops.ed25519.verify_one) —
    # gossip/repair/shred tests all hit it
    _t("verify_one (1,1280)",
       lambda: ed.verify_one(bytes(64), b"msg", bytes(32)))

    # packed single-blob dispatch (round 5): the pipeline/bench device
    # leg; (16,256) at full width + trimmed-to-64 (the parity test's
    # shapes)
    v = SigVerifier(VerifierConfig(batch=16, msg_maxlen=256))
    args = make_example_batch(16, 256, valid=True, sign_pool=2)
    _t("packed (16,256) ml=256",
       lambda: np.asarray(v.packed_dispatch(*args)))
    _t("packed (16,256) ml=64",
       lambda: np.asarray(v.packed_dispatch(
           *args, ml=int(np.asarray(args[1]).max()))))

    # round-4 shapes: the real-corpora conformance batch (1536,128)
    v = SigVerifier(VerifierConfig(batch=1536, msg_maxlen=128))
    args = make_example_batch(1536, 128, valid=True, sign_pool=2)
    _t("verify strict (1536,128)", lambda: np.asarray(v(*args)))

    # collective RLC over the 8-device mesh + its single-device twin
    # (dryrun_multichip exercises both every round)
    try:
        import jax.numpy as jnp

        from firedancer_tpu.parallel import collectives as pc
        from firedancer_tpu.parallel import mesh as pm

        mesh = pm.make_mesh(8)
        rng = np.random.default_rng(5)
        args = make_example_batch(64, 64, valid=True, sign_pool=8)
        z = jnp.asarray(rng.integers(0, 256, size=(64, 16), dtype=np.uint8))
        rlc = pc.shard_rlc_verify(mesh, m=2)
        _t("sharded rlc 8dev (64,64)",
           lambda: np.asarray(rlc(*pm.shard_batch(mesh, *args), z)[0]))
        _t("rlc single (64,64) m=2",
           lambda: np.asarray(ed.verify_batch_rlc(*args, z, m=2)[0]))

        # round-7 dp-mesh serving path (test_sharded_verify + bench mc
        # lane): sharded rlc at the test shape, its single-chip twin, and
        # the strict (36,96) slice the uneven-batch test references
        args96 = make_example_batch(64, 96, valid=True, sign_pool=8)
        z96 = jnp.asarray(
            rng.integers(0, 256, size=(64, 16), dtype=np.uint8))
        rlc96 = pc.shard_rlc_verify(mesh, m=2)
        _t("sharded rlc 8dev (64,96)",
           lambda: np.asarray(rlc96(*pm.shard_batch(mesh, *args96),
                                    z96)[0]))
        _t("rlc single (64,96) m=2",
           lambda: np.asarray(ed.verify_batch_rlc(*args96, z96, m=2)[0]))
        v36 = SigVerifier(VerifierConfig(batch=36, msg_maxlen=96))
        a36 = tuple(np.asarray(a)[:36] for a in args96)
        _t("verify strict (36,96)", lambda: np.asarray(v36(*a36)))
    except ValueError as e:
        print(f"sharded rlc skipped: {e}", flush=True)

    # the 8-virtual-device sharded step compiles LAST and in a FRESH
    # subprocess: after the big crypto graphs above, this process's
    # accumulated RSS reproducibly drives LLVM into "Cannot allocate
    # memory" on the sharded compile (observed twice, round 5); a clean
    # address space compiles it fine (the driver's dryrun_multichip does
    # exactly that every round)
    import subprocess
    import sys as _sys
    rc = subprocess.run(
        [_sys.executable, os.path.abspath(__file__), "--sharded-only"],
        env=dict(os.environ)).returncode
    if rc:
        print(f"sharded-step subprocess rc={rc}", flush=True)

    # sentinel: tests/conftest.py's prime-or-skip policy reads this to
    # decide whether graph-compiling fast-tier modules run warm or defer
    # to the slow tier (VERDICT r4 weak #4: the fast tier must be fast
    # COLD too).  Keyed by the crypto-op source hash so an edited graph
    # invalidates it.
    from firedancer_tpu.utils.aot import _src_hash
    from firedancer_tpu.utils.xla_cache import cache_dir
    cdir = cache_dir()  # the SAME resolution enable() used above
    os.makedirs(cdir, exist_ok=True)
    for old in os.listdir(cdir):
        if old.startswith("PRIMED-"):
            os.remove(os.path.join(cdir, old))
    open(os.path.join(cdir, f"PRIMED-{_src_hash()}"), "w").close()
    print("done; cache at", os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                           ".xla_cache"), flush=True)


def _prime_sharded():
    from firedancer_tpu.models.verifier import (
        SigVerifier,
        VerifierConfig,
        make_example_batch,
    )
    from firedancer_tpu.parallel import mesh as pm

    try:
        mesh = pm.make_mesh(8)
        step = pm.shard_verify_step(mesh)
        args = make_example_batch(64, 64, valid=True, sign_pool=8)
        sharded = pm.shard_batch(mesh, *args)
        _t("sharded verify 8dev (64,64)",
           lambda: np.asarray(step(*sharded)[0]))

        # round-7 serving path at the test shape (64,96): the donated
        # sharded packed step (even + masked-padding variants), its
        # 4-array twin, and the single-chip graphs the bit-identity
        # tests compare against
        sv = SigVerifier(VerifierConfig(batch=64, msg_maxlen=96),
                         mesh=mesh)
        ref = SigVerifier(VerifierConfig(batch=64, msg_maxlen=96))
        a96 = make_example_batch(64, 96, valid=True, sign_pool=8)
        _t("sharded packed 8dev (64,96)",
           lambda: np.asarray(sv.packed_dispatch(*a96)))
        _t("sharded packed 8dev (36->40,96) masked",
           lambda: np.asarray(sv.packed_dispatch(
               *(np.asarray(a)[:36] for a in a96))))
        _t("sharded 4-array 8dev (64,96)", lambda: np.asarray(sv(*a96)))
        _t("packed single (64,96)",
           lambda: np.asarray(ref.packed_dispatch(*a96)))
    except ValueError as e:
        print(f"sharded step skipped: {e}", flush=True)


if __name__ == "__main__":
    import sys as _sys
    main(sharded_only="--sharded-only" in _sys.argv)
