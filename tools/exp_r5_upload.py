"""Upload-path A/B at the bench shapes: serial device_put vs chunked
multi-stream, then the full fresh-ingest loop both ways."""
import os, sys, time
import numpy as np
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from firedancer_tpu.utils import xla_cache
xla_cache.enable()
import jax
from firedancer_tpu.models.verifier import SigVerifier, VerifierConfig, \
    make_example_batch
from _upload_lib import device_put_chunked

B = int(os.environ.get("B", 32768))
args = make_example_batch(B, 128, valid=True, sign_pool=64)
host = [np.asarray(a) for a in args]
nbytes = sum(a.nbytes for a in host)
print(f"batch bytes: {nbytes/1e6:.1f} MB", flush=True)

def put_serial():
    return [jax.device_put(a) for a in host]

def bw(name, fn, reps=6):
    outs = fn()
    for o in outs:
        o.block_until_ready()
    np.asarray(outs[0])  # true sync
    runs = []
    for _ in range(reps):
        t0 = time.perf_counter()
        outs = fn()
        np.asarray(outs[-1]); np.asarray(outs[0])
        runs.append(time.perf_counter() - t0)
    runs.sort()
    med = runs[len(runs)//2]
    print(f"{name:24s} {med*1e3:7.1f} ms  {nbytes/med/1e6:6.1f} MB/s", flush=True)

bw("serial device_put x4", put_serial)
for s in (2, 4, 8):
    bw(f"chunked streams={s}", lambda s=s: device_put_chunked(host, s))

# fresh-ingest loop both ways
v = SigVerifier(VerifierConfig(batch=B, msg_maxlen=128))
ok = v(*args); assert bool(np.asarray(ok).all())

def fresh(up, iters=8, reps=3):
    runs = []
    for _ in range(reps):
        t0 = time.perf_counter()
        ok = None
        for _ in range(iters):
            dev = up()
            ok = v(*dev)
        np.asarray(ok)
        runs.append(B * iters / (time.perf_counter() - t0))
    runs.sort()
    return runs[len(runs)//2]

print(f"fresh serial: {fresh(put_serial):,.0f} v/s", flush=True)
for s in (4, 8):
    print(f"fresh chunked s={s}: "
          f"{fresh(lambda s=s: device_put_chunked(host, s)):,.0f} v/s",
          flush=True)
