"""Same-process A/B: schoolbook vs Karatsuba field mul/sqr + full verify
throughput at production batches (slope/multi-dispatch rules)."""

import time

import jax
import jax.numpy as jnp
import numpy as np

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
from _bench import DISPATCH, slope, timed  # noqa: E402,F401

from firedancer_tpu.ops import f25519 as fe
from firedancer_tpu.utils import xla_cache

xla_cache.enable()

BATCH = 4096




def _school_conv(a, b):
    ar = [a[i] for i in range(fe.NLIMB)]
    br = [b[i] for i in range(fe.NLIMB)]
    cols = fe._conv_rows(ar, br)
    cols.append(jnp.zeros_like(cols[0]))
    return jnp.stack(cols, axis=0)


def mul_school(a, b):
    return fe._reduce_wide(_school_conv(a, b))


def main():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.integers(0, 4096, size=(22, BATCH), dtype=np.uint32))
    b = jnp.asarray(rng.integers(0, 4096, size=(22, BATCH), dtype=np.uint32))

    def mk(mulfn):
        def inner(steps):
            @jax.jit
            def f(x, y):
                def body(i, x):
                    return mulfn(x, y)
                return jax.lax.fori_loop(0, steps, body, x)
            return f, (a, b)
        return inner

    # correctness cross-check first
    ka = np.asarray(fe.mul(a, b))
    sc = np.asarray(mul_school(a, b))
    assert (ka == sc).all(), "karatsuba != schoolbook"
    print("conv cross-check ok", flush=True)

    slope("field mul SCHOOLBOOK", mk(mul_school), 2048, 6144, BATCH,
          "mul/lane")
    slope("field mul KARATSUBA", mk(fe.mul), 2048, 6144, BATCH, "mul/lane")

    def mk_sqr(steps):
        @jax.jit
        def f(x):
            def body(i, x):
                return fe.sqr(x)
            return jax.lax.fori_loop(0, steps, body, x)
        return f, (a,)

    slope("field sqr KARATSUBA", mk_sqr, 2048, 6144, BATCH, "sqr/lane")

    # full verify throughput
    from firedancer_tpu.models.verifier import SigVerifier, VerifierConfig, \
        make_example_batch

    for batch in (8192, 16384):
        v = SigVerifier(VerifierConfig(batch=batch, msg_maxlen=128))
        args = make_example_batch(batch, 128, valid=True, sign_pool=32)
        ok = v(*args)
        assert bool(np.asarray(ok).all())
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(4):
                ok = v(*args)
            np.asarray(ok)
            best = min(best, (time.perf_counter() - t0) / 4)
        print(f"verify strict batch={batch}: {best*1e3:8.1f} ms "
              f"-> {batch/best:10.0f} v/s", flush=True)


if __name__ == "__main__":
    main()
