"""Second-round upload experiments: packed single-blob-per-stream chunks
(4 RPCs/iter instead of 16), true-bytes msgs (64 of 128 cols), deeper
unsynced pipelining."""
import os, sys, time
import numpy as np
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from firedancer_tpu.utils import xla_cache
xla_cache.enable()
import jax
import jax.numpy as jnp
from firedancer_tpu.models.verifier import SigVerifier, VerifierConfig, \
    make_example_batch
from _upload_lib import device_put_chunked, _pool

B = int(os.environ.get("B", 32768))
args = make_example_batch(B, 128, valid=True, sign_pool=64)
host = [np.asarray(a) for a in args]
v = SigVerifier(VerifierConfig(batch=B, msg_maxlen=128))
ok = v(*args); assert bool(np.asarray(ok).all())

msgs, lens, sigs, pubs = host
ml = 64  # true msg bytes in this batch (lens.max())
assert int(lens.max()) == ml

# packed layout per row: msgs[:ml] | sigs(64) | pubs(32) | lens(4)
packed = np.concatenate([
    msgs[:, :ml],
    sigs, pubs, lens.astype(np.int32).view(np.uint8).reshape(B, 4)],
    axis=1)  # (B, ml+100)
print(f"packed bytes: {packed.nbytes/1e6:.1f} MB (was 7.5)", flush=True)

W = packed.shape[1]

@jax.jit
def unpack_verify(blob):
    m = jnp.zeros((B, 128), jnp.uint8).at[:, :ml].set(blob[:, :ml])
    s = blob[:, ml:ml + 64]
    p = blob[:, ml + 64:ml + 96]
    ln = jax.lax.bitcast_convert_type(
        blob[:, ml + 96:ml + 100], jnp.int32).reshape(B)
    from firedancer_tpu.ops import ed25519 as ed
    return ed.verify_batch(m, ln, s, p)

np.asarray(unpack_verify(jnp.asarray(packed)))

def fresh_packed(streams, iters=8, reps=3):
    pool = _pool(streams)
    step = -(-B // streams)
    bounds = [(i, min(i + step, B)) for i in range(0, B, step)]
    runs = []
    for _ in range(reps):
        t0 = time.perf_counter()
        ok = None
        for _ in range(iters):
            futs = [pool.submit(jax.device_put, packed[lo:hi])
                    for lo, hi in bounds]
            blob = jnp.concatenate([f.result() for f in futs], axis=0)
            ok = unpack_verify(blob)
        np.asarray(ok)
        runs.append(B * iters / (time.perf_counter() - t0))
    runs.sort()
    return runs[len(runs)//2]

for s in (1, 2, 4, 6, 8):
    print(f"fresh packed s={s}: {fresh_packed(s):,.0f} v/s", flush=True)
