"""Multi-stream host->device upload (EXPERIMENT SUPPORT, not wired into
the production path: the packed single-blob dispatch in
models/verifier.py measured better — one RPC beats four chunked streams
through this tunnel; see tools/exp_r5_upload2.py and docs/perf_ceiling).

Role: the ingest DMA path (wiredancer pushes txns into the card over
async DMA, src/wiredancer/c/wd_f1.h:85-113).  On real PCIe a single
device_put moves GB/s and this module is a pass-through; through this
container's tunneled TPU a single transfer stream tops out ~10-33 MB/s
while several CONCURRENT streams multiplex ~2-4x better (measured round
4/5).  So: split each array into row chunks, issue every chunk's
device_put from a thread pool, reassemble on device with one concat
(device-side copy, negligible next to the link).

The thread pool is per-process and lazy; chunked uploads of the verify
batch shapes are the intended use (bench fresh-ingest tier and the
VerifyPipeline's dispatch path).
"""

import os
from concurrent.futures import ThreadPoolExecutor

_POOL = None
_POOL_STREAMS = 0


def _pool(streams: int) -> ThreadPoolExecutor:
    global _POOL, _POOL_STREAMS
    if _POOL is None or _POOL_STREAMS < streams:
        if _POOL is not None:
            _POOL.shutdown(wait=False)
        _POOL = ThreadPoolExecutor(max_workers=streams,
                                   thread_name_prefix="fdtpu-upload")
        _POOL_STREAMS = streams
    return _POOL


def default_streams() -> int:
    return int(os.environ.get("FDTPU_UPLOAD_STREAMS", 4))


def device_put_chunked(arrays, streams: int | None = None):
    """Upload each array in `arrays` split into `streams` row-chunks
    issued concurrently; returns device arrays (reassembled by an
    on-device concatenate when chunked).

    Arrays too small to benefit (< 256 KB) upload whole.  Order of
    returned arrays matches the input."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if streams is None:
        streams = default_streams()
    if streams <= 1:
        return [jax.device_put(a) for a in arrays]

    pool = _pool(streams)
    plans = []  # (array, [chunk bounds] or None)
    for a in arrays:
        a = np.asarray(a)
        n = a.shape[0] if a.ndim else 0
        if a.nbytes < (256 << 10) or n < streams:
            plans.append((a, None))
        else:
            step = -(-n // streams)
            plans.append((a, [(i, min(i + step, n))
                              for i in range(0, n, step)]))

    futs = []
    for a, bounds in plans:
        if bounds is None:
            futs.append([pool.submit(jax.device_put, a)])
        else:
            futs.append([pool.submit(jax.device_put, a[lo:hi])
                         for lo, hi in bounds])

    out = []
    for (a, bounds), fs in zip(plans, futs):
        chunks = [f.result() for f in fs]
        out.append(chunks[0] if bounds is None
                   else jnp.concatenate(chunks, axis=0))
    return out
