"""CI host-path smoke: the round-8 zero-repack wire->device path plus
the round-11 one-pass native fast lane.

Four gates:
  1. verdict parity — `submit_rows` over device-blob-layout rows must be
     BIT-IDENTICAL to the legacy `_pack_into` host repack on a fixed
     seed with mixed valid/tampered lanes (the knob `FDTPU_INGEST_
     LEGACY_PACK=1` keeps the old path alive; both must agree).
  2. native/fallback parity — the round-11 one-pass C submit/harvest
     kernel (FDTPU_INGEST_NATIVE_HOSTPATH) must produce the SAME wires,
     survivor order, and metric counters as the NumPy fallback on fixed
     mixed-verdict, mixed-length, dup-bearing frags.
  3. packed egress identity — egress_packed=True (one arena frag per
     harvest) must carry exactly the bytes the legacy per-txn egress
     emits (bench._egress_packed_identical, the same gate the BENCH
     record ships as egress_packed_identical).
  4. 2-tile packed mp smoke — the packed-wire verify-bench topology
     (dcache frags ARE device-blob rows) boots with two verify tiles,
     the source's round-robin burst splitter deals work to BOTH, every
     txn arrives, and zero frags are torn-dropped by the post-dispatch
     seq re-check.

A real file (not a ci.sh heredoc) because tile processes use the
multiprocessing 'spawn' start method, which re-imports __main__ from
its path — stdin scripts have none.

Usage:  JAX_PLATFORMS=cpu python tools/hostpath_smoke.py
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def verdict_parity() -> None:
    from firedancer_tpu.models.verifier import (
        SigVerifier,
        VerifierConfig,
        make_example_batch,
    )
    from firedancer_tpu.tango.ring import PACKED_ROW_EXTRA

    B, ml = 64, 96
    sv = SigVerifier(VerifierConfig(batch=B, msg_maxlen=ml))
    msgs, lens, sigs, pubs = (np.asarray(a) for a in make_example_batch(
        B, ml, valid=True, sign_pool=8, seed=11))
    sigs = sigs.copy()
    sigs[5, 0] ^= 0xFF            # tampered lanes: verdict must be mixed
    sigs[23, 63] ^= 0x01

    os.environ["FDTPU_INGEST_LEGACY_PACK"] = "1"
    try:
        eng = sv.make_ingest(ml=ml, nbuf=2, depth=1)
        eng.submit(msgs, lens, sigs, pubs)
        (ref,) = eng.drain()
    finally:
        os.environ.pop("FDTPU_INGEST_LEGACY_PACK", None)
    assert ref.any() and not ref.all(), "need a mixed verdict"

    rows = np.zeros((B, ml + PACKED_ROW_EXTRA), np.uint8)
    rows[:, :ml] = msgs
    rows[:, ml:ml + 64] = sigs
    rows[:, ml + 64:ml + 96] = pubs
    rows[:, ml + 96:ml + 100] = (
        lens.astype(np.int32).view(np.uint8).reshape(B, 4))
    eng2 = sv.make_ingest(ml=ml, nbuf=2, depth=1)
    eng2.submit_rows(rows)
    (got,) = eng2.drain()
    assert np.array_equal(got, ref), "zero-repack verdicts diverged"
    print("hostpath parity ok: submit_rows bit-identical to legacy "
          f"_pack_into ({int(ref.sum())}/{B} pass)")


def native_fallback_parity() -> None:
    """Round-11 gate: the one-pass C kernel vs the NumPy fallback, wire
    for wire and counter for counter, on mixed-length frags with mixed
    verdicts and cross-frag dups (no device; verdicts injected)."""
    from firedancer_tpu.disco.pipeline import VerifyPipeline
    from firedancer_tpu.tango.ring import PACKED_ROW_EXTRA, packed_row_ml

    ml = packed_row_ml(256)
    stride = ml + PACKED_ROW_EXTRA
    rng = np.random.default_rng(29)
    n = 48
    frags = []
    for _ in range(3):
        rows = np.zeros((n, stride), np.uint8)
        lens = rng.integers(0, ml + 1, n)
        for i in range(n):
            li = int(lens[i])
            rows[i, :li] = rng.integers(0, 256, li, dtype=np.uint8)
            rows[i, ml:ml + 64] = rng.integers(0, 256, 64, dtype=np.uint8)
            rows[i, ml] = 1 + (i % 251)
            rows[i, ml + 96:ml + 100] = np.frombuffer(
                li.to_bytes(4, "little"), np.uint8)
        frags.append(rows)
    frags.append(frags[1])               # cross-frag dups

    class _Mixed:
        def __call__(self, m, l, s, p):
            return np.ones((np.asarray(m).shape[0],), bool)

        def dispatch_blob(self, blob, maxlen=None):
            return (blob[:, blob.shape[1] - 100 + 1] & 3) != 0

    def run(native: bool):
        pipe = VerifyPipeline(_Mixed(), buckets=[(n, ml)],
                              tcache_depth=1 << 12, max_inflight=0,
                              native_hostpath=native)
        wires = []
        for rows in frags:
            wires += [w for w, _ in pipe.submit_packed_rows(rows)]
        s = dict(pipe.metrics.snapshot())
        return wires, {k: s[k] for k in ("txns_in", "dedup_drop",
                                         "verify_fail", "verify_pass")}

    nat_w, nat_m = run(True)
    fb_w, fb_m = run(False)
    assert nat_w == fb_w, "native kernel wires diverged from fallback"
    assert nat_m == fb_m, f"metric divergence: {nat_m} vs {fb_m}"
    assert nat_m["verify_fail"] and nat_m["dedup_drop"], \
        "gate needs mixed verdicts and dups to mean anything"
    print(f"hostpath native parity ok: {len(nat_w)} wires bit-identical "
          f"to the NumPy fallback ({nat_m})")


def egress_packed_identity() -> None:
    """Round-11 gate: the packed verdict egress (one arena frag per
    harvest) ships the exact bytes of the legacy per-txn list — reuses
    bench._egress_packed_identical, the BENCH-record gate."""
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(root, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert bench._egress_packed_identical(), \
        "packed egress diverged from the legacy per-txn wires"
    print("hostpath egress identity ok: packed arena wires == legacy "
          "per-txn egress")


def packed_mp_smoke() -> None:
    from firedancer_tpu.app import config as config_mod
    from firedancer_tpu.disco.run import TopoRun
    from firedancer_tpu.tango.ring import packed_row_ml
    from firedancer_tpu.utils import aot

    ml = packed_row_ml(256)
    # AOT-first boot: spawn-context children must never cold-compile
    aot_dir = os.environ.get("FDTPU_CI_AOT_DIR", "/tmp/fdtpu_aot_ci")
    if aot.ensure_verify_packed(aot_dir, 64, ml) is None:
        print("hostpath mp smoke SKIPPED: AOT unusable on this backend")
        return

    n_txn = 2048
    cfg = config_mod.load(None)
    cfg["name"] = "fdtpu_ci_hostpath"
    cfg["topology"] = "verify-bench"
    cfg["layout"]["verify_tile_count"] = 2
    cfg["development"]["packed_wire"] = 1
    cfg["development"]["source_count"] = n_txn
    cfg["tiles"]["verify"]["batch"] = 64
    cfg["tiles"]["verify"]["aot_dir"] = aot_dir
    cfg["tiles"]["verify"]["aot_require"] = 1
    spec = config_mod.build_topology(cfg)
    with TopoRun(spec) as run:
        run.wait_ready(timeout=300)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sum(run.metrics(f"verify:{v}")["txn_in_cnt"]
                   for v in range(2)) >= n_txn:
                break
            time.sleep(0.2)
        m0 = run.metrics("verify:0")
        m1 = run.metrics("verify:1")
        assert m0["txn_in_cnt"] + m1["txn_in_cnt"] >= n_txn, (m0, m1)
        assert m0["txn_in_cnt"] > 0 and m1["txn_in_cnt"] > 0, \
            "burst splitter starved a tile"
        assert m0["torn_drop_cnt"] == 0 and m1["torn_drop_cnt"] == 0, \
            "unexpected torn-frag drops"
    print(f"hostpath mp smoke ok: 2 packed tiles split {n_txn} txns "
          f"({m0['txn_in_cnt']}/{m1['txn_in_cnt']}), 0 torn drops")


def main() -> int:
    verdict_parity()
    native_fallback_parity()
    egress_packed_identity()
    packed_mp_smoke()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
