"""CI host-path smoke: the round-8 zero-repack wire->device path.

Two gates:
  1. verdict parity — `submit_rows` over device-blob-layout rows must be
     BIT-IDENTICAL to the legacy `_pack_into` host repack on a fixed
     seed with mixed valid/tampered lanes (the knob `FDTPU_INGEST_
     LEGACY_PACK=1` keeps the old path alive; both must agree).
  2. 2-tile packed mp smoke — the packed-wire verify-bench topology
     (dcache frags ARE device-blob rows) boots with two verify tiles,
     the source's round-robin burst splitter deals work to BOTH, every
     txn arrives, and zero frags are torn-dropped by the post-dispatch
     seq re-check.

A real file (not a ci.sh heredoc) because tile processes use the
multiprocessing 'spawn' start method, which re-imports __main__ from
its path — stdin scripts have none.

Usage:  JAX_PLATFORMS=cpu python tools/hostpath_smoke.py
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def verdict_parity() -> None:
    from firedancer_tpu.models.verifier import (
        SigVerifier,
        VerifierConfig,
        make_example_batch,
    )
    from firedancer_tpu.tango.ring import PACKED_ROW_EXTRA

    B, ml = 64, 96
    sv = SigVerifier(VerifierConfig(batch=B, msg_maxlen=ml))
    msgs, lens, sigs, pubs = (np.asarray(a) for a in make_example_batch(
        B, ml, valid=True, sign_pool=8, seed=11))
    sigs = sigs.copy()
    sigs[5, 0] ^= 0xFF            # tampered lanes: verdict must be mixed
    sigs[23, 63] ^= 0x01

    os.environ["FDTPU_INGEST_LEGACY_PACK"] = "1"
    try:
        eng = sv.make_ingest(ml=ml, nbuf=2, depth=1)
        eng.submit(msgs, lens, sigs, pubs)
        (ref,) = eng.drain()
    finally:
        os.environ.pop("FDTPU_INGEST_LEGACY_PACK", None)
    assert ref.any() and not ref.all(), "need a mixed verdict"

    rows = np.zeros((B, ml + PACKED_ROW_EXTRA), np.uint8)
    rows[:, :ml] = msgs
    rows[:, ml:ml + 64] = sigs
    rows[:, ml + 64:ml + 96] = pubs
    rows[:, ml + 96:ml + 100] = (
        lens.astype(np.int32).view(np.uint8).reshape(B, 4))
    eng2 = sv.make_ingest(ml=ml, nbuf=2, depth=1)
    eng2.submit_rows(rows)
    (got,) = eng2.drain()
    assert np.array_equal(got, ref), "zero-repack verdicts diverged"
    print("hostpath parity ok: submit_rows bit-identical to legacy "
          f"_pack_into ({int(ref.sum())}/{B} pass)")


def packed_mp_smoke() -> None:
    from firedancer_tpu.app import config as config_mod
    from firedancer_tpu.disco.run import TopoRun
    from firedancer_tpu.tango.ring import packed_row_ml
    from firedancer_tpu.utils import aot

    ml = packed_row_ml(256)
    # AOT-first boot: spawn-context children must never cold-compile
    aot_dir = os.environ.get("FDTPU_CI_AOT_DIR", "/tmp/fdtpu_aot_ci")
    if aot.ensure_verify_packed(aot_dir, 64, ml) is None:
        print("hostpath mp smoke SKIPPED: AOT unusable on this backend")
        return

    n_txn = 2048
    cfg = config_mod.load(None)
    cfg["name"] = "fdtpu_ci_hostpath"
    cfg["topology"] = "verify-bench"
    cfg["layout"]["verify_tile_count"] = 2
    cfg["development"]["packed_wire"] = 1
    cfg["development"]["source_count"] = n_txn
    cfg["tiles"]["verify"]["batch"] = 64
    cfg["tiles"]["verify"]["aot_dir"] = aot_dir
    cfg["tiles"]["verify"]["aot_require"] = 1
    spec = config_mod.build_topology(cfg)
    with TopoRun(spec) as run:
        run.wait_ready(timeout=300)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sum(run.metrics(f"verify:{v}")["txn_in_cnt"]
                   for v in range(2)) >= n_txn:
                break
            time.sleep(0.2)
        m0 = run.metrics("verify:0")
        m1 = run.metrics("verify:1")
        assert m0["txn_in_cnt"] + m1["txn_in_cnt"] >= n_txn, (m0, m1)
        assert m0["txn_in_cnt"] > 0 and m1["txn_in_cnt"] > 0, \
            "burst splitter starved a tile"
        assert m0["torn_drop_cnt"] == 0 and m1["torn_drop_cnt"] == 0, \
            "unexpected torn-frag drops"
    print(f"hostpath mp smoke ok: 2 packed tiles split {n_txn} txns "
          f"({m0['txn_in_cnt']}/{m1['txn_in_cnt']}), 0 torn drops")


def main() -> int:
    verdict_parity()
    packed_mp_smoke()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
