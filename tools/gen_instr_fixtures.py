#!/usr/bin/env python
"""Generate the instruction-fixture corpus (round 4, VERDICT missing #2).

Each fixture encodes ONE top-level instruction's pre-state and expected
effects, with the expectation stated from the REFERENCE's rules (per-case
ref citations below point at the C that defines the behavior:
src/flamenco/runtime/program/fd_system_program.c, fd_vote_program.c,
fd_stake_program.c).  The replayer (flamenco/fixtures.py) runs them
through the native-program registry — the `run-test-vectors` altitude
(contrib/test/run_test_vectors.sh) without protobuf plumbing.

Output: tests/fixtures/instr_fixtures.json (list of fixture objects).
"""

import json
import os
import struct
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from firedancer_tpu.flamenco import stake_program as sp
from firedancer_tpu.flamenco import system_program as sysp
from firedancer_tpu.flamenco import vote_program as vp
from firedancer_tpu.flamenco.types import (
    STAKE_PROGRAM_ID, SYSTEM_PROGRAM_ID, VOTE_PROGRAM_ID)

FIX = []


def pk(i: int) -> bytes:
    return bytes([0xA0 + (i >> 8), i & 0xFF]) + bytes(30)


def acct(i, lamports=0, data=b"", owner=SYSTEM_PROGRAM_ID, signer=False,
         writable=True, missing=False, executable=False):
    return {"pubkey": pk(i).hex(), "lamports": lamports, "data": data.hex(),
            "owner": owner.hex(), "signer": signer, "writable": writable,
            "missing": missing, "executable": executable}


def fix(name, program_id, data, accounts, instr_accounts, expect, **extra):
    FIX.append({"name": name, "program_id": program_id.hex(),
                "data": data.hex(), "accounts": accounts,
                "instr_accounts": instr_accounts, "expect": expect, **extra})


# ===================================================================== system
# ref: src/flamenco/runtime/program/fd_system_program.c

for amt in (0, 1, 999, 5_000_000, 2**53):
    # transfer moves exactly `amt` (fd_system_program.c transfer path)
    fix(f"system_transfer_ok_{amt}", SYSTEM_PROGRAM_ID, sysp.ix_transfer(amt),
        [acct(0, lamports=2**54, signer=True), acct(1, lamports=7)],
        [0, 1],
        {"ok": True, "post": [{"index": 0, "lamports": 2**54 - amt},
                              {"index": 1, "lamports": 7 + amt}]})

fix("system_transfer_insufficient", SYSTEM_PROGRAM_ID, sysp.ix_transfer(100),
    [acct(0, lamports=99, signer=True), acct(1)], [0, 1],
    {"ok": False, "err_contains": "insufficient"})

fix("system_transfer_unsigned", SYSTEM_PROGRAM_ID, sysp.ix_transfer(10),
    [acct(0, lamports=100, signer=False), acct(1)], [0, 1],
    {"ok": False, "err_contains": "signature"})

fix("system_transfer_from_owned_account", SYSTEM_PROGRAM_ID,
    sysp.ix_transfer(10),
    [acct(0, lamports=100, signer=True, owner=VOTE_PROGRAM_ID), acct(1)],
    [0, 1], {"ok": False, "err_contains": "source"})

fix("system_transfer_missing_dest_creates_balance", SYSTEM_PROGRAM_ID,
    sysp.ix_transfer(55),
    [acct(0, lamports=100, signer=True), acct(1, missing=True)], [0, 1],
    {"ok": True, "post": [{"index": 0, "lamports": 45},
                          {"index": 1, "lamports": 55}]})

fix("system_transfer_short_data", SYSTEM_PROGRAM_ID, struct.pack("<I", 2),
    [acct(0, lamports=100, signer=True), acct(1)], [0, 1],
    {"ok": False})

for space in (0, 1, 64, 10 * 1024 * 1024):
    fix(f"system_create_ok_space_{space}", SYSTEM_PROGRAM_ID,
        sysp.ix_create_account(1000, space, VOTE_PROGRAM_ID),
        [acct(0, lamports=5000, signer=True),
         acct(1, missing=True, signer=True)], [0, 1],
        {"ok": True, "post": [{"index": 0, "lamports": 4000},
                              {"index": 1, "lamports": 1000,
                               "owner": VOTE_PROGRAM_ID.hex(),
                               "data_len": space}]})

fix("system_create_space_too_large", SYSTEM_PROGRAM_ID,
    sysp.ix_create_account(1000, 10 * 1024 * 1024 + 1, VOTE_PROGRAM_ID),
    [acct(0, lamports=5000, signer=True),
     acct(1, missing=True, signer=True)], [0, 1],
    {"ok": False, "err_contains": "length"})

fix("system_create_account_in_use", SYSTEM_PROGRAM_ID,
    sysp.ix_create_account(1000, 0, VOTE_PROGRAM_ID),
    [acct(0, lamports=5000, signer=True),
     acct(1, lamports=1, signer=True)], [0, 1],
    {"ok": False, "err_contains": "in use"})

fix("system_create_unsigned_to", SYSTEM_PROGRAM_ID,
    sysp.ix_create_account(1000, 0, VOTE_PROGRAM_ID),
    [acct(0, lamports=5000, signer=True),
     acct(1, missing=True, signer=False)], [0, 1],
    {"ok": False, "err_contains": "signature"})

fix("system_assign_ok", SYSTEM_PROGRAM_ID, sysp.ix_assign(VOTE_PROGRAM_ID),
    [acct(0, lamports=10, signer=True)], [0],
    {"ok": True, "post": [{"index": 0, "owner": VOTE_PROGRAM_ID.hex()}]})

fix("system_assign_unsigned", SYSTEM_PROGRAM_ID,
    sysp.ix_assign(VOTE_PROGRAM_ID),
    [acct(0, lamports=10, signer=False)], [0],
    {"ok": False, "err_contains": "signature"})

fix("system_assign_not_system_owned", SYSTEM_PROGRAM_ID,
    sysp.ix_assign(STAKE_PROGRAM_ID),
    [acct(0, lamports=10, signer=True, owner=VOTE_PROGRAM_ID)], [0],
    {"ok": False, "err_contains": "owned"})

for space in (1, 100, 1024):
    fix(f"system_allocate_ok_{space}", SYSTEM_PROGRAM_ID,
        sysp.ix_allocate(space),
        [acct(0, lamports=10, signer=True)], [0],
        {"ok": True, "post": [{"index": 0, "data_len": space}]})

fix("system_allocate_nonempty", SYSTEM_PROGRAM_ID, sysp.ix_allocate(10),
    [acct(0, lamports=10, data=b"\x01", signer=True)], [0],
    {"ok": False})

fix("system_unknown_instruction", SYSTEM_PROGRAM_ID, struct.pack("<I", 99),
    [acct(0, lamports=10, signer=True)], [0],
    {"ok": False, "err_contains": "unsupported"})

fix("system_empty_data", SYSTEM_PROGRAM_ID, b"",
    [acct(0, lamports=10, signer=True)], [0],
    {"ok": False, "err_contains": "short"})

fix("system_transfer_missing_account", SYSTEM_PROGRAM_ID,
    sysp.ix_transfer(10), [acct(0, lamports=100, signer=True)], [0],
    {"ok": False, "err_contains": "account"})

# ======================================================================= vote
# ref: src/flamenco/runtime/program/fd_vote_program.c

NODE, VOTER = pk(100), pk(101)


def vote_acct(i, vs: vp.VoteState | None, lamports=10_000, **kw):
    data = vs.serialize() if vs is not None else bytes(200)
    return acct(i, lamports=lamports, data=data, owner=VOTE_PROGRAM_ID, **kw)


fix("vote_initialize_ok", VOTE_PROGRAM_ID,
    vp.ix_initialize(NODE, VOTER, commission=5),
    [acct(0, lamports=10_000, data=bytes(200), owner=VOTE_PROGRAM_ID),
     acct(100, signer=True)], [0, 1],
    {"ok": True})

fix("vote_initialize_node_must_sign", VOTE_PROGRAM_ID,
    vp.ix_initialize(NODE, VOTER),
    [acct(0, lamports=10_000, data=bytes(200), owner=VOTE_PROGRAM_ID),
     acct(100, signer=False)], [0, 1],
    {"ok": False, "err_contains": "sign"})

fix("vote_initialize_twice", VOTE_PROGRAM_ID, vp.ix_initialize(NODE, VOTER),
    [vote_acct(0, vp.VoteState(NODE, VOTER)), acct(100, signer=True)],
    [0, 1], {"ok": False, "err_contains": "initialized"})

fix("vote_initialize_wrong_owner", VOTE_PROGRAM_ID,
    vp.ix_initialize(NODE, VOTER),
    [acct(0, lamports=10_000, data=bytes(200)), acct(100, signer=True)],
    [0, 1], {"ok": False, "err_contains": "owned"})

for slots in ([5], [5, 6, 7], list(range(1, 32))):
    fix(f"vote_vote_ok_{len(slots)}", VOTE_PROGRAM_ID, vp.ix_vote(slots),
        [vote_acct(0, vp.VoteState(NODE, VOTER)), acct(101, signer=True)],
        [0, 1], {"ok": True})

fix("vote_vote_unsigned_voter", VOTE_PROGRAM_ID, vp.ix_vote([5]),
    [vote_acct(0, vp.VoteState(NODE, VOTER)), acct(101, signer=False)],
    [0, 1], {"ok": False, "err_contains": "sign"})

fix("vote_vote_uninitialized", VOTE_PROGRAM_ID, vp.ix_vote([5]),
    [vote_acct(0, None), acct(101, signer=True)], [0, 1],
    {"ok": False, "err_contains": "uninitialized"})

fix("vote_vote_empty", VOTE_PROGRAM_ID, vp.ix_vote([]),
    [vote_acct(0, vp.VoteState(NODE, VOTER)), acct(101, signer=True)],
    [0, 1], {"ok": False, "err_contains": "empty"})

fix("vote_old_slot_rejected", VOTE_PROGRAM_ID, vp.ix_vote([5, 5]),
    [vote_acct(0, vp.VoteState(NODE, VOTER)), acct(101, signer=True)],
    [0, 1], {"ok": False})

fix("vote_unknown_instruction", VOTE_PROGRAM_ID, struct.pack("<I", 9),
    [vote_acct(0, vp.VoteState(NODE, VOTER))], [0],
    {"ok": False, "err_contains": "unsupported"})

# ====================================================================== stake
# ref: src/flamenco/runtime/program/fd_stake_program.c

STAKER, WITHDRAWER = pk(200), pk(201)


def stake_state(kind=None, staker=STAKER, withdrawer=WITHDRAWER,
                stake=0, act=0, deact=sp.U64_MAX, voter=bytes(32)):
    st = sp.StakeState()
    if kind is not None:
        st.kind = kind
        st.staker, st.withdrawer = staker, withdrawer
        st.stake, st.activation_epoch, st.deactivation_epoch = (
            stake, act, deact)
        st.voter = voter
    return st


def stake_acct(i, st: "sp.StakeState", lamports=10_000, **kw):
    return acct(i, lamports=lamports, data=st.serialize(),
                owner=STAKE_PROGRAM_ID, **kw)


fix("stake_initialize_ok", STAKE_PROGRAM_ID,
    sp.ix_initialize(STAKER, WITHDRAWER),
    [stake_acct(0, stake_state())], [0], {"ok": True})

fix("stake_initialize_twice", STAKE_PROGRAM_ID,
    sp.ix_initialize(STAKER, WITHDRAWER),
    [stake_acct(0, stake_state(sp.StakeState.INITIALIZED))], [0],
    {"ok": False, "err_contains": "initialized"})

fix("stake_initialize_wrong_owner", STAKE_PROGRAM_ID,
    sp.ix_initialize(STAKER, WITHDRAWER),
    [acct(0, lamports=10_000, data=bytes(200))], [0],
    {"ok": False, "err_contains": "owned"})

fix("stake_delegate_ok", STAKE_PROGRAM_ID, sp.ix_delegate(),
    [stake_acct(0, stake_state(sp.StakeState.INITIALIZED)),
     vote_acct(1, vp.VoteState(NODE, VOTER)),
     acct(200, signer=True)], [0, 1, 2],
    {"ok": True})

fix("stake_delegate_not_vote_account", STAKE_PROGRAM_ID, sp.ix_delegate(),
    [stake_acct(0, stake_state(sp.StakeState.INITIALIZED)),
     acct(1, lamports=5), acct(200, signer=True)], [0, 1, 2],
    {"ok": False, "err_contains": "vote account"})

fix("stake_delegate_unsigned", STAKE_PROGRAM_ID, sp.ix_delegate(),
    [stake_acct(0, stake_state(sp.StakeState.INITIALIZED)),
     vote_acct(1, vp.VoteState(NODE, VOTER)),
     acct(200, signer=False)], [0, 1, 2],
    {"ok": False, "err_contains": "sign"})

fix("stake_delegate_already_active", STAKE_PROGRAM_ID, sp.ix_delegate(),
    [stake_acct(0, stake_state(sp.StakeState.DELEGATED, stake=100, act=1)),
     vote_acct(1, vp.VoteState(NODE, VOTER)),
     acct(200, signer=True)], [0, 1, 2],
    {"ok": False, "err_contains": "delegated"})

fix("stake_deactivate_ok", STAKE_PROGRAM_ID, sp.ix_deactivate(),
    [stake_acct(0, stake_state(sp.StakeState.DELEGATED, stake=100, act=1)),
     acct(200, signer=True)], [0, 1],
    {"ok": True}, epoch=5)

fix("stake_deactivate_not_active", STAKE_PROGRAM_ID, sp.ix_deactivate(),
    [stake_acct(0, stake_state(sp.StakeState.INITIALIZED)),
     acct(200, signer=True)], [0, 1],
    {"ok": False, "err_contains": "active"})

for amt, free, ok in ((100, 10_000, True), (10_000, 10_000, True),
                      (10_001, 10_000, False)):
    fix(f"stake_withdraw_{amt}_of_{free}", STAKE_PROGRAM_ID,
        sp.ix_withdraw(amt),
        [stake_acct(0, stake_state(sp.StakeState.INITIALIZED),
                    lamports=free),
         acct(1, lamports=3), acct(201, signer=True)], [0, 1, 2],
        {"ok": ok, **({"post": [{"index": 0, "lamports": free - amt},
                                {"index": 1, "lamports": 3 + amt}]}
                      if ok else {"err_contains": "withdrawable"})})

fix("stake_withdraw_unsigned", STAKE_PROGRAM_ID, sp.ix_withdraw(1),
    [stake_acct(0, stake_state(sp.StakeState.INITIALIZED)),
     acct(1), acct(201, signer=False)], [0, 1, 2],
    {"ok": False, "err_contains": "sign"})

fix("stake_withdraw_active_stake_blocked", STAKE_PROGRAM_ID,
    sp.ix_withdraw(1),
    [stake_acct(0, stake_state(sp.StakeState.DELEGATED, stake=100, act=1)),
     acct(1), acct(201, signer=True)], [0, 1, 2],
    {"ok": False, "err_contains": "deactivated"}, epoch=5)

fix("stake_authorize_staker_ok", STAKE_PROGRAM_ID,
    sp.ix_authorize(pk(210), 0),
    [stake_acct(0, stake_state(sp.StakeState.INITIALIZED)),
     acct(200, signer=True)], [0, 1], {"ok": True})

fix("stake_authorize_withdrawer_ok", STAKE_PROGRAM_ID,
    sp.ix_authorize(pk(211), 1),
    [stake_acct(0, stake_state(sp.StakeState.INITIALIZED)),
     acct(201, signer=True)], [0, 1], {"ok": True})

fix("stake_authorize_wrong_signer", STAKE_PROGRAM_ID,
    sp.ix_authorize(pk(210), 0),
    [stake_acct(0, stake_state(sp.StakeState.INITIALIZED)),
     acct(201, signer=True)], [0, 1],
    {"ok": False, "err_contains": "sign"})

fix("stake_unknown_instruction", STAKE_PROGRAM_ID, struct.pack("<I", 77),
    [stake_acct(0, stake_state())], [0],
    {"ok": False, "err_contains": "unsupported"})

fix("stake_short_data", STAKE_PROGRAM_ID, b"\x01",
    [stake_acct(0, stake_state())], [0],
    {"ok": False, "err_contains": "short"})

# ------------------------------------------------- adversarial truncations
# every program must convert malformed data into an instruction error
# (ref: fd_executor.c converts all program failures to instr error codes)
for name, pid, good in (
        ("system_create", SYSTEM_PROGRAM_ID,
         sysp.ix_create_account(10, 5, VOTE_PROGRAM_ID)),
        ("system_assign", SYSTEM_PROGRAM_ID, sysp.ix_assign(VOTE_PROGRAM_ID)),
        ("vote_init", VOTE_PROGRAM_ID, vp.ix_initialize(NODE, VOTER)),
        ("vote_vote", VOTE_PROGRAM_ID, vp.ix_vote([3])),
        ("stake_init", STAKE_PROGRAM_ID, sp.ix_initialize(STAKER, WITHDRAWER)),
        ("stake_withdraw", STAKE_PROGRAM_ID, sp.ix_withdraw(5)),
        ("stake_authorize", STAKE_PROGRAM_ID, sp.ix_authorize(pk(210), 0))):
    for cut in (1, 3, len(good) // 2, len(good) - 1):
        if cut >= len(good):
            continue
        accounts = [acct(0, lamports=1000, data=bytes(200),
                         owner=pid, signer=True),
                    acct(1, lamports=1000, signer=True),
                    acct(100, signer=True), acct(101, signer=True),
                    acct(200, signer=True), acct(201, signer=True)]
        fix(f"trunc_{name}_{cut}", pid, good[:cut], accounts,
            [0, 1], {"ok": False})


# --------------------------------------------------- round-out to >= 100
# more boundary cases, same per-rule citations as the sections above

fix("system_create_insufficient_funds", SYSTEM_PROGRAM_ID,
    sysp.ix_create_account(5001, 0, VOTE_PROGRAM_ID),
    [acct(0, lamports=5000, signer=True),
     acct(1, missing=True, signer=True)], [0, 1],
    {"ok": False, "err_contains": "insufficient"})

fix("system_create_unsigned_from", SYSTEM_PROGRAM_ID,
    sysp.ix_create_account(100, 0, VOTE_PROGRAM_ID),
    [acct(0, lamports=5000, signer=False),
     acct(1, missing=True, signer=True)], [0, 1],
    {"ok": False, "err_contains": "signature"})

fix("system_allocate_too_large", SYSTEM_PROGRAM_ID,
    sysp.ix_allocate(10 * 1024 * 1024 + 1),
    [acct(0, lamports=10, signer=True)], [0],
    {"ok": False})

fix("system_allocate_unsigned", SYSTEM_PROGRAM_ID, sysp.ix_allocate(16),
    [acct(0, lamports=10, signer=False)], [0],
    {"ok": False})

fix("system_assign_missing_account", SYSTEM_PROGRAM_ID,
    sysp.ix_assign(VOTE_PROGRAM_ID),
    [acct(0, missing=True, signer=True)], [0],
    {"ok": False})

for amt in (1, 100):
    # self-transfer is a no-op on the balance (same account both sides)
    fix(f"system_transfer_self_{amt}", SYSTEM_PROGRAM_ID,
        sysp.ix_transfer(amt),
        [acct(0, lamports=500, signer=True), acct(0, lamports=500)],
        [0, 1],
        {"ok": True, "post": [{"index": 0, "lamports": 500}]})

# tower mechanics: 31 consecutive votes root the oldest (vote credits)
fix("vote_tower_roots_at_32", VOTE_PROGRAM_ID,
    vp.ix_vote(list(range(1, 33))),
    [vote_acct(0, vp.VoteState(NODE, VOTER)), acct(101, signer=True)],
    [0, 1], {"ok": True})

fix("vote_nonmonotonic_slots", VOTE_PROGRAM_ID, vp.ix_vote([9, 3]),
    [vote_acct(0, vp.VoteState(NODE, VOTER)), acct(101, signer=True)],
    [0, 1], {"ok": False})

fix("vote_vote_wrong_owner", VOTE_PROGRAM_ID, vp.ix_vote([5]),
    [acct(0, lamports=10, data=bytes(200)), acct(101, signer=True)],
    [0, 1], {"ok": False, "err_contains": "owned"})

fix("vote_vote_missing_account", VOTE_PROGRAM_ID, vp.ix_vote([5]),
    [acct(0, missing=True), acct(101, signer=True)], [0, 1],
    {"ok": False})

fix("stake_redelegate_after_deactivation", STAKE_PROGRAM_ID,
    sp.ix_delegate(),
    [stake_acct(0, stake_state(sp.StakeState.DELEGATED, stake=100, act=1,
                               deact=3)),
     vote_acct(1, vp.VoteState(NODE, VOTER)),
     acct(200, signer=True)], [0, 1, 2],
    {"ok": True}, epoch=5)

fix("stake_withdraw_after_deactivation_epoch", STAKE_PROGRAM_ID,
    sp.ix_withdraw(100),
    [stake_acct(0, stake_state(sp.StakeState.DELEGATED, stake=100, act=1,
                               deact=3), lamports=10_000),
     acct(1, lamports=0), acct(201, signer=True)], [0, 1, 2],
    {"ok": True, "post": [{"index": 0, "lamports": 9_900},
                          {"index": 1, "lamports": 100}]}, epoch=5)

fix("stake_withdraw_uninitialized_self_sign", STAKE_PROGRAM_ID,
    sp.ix_withdraw(10),
    [stake_acct(0, stake_state(), signer=True), acct(1, lamports=0),
     acct(201, signer=False)], [0, 1, 2],
    {"ok": True, "post": [{"index": 1, "lamports": 10}]})

fix("stake_withdraw_uninitialized_no_self_sign", STAKE_PROGRAM_ID,
    sp.ix_withdraw(10),
    [stake_acct(0, stake_state(), signer=False), acct(1, lamports=0),
     acct(201, signer=True)], [0, 1, 2],
    {"ok": False, "err_contains": "own signature"})

fix("stake_deactivate_twice", STAKE_PROGRAM_ID, sp.ix_deactivate(),
    [stake_acct(0, stake_state(sp.StakeState.DELEGATED, stake=100, act=1,
                               deact=3)),
     acct(200, signer=True)], [0, 1],
    {"ok": False, "err_contains": "active"}, epoch=5)

fix("stake_authorize_role_withdrawer_by_staker_fails", STAKE_PROGRAM_ID,
    sp.ix_authorize(pk(212), 1),
    [stake_acct(0, stake_state(sp.StakeState.INITIALIZED)),
     acct(200, signer=True)], [0, 1],
    {"ok": False, "err_contains": "sign"})

fix("stake_delegate_missing_vote", STAKE_PROGRAM_ID, sp.ix_delegate(),
    [stake_acct(0, stake_state(sp.StakeState.INITIALIZED)),
     acct(1, missing=True), acct(200, signer=True)], [0, 1, 2],
    {"ok": False, "err_contains": "vote"})


def main():
    out_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "fixtures")
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "instr_fixtures.json")
    with open(path, "w") as f:
        json.dump(FIX, f, indent=1)
    print(f"{path}: {len(FIX)} fixtures")


if __name__ == "__main__":
    main()
