"""(a) Real MXU rates with loop-carried (non-hoistable) matmuls;
(b) honest strict-vs-RLC A/B at production batch sizes.

Slope timing + multi-dispatch per tools/exp_op_floors.py."""

import time

import jax
import jax.numpy as jnp
import numpy as np

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
from _bench import DISPATCH, slope, timed  # noqa: E402,F401

from firedancer_tpu.utils import xla_cache

xla_cache.enable()

BATCH = 4096






def mxu():
    rng = np.random.default_rng(0)
    wi = jnp.asarray(rng.integers(-64, 64, size=(128, 128), dtype=np.int8))
    x0 = jnp.asarray(rng.integers(-64, 64, size=(BATCH, 128), dtype=np.int8))

    def mk_mm(steps):
        @jax.jit
        def f(x, w):
            def body(i, x):
                y = jax.lax.dot_general(
                    x, w, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                # carry depends on y: no loop-invariant hoisting possible
                return (y & 63).astype(jnp.int8)
            return jax.lax.fori_loop(0, steps, body, x)
        return f, (x0, wi)

    slope("int8 mm (4096x128)@(128x128) carried", mk_mm, 512, 2048,
          BATCH * 128 * 128, "MAC")

    w2 = jnp.asarray(rng.integers(-64, 64, size=(512, 512), dtype=np.int8))
    x2 = jnp.asarray(rng.integers(-64, 64, size=(BATCH, 512), dtype=np.int8))

    def mk_mm2(steps):
        @jax.jit
        def f(x, w):
            def body(i, x):
                y = jax.lax.dot_general(
                    x, w, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                return (y & 63).astype(jnp.int8)
            return jax.lax.fori_loop(0, steps, body, x)
        return f, (x2, w2)

    slope("int8 mm (4096x512)@(512x512) carried", mk_mm2, 128, 512,
          BATCH * 512 * 512, "MAC")

    # batched per-lane matvec (VERDICT's banded-matrix conv shape), carried
    Mb = jnp.asarray(rng.integers(0, 1 << 12, size=(BATCH, 44, 22),
                                  dtype=np.int32))
    v0 = jnp.asarray(rng.integers(0, 1 << 12, size=(BATCH, 22),
                                  dtype=np.int32))

    def mk_bmv(steps):
        @jax.jit
        def f(M, v):
            def body(i, v):
                c = jax.lax.dot_general(
                    M, v, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.int32)  # (B, 44)
                return c[:, :22] & 4095
            return jax.lax.fori_loop(0, steps, body, v)
        return f, (Mb, v0)

    slope("batched matvec (B,44,22)@(B,22) carried", mk_bmv, 256, 1024,
          BATCH, "fieldmul-equiv")


def rlc_ab():
    from firedancer_tpu.models.verifier import SigVerifier, VerifierConfig, \
        make_example_batch

    for batch, mode, m in ((8192, "strict", 8), (8192, "rlc", 8),
                           (8192, "rlc", 16), (16384, "strict", 8),
                           (16384, "rlc", 16)):
        cfg = VerifierConfig(batch=batch, msg_maxlen=128)
        v = SigVerifier(cfg, mode=mode, msm_m=m)
        args = make_example_batch(batch, 128, valid=True, sign_pool=32)
        ok = v(*args)
        assert bool(np.asarray(ok).all())
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(4):
                ok = v(*args)
            np.asarray(ok)
            best = min(best, (time.perf_counter() - t0) / 4)
        print(f"verify batch={batch} mode={mode} m={m}: "
              f"{best*1e3:8.1f} ms -> {batch/best:10.0f} v/s", flush=True)


if __name__ == "__main__":
    mxu()
    rlc_ab()
