"""blk sweep for the fused verify kernel (the r3 sweep picked 128 for the
split dsm kernel; the fused kernel's live set differs)."""
import os, sys, time
import numpy as np
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from firedancer_tpu.utils import xla_cache
xla_cache.enable()
import jax
import jax.numpy as jnp
from firedancer_tpu.models.verifier import make_example_batch
from firedancer_tpu.ops import curve_pallas as cpal
from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.ops import sha512 as sh

B = int(os.environ.get("B", 32768))
msgs, lens, sigs, pubs = make_example_batch(B, 128, valid=True, sign_pool=64)
r_bytes, s_bytes = sigs[:, :32], sigs[:, 32:]
pre = jnp.concatenate([r_bytes, pubs, msgs], axis=1)
digest = jax.jit(sh.sha512)(pre, lens + 64)
np.asarray(digest)
y_r = jnp.asarray(np.asarray(ed._parse_r_bytes(r_bytes)[0]))

for blk in (64, 128, 256, 512):
    try:
        f = jax.jit(lambda s, d, y, _b=blk: cpal.verify_tail_fused(
            pubs, s, d, y, blk=_b)[1])
        t0 = time.perf_counter()
        np.asarray(f(s_bytes, digest, y_r))
        ct = time.perf_counter() - t0
        runs = []
        for _ in range(5):
            t0 = time.perf_counter()
            o = None
            for _ in range(16):
                o = f(s_bytes, digest, y_r)
            np.asarray(o)
            runs.append((time.perf_counter() - t0) / 16 * 1e3)
        runs.sort()
        print(f"blk={blk:4d} {runs[2]:8.2f} ms ({runs[0]:.2f}..{runs[-1]:.2f})"
              f"  compile {ct:.0f}s", flush=True)
    except Exception as e:
        print(f"blk={blk:4d} FAILED: {str(e)[:100]}", flush=True)
